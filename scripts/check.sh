#!/usr/bin/env bash
# CI gate: static analysis first, then the tier-1 test suite.
# Fails on either.  Run from the repo root: scripts/check.sh
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== deneva_tpu.lint =="
env JAX_PLATFORMS=cpu python -m deneva_tpu.lint deneva_tpu
lint_rc=$?
if [ "$lint_rc" -ne 0 ]; then
    echo "lint FAILED (rc=$lint_rc)"
    exit "$lint_rc"
fi

echo "== compaction parity smoke =="
# one fast compacted-vs-padded bit-identity cell (the full 7-alg matrix
# lives in tests/test_compaction.py and runs in the tier-1 gate below)
env JAX_PLATFORMS=cpu python -m pytest \
    "tests/test_compaction.py::test_ycsb_parity_compact_vs_padded[NO_WAIT]" \
    -q -p no:cacheprovider
parity_rc=$?
if [ "$parity_rc" -ne 0 ]; then
    echo "compaction parity smoke FAILED (rc=$parity_rc)"
    exit "$parity_rc"
fi

echo "== obs smoke (waterfall + watchdog) =="
# one small attributed+traced cell through bench.py's observed path: the
# exit code ORs reconciliation failures with the obs watchdog bitmask
# (RECONCILE=1 LIVELOCK=2 SPILL=4 STARVED=8 OVERLOAD=16,
# deneva_tpu/obs/report.py),
# then the report CLI re-derives the same verdict from the run record
obs_dir=$(mktemp -d)
env JAX_PLATFORMS=cpu python bench.py --trace --profile --ticks 40 \
    --out-dir "$obs_dir"
obs_rc=$?
if [ "$obs_rc" -eq 0 ]; then
    env JAX_PLATFORMS=cpu python -m deneva_tpu.obs.report \
        "$obs_dir"/run_*.json > /dev/null
    obs_rc=$?
fi
rm -rf "$obs_dir"
if [ "$obs_rc" -ne 0 ]; then
    echo "obs smoke FAILED (watchdog/reconcile bitmask rc=$obs_rc)"
    exit "$obs_rc"
fi

echo "== xmeter smoke (recompile sentinel + ledger reconcile) =="
# the compile & memory observatory on the same small cell: nonzero means
# a post-warmup recompile (rc&1) or the HBM ledger disagreeing with the
# compiled tick's own memory_analysis() by >1% (rc&2)
xm_dir=$(mktemp -d)
env JAX_PLATFORMS=cpu python bench.py --xmeter --ticks 40 \
    --out-dir "$xm_dir"
xm_rc=$?
rm -rf "$xm_dir"
if [ "$xm_rc" -ne 0 ]; then
    echo "xmeter smoke FAILED (sentinel/ledger bitmask rc=$xm_rc)"
    exit "$xm_rc"
fi

echo "== saturation smoke (open-system knee + OVERLOAD) =="
# a tiny two-point offered-load sweep (deneva_tpu/traffic/): the
# sub-knee point must serve >= 95% of arrivals with a clean watchdog,
# the over-offered point must trip the OVERLOAD bit (16); the emitted
# knee JSON must carry the schema the regression gate consumes
sat_dir=$(mktemp -d)
env JAX_PLATFORMS=cpu python bench.py --offered-load --rates 4,48 \
    --algs NO_WAIT --ticks 60 --no-history --out-dir "$sat_dir"
sat_rc=$?
if [ "$sat_rc" -eq 0 ]; then
    env JAX_PLATFORMS=cpu python - "$sat_dir/offered_load_sweep.json" <<'PYEOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["metric"] == "offered_load_knee", doc["metric"]
for key in ("value", "unit", "offered_load", "knee", "algs", "sweep"):
    assert key in doc, f"knee JSON missing {key}"
pts = doc["sweep"]["NO_WAIT"]
assert doc["knee"]["NO_WAIT"] == pts[0]["offered"], "knee below low point"
assert pts[0]["served_frac"] >= 0.95 and pts[0]["watchdog"] == 0, pts[0]
assert pts[-1]["watchdog"] & 16, f"over-offered point missed OVERLOAD: {pts[-1]}"
print(f"[saturation] knee={doc['knee']['NO_WAIT']} "
      f"overload point queue_len={pts[-1]['queue_len']}")
PYEOF
    sat_rc=$?
fi
rm -rf "$sat_dir"
if [ "$sat_rc" -ne 0 ]; then
    echo "saturation smoke FAILED (rc=$sat_rc)"
    exit "$sat_rc"
fi

echo "== flight smoke (lifecycle spans + tail attribution) =="
# the transaction flight recorder (deneva_tpu/obs/flight.py) on one
# short attributed cell: full-sampling spans must reconcile EXACTLY
# against the lat_* integrals and abort_* counters (rc=1 on mismatch,
# ORed with the watchdog bitmask), the run record must export through
# the unified Perfetto CLI, and the report must render a [tail] section
flt_dir=$(mktemp -d)
env JAX_PLATFORMS=cpu python bench.py --flight --algs NO_WAIT \
    --ticks 40 --no-history --out-dir "$flt_dir"
flt_rc=$?
if [ "$flt_rc" -eq 0 ]; then
    env JAX_PLATFORMS=cpu python -m deneva_tpu.obs.export \
        "$flt_dir"/run_flight_*.json -o "$flt_dir/flight_trace.json" \
        && env JAX_PLATFORMS=cpu python -m deneva_tpu.obs.report \
            "$flt_dir"/run_flight_no_wait.json | grep -q '^\[tail\]'
    flt_rc=$?
fi
rm -rf "$flt_dir"
if [ "$flt_rc" -ne 0 ]; then
    echo "flight smoke FAILED (reconcile/export/tail rc=$flt_rc)"
    exit "$flt_rc"
fi

echo "== depgraph smoke (wait-for graph + critical paths) =="
# the conflict dependency observatory (Config.depgraph,
# deneva_tpu/obs/depgraph.py) on the contended zipf-0.9 cell: an
# abort-only plugin (NO_WAIT) and a waiting one (MVCC) must both
# reconcile their sampled edges EXACTLY against the wait/abort counter
# integrals (a wrapped ring refuses loudly; rc=1 on any mismatch or a
# post-warm recompile under the xmeter sentinel, ORed with the
# watchdog bitmask minus the expected CONVOY bit), the report
# must render a [depgraph] section, and the merged Perfetto trace must
# carry the blocker->waiter flow arrows in the per-record "<pid>:dep<n>"
# flow-id namespace
dep_dir=$(mktemp -d)
env JAX_PLATFORMS=cpu python bench.py --depgraph --algs NO_WAIT,MVCC \
    --ticks 40 --no-history --out-dir "$dep_dir"
dep_rc=$?
if [ "$dep_rc" -eq 0 ]; then
    env JAX_PLATFORMS=cpu python -m deneva_tpu.obs.report \
        "$dep_dir"/run_depgraph_mvcc.json | grep -q '^\[depgraph\]' \
    && env JAX_PLATFORMS=cpu python -m deneva_tpu.obs.export \
        "$dep_dir"/run_depgraph_*.json -o "$dep_dir/depgraph_trace.json" \
    && grep -q '"id": "[0-9]*:dep' "$dep_dir/depgraph_trace.json"
    dep_rc=$?
fi
rm -rf "$dep_dir"
if [ "$dep_rc" -ne 0 ]; then
    echo "depgraph smoke FAILED (reconcile/report/flows rc=$dep_rc)"
    exit "$dep_rc"
fi

echo "== fused arbitration smoke (parity + sort-count) =="
# the fused VMEM sort+scan kernel (Config.fused_arbitrate, ops/fused.py)
# on one small contended MAAT cell, interpret mode on CPU: the [summary]
# dict must be bit-identical to the lax path's, and the fused tick's
# jaxpr must carry strictly fewer standalone lax.sort ops (the kernel
# absorbed them); the full 7-plugin matrix lives in tests/test_fused.py
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import jax
from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine

KW = dict(cc_alg="MAAT", batch_size=16, req_per_query=8,
          synth_table_size=128, zipf_theta=0.8, query_pool_size=256,
          admit_cap=4, max_ticks=10**6, warmup_ticks=0)


def sorts(eng):
    def walk(j):
        n = 0
        for eqn in j.eqns:
            n += eqn.primitive.name == "sort"
            for v in eqn.params.values():
                for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                    if getattr(sub, "jaxpr", None) is not None:
                        n += walk(sub.jaxpr)
        return n
    return walk(jax.make_jaxpr(eng._tick_fn)(eng.init_state()).jaxpr)


out = {}
for fused in (False, True):
    eng = Engine(Config(fused_arbitrate=fused, **KW))
    out[fused] = (eng.summary(eng.run(40)), sorts(eng))
assert out[True][0] == out[False][0], "fused vs lax summary diverged"
assert out[True][1] < out[False][1], \
    f"fused tick kept {out[True][1]} sorts (lax {out[False][1]})"
print(f"[fused] parity held; standalone sorts "
      f"{out[False][1]} -> {out[True][1]}")
PYEOF
fused_rc=$?
if [ "$fused_rc" -ne 0 ]; then
    echo "fused smoke FAILED (parity/sort-count rc=$fused_rc)"
    exit "$fused_rc"
fi

echo "== mesh smoke (traffic matrix + reconciliation) =="
# the cluster mesh observatory (deneva_tpu/obs/mesh.py) on a 4-node
# virtual-device dryrun: the [mesh] report section must render, and the
# N x N x type traffic matrix must reconcile EXACTLY against
# remote_entry_cnt (attempted == delivered + dropped), transpose to the
# rx planes, and mirror one response per delivered entry; the psum'd
# cluster matrix must equal the numpy sum of the per-node planes
env JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
    python - <<'PYEOF'
import numpy as np
from deneva_tpu.config import Config
from deneva_tpu.obs import mesh as obs_mesh
from deneva_tpu.obs import report as obs_report
from deneva_tpu.parallel.sharded import ShardedEngine

cfg = Config(cc_alg="WAIT_DIE", node_cnt=4, part_cnt=4, batch_size=32,
             synth_table_size=1 << 12, req_per_query=4,
             query_pool_size=1 << 10, zipf_theta=0.6, tup_read_perc=0.5,
             warmup_ticks=0, mpr=1.0, part_per_txn=2, mesh=True)
eng = ShardedEngine(cfg)
st = eng.run(40)
s = eng.summary(st)
snap = eng.mesh_snapshot(st)
bad = obs_mesh.reconcile(snap, s)
assert bad == [], f"mesh matrix failed to reconcile: {bad}"
cm = np.asarray(eng.mesh_cluster_matrix(st))
tx = np.asarray(st.stats["arr_mesh_tx"])
assert np.array_equal(cm, tx.sum(axis=0, dtype=np.int32)), \
    "psum cluster matrix != sum of per-node planes"
rep = obs_report.build_report(s, mesh=obs_mesh.mesh_report(snap,
                                                           cap=eng.cap))
text = obs_report.render_text(rep)
assert "[mesh]" in text, "report missing the [mesh] section"
print(next(ln for ln in text.splitlines() if ln.startswith("[mesh]")))
print(f"[mesh] reconciled: {s['mesh_tx_total']} msgs, "
      f"jain={s['imb_jain']:.3f}")
PYEOF
mesh_rc=$?
if [ "$mesh_rc" -ne 0 ]; then
    echo "mesh smoke FAILED (reconcile/report rc=$mesh_rc)"
    exit "$mesh_rc"
fi

echo "== fault smoke (kill-a-node replay recovery) =="
# the deterministic fault plane (Config.faults, deneva_tpu/faults/) on
# the 2-node sharded CALVIN cell: a mid-run kill must recover by
# deterministic replay from the last checkpoint to a [summary] that is
# bit-identical to the fault-free oracle (the exit code carries the
# RECOVERY watchdog bit, 64, on any parity failure), and straggle /
# partition windows must gate work without aborting it; the printed
# parity line is the recovered-vs-oracle verdict
flt_dir=$(mktemp -d)
env JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
    python bench.py --faults --ticks 60 --no-history --out-dir "$flt_dir" \
    | tee "$flt_dir/faults.log"
faults_rc=${PIPESTATUS[0]}
if [ "$faults_rc" -eq 0 ]; then
    grep -q 'kill parity=OK' "$flt_dir/faults.log"
    faults_rc=$?
fi
rm -rf "$flt_dir"
if [ "$faults_rc" -ne 0 ]; then
    echo "fault smoke FAILED (recovery parity bitmask rc=$faults_rc)"
    exit "$faults_rc"
fi

echo "== scale smoke (16-node cells + 64-node split dryrun) =="
# the scale-out path (Config.exchange_split / Config.remote_cache): a
# 16-virtual-node NO_WAIT cell must run and reconcile its mesh matrix
# exactly; a 16-node CALVIN cell must run under the capacity-bounded
# epoch-split exchange with a buffer strictly below the worst case
# (eng.cap < B*R); the config shape the single-round exchange REFUSES
# (its 2^23 guard) must construct under exchange_split; and a 64-node
# CALVIN split cell must trace end-to-end (make_jaxpr dryrun) with no
# worst-case B*R allocation anywhere
env JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=16" \
    python - <<'PYEOF'
import numpy as np
from deneva_tpu.config import Config
from deneva_tpu.obs import mesh as obs_mesh
from deneva_tpu.parallel import sharded
from deneva_tpu.parallel.sharded import ShardedEngine

KW = dict(synth_table_size=1 << 12, req_per_query=4, zipf_theta=0.6,
          tup_read_perc=0.5, query_pool_size=1 << 10, warmup_ticks=0,
          mpr=1.0, part_per_txn=2)

# 16-node NO_WAIT: runs, commits, mesh matrix reconciles exactly
cfg = Config(cc_alg="NO_WAIT", node_cnt=16, part_cnt=16, batch_size=32,
             mesh=True, **KW)
eng = ShardedEngine(cfg)
st = eng.run(20)
s = eng.summary(st)
assert s["txn_cnt"] > 0, "16-node NO_WAIT cell committed nothing"
bad = obs_mesh.reconcile(eng.mesh_snapshot(st), s)
assert bad == [], f"16-node mesh failed to reconcile: {bad}"
print(f"[scale] NO_WAIT 16n: {s['txn_cnt']} commits, "
      f"{s['mesh_tx_total']} msgs reconciled")

# 16-node CALVIN under the split exchange: capacity-bounded buffer
cfg = Config(cc_alg="CALVIN", node_cnt=16, part_cnt=16, batch_size=32,
             exchange_split=True, mesh=True, **KW)
eng = ShardedEngine(cfg)
assert eng.cap < cfg.batch_size * cfg.req_per_query, \
    f"split cap {eng.cap} not below worst case"
st = eng.run(20)
s = eng.summary(st)
assert s["txn_cnt"] > 0, "16-node CALVIN split cell committed nothing"
bad = obs_mesh.reconcile(eng.mesh_snapshot(st), s)
assert bad == [], f"16-node CALVIN mesh failed to reconcile: {bad}"
print(f"[scale] CALVIN 16n split: cap {eng.cap} (worst case "
      f"{cfg.batch_size * cfg.req_per_query}), {s['txn_cnt']} commits")

# the shape the single-round exchange refuses (N*B*R > 2^23) must
# construct once split; the worst-case capacity call must still raise
big = dict(cc_alg="CALVIN", node_cnt=16, part_cnt=16, batch_size=8192,
           req_per_query=128, synth_table_size=1 << 16,
           query_pool_size=1 << 10, warmup_ticks=0, mpr=1.0,
           part_per_txn=2)
try:
    ShardedEngine(Config(**big))
    raise SystemExit("worst-case CALVIN capacity failed to raise")
except ValueError as e:
    assert "exchange_split" in str(e), e
cap = ShardedEngine(Config(**big, exchange_split=True)).cap
assert cap < 8192 * 128, cap
print(f"[scale] 16n x 8192 x 128 CALVIN: guard raises without split, "
      f"cap {cap} with it")
PYEOF
scale_rc=$?
if [ "$scale_rc" -eq 0 ]; then
    # 64-node dryrun: the full split tick must TRACE with the bounded
    # buffer (worst-case allocation would show up at trace time)
    env JAX_PLATFORMS=cpu \
        XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=64" \
        python - <<'PYEOF'
import jax
from deneva_tpu.config import Config
from deneva_tpu.parallel import sharded

cfg = Config(cc_alg="CALVIN", node_cnt=64, part_cnt=64, batch_size=32,
             exchange_split=True, synth_table_size=1 << 12,
             req_per_query=4, query_pool_size=1 << 10, warmup_ticks=0,
             mpr=1.0, part_per_txn=2)
eng = sharded.ShardedEngine(cfg)
eng._build()
jax.make_jaxpr(eng._tick_raw)(eng.init_state())
assert eng.cap < cfg.batch_size * cfg.req_per_query, eng.cap
print(f"[scale] CALVIN 64n split dryrun traced, cap {eng.cap} "
      f"(worst case {cfg.batch_size * cfg.req_per_query})")
PYEOF
    scale_rc=$?
fi
if [ "$scale_rc" -ne 0 ]; then
    echo "scale smoke FAILED (rc=$scale_rc)"
    exit "$scale_rc"
fi

echo "== pipeline smoke (split-exchange overlap parity + reconcile) =="
# the software-pipelined sharded tick (Config.pipeline_exchange,
# parallel/sharded.py): (1) the 4-node CALVIN oracle cell must be
# BIT-identical to the unpipelined split exchange — every summary
# counter and the data array — adding only the two occupancy counters;
# (2) the overlap counters must reconcile (0 < overlapped < issued legs
# on a multi-sub-round cell) and the mesh round-windows identity
# (mesh_round_sum == exchange_round_cnt) must balance exactly; (3) the
# sharded certifier must hold the pipelined collective plan clean
env JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
    python - <<'PYEOF'
import numpy as np
from deneva_tpu.config import Config
from deneva_tpu.obs import mesh as obs_mesh
from deneva_tpu.parallel.sharded import ShardedEngine

KW = dict(cc_alg="CALVIN", node_cnt=4, part_cnt=4, batch_size=32,
          synth_table_size=1 << 10, query_pool_size=256,
          req_per_query=4, warmup_ticks=2, exchange_split=True,
          route_capacity_factor=0.25, mesh=True)

def run(**kw):
    eng = ShardedEngine(Config(**{**KW, **kw}))
    st = eng.run(20)
    return eng, st, eng.summary(st)

_, s0, a = run()
eng, s1, b = run(pipeline_exchange=True)
extra = set(b) - set(a)
assert extra == {"pipe_leg_cnt", "pipe_overlap_cnt"}, extra
for k in a:
    assert a[k] == b[k], (k, a[k], b[k])
assert np.array_equal(np.asarray(s0.data), np.asarray(s1.data)), \
    "pipelined data array diverged"
assert 0 < b["pipe_overlap_cnt"] < b["pipe_leg_cnt"], \
    (b["pipe_overlap_cnt"], b["pipe_leg_cnt"])
bad = obs_mesh.reconcile(eng.mesh_snapshot(s1), b)
assert bad == [], f"pipelined mesh failed to reconcile: {bad}"
assert b["mesh_round_sum"] == b["exchange_round_cnt"] > 0
frac = b["pipe_overlap_cnt"] / b["pipe_leg_cnt"]
print(f"[pipeline] CALVIN 4n parity OK: {b['pipe_leg_cnt']} legs, "
      f"overlap {frac:.2f}, rounds {b['exchange_round_cnt']} balanced")
PYEOF
pipe_rc=$?
if [ "$pipe_rc" -eq 0 ]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
        python -m deneva_tpu.lint.shard_certify --flags pipeline_exchange \
        --algs CALVIN
    pipe_rc=$?
fi
if [ "$pipe_rc" -ne 0 ]; then
    echo "pipeline smoke FAILED (parity/reconcile/certify rc=$pipe_rc)"
    exit "$pipe_rc"
fi

echo "== adaptive smoke (controller purity + steady compiles) =="
# the adaptive contention controller (Config.adaptive, deneva_tpu/ctrl/):
# (1) the DEFAULT tick must carry zero controller state and repeat to an
# identical counter dict; (2) with the controller + xmeter on, a mid-run
# hot-set SHIFT (pool front half hot at the low ids, back half shifted
# to mid-table) must adapt with ZERO post-warmup recompiles — every
# decision is pre-traced; (3) the ctrl_* keys must round-trip the
# [summary] line; (4) the certifier must hold the adaptive flag clean on
# a two-alg cell (the full matrix runs in the certify stage below)
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import dataclasses

from deneva_tpu import stats as stats_mod
from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.workloads.ycsb import gen_query_pool

# --- (1) off path: no controller state, deterministic repeat ---------
cfg0 = Config(cc_alg="NO_WAIT", batch_size=64, synth_table_size=256,
              req_per_query=4, zipf_theta=0.9, query_pool_size=512,
              warmup_ticks=0)
runs = []
for _ in range(2):
    eng0 = Engine(cfg0)
    st0 = eng0.run(30)
    assert not any(k.startswith(("ctrl_", "arr_ctrl_"))
                   for k in st0.stats), "off-path run leaked ctrl state"
    runs.append({k: int(v) for k, v in eng0.summary(st0).items()
                 if isinstance(v, (int,)) or getattr(v, "ndim", 1) == 0})
assert runs[0] == runs[1], "off-path counters not deterministic"

# --- (2) adaptive through an induced hot-set shift, zero recompiles --
cfg = Config(cc_alg="NO_WAIT", adaptive=True, abort_attribution=True,
             heatmap_bins=32, xmeter=True, skew_method="hot",
             access_perc=0.95, data_perc=0.01, batch_size=128,
             synth_table_size=512, req_per_query=4,
             query_pool_size=1024, warmup_ticks=0, admit_cap=32,
             ctrl_esc_up=2, ctrl_esc_down=1)
pool = gen_query_pool(cfg)
n = cfg.synth_table_size - 1
keys = pool.keys.copy()
half = keys.shape[0] // 2
# bijective remap of the back half: the hot set jumps to mid-table when
# the pool cursor crosses (and again on every wrap) with zero retrace
keys[half:] = ((keys[half:] + n // 2 - 1) % n) + 1
eng = Engine(cfg, pool=dataclasses.replace(pool, keys=keys))
state = eng.run(40)                       # warmup: compiles land here
eng.xmeter.mark_warm()
state = eng.run(80, state)                # cursor crosses the shift
viol = eng.xmeter.steady_violations()
assert viol == [], f"controller recompiled post-warmup: {viol}"
s = eng.summary(state)
assert int(s["ctrl_escalate_cnt"]) >= 1, "controller never escalated"
assert int(s["ctrl_esc_block_cnt"]) >= 1, "serialization gate never fired"

# --- (3) ctrl_* keys round-trip the [summary] line -------------------
ref = stats_mod.reference_summary(s)
parsed = stats_mod.parse_summary(stats_mod.format_summary(ref))
ctrl_keys = [k for k in ref if k.startswith("ctrl_")]
assert ctrl_keys, "no ctrl_ keys on the [summary] line"
for k in ctrl_keys:
    assert int(parsed[k]) == int(ref[k]), k
print(f"[adaptive] off-path clean + deterministic; hot-set shift held "
      f"steady (0 post-warmup recompiles), "
      f"{int(s['ctrl_escalate_cnt'])} escalation(s), "
      f"{int(s['ctrl_esc_block_cnt'])} gate stall(s); "
      f"{len(ctrl_keys)} ctrl keys round-tripped")
PYEOF
adapt_rc=$?
if [ "$adapt_rc" -eq 0 ]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
        python -m deneva_tpu.lint.certify --flags adaptive \
        --algs NO_WAIT,OCC
    adapt_rc=$?
fi
if [ "$adapt_rc" -ne 0 ]; then
    echo "adaptive smoke FAILED (purity/steady-compile/certify rc=$adapt_rc)"
    exit "$adapt_rc"
fi

echo "== telemetry smoke (serve loop + SLO alert lifecycle) =="
# the streaming telemetry plane (deneva_tpu/obs/{histo,slo,telemetry}.py)
# end to end: the flash-crowd serve loop must run with ZERO steady-state
# recompiles, the exact-histogram reconciliation identity must hold, the
# burn-rate alert must FIRE inside the crowd and CLEAR after the drain
# (a stuck alert is the SLO watchdog bit 128 -> nonzero exit), and the
# exported OpenMetrics/JSONL artifacts must parse and reconcile against
# the serve record
slo_dir=$(mktemp -d)
env JAX_PLATFORMS=cpu python bench.py --serve --no-history \
    --out-dir "$slo_dir"
slo_rc=$?
if [ "$slo_rc" -eq 0 ]; then
    env JAX_PLATFORMS=cpu python - "$slo_dir" <<'PYEOF'
import json, os, sys
from deneva_tpu.obs import telemetry
d = sys.argv[1]
doc = json.load(open(os.path.join(d, "serve_slo.json")))
assert doc["metric"] == "serve_slo", doc["metric"]
assert doc["watchdog"] == 0 and doc["steady_recompiles"] == 0, doc
kinds = [e[1] for e in doc["alerts"]]
assert kinds and kinds[0] == "fire" and "clear" in kinds, doc["alerts"]
assert kinds[-1] == "clear", "alert still firing at run end"
recs = [json.loads(ln) for ln in
        open(os.path.join(d, "telemetry.jsonl"))]
assert [r["poll"] for r in recs] == list(range(len(recs)))
assert all(r["schema"] == telemetry.JSONL_SCHEMA for r in recs)
om = telemetry.parse_openmetrics(
    open(os.path.join(d, "metrics.om")).read())
assert om["eof"], "OpenMetrics exposition not EOF-terminated"
cnt = telemetry.sample_value(
    om, f"{telemetry.HIST_METRIC}_count", family=0)
assert cnt == recs[-1]["hist_total"], (cnt, recs[-1]["hist_total"])
print(f"[telemetry] p99={doc['value']} alerts={doc['alerts']} "
      f"breach_ticks={doc['breach_ticks']} polls={len(recs)}")
PYEOF
    slo_rc=$?
fi
rm -rf "$slo_dir"
if [ "$slo_rc" -ne 0 ]; then
    echo "telemetry smoke FAILED (serve/reconcile/export rc=$slo_rc)"
    exit "$slo_rc"
fi

echo "== causal diagnosis smoke (windowed deltas + differential diff) =="
# the diagnosis observatory end to end (deneva_tpu/obs/{windows,diff}.py):
# two short windowed runs differing by ONE knob (the CC plugin) must each
# prove the sum-of-deltas identity on the live engine ("[windows] ...
# identity OK" is a hard exit gate inside bench.py), diffing their
# records must emit a [diagnosis] whose ranked causes name a config
# lever, and the within-run phase split (--diff REC --windows) must
# segment the same record exactly
diag_dir=$(mktemp -d)
diag_rc=0
for alg in NO_WAIT WAIT_DIE; do
    env JAX_PLATFORMS=cpu python bench.py --windows --window-ticks 8 \
        --ticks 64 --cc-alg "$alg" --no-history --out-dir "$diag_dir" \
        > "$diag_dir/$alg.log" 2>&1 || diag_rc=$?
    grep -q "identity OK" "$diag_dir/$alg.log" || diag_rc=1
done
if [ "$diag_rc" -eq 0 ]; then
    rec_a=$(sed -n 's/^\[obs\] run record: //p' "$diag_dir/NO_WAIT.log")
    rec_b=$(sed -n 's/^\[obs\] run record: //p' "$diag_dir/WAIT_DIE.log")
    env JAX_PLATFORMS=cpu python -m deneva_tpu.obs.diff \
        "$rec_a" "$rec_b" -o "$diag_dir/diag.json" \
        > "$diag_dir/diff.log" 2>&1 || diag_rc=$?
    env JAX_PLATFORMS=cpu python bench.py --diff "$rec_b" --windows \
        >> "$diag_dir/diff.log" 2>&1 || diag_rc=$?
fi
if [ "$diag_rc" -eq 0 ]; then
    env JAX_PLATFORMS=cpu python - "$diag_dir" <<'PYEOF'
import json, os, sys
d = sys.argv[1]
log = open(os.path.join(d, "diff.log")).read()
assert log.count("[diagnosis]") == 2, "run diff + window diff reports"
diag = json.load(open(os.path.join(d, "diag.json")))
assert diag["kind"] == "run_diff" and diag["causes"], diag
assert diag["top_cause"] and diag["top_lever"], diag
# the one-knob delta must surface in the abort taxonomy: the WAIT_DIE
# side aborts by wound, the NO_WAIT side by immediate conflict
names = {c["cause"] for c in diag["causes"]}
assert any(n.startswith("abort_mix[") for n in names), names
print(f"[diff] {len(diag['causes'])} ranked cause(s); verdict "
      f"{diag['top_cause']} -> Config.{diag['top_lever']}")
PYEOF
    diag_rc=$?
fi
rm -rf "$diag_dir"
if [ "$diag_rc" -ne 0 ]; then
    echo "causal diagnosis smoke FAILED (identity/diff rc=$diag_rc)"
    exit "$diag_rc"
fi

echo "== bench regression gate =="
# gate the latest trajectory point (committed BENCH_r*.json snapshots +
# any results/bench_history.jsonl) against the median of its priors;
# exit code = number of regressions
env JAX_PLATFORMS=cpu python -m deneva_tpu.obs.regress \
    BENCH_r*.json results/
regress_rc=$?
if [ "$regress_rc" -ne 0 ]; then
    echo "bench regression gate FAILED (rc=$regress_rc)"
    exit "$regress_rc"
fi

echo "== tick certifier (lint engine 3) =="
# whole-program differential jaxpr certification over the full config
# matrix: off-path purity, carry fixed points, donation, racy scatters,
# dtype widening.  Exit code = number of unsuppressed findings.  The
# sharded cells need >= 4 virtual devices, hence the XLA flag.
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
    python -m deneva_tpu.lint --certify
certify_rc=$?
if [ "$certify_rc" -ne 0 ]; then
    echo "tick certifier FAILED (rc=$certify_rc unsuppressed findings)"
    exit "$certify_rc"
fi

echo "== sharded collective certifier (lint engine 4) =="
# post-partitioning StableHLO certification of the distributed data
# plane: lower every plugin x workload x distributed-flag cell through
# the SPMD partitioner and prove each collective against COMM_CONTRACT
# (declared site, legal combiner for its role, full-axis grouping, no
# loop-carried collectives, replicated regions communication-free).
# Exit code = number of unsuppressed findings.
timeout -k 10 720 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
    python -m deneva_tpu.lint --certify-sharded
shard_certify_rc=$?
if [ "$shard_certify_rc" -ne 0 ]; then
    echo "sharded collective certifier FAILED (rc=$shard_certify_rc" \
         "unsuppressed findings)"
    exit "$shard_certify_rc"
fi

echo "== tier-1 pytest =="
rm -f /tmp/_t1.log
timeout -k 10 1080 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit "$rc"
