"""Sweep batch_size x admit_cap for the headline faithful/greedy cells.

The tuned operating point shifts whenever the tick gets faster (the
abort-rate equilibrium depends on in-flight concurrency, not kernel cost),
so re-run this after kernel work and pin the winners in bench.py.

Usage: python experiments/sweep_operating_point.py [faithful|greedy|both]
"""

from __future__ import annotations

import sys
import os
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine

ITERS = 200


def cell(window, B, cap):
    cfg = Config(cc_alg="NO_WAIT", batch_size=B, synth_table_size=1 << 24,
                 req_per_query=10, zipf_theta=0.6, tup_read_perc=0.5,
                 query_pool_size=1 << 16, warmup_ticks=0, backoff=True,
                 acquire_window=window, admit_cap=cap)
    eng = Engine(cfg)
    st = eng.run_compiled(ITERS)
    st = eng.run_compiled(ITERS, st)
    jax.block_until_ready(st.stats["txn_cnt"])
    tputs = []
    for _ in range(3):
        before = int(np.asarray(st.stats["txn_cnt"]))
        t0 = time.perf_counter()
        st = eng.run_compiled(ITERS, st)
        jax.block_until_ready(st.stats["txn_cnt"])
        dt = time.perf_counter() - t0
        tputs.append((int(np.asarray(st.stats["txn_cnt"])) - before) / dt)
    s = eng.summary(st)
    tput = float(np.median(tputs))
    print(f"win={window} B={B:>6} cap={cap!s:>5}: {tput/1e3:8.1f} k/s  "
          f"abort={s['abort_rate']:.3f}", flush=True)
    return tput


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which in ("faithful", "both"):
        print("# faithful (window=1)")
        for B, cap in [(4096, 512), (4096, 1024), (8192, 1024),
                       (8192, 2048), (16384, 2048), (16384, 4096)]:
            cell(1, B, cap)
    if which in ("greedy", "both"):
        print("# greedy (window=10)")
        for B, cap in [(8192, 1024), (8192, 2048), (16384, 2048),
                       (16384, 1024)]:
            cell(10, B, cap)


if __name__ == "__main__":
    main()
