"""Sweep batch_size x admit_cap for the headline faithful/greedy cells.

The tuned operating point shifts whenever the tick gets faster (the
abort-rate equilibrium depends on in-flight concurrency, not kernel cost),
so re-run this after kernel work and pin the winners in bench.py.

Measurement goes through bench.run_cell — the SAME warmup/median protocol
as the benchmark that pins the winners.

Usage: python experiments/sweep_operating_point.py [faithful|greedy|both]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bench import YCSB_KW, run_cell  # noqa: E402
from deneva_tpu.config import Config  # noqa: E402


def cell(window, B, cap):
    cfg = Config(cc_alg="NO_WAIT",
                 **{**YCSB_KW, "batch_size": B, "admit_cap": cap,
                    "acquire_window": window})
    tput, cpt = run_cell(cfg, n_ticks=200)
    print(f"win={window} B={B:>6} cap={cap!s:>5}: {tput/1e3:8.1f} k/s  "
          f"commits/tick={cpt:7.1f}", flush=True)
    return tput


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which in ("faithful", "both"):
        print("# faithful (window=1)")
        for B, cap in [(4096, 512), (4096, 1024), (8192, 1024),
                       (8192, 2048), (16384, 2048), (16384, 4096)]:
            cell(1, B, cap)
    if which in ("greedy", "both"):
        print("# greedy (window=10)")
        for B, cap in [(8192, 1024), (8192, 2048), (16384, 2048),
                       (16384, 1024)]:
            cell(10, B, cap)


if __name__ == "__main__":
    main()
