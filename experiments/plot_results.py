"""Plot the sweep grids — the rebuild of scripts/plot.py / paper_plots.py.

Reads the cached per-cell results (results/<grid>.<alg>.<idx>.json, written
by run_grid.py) and renders the VLDB'17-style curves: throughput vs node
count and throughput/abort-rate vs zipf theta, one line per CC algorithm.

Chart conventions (dataviz method): line form for change-over-a-dimension;
categorical hues assigned in a FIXED validated order (the reference
palette's slots 1-7, pre-validated for adjacent-pair CVD separation on a
white surface); one axis per panel; recessive grid; legend present (7
series is past the direct-label budget); text in ink, not series colors.

Usage: python experiments/plot_results.py   (writes results/plots/*.png)
"""

from __future__ import annotations

import json
import os
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

RESULTS_DIR = os.path.join(os.path.dirname(__file__), "..", "results")
PLOTS_DIR = os.path.join(RESULTS_DIR, "plots")

from experiments._plot_style import INK, PALETTE, style_axes as style  # noqa: E402,E501

ALGS = ("NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT", "CALVIN")
COLORS = PALETTE[:7]


def load(grid: str) -> dict:
    """All cached cells per algorithm (glob, so a missing middle index
    cannot silently truncate a curve)."""
    import glob as _glob
    rows = {}
    for alg in ALGS:
        paths = sorted(_glob.glob(
            os.path.join(RESULTS_DIR, f"{grid}.{alg}.*.json")))
        for path in paths:
            with open(path) as f:
                rows.setdefault(alg, []).append(json.load(f))
    return rows


def plot_lines(ax, rows, xs_of, y_of):
    for alg, color in zip(ALGS, COLORS):
        cells = rows.get(alg, [])
        if not cells:
            continue
        xs = [xs_of(c) for c in cells]
        ys = [y_of(c) for c in cells]
        order = sorted(range(len(xs)), key=lambda i: xs[i])
        ax.plot([xs[i] for i in order], [ys[i] for i in order],
                color=color, linewidth=2, marker="o", markersize=5,
                label=alg, zorder=3)


def main():
    os.makedirs(PLOTS_DIR, exist_ok=True)

    n_of = lambda c: int(c["cell"].split("-n")[1])  # noqa: E731
    for grid, xlabel, xs_of in (
            ("ycsb_scaling", "nodes", n_of),
            ("tpcc_scaling", "nodes", n_of),
            ("tpcc_scaling2", "nodes", n_of),
            ("pps_scaling", "nodes", n_of),
            ("ycsb_partitions", "partitions per txn (D=1)",
             lambda c: int(c["cell"].split("-ppt")[1]))):
        rows = load(grid)
        if not rows:
            continue
        fig, ax = plt.subplots(figsize=(5.2, 3.4), dpi=150)
        plot_lines(ax, rows, xs_of, lambda c: c["row"]["txn_cnt"])
        style(ax, xlabel, "committed txns (30 measured ticks)",
              f"{grid}: total commits vs cluster size")
        ax.legend(fontsize=7, frameon=False, ncol=2, labelcolor=INK)
        fig.tight_layout()
        fig.savefig(os.path.join(PLOTS_DIR, f"{grid}.png"))
        plt.close(fig)

    rows = load("ycsb_skew")
    if rows:
        theta_of = lambda c: float(c["cell"].split("-th")[1])  # noqa: E731
        fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(9.6, 3.4), dpi=150)
        plot_lines(ax1, rows, theta_of, lambda c: c["row"]["tput_per_tick"])
        style(ax1, "zipf theta", "commits per tick",
              "ycsb_skew: throughput vs skew (8 nodes)")
        ax1.legend(fontsize=7, frameon=False, ncol=2, labelcolor=INK)
        plot_lines(ax2, rows, theta_of, lambda c: c["row"]["abort_rate"])
        style(ax2, "zipf theta", "abort rate",
              "ycsb_skew: abort rate vs skew")
        ax2.set_ylim(-0.02, 1.0)
        fig.tight_layout()
        fig.savefig(os.path.join(PLOTS_DIR, "ycsb_skew.png"))
        plt.close(fig)

    rows = load("ycsb_network")
    if rows:
        d_of = lambda c: int(c["cell"].split("-d")[1])  # noqa: E731
        fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(9.6, 3.4), dpi=150)
        plot_lines(ax1, rows, d_of, lambda c: c["row"]["tput_per_tick"])
        style(ax1, "one-way message delay D (ticks)", "commits per tick",
              "ycsb_network: the distributed tax (4 nodes)")
        ax1.legend(fontsize=7, frameon=False, ncol=2, labelcolor=INK)
        plot_lines(ax2, rows, d_of,
                   lambda c: c["row"]["avg_latency_ticks_short"])
        style(ax2, "one-way message delay D (ticks)",
              "commit latency (ticks)",
              "ycsb_network: latency vs delay")
        fig.tight_layout()
        fig.savefig(os.path.join(PLOTS_DIR, "ycsb_network.png"))
        plt.close(fig)

    print(f"wrote plots to {PLOTS_DIR}")


if __name__ == "__main__":
    main()
