"""Per-HLO-op time breakdown of an engine run from a jax.profiler trace.

Captures a trace of `run_compiled` on the current default device, parses the
xplane protobuf (via tensorflow's bundled xplane_pb2 — the plugin's converter
is version-incompatible here), and prints the top ops by total self-time,
aggregated by HLO op name and by category.

Usage:
  python experiments/profile_hlo.py [--mode NORMAL] [--batch 8192] [--top 40]
"""

from __future__ import annotations

import argparse
import collections
import glob
import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")


def capture(batch: int, mode: str, ticks: int = 200) -> str:
    import jax
    from deneva_tpu.config import Config
    from deneva_tpu.engine.scheduler import Engine

    cfg = Config(cc_alg="NO_WAIT", batch_size=batch,
                 synth_table_size=1 << 24, req_per_query=10, zipf_theta=0.6,
                 tup_read_perc=0.5, query_pool_size=1 << 16, warmup_ticks=0,
                 backoff=True, acquire_window=1, admit_cap=1024, mode=mode)
    eng = Engine(cfg)
    st = eng.run_compiled(ticks)
    st = eng.run_compiled(ticks, st)
    jax.block_until_ready(st.stats["txn_cnt"])
    tdir = tempfile.mkdtemp(prefix="hloprof")
    with jax.profiler.trace(tdir):
        st = eng.run_compiled(ticks, st)
        jax.block_until_ready(st.stats["txn_cnt"])
    pbs = glob.glob(os.path.join(tdir, "**", "*.xplane.pb"), recursive=True)
    assert pbs, f"no trace written under {tdir}"
    return pbs[0]


#: leading fusion-instance counters etc.: "fusion.123" -> "fusion"
_NAME_RE = re.compile(r"^([a-zA-Z-_]+)")


def op_table(pb_path: str, ticks: int):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(pb_path, "rb") as f:
        xs.ParseFromString(f.read())

    by_op = collections.Counter()
    occ = collections.Counter()
    total_ps = 0
    for plane in xs.planes:
        if "TPU" not in plane.name and "/device" not in plane.name.lower():
            continue
        metas = {m.id: m.name for m in plane.event_metadata.values()} if \
            isinstance(plane.event_metadata, dict) else \
            {mid: m.name for mid, m in plane.event_metadata.items()}
        for line in plane.lines:
            if "XLA Ops" not in line.name and "xla op" not in \
                    line.name.lower():
                continue
            for ev in line.events:
                name = metas.get(ev.metadata_id, str(ev.metadata_id))
                m = _NAME_RE.match(name)
                key = m.group(1) if m else name
                by_op[key] += ev.duration_ps
                occ[key] += 1
                total_ps += ev.duration_ps
    return by_op, occ, total_ps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="NORMAL")
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--ticks", type=int, default=200)
    ap.add_argument("--pb", help="parse an existing .xplane.pb instead")
    args = ap.parse_args()

    pb = args.pb or capture(args.batch, args.mode, args.ticks)
    by_op, occ, total_ps = op_table(pb, args.ticks)
    print(f"# {pb}")
    print(f"total device op-time: {total_ps/1e9:.3f} ms over {args.ticks} "
          f"ticks = {total_ps/1e9/args.ticks:.4f} ms/tick")
    print(f"{'op':<40} {'ms/tick':>9} {'%':>6} {'count':>8}")
    for op, ps in by_op.most_common(args.top):
        print(f"{op:<40} {ps/1e9/args.ticks:>9.4f} "
              f"{100*ps/max(total_ps,1):>6.1f} {occ[op]:>8}")


if __name__ == "__main__":
    main()
