"""Per-phase cost of the sharded tick, by end-to-end ablation.

The reference accumulates per-queue mutex and processing timers on the
host (message queue, work queue — statistics/stats.h time families).  In
the rebuild one tick is ONE fused XLA program, so phase wall-times cannot
be read from inside a run; instead this harness ablates whole-tick
configurations whose deltas attribute cost to phases:

  local-only tick   (mpr=0):    admission + local arbitration + commit
  mixed tick        (mpr=1):    + pack + 3 all_to_all exchanges + unpack
  NOCC mixed tick:              mixed minus the CC arbitration kernel

so  exchange+routing ~= mixed - local,  arbitration ~= mixed - NOCC.
End-to-end ablation is the only honest attribution: isolated micro-
kernels get dead-code-eliminated or lose their fusion context (the
PROFILE.md cost model was measured the same way).  The per-run [summary]
line carries phase WORK counters instead (remote_entry_cnt,
commit_defer_cnt, lat_network_time).

Usage: python experiments/profile_phases.py [n_nodes] [batch]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax
import numpy as np

jax.config.update("jax_platforms", "cpu")

from deneva_tpu.config import Config
from deneva_tpu.parallel.sharded import ShardedEngine

ITERS = 30


def time_tick(cfg) -> float:
    eng = ShardedEngine(cfg)
    eng._build()
    st = eng.init_state()
    for _ in range(3):                      # compile + warm
        st = eng._jit_tick(st)
    jax.block_until_ready(st.tick)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            st = eng._jit_tick(st)
        jax.block_until_ready(st.tick)
        ts.append((time.perf_counter() - t0) / ITERS * 1e3)
    return float(np.median(ts))


def main(n_nodes: int = 4, B: int = 256):
    base = dict(cc_alg="NO_WAIT", node_cnt=n_nodes, part_cnt=n_nodes,
                batch_size=B, synth_table_size=1 << 14, req_per_query=6,
                query_pool_size=1 << 12)
    t_local = time_tick(Config(mpr=0.0, part_per_txn=1, **base))
    t_mixed = time_tick(Config(mpr=1.0, part_per_txn=2, **base))
    t_nocc = time_tick(Config(mpr=1.0, part_per_txn=2, mode="NOCC",
                              **base))

    print(f"# sharded phase costs by ablation, {n_nodes} nodes, B={B} "
          f"(virtual CPU mesh; shapes-only, the real fabric is ICI)")
    print(f"local-only tick (no remote routing): {t_local:.3f} ms")
    print(f"mixed tick (pack + 3 exchanges):     {t_mixed:.3f} ms")
    print(f"NOCC mixed tick (no arbitration):    {t_nocc:.3f} ms")
    print(f"-> routing + exchange share: {t_mixed - t_local:+.3f} ms")
    print(f"-> CC arbitration share:     {t_mixed - t_nocc:+.3f} ms")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 4,
         int(sys.argv[2]) if len(sys.argv) > 2 else 256)
