"""Experiment sweep harness — rebuild of scripts/run_experiments.py +
experiments.py.

The reference's harness rewrites config.h per (CC_ALG x workload x knobs)
cell, rebuilds binaries, runs the cluster, and collects ``[summary]`` lines
parsed by parse_results.py (experiments.py:345-407).  Here a cell is a
Config; multi-node cells run the real ShardedEngine on a virtual CPU device
mesh (the TPORT_TYPE=IPC local mode analog, SURVEY.md §4), single-node
cells run the single-shard engine; every cell's ``[summary]`` line is
appended to results/<grid>.out in the parse_results.py format.

Each (grid, algorithm) slice runs in its OWN subprocess — the rebuild of
the reference running each config as a separate binary, and a workaround
for XLA:CPU collective rendezvous deadlocks after many shard_map programs
accumulate in one process.

Grids mirror scripts/experiments.py:
- ycsb_scaling     (:61-75):  NODE_CNT x CC_ALG, zipf 0.6, 50/50 rw
- ycsb_skew        (:100-113): fixed nodes, zipf theta in {0 .. 0.9}
- ycsb_network     (msg_queue.cpp:81-124): net_delay_ticks in {0,1,4}
- ycsb_partitions  (:303-341): PART_PER_TXN sweep, strict_ppt
- isolation_levels (config.h:336-340): the 4-level ladder, lock family
- tpcc_scaling     (:303-341 map): TPC-C, NUM_WH ~ PART_CNT (contended)
- tpcc_scaling2    (:303-341 map): NUM_WH scaled 16x/node (throughput)
- pps_scaling      (:51-58): PPS, NODE_CNT x CC_ALG
Row counts are scaled down from the paper's 16M/node to fit the CPU-mesh
CI budget; the SHAPES of the curves (Calvin flat under contention, NO_WAIT
collapsing at high theta) are the assertions, not absolute numbers
(EXPERIMENTS.md).

Usage:  python experiments/run_grid.py [grid ...]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

RESULTS_DIR = os.path.join(os.path.dirname(__file__), "..", "results")

CC_ALGS = ("NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT",
           "CALVIN")
SCALING_NODES = (1, 2, 4, 8)
SKEW_THETAS = (0.0, 0.25, 0.5, 0.55, 0.6, 0.65, 0.7, 0.75, 0.8, 0.9)
SKEW_NODES = 8          # reference uses 16 (experiments.py:100); mesh is 8
N_TICKS = 40
WARMUP = 10


def base_cfg(**kw):
    from deneva_tpu.config import Config
    base = dict(batch_size=32, req_per_query=10, tup_read_perc=0.5,
                query_pool_size=1 << 12, warmup_ticks=WARMUP,
                zipf_theta=0.6, mpr=1.0, part_per_txn=2)
    base.update(kw)
    return Config(**base)


def cells_for(grid: str, alg: str):
    """Yield (cell_name, cfg, n_ticks) per grid slice.  TPC-C cells run
    200 ticks: a NewOrder is up to ~33 sequential accesses (one per tick,
    reference-faithful), so short runs cannot even complete txns that
    abort once mid-program — the round-3 grids' degenerate 2PL cells were
    mostly THIS length artifact, not CC behavior."""
    if grid == "ycsb_scaling":
        for n in SCALING_NODES:
            yield (f"{alg}-n{n}",
                   base_cfg(cc_alg=alg, node_cnt=n, part_cnt=n,
                            synth_table_size=1 << 17), N_TICKS)
    elif grid == "ycsb_skew":
        # table sized so the theta=0 baseline is conflict-light (the paper
        # grid uses 16M rows/node; 2^17 keeps the same qualitative regime
        # at CI scale)
        for th in SKEW_THETAS:
            yield (f"{alg}-th{th}",
                   base_cfg(cc_alg=alg, node_cnt=SKEW_NODES,
                            part_cnt=SKEW_NODES, zipf_theta=th,
                            synth_table_size=1 << 17), N_TICKS)
    elif grid == "ycsb_skew16":
        # the paper's ACTUAL skew grid shape: 16 nodes
        # (scripts/experiments.py:100 uses 16 servers); runs on 16 VIRTUAL
        # CPU devices (the worker sizes the platform per cell)
        for th in (0.0, 0.6, 0.9):
            yield (f"{alg}-th{th}",
                   base_cfg(cc_alg=alg, node_cnt=16, part_cnt=16,
                            zipf_theta=th,
                            synth_table_size=1 << 17), N_TICKS)
    elif grid == "ycsb_network":
        # the distributed-tax sweep (NETWORK_DELAY_TEST,
        # msg_queue.cpp:81-124): fixed 4-node mesh, one-way delay D in
        # ticks; runs long enough that D=4's ~50-tick txn lifetimes reach
        # steady state
        for D in (0, 1, 4):
            yield (f"{alg}-d{D}",
                   base_cfg(cc_alg=alg, node_cnt=4, part_cnt=4,
                            net_delay_ticks=D, synth_table_size=1 << 17,
                            warmup_ticks=50), 150)
    elif grid == "ycsb_partitions":
        # PART_PER_TXN sweep (scripts/experiments.py:303-341
        # ycsb_partitions): strict_ppt so each txn touches EXACTLY that
        # many partitions; run UNDER the network cost model (D=1) — at
        # D=0 multi-partition coordination is free and the curve is flat
        # (the reference's sweep is only meaningful because messages cost)
        for ppt in (1, 2, 4, 8):
            yield (f"{alg}-ppt{ppt}",
                   base_cfg(cc_alg=alg, node_cnt=8, part_cnt=8,
                            part_per_txn=ppt, strict_ppt=True,
                            net_delay_ticks=1, warmup_ticks=20,
                            synth_table_size=1 << 17), 80)
    elif grid == "isolation_levels":
        # isolation ladder (config.h:336-340); meaningful for the lock
        # family — other algorithms yield no cells
        if alg in ("NO_WAIT", "WAIT_DIE"):
            for lvl in ("SERIALIZABLE", "READ_COMMITTED",
                        "READ_UNCOMMITTED", "NOLOCK"):
                yield (f"{alg}-{lvl}",
                       base_cfg(cc_alg=alg, node_cnt=4, part_cnt=4,
                                isolation_level=lvl,
                                synth_table_size=1 << 17), N_TICKS)
    elif grid == "tpcc_scaling":
        # the reference's contended regime (NUM_WH ~ PART_CNT): few
        # warehouses, every Payment/NewOrder colliding on wh + district
        # rows.  batch_size throttled to 8/node — the reference runs 4
        # worker threads/node (config.h THREAD_CNT), so B=32 in-flight
        # txns/node was an operating point the reference never sees
        for n in SCALING_NODES:
            yield (f"{alg}-n{n}",
                   base_cfg(cc_alg=alg, workload="TPCC", node_cnt=n,
                            part_cnt=n, num_wh=2 * n, batch_size=8,
                            cust_per_dist=1000, max_items=64,
                            warmup_ticks=50,
                            synth_table_size=2048 * 8), 200)
    elif grid == "tpcc_scaling2":
        # the reference's scaled-warehouse regime (NUM_WH=128 x NODE_CNT,
        # scripts/experiments.py:303-341) at CI scale: 16 wh/node keeps
        # the same in-flight/warehouse ratio story — 2PL aborts < 0.6,
        # commits comparable to the T/O family
        for n in SCALING_NODES:
            yield (f"{alg}-n{n}",
                   base_cfg(cc_alg=alg, workload="TPCC", node_cnt=n,
                            part_cnt=n, num_wh=16 * n, batch_size=8,
                            cust_per_dist=1000, max_items=64,
                            warmup_ticks=50,
                            synth_table_size=2048 * 8), 200)
    elif grid == "pps_scaling":
        # PPS product-parts-supplier scaling (scripts/experiments.py:51-58)
        for n in SCALING_NODES:
            yield (f"{alg}-n{n}",
                   base_cfg(cc_alg=alg, workload="PPS", node_cnt=n,
                            part_cnt=n, batch_size=32,
                            synth_table_size=1 << 14), 60)
    else:  # pragma: no cover
        raise ValueError(grid)


GRIDS = ("ycsb_scaling", "ycsb_skew", "ycsb_skew16", "ycsb_network",
         "ycsb_partitions", "isolation_levels", "tpcc_scaling",
         "tpcc_scaling2", "pps_scaling")


def run_cell(cfg, n_ticks=N_TICKS):
    t0 = time.perf_counter()
    if cfg.node_cnt == 1:
        from deneva_tpu.engine.scheduler import Engine
        eng = Engine(cfg)
    else:
        from deneva_tpu.parallel.sharded import ShardedEngine
        eng = ShardedEngine(cfg)
    # one fused dispatch: with few host cores behind the virtual mesh,
    # per-tick dispatch churn can starve the XLA:CPU collective rendezvous
    st = eng.run_compiled(n_ticks)
    wall = time.perf_counter() - t0
    s = eng.summary(st)
    return ({k: v for k, v in s.items() if np.isscalar(v)},
            eng.summary_line(st, wall_seconds=wall))


def worker(grid: str, alg: str, idx: int):
    cell_name, cfg, n_ticks = list(cells_for(grid, alg))[idx]
    ndev = max(cfg.node_cnt, 8)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={ndev}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    s, line = run_cell(cfg, n_ticks)
    print(f"{grid}/{cell_name}: txn_cnt={s['txn_cnt']} "
          f"abort_rate={s['abort_rate']:.3f} "
          f"tput_per_tick={s['tput_per_tick']:.2f}", flush=True)
    os.makedirs(RESULTS_DIR, exist_ok=True)
    path = os.path.join(RESULTS_DIR, f"{grid}.{alg}.{idx}.json")
    with open(path, "w") as f:
        json.dump({"cell": cell_name, "row": s, "line": line}, f)


GRID_NOTES = {
    "tpcc_scaling": "Contended regime (NUM_WH ~ PART_CNT, B=8/node — the "
    "reference runs 4 worker threads/node, so a 32-txn in-flight window "
    "was an operating point it never sees; 200 ticks because a NewOrder "
    "is ~33 sequential accesses).",
    "tpcc_scaling2": "Scaled-warehouse regime (16 wh/node): the same "
    "admission throttle with warehouse headroom — 2PL aborts < 0.6 and "
    "commits comparable to the T/O family (the reference's "
    "NUM_WH=128xNODE_CNT map at CI scale).",
    "ycsb_network": "net_delay_ticks sweep (NETWORK_DELAY_TEST analog): "
    "remote accesses pay 2D ticks, 2PC prepare 2D more; CALVIN pays D "
    "per epoch + D at finish only — the deterministic protocol's "
    "graceful degradation is the reference paper's headline.",
    "ycsb_partitions": "part_per_txn sweep under D=1: each extra "
    "partition adds per-access round trips and a wider 2PC fan-out.",
    "isolation_levels": "Lock-family ladder: weaker isolation releases "
    "read locks early (RC/RU) or skips them (NOLOCK), monotonically "
    "shedding aborts.",
    "pps_scaling": "PPS 8-type mix with chain walks; CALVIN's recon "
    "types (GETPARTBY*/ORDERPRODUCT) pay the one-epoch recon pass with "
    "its read-lock shadow traffic.",
}


def emit_markdown(all_rows: dict, path: str):
    lines = ["# EXPERIMENTS — sweep grids on the virtual 8-device CPU mesh",
             "",
             "Generated by `experiments/run_grid.py` (the rebuild of "
             "scripts/run_experiments.py; grids from scripts/experiments.py"
             ":61-113,303-341). Row counts are scaled to the CI budget — "
             "curve SHAPES are the contract, not absolute numbers. Every "
             "cell's `[summary]` line is in `results/<grid>.out` in the "
             "parse_results.py format.",
             ""]
    for grid, rows in all_rows.items():
        lines.append(f"## {grid}")
        lines.append("")
        if grid in GRID_NOTES:
            lines.append(GRID_NOTES[grid])
            lines.append("")
        lines.append("| cell | committed txns | abort rate | commits/tick |")
        lines.append("|---|---|---|---|")
        for cell, s in rows.items():
            lines.append(f"| {cell} | {s['txn_cnt']} | "
                         f"{s['abort_rate']:.3f} | "
                         f"{s['tput_per_tick']:.2f} |")
        lines.append("")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def qualitative_checks(all_rows: dict) -> list[str]:
    """The known curve shapes from the VLDB'17 evaluation."""
    notes = []
    skew = all_rows.get("ycsb_skew", {})
    if skew:
        nw_lo = skew["NO_WAIT-th0.0"]["abort_rate"]
        nw_hi = skew["NO_WAIT-th0.9"]["abort_rate"]
        notes.append(f"NO_WAIT abort rate rises {nw_lo:.3f} -> {nw_hi:.3f} "
                     f"with skew (collapse at high theta): "
                     f"{'OK' if nw_hi > 0.5 and nw_lo < 0.1 else 'UNEXPECTED'}")
        cv = [skew[f"CALVIN-th{t}"]["abort_rate"] for t in (0.0, 0.6, 0.9)]
        notes.append(f"CALVIN abort-free at every skew {cv}: "
                     f"{'OK' if all(v == 0 for v in cv) else 'UNEXPECTED'}")
        # Calvin's paper-grade flatness relies on draining lock chains
        # within an epoch; the tick-quantized rebuild pays one tick per
        # hot-row chain link, so the honest check is relative: Calvin keeps
        # pace with the lock-based family at extreme skew WITHOUT aborting
        s16 = all_rows.get("ycsb_skew16", {})
        if s16:
            nw = [s16[f"NO_WAIT-th{t}"]["abort_rate"] for t in (0.0, 0.9)]
            cv16 = [s16[f"CALVIN-th{t}"]["abort_rate"] for t in (0.0, 0.9)]
            notes.append(
                f"16-node (the paper's grid shape): NO_WAIT abort "
                f"{nw[0]:.3f} -> {nw[1]:.3f} with skew, CALVIN abort-free "
                f"{cv16}: "
                f"{'OK' if nw[1] > 0.5 and all(v == 0 for v in cv16) else 'UNEXPECTED'}")
        cv9 = skew["CALVIN-th0.9"]["tput_per_tick"]
        nw9 = skew["NO_WAIT-th0.9"]["tput_per_tick"]
        notes.append(
            f"CALVIN tput at theta=0.9 within 2x of NO_WAIT with zero "
            f"aborts ({cv9:.1f} vs {nw9:.1f}): "
            f"{'OK' if cv9 > 0.5 * nw9 else 'UNEXPECTED'} "
            f"(tick-quantized epochs drain one hot-chain link per tick, "
            f"unlike the reference's sub-epoch draining)")
    scal = all_rows.get("ycsb_scaling", {})
    if scal:
        for alg in ("NO_WAIT", "CALVIN"):
            t1 = scal[f"{alg}-n1"]["txn_cnt"]
            t8 = scal[f"{alg}-n8"]["txn_cnt"]
            notes.append(f"{alg} total commits grow 1->8 nodes "
                         f"({t1} -> {t8}): "
                         f"{'OK' if t8 > t1 else 'UNEXPECTED'}")
    net = all_rows.get("ycsb_network", {})
    if net:
        # the distributed tax: tput falls and latency rises with delay
        for alg in ("NO_WAIT", "MAAT", "CALVIN"):
            tp = [net[f"{alg}-d{d}"]["tput_per_tick"] for d in (0, 1, 4)]
            lat = [net[f"{alg}-d{d}"]["avg_latency_ticks_short"]
                   for d in (0, 1, 4)]
            notes.append(
                f"{alg} pays the network: tput/tick {tp[0]:.1f} -> "
                f"{tp[1]:.1f} -> {tp[2]:.1f}, latency {lat[0]:.1f} -> "
                f"{lat[1]:.1f} -> {lat[2]:.1f} ticks at D=0/1/4: "
                f"{'OK' if tp[0] > tp[1] > tp[2] and lat[0] < lat[1] < lat[2] else 'UNEXPECTED'}")
        nw = [net[f"NO_WAIT-d{d}"]["lat_network_time"] for d in (0, 1, 4)]
        notes.append(
            f"NO_WAIT network-wait integral grows with D "
            f"({nw[1]:.0f} -> {nw[2]:.0f} txn-ticks at D=1/4): "
            f"{'OK' if nw[2] > nw[1] > 0 else 'UNEXPECTED'}")
    part = all_rows.get("ycsb_partitions", {})
    if part:
        for alg in ("NO_WAIT", "MAAT"):
            tp = [part[f"{alg}-ppt{p}"]["tput_per_tick"] for p in (1, 2, 8)]
            notes.append(
                f"{alg} multi-partition cost: tput/tick {tp[0]:.1f} -> "
                f"{tp[1]:.1f} -> {tp[2]:.1f} at 1/2/8 parts per txn: "
                f"{'OK' if tp[0] >= tp[1] >= tp[2] else 'UNEXPECTED'}")
    iso = all_rows.get("isolation_levels", {})
    if iso:
        ab = {lvl: iso[f"NO_WAIT-{lvl}"]["abort_rate"]
              for lvl in ("SERIALIZABLE", "READ_COMMITTED", "NOLOCK")}
        notes.append(
            f"NO_WAIT abort rate falls as isolation weakens "
            f"(SER {ab['SERIALIZABLE']:.3f} >= RC "
            f"{ab['READ_COMMITTED']:.3f} >= NOLOCK {ab['NOLOCK']:.3f}): "
            f"{'OK' if ab['SERIALIZABLE'] >= ab['READ_COMMITTED'] >= ab['NOLOCK'] else 'UNEXPECTED'}")
    t2 = all_rows.get("tpcc_scaling2", {})
    if t2:
        for alg in ("NO_WAIT", "WAIT_DIE"):
            a1 = t2[f"{alg}-n1"]["abort_rate"]
            c1 = t2[f"{alg}-n1"]["txn_cnt"]
            ts1 = t2["TIMESTAMP-n1"]["txn_cnt"]
            notes.append(
                f"{alg} tpcc_scaling2 n1: abort {a1:.3f} < 0.6 and commits "
                f"{c1} within 2.5x of TIMESTAMP's {ts1}: "
                f"{'OK' if a1 < 0.6 and c1 * 2.5 >= ts1 else 'UNEXPECTED'}")
    pps = all_rows.get("pps_scaling", {})
    if pps:
        t1 = pps["NO_WAIT-n1"]["txn_cnt"]
        t8 = pps["NO_WAIT-n8"]["txn_cnt"]
        notes.append(f"NO_WAIT PPS commits grow 1->8 nodes "
                     f"({t1} -> {t8}): "
                     f"{'OK' if t8 > t1 else 'UNEXPECTED'}")
        # CALVIN pays a one-time cliff from n1 to n2 (recon deferral +
        # cross-node hot USES chains drain one FIFO link per tick); the
        # distributed-scaling check is n2 -> n8
        c2 = pps["CALVIN-n2"]["txn_cnt"]
        c8 = pps["CALVIN-n8"]["txn_cnt"]
        notes.append(f"CALVIN PPS commits grow 2->8 nodes "
                     f"({c2} -> {c8}; n1 runs chain-local with no recon "
                     f"shadow traffic to pay): "
                     f"{'OK' if c8 > c2 else 'UNEXPECTED'}")
    return notes


def emit_only(grids):
    """Rebuild EXPERIMENTS.md from cached results/<grid>.<alg>.<idx>.json."""
    all_rows = {}
    for g in grids:
        rows = {}
        for alg in CC_ALGS:
            for idx in range(len(list(cells_for(g, alg)))):
                path = os.path.join(RESULTS_DIR, f"{g}.{alg}.{idx}.json")
                with open(path) as f:
                    data = json.load(f)
                rows[data["cell"]] = data["row"]
        all_rows[g] = rows
    finish(all_rows)


def finish(all_rows):
    md_path = os.path.join(os.path.dirname(__file__), "..", "EXPERIMENTS.md")
    emit_markdown(all_rows, md_path)
    notes = qualitative_checks(all_rows)
    with open(md_path, "a") as f:
        f.write("\n## Qualitative shape checks (VLDB'17 expectations)\n\n")
        for n in notes:
            f.write(f"- {n}\n")
    for n in notes:
        print(n)


def main(grids):
    all_rows = {}
    for g in grids:
        rows = {}
        out_path = os.path.join(RESULTS_DIR, f"{g}.out")
        os.makedirs(RESULTS_DIR, exist_ok=True)
        with open(out_path, "w") as outf:
            for alg in CC_ALGS:
                n_cells = len(list(cells_for(g, alg)))
                for idx in range(n_cells):
                    for attempt in (1, 2, 3):   # XLA:CPU rendezvous can flake
                        try:
                            r = subprocess.run(
                                [sys.executable, __file__, "--worker", g,
                                 alg, str(idx)],
                                capture_output=True, text=True, timeout=1800)
                        except subprocess.TimeoutExpired as e:
                            # a hung worker (deadlocked rendezvous) counts
                            # as a failed attempt, not a sweep abort
                            r = subprocess.CompletedProcess(
                                e.cmd, 124, e.stdout or "", e.stderr or "")
                        if r.returncode == 0:
                            break
                    if r.returncode != 0:
                        print(f"WORKER FAILED {g}/{alg}#{idx}:\n"
                              f"{r.stdout[-1500:]}\n{r.stderr[-1500:]}")
                        raise SystemExit(1)
                    sys.stdout.write(r.stdout)
                    with open(os.path.join(RESULTS_DIR,
                                           f"{g}.{alg}.{idx}.json")) as f:
                        data = json.load(f)
                    rows[data["cell"]] = data["row"]
                    outf.write(f"# {data['cell']}\n{data['line']}\n")
        all_rows[g] = rows
    finish(all_rows)


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--worker":
        worker(sys.argv[2], sys.argv[3], int(sys.argv[4]))
    elif len(sys.argv) >= 2 and sys.argv[1] == "--emit-only":
        emit_only(sys.argv[2:] or list(GRIDS))
    else:
        main(sys.argv[1:] or list(GRIDS))
