"""Render the engine's event trace — the rebuild of scripts/timeline.py
(which consumes DEBUG_TIMELINE printfs, config.h:269).

Two panels from a run with Config.trace_ticks > 0:
1. per-tick event series: admissions / commits / aborts / waiting slots —
   the tensorized replacement for per-event printf lines;
2. recent txn lifetimes: (start_tick, duration) segments from the
   commit-latency sampling ring, one horizontal bar per committed txn —
   the Gantt view timeline.py draws from per-txn start/commit events.

Usage:
    from experiments.timeline_plot import render
    render(engine, state, "timeline.png")
"""

from __future__ import annotations

import numpy as np
import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from deneva_tpu.obs import trace as obs_trace  # noqa: E402
from experiments._plot_style import INK, PALETTE, style_axes  # noqa: E402

#: panel label -> (obs.trace column, color); obs.trace.timeline sums the
#: node axis of sharded (N, T, K) buffers for the cluster-wide view
SERIES = {"admitted": ("admit", PALETTE[0]),
          "committed": ("commit", PALETTE[2]),
          "aborted": ("abort", PALETTE[1]),
          "waiting slots": ("occ_waiting", PALETTE[3])}


def _lifetimes(stats):
    """(start, duration) samples; per-node rings concatenate their valid
    prefixes (matching ShardedEngine.summary)."""
    dur = np.asarray(stats["arr_lat_short"])
    start = np.asarray(stats["arr_lat_start"])
    cur = np.asarray(stats["lat_ring_cursor"])
    if dur.ndim == 2:
        parts = [(start[i][:min(int(cur[i]), dur.shape[1])],
                  dur[i][:min(int(cur[i]), dur.shape[1])])
                 for i in range(dur.shape[0])]
        return (np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]))
    n = min(int(cur), dur.shape[0])
    return start[:n], dur[:n]


def render(eng, state, path: str, max_lifetimes: int = 200):
    cfg = eng.cfg
    assert cfg.trace_ticks > 0, "run with Config.trace_ticks > 0"
    T = min(int(np.asarray(state.tick).max()), cfg.trace_ticks)
    tl = obs_trace.timeline(state.stats)
    series = {name: tl[col][:T] for name, (col, _) in SERIES.items()}

    start, dur = _lifetimes(state.stats)
    k = min(max_lifetimes, start.shape[0])

    fig, (ax1, ax2) = plt.subplots(2, 1, figsize=(8, 6), dpi=150,
                                   height_ratios=[1, 1.2])
    for name, ys in series.items():
        ax1.plot(np.arange(T), ys, linewidth=2, label=name,
                 color=SERIES[name][1])
    style_axes(ax1, "tick", "count", "per-tick events")
    ax1.legend(fontsize=7, frameon=False, ncol=4, labelcolor=INK)

    order = np.argsort(start[:k])
    for lane, i in enumerate(order):
        ax2.plot([start[i], start[i] + dur[i]], [lane, lane],
                 color=PALETTE[0], linewidth=1.2, solid_capstyle="butt")
    style_axes(ax2, "tick", "committed txn (sample)",
               "txn lifetimes: last restart -> commit")
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)
    return path
