"""Acceptance experiment: the comparator re-derives the hand findings.

Two regressions in this repo's history were diagnosed BY HAND from
counter dumps before obs/diff.py existed:

1. the flat MAAT scaling curve (EXPERIMENTS.md "Diagnosing the flat
   MAAT scaling curve"): the 8-node cell commits ~1x the 1-node cell
   because every multi-partition access re-ships remote grants —
   remote amplification, NOT load imbalance (Jain stays >= 0.99 across
   the grid) — and the fix was ``Config.remote_cache``;
2. the NO_WAIT hot-cell collapse (EXPERIMENTS.md "Adaptive contention
   controller", known limit): on the saturated hot set (ACCESS_PERC
   0.95 of DATA_PERC 0.001 — ~4 rows) the controller's escalation gate
   serializes writers one-per-tick on keys that were ALREADY wedged,
   so adaptive lands ~9x below the best static ladder point.

This script re-runs both pairs at CI scale and feeds the raw summaries
to ``obs/diff.py`` with NO other input.  Acceptance: the top-ranked
cause must name remote amplification (lever ``remote_cache``) for (1)
and an escalation-family cause (lever ``adaptive``) for (2) — i.e. the
automated triage reproduces what previously took a human reading
counter dumps.  Imbalance must NOT outrank amplification in (1).

Usage:  python experiments/diagnose_known_regressions.py
          [--grid-ticks N] [--hot-ticks N] [-o results/...]

Writes ``results/diagnosis_acceptance.json`` (both full diagnosis
dicts + verdicts); exit 0 only when BOTH verdicts match the hand
findings.  EXPERIMENTS.md "Causal diagnosis observatory" records a
run; scripts/check.sh runs a shorter single-engine smoke instead.
"""

from __future__ import annotations

import os

# virtual 8-device CPU mesh for the sharded cells (SURVEY.md §4); forced
# BEFORE jax import, like tests/conftest.py and run_grid.py workers
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import dataclasses  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from deneva_tpu.config import Config  # noqa: E402
from deneva_tpu.obs import diff as obs_diff  # noqa: E402

RESULTS = os.path.join(os.path.dirname(__file__), "..", "results")


def _record(eng, st):
    """The run-record shape diff_records consumes (summary + config)."""
    return {"summary": eng.summary(st),
            "config": dataclasses.asdict(eng.cfg)}


def run_maat_pair(n_ticks: int):
    """The scaling-grid 8x32 MAAT cell, remote_cache ON (healthy A) vs
    OFF (the flat curve, B) — bench.py run_scaling_grid's exact cell
    shape (GRID_KW, mesh on, part_per_txn=2)."""
    from bench import GRID_KW
    from deneva_tpu.parallel.sharded import ShardedEngine

    recs = {}
    for name, extra in (("maat8x32+rc", {"remote_cache": True}),
                        ("maat8x32", {})):
        cfg = Config(cc_alg="MAAT", node_cnt=8, part_cnt=8,
                     batch_size=32, part_per_txn=2, mesh=True,
                     **GRID_KW, **extra)
        eng = ShardedEngine(cfg)
        st = eng.run_compiled(n_ticks)
        recs[name] = _record(eng, st)
        s = recs[name]["summary"]
        print(f"[cell] {name}: txn_cnt={s['txn_cnt']} "
              f"remote_entry_cnt={s.get('remote_entry_cnt', 0)} "
              f"imb_jain={s.get('imb_jain', 0):.3f}", flush=True)
    return obs_diff.diff_records(recs["maat8x32+rc"], recs["maat8x32"],
                                 "maat8x32+rc", "maat8x32")


def run_hot_pair(n_ticks: int):
    """The adaptive sweep's NO_WAIT hot cell, best-known static backoff
    (A) vs the adaptive controller (B) — bench.py run_adaptive's exact
    cell shape (ADAPT_KW + the hot-skew knobs)."""
    from bench import ADAPT_KW
    from deneva_tpu.engine.scheduler import Engine

    hot = dict(skew_method="hot", access_perc=0.95, data_perc=0.001)
    recs = {}
    for name, extra in (("nowait@hot/p4", {"abort_penalty_ticks": 4}),
                        ("nowait@hot/adaptive",
                         {"adaptive": True, "heatmap_bins": 64})):
        cfg = Config(cc_alg="NO_WAIT", abort_attribution=True,
                     **ADAPT_KW, **hot, **extra)
        eng = Engine(cfg)
        st = eng.run_compiled(n_ticks)
        recs[name] = _record(eng, st)
        s = recs[name]["summary"]
        print(f"[cell] {name}: txn_cnt={s['txn_cnt']} "
              f"escalations={s.get('ctrl_escalate_cnt', 0)} "
              f"gate_blocks={s.get('ctrl_esc_block_cnt', 0)}", flush=True)
    return obs_diff.diff_records(recs["nowait@hot/p4"],
                                 recs["nowait@hot/adaptive"],
                                 "nowait@hot/p4", "nowait@hot/adaptive")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--grid-ticks", type=int, default=48,
                   help="ticks per sharded MAAT cell")
    p.add_argument("--hot-ticks", type=int, default=160,
                   help="ticks per NO_WAIT hot cell")
    p.add_argument("-o", "--out",
                   default=os.path.join(RESULTS,
                                        "diagnosis_acceptance.json"))
    args = p.parse_args(argv)

    print("== finding 1: flat MAAT scaling (expect remote_amplification"
          " / remote_cache) ==", flush=True)
    d_grid = run_maat_pair(args.grid_ticks)
    print(obs_diff.render_diagnosis(d_grid), flush=True)
    amp = next((c for c in d_grid["causes"]
                if c["cause"] == "remote_amplification"), None)
    imb = next((c for c in d_grid["causes"]
                if c["cause"] == "imbalance"), None)
    grid_ok = (d_grid["top_cause"] == "remote_amplification"
               and d_grid["top_lever"] == "remote_cache"
               and amp is not None and amp["regressing"]
               and (imb is None or imb["score"] < amp["score"]))

    print("\n== finding 2: NO_WAIT hot-cell collapse (expect escalation"
          " family / adaptive) ==", flush=True)
    d_hot = run_hot_pair(args.hot_ticks)
    print(obs_diff.render_diagnosis(d_hot), flush=True)
    hot_ok = (d_hot["top_cause"] in ("ctrl_escalations_per_commit",
                                     "ctrl_gate_stalls_per_commit")
              and d_hot["top_lever"] == "adaptive")

    doc = {"maat_scaling": {"diff": d_grid, "reproduced": grid_ok,
                            "expect": "remote_amplification/remote_cache"},
           "nowait_hot": {"diff": d_hot, "reproduced": hot_ok,
                          "expect": "ctrl_*_per_commit/adaptive"}}
    os.makedirs(RESULTS, exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"\n[acceptance] maat_scaling reproduced: {grid_ok}; "
          f"nowait_hot reproduced: {hot_ok}; wrote {args.out}")
    return 0 if (grid_ok and hot_ok) else 1


if __name__ == "__main__":
    raise SystemExit(main())
