"""HBM budget sizing sweep — the --budget-mb recipe behind EXPERIMENTS.md.

Answers the capacity-planning question the xmeter ledger makes tractable:
at R accesses per txn, how many in-flight txns (B) fit a per-node HBM
budget, and which arrays spill first?  Probes the per-array footprint
ledger (deneva_tpu/obs/xmeter.py state_ledger) at a few batch sizes —
init_state only, no run, so the sweep is seconds even at paper-scale
row counts — fits the linear bytes(B) = fixed + per_txn * B model, and
prints the max batch per budget row.

Usage:
    python experiments/hbm_sizing.py [--req 10] [--rows $((1<<24))]
        [--node-cnt 1] [--budgets-mb 1024,4096,16384]

The same single-budget check (with spill flagging and exit code) is the
``python -m deneva_tpu.obs.xmeter --budget-mb ...`` CLI; this sweep is
the multi-budget planning view.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from deneva_tpu.config import Config  # noqa: E402
from deneva_tpu.engine.scheduler import Engine  # noqa: E402
from deneva_tpu.obs import xmeter as obs_xmeter  # noqa: E402

#: probe batches for the linear model (small enough to allocate anywhere,
#: far enough apart that per-txn slope dominates rounding)
PROBES = (256, 1024)


def ledger_at(batch: int, req: int, rows: int, cc_alg: str) -> list[dict]:
    cfg = Config(cc_alg=cc_alg, batch_size=batch, synth_table_size=rows,
                 req_per_query=req, query_pool_size=min(1 << 12, rows))
    eng = Engine(cfg)
    return obs_xmeter.state_ledger(eng.init_state(),
                                   constants={"pool": eng.pool_dev})


def sweep(budgets_mb, req: int, rows: int, node_cnt: int,
          cc_alg: str) -> dict:
    probes = {b: obs_xmeter.ledger_totals(
        ledger_at(b, req, rows, cc_alg))["total"] for b in PROBES}
    out = {"req": req, "rows": rows, "node_cnt": node_cnt,
           "cc_alg": cc_alg, "probes": probes, "budgets": []}
    for mb in budgets_mb:
        fit = obs_xmeter.fit_batch(mb, probes, node_cnt=node_cnt)
        out["budgets"].append({"budget_mb": mb, **fit})
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--req", type=int, default=10)
    p.add_argument("--rows", type=int, default=1 << 24)
    p.add_argument("--node-cnt", type=int, default=1)
    p.add_argument("--cc-alg", default="NO_WAIT")
    p.add_argument("--budgets-mb", default="1024,4096,16384",
                   help="comma-separated per-node budgets (v5e: 16384)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    budgets = [float(b) for b in args.budgets_mb.split(",") if b]

    doc = sweep(budgets, args.req, args.rows, args.node_cnt, args.cc_alg)
    if args.json:
        print(json.dumps(doc))
        return 0
    fixed = doc["budgets"][0]["fixed_bytes"]
    per_txn = doc["budgets"][0]["per_txn_bytes"]
    print(f"[sizing] {args.cc_alg} R={args.req} rows={args.rows} "
          f"nodes={args.node_cnt}: bytes(B) = {fixed / 1e6:.2f} MB + "
          f"{per_txn:.0f} B/txn")
    print("| budget MB/node | max B/node | max B cluster |")
    print("|---|---|---|")
    for row in doc["budgets"]:
        print(f"| {row['budget_mb']:.0f} | {row['max_batch_per_node']} | "
              f"{row['max_batch_cluster']} |")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
