"""Decompose the faithful-cell tick cost on the real chip (PROFILE.md data).

Times, for a range of batch sizes on the headline cell (YCSB NO_WAIT,
zipf 0.6, 50/50 rw, 16M rows, R=10, acquire_window=1):

  - the full tick (mode NORMAL),
  - the tick with CC disabled (mode NOCC: no arbitration kernel),
  - the bare ``arbitrate`` kernel on matching shapes,
  - the bare 3-operand ``lax.sort`` that dominates it,
  - the commit write-apply scatter alone.

Each measurement runs the target in a 200-iteration device-side
``lax.fori_loop`` with a live data dependence and reports ms/iteration
(median of 3 windows after one discarded warmup dispatch).

Usage: python experiments/profile_tick.py [B ...]
       python experiments/profile_tick.py --compact [B]   (round-5 ablation)
       python experiments/profile_tick.py --fused [B]     (round-7 ablation)
       python experiments/profile_tick.py --pipeline [B]  (round-8 ablation)
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if "--pipeline" in sys.argv and "--xla_force_host_platform_device_count" \
        not in os.environ.get("XLA_FLAGS", ""):
    # the sharded ablation needs virtual nodes; must land before the
    # jax import below or the platform is already frozen (bench.py
    # precedent) — real-chip runs preset their own XLA_FLAGS
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax
import jax.numpy as jnp
import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.engine.state import Entries
from deneva_tpu.cc import twopl

ITERS = 200


def _time_loop(body, state):
    """ms per iteration of body in a fori_loop (median of 3 windows)."""
    fn = jax.jit(lambda s: jax.lax.fori_loop(0, ITERS, lambda _, x: body(x),
                                             s))
    out = fn(state)
    jax.block_until_ready(out)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(state)
        jax.block_until_ready(out)
        ts.append((time.perf_counter() - t0) / ITERS * 1e3)
    return float(np.median(ts))


def cell_cfg(B, window=1, mode="NORMAL"):
    return Config(cc_alg="NO_WAIT", batch_size=B, synth_table_size=1 << 24,
                  req_per_query=10, zipf_theta=0.6, tup_read_perc=0.5,
                  query_pool_size=1 << 16, warmup_ticks=0, backoff=True,
                  acquire_window=window, admit_cap=1024, mode=mode)


def time_engine(B, mode="NORMAL"):
    eng = Engine(cell_cfg(B, mode=mode))
    st = eng.run_compiled(ITERS)          # reach steady-state occupancy
    st = eng.run_compiled(ITERS, st)
    jax.block_until_ready(st.stats["txn_cnt"])
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        st = eng.run_compiled(ITERS, st)
        jax.block_until_ready(st.stats["txn_cnt"])
        ts.append((time.perf_counter() - t0) / ITERS * 1e3)
    committed = st.stats["txn_cnt"]
    return float(np.median(ts)), eng, st


def time_arbitrate(B, R=10):
    """Bare NO_WAIT arbitration on a synthetic steady-state entry mix."""
    rng = np.random.default_rng(0)
    n = B * R
    keys = rng.zipf(1.6, n).astype(np.int32) % (1 << 24)
    held = rng.random(n) < 0.35
    req = ~held & (rng.random(n) < 0.12)
    ent = Entries(
        key=jnp.asarray(keys),
        txn=jnp.asarray(np.repeat(np.arange(B, dtype=np.int32), R)),
        ridx=jnp.asarray(np.tile(np.arange(R, dtype=np.int32), B)),
        ts=jnp.asarray(rng.permutation(n).astype(np.int32) + 1),
        is_write=jnp.asarray(rng.random(n) < 0.5),
        held=jnp.asarray(held), req=jnp.asarray(req))

    def body(ts):
        g, w, a = twopl.arbitrate(ent._replace(ts=ts), "NO_WAIT")
        return ts + g.astype(jnp.int32) - a.astype(jnp.int32)

    return _time_loop(body, ent.ts)


def time_sort(B, R=10, operands=3, num_keys=2):
    n = B * R
    rng = np.random.default_rng(0)
    arrs = [jnp.asarray(rng.integers(0, 1 << 30, n).astype(np.int32))
            for _ in range(operands)]

    def body(a0):
        out = jax.lax.sort((a0, *arrs[1:]), num_keys=num_keys,
                           is_stable=False)
        return out[0]

    return _time_loop(body, arrs[0])


def time_write_scatter(B, R=10, n_rows=1 << 24):
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.zipf(1.6, B * R).astype(np.int32) % n_rows)
    mask = jnp.asarray(rng.random(B * R) < 0.02)

    def body(data):
        idx = jnp.where(mask & (data[0] >= 0), keys, jnp.int32(2**31 - 1))
        return data.at[idx].add(1, mode="drop")

    return _time_loop(body, jnp.zeros(n_rows, jnp.int32))


def time_engine_cfg(cfg):
    eng = Engine(cfg)
    st = eng.run_compiled(ITERS)
    st = eng.run_compiled(ITERS, st)
    jax.block_until_ready(st.stats["txn_cnt"])
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        st = eng.run_compiled(ITERS, st)
        jax.block_until_ready(st.stats["txn_cnt"])
        ts.append((time.perf_counter() - t0) / ITERS * 1e3)
    return float(np.median(ts)), eng


def sort_widths(eng):
    """Histogram {operand_width: count} of lax.sort ops in the tick jaxpr
    — the structural evidence that compacted chains run at K lanes."""
    jaxpr = jax.make_jaxpr(eng._tick_fn)(eng.init_state())
    widths: dict[int, int] = {}

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "sort":
                w = int(np.prod(eqn.invars[0].aval.shape or (1,)))
                widths[w] = widths.get(w, 0) + 1
            for v in eqn.params.values():
                for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                    inner = getattr(sub, "jaxpr", None)
                    if inner is not None:
                        walk(inner)

    walk(jaxpr.jaxpr)
    return dict(sorted(widths.items()))


def compact_ablation(B):
    """Round-5 ablation: whole-tick ms with live-entry compaction on vs
    off, plus the tick's sort-width histogram, for the sort-bound cells
    (MAAT/MVCC YCSB + the TPC-C MVCC cell)."""
    ycsb = dict(batch_size=B, synth_table_size=1 << 24, req_per_query=10,
                zipf_theta=0.6, tup_read_perc=0.5, query_pool_size=1 << 16,
                warmup_ticks=0, backoff=True, acquire_window=1,
                admit_cap=max(B // 8, 1))
    tpcc = dict(workload="TPCC", cc_alg="MVCC", batch_size=B, num_wh=64,
                cust_per_dist=2000, max_items=1024, query_pool_size=1 << 16,
                warmup_ticks=0, admit_cap=max(B // 8, 1))
    cells = [("MAAT/ycsb", dict(cc_alg="MAAT", **ycsb)),
             ("MVCC/ycsb", dict(cc_alg="MVCC", **ycsb)),
             ("TPCC/mvcc", tpcc)]
    print(f"{'cell':>10} {'on(ms)':>8} {'off(ms)':>8} {'x':>5}  "
          "K-lane sorts -> padded sorts")
    for name, kw in cells:
        on_ms, on_eng = time_engine_cfg(Config(compact_auto=True, **kw))
        off_ms, off_eng = time_engine_cfg(
            Config(entry_compaction=False, **kw))
        n = on_eng.cfg.batch_size * on_eng.pool.max_req
        k = on_eng.cfg.compact_width(n, on_eng.cfg.batch_size)
        w_on, w_off = sort_widths(on_eng), sort_widths(off_eng)
        print(f"{name:>10} {on_ms:>8.3f} {off_ms:>8.3f} "
              f"{off_ms / on_ms:>5.2f}  K={k}/n={n} on={w_on} off={w_off}",
              flush=True)


def fused_ablation(B):
    """Round-7 ablation: whole-tick ms with the fused VMEM
    sort+scan kernel (Config.fused_arbitrate) on vs off, at compacted
    width (compact_auto on BOTH sides, so the delta isolates the kernel
    itself), plus the standalone lax.sort count left in each tick jaxpr
    — the direct evidence of how many sort+scan chains the fused path
    absorbed (MAAT, whose validate runs the longest chain, drops the
    most)."""
    ycsb = dict(batch_size=B, synth_table_size=1 << 24, req_per_query=10,
                zipf_theta=0.6, tup_read_perc=0.5, query_pool_size=1 << 16,
                warmup_ticks=0, backoff=True, acquire_window=1,
                admit_cap=max(B // 8, 1), compact_auto=True)
    tpcc = dict(workload="TPCC", cc_alg="MVCC", batch_size=B, num_wh=64,
                cust_per_dist=2000, max_items=1024, query_pool_size=1 << 16,
                warmup_ticks=0, admit_cap=max(B // 8, 1), compact_auto=True)
    cells = [("MAAT/ycsb", dict(cc_alg="MAAT", **ycsb)),
             ("MVCC/ycsb", dict(cc_alg="MVCC", **ycsb)),
             ("NO_WAIT/ycsb", dict(cc_alg="NO_WAIT", **ycsb)),
             ("TPCC/mvcc", tpcc)]
    print(f"{'cell':>12} {'fused(ms)':>10} {'lax(ms)':>8} {'x':>5}  "
          "standalone sorts (width histogram)")
    for name, kw in cells:
        on_ms, on_eng = time_engine_cfg(Config(fused_arbitrate=True, **kw))
        off_ms, off_eng = time_engine_cfg(Config(**kw))
        w_on, w_off = sort_widths(on_eng), sort_widths(off_eng)
        n_on, n_off = sum(w_on.values()), sum(w_off.values())
        print(f"{name:>12} {on_ms:>10.3f} {off_ms:>8.3f} "
              f"{off_ms / on_ms:>5.2f}  {n_off}->{n_on} "
              f"fused={w_on} lax={w_off}", flush=True)


def pipeline_ablation(B):
    """Round-8 ablation: whole-tick ms on the sharded CALVIN split cells
    with the double-buffered exchange pipeline (Config.pipeline_exchange)
    on vs off.  The pipeline is bit-identical dataflow, so the whole
    delta is serialized collective wait the async scheduler recovered;
    the occupancy columns (sub-rounds/tick and the fraction of legs
    issued with another leg in flight) say how much overlap the cell
    exposes structurally."""
    from deneva_tpu.parallel.sharded import ShardedEngine

    def time_sharded(cfg, iters):
        eng = ShardedEngine(cfg)
        st = eng.run_compiled(iters)           # warm + steady occupancy
        st = eng.run_compiled(iters, st)
        jax.block_until_ready(st.stats["txn_cnt"])
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            st = eng.run_compiled(iters, st)
            jax.block_until_ready(st.stats["txn_cnt"])
            ts.append((time.perf_counter() - t0) / iters * 1e3)
        return float(np.median(ts)), eng.summary(st)

    iters = 50                                  # 5 windows x 50 sharded ticks
    base = dict(cc_alg="CALVIN", batch_size=B, synth_table_size=1 << 16,
                query_pool_size=1 << 12, req_per_query=4, zipf_theta=0.6,
                tup_read_perc=0.5, warmup_ticks=0, exchange_split=True,
                route_capacity_factor=0.25)     # low cap -> many sub-rounds
    nodes = [n for n in (4, 8) if n <= jax.device_count()]
    print(f"{'cell':>14} {'pipe(ms)':>9} {'serial(ms)':>10} {'x':>5} "
          f"{'rounds/tick':>11} {'overlap':>8}")
    for n in nodes:
        cfg = dict(base, node_cnt=n, part_cnt=n)
        on_ms, s_on = time_sharded(
            Config(pipeline_exchange=True, **cfg), iters)
        off_ms, _ = time_sharded(Config(**cfg), iters)
        # occupancy from the LAST timed window's psum'd counters: the
        # summary accumulates across all 5 windows, so normalise by the
        # total measured ticks
        # exchange_round_cnt is psum'd over nodes -> per-node mean
        rounds = (s_on["exchange_round_cnt"]
                  / max(s_on["measured_ticks"], 1) / n)
        frac = s_on["pipe_overlap_cnt"] / max(s_on["pipe_leg_cnt"], 1)
        print(f"{'CALVIN/'+str(n)+'n':>14} {on_ms:>9.3f} {off_ms:>10.3f} "
              f"{off_ms / on_ms:>5.2f} {rounds:>11.2f} {frac:>8.3f}",
              flush=True)


def main():
    if "--pipeline" in sys.argv:
        args = [a for a in sys.argv[1:] if a != "--pipeline"]
        pipeline_ablation(int(args[0]) if args else 256)
        return
    if "--fused" in sys.argv:
        args = [a for a in sys.argv[1:] if a != "--fused"]
        fused_ablation(int(args[0]) if args else 8192)
        return
    if "--compact" in sys.argv:
        args = [a for a in sys.argv[1:] if a != "--compact"]
        compact_ablation(int(args[0]) if args else 8192)
        return
    Bs = [int(a) for a in sys.argv[1:]] or [2048, 4096, 8192, 16384]
    print(f"{'B':>6} {'tick':>7} {'nocc':>7} {'arb':>7} {'sort3':>7} "
          f"{'sort1':>7} {'wscat':>7}  (ms)")
    for B in Bs:
        tick, eng, st = time_engine(B)
        nocc, _, _ = time_engine(B, mode="NOCC")
        arb = time_arbitrate(B)
        s3 = time_sort(B, operands=3, num_keys=2)
        s1 = time_sort(B, operands=1, num_keys=1)
        ws = time_write_scatter(B)
        print(f"{B:>6} {tick:>7.3f} {nocc:>7.3f} {arb:>7.3f} {s3:>7.3f} "
              f"{s1:>7.3f} {ws:>7.3f}", flush=True)


if __name__ == "__main__":
    main()
