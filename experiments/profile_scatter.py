"""Scatter/gather cost matrix on the real chip — what sets the price?

Sweeps update-lane count x target-array size x (add vs set) x
(unique_indices/indices_are_sorted hints) for scatter, and index count x
source size for gather.  Each op runs in a 200-iteration device loop with a
live dependence; reports ms/iter.

Usage: python experiments/profile_scatter.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

ITERS = 200


def _time_loop(body, state):
    fn = jax.jit(lambda s: jax.lax.fori_loop(0, ITERS, lambda _, x: body(x),
                                             s))
    out = fn(state)
    jax.block_until_ready(out)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(state)
        jax.block_until_ready(out)
        ts.append((time.perf_counter() - t0) / ITERS * 1e3)
    return float(np.median(ts))


def scatter_ms(lanes, target, op="add", unique=False, srt=False,
               mask_frac=1.0):
    rng = np.random.default_rng(0)
    if unique:
        idx = rng.choice(target, size=lanes, replace=False).astype(np.int32)
    else:
        idx = rng.integers(0, target, lanes).astype(np.int32)
    if srt:
        idx = np.sort(idx)
    if mask_frac < 1.0:
        dead = rng.random(lanes) >= mask_frac
        idx = np.where(dead, np.int32(2**31 - 1), idx)
    idxj = jnp.asarray(idx)

    def body(data):
        upd = jnp.full(lanes, 1, jnp.int32) + data[0]
        ref = data.at[idxj]
        kw = dict(mode="drop", unique_indices=unique,
                  indices_are_sorted=srt)
        return ref.add(upd, **kw) if op == "add" else ref.set(upd, **kw)

    return _time_loop(body, jnp.zeros(target, jnp.int32))


def gather_ms(lanes, source, srt=False):
    rng = np.random.default_rng(0)
    idx = rng.integers(0, source, lanes).astype(np.int32)
    if srt:
        idx = np.sort(idx)
    idxj = jnp.asarray(idx)
    src = jnp.asarray(rng.integers(0, 100, source).astype(np.int32))

    def body(acc):
        vals = src[(idxj + acc[0]) % source]
        return acc + vals[:1]

    return _time_loop(body, jnp.zeros(1, jnp.int32))


def main():
    print("scatter (ms/iter):")
    print(f"{'lanes':>7} {'target':>9} {'op':>4} {'uniq':>5} {'sort':>5} "
          f"{'ms':>8}")
    for lanes in (8192, 81920):
        for target in (1 << 16, 1 << 20, 1 << 24):
            for op in ("add", "set"):
                for unique, srt in ((False, False), (True, False),
                                    (True, True)):
                    if unique and lanes > target:
                        continue
                    ms = scatter_ms(lanes, target, op, unique, srt)
                    print(f"{lanes:>7} {target:>9} {op:>4} {unique!s:>5} "
                          f"{srt!s:>5} {ms:>8.3f}", flush=True)
    print("\ngather (ms/iter):")
    print(f"{'lanes':>7} {'source':>9} {'sort':>5} {'ms':>8}")
    for lanes in (1024, 8192, 81920):
        for source in (1 << 16, 1 << 20, 1 << 24):
            for srt in (False, True):
                ms = gather_ms(lanes, source, srt)
                print(f"{lanes:>7} {source:>9} {srt!s:>5} {ms:>8.3f}",
                      flush=True)


if __name__ == "__main__":
    main()
