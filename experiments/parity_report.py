"""Generate PARITY.md: abort-rate parity of the batched TPU engine vs the
sequential reference interpreter across the BASELINE.json config cells.

Usage: python experiments/parity_report.py [--quick]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

from deneva_tpu.config import Config                              # noqa: E402
from deneva_tpu.oracle.parity import run_pair, run_pair_sharded   # noqa: E402

ALGS = ["NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT", "CALVIN"]


def extra(alg: str) -> dict:
    """Per-algorithm refinement knobs the published cells run at
    (single source: oracle/parity.py PARITY_EXTRA)."""
    from deneva_tpu.oracle.parity import PARITY_EXTRA
    return PARITY_EXTRA.get(alg, {})

CELLS = [
    # (label, cfg_kw)  — the BASELINE.json five config families, scaled to
    # interpreter-feasible sizes (the oracle is pure Python)
    ("uniform read-only", dict(zipf_theta=0.0, txn_read_perc=1.0)),
    ("zipf 0.6, 50/50 rw", dict(zipf_theta=0.6)),
    ("zipf 0.9, 50/50 rw", dict(zipf_theta=0.9)),
]

BASE = dict(batch_size=256, synth_table_size=1 << 16, req_per_query=10,
            query_pool_size=1 << 12, tup_read_perc=0.5, warmup_ticks=0)


def main():
    quick = "--quick" in sys.argv
    n_ticks = 30 if quick else 60
    lines = [
        "# PARITY — batched TPU engine vs sequential reference interpreter",
        "",
        "The C++ reference cannot be built here (vendored boost/nanomsg/"
        "jemalloc absent, no network), so the baseline is "
        "`deneva_tpu/oracle/sequential.py`: the reference's per-row decision "
        "rules (row_lock/row_ts/row_mvcc/occ/maat .cpp) replayed "
        "sequentially on the SAME query pool under the same slot/tick "
        "protocol.  Metric definitions follow statistics/stats.cpp:431-456 "
        "(abort_rate = aborts / (aborts + commits)).",
        "",
        f"Config: B={BASE['batch_size']}, table={BASE['synth_table_size']}, "
        f"R={BASE['req_per_query']}, {n_ticks} ticks, acquire_window=1.",
        "",
    ]
    for label, kw in CELLS:
        lines += [f"## {label}", "",
                  "| CC_ALG | batched abort rate | sequential abort rate | "
                  "divergence | tput ratio | conserved |",
                  "|---|---|---|---|---|---|"]
        for alg in ALGS:
            cfg = Config(cc_alg=alg, **{**BASE, **kw, **extra(alg)})
            r = run_pair(cfg, n_ticks)
            lines.append(
                f"| {alg} | {r['batched']['abort_rate']:.4f} "
                f"| {r['sequential']['abort_rate']:.4f} "
                f"| {r['abort_rate_divergence']:.4f} "
                f"| {r['tput_ratio']:.3f} "
                f"| {'yes' if r['batched_conserved'] and r['sequential_conserved'] else 'NO'} |")
            print(label, alg, f"div={r['abort_rate_divergence']:.4f}")
        lines.append("")
    # --- refinement knobs: divergence -> 0 as the batched engine's time
    # quantization is refined (Config.sub_ticks) or the version ring grows
    # (his_recycle_len); seed-averaged to separate signal from sampling
    # noise (single cells have ~0.5-1.5% standard deviation) ---
    import numpy as np

    def seed_avg(cfg_kw, n_seeds=3):
        ds = []
        for seed in range(1, n_seeds + 1):
            cfg = Config(seed=seed, **{**BASE, **cfg_kw})
            r = run_pair(cfg, n_ticks)
            ds.append(r["batched"]["abort_rate"]
                      - r["sequential"]["abort_rate"])
        return float(np.mean(ds)), float(np.std(ds))

    lines += ["## refinement: divergence vs engine knobs (zipf 0.9, "
              "seed-averaged signed divergence)", "",
              "| cell | mean divergence | std |", "|---|---|---|"]
    for alg in ("NO_WAIT", "WAIT_DIE"):
        for K in (1, 4, 8):
            m, sd = seed_avg(dict(cc_alg=alg, zipf_theta=0.9, sub_ticks=K))
            lines.append(f"| {alg} sub_ticks={K} | {m:+.4f} | {sd:.4f} |")
            print(f"refine {alg} K={K} mean={m:+.4f}")
    for hrl in (8, 32):
        m, sd = seed_avg(dict(cc_alg="MVCC", zipf_theta=0.9,
                              his_recycle_len=hrl))
        lines.append(f"| MVCC his_recycle_len={hrl} | {m:+.4f} | {sd:.4f} |")
        print(f"refine MVCC hrl={hrl} mean={m:+.4f}")
    for W in (8, 64):
        m, sd = seed_avg(dict(cc_alg="MAAT", zipf_theta=0.9,
                              maat_chain_window=W), n_seeds=5)
        lines.append(f"| MAAT chain_window={W} | {m:+.4f} | {sd:.4f} |")
        print(f"refine MAAT W={W} mean={m:+.4f}")
    lines.append("")

    # --- TPC-C parity: same pools through the extended oracle ---
    lines += ["## TPC-C (4 warehouses, 50/50 Payment/NewOrder)", "",
              "| CC_ALG | mean divergence | std |", "|---|---|---|"]
    tpcc_kw = dict(workload="TPCC", batch_size=64, num_wh=4,
                   cust_per_dist=1000, max_items=128,
                   query_pool_size=1 << 10, warmup_ticks=0,
                   synth_table_size=8, req_per_query=10,
                   tup_read_perc=0.5)
    for alg in ("NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT",
                "CALVIN"):
        ds = []
        for seed in (1, 2, 3):
            cfg = Config(cc_alg=alg, seed=seed, **{**tpcc_kw, **extra(alg)})
            r = run_pair(cfg, n_ticks)
            ds.append(r["batched"]["abort_rate"]
                      - r["sequential"]["abort_rate"])
        lines.append(f"| {alg} | {float(np.mean(ds)):+.4f} "
                     f"| {float(np.std(ds)):.4f} |")
        print(f"tpcc {alg} mean={float(np.mean(ds)):+.4f}")
    lines.append("")

    # --- PPS parity: chain-walk pools through the same oracle ---
    lines += ["## PPS (8-type mix, 256-key tables, chain walks)", "",
              "| CC_ALG | mean divergence | std |", "|---|---|---|"]
    pps_kw = dict(workload="PPS", batch_size=64, query_pool_size=1 << 10,
                  warmup_ticks=0, synth_table_size=8, max_part_key=256,
                  max_product_key=256, max_supplier_key=256)
    for alg in ALGS:
        ds = []
        for seed in (1, 2, 3):
            cfg = Config(cc_alg=alg, seed=seed, **{**pps_kw, **extra(alg)})
            r = run_pair(cfg, n_ticks)
            ds.append(r["batched"]["abort_rate"]
                      - r["sequential"]["abort_rate"])
        lines.append(f"| {alg} | {float(np.mean(ds)):+.4f} "
                     f"| {float(np.std(ds)):.4f} |")
        print(f"pps {alg} mean={float(np.mean(ds)):+.4f}")
    lines.append("(CALVIN+PPS replays the recon deferral — one-epoch "
                 "sleep, shadow read pass, epoch-slot consumption, "
                 "sequencer.cpp:88-114 — and is exact.)")
    lines.append("")

    # --- TPC-C with NewOrder rollbacks (rbk) enabled ---
    lines += ["## TPC-C with rbk=1% (user-abort path)", "",
              "| CC_ALG | mean divergence | std |", "|---|---|---|"]
    for alg in ("NO_WAIT", "WAIT_DIE", "MVCC", "MAAT", "CALVIN"):
        ds = []
        for seed in (1, 2, 3):
            cfg = Config(cc_alg=alg, seed=seed, tpcc_rbk_perc=0.01,
                         **{**tpcc_kw, **extra(alg)})
            r = run_pair(cfg, n_ticks)
            ds.append(r["batched"]["abort_rate"]
                      - r["sequential"]["abort_rate"])
        lines.append(f"| {alg} | {float(np.mean(ds)):+.4f} "
                     f"| {float(np.std(ds)):.4f} |")
        print(f"tpcc-rbk {alg} mean={float(np.mean(ds)):+.4f}")
    lines.append("")

    # multi-shard parity: ShardedEngine on the virtual mesh vs the N-node
    # sequential oracle (exercises routing, owner arbitration, 2PC votes)
    lines += ["## multi-shard (zipf 0.6, 50/50 rw, mpr=1, ppt=2)", "",
              "| CC_ALG | nodes | batched abort rate | sequential abort "
              "rate | divergence | tput ratio | conserved |",
              "|---|---|---|---|---|---|---|"]
    for alg in ALGS:
        for n in (2, 4, 8):
            cfg = Config(cc_alg=alg, node_cnt=n, part_cnt=n, batch_size=64,
                         synth_table_size=1 << 14, req_per_query=6,
                         zipf_theta=0.6, query_pool_size=1 << 12, mpr=1.0,
                         part_per_txn=2, warmup_ticks=0, **extra(alg))
            r = run_pair_sharded(cfg, n_ticks)
            lines.append(
                f"| {alg} | {n} | {r['batched']['abort_rate']:.4f} "
                f"| {r['sequential']['abort_rate']:.4f} "
                f"| {r['abort_rate_divergence']:.4f} "
                f"| {r['tput_ratio']:.3f} "
                f"| {'yes' if r['batched_conserved'] and r['sequential_conserved'] else 'NO'} |")
            print("multi-shard", alg, n,
                  f"div={r['abort_rate_divergence']:.4f}")
    lines.append("")

    # --- network-delay parity: the delayed tick protocol, engine vs the
    # oracle's _tick_delay replay (msg_queue.cpp:81-124 analog) ---
    # NOTE: by this point the process has compiled ~100 XLA programs and
    # LLVM can hit "Cannot allocate memory" on constrained hosts; if this
    # section dies, regenerate it standalone in a fresh process and
    # append before the "Enforced continuously" line (round-4 ran it
    # that way).
    lines += ["## multi-shard with message delay (D=1, 2 nodes, mpr=1, "
              "ppt=2)", "",
              "| CC_ALG | divergence | tput ratio | conserved |",
              "|---|---|---|---|"]
    for alg in ALGS:
        cfg = Config(cc_alg=alg, node_cnt=2, part_cnt=2, batch_size=64,
                     synth_table_size=1 << 14, req_per_query=6,
                     zipf_theta=0.6, query_pool_size=1 << 12, mpr=1.0,
                     part_per_txn=2, warmup_ticks=0, net_delay_ticks=1,
                     **extra(alg))
        r = run_pair_sharded(cfg, n_ticks)
        lines.append(
            f"| {alg} | {r['abort_rate_divergence']:.4f} "
            f"| {r['tput_ratio']:.3f} "
            f"| {'yes' if r['batched_conserved'] and r['sequential_conserved'] else 'NO'} |")
        print("delay", alg, f"div={r['abort_rate_divergence']:.4f}")
    lines.append("(remote accesses pay 2D with owner-binding arbitration; "
                 "MAAT's residual is cross-owner same-tick push "
                 "invisibility during the prepare/commit transit — "
                 "tests/test_netdelay.py enforces these levels.)")
    lines.append("")
    lines += [
        "Enforced continuously by `tests/test_parity.py`.",
        "",
        "### Divergence accounting (rounds 3-4)",
        "",
        "- **Multi-shard (round 4)**: three systematic gaps were found and "
        "closed — local entries funneling through the exchange self-lane "
        "(overflow aborts at mpr<1), per-entry instead of per-node OCC "
        "active sets, and the oracle releasing aborted txns' locks "
        "mid-pass where the engine (and the reference's release messages) "
        "release next tick.  With the oracle also drawing restart and "
        "admission timestamps in one slot-order pass, multi-shard "
        "divergence is now EXACT (0.0000) for "
        "NO_WAIT/WAIT_DIE/TIMESTAMP/MVCC/OCC/CALVIN at 2-8 nodes; "
        "net_delay_ticks cells replay near-exactly "
        "(tests/test_netdelay.py).",
        "- **2PL (NO_WAIT / WAIT_DIE)**: the one-round tick's only bias is "
        "within-tick lock-release timing (an aborting txn's locks stay "
        "visible until tick end).  `Config.sub_ticks` refines the time "
        "quantization; divergence converges to 0 by K=8 (table above) — "
        "the batched kernels are otherwise exact.",
        "- **MVCC**: two sources found and fixed/sized: same-tick same-row "
        "multi-commit folding (now every commit installs a version) and "
        "version-ring eviction (his_recycle_len=32 saturates at this "
        "scale).  Residual is at sampling-noise level.",
        "- **MAAT (round 5)**: the order-blind live-set join was replaced "
        "by an access-order-aware commit chain — membership in access-"
        "time snapshot sets (row_maat.cpp:64-95) is reconstructed from "
        "per-entry access ticks (MaaT never blocks, so access r lands at "
        "start_tick+r//window), the validator self-adjustment ducks "
        "(maat.cpp:121-152) are applied from access-order prefixes, and "
        "the sharded engine applies commit-time forward validation "
        "(row_maat.cpp:208-307) at the commit exchange for globally-"
        "committed txns only, with the oracle replaying the per-node "
        "TimeTable protocol (per-owner verdicts/overlays, VALIDATED "
        "residency during the 2PC window).  Single-shard bias fell from "
        "~+2.3% to ~0.0-0.6%; 2/4/8-node cells from 1.3-2.5% to <1%; "
        "D=1 from +4.5% to ~-1.8% (the residual: cross-owner pushes "
        "within one transit window are mutually invisible).",
        "- **TIMESTAMP on TPC-C** (+5% +-2%, the one outstanding cell): "
        "isolated to the MIXED workload — pure-Payment and pure-NewOrder "
        "cells are EXACT (0.0000 over seeds), and the divergence is "
        "bit-invariant under sub_ticks refinement, so it is NOT a "
        "within-tick ordering or decision-rule error; it is an "
        "interleaving effect of heterogeneous txn lengths (3-access "
        "Payments vs 33-access NewOrders) on WAIT/retry timing between "
        "the tick-batched and sequential drivers; enforced at its "
        "measured level by test_tpcc_timestamp_mixed_cell_bounded.",
        "- **CALVIN**: exact (both sides deterministic and abort-free).",
        "",
    ]
    with open("PARITY.md", "w") as f:
        f.write("\n".join(lines))
    print("wrote PARITY.md")


if __name__ == "__main__":
    main()
