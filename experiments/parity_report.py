"""Generate PARITY.md: abort-rate parity of the batched TPU engine vs the
sequential reference interpreter across the BASELINE.json config cells.

Usage: python experiments/parity_report.py [--quick]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

from deneva_tpu.config import Config                              # noqa: E402
from deneva_tpu.oracle.parity import run_pair, run_pair_sharded   # noqa: E402

ALGS = ["NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT", "CALVIN"]

CELLS = [
    # (label, cfg_kw)  — the BASELINE.json five config families, scaled to
    # interpreter-feasible sizes (the oracle is pure Python)
    ("uniform read-only", dict(zipf_theta=0.0, txn_read_perc=1.0)),
    ("zipf 0.6, 50/50 rw", dict(zipf_theta=0.6)),
    ("zipf 0.9, 50/50 rw", dict(zipf_theta=0.9)),
]

BASE = dict(batch_size=256, synth_table_size=1 << 16, req_per_query=10,
            query_pool_size=1 << 12, tup_read_perc=0.5, warmup_ticks=0)


def main():
    quick = "--quick" in sys.argv
    n_ticks = 30 if quick else 60
    lines = [
        "# PARITY — batched TPU engine vs sequential reference interpreter",
        "",
        "The C++ reference cannot be built here (vendored boost/nanomsg/"
        "jemalloc absent, no network), so the baseline is "
        "`deneva_tpu/oracle/sequential.py`: the reference's per-row decision "
        "rules (row_lock/row_ts/row_mvcc/occ/maat .cpp) replayed "
        "sequentially on the SAME query pool under the same slot/tick "
        "protocol.  Metric definitions follow statistics/stats.cpp:431-456 "
        "(abort_rate = aborts / (aborts + commits)).",
        "",
        f"Config: B={BASE['batch_size']}, table={BASE['synth_table_size']}, "
        f"R={BASE['req_per_query']}, {n_ticks} ticks, acquire_window=1.",
        "",
    ]
    for label, kw in CELLS:
        lines += [f"## {label}", "",
                  "| CC_ALG | batched abort rate | sequential abort rate | "
                  "divergence | tput ratio | conserved |",
                  "|---|---|---|---|---|---|"]
        for alg in ALGS:
            cfg = Config(cc_alg=alg, **{**BASE, **kw})
            r = run_pair(cfg, n_ticks)
            lines.append(
                f"| {alg} | {r['batched']['abort_rate']:.4f} "
                f"| {r['sequential']['abort_rate']:.4f} "
                f"| {r['abort_rate_divergence']:.4f} "
                f"| {r['tput_ratio']:.3f} "
                f"| {'yes' if r['batched_conserved'] and r['sequential_conserved'] else 'NO'} |")
            print(label, alg, f"div={r['abort_rate_divergence']:.4f}")
        lines.append("")
    # multi-shard parity: ShardedEngine on the virtual mesh vs the N-node
    # sequential oracle (exercises routing, owner arbitration, 2PC votes)
    lines += ["## multi-shard (zipf 0.6, 50/50 rw, mpr=1, ppt=2)", "",
              "| CC_ALG | nodes | batched abort rate | sequential abort "
              "rate | divergence | tput ratio | conserved |",
              "|---|---|---|---|---|---|---|"]
    for alg in ALGS:
        for n in (2, 4, 8):
            cfg = Config(cc_alg=alg, node_cnt=n, part_cnt=n, batch_size=64,
                         synth_table_size=1 << 14, req_per_query=6,
                         zipf_theta=0.6, query_pool_size=1 << 12, mpr=1.0,
                         part_per_txn=2, warmup_ticks=0)
            r = run_pair_sharded(cfg, n_ticks)
            lines.append(
                f"| {alg} | {n} | {r['batched']['abort_rate']:.4f} "
                f"| {r['sequential']['abort_rate']:.4f} "
                f"| {r['abort_rate_divergence']:.4f} "
                f"| {r['tput_ratio']:.3f} "
                f"| {'yes' if r['batched_conserved'] and r['sequential_conserved'] else 'NO'} |")
            print("multi-shard", alg, n,
                  f"div={r['abort_rate_divergence']:.4f}")
    lines.append("")
    lines += [
        "Enforced continuously by `tests/test_parity.py` (thresholds with "
        "~1.5x noise headroom).  Remaining known divergence sources: "
        "tick-granular wait retries vs in-place waiter promotion (2PL), "
        "MVCC's bounded version ring vs unbounded lists, MaaT's live-set "
        "join approximating access-time set snapshots.",
        "",
    ]
    with open("PARITY.md", "w") as f:
        f.write("\n".join(lines))
    print("wrote PARITY.md")


if __name__ == "__main__":
    main()
