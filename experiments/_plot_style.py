"""Shared chart styling for the experiment plots (plot_results.py,
timeline_plot.py): ink/grid tokens, the fixed validated categorical
palette (reference-palette slots, pre-validated for adjacent-pair CVD
separation on a white surface), and the recessive-axes styler."""

from __future__ import annotations

INK = "#333333"
GRID = "#dddddd"
#: fixed categorical order — never cycled, never re-ranked
PALETTE = ("#2a78d6", "#eb6834", "#1baf7a", "#eda100", "#e87ba4", "#008300",
           "#4a3aa7", "#e34948")


def style_axes(ax, xlabel: str = "", ylabel: str = "", title: str = ""):
    if xlabel:
        ax.set_xlabel(xlabel, color=INK)
    if ylabel:
        ax.set_ylabel(ylabel, color=INK)
    if title:
        ax.set_title(title, color=INK, fontsize=11)
    ax.grid(True, color=GRID, linewidth=0.6, zorder=0)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color(GRID)
    ax.tick_params(colors=INK, labelsize=8)
