"""YCSB query-generator statistics vs the reference's formulas
(benchmarks/ycsb_query.cpp:181-202,303-376)."""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.workloads import ycsb


def test_zeta_matches_direct_sum():
    n, theta = 1000, 0.6
    direct = sum((1.0 / i) ** theta for i in range(1, n + 1))
    assert abs(ycsb.zeta(n, theta) - direct) < 1e-9


def test_zipf_range_and_skew():
    n, theta = 4095, 0.9
    s = ycsb.ZipfSampler(n, theta)
    rng = np.random.default_rng(0)
    x = s.sample(rng, 200_000)
    assert x.min() >= 1 and x.max() <= n
    # zipf pmf: p(k) = (1/k^theta)/zetan — check the head frequencies
    zetan = s.zetan
    for k in (1, 2, 3):
        expect = (1.0 / k**theta) / zetan
        got = float(np.mean(x == k))
        assert abs(got - expect) < 0.01, (k, got, expect)


def test_theta_zero_is_uniform():
    n = 1023
    s = ycsb.ZipfSampler(n, 0.0)
    rng = np.random.default_rng(1)
    x = s.sample(rng, 100_000)
    # all keys roughly equally likely
    counts = np.bincount(x, minlength=n + 1)[1:]
    assert counts.min() > 0
    assert counts.max() / counts.mean() < 1.6


def test_pool_shape_and_distinct_keys():
    cfg = Config(query_pool_size=2048, req_per_query=10,
                 synth_table_size=1 << 12, zipf_theta=0.9)
    pool = ycsb.gen_query_pool(cfg)
    assert pool.keys.shape == (2048, 10)
    # distinct keys within each txn (ycsb_query.cpp:346-353)
    srt = np.sort(pool.keys, axis=1)
    assert not (srt[:, 1:] == srt[:, :-1]).any()
    assert pool.keys.min() >= 0
    assert pool.keys.max() < cfg.synth_table_size


def test_partition_striping():
    cfg = Config(query_pool_size=1024, part_cnt=4, node_cnt=4,
                 synth_table_size=1 << 12, first_part_local=True)
    pool = ycsb.gen_query_pool(cfg)
    # key % part_cnt == partition (ycsb_wl.cpp:70-74); first req on home part
    assert (pool.keys[:, 0] % 4 == pool.home_part).all()
    parts = np.unique(pool.keys % 4)
    assert len(parts) == 4


def test_write_fraction():
    cfg = Config(query_pool_size=4096, tup_read_perc=0.5, txn_read_perc=0.0,
                 synth_table_size=1 << 14)
    pool = ycsb.gen_query_pool(cfg)
    frac = pool.is_write.mean()
    assert 0.45 < frac < 0.55
    cfg2 = cfg.replace(txn_read_perc=1.0)
    pool2 = ycsb.gen_query_pool(cfg2)
    assert not pool2.is_write.any()


def test_mpr_gates_multi_partition_rate():
    # mpr=0 -> every request stays in the home partition; mpr=1 -> non-first
    # requests choose partitions uniformly; mpr=0.5 -> about half the txns
    # are single-partition (ycsb_query.cpp:213-217).
    from deneva_tpu.config import Config
    from deneva_tpu.workloads.ycsb import gen_query_pool

    base = dict(node_cnt=4, part_cnt=4, synth_table_size=1 << 12,
                req_per_query=4, query_pool_size=4096, zipf_theta=0.0)
    for mpr, lo, hi in [(0.0, 0.0, 0.0), (0.5, 0.40, 0.60), (1.0, 0.95, 1.0)]:
        pool = gen_query_pool(Config(mpr=mpr, **base))
        parts = pool.keys % 4
        multi = (parts != parts[:, :1]).any(axis=1)
        frac = multi.mean()
        # at mpr=1 a txn can still be single-partition by chance (~(1/4)^3
        # of txns), hence hi < 1 tolerance handled via lo bound
        assert lo <= frac <= hi + 1e-9, (mpr, frac)


def test_mpr_zero_single_partition_keys():
    from deneva_tpu.config import Config
    from deneva_tpu.workloads.ycsb import gen_query_pool
    pool = gen_query_pool(Config(node_cnt=2, part_cnt=2,
                                 synth_table_size=1 << 10, req_per_query=3,
                                 query_pool_size=512, mpr=0.0))
    assert ((pool.keys % 2) == pool.home_part[:, None]).all()
