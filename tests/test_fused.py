"""Fused bitonic-sort+segmented-scan kernel tests (ops/fused.py +
the ``Config.fused_arbitrate`` dispatch in ops/segment.py).

Four layers:

1. primitive units: ``fused_sort_scan`` vs the ``lax.sort`` stable
   reference — multi-operand packs with bool payloads, tie-heavy keys,
   non-pow2 widths (padding edge), sentinel-valued real keys, and the
   in-kernel segment-starts / start-index outputs vs the ops/segment.py
   scans; all under ``jax.jit`` too;
2. the structural claim: inside a ``fused_scope`` an eligible sort_by
   emits ZERO standalone ``sort`` primitives in the jaxpr (the kernel's
   bitonic network is compare-exchange only), while the default path is
   untouched — the same histogram ``experiments/profile_tick.py
   --fused`` prints;
3. the headline guarantee: fused-vs-lax BIT-IDENTICAL ``[summary]``
   lines for all seven CC plugins at compacted width (YCSB + TPC-C),
   plus the MAAT chain-window gate — uncontended cells skip the
   pairwise chain (``maat_chain_*`` counters stay zero) with parity
   preserved on contended cells;
4. the capacity discipline: an over-budget width falls back to
   ``lax.sort`` STATICALLY and LOUDLY (warning + run-record accounting,
   identical summaries — never a silent wrong answer), and the fused
   tick stays recompile-free after warmup under the xmeter sentinel.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.obs import profiler as obs_profiler
from deneva_tpu.ops import fused
from deneva_tpu.ops import segment as seg

# ---------------------------------------------------------------------------
# 1. primitive units vs the lax.sort stable reference


def _rand_pack(n, num_keys, n_pay, seed, hi=6):
    """Tie-heavy int32 keys (small value range) + mixed payloads, the
    last payload a bool — exercises the dtype round trip."""
    rng = np.random.default_rng(seed)
    keys = tuple(jnp.asarray(rng.integers(0, hi, n).astype(np.int32))
                 for _ in range(num_keys))
    pays = tuple(jnp.asarray(rng.integers(0, 1 << 20, n).astype(np.int32))
                 for _ in range(max(n_pay - 1, 0)))
    if n_pay:
        pays = pays + (jnp.asarray(rng.random(n) < 0.5),)
    return keys + pays


@pytest.mark.parametrize("n", [1, 2, 7, 64, 96, 128, 130])
def test_fused_matches_stable_lax_sort(n):
    ops = _rand_pack(n, num_keys=2, n_pay=3, seed=n)
    got, _, _ = fused.fused_sort_scan(ops, num_keys=2)
    want = jax.lax.sort(ops, num_keys=2, is_stable=True)
    for g, w in zip(got, want):
        assert g.dtype == w.dtype
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_stable_tie_order_is_exact():
    # all keys equal: a stable sort is the identity permutation
    n = 37
    keys = (jnp.zeros(n, jnp.int32),)
    pay = (jnp.arange(n, dtype=jnp.int32) * 3,)
    (sk, sp), _, _ = fused.fused_sort_scan(keys + pay, num_keys=1)
    np.testing.assert_array_equal(np.asarray(sp), np.asarray(pay[0]))


def test_sentinel_keys_survive_padding():
    # real lanes carrying the INT32_MAX pad sentinel (NULL_KEY rows) must
    # still sort into the real prefix: the lane-index tiebreak orders
    # every real lane before every pad lane
    k = jnp.asarray([2**31 - 1, 3, 2**31 - 1, 1, 2], jnp.int32)
    p = jnp.arange(5, dtype=jnp.int32)
    (sk, sp), starts, _ = fused.fused_sort_scan((k, p), num_keys=1)
    wk, wp = jax.lax.sort((k, p), num_keys=1, is_stable=True)
    np.testing.assert_array_equal(np.asarray(sk), np.asarray(wk))
    np.testing.assert_array_equal(np.asarray(sp), np.asarray(wp))


@pytest.mark.parametrize("n", [5, 64, 130])
def test_in_kernel_scans_match_segment_reference(n):
    ops = _rand_pack(n, num_keys=1, n_pay=1, seed=100 + n)
    (sk, _), starts, sidx = fused.fused_sort_scan(ops, num_keys=1)
    ref_starts = seg.segment_starts(sk)
    ref_sidx = seg.start_index(ref_starts)
    assert starts.dtype == ref_starts.dtype
    np.testing.assert_array_equal(np.asarray(starts),
                                  np.asarray(ref_starts))
    np.testing.assert_array_equal(np.asarray(sidx), np.asarray(ref_sidx))


def test_fused_sort_scan_under_jit():
    ops = _rand_pack(96, num_keys=2, n_pay=2, seed=7)

    @jax.jit
    def f(*ops):
        return fused.fused_sort_scan(ops, num_keys=2)

    got, starts, _ = f(*ops)
    want = jax.lax.sort(ops, num_keys=2, is_stable=True)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    np.testing.assert_array_equal(np.asarray(starts),
                                  np.asarray(seg.segment_starts(want[0])))


# ---------------------------------------------------------------------------
# 2. dispatch structure: sort primitives leave the jaxpr inside the scope


def _sort_eqn_count(jx):
    n = 0
    for eqn in jx.eqns:
        if eqn.primitive.name == "sort":
            n += 1
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None:
                    n += _sort_eqn_count(inner)
    return n


def test_scope_replaces_sort_primitive():
    k = jnp.asarray([3, 1, 2, 1, 3, 1], jnp.int32)

    # a fresh closure per trace: jax caches traces by function identity,
    # and the scope is a trace-TIME static — exactly why the engine
    # builds one tick closure per Engine (scheduler.make_tick)
    def mk():
        def f(k):
            (sk,), (p,) = seg.sort_by(
                (k,), (jnp.arange(6, dtype=jnp.int32),))
            return sk, p, seg.segment_starts(sk)
        return f

    assert _sort_eqn_count(jax.make_jaxpr(mk())(k).jaxpr) == 1
    cfg = Config(cc_alg="NO_WAIT", fused_arbitrate=True)
    with seg.fused_scope(cfg):
        fused_jx = jax.make_jaxpr(mk())(k)
    assert _sort_eqn_count(fused_jx.jaxpr) == 0
    # and the scope is not sticky
    assert _sort_eqn_count(jax.make_jaxpr(mk())(k).jaxpr) == 1


def test_scope_results_match_lax_path():
    k = jnp.asarray([5, 1, 5, 2, 1, 1, 5, 9], jnp.int32)
    v = jnp.arange(8, dtype=jnp.int32)

    def f(k, v):
        (sk,), (sv,) = seg.sort_by((k,), (v,))
        st = seg.segment_starts(sk)
        return sk, sv, st, seg.start_index(st)

    base = f(k, v)
    with seg.fused_scope(Config(cc_alg="NO_WAIT", fused_arbitrate=True)):
        got = f(k, v)
    for b, g in zip(base, got):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(g))


# ---------------------------------------------------------------------------
# 3. engine parity: fused vs lax bit-identical [summary]

YCSB_KW = dict(batch_size=16, req_per_query=8, synth_table_size=128,
               zipf_theta=0.8, query_pool_size=256, admit_cap=4,
               max_ticks=10**6, warmup_ticks=0)

TPCC_KW = dict(workload="TPCC", batch_size=64, num_wh=4, part_cnt=1,
               node_cnt=1, query_pool_size=1024, cust_per_dist=1000,
               max_items=128, perc_payment=0.5, admit_cap=16,
               warmup_ticks=0)

ALL_ALGS = ("NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT",
            "CALVIN")


def _summary(cfg, n_ticks):
    eng = Engine(cfg)
    with warnings.catch_warnings():
        # a loud capacity fallback is legal here; tested separately
        warnings.simplefilter("ignore")
        return eng.summary(eng.run(n_ticks))


def _assert_fused_parity(alg, base_kw, n_ticks):
    sl = _summary(Config(cc_alg=alg, **base_kw), n_ticks)
    sf = _summary(Config(cc_alg=alg, fused_arbitrate=True, **base_kw),
                  n_ticks)
    diff = {k: (sl[k], sf.get(k)) for k in sl if sl[k] != sf.get(k)}
    assert not diff, f"{alg}: fused vs lax summary diverged: {diff}"
    assert set(sf) == set(sl)
    assert sl["txn_cnt"] > 0


# tier-1 870s budget split (precedent: PR 2/PR 6 re-splits): each pair
# compiles two engines, so only the cheapest cell stays tier-1; MAAT's
# fused-vs-lax bit-identity is ALSO tier-1 via the contended chain-gate
# cell below, which doubles as its parity pair
@pytest.mark.parametrize("alg", [
    "NO_WAIT",
    pytest.param("WAIT_DIE", marks=pytest.mark.slow),
    pytest.param("TIMESTAMP", marks=pytest.mark.slow),
    pytest.param("MVCC", marks=pytest.mark.slow),
    pytest.param("OCC", marks=pytest.mark.slow),
    pytest.param("MAAT", marks=pytest.mark.slow),
    pytest.param("CALVIN", marks=pytest.mark.slow),
])
def test_ycsb_fused_parity(alg):
    _assert_fused_parity(alg, YCSB_KW, n_ticks=120)


# the TPC-C cells trace the fused bitonic network at the compact_auto
# width (P=2048, ~120 inlined merge stages in interpret mode): each
# pair compiles two full engines at 80-150s on CPU, so the whole matrix
# rides the slow lane (precedent: test_compaction's TPC-C MAAT cell);
# the YCSB matrix above keeps all seven plugins tier-1
@pytest.mark.slow
@pytest.mark.parametrize("alg", ALL_ALGS)
def test_tpcc_fused_parity(alg):
    _assert_fused_parity(alg, dict(compact_auto=True, **TPCC_KW),
                         n_ticks=40)


# ---- MAAT chain-window gate ----

UNCONTENDED_KW = dict(batch_size=16, req_per_query=4,
                      synth_table_size=65536, zipf_theta=0.0,
                      query_pool_size=256, admit_cap=4,
                      max_ticks=10**6, warmup_ticks=0)

CONTENDED_KW = dict(batch_size=16, req_per_query=8, synth_table_size=64,
                    zipf_theta=0.9, query_pool_size=256, admit_cap=8,
                    max_ticks=10**6, warmup_ticks=0)


def test_maat_chain_gate_skips_uncontended():
    # no same-row same-tick validators -> the lax.cond takes the skip
    # branch every tick: the chain never caps, never pushes, never
    # overflows, and commits still flow
    s = _summary(Config(cc_alg="MAAT", **UNCONTENDED_KW), 150)
    assert s["txn_cnt"] > 0
    assert s["maat_chain_cap_cnt"] == 0
    assert s["maat_chain_push_cnt"] == 0
    assert s["maat_chain_overflow_cnt"] == 0


# the contended MAAT pair is two chain-gate compiles (~37 s on the
# tier-1 box) — slow lane, same as the TPC-C MAAT cell above; the
# uncontended gate + skip test keeps the chain gate tier-1
@pytest.mark.slow
def test_maat_chain_gate_contended_parity():
    # contended cell: the chain genuinely engages (counters move) and
    # the fused path reproduces it bit-for-bit
    sl = _summary(Config(cc_alg="MAAT", **CONTENDED_KW), 150)
    sf = _summary(Config(cc_alg="MAAT", fused_arbitrate=True,
                         **CONTENDED_KW), 150)
    assert sl["maat_chain_cap_cnt"] > 0
    assert sl["maat_chain_push_cnt"] > 0
    assert sl == sf


# ---------------------------------------------------------------------------
# 4. capacity discipline + recompile sentinel


def test_capacity_fallback_is_loud_and_counted():
    fused.reset_fallbacks()
    cfg = Config(cc_alg="NO_WAIT", fused_arbitrate=True,
                 fused_max_lanes=32, **YCSB_KW)
    eng = Engine(cfg)
    with pytest.warns(UserWarning, match="fallback to lax.sort"):
        st = eng.run(40)
    s = eng.summary(st)
    snap = fused.fallback_snapshot()
    assert snap["count"] > 0
    assert any(e["reason"] == "width" for e in snap["events"])
    # counted in the run record (obs/profiler.py), NOT in [summary] —
    # summary lines must stay bit-identical to the lax path's
    rec = obs_profiler.run_record(cfg, s)
    assert rec["fused_fallbacks"]["count"] == snap["count"]
    assert "fused_fallbacks" not in s
    # the fallback IS the lax path: never a silent wrong answer
    sl = _summary(Config(cc_alg="NO_WAIT", **YCSB_KW), 40)
    assert s == sl


def test_ineligible_dtype_falls_back():
    fused.reset_fallbacks()
    cfg = Config(cc_alg="NO_WAIT", fused_arbitrate=True)
    k64 = jnp.arange(8, dtype=jnp.float32)
    with seg.fused_scope(cfg), warnings.catch_warnings():
        warnings.simplefilter("ignore")
        out = seg.sort_pack((k64,), num_keys=1)
    np.testing.assert_array_equal(np.asarray(out[0]),
                                  np.asarray(jnp.sort(k64)))
    assert any(e["reason"] == "dtype"
               for e in fused.fallback_snapshot()["events"])


@pytest.mark.parametrize("alg", [
    "NO_WAIT",
    # the MAAT fused compile alone is ~29 s — slow lane (tier-1 budget)
    pytest.param("MAAT", marks=pytest.mark.slow),
])
def test_fused_zero_post_warm_recompiles(alg):
    eng = Engine(Config(cc_alg=alg, fused_arbitrate=True, xmeter=True,
                        **YCSB_KW))
    st = eng.run(12)
    xm = eng.xmeter
    assert xm.entries["tick"].compile_cnt == 1
    xm.mark_warm()
    eng.run(12, st)
    assert xm.steady_violations() == []
    assert xm.entries["tick"].compile_cnt == 1
