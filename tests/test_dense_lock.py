"""Equivalence of the dense scatter arbitration vs the sorted-segment join.

cc/twopl.py has two implementations of the same decision rules:
`arbitrate` (bitonic sort + segment reductions) and `arbitrate_window`
(per-row held-lock scratch + request-only sort).  They must produce
IDENTICAL schedules, so a
full engine run under either must match in every stat and every row of the
data oracle — under contention, where the decision algebra actually bites.
"""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine


def run_pair(alg, **kw):
    base = dict(cc_alg=alg, batch_size=256, synth_table_size=1 << 10,
                req_per_query=6, zipf_theta=0.8, tup_read_perc=0.5,
                query_pool_size=1 << 10)
    base.update(kw)
    outs = []
    for dense in (True, False):
        eng = Engine(Config(dense_lock_state=dense, **base))
        st = eng.run(40)
        outs.append((eng.summary(st), np.asarray(st.data)))
    return outs


@pytest.mark.parametrize("alg", ["NO_WAIT", "WAIT_DIE", "CALVIN"])
def test_single_shard_equivalence(alg):
    (s1, d1), (s2, d2) = run_pair(alg)
    assert s1 == s2
    assert (d1 == d2).all()


@pytest.mark.parametrize("alg", ["NO_WAIT", "WAIT_DIE"])
def test_equivalence_under_greedy_window(alg):
    (s1, d1), (s2, d2) = run_pair(alg, acquire_window=6)
    assert s1 == s2
    assert (d1 == d2).all()


def test_equivalence_read_heavy_wait_die():
    (s1, d1), (s2, d2) = run_pair("WAIT_DIE", tup_read_perc=0.9,
                                  zipf_theta=0.95)
    assert s1 == s2
    assert (d1 == d2).all()


# Unlocked by the shard_map compat fix (failed at the seed); exceeds
# the tier-1 time budget -- run with `-m slow`.
@pytest.mark.slow
@pytest.mark.parametrize("alg", ["NO_WAIT", "WAIT_DIE", "CALVIN"])
def test_sharded_equivalence(alg):
    from deneva_tpu.parallel.sharded import ShardedEngine
    outs = []
    for dense in (True, False):
        cfg = Config(cc_alg=alg, dense_lock_state=dense, node_cnt=4,
                     part_cnt=4, batch_size=32, synth_table_size=1 << 10,
                     req_per_query=4, zipf_theta=0.8,
                     query_pool_size=512, mpr=1.0, part_per_txn=4)
        eng = ShardedEngine(cfg)
        st = eng.run(25)
        outs.append((eng.summary(st),
                     np.concatenate([np.asarray(st.data[i])
                                     for i in range(4)])))
    (s1, d1), (s2, d2) = outs
    assert s1 == s2
    assert (d1 == d2).all()


def test_tpcc_equivalence():
    outs = []
    for dense in (True, False):
        cfg = Config(workload="TPCC", cc_alg="NO_WAIT",
                     dense_lock_state=dense, batch_size=64, num_wh=4,
                     query_pool_size=512, cust_per_dist=1000, max_items=64)
        eng = Engine(cfg)
        st = eng.run(30)
        outs.append((eng.summary(st),
                     {k: np.asarray(v) for k, v in st.tables.items()}))
    (s1, t1), (s2, t2) = outs
    assert s1 == s2
    for k in t1:
        assert (t1[k] == t2[k]).all(), k
