"""Golden micro-schedules for the T/O family (row_ts.cpp / row_mvcc.cpp)."""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.engine.state import STATUS_BACKOFF, STATUS_WAITING
from tests.test_engine_nowait import make_pool, small_cfg


def test_write_too_late_aborts():
    # txn0 (ts=1): [k1 W, k5 W]; txn1 (ts=2): [k5 R, k2 R].
    # tick0: txn0 prewrites k1; txn1 reads k5 -> rts[k5]=2.
    # tick1: txn0 prewrites k5 at ts=1 < rts=2 -> Abort (row_ts.cpp:192-194).
    keys = np.array([[1, 5], [5, 2]], np.int32)
    iw = np.array([[True, True], [False, False]])
    pool = make_pool(keys, iw)
    eng = Engine(small_cfg(cc_alg="TIMESTAMP", batch_size=2, query_pool_size=2),
                 pool=pool)
    st = eng.run(2)
    assert int(st.txn.status[0]) == STATUS_BACKOFF
    assert eng.summary(st)["total_txn_abort_cnt"] == 1


def test_read_waits_on_older_prewrite_then_proceeds():
    # txn0 (ts=1): [k5 W, k1 W]; txn1 (ts=2): [k2 R, k5 R].
    # tick1: txn1's read of k5 at ts=2 sees pending prewrite (pts=1 < 2)
    #        -> WAIT (row_ts.cpp:181-186).
    # tick2: txn0 commits (wts[k5]=1); txn1's read retries: 2 >= 1 -> grant.
    keys = np.array([[5, 1], [2, 5]], np.int32)
    iw = np.array([[True, True], [False, False]])
    pool = make_pool(keys, iw)
    eng = Engine(small_cfg(cc_alg="TIMESTAMP", batch_size=2, query_pool_size=2),
                 pool=pool)
    st = eng.run(2)
    assert int(st.txn.status[1]) == STATUS_WAITING
    st = eng.run(1, st)
    assert int(st.txn.cursor[1]) == 2       # read granted after commit
    s = eng.summary(st)
    assert s["txn_cnt"] == 1 and s["total_txn_abort_cnt"] == 0


def _old_read_pool():
    # txn0 (ts=1): [k7 R, k6 R, k5 R] — reads k5 in tick2's access phase.
    # txn1 (ts=2): [k5 W, k8 W], n_req=2 — finishes tick1, commits in
    # tick2's commit phase (before txn0's read): wts[k5] = 2 > 1.
    keys = np.array([[7, 6, 5], [5, 8, 8]], np.int32)
    iw = np.array([[False, False, False], [True, True, True]])
    return make_pool(keys, iw, n_req=[3, 2])


def test_to_aborts_but_mvcc_reads_old_version():
    # txn1 commits version wts=2 of k5 at tick3; txn0 reads k5 at ts=1 in
    # tick3 (after commit phase).  TIMESTAMP: 1 < wts=2 -> Abort
    # (row_ts.cpp:176).  MVCC: no version <= ts=1 exists but ring never
    # wrapped -> initial version serves the read (row_mvcc.cpp:266-271).
    pool = _old_read_pool()
    cfg = dict(batch_size=2, query_pool_size=2, req_per_query=3)

    eng_to = Engine(small_cfg(cc_alg="TIMESTAMP", **cfg), pool=pool)
    st = eng_to.run(4)
    assert eng_to.summary(st)["total_txn_abort_cnt"] >= 1

    eng_mv = Engine(small_cfg(cc_alg="MVCC", **cfg), pool=pool)
    st = eng_mv.run(5)
    s = eng_mv.summary(st)
    assert s["total_txn_abort_cnt"] == 0
    assert s["txn_cnt"] >= 2


def test_mvcc_write_too_late_aborts():
    # txn2 (ts=3) reads k5 (version 0) at tick0 -> rts0[k5]=3.
    # txn0 (ts=1) prewrites k5 at tick1: target version 0 has rts=3 > 1
    # -> Abort (row_mvcc.cpp:217-239).
    keys = np.array([[1, 5, 9], [11, 12, 13], [5, 8, 7]], np.int32)
    iw = np.array([[True, True, True], [False, False, False],
                   [False, False, False]])
    pool = make_pool(keys, iw)
    eng = Engine(small_cfg(cc_alg="MVCC", batch_size=3, query_pool_size=3,
                           req_per_query=3), pool=pool)
    st = eng.run(2)
    assert int(st.txn.status[0]) == STATUS_BACKOFF


@pytest.mark.parametrize("alg", ["TIMESTAMP", "MVCC"])
@pytest.mark.parametrize("window", [1, 4])
def test_oracle_under_contention(alg, window):
    cfg = Config(batch_size=64, synth_table_size=256, req_per_query=4,
                 query_pool_size=512, zipf_theta=0.9, tup_read_perc=0.5,
                 cc_alg=alg, warmup_ticks=0, acquire_window=window,
                 his_recycle_len=4)
    eng = Engine(cfg)
    st = eng.run(60)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert np.asarray(st.data).sum() == s["write_cnt"]


@pytest.mark.parametrize("alg", ["NO_WAIT", "WAIT_DIE"])
def test_greedy_window_oracle_and_progress(alg):
    # low contention (128 concurrent requests on 16k rows): greedy mode
    # completes txns in ~2-3 ticks instead of R+1
    cfg = Config(batch_size=32, synth_table_size=1 << 14, req_per_query=4,
                 query_pool_size=512, zipf_theta=0.0, tup_read_perc=0.5,
                 cc_alg=alg, warmup_ticks=0, acquire_window=4)
    eng = Engine(cfg)
    st = eng.run(30)
    s = eng.summary(st)
    assert s["txn_cnt"] > 250          # vs ~180 max in strict mode (30/5*32)
    assert np.asarray(st.data).sum() == s["write_cnt"]


def test_mvcc_out_of_order_commit_does_not_serve_stale_version():
    # Ring H=1.  txn1 (ts=2) commits k5 quickly (version 2).  txn0 (ts=1,
    # long-running) commits its k5 write LATE: with eviction by insertion
    # order the late old version would shadow version 2 and a read at ts=3
    # would silently be served version 1; with min-ts replacement + floor,
    # version 1 folds into w_floor, version 2 stays, and the ts=3 read
    # correctly observes version 2.
    keys = np.array([[5, 1, 2, 3], [5, 8, 8, 8], [7, 9, 10, 5]], np.int32)
    iw = np.array([[True, True, True, True],
                   [True, True, True, True],
                   [False, False, False, False]])
    pool = make_pool(keys, iw, n_req=[4, 2, 4])
    eng = Engine(small_cfg(cc_alg="MVCC", batch_size=3, query_pool_size=3,
                           req_per_query=4), pool=pool)
    eng_cfg = eng.cfg.replace(his_recycle_len=1)
    eng = Engine(eng_cfg, pool=pool)
    st = eng.run(6)   # up to txn0's late commit, before pool wraparound
    db = st.db
    # version 2 must still be in the ring (not shadowed by the late ts=1)
    assert int(np.asarray(db["w_ring"][5 * 1 + 0])) == 2   # flat ring, H=1
    assert int(np.asarray(db["w_floor"][5])) >= 1
    s = eng.summary(st)
    assert np.asarray(st.data).sum() == s["write_cnt"]
    # same-tick same-row committers: winner must be chosen by ts, not slot
    # order (two reincarnated writers of k5 with ts 4 and 5 commit together
    # at tick 7 after the pool wraps)
    st = eng.run(2, st)
    assert int(np.asarray(st.db["w_ring"][5 * 1 + 0])) == 5
    assert int(np.asarray(st.db["w_floor"][5])) >= 4


def test_mvcc_ring_eviction_is_safe():
    # tiny ring + hot keys: evictions must abort readers, never corrupt
    cfg = Config(batch_size=32, synth_table_size=64, req_per_query=2,
                 query_pool_size=256, zipf_theta=0.9, tup_read_perc=0.3,
                 cc_alg="MVCC", warmup_ticks=0, his_recycle_len=2)
    eng = Engine(cfg)
    st = eng.run(80)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert np.asarray(st.data).sum() == s["write_cnt"]
