"""Static analyzer tests: per-rule fixture snippets for the AST engine,
the suppression grammar, the CLI contract (exit code / JSON), the jaxpr
plugin verifier (accepts all shipped plugins, rejects a broken one), the
self-lint gate, and the scatter-race regression that motivated the
SCATTER-RACE rule (twopl's identity-restore of the held-lock scratch).
"""

import json
import subprocess
import sys
import textwrap

import jax.numpy as jnp
import pytest

from deneva_tpu.lint import run_lint

pytestmark = pytest.mark.lint


# ---------------------------------------------------------------------------
# helpers

def lint_src(tmp_path, code, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(code))
    return run_lint([str(p)], jaxpr=False)


def active_rules(findings):
    return sorted({f.rule for f in findings if not f.suppressed})


# ---------------------------------------------------------------------------
# AST rules: each has a bad fixture (flagged) and a good one (clean)

BAD_TRACED_BRANCH = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        if jnp.sum(x) > 0:
            return x + 1
        return x
"""

GOOD_TRACED_BRANCH = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return jnp.where(jnp.sum(x) > 0, x + 1, x)
"""

BAD_CONCRETIZE = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        n = int(jnp.sum(x))
        return x[:1] * n
"""

BAD_ITEM = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return x * jnp.max(x).item()
"""

BAD_DATA_DEP = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        idx = jnp.nonzero(x > 0)[0]
        return x[idx]
"""

GOOD_DATA_DEP = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        idx = jnp.nonzero(x > 0, size=4, fill_value=0)[0]
        return x[idx]
"""

BAD_DTYPE = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return x + jnp.arange(8)
"""

GOOD_DTYPE = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return x + jnp.arange(8, dtype=jnp.int32)
"""

BAD_HOST = """
    import time
    import jax

    @jax.jit
    def step(x):
        print("tick at", time.time())
        return x
"""

GOOD_HOST = """
    import time

    def driver(x):
        # host code outside any kernel region: host calls are fine
        print("tick at", time.time())
        return x
"""

BAD_SCATTER = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(db, keys, vals):
        return db.at[keys].set(vals, mode="drop")
"""

GOOD_SCATTER_ADD = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(db, keys, vals):
        return db.at[keys].add(vals, mode="drop")
"""

GOOD_SCATTER_UNIQUE = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(db, keys, vals):
        return db.at[keys].set(vals, mode="drop", unique_indices=True)
"""

GOOD_SCATTER_ARANGE = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(db, vals):
        return db.at[jnp.arange(8, dtype=jnp.int32)].set(vals)
"""

BAD_PAD_SORT = """
    import jax
    from deneva_tpu.ops import segment as seg

    @jax.jit
    def step(live, key, ts):
        view, (ckey, cts) = seg.compact_entries(live, 8, key, ts)
        padded = jax.lax.sort((key, ts), num_keys=1, is_stable=False)
        return view, ckey, cts, padded
"""

GOOD_PAD_SORT_COMPACTED = """
    import jax
    from deneva_tpu.ops import segment as seg

    @jax.jit
    def step(live, key, ts):
        view, (ckey, cts) = seg.compact_entries(live, 8, key, ts)
        return jax.lax.sort((ckey, cts), num_keys=1, is_stable=False)
"""

GOOD_PAD_SORT_NO_VIEW = """
    import jax

    @jax.jit
    def step(key, ts):
        # no compaction view in scope: full-width sorts are fine
        return jax.lax.sort((key, ts), num_keys=1, is_stable=False)
"""

GOOD_IS_NONE_DEFAULT = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x, scale=None):
        if scale is None:
            scale = jnp.sum(x)
        return x * scale
"""

BAD_PALLAS_KERNEL = """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def fused(x):
        def kernel(x_ref, o_ref):
            v = x_ref[:]
            if jnp.sum(v) > 0:
                o_ref[:] = v + 1
            else:
                o_ref[:] = v
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)
"""

GOOD_PALLAS_KERNEL = """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def fused(x):
        def kernel(x_ref, o_ref):
            v = x_ref[:]
            o_ref[:] = jnp.where(jnp.sum(v) > 0, v + 1, v)
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)
"""

BAD_JIT_IN_LOOP = """
    import jax

    def driver(fn, xs):
        outs = []
        for x in xs:
            outs.append(jax.jit(fn)(x))
        return outs
"""

BAD_PARTIAL_JIT_IN_LOOP = """
    import functools

    import jax

    def driver(fn, x):
        for _ in range(4):
            step = functools.partial(jax.jit, donate_argnums=0)(fn)
            x = step(x)
        return x
"""

BAD_STATIC_ARGNUMS_IN_LOOP = """
    def driver(wrap, fn, x):
        n = 0
        while n < 4:
            step = wrap(fn, static_argnums=(0,))
            x = step(n, x)
            n += 1
        return x
"""

GOOD_JIT_HOISTED = """
    import jax

    def driver(fn, xs):
        step = jax.jit(fn)          # hoisted: one dispatch cache
        outs = []
        for x in xs:
            outs.append(step(x))
        return outs
"""


@pytest.mark.parametrize("code,rule", [
    (BAD_TRACED_BRANCH, "TRACED-BRANCH"),
    (BAD_CONCRETIZE, "TRACER-CONCRETIZE"),
    (BAD_ITEM, "TRACER-CONCRETIZE"),
    (BAD_DATA_DEP, "DATA-DEP-SHAPE"),
    (BAD_DTYPE, "IMPLICIT-DTYPE"),
    (BAD_HOST, "HOST-CALL"),
    (BAD_SCATTER, "SCATTER-RACE"),
    (BAD_PAD_SORT, "PAD-WIDTH-SORT"),
    (BAD_PALLAS_KERNEL, "TRACED-BRANCH"),
    (BAD_JIT_IN_LOOP, "COMPILE-IN-LOOP"),
    (BAD_PARTIAL_JIT_IN_LOOP, "COMPILE-IN-LOOP"),
    (BAD_STATIC_ARGNUMS_IN_LOOP, "COMPILE-IN-LOOP"),
], ids=["traced-branch", "concretize-int", "concretize-item", "data-dep",
        "implicit-dtype", "host-call", "scatter-race", "pad-width-sort",
        "pallas-kernel-seeded", "jit-in-loop", "partial-jit-in-loop",
        "static-argnums-in-loop"])
def test_bad_fixture_is_flagged(tmp_path, code, rule):
    assert rule in active_rules(lint_src(tmp_path, code))


@pytest.mark.parametrize("code", [
    GOOD_TRACED_BRANCH, GOOD_DATA_DEP, GOOD_DTYPE, GOOD_HOST,
    GOOD_SCATTER_ADD, GOOD_SCATTER_UNIQUE, GOOD_SCATTER_ARANGE,
    GOOD_PAD_SORT_COMPACTED, GOOD_PAD_SORT_NO_VIEW, GOOD_PALLAS_KERNEL,
    GOOD_IS_NONE_DEFAULT, GOOD_JIT_HOISTED,
], ids=["where", "sized-nonzero", "explicit-dtype", "host-outside-kernel",
        "commutative-add", "declared-unique", "arange-index",
        "sort-on-compacted", "sort-without-view", "pallas-kernel-clean",
        "is-none-default", "jit-hoisted"])
def test_good_fixture_is_clean(tmp_path, code):
    assert active_rules(lint_src(tmp_path, code)) == []


def test_rules_only_apply_inside_kernel_regions(tmp_path):
    # the same hazards in plain host code are not findings
    code = """
        import jax.numpy as jnp

        def host_helper(x):
            if jnp.sum(x) > 0:
                return int(jnp.sum(x))
            return 0
    """
    assert active_rules(lint_src(tmp_path, code)) == []


def test_kernel_marker_promotes_function(tmp_path):
    # no decorator the seed scan could find, only the explicit marker
    code = """
        import jax.numpy as jnp

        # lint: kernel
        def step(x):
            if jnp.sum(x) > 0:
                return x + 1
            return x
    """
    assert "TRACED-BRANCH" in active_rules(lint_src(tmp_path, code))


def test_kernelness_propagates_through_calls(tmp_path):
    # helper is only hazardous because a jitted caller reaches it
    code = """
        import jax
        import jax.numpy as jnp

        def helper(x):
            if jnp.sum(x) > 0:
                return x + 1
            return x

        @jax.jit
        def step(x):
            return helper(x)
    """
    assert "TRACED-BRANCH" in active_rules(lint_src(tmp_path, code))


# ---------------------------------------------------------------------------
# suppression grammar

def test_suppression_with_reason(tmp_path):
    code = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(db, keys, vals):
            return db.at[keys].set(
                vals, mode="drop")  # lint: disable=SCATTER-RACE unique keys
    """
    findings = lint_src(tmp_path, code)
    sup = [f for f in findings if f.suppressed]
    assert active_rules(findings) == []
    assert len(sup) == 1 and sup[0].rule == "SCATTER-RACE"
    assert "unique keys" in sup[0].suppress_reason


def test_disable_next_form(tmp_path):
    code = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(db, keys, vals):
            # lint: disable-next=SCATTER-RACE keys proven unique upstream
            out = db.at[keys].set(vals, mode="drop")
            return out
    """
    findings = lint_src(tmp_path, code)
    assert active_rules(findings) == []
    assert sum(f.suppressed for f in findings) == 1


def test_bare_suppression_is_a_finding(tmp_path):
    code = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(db, keys, vals):
            # lint: disable-next=SCATTER-RACE
            return db.at[keys].set(vals, mode="drop")
    """
    # the scatter itself is silenced, but the reasonless comment is not
    assert active_rules(lint_src(tmp_path, code)) == ["SUPPRESS-NO-REASON"]


# ---------------------------------------------------------------------------
# CLI contract

def run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "deneva_tpu.lint", *argv],
        capture_output=True, text=True)


def test_cli_exit_nonzero_on_bad_file(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text(textwrap.dedent(BAD_SCATTER))
    r = run_cli(str(p), "--no-jaxpr")
    assert r.returncode > 0
    assert "SCATTER-RACE" in r.stdout


def test_cli_exit_zero_on_clean_file(tmp_path):
    p = tmp_path / "good.py"
    p.write_text(textwrap.dedent(GOOD_SCATTER_ADD))
    r = run_cli(str(p), "--no-jaxpr")
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_json_format(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text(textwrap.dedent(BAD_DTYPE))
    r = run_cli(str(p), "--no-jaxpr", "--format", "json")
    doc = json.loads(r.stdout)
    assert doc["unsuppressed"] == r.returncode > 0
    assert any(f["rule"] == "IMPLICIT-DTYPE" for f in doc["findings"])


# ---------------------------------------------------------------------------
# jaxpr plugin verifier

def test_verifier_accepts_all_shipped_plugins():
    from deneva_tpu.cc import REGISTRY
    from deneva_tpu.lint.jaxpr_engine import verify_all
    assert len(REGISTRY) >= 7
    assert verify_all() == []


def test_verifier_rejects_contract_violations():
    from deneva_tpu.cc import REGISTRY, register
    from deneva_tpu.cc.base import AccessDecision, CCPlugin
    from deneva_tpu.lint.jaxpr_engine import verify_plugin

    class Broken(CCPlugin):
        name = "LINT_TEST_BROKEN"
        txn_db_fields = ()

        def init_db(self, cfg, n_rows, B, R):
            return {"x": jnp.zeros(n_rows, jnp.int32)}

        def on_start(self, cfg, db, txn, mask_b):
            # contract violation: output pytree structure changed
            return {"x": db["x"], "extra": jnp.zeros(3, jnp.float32)}

        def access(self, cfg, db, txn, mask_b):
            import jax
            jax.debug.print("boo")  # contract violation: callback prim
            B, R = txn.keys.shape
            z = jnp.zeros((B, R), bool)
            return AccessDecision(grant=z, wait=z, abort=z), db

    register(Broken())
    try:
        rules = {f.rule for f in verify_plugin("LINT_TEST_BROKEN")}
        assert "CONTRACT-STRUCT" in rules
        assert "CONTRACT-CALLBACK" in rules
    finally:
        del REGISTRY["LINT_TEST_BROKEN"]


# ---------------------------------------------------------------------------
# self-lint: the shipped tree stays clean (modulo recorded suppressions)

def test_self_lint_tree_is_clean():
    import os

    import deneva_tpu
    pkg = os.path.dirname(deneva_tpu.__file__)
    findings = [f for f in run_lint([pkg], jaxpr=False) if not f.suppressed]
    assert findings == [], "\n".join(
        f"{f.location()}: {f.rule}: {f.message}" for f in findings)


# ---------------------------------------------------------------------------
# scatter-race regression: twopl's held-scratch identity-restore

def test_duplicate_index_set_is_order_dependent():
    # two S-lock holders of one row -> duplicate row ids in the scatter.
    # With per-lane payloads, a .set applies in unspecified order: the
    # lane permutation changes the result, so the schedule would depend
    # on XLA's scatter ordering.  The commutative .max does not.
    row = jnp.array([3, 3, 5], jnp.int32)
    val = jnp.array([10, 20, 30], jnp.int32)
    base = jnp.zeros(8, jnp.int32)
    fwd = base.at[row].set(val)
    rev = base.at[row[::-1]].set(val[::-1])  # same (row, val) pairs
    assert int(fwd[3]) != int(rev[3])  # order leaks into the result
    m_fwd = base.at[row].max(val)
    m_rev = base.at[row[::-1]].max(val[::-1])
    assert (m_fwd == m_rev).all()


def test_twopl_identity_restore_with_duplicate_holders():
    # arbitrate_window must hand back an identity-valued scratch even when
    # several read holders share a row (duplicate indices in the restore
    # scatter); the .max(BIG_TS) restore saturates every touched row back
    # to the identity regardless of scatter order
    from deneva_tpu.cc.twopl import BIG_TS, arbitrate_window, init_lock_tmp
    from deneva_tpu.engine.state import TxnState

    B, R = 4, 2
    txn = TxnState.empty(B, R)
    # three txns hold a read lock on row 7 (cursor past the access)
    txn = txn._replace(
        keys=txn.keys.at[0:3, 0].set(7),
        is_write=txn.is_write.at[:, :].set(False),
        cursor=txn.cursor.at[0:3].set(1),
        n_req=txn.n_req.at[0:3].set(2),
        ts=jnp.arange(1, B + 1, dtype=jnp.int32))
    active = jnp.array([True, True, True, False])
    tmp = init_lock_tmp(16)
    *_, tmp2 = arbitrate_window(txn, active, "NO_WAIT", tmp, window=1)
    assert (tmp2["lk_held"] == BIG_TS).all()
