"""WAIT_DIE golden micro-schedules (semantics of row_lock.cpp:91-151):
older txns wait for younger lock holders; younger txns die."""

import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.engine.state import STATUS_BACKOFF, STATUS_WAITING
from tests.test_engine_nowait import make_pool, small_cfg


def wd_cfg(**kw):
    kw.setdefault("cc_alg", "WAIT_DIE")
    return small_cfg(**kw)


def test_older_waits_for_younger_holder():
    # txn0 (older): [k1, k5];  txn1 (younger): [k5, k2] — all writes.
    # tick0: txn0 takes k1, txn1 takes k5.
    # tick1: txn0 wants k5 (held by younger txn1) -> WAIT; txn1 takes k2.
    # tick2: txn1 finishes+commits, releasing k5 -> txn0 grabs it same tick.
    keys = np.array([[1, 5], [5, 2]], np.int32)
    pool = make_pool(keys, np.ones((2, 2), bool))
    eng = Engine(wd_cfg(batch_size=2, query_pool_size=2), pool=pool)

    st = eng.run(2)
    assert int(st.txn.status[0]) == STATUS_WAITING
    assert int(st.txn.cursor[0]) == 1
    assert int(st.txn.cursor[1]) == 2

    st = eng.run(1, st)
    s = eng.summary(st)
    assert s["txn_cnt"] == 1           # txn1 committed
    assert int(st.txn.cursor[0]) == 2  # txn0 acquired k5 after release
    assert s["total_txn_abort_cnt"] == 0

    st = eng.run(1, st)
    assert eng.summary(st)["txn_cnt"] == 2


def test_younger_dies_on_older_holder():
    # txn0 (older): [k5, k1]; txn1 (younger): [k2, k5].
    # tick1: txn1 wants k5 held by OLDER txn0 -> die (ts1 > ts0).
    keys = np.array([[5, 1], [2, 5]], np.int32)
    pool = make_pool(keys, np.ones((2, 2), bool))
    eng = Engine(wd_cfg(batch_size=2, query_pool_size=2), pool=pool)
    st = eng.run(2)
    assert int(st.txn.status[1]) == STATUS_BACKOFF
    assert int(st.txn.restarts[1]) == 1
    assert eng.summary(st)["total_txn_abort_cnt"] == 1


def test_same_tick_ww_younger_dies():
    # both request k5 first access in the same tick: older (slot 0) is
    # processed first in ts order and wins; younger conflicts with a granted
    # owner that is older -> die.
    keys = np.array([[5, 1], [5, 2]], np.int32)
    pool = make_pool(keys, np.ones((2, 2), bool))
    eng = Engine(wd_cfg(batch_size=2, query_pool_size=2), pool=pool)
    st = eng.run(1)
    assert int(st.txn.cursor[0]) == 1
    assert int(st.txn.status[1]) == STATUS_BACKOFF


def test_ts_kept_across_restart():
    # WAIT_DIE assigns its timestamp once at first start
    # (worker_thread.cpp:478-480): after an abort+restart the ts must not change.
    keys = np.array([[5, 1], [5, 2]], np.int32)
    pool = make_pool(keys, np.ones((2, 2), bool))
    eng = Engine(wd_cfg(batch_size=2, query_pool_size=2, abort_penalty_ticks=1),
                 pool=pool)
    st = eng.run(1)
    ts_before = int(st.txn.ts[1])
    st = eng.run(3, st)  # backoff expires, restarts
    assert int(st.txn.ts[1]) == ts_before


def test_no_deadlock_and_oracle_under_contention():
    cfg = Config(batch_size=64, synth_table_size=256, req_per_query=4,
                 query_pool_size=512, zipf_theta=0.9, tup_read_perc=0.5,
                 cc_alg="WAIT_DIE", warmup_ticks=0)
    eng = Engine(cfg)
    st = eng.run(60)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert s["twopl_wait_cnt"] > 0      # waits must actually happen
    assert np.asarray(st.data).sum() == s["write_cnt"]
