"""Observability subsystem tests (deneva_tpu/obs): [prog] round-trip,
trace-vs-summary reconciliation, Chrome-trace schema, profiler phases,
run records, and the disabled path's bit-identical summaries."""

import pytest
import json

import numpy as np

from deneva_tpu import stats as stats_mod
from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.obs import profiler as obs_profiler
from deneva_tpu.obs import trace as obs_trace
from deneva_tpu.obs.prog import ProgressEmitter

BASE = dict(cc_alg="NO_WAIT", batch_size=128, synth_table_size=1 << 10,
            req_per_query=4, zipf_theta=0.8, query_pool_size=1 << 10)


def run(n_ticks=30, **kw):
    eng = Engine(Config(**{**BASE, **kw}))
    return eng, eng.run(n_ticks)


# ---- [prog] ---------------------------------------------------------------

def test_prog_lines_round_trip():
    eng = Engine(Config(**BASE, prog_interval=10))
    sink = []
    prog = ProgressEmitter(eng, eng.cfg.prog_interval, out=sink.append)
    state = None
    for i in range(30):
        state = eng._tick_jit(state if state is not None
                              else eng.init_state())
        prog.maybe_emit(state, i + 1)
    assert len(sink) == 3 and sink == prog.lines
    final = stats_mod.parse_summary(eng.summary_line(state))
    for line in sink:
        assert line.startswith("[prog] ")
        parsed = stats_mod.parse_summary(line)
        assert set(parsed) == set(final)
    # cumulative counters are monotone across heartbeats
    cnts = [stats_mod.parse_summary(ln)["txn_cnt"] for ln in sink]
    assert cnts == sorted(cnts)
    assert cnts[-1] <= final["txn_cnt"]


def test_run_emits_prog_from_config(capsys):
    eng = Engine(Config(**BASE, prog_interval=10))
    eng.run(20)
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("[prog] ")]
    assert len(lines) == 2
    assert stats_mod.parse_summary(lines[-1])["txn_cnt"] >= 0


# ---- trace reconciliation -------------------------------------------------

def test_trace_totals_reconcile_with_summary():
    eng, st = run(trace_ticks=64)
    s = eng.summary(st)
    tot = obs_trace.totals(st)
    assert tot["commit"] == s["txn_cnt"]
    assert tot["abort"] == s["total_txn_abort_cnt"]
    assert tot["admit"] == s["local_txn_start_cnt"]
    assert tot["vabort"] == s["vabort_cnt"]
    assert tot["user_abort"] == s["user_abort_cnt"]
    assert tot["lock_wait"] == s["twopl_wait_cnt"]
    # occupancy columns integrate to the latency decomposition
    assert tot["occ_running"] == s["lat_process_time"]
    assert tot["occ_waiting"] == s["lat_cc_block_time"]
    assert tot["occ_backoff"] == s["lat_abort_time"]


def test_trace_reconciles_commit_after_access():
    # the other commit ordering splits abort bumps into abort_now + vabort;
    # the abort column must still integrate to total_txn_abort_cnt
    eng, st = run(cc_alg="OCC", commit_after_access=True, trace_ticks=64)
    s = eng.summary(st)
    tot = obs_trace.totals(st)
    assert tot["commit"] == s["txn_cnt"]
    assert tot["abort"] == s["total_txn_abort_cnt"]
    assert tot["vabort"] == s["vabort_cnt"]


def test_timeline_series_shapes():
    eng, st = run(trace_ticks=64)
    tl = obs_trace.timeline(st)
    assert set(tl) == set(obs_trace.TRACE_COLUMNS)
    assert all(v.shape == (64,) for v in tl.values())
    occ = sum(tl[c] for c in ("occ_free", "occ_running", "occ_waiting",
                              "occ_backoff"))
    ticks = int(np.asarray(st.tick))
    assert (occ[:ticks] == eng.cfg.batch_size).all()


# ---- Chrome trace export --------------------------------------------------

def test_chrome_trace_schema(tmp_path):
    eng, st = run(trace_ticks=64)
    path = obs_trace.to_chrome_trace(st, str(tmp_path / "trace.json"),
                                     n_ticks=30)
    with open(path) as f:
        doc = json.load(f)
    assert isinstance(doc["traceEvents"], list)
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    metas = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
    assert len(metas) == 1 and metas[0]["name"] == "process_name"
    assert len(counters) == 3 * 30      # flow + occupancy + compaction per tick
    for e in counters:
        assert {"name", "ph", "ts", "pid", "args"} <= set(e)
        assert e["name"] in ("txn flow", "slot occupancy", "compaction")
        assert all(isinstance(v, int) for v in e["args"].values())
    # flow counter events integrate to the same totals as the buffer
    commits = sum(e["args"]["commit"] for e in counters
                  if e["name"] == "txn flow")
    assert commits == eng.summary(st)["txn_cnt"]


# ---- disabled path --------------------------------------------------------
# (The trace_ticks=0 bit-identity cell that used to live here is now
# proven statically by the tick certifier's OFFPATH-IMPURE rule —
# trace_ticks is a registered opt-in flag, so every plugin x workload
# cell checks that the off-trace jaxpr is alpha-equivalent to baseline;
# see deneva_tpu/lint/certify.py and LINT.md engine 3.  The runtime
# off-path sentinel for engine 1 lives in test_flight.py.)


# ---- profiler + run record ------------------------------------------------

def test_profiler_phases_and_recompile_count():
    eng, st = run(profile=True)
    snap = eng.profiler.snapshot()
    assert snap["counters"]["jit_recompiles"] == 1     # one tick compile
    assert snap["phases"]["trace_lower_compile"]["count"] == 1
    assert snap["phases"]["execute"]["count"] == 30
    assert snap["phases"]["dispatch"]["count"] == 29   # post-compile ticks
    assert all(p["seconds"] >= 0 for p in snap["phases"].values())


def test_run_record_written(tmp_path):
    eng, st = run(trace_ticks=64, profile=True)
    summary = eng.summary(st)
    rec = obs_profiler.run_record(
        eng.cfg, summary, phases=eng.profiler.snapshot(),
        timeline=obs_trace.timeline(st))
    path = obs_profiler.write_run_record(rec, out_dir=str(tmp_path))
    with open(path) as f:
        loaded = json.load(f)
    assert loaded["schema"] == obs_profiler.RECORD_SCHEMA
    assert loaded["config_fingerprint"] == \
        obs_profiler.config_fingerprint(eng.cfg)
    assert loaded["summary"]["txn_cnt"] == summary["txn_cnt"]
    assert loaded["config"]["trace_ticks"] == 64
    assert sum(loaded["timeline"]["commit"]) == summary["txn_cnt"]
    assert loaded["profile"]["counters"]["jit_recompiles"] >= 1


def test_fingerprint_tracks_config_not_run():
    a = Config(**BASE)
    b = Config(**BASE)
    c = Config(**{**BASE, "zipf_theta": 0.99})
    assert obs_profiler.config_fingerprint(a) == \
        obs_profiler.config_fingerprint(b)
    assert obs_profiler.config_fingerprint(a) != \
        obs_profiler.config_fingerprint(c)


def test_run_compiled_profiled():
    eng = Engine(Config(**BASE, profile=True))
    st = eng.run_compiled(10)
    st = eng.run_compiled(10, st)        # second call: cached scan
    snap = eng.profiler.snapshot()
    assert snap["phases"]["trace_lower_compile"]["count"] == 1
    assert snap["phases"]["dispatch"]["count"] == 1
    assert int(np.asarray(st.stats["measured_ticks"])) == 20


# ---- abort attribution / contention observatory ---------------------------

from deneva_tpu import cc as cc_registry                    # noqa: E402
from deneva_tpu.cc.base import ABORT_REASONS                # noqa: E402
from deneva_tpu.obs import report as obs_report             # noqa: E402

ALGS = ("NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT", "CALVIN")

#: small attributed YCSB cell on the acceptance contention point (zipf 0.6)
ATTR = dict(batch_size=64, synth_table_size=256, req_per_query=4,
            zipf_theta=0.6, query_pool_size=512, warmup_ticks=0,
            abort_attribution=True, heatmap_bins=64)


def _reason_sum(s):
    return sum(s[f"abort_{n}_cnt"] for n in ABORT_REASONS)


# the MAAT cell compiles the chain-validate and alone costs ~14 s —
# `-m slow` per the tier-1 870 s budget split
@pytest.mark.parametrize("alg", [
    pytest.param(a, marks=pytest.mark.slow) if a == "MAAT" else a
    for a in ALGS])
def test_taxonomy_exact_and_exhaustive(alg):
    # per-reason counters must sum EXACTLY to the aggregate abort counters
    # (vaborts count at both their own site and the total site — the
    # identity is total + vabort + user), and every nonzero reason must be
    # one the plugin declared it can emit under this config
    cfg = Config(cc_alg=alg, **ATTR)
    eng = Engine(cfg)
    st = eng.run(40)
    s = eng.summary(st)
    assert _reason_sum(s) == (s["total_txn_abort_cnt"] + s["vabort_cnt"]
                              + s["user_abort_cnt"])
    emitted = {n for n in ABORT_REASONS if s[f"abort_{n}_cnt"] > 0}
    assert emitted <= cc_registry.get(alg).emitted_reasons(cfg)
    assert s["abort_other_cnt"] == 0      # every abort carries a real code


def test_taxonomy_tpcc_user_aborts():
    cfg = Config(workload="TPCC", cc_alg="NO_WAIT", batch_size=64,
                 num_wh=4, cust_per_dist=1000, max_items=128,
                 query_pool_size=256, warmup_ticks=0, tpcc_rbk_perc=0.5,
                 abort_attribution=True)
    eng = Engine(cfg)
    st = eng.run(120)
    s = eng.summary(st)
    assert s["user_abort_cnt"] > 0        # rbk 50% must fire
    assert s["abort_user_abort_cnt"] == s["user_abort_cnt"]
    assert _reason_sum(s) == (s["total_txn_abort_cnt"] + s["vabort_cnt"]
                              + s["user_abort_cnt"])


def test_taxonomy_commit_after_access_ordering():
    cfg = Config(cc_alg="OCC", commit_after_access=True, **ATTR)
    eng = Engine(cfg)
    st = eng.run(40)
    s = eng.summary(st)
    assert _reason_sum(s) == (s["total_txn_abort_cnt"] + s["vabort_cnt"]
                              + s["user_abort_cnt"])
    # each vabort is tagged at BOTH its own bump site and the total site
    # (the identity's double count), so the reason counter reads 2x
    assert s["abort_occ_validation_cnt"] == 2 * s["vabort_cnt"]


def test_heatmap_invariant_and_hot_keys():
    cfg = Config(cc_alg="NO_WAIT", **{**ATTR, "zipf_theta": 0.9})
    eng = Engine(cfg)
    st = eng.run(40)
    s = eng.summary(st)
    # every conflict event (parked continuation or CC access denial)
    # lands exactly one histogram increment; vaborts are not key-local
    hist = np.asarray(st.stats["arr_conflict_hist"])
    assert hist.sum() == (s["twopl_wait_cnt"] + s["total_txn_abort_cnt"]
                          - s["vabort_cnt"])
    hk = obs_report.hot_keys(st.stats, topk=cfg.heatmap_topk)
    assert len(hk) <= cfg.heatmap_topk
    hits = [h["hits"] for h in hk]
    assert hits == sorted(hits, reverse=True)
    assert all(h["hits"] > 0 for h in hk)
    # wait-depth histogram counts ended wait streaks (NO_WAIT never
    # waits, so drive one through WAIT_DIE instead)
    cfg2 = Config(cc_alg="WAIT_DIE", **{**ATTR, "zipf_theta": 0.9})
    eng2 = Engine(cfg2)
    st2 = eng2.run(40)
    wd = np.asarray(st2.stats["arr_wait_depth_hist"])
    assert wd.shape == (16,) and (wd >= 0).all() and wd.sum() > 0


def test_summary_line_round_trips_with_abort_keys():
    # satellite contract: reference_summary passes unknown abort_*
    # counters through verbatim and parse_summary round-trips them
    eng, st = run(**{k: v for k, v in ATTR.items()
                     if k not in ("batch_size", "synth_table_size",
                                  "query_pool_size")})
    s = eng.summary(st)
    line = eng.summary_line(st)
    parsed = stats_mod.parse_summary(line)
    for n in ABORT_REASONS:
        assert parsed[f"abort_{n}_cnt"] == float(s[f"abort_{n}_cnt"])


def test_chrome_trace_reason_track(tmp_path):
    eng, st = run(trace_ticks=30, abort_attribution=True)
    path = obs_trace.to_chrome_trace(st, str(tmp_path / "t.json"),
                                     n_ticks=30)
    with open(path) as f:
        doc = json.load(f)
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    assert len(counters) == 4 * 30        # + the abort-reasons track
    rtrack = [e for e in counters if e["name"] == "abort reasons"]
    assert len(rtrack) == 30
    assert doc["metadata"]["reason_columns"] == \
        [f"abort_{n}" for n in ABORT_REASONS]
    s = eng.summary(st)
    for n in ABORT_REASONS:
        got = sum(e["args"][f"abort_{n}"] for e in rtrack)
        assert got == s[f"abort_{n}_cnt"]


def test_attribution_off_carries_nothing():
    eng, st = run()
    s = eng.summary(st)
    assert not any(k.startswith("abort_") and k.endswith("_cnt")
                   for k in s)
    for k in ("arr_last_abort_reason", "arr_conflict_hist",
              "arr_wait_streak"):
        assert k not in st.stats


def test_waterfall_report_and_watchdog_clean():
    eng, st = run(trace_ticks=64, abort_attribution=True, heatmap_bins=64)
    s = eng.summary(st)
    rep = obs_report.build_report(s, timeline=obs_trace.timeline(st),
                                  stats=st.stats)
    assert rep["reconcile_failures"] == []
    assert rep["watchdog"]["exit_code"] == 0
    assert rep["commits"] == s["txn_cnt"]
    assert sum(rep["abort_reasons"].values()) == _reason_sum(s)
    # phase rows reconcile with the [summary] latency decomposition
    assert rep["phases"]["process"] == s["lat_process_time"]
    assert rep["phases"]["cc_block"] == s["lat_cc_block_time"]
    assert rep["phases"]["abort_backoff"] == s["lat_abort_time"]
    text = obs_report.render_text(rep)
    assert "[waterfall]" in text and "[watchdog] clean" in text


def test_watchdog_flags():
    # live-lock: zero commits against churn
    live = {"txn_cnt": 0, "total_txn_abort_cnt": 50}
    _, code = obs_report.watchdog(live)
    assert code & obs_report.LIVELOCK
    # starved shard: one shard idle on the per-shard commit series
    tl = {"commit": np.array([[5] * 32, [0] * 32]),
          "abort": np.zeros((2, 32), int),
          "admit": np.zeros((2, 32), int)}
    _, code = obs_report.watchdog({"txn_cnt": 160}, tl)
    assert code & obs_report.STARVED
    # spill storm from the taxonomy counter
    _, code = obs_report.watchdog(
        {"txn_cnt": 10, "total_txn_abort_cnt": 10,
         "abort_compact_spill_cnt": 10})
    assert code & obs_report.SPILL
    # reconciliation breach
    bad = {"txn_cnt": 1, "total_txn_abort_cnt": 3, "vabort_cnt": 0,
           "user_abort_cnt": 0,
           **{f"abort_{n}_cnt": 0 for n in ABORT_REASONS}}
    _, code = obs_report.watchdog(bad)
    assert code & obs_report.RECONCILE


# ---- sharded --------------------------------------------------------------

@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_sharded_trace_per_shard_commits():
    import pytest
    try:
        from deneva_tpu.parallel.sharded import ShardedEngine
    except ImportError as e:         # pragma: no cover - jax api drift
        pytest.skip(f"sharded engine unavailable: {e}")
    cfg = Config(cc_alg="NO_WAIT", node_cnt=2, part_cnt=2, batch_size=32,
                 synth_table_size=1 << 10, req_per_query=4, zipf_theta=0.6,
                 query_pool_size=512, trace_ticks=32, profile=True)
    eng = ShardedEngine(cfg)
    st = eng.run(20)
    s = eng.summary(st)
    tot = obs_trace.totals(st)
    assert tot["commit"] == s["txn_cnt"]
    assert tot["abort"] == s["total_txn_abort_cnt"]
    per_shard = obs_trace.timeline(st, per_shard=True)["commit"]
    assert per_shard.shape == (2, 32)
    snap = eng.profiler.snapshot()
    assert snap["counters"]["jit_recompiles"] >= 1
    assert snap["phases"]["execute"]["count"] == 20


@pytest.mark.slow  # multi-device shard_map cell, over the tier-1 time budget
def test_sharded_reason_counters_bitexact_and_reconcile():
    try:
        from deneva_tpu.parallel.sharded import ShardedEngine
    except ImportError as e:         # pragma: no cover - jax api drift
        pytest.skip(f"sharded engine unavailable: {e}")
    cfg = Config(cc_alg="NO_WAIT", node_cnt=2, part_cnt=2, batch_size=32,
                 synth_table_size=1 << 10, req_per_query=4, zipf_theta=0.8,
                 query_pool_size=512, warmup_ticks=0,
                 abort_attribution=True, heatmap_bins=64, trace_ticks=32)
    eng = ShardedEngine(cfg)
    st = eng.run(25)
    s = eng.summary(st)
    # cluster counters (device psum) == host sum of per-shard counters,
    # bit-exact, for every taxonomy counter and the aggregates
    for n in ABORT_REASONS:
        k = f"abort_{n}_cnt"
        assert s[k] == int(np.asarray(st.stats[k]).sum())
        assert isinstance(s[k], int)
    for k in ("total_txn_abort_cnt", "vabort_cnt", "user_abort_cnt",
              "txn_cnt"):
        assert s[k] == int(np.asarray(st.stats[k]).sum())
    assert _reason_sum(s) == (s["total_txn_abort_cnt"] + s["vabort_cnt"]
                              + s["user_abort_cnt"])
    # per-reason trace series stack per shard and integrate to the counters
    tl = obs_trace.timeline(st)
    rep = obs_report.build_report(s, timeline=tl, stats=st.stats)
    assert rep["reconcile_failures"] == []
    assert rep["watchdog"]["exit_code"] == 0
