"""Differential run comparator tests (obs/diff.py): cause ranking on
synthetic records, the lever map, window-vs-window segmentation, the
regress-gate triage, and the same-platform gate filter."""

import numpy as np
import pytest

from deneva_tpu.obs import diff as obs_diff
from deneva_tpu.obs import regress as obs_regress

BASE_SUMMARY = dict(
    txn_cnt=1000, total_txn_abort_cnt=200, measured_ticks=100,
    lat_process_time=3000.0, lat_cc_block_time=1000.0,
    lat_abort_time=500.0, lat_network_time=200.0,
    txn_total_time_ticks=8000.0, remote_entry_cnt=0, imb_jain=0.99,
    abort_nowait_conflict_cnt=150, abort_compact_spill_cnt=50)


def test_remote_amplification_ranks_top():
    # the PR 9 scenario in miniature: run B commits less while shipping
    # ~8x remote entries per access; a near-constant imbalance and mild
    # abort growth must NOT outrank it.  The extractor is bench.py's
    # scaling-grid formula (remote_entry_cnt / (txn_cnt * req_per_query))
    b = dict(BASE_SUMMARY, txn_cnt=400, remote_entry_cnt=400 * 16 * 8,
             lat_network_time=9000.0, imb_jain=0.98,
             txn_total_time_ticks=20000.0)
    cfg = {"req_per_query": 16}
    d = obs_diff.diff_summaries(BASE_SUMMARY, b, cfg, cfg)
    assert d["top_cause"] == "remote_amplification"
    assert d["top_lever"] == "remote_cache"
    amp = next(c for c in d["causes"]
               if c["cause"] == "remote_amplification")
    assert amp["b"] == pytest.approx(8.0)
    assert amp["regressing"]
    imb = next(c for c in d["causes"] if c["cause"] == "imbalance")
    assert imb["score"] < 0.1 < amp["score"]


def test_escalation_serialization_ranks_top():
    # the PR 13 hot-cell scenario: the controller escalates the
    # saturated hot set and serializes the batch — gate stalls and
    # escalations per commit explode while remote traffic is absent
    b = dict(BASE_SUMMARY, txn_cnt=300,
             ctrl_escalate_cnt=280, ctrl_esc_block_cnt=250,
             lat_cc_block_time=4000.0)
    a = dict(BASE_SUMMARY, ctrl_escalate_cnt=5, ctrl_esc_block_cnt=2)
    d = obs_diff.diff_summaries(a, b)
    assert d["top_cause"] in ("ctrl_escalations_per_commit",
                              "ctrl_gate_stalls_per_commit")
    assert d["top_lever"] == "adaptive"


def test_abort_mix_maps_reason_families_to_levers():
    b = dict(BASE_SUMMARY, abort_compact_spill_cnt=600,
             abort_route_overflow_cnt=300, total_txn_abort_cnt=1100)
    d = obs_diff.diff_summaries(BASE_SUMMARY, b)
    by = {c["cause"]: c for c in d["causes"]}
    assert by["abort_mix[compact_spill]"]["lever"] == "compact_auto"
    assert by["abort_mix[route_overflow]"]["lever"] == "exchange_split"
    assert by["abort_mix[nowait_conflict]"]["lever"] == "adaptive"


def test_absent_planes_ride_as_zero_not_crash():
    # a cause joins only when either side carries its probe key; a
    # summary pair without controller/SLO/mesh planes must diff cleanly
    a = {"txn_cnt": 10, "measured_ticks": 5, "total_txn_abort_cnt": 0}
    d = obs_diff.diff_summaries(a, dict(a, txn_cnt=20))
    names = {c["cause"] for c in d["causes"]}
    assert "ctrl_escalations_per_commit" not in names
    assert "burn_fast" not in names


def test_window_segmentation_is_exact_and_refuses_wrap():
    cols_i = ["tick", "txn_cnt", "total_txn_abort_cnt", "measured_ticks"]
    ring = [[4, 10, 2, 4], [8, 15, 8, 8], [12, 40, 9, 12]]
    rec = {"config": {}, "summary": {},
           "windows": {"cols_i": cols_i, "cols_f": ["lat_abort_time"],
                       "ring_i": ring, "ring_f": [[1.0], [4.0], [6.0]],
                       "cnt": 3, "slots": 8, "window_ticks": 4,
                       "nodes": 1, "wrapped": False}}
    sa, sb, split = obs_diff.segment_summaries(rec, split_tick=8)
    assert (sa["txn_cnt"], sb["txn_cnt"]) == (15, 25)
    assert (sa["measured_ticks"], sb["measured_ticks"]) == (8, 4)
    assert sa["lat_abort_time"] + sb["lat_abort_time"] == 6.0
    d = obs_diff.diff_windows(rec, split_tick=8)
    assert d["kind"] == "window_diff" and d["split_tick"] == 8
    rec["windows"]["wrapped"] = True
    rec["windows"]["cnt"] = 99
    with pytest.raises(ValueError, match="wrapped"):
        obs_diff.segment_summaries(rec)


def _entry(i, amp, eff, platform=None, value=10.0):
    doc = {"metric": "scaling_grid", "value": value,
           "scaling_grid": {"MAAT@8x256": {"efficiency": eff,
                                           "amplification": amp}}}
    if platform:
        doc["platform"] = platform
    return obs_regress._entry(f"p{i}", (1, i), doc)


def test_failing_gate_attaches_ranked_diagnosis():
    # an amplification blow-up fails the inverted gate AND arrives
    # pre-triaged: the diagnosis names the cell and the remote_cache
    # lever without any human reading counters
    hist = [_entry(i, 1.0, 0.9) for i in range(3)]
    res = obs_regress.gate(hist + [_entry(9, 8.44, 0.24)])
    assert res["failures"]
    diag = res["diagnosis"]
    assert diag["top_cause"] == "amplification[MAAT@8x256]"
    assert diag["top_lever"] == "remote_cache"
    text = obs_regress.render_text(res)
    assert "[diagnosis]" in text
    # a clean gate attaches nothing
    ok = obs_regress.gate(hist + [_entry(9, 1.0, 0.9)])
    assert not ok["failures"] and "diagnosis" not in ok


def test_gate_is_platform_scoped():
    # satellite 1: a cpu point must gate only against cpu (and legacy
    # untagged) priors — tpu history with far higher cells must neither
    # fail it nor lower its median
    tpu = [_entry(i, 1.0, 0.9, platform="tpu", value=100.0)
           for i in range(4)]
    cur = _entry(9, 1.0, 0.2, platform="cpu", value=5.0)
    res = obs_regress.gate(tpu + [cur])
    assert res["failures"] == []
    assert all("no prior data" in s for s in res["skipped"])
    # same-platform priors DO gate it
    cpu = [_entry(i, 1.0, 0.9, platform="cpu") for i in range(3)]
    res2 = obs_regress.gate(cpu + [cur])
    assert any("scaling_grid_efficiency" in f for f in res2["failures"])
    # legacy untagged priors keep gating a tagged current
    legacy = [_entry(i, 1.0, 0.9) for i in range(3)]
    res3 = obs_regress.gate(legacy + [cur])
    assert any("scaling_grid_efficiency" in f for f in res3["failures"])


def test_render_diagnosis_names_verdict_and_lever():
    a = dict(BASE_SUMMARY)
    b = dict(BASE_SUMMARY, remote_entry_cnt=32000, txn_cnt=500)
    d = obs_diff.diff_summaries(a, b, {"req_per_query": 4},
                                {"req_per_query": 4})
    text = obs_diff.render_diagnosis(d)
    assert text.startswith("[diagnosis]")
    assert "verdict: remote_amplification" in text
    assert "Config.remote_cache" in text
