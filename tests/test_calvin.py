"""CALVIN: golden epoch schedules, zero-abort invariant, determinism,
and multi-shard conservation (reference: system/sequencer.cpp,
system/calvin_thread.cpp, row_lock.cpp:78-81,152-170)."""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.engine.state import (STATUS_RUNNING, STATUS_WAITING)
from deneva_tpu.workloads.base import QueryPool


def make_pool(keys, is_write, n_req=None):
    keys = np.asarray(keys, np.int32)
    is_write = np.asarray(is_write, bool)
    Q, R = keys.shape
    if n_req is None:
        n_req = np.full(Q, R, np.int32)
    return QueryPool(
        keys=keys, is_write=is_write,
        n_req=np.asarray(n_req, np.int32),
        home_part=np.zeros(Q, np.int32),
        txn_type=np.zeros(Q, np.int32),
        args=np.zeros((Q, 1), np.int32),
    )


def calvin_cfg(**kw):
    base = dict(batch_size=4, synth_table_size=64, req_per_query=2,
                query_pool_size=4, backoff=False, warmup_ticks=0,
                cc_alg="CALVIN")
    base.update(kw)
    return Config(**base)


def test_golden_conflict_chain_schedule():
    # Conflict chain T0 -w1- T1 -w2- T2, T3 independent; all writes.
    # Sequence numbers = admission order (T0 < T1 < T2 < T3).  FIFO grant;
    # a committing txn releases its locks before the same tick's
    # arbitration (calvin_wrapup then waiter promotion):
    #   tick 0: T0 grants both (head of rows 0,1); T1 blocked on row 1;
    #           T2 blocked on row 2 (T1's earlier entry); T3 grants both.
    #   tick 1: T0, T3 commit; T1 grants both; T2 still behind T1.
    #   tick 2: T1 commits; T2 grants both.
    #   tick 3: T2 commits.
    keys = np.array([[0, 1], [1, 2], [2, 3], [4, 5]], np.int32)
    extra = np.arange(10, 26, dtype=np.int32).reshape(8, 2)  # wrap filler,
    keys = np.vstack([keys, extra])                          # no conflicts
    pool = make_pool(keys, np.ones_like(keys, bool))
    eng = Engine(calvin_cfg(query_pool_size=12), pool=pool)

    st = eng.run(1)
    assert st.txn.cursor.tolist() == [2, 0, 0, 2]
    assert int(st.txn.status[1]) == STATUS_WAITING
    assert int(st.txn.status[2]) == STATUS_WAITING

    st = eng.run(1, st)   # tick 1
    s = eng.summary(st)
    assert s["txn_cnt"] == 2            # T0, T3
    assert int(st.txn.cursor[1]) == 2   # T1 promoted after T0's release
    assert int(st.txn.status[2]) == STATUS_WAITING

    st = eng.run(1, st)   # tick 2: T1 commits, T2 grants
    s = eng.summary(st)
    assert s["txn_cnt"] == 3
    assert int(st.txn.cursor[2]) == 2

    st = eng.run(1, st)   # tick 3: T2 + the 2 fillers admitted at tick 2
    s = eng.summary(st)
    assert s["txn_cnt"] == 6
    assert s["total_txn_abort_cnt"] == 0
    # chain fully committed: shared rows 1,2 incremented by both writers
    assert np.asarray(st.data)[:6].tolist() == [1, 2, 2, 1, 1, 1]


def test_write_write_fifo_order():
    # Two writers on the same row: the smaller sequence number wins the
    # first grant; the loser WAITS (never aborts) and commits right after.
    keys = np.array([[7, 1], [7, 2], [20, 21], [22, 23]], np.int32)
    pool = make_pool(keys, np.ones_like(keys, bool))
    eng = Engine(calvin_cfg(), pool=pool)
    st = eng.run(1)
    assert int(st.txn.cursor[0]) == 2
    assert int(st.txn.status[1]) == STATUS_WAITING
    assert int(st.txn.restarts[1]) == 0          # waiting, not aborted
    st = eng.run(5, st)
    s = eng.summary(st)
    assert s["total_txn_abort_cnt"] == 0
    assert np.asarray(st.data).sum() == s["write_cnt"]


def test_read_shares_write_blocks():
    # T0 reads row 5, T1 reads row 5 (both grant: no write precedes),
    # T2 writes row 5 (blocked: two earlier read entries).
    keys = np.array([[5, 1], [5, 2], [5, 3], [8, 9]], np.int32)
    iw = np.array([[False, False], [False, False], [True, True],
                   [False, False]])
    pool = make_pool(keys, iw)
    eng = Engine(calvin_cfg(), pool=pool)
    st = eng.run(1)
    assert int(st.txn.cursor[0]) == 2
    assert int(st.txn.cursor[1]) == 2
    assert int(st.txn.status[2]) == STATUS_WAITING


def test_zero_abort_under_extreme_contention():
    # zipf 0.99 on a tiny table: every other algorithm aborts heavily;
    # Calvin must never abort (row_lock.cpp:78-81) and still make progress.
    cfg = Config(cc_alg="CALVIN", batch_size=64, synth_table_size=256,
                 req_per_query=4, query_pool_size=512, zipf_theta=0.99,
                 tup_read_perc=0.5, warmup_ticks=0)
    eng = Engine(cfg)
    st = eng.run(40)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert s["total_txn_abort_cnt"] == 0
    assert s["unique_txn_abort_cnt"] == 0
    assert np.asarray(st.data).sum() == s["write_cnt"]


def test_deterministic_schedule():
    # Same pool => bit-identical commit schedule and data state.
    cfg = Config(cc_alg="CALVIN", batch_size=32, synth_table_size=128,
                 req_per_query=3, query_pool_size=128, zipf_theta=0.9,
                 warmup_ticks=0)
    runs = []
    for _ in range(2):
        eng = Engine(cfg)
        st = eng.run(25)
        runs.append((eng.summary(st), np.asarray(st.data)))
    assert runs[0][0] == runs[1][0]
    assert (runs[0][1] == runs[1][1]).all()


def test_epoch_size_gates_admission():
    # epoch_size=2: only 2 txns admitted per tick even though 4 slots free.
    keys = np.arange(16, dtype=np.int32).reshape(8, 2)
    pool = make_pool(keys, np.ones_like(keys, bool))
    eng = Engine(calvin_cfg(query_pool_size=8, seq_batch_size=2), pool=pool)
    st = eng.run(1)
    s = eng.summary(st)
    assert s["local_txn_start_cnt"] == 2
    st = eng.run(1, st)
    assert eng.summary(st)["local_txn_start_cnt"] == 4


def test_matches_sequential_outcome():
    # All txns commit exactly once per pool pass (no aborts, no loss):
    # total commits across a bounded run == admissions that finished.
    cfg = Config(cc_alg="CALVIN", batch_size=16, synth_table_size=64,
                 req_per_query=2, query_pool_size=64, zipf_theta=0.8,
                 warmup_ticks=0)
    eng = Engine(cfg)
    st = eng.run(60)
    s = eng.summary(st)
    assert s["total_txn_abort_cnt"] == 0
    assert np.asarray(st.data).sum() == s["write_cnt"]


# ---- multi-shard Calvin (sequencer id interleave + owner-side FIFO) ----

@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_sharded_calvin_conservation_zero_abort():
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(cc_alg="CALVIN", node_cnt=2, part_cnt=2, batch_size=32,
                 synth_table_size=1 << 12, req_per_query=4,
                 query_pool_size=1 << 10, zipf_theta=0.6, tup_read_perc=0.5,
                 warmup_ticks=0, mpr=1.0, part_per_txn=2)
    eng = ShardedEngine(cfg)
    st = eng.run(30)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert s["total_txn_abort_cnt"] == 0
    assert s["remote_entry_cnt"] > 0
    assert eng.global_data_sum(st) == s["write_cnt"]


@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_sharded_calvin_four_nodes_contended():
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(cc_alg="CALVIN", node_cnt=4, part_cnt=4, batch_size=16,
                 synth_table_size=1 << 10, req_per_query=4,
                 query_pool_size=1 << 9, zipf_theta=0.9, tup_read_perc=0.5,
                 warmup_ticks=0, mpr=1.0, part_per_txn=4)
    eng = ShardedEngine(cfg)
    st = eng.run(30)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert s["total_txn_abort_cnt"] == 0
    assert eng.global_data_sum(st) == s["write_cnt"]


@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_sharded_calvin_no_entry_loss():
    # Calvin forces the exchange to worst-case capacity: no entry may ever
    # be dropped (a hidden held lock would break the FIFO schedule), even
    # when the user config asks for a starved exchange.
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(cc_alg="CALVIN", node_cnt=2, part_cnt=2, batch_size=32,
                 synth_table_size=1 << 12, req_per_query=4,
                 query_pool_size=1 << 9, zipf_theta=0.0, warmup_ticks=0,
                 mpr=1.0, part_per_txn=2, route_capacity_factor=0.05)
    eng = ShardedEngine(cfg)
    assert eng.cap == cfg.batch_size * eng.pool.max_req
    st = eng.run(30)
    s = eng.summary(st)
    assert s["total_txn_abort_cnt"] == 0
    assert s["route_overflow_abort_cnt"] == 0
    assert s["commit_defer_cnt"] == 0        # capacity makes overflow impossible
    assert s["txn_cnt"] > 0
    assert eng.global_data_sum(st) == s["write_cnt"]
