"""Sorted-segment primitives vs straightforward numpy references."""

import numpy as np
import jax.numpy as jnp

from deneva_tpu.ops import segment as seg


def _np_starts(ids):
    return np.array([i == 0 or ids[i] != ids[i - 1] for i in range(len(ids))])


def test_segment_starts_and_pos():
    ids = jnp.array([3, 3, 5, 5, 5, 9, 11, 11])
    starts = seg.segment_starts(ids)
    np.testing.assert_array_equal(np.asarray(starts), _np_starts(np.asarray(ids)))
    pos = seg.pos_in_segment(starts)
    np.testing.assert_array_equal(np.asarray(pos), [0, 1, 0, 1, 2, 0, 0, 1])


def test_seg_cumsum_exclusive_and_any_before():
    ids = jnp.array([1, 1, 1, 4, 4, 7])
    x = jnp.array([1, 0, 1, 1, 1, 1])
    starts = seg.segment_starts(ids)
    out = seg.seg_cumsum_exclusive(x, starts)
    np.testing.assert_array_equal(np.asarray(out), [0, 1, 1, 0, 1, 0])
    any_b = seg.seg_any_before(x.astype(bool), starts)
    np.testing.assert_array_equal(np.asarray(any_b), [0, 1, 1, 0, 1, 0])


def test_seg_reduce_and_min_where():
    ids = jnp.array([0, 0, 2, 2, 2, 6])
    vals = jnp.array([5, 3, 9, 1, 7, 4])
    starts = seg.segment_starts(ids)
    np.testing.assert_array_equal(
        np.asarray(seg.seg_reduce(vals, starts, "min")), [3, 3, 1, 1, 1, 4])
    np.testing.assert_array_equal(
        np.asarray(seg.seg_reduce(vals, starts, "sum")), [8, 8, 17, 17, 17, 4])
    where = jnp.array([True, False, False, True, True, False])
    out = seg.seg_min_where(vals, where, starts, 99)
    np.testing.assert_array_equal(np.asarray(out), [5, 5, 1, 1, 1, 99])


def test_sort_by_lexicographic():
    k1 = jnp.array([2, 1, 2, 1])
    k2 = jnp.array([9, 8, 3, 7])
    p = jnp.array([0, 1, 2, 3])
    (s1, s2), (sp,) = seg.sort_by((k1, k2), (p,))
    np.testing.assert_array_equal(np.asarray(s1), [1, 1, 2, 2])
    np.testing.assert_array_equal(np.asarray(s2), [7, 8, 3, 9])
    np.testing.assert_array_equal(np.asarray(sp), [3, 1, 2, 0])


def test_seg_suffix_min_max():
    import numpy as np
    import jax.numpy as jnp
    from deneva_tpu.ops import segment as seg
    ids = jnp.asarray(np.array([0, 0, 0, 1, 1, 2], np.int32))
    vals = jnp.asarray(np.array([5, 2, 9, 7, 1, 4], np.int32))
    starts = seg.segment_starts(ids)
    sm = seg.seg_suffix_min(vals, starts, 99)
    sx = seg.seg_suffix_max(vals, starts, 0)
    # strictly-after reductions within each id run
    assert sm.tolist() == [2, 9, 99, 1, 99, 99]
    assert sx.tolist() == [9, 9, 0, 1, 0, 0]
