"""Deterministic fault plane + recovery (Config.faults,
deneva_tpu/faults/): schedule validation, in-tick gating counters, the
kill-a-node replay-recovery bit-parity contract, and the satellite
CALVIN exchange-overflow guard."""

import numpy as np
import pytest

from deneva_tpu import faults as faults_mod
from deneva_tpu.config import Config
from deneva_tpu.faults import plan as fault_plan
from deneva_tpu.parallel.sharded import ShardedEngine


def shard_cfg(n=2, **kw):
    base = dict(node_cnt=n, part_cnt=n, batch_size=32,
                synth_table_size=1 << 12, req_per_query=4,
                query_pool_size=1 << 10, zipf_theta=0.6, tup_read_perc=0.5,
                warmup_ticks=0, mpr=1.0, part_per_txn=n)
    base.update(kw)
    return Config(**base)


# ---------------------------------------------------------------- plan


def test_availability_masks_pure():
    faults = (("straggle", 1, 3, 8), ("partition", 0, 2, 5, 10),
              ("kill", 3, 7))
    # outside every window: all clear
    dest, me = fault_plan.availability(faults, 0, 0, 4)
    assert np.asarray(dest).all() and bool(me)
    # inside the straggle window, every node withholds new work to node 1
    dest, me = fault_plan.availability(faults, 4, 0, 4)
    assert not np.asarray(dest)[1] and np.asarray(dest)[[0, 2, 3]].all()
    assert bool(me)                       # node 0 itself is fine
    # ... and node 1 itself freezes
    _, me = fault_plan.availability(faults, 4, 1, 4)
    assert not bool(me)
    # the partition cuts 0<->2 symmetrically, leaves 1 and 3 alone
    # (t=9: the straggle window [3, 8) has closed, only the cut is live)
    dest0, _ = fault_plan.availability(faults, 9, 0, 4)
    dest2, _ = fault_plan.availability(faults, 9, 2, 4)
    dest1, _ = fault_plan.availability(faults, 9, 1, 4)
    assert not np.asarray(dest0)[2] and not np.asarray(dest2)[0]
    assert np.asarray(dest1).all()
    # kills never gate in-tick work (the host driver owns them)
    dest, me = fault_plan.availability((("kill", 3, 7),), 7, 3, 4)
    assert np.asarray(dest).all() and bool(me)


def test_kill_events_and_window_span():
    faults = (("straggle", 0, 2, 9), ("kill", 1, 12), ("kill", 0, 4),
              ("partition", 0, 1, 3, 15))
    assert fault_plan.kill_events(faults) == [(4, 0), (12, 1)]
    assert fault_plan.window_span(faults) == 15
    assert fault_plan.window_span((("kill", 0, 4),)) == 0


def test_chaos_plan_deterministic_and_valid():
    a = fault_plan.chaos_plan(7, n_nodes=4, n_ticks=40, n_events=6)
    b = fault_plan.chaos_plan(7, n_nodes=4, n_ticks=40, n_events=6)
    assert a == b                          # replayable by construction
    assert a != fault_plan.chaos_plan(8, n_nodes=4, n_ticks=40, n_events=6)
    # every drawn schedule passes Config validation as-is
    cfg = shard_cfg(4, faults=a)
    assert cfg.faults == a
    for spec in a:
        assert spec[0] in fault_plan.KINDS


def test_config_validation_rejects_bad_specs():
    with pytest.raises(AssertionError):
        shard_cfg(2, faults=(("flood", 0, 3),))          # unknown kind
    with pytest.raises(AssertionError):
        shard_cfg(2, faults=(("straggle", 5, 3, 8),))    # node out of range
    with pytest.raises(AssertionError):
        shard_cfg(2, faults=(("straggle", 0, 8, 3),))    # empty window
    with pytest.raises(AssertionError):
        shard_cfg(2, faults=(("partition", 1, 1, 3, 8),))  # a == b
    with pytest.raises(AssertionError):
        Config(faults=(("kill", 0, 3),))                 # single node
    with pytest.raises(AssertionError):
        shard_cfg(2, faults=(("kill", 0, 3),), net_delay_ticks=2)


# ------------------------------------------------------- in-tick gating


def test_windows_gate_and_kill_recovers_bit_exact():
    """The acceptance experiment on ONE compiled schedule (straggle +
    partition windows and a mid-run kill share the 2-node CALVIN tick):

    - the windows freeze new admissions/requests and defer finishing
      txns — counters account for every gated lane, work is DELAYED
      never aborted (CALVIN still never aborts), the cluster keeps
      committing, and the CALVIN epoch log records the admissions;
    - the killed node recovers by deterministic epoch-log replay, the
      replayed slice (epoch log included) validates bit-for-bit, and
      the recovered run's [summary] matches the fault-free oracle on
      every integer counter."""
    cfg = shard_cfg(2, cc_alg="CALVIN", fault_elog_cap=64,
                    faults=(("straggle", 1, 3, 8),
                            ("partition", 0, 1, 9, 13),
                            ("kill", 1, 6)))
    eng = ShardedEngine(cfg)
    state, counters = faults_mod.run_with_faults(eng, 18)
    # --- kill recovery: replay crossed the live straggle window too
    assert counters["fault_kill_cnt"] == 1
    assert counters["recovery_replay_ok"] == 1    # slice bit-parity
    assert counters["recovery_elog_ok"] == 1      # epoch-log bit-parity
    assert counters["recovery_lag_ticks"] == 6    # replayed the prefix
    # --- oracle: the same jitted tick without the host-side kill (a
    # kill spec has no in-tick effect, so eng's compiled tick is shared)
    o = eng.init_state()
    for _ in range(18):
        o = eng._jit_tick(o)
    s_f, s_o = eng.summary(state), eng.summary(o)
    assert s_f["txn_cnt"] > 0
    for k, v in s_o.items():
        if isinstance(v, (int, np.integer)):
            assert int(s_f[k]) == int(v), k
    # --- window gating: delay, never abort
    assert s_f["total_txn_abort_cnt"] == 0
    # only the straggle window stalls a node's OWN work — the partition
    # window gates cross-pair requests without freezing either node
    assert s_f["fault_stall_ticks"] == 5          # the [3, 8) window
    assert s_f["fault_req_blocked_cnt"] > 0
    assert eng.global_data_sum(state) == s_f["write_cnt"]
    # the keep-last epoch log is live on every node
    lsn = np.asarray(state.stats["fault_elog_lsn"])
    txn = np.asarray(state.stats["arr_fault_elog_txn"])
    assert (lsn > 0).all()                 # every node admitted work
    assert (txn >= 0).any(axis=1).all()    # ... and logged it


# ------------------------------------------------ satellite: guard


def test_calvin_exchange_guard_names_offenders():
    """The 2^23 packed-arbitration bound rejects oversized CALVIN cells
    with a structured ValueError naming (N, B, R) and the epoch_size
    remedy — not a bare assert."""
    with pytest.raises(ValueError) as ei:
        ShardedEngine(Config(
            cc_alg="CALVIN", node_cnt=2, part_cnt=2, part_per_txn=2,
            batch_size=1 << 16, req_per_query=128,
            synth_table_size=1 << 12, query_pool_size=1 << 10,
            warmup_ticks=0, mpr=1.0))
    msg = str(ei.value)
    assert "node_cnt=2" in msg
    assert "batch_size=65536" in msg
    assert "max_req=128" in msg
    assert "epoch_size" in msg
    assert "2^23" in msg
