"""Compile & memory observatory tests (deneva_tpu/obs/xmeter.py +
obs/regress.py, Config.xmeter): recompile-sentinel exactness across all
seven CC algorithms, shape-varying recompile detection, the HBM ledger
reconciled against both the raw state pytree and the compiled tick's own
memory_analysis(), the roofline row schema, the bench regression gate
(passes the repo's real trajectory, fails a synthetic 20% drop), the
budget/sizing helpers, and the off path's byte-identical [summary]."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deneva_tpu import stats as stats_mod
from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.obs import regress as obs_regress
from deneva_tpu.obs import report as obs_report
from deneva_tpu.obs import trace as obs_trace
from deneva_tpu.obs import xmeter as obs_xmeter

BASE = dict(cc_alg="NO_WAIT", batch_size=128, synth_table_size=1 << 10,
            req_per_query=4, zipf_theta=0.8, query_pool_size=1 << 10)

ALL_ALGS = ("NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT",
            "CALVIN")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_metered(n_ticks=12, **kw):
    eng = Engine(Config(**{**BASE, **kw}, xmeter=True))
    return eng, eng.run(n_ticks)


# ---- recompile sentinel ---------------------------------------------------

# the MAAT cell compiles the chain-validate and alone costs ~13 s —
# `-m slow` per the tier-1 870 s budget split (MAAT recompile freedom
# stays tier-1 via test_fused.py's zero-post-warm-recompile cell set)
@pytest.mark.parametrize("alg", [
    pytest.param(a, marks=pytest.mark.slow) if a == "MAAT" else a
    for a in ALL_ALGS])
def test_exact_compile_counts_per_alg(alg):
    # ONE compile per entry point across warmup + steady state: the tick
    # jit and the final flush.  A second run after mark_warm must hit the
    # dispatch cache every call — zero violations, zero new compiles.
    eng, st = run_metered(cc_alg=alg)
    xm = eng.xmeter
    assert xm.entries["tick"].compile_cnt == 1
    assert xm.entries["flush_writes"].compile_cnt == 1
    xm.mark_warm()
    eng.run(12, st)
    assert xm.steady_violations() == []
    assert xm.entries["tick"].compile_cnt == 1


def test_shape_varying_call_is_caught_and_named():
    xm = obs_xmeter.XMeter()
    f = xm.wrap("grow", jax.jit(lambda x: x + 1))
    f(jnp.zeros(8, jnp.int32))
    xm.mark_warm()
    f(jnp.zeros(16, jnp.int32))        # new shape -> new compile, post-warm
    assert xm.entries["grow"].compile_cnt == 2
    viol = xm.steady_violations()
    assert len(viol) == 1 and viol[0]["entry"] == "grow"
    assert viol[0]["signature"] is not None


def test_summary_fields_round_trip_the_line():
    eng, st = run_metered()
    line = eng.summary_line(st)
    parsed = stats_mod.parse_summary(line)
    assert parsed["compile_cnt"] == 2.0      # tick + flush_writes
    assert parsed["compile_ms"] > 0
    assert parsed["hbm_bytes"] > 0


# ---- HBM footprint ledger -------------------------------------------------

def test_ledger_carry_total_equals_state_nbytes():
    eng, st = run_metered()
    rows = eng.ledger(st)
    tot = obs_xmeter.ledger_totals(rows)
    want = sum(np.asarray(x).nbytes for x in jax.tree_util.tree_leaves(st))
    assert tot[obs_xmeter.KIND_CARRY] == want
    # every row names a real array with consistent bookkeeping
    for r in rows:
        assert r["nbytes"] == int(np.prod(r["shape"]) or 1) * \
            np.dtype(r["dtype"]).itemsize


def test_ledger_reconciles_with_memory_analysis():
    # the tick donates its whole carry, so the executable's argument
    # accounting and the ledger's carry total count the same buffers
    eng, st = run_metered()
    analysis = eng.xmeter.analyze("tick")
    rec = obs_xmeter.reconcile_ledger(eng.ledger(st), analysis)
    assert rec["ok"], rec
    assert abs(rec["ratio"] - 1.0) <= 0.01


def test_budget_check_flags_spill():
    eng, st = run_metered()
    rows = eng.ledger(st)
    tight = obs_xmeter.budget_check(rows, budget_mb=1e-4)
    roomy = obs_xmeter.budget_check(rows, budget_mb=1024)
    assert tight["spill"] and not roomy["spill"]
    assert 0 < tight["txn_plane_bytes"] <= tight["per_node_bytes"]
    assert roomy["cluster_bytes"] == roomy["per_node_bytes"]


def test_fit_batch_linear_model():
    # bytes(B) = 1000 + 10*B, budget 1 MB -> max B = (2**20 - 1000) / 10
    fit = obs_xmeter.fit_batch(1.0, {256: 1000 + 2560, 512: 1000 + 5120})
    assert fit["fixed_bytes"] == 1000
    assert fit["per_txn_bytes"] == 10.0
    assert fit["max_batch_per_node"] == int(((1 << 20) - 1000) / 10)
    assert obs_xmeter.fit_batch(
        1.0, {256: 3560, 512: 6120}, node_cnt=4)["max_batch_cluster"] == \
        4 * fit["max_batch_per_node"]


# ---- roofline -------------------------------------------------------------

def test_roofline_row_schema():
    eng, st = run_metered()
    eng.xmeter.block = True
    st = eng.run(8, st)                  # blocked calls -> wall-true ms
    eng.xmeter.analyze("tick")
    rows = eng.xmeter.roofline()
    row = next(r for r in rows if r["entry"] == "tick")
    for key in ("entry", "calls", "mean_ms", "flops", "bytes_accessed",
                "achieved_gflops", "achieved_gbps", "peak_flop_frac",
                "peak_bw_frac", "bound"):
        assert key in row
    assert row["mean_ms"] > 0 and row["calls"] >= 8
    assert row["peak_flop_frac"] > 0 and row["peak_bw_frac"] > 0
    assert row["bound"] in ("memory", "compute")
    md = obs_xmeter.roofline_markdown(rows)
    assert md.splitlines()[0].startswith("| entry |")
    assert "| tick |" in md


def test_snapshot_schema_and_report_section():
    eng, st = run_metered()
    eng.xmeter.block = True
    st = eng.run(8, st)
    eng.xmeter.analyze("tick")
    snap = eng.xmeter.snapshot()
    assert snap["schema"] == obs_xmeter.SNAPSHOT_SCHEMA
    assert snap["compile_cnt"] == 2 and "tick" in snap["entries"]
    json.dumps(snap)                     # JSON-serializable end to end
    rep = obs_report.build_report(eng.summary(st), xmeter=snap)
    text = obs_report.render_text(rep)
    assert "[compile]" in text and "[roofline]" in text
    assert "tick" in text


def test_chrome_trace_fifth_track(tmp_path):
    eng, st = run_metered(trace_ticks=16)
    eng.xmeter.block = True
    st = eng.run(8, st)
    snap = eng.xmeter.snapshot()
    p1 = obs_trace.to_chrome_trace(st, str(tmp_path / "with.json"),
                                   xmeter=snap)
    doc = json.load(open(p1))
    kernel = [e for e in doc["traceEvents"] if e["name"] == "kernel ms"]
    assert kernel and all(e["ph"] == "C" for e in kernel)
    assert "tick" in doc["metadata"]["xmeter_entries"]
    # the 5-track schema is opt-in: no snapshot, no track (compatibility)
    p2 = obs_trace.to_chrome_trace(st, str(tmp_path / "without.json"))
    doc2 = json.load(open(p2))
    assert not any(e["name"] == "kernel ms" for e in doc2["traceEvents"])
    assert "xmeter_entries" not in doc2["metadata"]


# ---- off-path parity ------------------------------------------------------

def test_xmeter_off_summary_is_byte_identical():
    off = Engine(Config(**BASE))
    on = Engine(Config(**BASE, xmeter=True))
    assert off.xmeter is None and on.xmeter is not None
    line_off = off.summary_line(off.run(10))
    line_on = on.summary_line(on.run(10))
    s_off = stats_mod.parse_summary(line_off)
    s_on = stats_mod.parse_summary(line_on)
    extra = set(s_on) - set(s_off)
    assert extra == {"compile_cnt", "compile_ms", "hbm_bytes"}
    # host-only keys aside, the two lines agree byte for byte: the meter
    # must not perturb the schedule
    host_keys = {"mem_util", "cpu_util", "total_runtime", "tput"}
    for k in s_off:
        if k in host_keys or k.startswith("ccl"):
            continue
        assert s_off[k] == s_on[k], k


def test_parse_summary_tolerates_unknown_future_keys():
    parsed = stats_mod.parse_summary(
        "[summary] txn_cnt=5,weird=hello,x=a=b,malformed,new_cnt=2")
    assert parsed["txn_cnt"] == 5.0
    assert parsed["weird"] == "hello"    # non-numeric kept verbatim
    assert parsed["x"] == "a=b"          # split once: '=' in value is ok
    assert parsed["new_cnt"] == 2.0
    assert "malformed" not in parsed


# ---- bench regression gate ------------------------------------------------

def _snap(tmp_path, n, value, cpt, rc=0):
    doc = {"n": n, "rc": rc,
           "parsed": None if rc else {
               "metric": "ycsb_nowait_zipf0.6_tput_faithful",
               "value": value,
               "algs": {"NO_WAIT": {"commits_per_tick": cpt}}}}
    p = tmp_path / f"BENCH_r{n:02d}.json"
    p.write_text(json.dumps(doc))
    return str(p)


def test_regress_passes_real_trajectory():
    snaps = sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json")))
    assert len(snaps) >= 3, "repo trajectory missing"
    rc = obs_regress.main(snaps + [os.path.join(REPO_ROOT, "results")])
    assert rc == 0


def test_regress_fails_synthetic_20pct_drop(tmp_path, capsys):
    paths = [_snap(tmp_path, n, 100.0, 100.0) for n in (1, 2, 3)]
    paths.append(_snap(tmp_path, 4, 100.0, 80.0))   # cpt -20% > 15% tol
    rc = obs_regress.main(paths)
    assert rc == 1
    assert "FAIL commits_per_tick[NO_WAIT]" in capsys.readouterr().out


def test_regress_skips_failed_snapshots_and_arms_gates(tmp_path, capsys):
    assert obs_regress.load_snapshot(
        _snap(tmp_path, 2, None, None, rc=1)) is None
    # a failed round in the middle of the trajectory is not a data point
    paths = [_snap(tmp_path, 1, 100.0, 100.0),
             _snap(tmp_path, 2, None, None, rc=1),
             _snap(tmp_path, 3, 99.0, 99.0)]
    rc = obs_regress.main(paths)
    assert rc == 0
    entries = obs_regress.load_trajectory(paths)
    assert [e["value"] for e in entries] == [100.0, 99.0]
    # gates with no prior data self-arm (skip, not fail)
    res = obs_regress.gate([entries[0]])
    assert res["failures"] == [] and res["skipped"]


def test_regress_required_cells_cannot_vanish(tmp_path, capsys):
    # a headline point that DROPS a sort-bound cell the trajectory has
    # carried (here MAAT) fails even though every present cell is
    # healthy; a cell that never appeared only arms the requirement
    def snap(n, algs):
        doc = {"n": n, "rc": 0,
               "parsed": {"metric": obs_regress.HEADLINE_METRIC,
                          "value": 100.0,
                          "algs": {a: {"commits_per_tick": 10.0}
                                   for a in algs}}}
        p = tmp_path / f"BENCH_r{n:02d}.json"
        p.write_text(json.dumps(doc))
        return str(p)

    full = ("NO_WAIT",) + obs_regress.REQUIRED_CELLS
    paths = [snap(1, full), snap(2, full),
             snap(3, ("NO_WAIT", "MVCC", "OCC", "TPCC_MVCC_64wh"))]
    rc = obs_regress.main(paths)
    assert rc == 1
    assert "required cell commits_per_tick[MAAT]" in capsys.readouterr().out
    # never-seen cells skip (the synthetic NO_WAIT-only trajectories of
    # the tests above must keep passing)
    res = obs_regress.gate(obs_regress.load_trajectory(paths[:1]))
    assert res["failures"] == []


def test_regress_reads_bench_history_jsonl(tmp_path):
    hist = tmp_path / "bench_history.jsonl"
    lines = [json.dumps({"unix_time": 100 + i, "metric": "m",
                         "value": 50.0, "algs": {"OCC": 10.0}})
             for i in range(3)]
    hist.write_text("\n".join(lines + ["{not json"]) + "\n")
    entries = obs_regress.load_trajectory([str(tmp_path)])
    assert len(entries) == 3             # malformed line skipped
    res = obs_regress.gate(entries)
    assert res["failures"] == []
    assert any(c["name"] == "commits_per_tick[OCC]" for c in res["checks"])
