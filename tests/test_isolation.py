"""Isolation levels (reference config.h:336-340; early-release hooks
ycsb_txn.cpp:233-251, NOLOCK bypass storage/row.cpp:199-206)."""

import numpy as np

from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.engine.state import STATUS_BACKOFF
from tests.test_engine_nowait import make_pool, small_cfg


def test_read_committed_releases_read_locks():
    # txn0 READS k5 then k1; txn1 WRITES k5 then k2.
    # tick0: txn0 reads k5 (S), txn1's write of k5 conflicts -> aborts under
    # SERIALIZABLE.  Under READ_COMMITTED the S lock is released right after
    # the read, so at tick1 txn1's retry... but with no backoff txn1 aborts
    # at tick0 either way (same-tick conflict).  Distinguish at tick1+:
    # under RC txn0's completed read of k5 is NOT held, so txn1 (restarted)
    # can take k5 while txn0 still runs.
    keys = np.array([[5, 1], [5, 2]], np.int32)
    iw = np.array([[False, False], [True, True]])
    pool = make_pool(keys, iw)

    # SERIALIZABLE: txn1 keeps dying while txn0 holds S(k5) (until commit)
    eng = Engine(small_cfg(batch_size=2, query_pool_size=2,
                           isolation_level="SERIALIZABLE"), pool=pool)
    st = eng.run(1)
    assert int(st.txn.status[1]) == STATUS_BACKOFF

    # READ_COMMITTED: at tick1 txn1 restarts; txn0 holds only its *current*
    # request (k1 read), S(k5) was dropped -> txn1 takes k5.
    eng2 = Engine(small_cfg(batch_size=2, query_pool_size=2,
                            isolation_level="READ_COMMITTED"), pool=pool)
    st2 = eng2.run(2)
    assert int(st2.txn.cursor[1]) == 1  # write of k5 granted on retry


def test_read_uncommitted_reads_bypass_x_locks():
    # txn0 WRITES k5 (X lock, long txn); txn1 READS k5.
    keys = np.array([[5, 1], [5, 2]], np.int32)
    iw = np.array([[True, True], [False, False]])
    pool = make_pool(keys, iw)

    eng = Engine(small_cfg(batch_size=2, query_pool_size=2,
                           isolation_level="SERIALIZABLE"), pool=pool)
    st = eng.run(1)
    assert int(st.txn.status[1]) == STATUS_BACKOFF  # reader dies (NO_WAIT)

    eng2 = Engine(small_cfg(batch_size=2, query_pool_size=2,
                            isolation_level="READ_UNCOMMITTED"), pool=pool)
    st2 = eng2.run(1)
    assert int(st2.txn.cursor[1]) == 1  # read granted despite held X


def test_nolock_never_conflicts():
    keys = np.array([[5, 1], [5, 2], [5, 3], [5, 4]], np.int32)
    pool = make_pool(keys, np.ones((4, 2), bool))
    eng = Engine(small_cfg(isolation_level="NOLOCK"), pool=pool)
    st = eng.run(3)
    s = eng.summary(st)
    assert s["total_txn_abort_cnt"] == 0
    assert s["txn_cnt"] == 4
