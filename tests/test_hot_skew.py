"""HOT skew mode (Config.skew_method, ycsb_query.cpp:205-301).

The reference's second skew generator: ACCESS_PERC of the traffic goes
to the DATA_PERC fraction of the table (the lowest row ids).  These
tests pin the sampler's statistics and the gen_query_pool dispatch.
"""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.workloads.ycsb import (HotSampler, ZipfSampler,
                                       gen_query_pool, make_sampler)


def test_hot_sampler_distribution():
    s = HotSampler(1000, access_perc=0.8, data_perc=0.1)
    assert s.hot_n == 100
    ids = s.sample(np.random.default_rng(7), 100_000)
    assert ids.min() >= 1 and ids.max() <= 1000
    frac = float((ids <= s.hot_n).mean())
    assert abs(frac - 0.8) < 0.05, frac
    # hot draws are uniform over the hot set (each hot row ~ frac/hot_n)
    hot_counts = np.bincount(ids[ids <= s.hot_n], minlength=s.hot_n + 1)[1:]
    assert hot_counts.min() > 0
    assert hot_counts.max() < 4 * hot_counts.mean()


def test_hot_sampler_degenerate_all_hot():
    s = HotSampler(50, access_perc=0.75, data_perc=1.0)
    assert s.hot_n == 50
    ids = s.sample(np.random.default_rng(0), 10_000)
    assert ids.min() >= 1 and ids.max() <= 50


def test_hot_sampler_min_one_row():
    s = HotSampler(10, access_perc=0.9, data_perc=0.001)
    assert s.hot_n == 1
    ids = s.sample(np.random.default_rng(1), 10_000)
    assert abs(float((ids == 1).mean()) - 0.9) < 0.05


def test_make_sampler_dispatch():
    hot = Config(cc_alg="NO_WAIT", skew_method="hot")
    assert isinstance(make_sampler(hot, 100), HotSampler)
    zipf = Config(cc_alg="NO_WAIT")
    assert isinstance(make_sampler(zipf, 100), ZipfSampler)


def test_pool_hot_fraction():
    cfg = Config(cc_alg="NO_WAIT", skew_method="hot", access_perc=0.75,
                 data_perc=0.1, synth_table_size=4096,
                 query_pool_size=2048, req_per_query=4, warmup_ticks=0)
    pool = gen_query_pool(cfg)
    # part_cnt 1: primary key == row id, hot set == ids [1, hot_n]
    hot_n = max(1, int(cfg.data_perc * (cfg.synth_table_size - 1)))
    frac = float((pool.keys <= hot_n).mean())
    assert abs(frac - cfg.access_perc) < 0.05, frac


def test_skew_method_validated():
    with pytest.raises(AssertionError):
        Config(cc_alg="NO_WAIT", skew_method="pareto")
