"""Tick-certifier tests (lint engine 3, deneva_tpu/lint/certify.py).

Three layers: the jaxpr canonicalizer's invariances (alpha-equivalence
under variable renaming and reordering of independent equations, dead
code/const elimination), deliberately-broken tick fixtures each rejected
with its named rule (OFFPATH-IMPURE / CARRY-DRIFT / DONATION-DECLINED /
SCATTER-RACE-JAXPR / DTYPE-WIDEN), and the matrix itself: a small cell
in tier-1, the clean full matrix under `-m slow` (the same run
scripts/check.sh gates on), and the auto-discovery guard that fails
loudly when a future flag-shaped Config field ships without
certification coverage.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from deneva_tpu import config as config_mod
from deneva_tpu.config import NON_OPTIN_KNOBS, Config, optin_flags
from deneva_tpu.lint import certify, diff_engine

pytestmark = pytest.mark.lint


def canon(fn, *args):
    closed = jax.make_jaxpr(fn)(*args)
    return diff_engine.canonicalize(closed.jaxpr, closed.consts)


# ---------------------------------------------------------------------------
# canonicalizer unit suite


def test_canon_var_renaming_invariance():
    # two separate traces bind fresh Var objects throughout — the
    # canonical forms must still be identical, and so their fingerprints
    def f(x, y):
        return x * 2 + y

    c1 = canon(f, jnp.float32(1), jnp.float32(2))
    c2 = canon(f, jnp.float32(1), jnp.float32(2))
    assert c1 == c2
    assert diff_engine.diff(c1, c2) is None


def test_canon_reorder_independent_eqns():
    def ab(x, y):
        a = x * 2
        b = y + 3
        return a + b

    def ba(x, y):
        b = y + 3
        a = x * 2
        return a + b

    one, two = jnp.float32(1), jnp.float32(2)
    assert canon(ab, one, two) == canon(ba, one, two)


def test_canon_dead_code_and_consts_dropped():
    import numpy as np
    big = jnp.asarray(np.arange(64, dtype=np.int32))

    def clean(x):
        return x + 1

    def with_dead(x):
        dead = (big * 2).sum()          # traced but unused
        del dead
        return x + 1

    x = jnp.zeros((8,), jnp.int32)
    assert canon(clean, x) == canon(with_dead, x)


def test_canon_detects_real_difference():
    def f(x):
        return x + 1

    def g(x):
        return x * 2

    x = jnp.zeros((8,), jnp.int32)
    cf, cg = canon(f, x), canon(g, x)
    assert cf != cg
    msg = diff_engine.diff(cf, cg, "base", "other")
    assert msg is not None and "add" in msg and "mul" in msg


def test_canon_sub_jaxpr_reorder_normalized():
    # a reorder INSIDE a scan body must also canonicalize away: the body
    # jaxpr rides in eqn params and is fingerprinted recursively
    def body_ab(c, x):
        a = c * 2
        b = x + 3
        return a + b, x

    def body_ba(c, x):
        b = x + 3
        a = c * 2
        return a + b, x

    xs = jnp.zeros((4,), jnp.float32)

    def scan_with(body):
        return lambda c: jax.lax.scan(body, c, xs)

    c0 = jnp.float32(0)
    assert canon(scan_with(body_ab), c0) == canon(scan_with(body_ba), c0)


def test_fingerprint_matches_canonical_equality():
    def f(x):
        return x - 1

    x = jnp.zeros((4,), jnp.int32)
    j1, j2 = jax.make_jaxpr(f)(x), jax.make_jaxpr(f)(x)
    assert diff_engine.fingerprint(j1.jaxpr, j1.consts) == \
        diff_engine.fingerprint(j2.jaxpr, j2.consts)


# ---------------------------------------------------------------------------
# broken fixtures: each rejected with the named rule

STATE = {"x": jnp.zeros((8,), jnp.int32), "y": jnp.zeros((8,), jnp.int32)}


def _fake_trace(fn, state=STATE):
    closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(state)
    return closed, out_shape, state, fn


def test_fixture_offpath_leak(monkeypatch):
    """A flag whose ON build leaks trace state: the off-after-on re-trace
    no longer matches the baseline -> OFFPATH-IMPURE, anchored at the
    flag's config.py field line."""
    def clean(s):
        return {"x": s["x"] + 1, "y": s["y"]}

    def leaked(s):
        # the leak: an extra array the off path was promised not to carry
        return {"x": s["x"] + 1 + jnp.arange(8, dtype=jnp.int32),
                "y": s["y"]}

    base_closed = jax.make_jaxpr(clean)(STATE)
    base_canon = diff_engine.canonicalize(base_closed.jaxpr,
                                          base_closed.consts)
    monkeypatch.setattr(certify, "trace_tick",
                        lambda cfg, engine: _fake_trace(leaked))
    flag = optin_flags()["abort_attribution"]
    found = certify.check_offpath("tick:FIXTURE", flag, base_canon,
                                  None, "tick")
    assert [f.rule for f in found] == ["OFFPATH-IMPURE"]
    assert found[0].path == config_mod.__file__
    assert found[0].line > 0
    assert "abort_attribution" in found[0].message


def test_fixture_offpath_clean(monkeypatch):
    def clean(s):
        return {"x": s["x"] + 1, "y": s["y"]}

    base_closed = jax.make_jaxpr(clean)(STATE)
    base_canon = diff_engine.canonicalize(base_closed.jaxpr,
                                          base_closed.consts)
    monkeypatch.setattr(certify, "trace_tick",
                        lambda cfg, engine: _fake_trace(clean))
    flag = optin_flags()["abort_attribution"]
    assert certify.check_offpath("tick:FIXTURE", flag, base_canon,
                                 None, "tick") == []


def test_fixture_carry_drift():
    """A dummy tick whose output widens a carry leaf dtype -> CARRY-DRIFT
    naming the leaf."""
    def drifting(s):
        return {"x": s["x"].astype(jnp.float32), "y": s["y"]}

    _, out_shape, state, _ = _fake_trace(drifting)
    found = certify.check_carry("tick:FIXTURE", "tick", state, out_shape)
    assert [f.rule for f in found] == ["CARRY-DRIFT"]
    assert "'x'" in found[0].message and "float32" in found[0].message


def test_fixture_carry_structure_drift():
    def restructure(s):
        return {"x": s["x"], "y": s["y"], "z": s["x"] + 1}

    _, out_shape, state, _ = _fake_trace(restructure)
    found = certify.check_carry("tick:FIXTURE", "tick", state, out_shape)
    assert [f.rule for f in found] == ["CARRY-DRIFT"]
    assert "structure" in found[0].message


def test_fixture_donation_declined():
    """An entry point that replaces a carry leaf with a fresh constant:
    XLA cannot alias the donated input into that output, so the lowering
    marks fewer leaves than the carry has -> DONATION-DECLINED."""
    def const_out(s):
        return {"x": jnp.zeros((8,), jnp.int32), "y": s["y"] + 1}

    _, _, state, fn = _fake_trace(const_out)
    found = certify.check_donation("tick:FIXTURE", "tick", fn, state)
    assert [f.rule for f in found] == ["DONATION-DECLINED"]
    assert "1/2" in found[0].message


def test_fixture_donation_clean():
    def good(s):
        return {"x": s["x"] + 1, "y": s["y"] + 1}

    _, _, state, fn = _fake_trace(good)
    assert certify.check_donation("tick:FIXTURE", "tick", fn, state) == []


def test_fixture_scatter_race_jaxpr():
    """Duplicate-capable tracer-built indices with a non-commutative
    `.set` scatter -> SCATTER-RACE-JAXPR.  The indices come from tracer
    arithmetic, exactly the case the AST engine must skip."""
    def racy(s):
        idx = s["y"] % 4                      # duplicates possible
        return {"x": s["x"].at[idx].set(1), "y": s["y"]}

    closed, _, _, _ = _fake_trace(racy)
    found = certify.walk_tick("tick:FIXTURE", closed)
    assert "SCATTER-RACE-JAXPR" in [f.rule for f in found]
    f = next(f for f in found if f.rule == "SCATTER-RACE-JAXPR")
    assert f.path.endswith("test_certify.py") and f.line > 0


def test_fixture_scatter_commutative_clean():
    def additive(s):
        idx = s["y"] % 4
        return {"x": s["x"].at[idx].add(1), "y": s["y"]}

    closed, _, _, _ = _fake_trace(additive)
    assert certify.walk_tick("tick:FIXTURE", closed) == []


def test_fixture_dtype_widen():
    """An int64 widening (traced under x64 so jax does not silently
    truncate it back) -> DTYPE-WIDEN."""
    with jax.experimental.enable_x64():
        def widening(s):
            return {"x": (s["x"].astype(jnp.int64)
                          + jnp.int64(1)).astype(jnp.int32),
                    "y": s["y"]}

        state = {"x": jnp.zeros((8,), jnp.int32),
                 "y": jnp.zeros((8,), jnp.int32)}
        closed, _, _, _ = _fake_trace(widening, state)
    found = certify.walk_tick("tick:FIXTURE", closed)
    assert "DTYPE-WIDEN" in [f.rule for f in found]
    f = next(f for f in found if f.rule == "DTYPE-WIDEN")
    assert "int64" in f.message


# ---------------------------------------------------------------------------
# auto-discovery guard: certified flags == flag-shaped Config fields


def _flag_shaped_fields():
    """Heuristic surface a future feature flag will land on: bool
    defaulting False, Optional defaulting None, or int defaulting 0."""
    out = []
    for f in dataclasses.fields(Config):
        if f.default is dataclasses.MISSING:
            default = (f.default_factory()
                       if f.default_factory is not dataclasses.MISSING
                       else None)
        else:
            default = f.default
        ty = str(f.type)
        if (default is False and "bool" in ty) \
                or (default is None and "Optional" in ty) \
                or (default == 0 and default is not False
                    and "int" in ty):
            out.append(f.name)
    return out


def test_autodiscovery_guard_every_flag_covered():
    """Every flag-shaped field must be certified (_optin) or excused in
    NON_OPTIN_KNOBS with a reason — a new flag without coverage fails
    here, loudly, before it ships uncertified."""
    flags = optin_flags()
    uncovered = [n for n in _flag_shaped_fields()
                 if n not in flags and n not in NON_OPTIN_KNOBS]
    assert uncovered == [], (
        f"Config fields {uncovered} look like opt-in feature flags but "
        "are neither declared with _optin(...) (certified by the lint "
        "tick certifier) nor excused in NON_OPTIN_KNOBS with a reason — "
        "add one or the other (config.py)")
    # no stale excuses, and every excuse carries a reason
    assert all(NON_OPTIN_KNOBS.values()), "bare NON_OPTIN_KNOBS excuse"
    overlap = set(flags) & set(NON_OPTIN_KNOBS)
    assert overlap == set(), f"{overlap} both certified and excused"


def test_optin_registry_on_kwargs_construct():
    """Every flag's on-kwargs must yield a valid Config on its declared
    engines' baseline cells (otherwise the matrix would silently skip)."""
    for name, flag in optin_flags().items():
        engine = flag.engines[0]
        base = certify.base_cfg("NO_WAIT", "YCSB", engine)
        on = base.replace(**flag.on)
        assert getattr(on, name) != flag.default, name
        assert flag.engines and all(
            e in ("tick", "sharded_tick") for e in flag.engines), name


# ---------------------------------------------------------------------------
# the matrix


def test_certify_small_cell_clean():
    """One single-engine cell with a non-inert flag sweep: the tier-1
    anchor that the certifier passes end to end on real ticks."""
    found = certify.run_certify(
        algs=("NO_WAIT",), workloads=("YCSB",), engines=("tick",),
        flags=("abort_attribution", "trace_ticks", "xmeter"))
    assert [f for f in found if not f.suppressed] == []


def test_certify_sharded_cell_clean():
    found = certify.run_certify(
        algs=("WAIT_DIE",), workloads=("YCSB",),
        engines=("sharded_tick",), flags=("mesh",))
    assert [f for f in found if not f.suppressed] == []


@pytest.mark.slow
def test_certify_full_matrix_clean():
    """The acceptance criterion: 0 unsuppressed findings over the full
    matrix (same run scripts/check.sh gates on)."""
    found = certify.run_certify()
    assert [f for f in found if not f.suppressed] == [], \
        [f"{f.rule} {f.location()}: {f.message}" for f in found
         if not f.suppressed]


def test_certify_cli_exit_code_and_json(tmp_path):
    import json
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable, "-m", "deneva_tpu.lint.certify",
         "--algs", "NO_WAIT", "--workloads", "YCSB",
         "--engines", "tick", "--flags", "profile",
         "--format", "json"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["unsuppressed"] == 0
