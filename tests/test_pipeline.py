"""Pipelined sharded ticks (Config.pipeline_exchange).

The software pipeline is a pure trace-order restructure of the
epoch-split exchange's unrolled sub-round loops (parallel/sharded.py):
sub-round k+1's pack + all_to_all are ISSUED before sub-round k's
received lanes are consumed, so XLA's async collective scheduler can
overlap the ICI transfer with shard-local compute.  One level down the
single-chip engine hoists every ``sub_ticks`` round's request plane out
of the serial grant chain (cc/twopl.py arbitrate_subticked).  Both legs
are dataflow-identical reorders, so the covering contract is BIT-PARITY:

- the 4-node CALVIN oracle cell must produce the identical [summary]
  (modulo the two new occupancy counters) and the identical data array;
- the single-chip sub_ticks kernel must return identical G/W/A masks on
  every policy (the ``~dead`` request-mask term it drops is provably
  redundant: arbitrate only aborts request positions, and a txn's sole
  request lane enters at exactly its own group's round);
- the flag is trait-gated inert without ``exchange_split`` (and without
  its never-aborts plugin gate) — zero extra device state;
- zero steady-state recompiles under the xmeter sentinel, and the mesh
  round-windows identity (``mesh_round_sum == exchange_round_cnt``)
  still reconciles exactly on the pipelined path.
"""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.parallel.sharded import ShardedEngine

# rcf=0.5 keeps the cell multi-sub-round (overlap strictly between 0
# and the leg count) at half the unrolled trace of the rcf=0.25 smoke
# in scripts/check.sh — the tier-1 sentinel stays compile-cheap
BASE = dict(cc_alg="CALVIN", node_cnt=4, part_cnt=4, batch_size=32,
            synth_table_size=1 << 10, query_pool_size=256,
            req_per_query=4, warmup_ticks=2, exchange_split=True,
            route_capacity_factor=0.5)


def run_cell(ticks=20, **kw):
    eng = ShardedEngine(Config(**{**BASE, **kw}))
    st = eng.run(ticks)
    return eng, st, eng.summary(st)


def test_pipelined_bit_parity_and_mesh_identity_on_oracle_cell():
    """The 4-node CALVIN oracle cell at a capacity forcing many
    sub-rounds per epoch, mesh observatory on BOTH sides (one tier-1
    sentinel, two engine builds): every summary counter and the
    row-version data array must be bit-identical, the pipelined run
    adding only its two occupancy counters; and the pipelined path's
    mesh-side window count must still land exactly on the engine's
    round_plan bookkeeping — the round_windows reconcile identity
    (obs/mesh.py) plus every preexisting mesh identity, with zero
    structural drops."""
    from deneva_tpu.obs import mesh as obs_mesh
    _, s0, a = run_cell(mesh=True)
    eng, s1, b = run_cell(mesh=True, pipeline_exchange=True)
    assert set(b) - set(a) == {"pipe_leg_cnt", "pipe_overlap_cnt"}
    for k in a:
        assert a[k] == b[k], (k, a[k], b[k])
    assert np.array_equal(np.asarray(s0.data), np.asarray(s1.data))
    # a multi-sub-round cell must actually overlap: each pass's legs
    # beyond its first are issued with another leg in flight
    assert b["pipe_leg_cnt"] > 0
    assert 0 < b["pipe_overlap_cnt"] < b["pipe_leg_cnt"]
    snap = eng.mesh_snapshot(s1)
    assert obs_mesh.reconcile(snap, b) == []
    assert snap["round_sum"] is not None
    assert np.array_equal(snap["round_sum"], snap["rounds"])
    assert b["mesh_round_sum"] == b["exchange_round_cnt"] > 0


# tier-2: the certifier already proves the NO_WAIT pipelined cell inert
# STATICALLY (on-jaxpr == baseline, lint/certify.py) — this runtime
# double-build re-verifies the summary surface on the slow path only
@pytest.mark.slow
def test_abort_capable_plugin_stays_inert():
    """exchange_split (and therefore the pipeline riding it) is gated
    on never-aborts plugins: an abort-capable sharded cell with both
    flags set must carry NO extra device state and produce the
    bit-identical summary."""
    _, s0, a = run_cell(cc_alg="NO_WAIT")
    _, s1, b = run_cell(cc_alg="NO_WAIT", pipeline_exchange=True)
    assert set(a) == set(b)
    assert not any(k.startswith("pipe_") for k in b)
    assert "exchange_round_cnt" not in b
    for k in a:
        assert a[k] == b[k], (k, a[k], b[k])
    assert np.array_equal(np.asarray(s0.data), np.asarray(s1.data))


def test_flag_inert_without_exchange_split():
    """Trait gating: pipeline_exchange without exchange_split adds no
    stats keys — the sharded leg requires the split path (the on-dict
    sets both, but a hand-built Config can set the flag alone)."""
    st = ShardedEngine(Config(**{**BASE, "exchange_split": False,
                                 "pipeline_exchange": True})).init_state()
    assert not any(k.startswith("pipe_") for k in st.stats)
    assert "exchange_round_cnt" not in st.stats
    assert "mesh_round_sum" not in st.stats


def test_subticked_kernel_identity_all_policies():
    """The single-chip leg's hoist identity, directly on the kernel:
    pipelined=True must return bit-identical grant/wait/abort masks for
    every lock policy over randomized txn states."""
    import jax.numpy as jnp
    from deneva_tpu.cc import twopl
    from deneva_tpu.engine.state import TxnState
    rng = np.random.default_rng(0)
    B, R, K = 64, 4, 8
    for policy in ("NO_WAIT", "WAIT_DIE", "CALVIN"):
        keys = rng.integers(0, 32, (B, R)).astype(np.int32)
        txn = TxnState(
            status=jnp.zeros(B, jnp.int32),
            cursor=jnp.asarray(rng.integers(0, R, B), jnp.int32),
            ts=jnp.asarray(rng.permutation(B).astype(np.int32) + 1),
            pool_idx=jnp.zeros(B, jnp.int32),
            restarts=jnp.zeros(B, jnp.int32),
            backoff_until=jnp.zeros(B, jnp.int32),
            start_tick=jnp.zeros(B, jnp.int32),
            first_start_tick=jnp.zeros(B, jnp.int32),
            keys=jnp.asarray(keys),
            is_write=jnp.asarray(rng.random((B, R)) < 0.5),
            n_req=jnp.full(B, R, jnp.int32),
            txn_type=jnp.zeros(B, jnp.int32),
            targs=jnp.zeros((B, 1), jnp.int32),
            aux=jnp.zeros((B, 1), jnp.int32))
        active = jnp.asarray(rng.random(B) < 0.8)
        a = twopl.arbitrate_subticked(txn, active, policy, K)
        b = twopl.arbitrate_subticked(txn, active, policy, K,
                                      pipelined=True)
        for x, y in zip(a, b):
            assert np.array_equal(np.asarray(x), np.asarray(y)), policy


def test_single_chip_subticks_parity_abort_capable():
    """The single-chip engine's sub_ticks leg with an abort-capable
    plugin (NO_WAIT): pipelined and in-order schedules must be
    bit-identical through a full run."""
    from deneva_tpu.engine.scheduler import Engine
    kw = dict(cc_alg="NO_WAIT", batch_size=64, synth_table_size=1 << 10,
              query_pool_size=256, req_per_query=4, warmup_ticks=2,
              sub_ticks=4)
    a = Engine(Config(**kw))
    b = Engine(Config(**kw, pipeline_exchange=True))
    sa, sb = a.run(30), b.run(30)
    ra, rb = a.summary(sa), b.summary(sb)
    assert set(ra) == set(rb)
    for k in ra:
        assert ra[k] == rb[k], (k, ra[k], rb[k])


# tier-2: the tier-1 sentinels above cover parity + gating; the sentinel
# run below costs two extra compiled windows
@pytest.mark.slow
def test_zero_steady_recompiles_pipelined():
    """The pipeline is a trace-time restructure — no shape or count
    depends on data, so the xmeter sentinel must report ZERO post-warm
    compiles on the pipelined cell."""
    eng = ShardedEngine(Config(**{**BASE, "pipeline_exchange": True,
                                  "mesh": True, "xmeter": True}))
    st = eng.run(12)
    eng.xmeter.mark_warm()
    eng.run(12, st)
    assert eng.xmeter.steady_violations() == []
