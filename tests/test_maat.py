"""MaaT golden micro-schedules (maat.cpp:29-190, row_maat.cpp:99-314)."""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.engine.state import STATUS_BACKOFF
from tests.test_engine_nowait import make_pool, small_cfg


def test_disjoint_txns_commit_with_full_ranges():
    keys = np.arange(8, dtype=np.int32).reshape(4, 2)
    pool = make_pool(keys, np.ones((4, 2), bool))
    eng = Engine(small_cfg(cc_alg="MAAT"), pool=pool)
    st = eng.run(4)
    s = eng.summary(st)
    assert s["txn_cnt"] == 4
    assert s["total_txn_abort_cnt"] == 0


def test_rw_overlap_both_commit_with_adjusted_ranges():
    # MaaT's whole point: reader and writer of the same row can BOTH commit,
    # ordered by timestamp ranges instead of aborting (unlike NO_WAIT).
    # txn0 reads k5, txn1 writes k5, fully overlapped in time.
    keys = np.array([[5, 1], [5, 2]], np.int32)
    iw = np.array([[False, False], [True, True]])
    pool = make_pool(keys, iw)
    eng = Engine(small_cfg(cc_alg="MAAT", batch_size=2, query_pool_size=2),
                 pool=pool)
    st = eng.run(4)
    s = eng.summary(st)
    assert s["txn_cnt"] == 2
    assert s["total_txn_abort_cnt"] == 0


def test_read_after_commit_serializes_after():
    # txn1 writes k5 and commits with commit_ts; a later txn reading k5
    # snapshots gw = lw >= commit_ts, so its lower > commit_ts: both commit,
    # no abort (case 1 path, maat.cpp:46-48).
    keys = np.array([[5, 8], [5, 9]], np.int32)
    iw = np.array([[True, True], [False, False]])
    pool = make_pool(keys, iw, n_req=[2, 2])
    eng = Engine(small_cfg(cc_alg="MAAT", batch_size=2, query_pool_size=2),
                 pool=pool)
    st = eng.run(6)
    s = eng.summary(st)
    assert s["txn_cnt"] >= 2
    db = st.db
    assert int(np.asarray(db["maat_lw"][5])) >= 1   # commit bumped lw


def test_squeezed_to_empty_range_aborts():
    # force lower >= upper: txn0 writes k5 with a long program; two txns
    # read k5 and commit, pushing txn0's lower up while... the reliable
    # empty-range case in one tick: two same-tick finishers where the
    # earlier writer forces the later reader's upper below its lower is
    # exercised under contention instead; here just check aborts occur at
    # high contention and the oracle holds.
    cfg = Config(batch_size=64, synth_table_size=128, req_per_query=4,
                 query_pool_size=512, zipf_theta=0.9, tup_read_perc=0.5,
                 cc_alg="MAAT", warmup_ticks=0)
    eng = Engine(cfg)
    st = eng.run(60)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert np.asarray(st.data).sum() == s["write_cnt"]


def test_single_key_writers_serialize():
    # Degenerate single-key cell: every entry lands in ONE sorted segment, so
    # the jnp.roll(·, d) pair windows wrap around the array end.  Before the
    # `lane >= d` masks, the wrapped pairs poisoned the chain classification
    # and no caps fired at all — four same-tick writers of the same row all
    # committed unserialized (data[5] counted every "commit", not one write
    # per serialized winner).
    keys = np.full((4, 1), 5, np.int32)
    pool = make_pool(keys, np.ones((4, 1), bool))
    eng = Engine(small_cfg(cc_alg="MAAT", req_per_query=1, batch_size=4,
                           query_pool_size=4), pool=pool)
    st = eng.run(8)
    s = eng.summary(st)
    assert int(np.asarray(st.data)[5]) == s["txn_cnt"]  # one write per commit
    assert s["vabort_cnt"] > 0            # concurrent writers now conflict
    assert s["maat_chain_cap_cnt"] > 0    # chain caps actually fire
    assert s["maat_chain_overflow_cnt"] == 0  # 4 validators <= window 8


@pytest.mark.parametrize("window",
                         [1, pytest.param(4, marks=pytest.mark.slow)])
def test_oracle_and_better_than_nowait_commit_rate(window):
    # MaaT should commit at least as much as NO_WAIT under rw-heavy
    # contention (it never aborts on pure rw overlap)
    common = dict(batch_size=64, synth_table_size=256, req_per_query=4,
                  query_pool_size=512, zipf_theta=0.9, tup_read_perc=0.7,
                  warmup_ticks=0, acquire_window=window)
    eng_m = Engine(Config(cc_alg="MAAT", **common))
    st_m = eng_m.run(50)
    s_m = eng_m.summary(st_m)
    assert np.asarray(st_m.data).sum() == s_m["write_cnt"]

    eng_n = Engine(Config(cc_alg="NO_WAIT", **common))
    s_n = eng_n.summary(eng_n.run(50))
    assert s_m["txn_cnt"] >= 0.8 * s_n["txn_cnt"]
