"""Open-system traffic engine tests (deneva_tpu/traffic/): arrival-stream
determinism, admission-backpressure conservation, flash-crowd recovery,
the OVERLOAD watchdog bit, the queue-depth trace plane and the off-path
byte-identity contract (``Config.arrival=None`` must leave the engine —
state carry, stats keys, [summary] line — untouched).

Conservation contract under test (traffic/arrival.py):
``arrival_cnt == queue_admit_cnt + queue_len`` at every boundary — txns
are shed by QUEUEING, never dropped.
"""

import numpy as np
import pytest

from deneva_tpu import stats as stats_mod
from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.obs import report as obs_report
from deneva_tpu.obs import trace as obs_trace

BASE = dict(cc_alg="NO_WAIT", batch_size=64, synth_table_size=1 << 10,
            req_per_query=4, zipf_theta=0.6, query_pool_size=1 << 10,
            warmup_ticks=0)

TRAFFIC_KEYS = ("arrival_cnt", "queue_admit_cnt", "queue_len", "queue_peak")


def summarize(cfg, n_ticks=40, compiled=False):
    eng = Engine(cfg)
    st = (eng.run_compiled(n_ticks) if compiled else eng.run(n_ticks))
    return eng, st, eng.summary(st)


def test_same_seed_bit_identical_across_runs_and_scan():
    cfg = Config(arrival="poisson", arrival_rate=6.0, **BASE)
    _, _, s1 = summarize(cfg)
    _, _, s2 = summarize(cfg)
    _, _, s3 = summarize(cfg, compiled=True)   # fori_loop scan stepping
    for k in TRAFFIC_KEYS + ("txn_cnt", "lat_work_queue_time"):
        assert s1[k] == s2[k], (k, s1[k], s2[k])
        assert s1[k] == s3[k], ("scan vs per-tick", k, s1[k], s3[k])
    # a different arrival seed draws a different stream
    _, _, s4 = summarize(cfg.replace(arrival_seed=99))
    assert s4["arrival_cnt"] != s1["arrival_cnt"]


@pytest.mark.parametrize("model,kw", [
    ("poisson", dict(arrival_rate=6.0)),
    ("mmpp", dict(arrival_rate=3.0, arrival_burst_rate=30.0)),
    ("step", dict(arrival_schedule=((0, 2.0), (10, 20.0), (25, 4.0)))),
])
def test_conservation_no_drop(model, kw):
    cfg = Config(arrival=model, **kw, **BASE)
    _, _, s = summarize(cfg)
    assert s["arrival_cnt"] > 0
    assert s["arrival_cnt"] == s["queue_admit_cnt"] + s["queue_len"], s
    assert s["queue_peak"] >= s["queue_len"]


def test_flash_crowd_drains_to_empty_queue():
    cfg = Config(arrival="step",
                 arrival_schedule=((0, 3.0), (15, 100.0), (25, 1.0)),
                 **{**BASE, "zipf_theta": 0.1, "req_per_query": 2})
    _, _, s = summarize(cfg, n_ticks=200)
    assert s["queue_peak"] > 0, "flash crowd never queued"
    assert s["queue_len"] == 0, f"backlog not drained: {s['queue_len']}"
    assert s["arrival_cnt"] == s["queue_admit_cnt"]
    # drained run must NOT trip the overload watchdog
    _, code = obs_report.watchdog(s)
    assert not (code & obs_report.OVERLOAD), code


def test_overload_bit_fires_and_recovers():
    # sustained over-offered rate: backlog at run end trips OVERLOAD
    over = Config(arrival="poisson", arrival_rate=200.0, **BASE)
    _, _, s = summarize(over, n_ticks=40)
    assert s["queue_len"] > 0
    findings, code = obs_report.watchdog(s)
    assert code & obs_report.OVERLOAD, (code, findings)
    assert any(f[0] == "OVERLOAD" for f in findings)
    # under-offered: clean
    _, _, s2 = summarize(Config(arrival="poisson", arrival_rate=2.0,
                                **BASE), n_ticks=40)
    _, c2 = obs_report.watchdog(s2)
    assert not (c2 & obs_report.OVERLOAD)
    # closed-loop summaries never reach the check at all
    _, c3 = obs_report.watchdog({"txn_cnt": 10, "measured_ticks": 5})
    assert not (c3 & obs_report.OVERLOAD)


def test_work_queue_time_nonzero_open_zero_closed():
    over = Config(arrival="poisson", arrival_rate=50.0, **BASE)
    _, _, s = summarize(over)
    assert s["lat_work_queue_time"] > 0
    d = stats_mod.reference_summary(s)
    assert d["lat_work_queue_time"] > 0
    # closed loop: the key is absent from the engine summary and exactly
    # 0.0 on the reference line (the pre-traffic hardwired contract)
    _, _, s0 = summarize(Config(**BASE))
    assert "lat_work_queue_time" not in s0
    d0 = stats_mod.reference_summary(s0)
    assert d0["lat_work_queue_time"] == 0.0


# (The per-plugin closed-loop purity cell that used to live here —
# arrival=None adds zero carry arrays and zero summary keys for all 7
# plugins — is now proven statically by the tick certifier's
# OFFPATH-IMPURE rule over the full config matrix; see
# deneva_tpu/lint/certify.py and LINT.md engine 3.  The runtime
# off-path sentinel for engine 1 lives in test_flight.py.)


def test_family_latency_rings_multi_family():
    # TPC-C carries two live txn families (workloads/tpcc.py program
    # ids: Payment=1, NewOrder=2; id 0 is unused): each gets its own
    # percentile ring and [summary] keys, and the empty family reports
    # zero samples rather than poisoning the percentiles
    cfg = Config(workload="TPCC", cc_alg="NO_WAIT", batch_size=64,
                 num_wh=4, cust_per_dist=1000, max_items=128,
                 query_pool_size=1 << 10, warmup_ticks=0,
                 synth_table_size=8, arrival="poisson", arrival_rate=8.0)
    eng, st, s = summarize(cfg, n_ticks=50)
    assert s["famlat1_n"] > 0 and s["famlat2_n"] > 0
    assert s["famlat0_n"] == 0 and s["famlat0_p99"] == 0.0
    assert s["famlat0_n"] + s["famlat1_n"] + s["famlat2_n"] == s["txn_cnt"]
    for f in (1, 2):
        assert s[f"famlat{f}_p50"] <= s[f"famlat{f}_p95"] \
            <= s[f"famlat{f}_p99"]
    line = eng.summary_line(st)
    parsed = stats_mod.parse_summary(line)
    for k in ("famlat0_n", "famlat1_p50", "famlat2_p99", "famlat2_n"):
        assert k in parsed, k


def test_queue_depth_trace_and_chrome_track(tmp_path):
    import json
    cfg = Config(arrival="poisson", arrival_rate=40.0, trace_ticks=64,
                 **BASE)
    eng, st, s = summarize(cfg, n_ticks=40)
    tl = obs_trace.timeline(st)
    assert "queue_depth" in tl
    # ring sum == the UNGATED backlog integral (warmup_ticks == 0 here,
    # so it equals the measured lat_work_queue_time integral exactly)
    assert obs_trace.totals(st)["queue_depth"] == \
        int(s["lat_work_queue_time"])
    p = tmp_path / "tr.json"
    obs_trace.to_chrome_trace(st, str(p))
    doc = json.loads(p.read_text())
    assert doc["metadata"].get("queue_track") is True
    assert any(ev.get("name") == "admission queue"
               for ev in doc["traceEvents"])
    # closed loop: no queue series, no counter track, no metadata flag
    cfg0 = Config(trace_ticks=64, **BASE)
    eng0 = Engine(cfg0)
    st0 = eng0.run(10)
    assert "queue_depth" not in obs_trace.timeline(st0)
    p0 = tmp_path / "tr0.json"
    obs_trace.to_chrome_trace(st0, str(p0))
    assert "queue_track" not in json.loads(p0.read_text())["metadata"]


def test_zero_steady_recompiles_across_rate_step():
    cfg = Config(arrival="step",
                 arrival_schedule=((0, 2.0), (15, 40.0), (30, 2.0)),
                 xmeter=True, **BASE)
    eng = Engine(cfg)
    st = eng.run(10)
    eng.xmeter.mark_warm()
    eng.run(30, state=st)          # crosses both rate steps post-warm
    assert eng.xmeter.steady_violations() == []


def test_arrival_config_validation():
    with pytest.raises(AssertionError):
        Config(arrival="bogus", **BASE)
    with pytest.raises(AssertionError):
        Config(arrival="poisson", **BASE)            # rate required
    with pytest.raises(AssertionError):
        Config(arrival="step", **BASE)               # schedule required
    with pytest.raises(AssertionError):
        Config(arrival="step",
               arrival_schedule=((10, 2.0), (5, 4.0)), **BASE)  # ordering
    with pytest.raises(AssertionError):
        Config(arrival="mmpp", arrival_rate=2.0, **BASE)  # burst required


@pytest.mark.slow  # sharded compile cost exceeds the tier-1 budget
def test_sharded_arrival_conservation_and_decorrelation():
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(cc_alg="NO_WAIT", node_cnt=4, part_cnt=4, batch_size=32,
                 synth_table_size=1 << 10, req_per_query=2, zipf_theta=0.5,
                 query_pool_size=1 << 10, warmup_ticks=0,
                 arrival="poisson", arrival_rate=4.0)
    eng = ShardedEngine(cfg)
    st = eng.run(30)
    s = eng.summary(st)
    # cluster-wide conservation (psum'd counters)
    assert s["arrival_cnt"] == s["queue_admit_cnt"] + s["queue_len"]
    # per-node conservation AND decorrelated per-node streams
    arr = np.asarray(st.stats["arrival_cnt"])
    adm = np.asarray(st.stats["queue_admit_cnt"])
    qln = np.asarray(st.stats["queue_len"])
    assert (arr == adm + qln).all()
    assert len(set(arr.tolist())) > 1, "per-node streams correlated"
    assert s["famlat0_n"] == s["txn_cnt"]
    line = eng.summary_line(st)
    parsed = stats_mod.parse_summary(line)
    for k in TRAFFIC_KEYS + ("famlat0_p99",):
        assert k in parsed, k
