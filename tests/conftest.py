"""Test fixture: run everything on a virtual 8-device CPU platform.

This is the rebuild's analog of the reference's TPORT_TYPE=IPC local mode
(transport.cpp:132-133, experiments.py:362): multi-node behavior exercised on
a single host.  NODE_CNT>1 shardings run on 8 virtual CPU devices via
--xla_force_host_platform_device_count, per SURVEY.md §4.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"  # the session presets an axon/tpu platform

import jax  # noqa: E402

# the env var alone does not beat the preinstalled tpu plugin's priority
jax.config.update("jax_platforms", "cpu")

# NOTE: do not enable the persistent compilation cache
# (jax_compilation_cache_dir) here: on this jaxlib (0.4.37 CPU) reloading
# a cached tick executable aborts the process (native CHECK failure in
# deserialization) partway through the suite.
