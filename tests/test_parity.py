"""Abort-rate parity: batched TPU kernels vs the sequential reference
interpreter on the same query pool (the BASELINE.json north-star metric;
stats.cpp:431-456 definitions).

Thresholds are calibrated per algorithm from PARITY.md measurements with
~1.5x headroom for pool-sampling noise; CALVIN is exact (both sides
deterministic and abort-free).  MVCC and MAAT get the most headroom (the
bounded version ring, and the live-set approximation of access-time set
snapshots, respectively).
"""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.oracle.parity import run_pair

CFG = dict(batch_size=256, synth_table_size=1 << 16, req_per_query=10,
           query_pool_size=1 << 12, zipf_theta=0.6, tup_read_perc=0.5,
           warmup_ticks=0)

# thresholds = PARITY.md measured divergence x ~1.5 noise headroom
# (tightened round 4: the oracle's joint slot-order ts draws + deferred
# N-node releases removed most systematic gaps; round 5: the MaaT
# access-order-aware commit chain brought MAAT under 1% mean — measured
# +0.0004+-0.0016 at zipf 0.6, +0.0033+-0.0059 at 0.9 with W=64 —
# so MAAT is now held to 1%, same order as the other refined cells)
THRESH = {
    "NO_WAIT": 0.02, "WAIT_DIE": 0.015, "TIMESTAMP": 0.008, "MVCC": 0.02,
    "OCC": 0.005, "MAAT": 0.01, "CALVIN": 0.0,
}

# per-algorithm refinement knobs the published PARITY.md cells use
# (single source: oracle/parity.py)
from deneva_tpu.oracle.parity import PARITY_EXTRA as EXTRA  # noqa: E402

# MaaT's per-access validation-range engine is by far the costliest
# compile in the matrix (~29 s for one parity cell on the tier-1 box);
# its parity cells ride the slow lane to keep tier-1 inside the 870 s
# budget — the other six plugins stay tier-1 here, and MAAT keeps its
# tier-1 correctness coverage in tests/test_maat.py
_SLOW_MAAT = pytest.param("MAAT", marks=pytest.mark.slow)


@pytest.mark.parametrize("alg", [_SLOW_MAAT if a == "MAAT" else a
                                 for a in THRESH])
def test_abort_rate_parity(alg):
    r = run_pair(Config(cc_alg=alg, **EXTRA.get(alg, {}), **CFG),
                 n_ticks=50)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= THRESH[alg], r
    # throughput should track closely too (not a hard target; generous)
    assert 0.8 <= r["tput_ratio"] <= 1.25, r


def test_timestamp_subticked_parity():
    """TIMESTAMP's sub-round path (pending-prewrite withdrawal visible to
    later groups) holds parity at high skew and conserves writes."""
    r = run_pair(Config(cc_alg="TIMESTAMP", sub_ticks=8,
                        **{**CFG, "zipf_theta": 0.9}), n_ticks=50)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= 0.02, r


def test_tpcc_timestamp_mixed_cell_bounded():
    """The one outstanding PARITY.md cell: the mixed-length TPC-C workload
    under TIMESTAMP measures +5% +-2%; enforce it stays at that level
    (a regression past ~3 sigma fails here)."""
    cfg = Config(workload="TPCC", cc_alg="TIMESTAMP", batch_size=64,
                 num_wh=4, cust_per_dist=1000, max_items=128,
                 query_pool_size=1 << 10, warmup_ticks=0,
                 synth_table_size=8)
    r = run_pair(cfg, 50)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= 0.12, r


@pytest.mark.slow  # two 50-tick TPCC oracle pairs; tier-1 keeps the
# mixed-cell bound (test_tpcc_timestamp_mixed_cell_bounded) on this axis
def test_tpcc_pure_mix_cells_exact():
    """The characterization behind PARITY.md's one outstanding cell:
    pure-Payment and pure-NewOrder TIMESTAMP cells match the oracle
    EXACTLY; only the mixed-length workload diverges."""
    for pp in (1.0, 0.0):
        cfg = Config(workload="TPCC", cc_alg="TIMESTAMP", perc_payment=pp,
                     batch_size=64, num_wh=4, cust_per_dist=1000,
                     max_items=128, query_pool_size=1 << 10,
                     warmup_ticks=0, synth_table_size=8)
        r = run_pair(cfg, 50)
        assert r["abort_rate_divergence"] == 0.0, (pp, r)


@pytest.mark.parametrize(
    "alg", ["NO_WAIT",
            # the WAIT_DIE twin costs a second ~25 s K=8 compile;
            # tier-1 keeps the NO_WAIT cell on this axis
            pytest.param("WAIT_DIE", marks=pytest.mark.slow)])
def test_subticked_parity_converges(alg):
    """With K=8 timestamp sub-rounds the 2PL kernels match the sequential
    reference to sampling noise even at zipf 0.9 (PARITY.md refinement
    table: seed-averaged mean < 0.1%)."""
    r = run_pair(Config(cc_alg=alg, sub_ticks=8,
                        **{**CFG, "zipf_theta": 0.9}), n_ticks=50)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= 0.012, r


@pytest.mark.parametrize("alg", ["NO_WAIT", "MVCC", "CALVIN"])
def test_commit_after_access_parity(alg):
    """The post-access commit ordering (Config.commit_after_access) is
    mirrored by the oracle; parity must hold in that mode too."""
    r = run_pair(Config(cc_alg=alg, commit_after_access=True, **CFG),
                 n_ticks=50)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= 0.035, r


def test_mvcc_ring_sized_parity():
    """With the version ring sized past eviction pressure the MVCC kernel
    is within noise of the unbounded-history reference."""
    r = run_pair(Config(cc_alg="MVCC", his_recycle_len=32,
                        **{**CFG, "zipf_theta": 0.9}), n_ticks=50)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= 0.03, r


def test_mvcc_tail_fold_counter_zero_with_sliced_merge():
    """With admit_cap forcing a REAL K < B*R commit-merge slice (the
    tail-fold branch is compiled), steady-state commits must never
    straddle it — the counter proves the fold bias path never fired."""
    import numpy as np
    from deneva_tpu.engine.scheduler import Engine
    cfg = Config(cc_alg="MVCC", batch_size=512, admit_cap=64,
                 synth_table_size=1 << 16, req_per_query=10,
                 query_pool_size=1 << 12, zipf_theta=0.9, warmup_ticks=0)
    # the slice must actually be smaller than the entry width, or this
    # test is vacuous
    assert max(4096, 64 * 10) < 512 * 10
    eng = Engine(cfg)
    st = eng.run(50)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert int(np.asarray(st.db["mvcc_tail_fold_cnt"])) == 0


@pytest.mark.parametrize("alg", ["NO_WAIT", "WAIT_DIE", _SLOW_MAAT, "CALVIN"])
def test_tpcc_parity(alg):
    """TPC-C pools through the same oracle: divergence at noise level
    (PARITY.md TPC-C table: seed-averaged means <= 0.1%)."""
    cfg = Config(workload="TPCC", cc_alg=alg, batch_size=64, num_wh=4,
                 cust_per_dist=1000, max_items=128, query_pool_size=1 << 10,
                 warmup_ticks=0, synth_table_size=8, **EXTRA.get(alg, {}))
    r = run_pair(cfg, 50)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= 0.02, r


PPS_THRESH = {
    # measured 3-seed means: MVCC/OCC exact, TIMESTAMP 0.3%, WAIT_DIE
    # 0.9%, NO_WAIT 1.6%, MAAT 1.5% (chain-walk read prefixes amplify
    # within-tick ordering for the lock family); x~1.5-2 headroom
    "NO_WAIT": 0.045, "WAIT_DIE": 0.03, "TIMESTAMP": 0.015,
    "MVCC": 0.005, "OCC": 0.005, "MAAT": 0.06,
}


@pytest.mark.parametrize("alg", [_SLOW_MAAT if a == "MAAT" else a
                                 for a in PPS_THRESH])
def test_pps_parity(alg):
    """PPS pools (8-type mix, USES/SUPPLIES chain walks) through the same
    oracle — the workload's long read chains and PART_AMOUNT writes."""
    cfg = Config(workload="PPS", cc_alg=alg, batch_size=64,
                 query_pool_size=1 << 10, warmup_ticks=0,
                 synth_table_size=8, max_part_key=256,
                 max_product_key=256, max_supplier_key=256,
                 **EXTRA.get(alg, {}))
    r = run_pair(cfg, 50)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= PPS_THRESH[alg], r


def test_calvin_pps_recon_parity():
    """CALVIN+PPS: the oracle replays the recon deferral (one-epoch sleep
    + shadow read pass + epoch-slot consumption, sequencer.cpp:88-114) —
    both sides are deterministic, so parity is EXACT."""
    cfg = Config(workload="PPS", cc_alg="CALVIN", batch_size=64,
                 query_pool_size=1 << 10, warmup_ticks=0,
                 synth_table_size=8, max_part_key=256,
                 max_product_key=256, max_supplier_key=256)
    r = run_pair(cfg, 50)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] == 0.0, r
    assert r["tput_ratio"] == 1.0, r


@pytest.mark.parametrize("alg", ["NO_WAIT", _SLOW_MAAT, "CALVIN"])
def test_tpcc_rbk_parity(alg):
    """TPC-C with NewOrder rollbacks enabled (tpcc_rbk_perc > 0): the
    oracle replays the user-abort path (release like an abort, free the
    slot, no retry, no abort-rate contribution)."""
    cfg = Config(workload="TPCC", cc_alg=alg, batch_size=64, num_wh=4,
                 cust_per_dist=1000, max_items=128, query_pool_size=1 << 10,
                 warmup_ticks=0, synth_table_size=8, tpcc_rbk_perc=0.01,
                 **EXTRA.get(alg, {}))
    r = run_pair(cfg, 50)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= 0.02, r


SHARDED_THRESH = {
    # The N-node oracle replays the sharded tick protocol exactly
    # (access-before-commit phase order, next-tick release visibility,
    # per-owner OCC verdicts, joint ts-draw order, local-entry bypass;
    # round 5 adds MaaT's per-node TimeTable protocol — per-owner
    # verdicts/overlays, VALIDATED residency, commit-exchange forward
    # validation): measured divergence is 0 for six of seven algorithms
    # at 2-8 nodes and <1% mean for MAAT (was 1.3-2.5% in round 4); the
    # MAAT residual is cross-owner same-tick push invisibility.
    "NO_WAIT": 0.003, "WAIT_DIE": 0.003, "TIMESTAMP": 0.003, "MVCC": 0.003,
    "OCC": 0.02, "MAAT": 0.02, "CALVIN": 0.0,
}


# Unlocked by the shard_map compat fix (failed at the seed); the
# alg x nodes sweep runs ~80 s and exceeds the tier-1 budget -- `-m slow`.
@pytest.mark.slow
@pytest.mark.parametrize("alg", list(SHARDED_THRESH))
@pytest.mark.parametrize("nodes", [2, 8])
def test_multi_shard_abort_rate_parity(alg, nodes):
    from deneva_tpu.oracle.parity import run_pair_sharded
    cfg = Config(cc_alg=alg, node_cnt=nodes, part_cnt=nodes, batch_size=64,
                 synth_table_size=1 << 14, req_per_query=6, zipf_theta=0.6,
                 query_pool_size=1 << 12, mpr=1.0, part_per_txn=2,
                 warmup_ticks=0, **EXTRA.get(alg, {}))
    r = run_pair_sharded(cfg, 40)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= SHARDED_THRESH[alg], r
    assert 0.85 <= r["tput_ratio"] <= 1.2, r


def test_occ_high_contention_exact():
    """OCC at zipf 0.9 matches the oracle exactly (the joint ts-draw-order
    oracle fix removed the last systematic gap)."""
    r = run_pair(Config(cc_alg="OCC", **{**CFG, "zipf_theta": 0.9}),
                 n_ticks=50)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= 0.005, r


def test_calvin_identical_commit_counts():
    r = run_pair(Config(cc_alg="CALVIN", **CFG), n_ticks=50)
    assert r["batched"]["total_txn_abort_cnt"] == 0
    assert r["sequential"]["total_txn_abort_cnt"] == 0


def test_oracle_standalone_sanity():
    # The oracle itself satisfies the increment-conservation invariant
    # under contention for every algorithm.
    from deneva_tpu.oracle.sequential import SequentialEngine
    for alg in THRESH:
        cfg = Config(cc_alg=alg, batch_size=32, synth_table_size=256,
                     req_per_query=4, query_pool_size=256, zipf_theta=0.9,
                     warmup_ticks=0)
        seq = SequentialEngine(cfg).run(30)
        s = seq.summary()
        assert s["txn_cnt"] > 0, alg
        assert int(seq.data.sum()) == s["write_cnt"], alg


def test_duplicate_key_txns_terminate_and_commit():
    # A txn touching the same row twice must not self-conflict (the
    # reference validates against OTHER txns' sets) nor hang the OCC/MaaT
    # validation fixed points.
    from deneva_tpu.engine.scheduler import Engine
    from tests.test_engine_nowait import make_pool
    keys = np.array([[5, 5], [9, 9], [5, 9], [7, 8]], np.int32)
    pool = make_pool(keys, np.ones_like(keys, bool))
    for alg in ("OCC", "MAAT"):
        cfg = Config(cc_alg=alg, batch_size=4, synth_table_size=64,
                     req_per_query=2, query_pool_size=4, warmup_ticks=0)
        eng = Engine(cfg, pool=pool)
        st = eng.run(10)
        s = eng.summary(st)
        assert s["txn_cnt"] > 0, alg
        assert np.asarray(st.data).sum() == s["write_cnt"], alg
