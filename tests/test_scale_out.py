"""Scale-out tests: the capacity-bounded epoch-split exchange
(Config.exchange_split) and remote-grant stickiness
(Config.remote_cache), parallel/sharded.py.

The split exchange replaces CALVIN's worst-case single-round buffer
(cap = B*R, whose owner-side width N*B*R must fit the packed
arbitration sort index — a hard 2^23 cluster-growth ceiling) with
trace-time-static sub-rounds of at most ``cap`` entries per
destination: held entries structurally always ship (delay, never
drop), so the guard disappears on the split path and 16-64 node
clusters construct.  The covering contract is bit-parity: on any
config both exchanges must produce the identical schedule, data array
included.  Remote-grant stickiness suppresses re-shipping decided
entries after an abort; every suppression must be visible in the
attempt counters (attempts == shipped + suppressed).

The mesh-identity and stats-line legs live in tests/test_mesh.py and
tests/test_stats.py; this file pins the sizing math, the 4-node oracle
parity, trait gating, the inverted regression gate, and — in a
subprocess with a 16-device platform — that the previously-raising
16-node CALVIN shape now constructs and dry-runs.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from deneva_tpu import cc as cc_registry
from deneva_tpu.config import Config
from deneva_tpu.parallel.sharded import ShardedEngine, exchange_capacity

BASE = dict(cc_alg="CALVIN", node_cnt=4, part_cnt=4, batch_size=32,
            synth_table_size=1 << 10, query_pool_size=256,
            req_per_query=4, warmup_ticks=2)


def test_exchange_capacity_guard_names_the_split_flag():
    """The CALVIN worst-case guard still fires without the split — and
    its remediation text now points at exchange_split; with the split
    the same shape gets a bounded capacity instead of an error."""
    plugin = cc_registry.get("CALVIN")
    big = Config(cc_alg="CALVIN", node_cnt=16, part_cnt=16,
                 batch_size=8192, req_per_query=128,
                 synth_table_size=1 << 16)
    with pytest.raises(ValueError, match="exchange_split"):
        exchange_capacity(big, plugin, 8192, 128)      # 2^24 > 2^23
    cap = exchange_capacity(
        Config(cc_alg="CALVIN", node_cnt=16, part_cnt=16,
               batch_size=8192, req_per_query=128,
               synth_table_size=1 << 16, exchange_split=True),
        plugin, 8192, 128)
    assert 0 < cap < 8192 * 128
    # standard (abort-capable) plugins never hit the guard
    assert exchange_capacity(big, cc_registry.get("MAAT"), 8192, 128) \
        < 8192 * 128


def test_split_capacity_is_bounded_not_worst_case():
    """Under the split the capacity follows route_capacity_factor, not
    the B*R worst case — the whole point of the sub-rounds."""
    plugin = cc_registry.get("CALVIN")
    cfg = Config(**{**BASE, "exchange_split": True,
                    "route_capacity_factor": 0.25})
    assert exchange_capacity(cfg, plugin, 32, 4) < 32 * 4
    assert exchange_capacity(Config(**BASE), plugin, 32, 4) == 32 * 4


def test_split_exchange_bit_parity_on_oracle_cell():
    """The 4-node CALVIN oracle cell: every summary counter AND the
    row-version data array must be bit-identical between the
    single-round exchange and the split exchange at a capacity small
    enough to force many sub-rounds per epoch."""
    e0 = ShardedEngine(Config(**BASE))
    e1 = ShardedEngine(Config(**{**BASE, "exchange_split": True,
                                 "route_capacity_factor": 0.25}))
    assert e1.cap < e0.cap
    s0, s1 = e0.run(20), e1.run(20)
    a, b = e0.summary(s0), e1.summary(s1)
    assert set(b) - set(a) == {"exchange_round_cnt"}
    assert b["exchange_round_cnt"] > 20      # multiple sub-rounds/tick
    for k in a:
        assert a[k] == b[k], (k, a[k], b[k])
    assert np.array_equal(np.asarray(s0.data), np.asarray(s1.data))


def test_flags_are_trait_gated_statically():
    """Trait-disjoint combinations stay statically OFF: exchange_split
    on an abort-capable plugin (MAAT) and remote_cache on a
    deterministic one (CALVIN) must add NO device state — the certifier
    proves the jaxpr fixed point, this pins the runtime surface."""
    on = ShardedEngine(Config(**{**BASE, "cc_alg": "MAAT",
                                 "remote_cache": True})).init_state()
    assert any(k.startswith("rc_") or k == "remote_attempt_cnt"
               for k in {**on.db, **on.stats}), \
        "MAAT + remote_cache must carry the cache planes"
    for cfg in (Config(**{**BASE, "exchange_split": True,
                          "cc_alg": "MAAT"}),
                Config(**{**BASE, "remote_cache": True})):
        st = ShardedEngine(cfg).init_state()
        assert not any(k.startswith("rc_") for k in {**st.db, **st.stats})
        assert "exchange_round_cnt" not in st.stats
        assert "remote_attempt_cnt" not in st.stats


def test_regress_gates_amplification_inverted():
    """obs/regress.py: the per-cell amplification ratio is gated as a
    CEILING — growth past (1 + tol) x median fails, a cut passes —
    while efficiency keeps its floor semantics."""
    from deneva_tpu.obs import regress as obs_regress

    def entry(amp, eff):
        return {"metric": "scaling_grid", "value": eff,
                "scaling_grid": {"MAAT@8x256": {
                    "efficiency": eff, "amplification": amp}}}

    hist = [obs_regress._entry("h", (1, i), entry(8.44, 0.24))
            for i in range(3)]
    good = obs_regress.gate(
        hist + [obs_regress._entry("cur", (1, 9), entry(3.99, 0.42))])
    assert good["failures"] == []
    bad = obs_regress.gate(
        hist + [obs_regress._entry("cur", (1, 9), entry(12.0, 0.24))])
    assert any("scaling_grid_amplification[MAAT@8x256]" in f
               for f in bad["failures"])


@pytest.mark.slow  # fresh 16-device JAX process; tier-1 budget split
def test_sixteen_node_calvin_constructs_and_dryruns():
    """Regression for the 2^23 ceiling: a 16-node CALVIN cluster — any
    shape of which the single-round exchange could only build below
    N*B*R <= 2^23 — constructs under exchange_split with a bounded
    buffer and its full sharded tick traces end-to-end.  Runs in a
    subprocess so the 16 virtual devices don't disturb the suite's
    8-device platform."""
    script = textwrap.dedent("""
        import jax
        from deneva_tpu.config import Config
        from deneva_tpu.parallel.sharded import ShardedEngine
        cfg = Config(cc_alg="CALVIN", node_cnt=16, part_cnt=16,
                     batch_size=32, synth_table_size=1 << 12,
                     req_per_query=4, query_pool_size=1 << 10,
                     warmup_ticks=0, mpr=1.0, part_per_txn=2,
                     exchange_split=True)
        eng = ShardedEngine(cfg)
        assert eng.cap < cfg.batch_size * cfg.req_per_query, eng.cap
        eng._build()
        jax.make_jaxpr(eng._tick_raw)(eng.init_state())
        print("DRYRUN_OK cap", eng.cap)
    """)
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=16"}
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=420,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    assert "DRYRUN_OK" in out.stdout
