"""TPC-C workload tests: generator statistics, end-to-end invariants under
every CC algorithm (single-shard and 8-node sharded), rbk user-abort, and
determinism.

The oracle here is TPC-C's own money/order conservation laws — the rebuild
of the reference's assertion-based testing (SURVEY.md §4): every committed
Payment moves h_amount through WAREHOUSE.W_YTD == DISTRICT.D_YTD ==
-CUSTOMER.C_BALANCE == HISTORY, and every committed NewOrder advances
D_NEXT_O_ID exactly once and appends consistent ORDER/NEW-ORDER/ORDER-LINE
rows (benchmarks/tpcc_txn.cpp:500-933 effects).
"""

import numpy as np
import pytest

from deneva_tpu.config import CC_ALGS, Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.workloads import tpcc
from deneva_tpu.workloads.tpcc import (TPCC_NEW_ORDER, TPCC_PAYMENT,
                                       TPCCWorkload)


def tpcc_cfg(**kw):
    base = dict(workload="TPCC", cc_alg="NO_WAIT", batch_size=64, num_wh=4,
                part_cnt=1, node_cnt=1, query_pool_size=1024,
                cust_per_dist=1000, max_items=128, perc_payment=0.5)
    base.update(kw)
    return Config(**base)


def checksums(cfg, tables):
    """Per-logical-column sums: packed 2-D blocks expand to their legacy
    column names (tpcc.RING_COLS) so conservation reads stay columnar."""
    out = {}
    blocks = {blk for blk, _ in tpcc.RING_COLS.values()}
    for k, v in tables.items():
        if k not in blocks:
            out[k] = int(np.asarray(v, dtype=np.int64).sum())
    for col in tpcc.RING_COLS:
        out[col] = int(np.asarray(tpcc.ring_view(tables, col),
                                  dtype=np.int64).sum())
    return out


def run_and_check(cfg, n_ticks=60):
    eng = Engine(cfg)
    st0 = eng.init_state()
    init = checksums(cfg, st0.tables)
    st = eng.run(n_ticks, st0)
    s = eng.summary(st)
    fin = checksums(cfg, st.tables)
    check_conservation(cfg, init, fin, s)
    return eng, st, s, init, fin


def check_conservation(cfg, init, fin, s):
    payments = fin["c_payment_cnt"] - init["c_payment_cnt"]
    neworders = fin["d_next_o_id"] - init["d_next_o_id"]
    assert payments + neworders == s["txn_cnt"]
    # money conservation across all four payment effects
    dw = fin["w_ytd"] - init["w_ytd"]
    dd = fin["d_ytd"] - init["d_ytd"]
    dc = -(fin["c_balance"] - init["c_balance"])
    dcy = fin["c_ytd_payment"] - init["c_ytd_payment"]
    dh = fin["h_amount"] - init["h_amount"]
    assert dd == dc == dcy == dh
    # W_YTD only moves when WH_UPDATE (run_payment_1, tpcc_txn.cpp:547-549)
    assert dw == (dd if cfg.wh_update else 0)
    assert fin["hist_cursor"] - init["hist_cursor"] == payments
    # order inserts: one ORDER + one NEW-ORDER per commit, ol_cnt lines
    assert fin["order_cursor"] - init["order_cursor"] == neworders
    assert fin["ol_cursor"] - init["ol_cursor"] == fin["o_ol_cnt"] - init["o_ol_cnt"]
    assert fin["s_order_cnt"] - init["s_order_cnt"] == \
        fin["ol_cursor"] - init["ol_cursor"]
    assert fin["s_ytd"] - init["s_ytd"] == \
        fin["ol_quantity"] - init["ol_quantity"]


# ---------------------------------------------------------------------------
# generator statistics (benchmarks/tpcc_query.cpp:149-263)
# ---------------------------------------------------------------------------

class TestGenerator:
    def test_mix_and_shapes(self):
        cfg = tpcc_cfg(query_pool_size=8192)
        pool = TPCCWorkload().gen_pool(cfg)
        is_pay = pool.txn_type == TPCC_PAYMENT
        frac = is_pay.mean()
        assert abs(frac - cfg.perc_payment) < 0.03
        assert (pool.n_req[is_pay] == 3).all()
        olc = pool.args[~is_pay, tpcc.TA_OLCNT]
        assert olc.min() >= 5 and olc.max() <= cfg.max_items_per_txn
        assert (pool.n_req[~is_pay] == 3 + 2 * olc).all()

    def test_keys_decode(self):
        cfg = tpcc_cfg(query_pool_size=2048)
        pool = TPCCWorkload().gen_pool(cfg)
        cat = tpcc.catalog(cfg)
        n = cat.rows_global
        for q in range(0, 2048, 97):
            for r in range(pool.n_req[q]):
                assert 0 <= pool.keys[q, r] < n
        # distinct keys within each txn's live prefix
        for q in range(0, 2048, 31):
            ks = pool.keys[q, :pool.n_req[q]]
            assert len(set(ks.tolist())) == len(ks)

    def test_remote_customer_fraction(self):
        cfg = tpcc_cfg(query_pool_size=16384, perc_payment=1.0, num_wh=8)
        pool = TPCCWorkload().gen_pool(cfg)
        remote = pool.args[:, tpcc.TA_CW] != pool.args[:, tpcc.TA_W]
        # reference: remote customer warehouse iff x <= 0.15
        assert abs(remote.mean() - 0.15) < 0.02

    def test_by_last_name_resolves_to_fixed_customer(self):
        cfg = tpcc_cfg(query_pool_size=4096, perc_payment=1.0)
        p1 = TPCCWorkload().gen_pool(cfg)
        p2 = TPCCWorkload().gen_pool(cfg)
        assert (p1.keys == p2.keys).all()
        assert (p1.args == p2.args).all()

    def test_warehouse_striping(self):
        cfg = tpcc_cfg(query_pool_size=4096, num_wh=8, part_cnt=4,
                       node_cnt=4)
        pool = TPCCWorkload().gen_pool(cfg)
        # FIRST_PART_LOCAL: home warehouse's part == home_part
        w = pool.args[:, tpcc.TA_W]
        assert ((w - 1) % cfg.part_cnt == pool.home_part).all()
        # warehouse access key routes to the home part
        assert (pool.keys[:, 0] % cfg.part_cnt == pool.home_part).all()


# ---------------------------------------------------------------------------
# single-shard end-to-end, all algorithms
# ---------------------------------------------------------------------------

class TestSingleShard:
    # MAAT's TPC-C chain-validate compile is the long pole (~17 s);
    # the 8-node slow sweep below still covers it — `-m slow` here too
    @pytest.mark.parametrize("alg", [pytest.param(a,
                                                  marks=pytest.mark.slow)
                                     if a == "MAAT" else a
                                     for a in CC_ALGS])
    def test_invariants(self, alg):
        cfg = tpcc_cfg(cc_alg=alg)
        eng, st, s, init, fin = run_and_check(cfg)
        assert s["txn_cnt"] > 0
        # engine-level write oracle still holds
        assert int(np.asarray(st.data).sum()) == s["write_cnt"]

    def test_o_id_unique_and_dense_per_district(self):
        cfg = tpcc_cfg(cc_alg="NO_WAIT", perc_payment=0.0)
        eng, st, s, init, fin = run_and_check(cfg)
        n = int(np.asarray(st.tables["order_cursor"]))
        assert n > 0
        o_id = np.asarray(tpcc.ring_view(st.tables, "o_id"))[:n]
        o_d = np.asarray(tpcc.ring_view(st.tables, "o_d_id"))[:n]
        o_w = np.asarray(tpcc.ring_view(st.tables, "o_w_id"))[:n]
        for (w, d) in set(zip(o_w.tolist(), o_d.tolist())):
            ids = np.sort(o_id[(o_w == w) & (o_d == d)])
            assert (np.diff(ids) == 1).all(), "o_ids not dense"
            assert ids[0] == 3001, "o_id must start at D_NEXT_O_ID init"

    def test_orderline_matches_orders(self):
        cfg = tpcc_cfg(cc_alg="WAIT_DIE", perc_payment=0.0)
        eng, st, s, init, fin = run_and_check(cfg)
        n = int(np.asarray(st.tables["order_cursor"]))
        nl = int(np.asarray(st.tables["ol_cursor"]))
        o_key = list(zip(np.asarray(tpcc.ring_view(st.tables, "o_w_id"))[:n].tolist(),
                         np.asarray(tpcc.ring_view(st.tables, "o_d_id"))[:n].tolist(),
                         np.asarray(tpcc.ring_view(st.tables, "o_id"))[:n].tolist()))
        o_cnt = np.asarray(tpcc.ring_view(st.tables, "o_ol_cnt"))[:n]
        ol_key = zip(np.asarray(tpcc.ring_view(st.tables, "ol_w_id"))[:nl].tolist(),
                     np.asarray(tpcc.ring_view(st.tables, "ol_d_id"))[:nl].tolist(),
                     np.asarray(tpcc.ring_view(st.tables, "ol_o_id"))[:nl].tolist())
        ol_num = np.asarray(tpcc.ring_view(st.tables, "ol_number"))[:nl]
        counts = {}
        for k, num in zip(ol_key, ol_num.tolist()):
            counts.setdefault(k, set()).add(num)
        for k, cnt in zip(o_key, o_cnt.tolist()):
            assert len(counts.get(k, set())) == cnt

    def test_stock_quantity_rule(self):
        # s_quantity always lands in [1+91-10, ...] window: q' >= q-10+81?
        # invariant from new_order_9: result is q-qty if q-qty > 10 else
        # q-qty+91, so s_quantity never drops below 2 given qty <= 10
        cfg = tpcc_cfg(cc_alg="TIMESTAMP", perc_payment=0.0)
        eng, st, s, init, fin = run_and_check(cfg, n_ticks=120)
        q = np.asarray(st.tables["s_quantity"])
        assert q.min() >= 2

    def test_rbk_user_abort(self):
        cfg = tpcc_cfg(cc_alg="NO_WAIT", perc_payment=0.0, tpcc_rbk_perc=1.0)
        eng = Engine(cfg)
        st0 = eng.init_state()
        init = checksums(cfg, st0.tables)
        st = eng.run(40, st0)
        s = eng.summary(st)
        fin = checksums(cfg, st.tables)
        assert s["txn_cnt"] == 0
        assert s["user_abort_cnt"] > 0
        assert fin["d_next_o_id"] == init["d_next_o_id"]
        assert fin["order_cursor"] == init["order_cursor"]
        assert int(np.asarray(st.data).sum()) == 0

    def test_wh_update_false_reads_warehouse(self):
        cfg = tpcc_cfg(cc_alg="NO_WAIT", perc_payment=1.0, wh_update=False)
        eng, st, s, init, fin = run_and_check(cfg)
        assert fin["w_ytd"] == init["w_ytd"]   # warehouse never written
        assert s["txn_cnt"] > 0
        # with the hottest write gone, payments mostly conflict on customer
        # rows only; throughput must beat the wh_update=True cell
        cfg2 = tpcc_cfg(cc_alg="NO_WAIT", perc_payment=1.0, wh_update=True)
        _, _, s2, _, _ = run_and_check(cfg2)
        assert s["txn_cnt"] > s2["txn_cnt"]

    def test_determinism(self):
        cfg = tpcc_cfg(cc_alg="MVCC")
        eng1 = Engine(cfg)
        st1 = eng1.run(40)
        eng2 = Engine(cfg)
        st2 = eng2.run(40)
        for k in st1.tables:
            assert (np.asarray(st1.tables[k]) == np.asarray(st2.tables[k])).all(), k
        assert eng1.summary(st1)["txn_cnt"] == eng2.summary(st2)["txn_cnt"]


# ---------------------------------------------------------------------------
# sharded end-to-end (8 virtual CPU devices, conftest.py)
# ---------------------------------------------------------------------------

# Unlocked by the shard_map compat fix (collection error at the seed);
# ~100 s of 8-node TPC-C exceeds the tier-1 time budget -- `-m slow`.
@pytest.mark.slow
class TestSharded:
    @pytest.mark.parametrize("alg", ["NO_WAIT", "WAIT_DIE", "TIMESTAMP",
                                     "MVCC", "OCC", "MAAT", "CALVIN"])
    def test_invariants_8node(self, alg):
        from deneva_tpu.parallel.sharded import ShardedEngine
        cfg = tpcc_cfg(cc_alg=alg, node_cnt=8, part_cnt=8, num_wh=8,
                       batch_size=16, query_pool_size=512, max_items=64)
        eng = ShardedEngine(cfg)
        st0 = eng.init_state()
        init = checksums(cfg, st0.tables)
        st = eng.run(40, st0)
        s = eng.summary(st)
        fin = checksums(cfg, st.tables)
        assert s["txn_cnt"] > 0
        check_conservation(cfg, init, fin, s)
        assert eng.global_data_sum(st) == s["write_cnt"]

    def test_remote_effects_cross_shards(self):
        """Remote-customer payments must move money on OTHER shards: per-
        shard W_YTD delta (home side) and C_BALANCE delta (customer side)
        disagree per shard but balance globally."""
        from deneva_tpu.parallel.sharded import ShardedEngine
        cfg = tpcc_cfg(cc_alg="WAIT_DIE", node_cnt=4, part_cnt=4, num_wh=8,
                       batch_size=32, query_pool_size=2048, perc_payment=1.0,
                       max_items=64)
        eng = ShardedEngine(cfg)
        st0 = eng.init_state()
        st = eng.run(60, st0)
        s = eng.summary(st)
        assert s["txn_cnt"] > 0
        assert s["remote_entry_cnt"] > 0
        dw = np.asarray(st.tables["w_ytd"]).sum(axis=1) - 300000 * 2
        dc = -(np.asarray(tpcc.ring_view(st.tables, "c_balance"), dtype=np.int64).sum(axis=1)
               - (-10) * 2 * cfg.dist_per_wh * cfg.cust_per_dist)
        assert dw.sum() == dc.sum()
        hist = np.asarray(st.tables["hist_cursor"])
        # history rows land on the CUSTOMER's shard, so some shard must
        # differ between home-side and customer-side counts eventually
        assert hist.sum() == s["txn_cnt"]

    def test_calvin_deterministic_across_runs(self):
        from deneva_tpu.parallel.sharded import ShardedEngine
        cfg = tpcc_cfg(cc_alg="CALVIN", node_cnt=4, part_cnt=4, num_wh=4,
                       batch_size=16, query_pool_size=512, max_items=64)
        e1 = ShardedEngine(cfg)
        s1 = e1.run(30)
        e2 = ShardedEngine(cfg)
        s2 = e2.run(30)
        for k in s1.tables:
            assert (np.asarray(s1.tables[k]) == np.asarray(s2.tables[k])).all(), k


def test_apply_commit_entries_compact_equals_full():
    """The K-lane compacted commit-effects path (apply_commit_entries) must
    produce tables identical to the full-width body — the suite's normal
    shapes short-circuit to the full body, so force compaction here
    (n > K via a small admit_cap and a wide synthetic entry array)."""
    import jax.numpy as jnp
    from deneva_tpu.workloads import get as get_wl

    cfg = tpcc_cfg(batch_size=512, admit_cap=16, num_wh=8)
    wl = get_wl(cfg)
    tables = wl.init_tables(cfg, 0)
    rng = np.random.default_rng(7)

    # synthetic effect entries spanning every role, with duplicates on
    # stock/district rows; n chosen above K = max(16384, 2*16*34) = 16384
    from deneva_tpu.workloads.tpcc import (ROLE_C_PAY, ROLE_D_NO,
                                           ROLE_D_PAY, ROLE_NONE,
                                           ROLE_S_NO, ROLE_W_PAY, catalog)
    cat = catalog(cfg)
    n = 17000
    assert n > 16384
    roles = rng.choice([ROLE_NONE, ROLE_W_PAY, ROLE_D_PAY, ROLE_C_PAY,
                        ROLE_D_NO, ROLE_S_NO], size=n).astype(np.int32)
    key = np.zeros(n, np.int32)
    for role, tab in ((ROLE_W_PAY, "WAREHOUSE"), (ROLE_D_PAY, "DISTRICT"),
                      (ROLE_C_PAY, "CUSTOMER"), (ROLE_D_NO, "DISTRICT"),
                      (ROLE_S_NO, "STOCK")):
        m = roles == role
        ti = cat.tables[tab]
        key[m] = ti.base + rng.integers(0, ti.n_local, int(m.sum()))
    dw = rng.integers(0, 10, n).astype(np.int32) \
        | (rng.integers(0, 8, n).astype(np.int32) << 4)
    role_f = np.where(roles != ROLE_NONE, roles | (dw << 3), 0).astype(
        np.int32)
    earg = rng.integers(0, 1 << 10, n).astype(np.int32)
    earg2 = rng.integers(0, 1 << 10, n).astype(np.int32)
    cts = rng.permutation(n).astype(np.int32) + 1
    live = roles != ROLE_NONE

    fields = {"role": jnp.asarray(role_f), "earg": jnp.asarray(earg),
              "earg2": jnp.asarray(earg2)}
    out_compact = wl.apply_commit_entries(
        cfg, tables, jnp.asarray(key), 0, fields, jnp.asarray(cts),
        jnp.asarray(live))
    out_full = wl._apply_entries_body(
        cfg, tables, jnp.asarray(key), 0, fields["role"], fields["earg"],
        fields["earg2"], jnp.asarray(cts), jnp.asarray(live))
    for k in out_full:
        assert (np.asarray(out_compact[k]) == np.asarray(out_full[k])).all(), k
