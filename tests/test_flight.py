"""Transaction flight recorder tests (deneva_tpu/obs/flight.py).

The recorder is an accounting identity, not an estimate — in
full-sampling mode (every completed txn keeps its span) the summed span
phases must reconcile EXACTLY against the engine's ``lat_*`` latency
integrals and the event histogram against the ``abort_*_cnt`` taxonomy
counters, for every CC plugin.  The off path (``Config.flight=False``,
the default) must carry zero extra device arrays and leave the
``[summary]`` line byte-identical; the on path must hold the zero
post-warmup recompile sentinel.
"""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.obs import flight as obs_flight
from deneva_tpu.obs import trace as obs_trace

BASE = dict(batch_size=64, synth_table_size=1 << 10, req_per_query=4,
            zipf_theta=0.8, query_pool_size=1 << 10, warmup_ticks=0)

ALGS = ["NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT",
        "CALVIN"]

#: the exact device-array surface the recorder adds (keep in sync with
#: obs/flight.py init_flight — the off-path purity test asserts the set)
FLIGHT_STATS_KEYS = {
    "arr_flight_admit", "arr_flight_facq", "arr_flight_span",
    "arr_flight_ev", "flight_span_cnt", "flight_ev_cnt",
    "arr_flight_queue", "arr_flight_proc", "arr_flight_block",
    "arr_flight_backoff", "arr_flight_net",
}


def flight_cfg(**kw):
    base = dict(cc_alg="NO_WAIT", flight=True, abort_attribution=True,
                flight_samples=1 << 14, **BASE)
    base.update(kw)
    return Config(**base)


def run(cfg, n_ticks=50):
    eng = Engine(cfg)
    st = eng.run(n_ticks)
    return eng, st, eng.summary(st)


# the MAAT cell recompiles the chain-validate and alone costs ~10 s —
# `-m slow` per the tier-1 870 s budget split (MAAT reconciliation is
# still covered tier-1 by the taxonomy/parity canonical cells)
@pytest.mark.parametrize("alg", ["NO_WAIT", "WAIT_DIE", "TIMESTAMP",
                                 "MVCC", "OCC",
                                 pytest.param("MAAT",
                                              marks=pytest.mark.slow),
                                 "CALVIN"])
def test_full_sampling_reconciles_exactly(alg):
    """Σ span phases == lat_* integrals, event hist == abort_*_cnt, and
    every completed txn kept its span — for every CC plugin."""
    _, st, s = run(flight_cfg(cc_alg=alg))
    snap = obs_flight.snapshot(st)
    assert snap["span_cnt"] > 0
    assert obs_flight.reconcile(snap, s) == []
    assert snap["span_cnt"] == s["txn_cnt"] + s["user_abort_cnt"]
    # user-abort spans are tagged (kind=1), commits kind=0
    kinds = {d["kind"] for d in snap["spans"]}
    assert kinds <= {0, 1}
    assert sum(d["kind"] for d in snap["spans"]) == s["user_abort_cnt"]


def test_reconciles_with_warmup():
    """The phase gate mirrors track_state_latencies' warmup gate, so the
    identity holds for ANY warmup (events filtered host-side by tick)."""
    _, st, s = run(flight_cfg(warmup_ticks=15), n_ticks=60)
    snap = obs_flight.snapshot(st)
    assert obs_flight.reconcile(snap, s, warmup_ticks=15) == []


def test_queue_phase_reconciles_with_arrival():
    """Open-system runs: Σ span.queue (+ open spans + the still-queued
    residual) == the Little's-law lat_work_queue_time integral."""
    cfg = flight_cfg(arrival="poisson", arrival_rate=20.0)
    n_ticks = 80
    _, st, s = run(cfg, n_ticks=n_ticks)
    snap = obs_flight.snapshot(st)
    assert snap["qdrop_cnt"] == 0
    # residual: wait already integrated for clients still queued at end
    ring = np.asarray(st.stats["arr_flight_qring"])
    qcap = ring.shape[0]
    head, tail = int(s["queue_admit_cnt"]), int(s["arrival_cnt"])
    s["flight_queue_residual"] = sum(
        int(n_ticks - ring[k % qcap]) for k in range(head, tail))
    assert obs_flight.reconcile(snap, s) == []
    assert sum(d["queue"] for d in snap["spans"]) > 0


def test_sampled_mode_keeps_last_window():
    """An undersized ring degrades to a sliding window over the MOST
    RECENT completions — the sampled spans are exactly the tail of the
    full-sampling run's span list (same seed, same schedule)."""
    S = 8
    _, st_full, _ = run(flight_cfg())
    _, st_small, _ = run(flight_cfg(flight_samples=S))
    full = obs_flight.snapshot(st_full)
    small = obs_flight.snapshot(st_small)
    assert not full["span_wrapped"]
    assert small["span_wrapped"]
    assert small["span_cnt"] == full["span_cnt"]    # count still exact
    assert len(small["spans"]) == S
    assert small["spans"] == full["spans"][-S:]
    # reconcile refuses wrapped rings instead of silently passing
    bad = obs_flight.reconcile(small, {})
    assert ("span_ring_wrapped", small["span_cnt"], S) in bad


# Single runtime sentinel.  Per-plugin off-path byte-identity is now
# proven statically for every cell by the tick certifier's OFFPATH-IMPURE
# rule (deneva_tpu/lint/certify.py, LINT.md engine 3); this one cell
# remains to pin the runtime surface (stats keys, summary line) that the
# jaxpr-level proof does not cover.
@pytest.mark.parametrize("alg", ["NO_WAIT"])
def test_flight_off_is_byte_identical_and_carries_nothing(alg):
    """flight=False (default): zero extra device arrays, zero summary
    keys; flight=True adds EXACTLY the documented surface."""
    off_cfg = Config(cc_alg=alg, abort_attribution=True, **BASE)
    eng_off, st_off, s_off = run(off_cfg, n_ticks=20)
    assert not any("flight" in k for k in st_off.stats)
    line = eng_off.summary_line(st_off)
    assert "flight" not in line

    def engine_bytes(ln):
        # everything on the line except the host-process utilization keys
        # (mem_util/cpu_util move with the test harness, not the engine)
        return ",".join(p for p in ln.split(",")
                        if not p.startswith(("mem_util=", "cpu_util=")))

    # rerunning the identical config reproduces the line byte for byte
    eng2, st2, _ = run(off_cfg, n_ticks=20)
    assert engine_bytes(eng2.summary_line(st2)) == engine_bytes(line)

    _, st_on, s_on = run(flight_cfg(cc_alg=alg), n_ticks=20)
    extra = set(st_on.stats) - set(st_off.stats)
    assert extra == FLIGHT_STATS_KEYS
    # the schedule itself is untouched — same commits, same aborts
    for k in ("txn_cnt", "total_txn_abort_cnt", "local_txn_start_cnt"):
        assert s_on[k] == s_off[k], (k, s_on[k], s_off[k])
    # summary gains only the ring fill counters (arr_ keys are skipped)
    assert set(s_on) - set(s_off) == {"flight_span_cnt", "flight_ev_cnt"}


def test_zero_steady_recompiles_with_flight_on():
    """The recorder is jit-safe carried state: no shape depends on data,
    so the xmeter sentinel must count ZERO post-warmup compiles."""
    cfg = flight_cfg(xmeter=True)
    eng = Engine(cfg)
    st = eng.run(12)
    eng.xmeter.mark_warm()
    st = eng.run(12, st)
    assert eng.xmeter.steady_violations() == []
    assert obs_flight.reconcile(obs_flight.snapshot(st),
                                eng.summary(st)) == []


def test_span_track_schema_and_tail(tmp_path):
    """Perfetto span track: one X lifecycle slice per span with nested
    attempt slices (restarts+1) and paired abort-reason flow arrows;
    to_chrome_trace merges it beside the counter tracks."""
    cfg = flight_cfg(trace_ticks=40)
    _, st, s = run(cfg, n_ticks=40)
    snap = obs_flight.snapshot(st)
    evs = obs_flight.span_events(snap)
    top = [e for e in evs if e.get("cat") == "flight"
           and not e["name"].startswith("attempt")]
    attempts = [e for e in evs if e.get("cat") == "flight"
                and e["name"].startswith("attempt")]
    assert len(top) == len(snap["spans"])
    flows_s = [e for e in evs if e.get("ph") == "s"]
    flows_f = [e for e in evs if e.get("ph") == "f"]
    assert len(flows_s) == len(flows_f)
    assert all(e["cat"] == "abort-flow" for e in flows_s + flows_f)
    # each span contributes (abort ticks inside it) + 1 attempt slices
    assert len(attempts) == len(top) + len(flows_s)
    for e in top:
        assert set(e["args"]) == {"facq", "restarts", *obs_flight._ACCS}
        assert e["ph"] == "X" and e["dur"] >= 1

    path = str(tmp_path / "tr.json")
    obs_trace.to_chrome_trace(st, path, n_ticks=40, flight=snap)
    import json
    doc = json.load(open(path))
    assert doc["metadata"]["flight_spans"] == len(snap["spans"])
    assert any(e.get("cat") == "flight" for e in doc["traceEvents"])
    assert any(e.get("name") == "txn flow" for e in doc["traceEvents"])

    tail = obs_flight.tail_attribution(snap)
    assert tail["cohort"] >= 1
    assert tail["dominant_phase"] in obs_flight._ACCS
    assert abs(sum(tail["phase_share"].values()) - 1.0) < 1e-9
    assert tail["top_reasons"], "contended cell must abort in the tail"


@pytest.mark.slow  # sharded compile cost exceeds the tier-1 budget
@pytest.mark.parametrize("dly", [0, 2])
def test_sharded_node_merge_reconciles(dly):
    """Cluster runs: per-node rings merge on one tick clock, spans carry
    their node id, and the net phase reconciles against the cluster
    lat_network_time in BOTH delay modes.  net_delay mode additionally
    un-hardwires lat_msg_queue_time (the per-message transit integral)."""
    from deneva_tpu import stats as stats_mod
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(cc_alg="NO_WAIT", node_cnt=2, part_cnt=2,
                 net_delay_ticks=dly, flight=True, abort_attribution=True,
                 flight_samples=1 << 14,
                 **{**BASE, "batch_size": 32, "zipf_theta": 0.6})
    eng = ShardedEngine(cfg)
    st = eng.run(60)
    s = eng.summary(st)
    snap = obs_flight.snapshot(st)
    assert snap["nodes"] == 2
    assert obs_flight.reconcile(snap, s) == []
    assert {d["node"] for d in snap["spans"]} == {0, 1}
    assert sum(d["net"] for d in snap["spans"]
               + snap["open_spans"]) == s["lat_network_time"]
    d = stats_mod.reference_summary(s)
    if dly:
        assert s["lat_msg_queue_time"] > 0
        assert d["lat_msg_queue_time"] == s["lat_msg_queue_time"]
    else:
        assert "lat_msg_queue_time" not in s
        assert d["lat_msg_queue_time"] == 0.0


def test_msg_queue_time_stays_zero_single_shard():
    """Satellite contract: single-shard engines carry NO
    lat_msg_queue_time key and the reference line prints exactly 0.0."""
    from deneva_tpu import stats as stats_mod
    _, st, s = run(flight_cfg(), n_ticks=20)
    assert "lat_msg_queue_time" not in s
    assert "lat_msg_queue_time" not in st.stats
    d = stats_mod.reference_summary(s)
    assert d["lat_msg_queue_time"] == 0.0
