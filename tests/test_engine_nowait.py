"""End-to-end NO_WAIT engine tests: golden micro-schedules + invariants."""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.workloads.base import QueryPool


def make_pool(keys, is_write, n_req=None):
    keys = np.asarray(keys, np.int32)
    is_write = np.asarray(is_write, bool)
    Q, R = keys.shape
    if n_req is None:
        n_req = np.full(Q, R, np.int32)
    return QueryPool(
        keys=keys, is_write=is_write,
        n_req=np.asarray(n_req, np.int32),
        home_part=np.zeros(Q, np.int32),
        txn_type=np.zeros(Q, np.int32),
        args=np.zeros((Q, 1), np.int32),
    )


def small_cfg(**kw):
    base = dict(batch_size=4, synth_table_size=64, req_per_query=2,
                query_pool_size=4, abort_penalty_ticks=1, backoff=False,
                warmup_ticks=0, cc_alg="NO_WAIT")
    base.update(kw)
    return Config(**base)


def test_conflict_free_txns_all_commit():
    # 4 txns, disjoint keys: everyone proceeds in lockstep, commits after
    # R grant ticks + 1 commit tick.
    keys = np.arange(8, dtype=np.int32).reshape(4, 2)
    pool = make_pool(keys, np.ones((4, 2), bool))
    eng = Engine(small_cfg(), pool=pool)
    st = eng.run(4)  # t0: admit+first grant, t1: second grant, t2: commit
    s = eng.summary(st)
    assert s["txn_cnt"] == 4
    assert s["total_txn_abort_cnt"] == 0
    # increment oracle: every committed write applied exactly once
    assert np.asarray(st.data).sum() == s["write_cnt"] == 8


def test_ww_conflict_younger_aborts():
    # txn0 and txn1 both write key 5 as their FIRST access; txn0 is admitted
    # with the smaller ts => wins; txn1 must abort (NO_WAIT conflict rule).
    keys = np.array([[5, 1], [5, 2], [10, 11], [12, 13]], np.int32)
    pool = make_pool(keys, np.ones((4, 2), bool))
    eng = Engine(small_cfg(), pool=pool)
    st = eng.run(1)
    txn = st.txn
    # slot 0 granted (cursor 1), slot 1 aborted (backoff)
    assert int(txn.cursor[0]) == 1
    assert int(txn.status[1]) == 3  # STATUS_BACKOFF
    assert int(txn.restarts[1]) == 1


def test_rr_share_no_conflict():
    # both txns READ key 5: shared lock, both proceed.
    keys = np.array([[5, 1], [5, 2], [10, 11], [12, 13]], np.int32)
    pool = make_pool(keys, np.zeros((4, 2), bool))
    eng = Engine(small_cfg(), pool=pool)
    st = eng.run(1)
    assert int(st.txn.cursor[0]) == 1
    assert int(st.txn.cursor[1]) == 1


def test_rw_conflict_aborts_writer():
    # txn0 reads key 5 (smaller ts), txn1 writes key 5 => writer aborts.
    keys = np.array([[5, 1], [5, 2], [10, 11], [12, 13]], np.int32)
    iw = np.array([[False, False], [True, True], [False, False], [False, False]])
    pool = make_pool(keys, iw)
    eng = Engine(small_cfg(), pool=pool)
    st = eng.run(1)
    assert int(st.txn.cursor[0]) == 1
    assert int(st.txn.status[1]) == 3


def test_aborted_txn_retries_and_commits():
    # Two writers on the same key; loser backs off, retries once the winner
    # committed, then commits.  Query pool has only these two txns (B=2).
    keys = np.array([[5, 1], [5, 2]], np.int32)
    pool = make_pool(keys, np.ones((2, 2), bool))
    cfg = small_cfg(batch_size=2, query_pool_size=2)
    eng = Engine(cfg, pool=pool)
    st = eng.run(12)
    s = eng.summary(st)
    assert s["txn_cnt"] >= 4          # both slots keep committing (pool wraps)
    assert s["total_txn_abort_cnt"] >= 1
    # serializability oracle: data increments == committed writes
    assert np.asarray(st.data).sum() == s["write_cnt"]


@pytest.mark.parametrize("theta", [0.0, 0.9])
def test_increment_oracle_under_contention(theta):
    cfg = Config(batch_size=64, synth_table_size=256, req_per_query=4,
                 query_pool_size=512, zipf_theta=theta, tup_read_perc=0.5,
                 cc_alg="NO_WAIT", warmup_ticks=0)
    eng = Engine(cfg)
    st = eng.run(40)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert np.asarray(st.data).sum() == s["write_cnt"]
    if theta == 0.9:
        assert s["total_txn_abort_cnt"] > 0  # hot keys must conflict


def test_read_only_never_aborts():
    cfg = Config(batch_size=32, synth_table_size=256, req_per_query=4,
                 query_pool_size=256, zipf_theta=0.9, txn_read_perc=1.0,
                 cc_alg="NO_WAIT", warmup_ticks=0)
    eng = Engine(cfg)
    st = eng.run(30)
    s = eng.summary(st)
    assert s["total_txn_abort_cnt"] == 0
    assert s["txn_cnt"] > 0
    assert np.asarray(st.data).sum() == 0


def test_warmup_gates_stats():
    cfg = Config(batch_size=16, synth_table_size=256, req_per_query=2,
                 query_pool_size=64, cc_alg="NO_WAIT", warmup_ticks=10)
    eng = Engine(cfg)
    st = eng.run(10)
    assert eng.summary(st)["txn_cnt"] == 0  # still warming up
    st = eng.run(20, st)
    assert eng.summary(st)["txn_cnt"] > 0


def test_run_compiled_matches_run():
    cfg = Config(batch_size=32, synth_table_size=512, req_per_query=3,
                 query_pool_size=128, zipf_theta=0.6, cc_alg="NO_WAIT")
    eng = Engine(cfg)
    s1 = eng.summary(eng.run(25))
    s2 = eng.summary(eng.run_compiled(25))
    assert s1 == s2
