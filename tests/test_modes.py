"""Debug mode ladder tests (reference MODE, config.h:314-319): each mode
strips one more layer — NOCC disables CC, QRY_ONLY also skips row writes,
SIMPLE acks at admission — so comparing adjacent rungs isolates where
throughput goes (the reference's bottleneck-hunting methodology,
SURVEY.md §4.4)."""

import numpy as np
import pytest

from deneva_tpu.config import CC_ALGS, Config
from deneva_tpu.engine.scheduler import Engine


def run(mode, alg="NO_WAIT", ticks=40, **kw):
    base = dict(cc_alg=alg, mode=mode, batch_size=128,
                synth_table_size=1 << 10, req_per_query=4, zipf_theta=0.9,
                query_pool_size=1 << 10)
    base.update(kw)
    eng = Engine(Config(**base))
    st = eng.run(ticks)
    return eng.summary(st), st


@pytest.mark.parametrize("alg", CC_ALGS)
def test_nocc_never_aborts(alg):
    s, st = run("NOCC", alg=alg)
    assert s["total_txn_abort_cnt"] == 0
    assert s["txn_cnt"] > 0
    # writes still applied in NOCC (row.cpp:199 returns the real row)
    assert int(np.asarray(st.data).sum()) == s["write_cnt"]


def test_qry_only_applies_no_writes():
    s, st = run("QRY_ONLY")
    assert s["txn_cnt"] > 0
    assert int(np.asarray(st.data).sum()) == 0


def test_simple_commits_without_executing():
    s, st = run("SIMPLE")
    assert s["txn_cnt"] > 0
    assert int(np.asarray(st.data).sum()) == 0
    # acked immediately: one tick of latency for every txn
    assert s["avg_latency_ticks_short"] <= 1.0


def test_ladder_orders_throughput():
    """Each stripped layer can only help: NORMAL <= NOCC <= SIMPLE commits
    under contention (the diagnostic signal the ladder exists for)."""
    n0, _ = run("NORMAL")
    n1, _ = run("NOCC")
    n3, _ = run("SIMPLE")
    assert n0["txn_cnt"] <= n1["txn_cnt"] <= n3["txn_cnt"]


def test_nocc_matches_nolock_isolation():
    """MODE NOCC and isolation NOLOCK disable CC through different gates
    (mode ladder vs isolation level) and must agree for 2PL."""
    a, _ = run("NOCC")
    b, _ = run("NORMAL", isolation_level="NOLOCK")
    assert a["txn_cnt"] == b["txn_cnt"]
    assert a["write_cnt"] == b["write_cnt"]


# ---------------------------------------------------------------------------
# invariant-check kernel (DEBUG_ASSERT/DEBUG_RACE analog, engine/debug.py)
# ---------------------------------------------------------------------------

# the MAAT cell compiles the chain-validate and alone costs ~15 s —
# `-m slow` per the tier-1 870 s budget split
@pytest.mark.parametrize("alg", [
    pytest.param(a, marks=pytest.mark.slow) if a == "MAAT" else a
    for a in CC_ALGS])
def test_invariant_kernel_clean_on_healthy_runs(alg):
    s, _ = run("NORMAL", alg=alg, debug_invariants=True)
    assert s["invariant_violation_cnt"] == 0
    assert s["txn_cnt"] > 0


def test_invariant_kernel_detects_corruption():
    from deneva_tpu.engine import debug as dbg
    from deneva_tpu import cc as cc_registry
    cfg = Config(cc_alg="NO_WAIT", batch_size=64, synth_table_size=1 << 10,
                 req_per_query=4, query_pool_size=1 << 8,
                 debug_invariants=True)
    eng = Engine(cfg)
    st = eng.run(10)
    plugin = cc_registry.get("NO_WAIT")
    assert int(dbg.count_violations(cfg, plugin, st.txn)) == 0

    # duplicate timestamp between two live slots
    ts = np.asarray(st.txn.ts).copy()
    status = np.asarray(st.txn.status).copy()
    status[0] = status[1] = 1          # RUNNING
    ts[0] = ts[1] = 7777
    bad = st.txn._replace(ts=np.asarray(ts), status=np.asarray(status))
    assert int(dbg.count_violations(cfg, plugin, bad)) > 0

    # cursor past n_req on a live slot
    cur = np.asarray(st.txn.cursor).copy()
    cur[2] = int(np.asarray(st.txn.n_req)[2]) + 1
    status2 = np.asarray(st.txn.status).copy()
    status2[2] = 1
    bad2 = st.txn._replace(cursor=np.asarray(cur),
                           status=np.asarray(status2))
    assert int(dbg.count_violations(cfg, plugin, bad2)) > 0

    # two exclusive holders on one row (lock-matrix check)
    keys = np.asarray(st.txn.keys).copy()
    iw = np.asarray(st.txn.is_write).copy()
    cur3 = np.asarray(st.txn.cursor).copy()
    status3 = np.asarray(st.txn.status).copy()
    ts3 = np.asarray(st.txn.ts).copy()
    nrq = np.asarray(st.txn.n_req).copy()
    for slot, t in ((4, 1001), (5, 1002)):
        status3[slot] = 1
        keys[slot, 0] = 99
        iw[slot, 0] = True
        cur3[slot] = 1
        nrq[slot] = 4
        ts3[slot] = t
    bad3 = st.txn._replace(keys=np.asarray(keys), is_write=np.asarray(iw),
                           cursor=np.asarray(cur3),
                           status=np.asarray(status3),
                           ts=np.asarray(ts3), n_req=np.asarray(nrq))
    assert int(dbg.count_violations(cfg, plugin, bad3)) > 0


@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_invariant_kernel_clean_sharded():
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(cc_alg="NO_WAIT", node_cnt=2, part_cnt=2, batch_size=64,
                 synth_table_size=1 << 10, req_per_query=4,
                 query_pool_size=1 << 8, debug_invariants=True)
    eng = ShardedEngine(cfg)
    st = eng.run(20, eng.init_state())
    s = eng.summary(st)
    assert s["invariant_violation_cnt"] == 0
    assert s["txn_cnt"] > 0


# Unlocked by the shard_map compat fix (failed at the seed); exceeds
# the tier-1 time budget -- run with `-m slow`.
@pytest.mark.slow
def test_mode_ladder_sharded():
    """The NOCC/QRY_ONLY/SIMPLE ladder now runs through the sharded
    engine (per-node bottleneck isolation, the round-3 gap): each
    stripped layer can only help commits, and QRY_ONLY applies no
    writes."""
    import numpy as np
    from deneva_tpu.parallel.sharded import ShardedEngine
    from deneva_tpu.config import Config

    def run(mode):
        cfg = Config(cc_alg="NO_WAIT", node_cnt=4, part_cnt=4,
                     batch_size=32, synth_table_size=1 << 12,
                     req_per_query=4, zipf_theta=0.8,
                     query_pool_size=1 << 10, mpr=1.0, part_per_txn=2,
                     mode=mode)
        eng = ShardedEngine(cfg)
        st = eng.run(30)
        return eng.summary(st), eng.global_data_sum(st)

    (s_n, d_n), (s_c, d_c), (s_q, d_q), (s_s, d_s) = (
        run("NORMAL"), run("NOCC"), run("QRY_ONLY"), run("SIMPLE"))
    assert s_n["txn_cnt"] <= s_c["txn_cnt"] <= s_s["txn_cnt"]
    assert s_c["total_txn_abort_cnt"] == 0
    assert d_n == s_n["write_cnt"] and d_c == s_c["write_cnt"]
    assert d_q == 0 and d_s == 0        # no writes applied
