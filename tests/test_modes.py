"""Debug mode ladder tests (reference MODE, config.h:314-319): each mode
strips one more layer — NOCC disables CC, QRY_ONLY also skips row writes,
SIMPLE acks at admission — so comparing adjacent rungs isolates where
throughput goes (the reference's bottleneck-hunting methodology,
SURVEY.md §4.4)."""

import numpy as np
import pytest

from deneva_tpu.config import CC_ALGS, Config
from deneva_tpu.engine.scheduler import Engine


def run(mode, alg="NO_WAIT", ticks=40, **kw):
    base = dict(cc_alg=alg, mode=mode, batch_size=128,
                synth_table_size=1 << 10, req_per_query=4, zipf_theta=0.9,
                query_pool_size=1 << 10)
    base.update(kw)
    eng = Engine(Config(**base))
    st = eng.run(ticks)
    return eng.summary(st), st


@pytest.mark.parametrize("alg", CC_ALGS)
def test_nocc_never_aborts(alg):
    s, st = run("NOCC", alg=alg)
    assert s["total_txn_abort_cnt"] == 0
    assert s["txn_cnt"] > 0
    # writes still applied in NOCC (row.cpp:199 returns the real row)
    assert int(np.asarray(st.data).sum()) == s["write_cnt"]


def test_qry_only_applies_no_writes():
    s, st = run("QRY_ONLY")
    assert s["txn_cnt"] > 0
    assert int(np.asarray(st.data).sum()) == 0


def test_simple_commits_without_executing():
    s, st = run("SIMPLE")
    assert s["txn_cnt"] > 0
    assert int(np.asarray(st.data).sum()) == 0
    # acked immediately: one tick of latency for every txn
    assert s["avg_latency_ticks_short"] <= 1.0


def test_ladder_orders_throughput():
    """Each stripped layer can only help: NORMAL <= NOCC <= SIMPLE commits
    under contention (the diagnostic signal the ladder exists for)."""
    n0, _ = run("NORMAL")
    n1, _ = run("NOCC")
    n3, _ = run("SIMPLE")
    assert n0["txn_cnt"] <= n1["txn_cnt"] <= n3["txn_cnt"]


def test_nocc_matches_nolock_isolation():
    """MODE NOCC and isolation NOLOCK disable CC through different gates
    (mode ladder vs isolation level) and must agree for 2PL."""
    a, _ = run("NOCC")
    b, _ = run("NORMAL", isolation_level="NOLOCK")
    assert a["txn_cnt"] == b["txn_cnt"]
    assert a["write_cnt"] == b["write_cnt"]
