"""Native command-log IO: durability + recovery oracle.

The C++ writer/reader (deneva_tpu/native/logio.cpp, the system/logger.cpp
analog) must round-trip the device engine's log ring, and REDO-replay of
the file must reconstruct the engine's data array exactly.  Corruption
(bit flips, torn tails, reordering) must be detected, not silently
replayed — the checksum/lsn contract of the reference's record format.
"""

import os

import numpy as np
import pytest

from deneva_tpu import native
from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine


def test_build_and_roundtrip(tmp_path):
    path = str(tmp_path / "cmd.log")
    keys = np.array([3, 1, 4, 1, 5], np.int32)
    tids = np.array([10, 11, 12, 13, 14], np.int32)
    assert native.log_append(path, keys, tids, 0) == 5
    counts = native.log_replay(path, 8)
    assert counts.tolist() == [0, 2, 0, 1, 1, 1, 0, 0]


def test_append_is_incremental(tmp_path):
    path = str(tmp_path / "cmd.log")
    native.log_append(path, np.array([1], np.int32),
                      np.array([0], np.int32), 0)
    native.log_append(path, np.array([2, 2], np.int32),
                      np.array([1, 1], np.int32), 1)
    counts = native.log_replay(path, 4)
    assert counts.tolist() == [0, 1, 2, 0]


def test_corruption_detected(tmp_path):
    path = str(tmp_path / "cmd.log")
    native.log_append(path, np.arange(16, dtype=np.int32),
                      np.zeros(16, np.int32), 0)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF                 # flip a payload byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(IOError):
        native.log_replay(path, 32)


def test_torn_tail_detected(tmp_path):
    path = str(tmp_path / "cmd.log")
    native.log_append(path, np.arange(4, dtype=np.int32),
                      np.zeros(4, np.int32), 0)
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-7])           # torn final record
    with pytest.raises(IOError):
        native.log_replay(path, 32)


def test_lsn_gap_detected(tmp_path):
    path = str(tmp_path / "cmd.log")
    native.log_append(path, np.array([1], np.int32),
                      np.array([0], np.int32), 0)
    native.log_append(path, np.array([2], np.int32),
                      np.array([0], np.int32), 5)   # gap: lsn 1..4 missing
    with pytest.raises(IOError):
        native.log_replay(path, 32)


def test_engine_flush_and_recover(tmp_path):
    """End to end: run with LOGGING, flush the device ring natively in two
    installments, then REDO-replay the file == the engine's data array."""
    path = str(tmp_path / "cmd.log")
    cfg = Config(cc_alg="NO_WAIT", batch_size=128, synth_table_size=1 << 12,
                 req_per_query=4, zipf_theta=0.6, query_pool_size=1 << 10,
                 logging=True, log_buf_cap=1 << 15)
    eng = Engine(cfg)
    st = eng.run(20)
    flushed = native.flush_engine_log(st, path, 0)
    st = eng.run(20, st)
    flushed = native.flush_engine_log(st, path, flushed)
    s = eng.summary(st)
    assert flushed == s["write_cnt"]
    counts = native.log_replay(path, cfg.synth_table_size)
    assert (counts == np.asarray(st.data)).all()
