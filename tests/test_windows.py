"""Causal-diagnosis window plane tests (Config.windows, obs/windows.py):
the sum-of-deltas identity must hold EXACTLY for every CC plugin, the
off path must stay byte-identical, wrap must refuse loudly, and the
latch must cost zero post-warmup recompiles."""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.obs import windows as obs_windows

# all 7 registered plugins; the run is 24 ticks on a tiny cell, so the
# whole sweep stays inside the tier-1 budget (the heavy compiles —
# MAAT's chain-validate — are already paid by other tier-1 cells)
ALL_ALGS = ["NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT",
            "CALVIN"]

BASE = dict(batch_size=64, synth_table_size=1 << 10, req_per_query=4,
            zipf_theta=0.8, tup_read_perc=0.5, query_pool_size=1 << 10,
            warmup_ticks=0)


def run_windowed(n_ticks=24, **kw):
    eng = Engine(Config(**{**BASE, "windows": True, "window_ticks": 4,
                           "window_slots": 16, **kw}))
    return eng, eng.run(n_ticks)


@pytest.mark.parametrize("alg", ALL_ALGS)
def test_sum_of_deltas_identity_per_plugin(alg):
    # the tentpole identity: per-window int deltas telescope EXACTLY to
    # the final cumulative counters, float columns latch the final
    # value bit-equal, tick stamps land on the latch cadence — for the
    # full derived vocabulary of every plugin (its private _cnt
    # counters included)
    eng, st = run_windowed(cc_alg=alg)
    snap = eng.window_snapshot(st)
    assert snap is not None and not obs_windows.wrapped(snap)
    assert obs_windows.n_valid(snap) == 6          # 24 ticks / 4
    assert obs_windows.reconcile(snap, eng.summary(st)) == []


def test_off_path_is_byte_identical():
    # windows off must be the EXACT seed engine: same summary keys, same
    # values; windows on adds exactly the window_* bookkeeping keys and
    # changes nothing else
    off_eng = Engine(Config(**BASE))
    off = off_eng.summary(off_eng.run(24))
    on_eng, on_st = run_windowed()
    on = on_eng.summary(on_st)
    extra = set(on) - set(off)
    assert extra == {"window_cnt", "window_wrapped", "window_slots",
                     "window_ticks_per"}
    for k in off:
        assert on[k] == off[k], k
    assert not any(k.startswith("arr_window")
                   for k in off_eng.init_state().stats)


def test_wrap_refuses_loudly():
    # more windows latched than kept: reconcile must lead with the
    # window_ring_wrapped finding instead of proving anything from a
    # lossy ring
    eng, st = run_windowed(window_slots=2)
    snap = eng.window_snapshot(st)
    assert obs_windows.wrapped(snap)
    bad = obs_windows.reconcile(snap, eng.summary(st))
    assert bad and bad[0][0] == "window_ring_wrapped"


def test_latch_costs_zero_postwarm_recompiles():
    # the latch is an unconditional scatter (OOB-drop on off ticks), so
    # the traced tick is identical every tick: continuing a windowed run
    # under the xmeter sentinel must hit the dispatch cache every call
    eng = Engine(Config(**BASE, windows=True, window_ticks=4,
                        window_slots=16, xmeter=True))
    st = eng.run(12)
    eng.xmeter.mark_warm()
    st = eng.run(12, st)
    assert eng.xmeter.steady_violations() == []
    snap = eng.window_snapshot(st)
    assert obs_windows.reconcile(snap, eng.summary(st)) == []


def test_record_extra_round_trips_through_diff():
    # the run-record "windows" block is what obs/diff.py segments: the
    # two phase pseudo-summaries must add back to the cumulative
    # counters (the identity, applied to the JSON form)
    from deneva_tpu.obs import diff as obs_diff
    eng, st = run_windowed()
    extra = obs_windows.record_extra(eng.cfg, st.stats, st.db)
    rec = {"summary": eng.summary(st), **extra}
    sa, sb, split = obs_diff.segment_summaries(rec)
    assert split == 12
    snap = eng.window_snapshot(st)
    for k, fin in snap["final_i"].items():
        assert sa.get(k, 0) + sb.get(k, 0) == fin, k


@pytest.mark.slow
def test_sharded_identity_and_cluster_plane():
    # sharded: each node latches its own ring inside the shard_map body;
    # the host snapshot psum-merges the node axis and the identity must
    # hold against the CLUSTER summary; the device psum plane must be
    # bit-equal to the host sum
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(node_cnt=4, part_cnt=4, batch_size=32,
                 synth_table_size=1 << 12, req_per_query=4,
                 query_pool_size=1 << 10, zipf_theta=0.6,
                 tup_read_perc=0.5, warmup_ticks=0, mpr=1.0,
                 part_per_txn=4, mesh=True, windows=True,
                 window_ticks=4, window_slots=16)
    eng = ShardedEngine(cfg)
    st = eng.run(16)
    snap = eng.window_snapshot(st)
    assert snap["nodes"] == 4
    assert obs_windows.reconcile(snap, eng.summary(st)) == []
    plane = np.asarray(eng.window_cluster_plane(st))
    host = np.asarray(st.stats["arr_window_i32"], np.int64).sum(axis=0)
    # the device psum merges the node axis on device; it must be
    # bit-equal to the host-side sum of the stacked per-node rings
    assert np.array_equal(plane.astype(np.int64), host)
