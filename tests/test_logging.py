"""Command-log + replication analog tests.

Reference: LOGGING (config.h:147) writes L_UPDATE records per write and
gates commit on the LogThread flush (system/logger.cpp,
worker_thread.cpp:535-554); REPLICA_CNT adds a replica ack round trip.

The recovery oracle: replaying the command log's increments must
reconstruct the data array exactly — the point of a command log.
"""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine


def cfg(**kw):
    base = dict(cc_alg="NO_WAIT", batch_size=128, synth_table_size=1 << 12,
                req_per_query=4, zipf_theta=0.6, query_pool_size=1 << 10,
                logging=True)
    base.update(kw)
    return Config(**base)


def test_log_records_every_committed_write():
    eng = Engine(cfg())
    st = eng.run(40)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    lsn = int(np.asarray(st.stats["log_lsn"]))
    assert lsn == s["write_cnt"]


def test_log_replay_reconstructs_data():
    c = cfg(log_buf_cap=1 << 16)   # large enough to avoid wrap in this run
    eng = Engine(c)
    st = eng.run(40)
    s = eng.summary(st)
    lsn = int(np.asarray(st.stats["log_lsn"]))
    assert lsn < c.log_buf_cap, "ring wrapped; grow cap for this test"
    keys = np.asarray(st.stats["arr_log_key"])[:lsn]
    replayed = np.zeros(c.synth_table_size, np.int64)
    np.add.at(replayed, keys, 1)
    assert (replayed == np.asarray(st.data)).all()
    assert replayed.sum() == s["write_cnt"]


def test_flush_latency_gates_commit():
    e0 = Engine(cfg(logging=False))
    s0 = e0.summary(e0.run(40))
    e2 = Engine(cfg(log_flush_ticks=3))
    s2 = e2.summary(e2.run(40))
    # same schedule delayed: commit latency grows by >= the flush ticks
    assert s2["avg_latency_ticks_short"] >= s0["avg_latency_ticks_short"] + 2
    assert s2["txn_cnt"] > 0


def test_logging_preserves_conservation():
    eng = Engine(cfg(cc_alg="MAAT"))
    st = eng.run(40)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert int(np.asarray(st.data).sum()) == s["write_cnt"]


def test_sharded_replication():
    from deneva_tpu.parallel.sharded import ShardedEngine
    c = Config(cc_alg="WAIT_DIE", node_cnt=4, part_cnt=4, batch_size=32,
               synth_table_size=1 << 12, req_per_query=4, zipf_theta=0.6,
               query_pool_size=512, mpr=1.0, part_per_txn=2,
               logging=True, repl_cnt=1, log_buf_cap=1 << 14)
    eng = ShardedEngine(c)
    st = eng.run(40)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    lsn = np.asarray(st.stats["log_lsn"])
    rlsn = np.asarray(st.stats["repl_lsn"])
    assert lsn.sum() == s["write_cnt"]
    # every shard's log is fully replicated on its successor
    assert (rlsn == np.roll(lsn, 1)).all()
    # replica rings hold the same multiset of keys as the primary rings
    for p in range(4):
        prim = np.sort(np.asarray(st.stats["arr_log_key"][p])[:int(lsn[p])])
        repl = np.sort(np.asarray(
            st.stats["arr_repl_key"][(p + 1) % 4])[:int(rlsn[(p + 1) % 4])])
        assert (prim == repl).all()


@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_sharded_log_replay_reconstructs_global_data():
    from deneva_tpu.parallel.sharded import ShardedEngine
    c = Config(cc_alg="NO_WAIT", node_cnt=4, part_cnt=4, batch_size=32,
               synth_table_size=1 << 12, req_per_query=4, zipf_theta=0.6,
               query_pool_size=512, mpr=1.0, part_per_txn=2,
               logging=True, log_buf_cap=1 << 14)
    eng = ShardedEngine(c)
    st = eng.run(40)
    lsn = np.asarray(st.stats["log_lsn"])
    replayed = np.zeros(c.synth_table_size, np.int64)
    for p in range(4):
        keys = np.asarray(st.stats["arr_log_key"][p])[:int(lsn[p])]
        np.add.at(replayed, keys, 1)
    # data is sharded local rows: global key k lives at shard k%N, row k//N
    glob = np.zeros(c.synth_table_size, np.int64)
    d = np.asarray(st.data)
    for p in range(4):
        glob[p::4] = d[p]
    assert (replayed == glob).all()


@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
class TestActivePassive:
    """AP replication (config.h:24-27 REPLICA_CNT, ISREPLICA global.h:301):
    dedicated replica nodes on the mesh's upper half receive the log
    stream; commit blocks until the replica's acked LSN covers the txn's
    records (LOG_MSG -> LOG_MSG_RSP, worker_thread.cpp:535-554)."""

    def _run(self, lag, ticks=40):
        from deneva_tpu.parallel.sharded import ShardedEngine
        cfg = Config(cc_alg="NO_WAIT", node_cnt=4, part_cnt=2,
                     batch_size=32, synth_table_size=1 << 12,
                     req_per_query=4, zipf_theta=0.6,
                     query_pool_size=1 << 10, mpr=1.0, part_per_txn=2,
                     logging=True, repl_cnt=1, repl_mode="ap",
                     repl_lag_ticks=lag)
        eng = ShardedEngine(cfg)
        st = eng.run(ticks)
        return eng, st, eng.summary(st)

    def test_replica_mirrors_worker_log_exactly(self):
        eng, st, s = self._run(lag=1)
        assert eng.global_data_sum(st) == s["write_cnt"]
        lsn = np.asarray(st.stats["log_lsn"])
        rlsn = np.asarray(st.stats["repl_lsn"])
        # workers (nodes 0,1) log; replicas (nodes 2,3) mirror exactly
        assert lsn[2] == lsn[3] == 0
        assert rlsn[0] == rlsn[1] == 0
        assert rlsn[2] == lsn[0] and rlsn[3] == lsn[1]
        assert lsn[0] > 0
        # and the replicated keys match the workers' log rings
        n0 = int(lsn[0])
        assert (np.asarray(st.stats["arr_log_key"][0][:n0])
                == np.asarray(st.stats["arr_repl_key"][2][:n0])).all()

    def test_commit_blocked_on_replica_ack_lag(self):
        _, _, fast = self._run(lag=1)
        _, _, slow = self._run(lag=8)
        assert fast["txn_cnt"] > 0 and slow["txn_cnt"] > 0
        # injected replica lag must stall commits and stretch latency
        assert slow["txn_cnt"] < fast["txn_cnt"]
        assert slow["avg_latency_ticks_short"] \
            > fast["avg_latency_ticks_short"]
