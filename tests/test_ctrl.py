"""Adaptive contention controller (Config.adaptive, deneva_tpu/ctrl/).

Unit-level checks of the three policies (per-reason backoff schedule,
hot-key escalation gate, width ladder) plus engine-level smoke: the
controller must escalate and gate under a forced hot key, keep the
taxonomy identity exact, surface round-trippable ctrl_* summary keys,
and leave the default (adaptive off) tick byte-untouched.  The
whole-matrix purity/compile proofs live in the certifier
(deneva_tpu/lint/certify.py) and scripts/check.sh's adaptive stage.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np

from deneva_tpu import ctrl
from deneva_tpu import stats as stats_mod
from deneva_tpu.cc import base as cc_base
from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.engine.state import NULL_KEY, TxnState
from deneva_tpu.workloads.ycsb import gen_query_pool

ADAPT = dict(adaptive=True, abort_attribution=True, heatmap_bins=32,
             batch_size=64, synth_table_size=256, req_per_query=4,
             zipf_theta=0.9, query_pool_size=512, warmup_ticks=0,
             admit_cap=16)


def test_off_path_carries_no_ctrl_state():
    eng = Engine(Config(cc_alg="NO_WAIT", batch_size=32,
                        synth_table_size=256, req_per_query=4,
                        query_pool_size=256, warmup_ticks=0))
    st = eng.run(10)
    assert not any(k.startswith(("ctrl_", "arr_ctrl_")) for k in st.stats)
    assert not any(k.startswith("ctrl_") for k in eng.summary(st))


def test_penalty_class_schedule():
    cfg = Config(cc_alg="NO_WAIT", **ADAPT)
    stats = ctrl.init_ctrl(cfg)
    B = 8
    zero = jnp.zeros(B, jnp.int32)
    t = jnp.zeros((), jnp.int32)
    fast = jnp.full(B, cc_base.REASON["nowait_conflict"], jnp.int32)
    slow = jnp.full(B, cc_base.REASON["occ_validation"], jnp.int32)

    # fresh EWMAs: every class starts at base 1, spread over [1, 2] by
    # the per-lane desync jitter (cohorts killed the same tick must not
    # all wake the same tick)
    pen = np.asarray(ctrl.penalty(cfg, stats, zero, fast, t))
    assert (pen >= 1).all() and (pen <= 2).all()
    pen = np.asarray(ctrl.penalty(cfg, stats, zero, slow, t))
    assert (pen >= 1).all() and (pen <= 2).all()
    assert len(set(pen.tolist())) > 1  # the cohort actually desyncs

    # lock kills compound exponentially in restarts up to the hard
    # ceiling (plus at most a half-penalty of jitter); the flat
    # validation class NEVER compounds
    many = jnp.full(B, 10, jnp.int32)
    pen = np.asarray(ctrl.penalty(cfg, stats, many, fast, t))
    assert (pen >= cfg.ctrl_backoff_max).all()
    assert (pen <= cfg.ctrl_backoff_max + cfg.ctrl_backoff_max // 2 + 1).all()
    pen = np.asarray(ctrl.penalty(cfg, stats, many, slow, t))
    assert (pen <= min(2, cfg.ctrl_backoff_max) + 2).all()

    # a hot abort-rate EWMA pushes the base to the class cap
    i = cc_base.REASON["nowait_conflict"] - 1
    hot = dict(stats)
    hot["arr_ctrl_reason_ewma"] = stats["arr_ctrl_reason_ewma"].at[i].set(
        jnp.int32(10_000 << ctrl.CTRL_SCALE))
    pen = np.asarray(ctrl.penalty(cfg, hot, zero, fast, t))
    assert (pen >= cfg.ctrl_backoff_max).all()

    # unregistered/zero codes fall back to "other", never zero ticks
    pen = np.asarray(ctrl.penalty(cfg, stats, zero, zero, t))
    assert (pen >= 1).all()


def test_esc_stall_oldest_writer_wins():
    cfg = Config(cc_alg="NO_WAIT", **ADAPT)
    stats = ctrl.init_ctrl(cfg)
    stats["arr_ctrl_esc_key"] = stats["arr_ctrl_esc_key"].at[0].set(7)
    B, R = 4, 2
    txn = TxnState.empty(B, R, A=1)
    txn = txn._replace(
        keys=jnp.array([[7, 1], [7, 2], [3, 7], [7, 4]], jnp.int32),
        is_write=jnp.array([[True, False], [True, False],
                            [True, False], [False, True]]),
        cursor=jnp.zeros(B, jnp.int32),
        n_req=jnp.full(B, 2, jnp.int32),
        ts=jnp.asarray([5, 9, 1, 3]).astype(txn.ts.dtype))
    active = jnp.ones(B, bool)
    stall = np.asarray(ctrl.esc_stall(cfg, stats, txn, active))
    # lane 0 (oldest writer of key 7) proceeds; lane 1 (younger writer
    # of 7) stalls; lane 2 targets an unescalated key; lane 3 READS 7
    assert stall.tolist() == [False, True, False, False]

    # empty ring: nobody stalls
    stats["arr_ctrl_esc_key"] = jnp.full_like(stats["arr_ctrl_esc_key"],
                                              NULL_KEY)
    assert not np.asarray(ctrl.esc_stall(cfg, stats, txn, active)).any()


def test_width_ladder_gears():
    cfg = Config(cc_alg="NO_WAIT", acquire_window=1, **ADAPT)
    eng = Engine(cfg)
    ladder = ctrl.width_ladder(cfg, eng.plugin)
    assert ladder[0] is cfg and len(ladder) > 1
    assert all(isinstance(c, Config) for c in ladder)
    off = dataclasses.replace(cfg, adaptive=False)
    assert ctrl.width_ladder(off, eng.plugin) == [off]


def test_escalation_fires_on_forced_hot_key():
    # the reference's HOT skew pointed at a 2-row hot set: the bucket
    # heat EWMA must cross ctrl_esc_up, the majority key must survive
    # the re-hash check, and the one-writer gate must actually stall
    cfg = Config(cc_alg="NO_WAIT", skew_method="hot", access_perc=0.95,
                 data_perc=0.01, ctrl_esc_up=2, ctrl_esc_down=1, **ADAPT)
    eng = Engine(cfg)
    st = eng.run(80)
    s = eng.summary(st)
    assert int(s["ctrl_escalate_cnt"]) >= 1
    assert int(s["ctrl_esc_block_cnt"]) >= 1
    assert int(s["ctrl_esc_active"]) >= 0  # hysteresis may have cycled


def test_taxonomy_identity_holds_under_adaptive():
    from deneva_tpu.obs import report as obs_report
    for alg in ("NO_WAIT", "OCC"):
        eng = Engine(Config(cc_alg=alg, **ADAPT))
        s = eng.summary(eng.run(40))
        assert obs_report.reconcile(s) == [], alg


def test_ctrl_keys_roundtrip_summary_line():
    eng = Engine(Config(cc_alg="NO_WAIT", **ADAPT))
    s = eng.summary(eng.run(30))
    ref = stats_mod.reference_summary(s)
    parsed = stats_mod.parse_summary(stats_mod.format_summary(ref))
    ctrl_keys = [k for k in ref if k.startswith("ctrl_")]
    assert "ctrl_escalate_cnt" in ctrl_keys
    for name in cc_base.ABORT_REASONS:
        assert f"ctrl_base_{name}" in ctrl_keys
    for k in ctrl_keys:
        assert int(parsed[k]) == int(ref[k]), k


def test_sharded_adaptive_runs_and_surfaces():
    from deneva_tpu.parallel.sharded import ShardedEngine
    kw = dict(ADAPT)
    kw.update(node_cnt=2, part_cnt=2, batch_size=32, mpr=1.0,
              part_per_txn=2)
    eng = ShardedEngine(Config(cc_alg="NO_WAIT", **kw))
    s = eng.summary(eng.run(20))
    assert s["txn_cnt"] > 0
    assert "ctrl_escalate_cnt" in s
    # off path: no controller keys anywhere
    kw.pop("adaptive")
    kw.pop("heatmap_bins")
    eng = ShardedEngine(Config(cc_alg="NO_WAIT", **kw))
    st = eng.run(10)
    assert not any(k.startswith(("ctrl_", "arr_ctrl_"))
                   for k in st.stats)


def test_hot_set_shift_adapts_without_retrace():
    # pool front half hot at the low ids, back half bijectively remapped
    # to mid-table: the cursor crossing the boundary moves the hot set;
    # the already-compiled tick must keep running and keep counting
    # (scripts/check.sh proves the zero-recompile half via the xmeter)
    cfg = Config(cc_alg="NO_WAIT", skew_method="hot", access_perc=0.95,
                 data_perc=0.01, ctrl_esc_up=2, ctrl_esc_down=1, **ADAPT)
    pool = gen_query_pool(cfg)
    n = cfg.synth_table_size - 1
    keys = pool.keys.copy()
    half = keys.shape[0] // 2
    keys[half:] = ((keys[half:] + n // 2 - 1) % n) + 1
    eng = Engine(cfg, pool=dataclasses.replace(pool, keys=keys))
    st = eng.run(60)
    st = eng.run(60, st)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert int(s["ctrl_escalate_cnt"]) >= 1
