"""PPS workload tests: 8-type mix generation, secondary-lookup chain
unrolling, PART_AMOUNT conservation, USES updates, and the Calvin recon
deferral — single-shard and 8-node sharded.

Reference: benchmarks/pps_txn.cpp (state machines), pps_wl.cpp:200-243
(association loaders), pps_helper.cpp:19-29 (partitioning),
system/sequencer.cpp:88-114 (recon).
"""

import numpy as np
import pytest

from deneva_tpu.config import CC_ALGS, Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.workloads import pps
from deneva_tpu.workloads.pps import PPSWorkload


def pps_cfg(**kw):
    base = dict(workload="PPS", cc_alg="NO_WAIT", batch_size=64,
                part_cnt=1, node_cnt=1, max_part_key=128,
                max_product_key=128, max_supplier_key=128, max_parts_per=5,
                query_pool_size=512, synth_table_size=8)
    base.update(kw)
    return Config(**base)


def table_sums(tables):
    return {k: int(np.asarray(v, dtype=np.int64).sum())
            for k, v in tables.items()}


class TestGenerator:
    def test_mix_all_types(self):
        cfg = pps_cfg(query_pool_size=8192,
                      perc_pps_getpart=0.1, perc_pps_getproduct=0.1,
                      perc_pps_getsupplier=0.1,
                      perc_pps_getpartbysupplier=0.2,
                      perc_pps_getpartbyproduct=0.1,
                      perc_pps_orderproduct=0.2,
                      perc_pps_updateproductpart=0.1,
                      perc_pps_updatepart=0.1)
        pool = PPSWorkload().gen_pool(cfg)
        counts = np.bincount(pool.txn_type, minlength=9)[1:]
        assert (counts > 0).all(), counts
        frac = counts / counts.sum()
        assert abs(frac[pps.PPS_ORDERPRODUCT - 1] - 0.2) < 0.03

    def test_chain_unrolling_matches_loader(self):
        cfg = pps_cfg(query_pool_size=2048)
        wl = PPSWorkload()
        pool = wl.gen_pool(cfg)
        _, uses, _ = wl._load(cfg)
        cat = pps.catalog(cfg)
        # every GETPARTBYPRODUCT txn's access list is PRODUCTS then the
        # (USES slot, PARTS) pairs of the loader's chain, in order
        qs = np.where(pool.txn_type == pps.PPS_GETPARTBYPRODUCT)[0][:20]
        for q in qs:
            pr = int(pool.args[q, pps.TA_PRODUCT])
            chain = uses[pr]
            assert pool.n_req[q] == 1 + 2 * len(chain)
            for i, pk in enumerate(chain):
                part_key = pool.keys[q, 2 + 2 * i]
                assert cat.local("PARTS", part_key) == pk // cfg.part_cnt
        # ORDERPRODUCT writes exactly the PARTS rows
        qs = np.where(pool.txn_type == pps.PPS_ORDERPRODUCT)[0][:20]
        for q in qs:
            n = pool.n_req[q]
            w = pool.is_write[q, :n]
            assert w[0] == False  # noqa: E712  (PRODUCTS read)
            assert (w[2::2] == True).all()  # noqa: E712 (PARTS writes)

    def test_first_part_local_striping(self):
        cfg = pps_cfg(query_pool_size=2048, part_cnt=4, node_cnt=4)
        pool = PPSWorkload().gen_pool(cfg)
        # home entity keys stripe to the home partition
        prod = pool.args[:, pps.TA_PRODUCT]
        assert ((prod % 4) == pool.home_part).all()


class TestSingleShard:
    # the MAAT cell compiles the chain-validate and alone costs ~14 s —
    # `-m slow` per the tier-1 870 s budget split
    @pytest.mark.parametrize("alg", [
        pytest.param(a, marks=pytest.mark.slow) if a == "MAAT" else a
        for a in CC_ALGS])
    def test_all_algorithms_commit(self, alg):
        cfg = pps_cfg(cc_alg=alg)
        eng = Engine(cfg)
        st0 = eng.init_state()
        t0 = table_sums(st0.tables)
        st = eng.run(40, st0)
        s = eng.summary(st)
        assert s["txn_cnt"] > 0, alg
        assert int(np.asarray(st.data).sum()) == s["write_cnt"]
        # PART_AMOUNT conservation: -1 per committed order line, +100 per
        # committed UPDATEPART; with updatepart off, delta = -(order lines)
        t1 = table_sums(st.tables)
        delta = t1["part_amount"] - t0["part_amount"]
        assert delta <= 0
        assert delta % 1 == 0

    def test_amount_conservation_exact(self):
        cfg = pps_cfg(cc_alg="WAIT_DIE", perc_pps_getpartbyproduct=0.0,
                      perc_pps_orderproduct=1.0,
                      perc_pps_updateproductpart=0.0)
        eng = Engine(cfg)
        st0 = eng.init_state()
        t0 = table_sums(st0.tables)
        st = eng.run(40, st0)
        s = eng.summary(st)
        t1 = table_sums(st.tables)
        # every committed write access is a PARTS decrement here
        assert t0["part_amount"] - t1["part_amount"] == s["write_cnt"]

    def test_updatepart_increments(self):
        cfg = pps_cfg(cc_alg="NO_WAIT", perc_pps_getpartbyproduct=0.0,
                      perc_pps_orderproduct=0.0,
                      perc_pps_updateproductpart=0.0,
                      perc_pps_updatepart=1.0)
        eng = Engine(cfg)
        st0 = eng.init_state()
        t0 = table_sums(st0.tables)
        st = eng.run(30, st0)
        s = eng.summary(st)
        t1 = table_sums(st.tables)
        assert t1["part_amount"] - t0["part_amount"] == 100 * s["txn_cnt"]

    def test_updateproductpart_rewrites_uses(self):
        cfg = pps_cfg(cc_alg="NO_WAIT", perc_pps_getpartbyproduct=0.0,
                      perc_pps_orderproduct=0.0,
                      perc_pps_updateproductpart=1.0)
        eng = Engine(cfg)
        st = eng.run(30)
        s = eng.summary(st)
        assert s["txn_cnt"] > 0
        # committed updates point first-chain-slot rows at the txn's part
        pool = eng.pool
        uses_col = np.asarray(st.tables["uses_part"])
        cat = pps.catalog(cfg)
        # at least one first-slot entry now differs from the loader value
        _, uses, _ = eng.workload._load(cfg)
        changed = 0
        for pr in range(1, cfg.max_product_key + 1):
            base = (pr // cfg.part_cnt) * cfg.max_parts_per
            if uses_col[base] != uses[pr][0]:
                changed += 1
        assert changed > 0

    def test_determinism(self):
        cfg = pps_cfg(cc_alg="MVCC")
        e1, e2 = Engine(cfg), Engine(cfg)
        s1, s2 = e1.run(30), e2.run(30)
        assert e1.summary(s1) == e2.summary(s2)
        for k in s1.tables:
            assert (np.asarray(s1.tables[k]) == np.asarray(s2.tables[k])).all()


class TestShardedAndCalvin:
    @pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
    def test_sharded_8node_conservation(self):
        from deneva_tpu.parallel.sharded import ShardedEngine
        cfg = pps_cfg(cc_alg="WAIT_DIE", node_cnt=8, part_cnt=8,
                      batch_size=16, query_pool_size=512)
        eng = ShardedEngine(cfg)
        st0 = eng.init_state()
        t0 = table_sums(st0.tables)
        st = eng.run(40, st0)
        s = eng.summary(st)
        assert s["txn_cnt"] > 0
        assert eng.global_data_sum(st) == s["write_cnt"]
        t1 = table_sums(st.tables)
        assert t1["part_amount"] <= t0["part_amount"]

    def test_calvin_recon_deferral(self):
        cfg = pps_cfg(cc_alg="CALVIN", batch_size=32)
        eng = Engine(cfg)
        st = eng.run(40)
        s = eng.summary(st)
        assert s["txn_cnt"] > 0
        assert s["total_txn_abort_cnt"] == 0       # Calvin never aborts
        assert s["recon_cnt"] > 0                  # recon passes happened
        # recon types pay >= 1 extra tick of long latency vs short
        assert s["txn_total_time_ticks"] > s["txn_run_time_ticks"]

    def test_non_calvin_has_no_recon(self):
        cfg = pps_cfg(cc_alg="NO_WAIT")
        eng = Engine(cfg)
        st = eng.run(20)
        assert eng.summary(st)["recon_cnt"] == 0
