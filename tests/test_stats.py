"""Stats contract tests: the [summary] emitter parses with the reference's
parser port, the latency decomposition integrates to the slot population,
and the percentile ring tracks real commit latencies.

Reference contract: statistics/stats.cpp:425-1575 ([summary] key=value
line), scripts/parse_results.py:19-37 (the consumer this must round-trip
through), stats_array.cpp (percentile arrays).
"""

import pytest
import numpy as np

from deneva_tpu import stats as stats_mod
from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine


def run_engine(**kw):
    base = dict(cc_alg="WAIT_DIE", batch_size=128, synth_table_size=1 << 12,
                req_per_query=6, zipf_theta=0.8, query_pool_size=1 << 10)
    base.update(kw)
    eng = Engine(Config(**base))
    st = eng.run(50)
    return eng, st


def test_summary_line_round_trips_through_reference_parser():
    eng, st = run_engine()
    line = eng.summary_line(st, wall_seconds=1.0)
    assert line.startswith("[summary] ")
    parsed = stats_mod.parse_summary(line)
    # the reference execution-block keys all present and numeric
    for key in ("total_runtime", "tput", "txn_cnt", "local_txn_start_cnt",
                "total_txn_commit_cnt", "total_txn_abort_cnt",
                "unique_txn_abort_cnt", "txn_run_time", "txn_run_avg_time",
                "record_write_cnt", "parts_touched", "avg_parts_touched",
                "lat_cc_block_time", "lat_abort_time", "lat_process_time",
                "lat_network_time", "ccl50", "ccl99"):
        assert key in parsed, key
    s = eng.summary(st)
    assert parsed["txn_cnt"] == s["txn_cnt"]
    assert parsed["tput"] == parsed["txn_cnt"] / parsed["total_runtime"]


def test_latency_decomposition_integrates_slot_population():
    eng, st = run_engine()
    s = eng.summary(st)
    # each measured tick classifies every non-free slot into exactly one of
    # the three states, so the integrals are bounded by B * ticks
    total = s["lat_process_time"] + s["lat_cc_block_time"] + s["lat_abort_time"]
    assert 0 < total <= eng.cfg.batch_size * s["measured_ticks"]
    # commit latencies are the RUNNING+WAITING span: avg short latency must
    # not exceed the per-commit share of those integrals (backoff excluded)
    assert s["avg_latency_ticks_short"] <= total


def test_percentiles_track_commit_latencies():
    eng, st = run_engine()
    s = eng.summary(st)
    d = stats_mod.reference_summary(s)
    assert s["ccl_valid"] > 0
    assert 0 <= d["ccl0"] <= d["ccl50"] <= d["ccl99"] <= d["ccl100"]
    # faithful window: a 6-access txn needs >= 6 ticks from (re)start
    assert d["ccl0"] >= eng.cfg.req_per_query
    # wall-clock conversion scales all time keys by tick seconds
    d2 = stats_mod.reference_summary(s, wall_seconds=s["measured_ticks"] * 2.0)
    assert abs(d2["ccl50"] - 2.0 * d["ccl50"]) < 1e-6


def test_vabort_and_parts_touched_keys():
    eng, st = run_engine(cc_alg="OCC", zipf_theta=0.9)
    s = eng.summary(st)
    assert s["vabort_cnt"] > 0            # OCC aborts at validation
    assert s["parts_touched"] == s["txn_cnt"]   # single partition


@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_sharded_summary_line():
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(cc_alg="NO_WAIT", node_cnt=4, part_cnt=4, batch_size=32,
                 synth_table_size=1 << 10, req_per_query=4, zipf_theta=0.6,
                 query_pool_size=512, mpr=1.0, part_per_txn=4)
    eng = ShardedEngine(cfg)
    st = eng.run(25)
    line = eng.summary_line(st, wall_seconds=0.5)
    parsed = stats_mod.parse_summary(line)
    assert parsed["txn_cnt"] > 0
    assert parsed["lat_network_time"] > 0      # cross-shard entries shipped
    assert parsed["multi_part_txn_cnt"] > 0
    assert parsed["avg_parts_touched"] > 1.0
    s = eng.summary(st)
    assert s["ccl_valid"] > 0


def test_prog_line_tag():
    eng, st = run_engine()
    line = eng.summary_line(st, prog=True)
    assert line.startswith("[prog] ")
    # [prog] lines carry the same key=value payload as [summary] and
    # round-trip through the same parser (obs/prog.py contract)
    parsed = stats_mod.parse_summary(line)
    summary = stats_mod.parse_summary(eng.summary_line(st))
    assert set(parsed) == set(summary)
    assert parsed["txn_cnt"] == summary["txn_cnt"]
    # anything else still parses to nothing
    assert stats_mod.parse_summary("no tag here k=1") == {}


def test_traffic_keys_round_trip_exactly():
    """Open-system runs (Config.arrival, deneva_tpu/traffic/) put the
    arrival/queue conservation counters and per-family percentile keys
    on the [summary] line; they round-trip through the parser port with
    EXACT key names, and the closed-loop line carries none of them."""
    eng, st = run_engine(arrival="poisson", arrival_rate=6.0)
    line = eng.summary_line(st, wall_seconds=1.0)
    parsed = stats_mod.parse_summary(line)
    for key in ("arrival_cnt", "queue_admit_cnt", "queue_len",
                "queue_peak", "lat_work_queue_time",
                "famlat0_n", "famlat0_p50", "famlat0_p95", "famlat0_p99"):
        assert key in parsed, key
    s = eng.summary(st)
    assert parsed["arrival_cnt"] == s["arrival_cnt"]
    assert parsed["queue_admit_cnt"] == s["queue_admit_cnt"]
    # the no-drop conservation identity survives the round trip
    assert parsed["arrival_cnt"] == parsed["queue_admit_cnt"] \
        + parsed["queue_len"]
    # timebase: the famlat percentiles are tick-valued latencies and
    # scale with wall seconds like ccl*; the sample COUNT stays integral
    d1 = stats_mod.reference_summary(s)
    d2 = stats_mod.reference_summary(s, wall_seconds=s["measured_ticks"]
                                     * 2.0)
    assert abs(d2["famlat0_p50"] - 2.0 * d1["famlat0_p50"]) < 1e-6
    assert d2["famlat0_n"] == d1["famlat0_n"]
    assert d2["arrival_cnt"] == d1["arrival_cnt"]   # counters unscaled

    # closed loop: no traffic keys at all, queue time exactly zero
    eng0, st0 = run_engine()
    p0 = stats_mod.parse_summary(eng0.summary_line(st0, wall_seconds=1.0))
    assert p0["lat_work_queue_time"] == 0.0
    assert not any(k.startswith(("arrival_", "queue_", "famlat"))
                   for k in p0)


def test_mesh_keys_round_trip_exactly():
    """Mesh-observatory runs (Config.mesh, obs/mesh.py) put the
    traffic-matrix totals and the imbalance keys on the [summary] line;
    the stats layer passes them through VERBATIM (counts and a
    dimensionless index, never time-scaled), they round-trip through
    the parser port exactly, and the default line carries none."""
    eng, st = run_engine()
    s = eng.summary(st)
    # the passthrough is engine-agnostic: inject the documented key set
    # (tests/test_mesh.py covers the sharded engine producing them)
    from deneva_tpu.obs.mesh import MESH_SUMMARY_KEYS
    mesh = {"mesh_tx_total": 6991, "mesh_drop_cnt": 3,
            "mesh_occ_sum": 3096, "mesh_occ_peak": 245,
            "straggler_tick_cnt": 25, "imb_jain": 0.9987}
    assert set(mesh) == set(MESH_SUMMARY_KEYS)
    d1 = stats_mod.reference_summary({**s, **mesh})
    d2 = stats_mod.reference_summary({**s, **mesh},
                                     wall_seconds=s["measured_ticks"]
                                     * 2.0)
    for k, v in mesh.items():
        assert d1[k] == v, k                       # verbatim
        assert d2[k] == v, k                       # never time-scaled
    parsed = stats_mod.parse_summary(stats_mod.format_summary(d1))
    for k, v in mesh.items():
        assert parsed[k] == pytest.approx(v)
    # the default (mesh-off) line carries none of them
    p0 = stats_mod.parse_summary(eng.summary_line(st, wall_seconds=1.0))
    assert not any(k.startswith(("mesh_", "imb_", "straggler_"))
                   for k in p0)


def test_fault_keys_round_trip_exactly():
    """Fault-plane runs (Config.faults, deneva_tpu/faults/) put the
    in-tick gating counters and the host-side recovery counters on the
    [summary] line; the stats layer passes them through VERBATIM (counts
    and 0/1 verdict flags, never time-scaled), they round-trip through
    the parser port exactly, and the default line carries none."""
    eng, st = run_engine()
    s = eng.summary(st)
    # the passthrough is engine-agnostic: inject the documented key set
    # (tests/test_faults.py covers the sharded engine producing the
    # in-tick counters; faults/recovery.py the host-side ones)
    from deneva_tpu.faults.recovery import HOST_COUNTERS
    fault = {"fault_req_blocked_cnt": 173, "fault_fin_deferred_cnt": 55,
             "fault_stall_ticks": 5, "fault_elog_lsn": 139,
             "fault_kill_cnt": 1, "fault_replay_ticks": 10,
             "recovery_lag_ticks": 10, "recovery_replay_ok": 1,
             "recovery_elog_ok": 1, "ckpt_save_cnt": 2,
             "ckpt_restore_cnt": 1}
    assert set(HOST_COUNTERS) <= set(fault)
    d1 = stats_mod.reference_summary({**s, **fault})
    d2 = stats_mod.reference_summary({**s, **fault},
                                     wall_seconds=s["measured_ticks"]
                                     * 2.0)
    for k, v in fault.items():
        assert d1[k] == v, k                       # verbatim
        assert d2[k] == v, k                       # never time-scaled
    parsed = stats_mod.parse_summary(stats_mod.format_summary(d1))
    for k, v in fault.items():
        assert parsed[k] == v, k
    # the default (fault-off) line carries none of them
    p0 = stats_mod.parse_summary(eng.summary_line(st, wall_seconds=1.0))
    assert not any(k.startswith(("fault_", "ckpt_", "recovery_"))
                   for k in p0)


def test_dep_keys_round_trip_exactly():
    """Conflict-dependency-observatory runs (Config.depgraph,
    obs/depgraph.py) put the edge counters, the chain-depth/convoy
    integrals, and the sampling-ring bookkeeping on the [summary] line;
    the stats layer passes them through VERBATIM (integers, never
    time-scaled), they round-trip through the parser port exactly, and
    the default line carries none."""
    eng, st = run_engine()
    s = eng.summary(st)
    # the passthrough is engine-agnostic: inject the documented key set
    # (tests/test_depgraph.py covers both engines producing them)
    dep = {"dep_wait_edge_cnt": 190, "dep_abort_edge_cnt": 303,
           "dep_nullkey_edge_cnt": 0, "dep_cross_edge_cnt": 893,
           "dep_depth_sum": 71, "dep_convoy_width_sum": 42,
           "dep_ring_cnt": 493, "dep_ring_wrapped": 0,
           "dep_peak_depth": 8, "dep_peak_convoy": 3}
    d1 = stats_mod.reference_summary({**s, **dep})
    d2 = stats_mod.reference_summary({**s, **dep},
                                     wall_seconds=s["measured_ticks"]
                                     * 2.0)
    for k, v in dep.items():
        assert d1[k] == v, k                       # verbatim
        assert d2[k] == v, k                       # never time-scaled
    parsed = stats_mod.parse_summary(stats_mod.format_summary(d1))
    for k, v in dep.items():
        assert parsed[k] == v, k
    # the default (depgraph-off) line carries none of them
    p0 = stats_mod.parse_summary(eng.summary_line(st, wall_seconds=1.0))
    assert not any(k.startswith("dep_") for k in p0)


def test_cc_case_counter_families():
    """The per-algorithm families (reference maat_case1/3 + this build's
    chain counters, occ check aborts) ride the [summary] line VERBATIM
    (the reference prints maat_caseN_cnt=%ld, stats.cpp:907) and
    round-trip through the parser port."""
    eng, st = run_engine(cc_alg="MAAT")
    line = eng.summary_line(st, wall_seconds=1.0)
    parsed = stats_mod.parse_summary(line)
    for k in ("maat_case1_cnt", "maat_case3_cnt", "maat_chain_cap_cnt",
              "maat_chain_push_cnt", "maat_range_abort_cnt",
              "maat_chain_overflow_cnt"):
        assert k in parsed, k
    # contention at zipf 0.8 must actually exercise the case machinery
    assert parsed["maat_case1_cnt"] > 0
    assert parsed["maat_range_abort_cnt"] >= 0
    # reference-name aliases of the chain counters (stats.py documents
    # the case2/4/6 mapping) so reference-format parsers keep the fields
    assert parsed["maat_case2_cnt"] == parsed["maat_chain_cap_cnt"]
    assert parsed["maat_case4_cnt"] == parsed["maat_chain_push_cnt"]
    assert parsed["maat_case6_cnt"] == parsed["maat_range_abort_cnt"]

    eng, st = run_engine(cc_alg="OCC")
    parsed = stats_mod.parse_summary(eng.summary_line(st, wall_seconds=1.0))
    assert "occ_hist_abort_cnt" in parsed \
        and "occ_active_abort_cnt" in parsed
    s = eng.summary(st)
    # every validation abort is classified into exactly one family
    assert parsed["occ_hist_abort_cnt"] + parsed["occ_active_abort_cnt"] \
        == s["vabort_cnt"]


@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_cc_counters_sharded_sum_across_nodes():
    from deneva_tpu.parallel.sharded import ShardedEngine
    kw = dict(node_cnt=4, part_cnt=4, batch_size=32,
              synth_table_size=1 << 12, req_per_query=4, zipf_theta=0.7,
              query_pool_size=1 << 10, mpr=1.0, part_per_txn=2)
    eng = ShardedEngine(Config(cc_alg="MAAT", **kw))
    st = eng.run(30)
    s = eng.summary(st)
    assert s["maat_case1_cnt"] > 0
    # per-owner validation events: a txn finishing on k owners counts one
    # event per owner, so events bound home-side aborts from above but
    # cannot exceed events-per-owner x validations
    eng = ShardedEngine(Config(cc_alg="OCC", **kw))
    st = eng.run(30)
    s = eng.summary(st)
    assert s["occ_hist_abort_cnt"] + s["occ_active_abort_cnt"] \
        >= s["vabort_cnt"]


def test_scale_out_keys_round_trip_exactly():
    """Scale-out runs (Config.exchange_split / Config.remote_cache,
    parallel/sharded.py) put the exchange sub-round count and the
    remote-grant cache counters on the [summary] line; the stats layer
    passes them through VERBATIM (integers, never time-scaled), they
    round-trip through the parser port exactly — with remote_entry_cnt
    pulled onto the line so the attempts == shipped + suppressed
    identity is checkable from the line alone — and the default line
    carries none."""
    eng, st = run_engine()
    s = eng.summary(st)
    # the passthrough is engine-agnostic: inject the documented key set
    # (tests/test_mesh.py covers the sharded engine producing them)
    scale = {"exchange_round_cnt": 522, "remote_attempt_cnt": 18749,
             "remote_cache_hit_cnt": 1855, "reship_suppressed_cnt": 7593,
             "remote_entry_cnt": 11156}
    assert (scale["remote_attempt_cnt"]
            == scale["remote_entry_cnt"] + scale["reship_suppressed_cnt"])
    d1 = stats_mod.reference_summary({**s, **scale})
    d2 = stats_mod.reference_summary({**s, **scale},
                                     wall_seconds=s["measured_ticks"]
                                     * 2.0)
    for k, v in scale.items():
        assert d1[k] == v, k                       # verbatim
        assert d2[k] == v, k                       # never time-scaled
    parsed = stats_mod.parse_summary(stats_mod.format_summary(d1))
    for k, v in scale.items():
        assert parsed[k] == pytest.approx(v)
    assert (parsed["remote_attempt_cnt"] == parsed["remote_entry_cnt"]
            + parsed["reship_suppressed_cnt"])
    # the default line carries none of them
    p0 = stats_mod.parse_summary(eng.summary_line(st, wall_seconds=1.0))
    assert not any(k.startswith(("exchange_", "remote_attempt_",
                                 "remote_cache_", "reship_"))
                   for k in p0)


def test_window_keys_round_trip_exactly():
    """Windowed runs (Config.windows, obs/windows.py) put the snapshot-
    ring bookkeeping on the [summary] line; the stats layer passes the
    window_*/diag_* families through VERBATIM (integers and
    dimensionless scores, never time-scaled), they round-trip through
    the parser port with EXACT key names, and the default line carries
    none of them."""
    eng = Engine(Config(cc_alg="NO_WAIT", batch_size=64,
                        synth_table_size=1 << 10, req_per_query=4,
                        zipf_theta=0.8, query_pool_size=1 << 10,
                        warmup_ticks=0, windows=True, window_ticks=4,
                        window_slots=16))
    st = eng.run(16)
    s = eng.summary(st)
    # the engine itself emits exactly the four bookkeeping keys
    assert {k for k in s if k.startswith("window_")} \
        == {"window_cnt", "window_wrapped", "window_slots",
            "window_ticks_per"}
    assert (s["window_cnt"], s["window_wrapped"]) == (4, 0)
    # diag_* gauges ride the same verbatim lane (host-side injection,
    # the mesh/fault passthrough discipline)
    diag = {"diag_top_score_milli": 940}
    d1 = stats_mod.reference_summary({**s, **diag})
    d2 = stats_mod.reference_summary({**s, **diag},
                                     wall_seconds=s["measured_ticks"]
                                     * 2.0)
    for k in ("window_cnt", "window_wrapped", "window_slots",
              "window_ticks_per", "diag_top_score_milli"):
        assert d1[k] == d2[k] == ({**s, **diag})[k], k   # never scaled
    parsed = stats_mod.parse_summary(stats_mod.format_summary(d1))
    for k in ("window_cnt", "window_wrapped", "window_slots",
              "window_ticks_per", "diag_top_score_milli"):
        assert parsed[k] == d1[k], k
    # the default (windows-off) line carries none of them
    eng0, st0 = run_engine()
    p0 = stats_mod.parse_summary(eng0.summary_line(st0, wall_seconds=1.0))
    assert not any(k.startswith(("window_", "diag_")) for k in p0)


def test_slo_keys_round_trip_exactly():
    """SLO-plane runs (Config.slo, obs/histo.py + obs/slo.py) put the
    exact-histogram percentiles and the error-budget fields on the
    [summary] line: the hist_*/burn_* keys pass through VERBATIM (counts
    and dimensionless burn rates, never time-scaled), the slo_fam*
    percentiles are tick-valued latencies and scale with wall seconds
    famlat-style while the slo_* counters stay integral, everything
    round-trips through the parser port, and the default line carries
    none of them."""
    eng, st = run_engine(slo=True, arrival="poisson", arrival_rate=6.0)
    s = eng.summary(st)
    # host-side tracker fields ride the same line (bench.py --serve
    # merges SloTracker.summary_fields() before formatting)
    host = {"slo_alert_cnt": 2, "slo_alert_active": 0,
            "slo_breach_ticks": 40, "slo_served_breach_cnt": 1,
            "slo_abort_breach_cnt": 0, "burn_fast": 0.0,
            "burn_slow": 1.5, "burn_served_frac": 0.98,
            "burn_abort_rate": 0.12}
    d1 = stats_mod.reference_summary({**s, **host})
    d2 = stats_mod.reference_summary({**s, **host},
                                     wall_seconds=s["measured_ticks"]
                                     * 2.0)
    # percentiles scale like famlat/ccl*; counts and burn rates never
    assert abs(d2["slo_fam0_p99"] - 2.0 * d1["slo_fam0_p99"]) < 1e-6
    for k in ("slo_fam0_n", "hist_total_cnt", "hist_phase_cnt",
              "slo_alert_cnt", "slo_breach_ticks"):
        assert d2[k] == d1[k] == (s | host)[k], k
    for k in ("burn_fast", "burn_slow", "burn_served_frac",
              "burn_abort_rate"):
        assert d2[k] == d1[k] == host[k], k
    # exact-name round trip through the parser port
    parsed = stats_mod.parse_summary(stats_mod.format_summary(d1))
    for k in list(host) + ["hist_total_cnt", "hist_phase_cnt",
                           "slo_fam0_n", "slo_fam0_p50", "slo_fam0_p95",
                           "slo_fam0_p99"]:
        assert parsed[k] == pytest.approx(d1[k]), k
    # the reconciliation identity survives the round trip
    assert parsed["hist_total_cnt"] == parsed["txn_cnt"]
    # the default (slo-off) line carries none of them
    eng0, st0 = run_engine()
    p0 = stats_mod.parse_summary(eng0.summary_line(st0, wall_seconds=1.0))
    assert not any(k.startswith(("slo_", "hist_", "burn_")) for k in p0)
