"""OCC golden micro-schedules (OptCC::central_validate, occ.cpp:116-294)."""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.engine.state import STATUS_BACKOFF
from tests.test_engine_nowait import make_pool, small_cfg


def test_committed_write_in_window_aborts_reader():
    # txn0 (start tick 0): [k5 R, k1 R, k2 R]; txn1: [k5 W, k8 W] n_req=2.
    # txn1 commits at tick2 (wcommit[k5]=2 > txn0.start_tick=0)
    # -> txn0's validation at tick3 fails (occ.cpp:167-180).
    keys = np.array([[5, 1, 2], [5, 8, 8]], np.int32)
    iw = np.array([[False, False, False], [True, True, True]])
    pool = make_pool(keys, iw, n_req=[3, 2])
    eng = Engine(small_cfg(cc_alg="OCC", batch_size=2, query_pool_size=2,
                           req_per_query=3), pool=pool)
    st = eng.run(4)
    s = eng.summary(st)
    assert s["txn_cnt"] == 1                  # txn1
    assert int(st.txn.status[0]) == STATUS_BACKOFF
    assert s["total_txn_abort_cnt"] == 1


def test_same_tick_writer_kills_later_reader():
    # both finish the same tick; serialized by ts: txn0 (writer, older)
    # passes, txn1 (reader of the same key, younger) conflicts
    # (active-writer check, occ.cpp:185-199).
    keys = np.array([[5, 1], [5, 2]], np.int32)
    iw = np.array([[True, True], [False, False]])
    pool = make_pool(keys, iw)
    eng = Engine(small_cfg(cc_alg="OCC", batch_size=2, query_pool_size=2),
                 pool=pool)
    st = eng.run(3)
    s = eng.summary(st)
    assert s["txn_cnt"] == 1
    assert int(st.txn.status[1]) == STATUS_BACKOFF


def test_same_tick_disjoint_writers_both_commit():
    # earlier reader does not invalidate later writer (backward validation
    # checks only earlier WRITE sets)
    keys = np.array([[5, 1], [5, 2]], np.int32)
    iw = np.array([[False, True], [True, True]])
    pool = make_pool(keys, iw)
    eng = Engine(small_cfg(cc_alg="OCC", batch_size=2, query_pool_size=2),
                 pool=pool)
    st = eng.run(3)
    # txn0 reads k5, writes k1; txn1 (younger) writes k5,k2: txn1's write of
    # k5 sits after txn0's READ only -> both commit
    assert eng.summary(st)["txn_cnt"] == 2


def test_read_only_never_aborts():
    cfg = Config(batch_size=32, synth_table_size=256, req_per_query=4,
                 query_pool_size=256, zipf_theta=0.9, txn_read_perc=1.0,
                 cc_alg="OCC", warmup_ticks=0)
    eng = Engine(cfg)
    st = eng.run(30)
    s = eng.summary(st)
    assert s["total_txn_abort_cnt"] == 0
    assert s["txn_cnt"] > 0


@pytest.mark.parametrize("window", [1, 4])
def test_oracle_under_contention(window):
    cfg = Config(batch_size=64, synth_table_size=256, req_per_query=4,
                 query_pool_size=512, zipf_theta=0.9, tup_read_perc=0.5,
                 cc_alg="OCC", warmup_ticks=0, acquire_window=window)
    eng = Engine(cfg)
    st = eng.run(60)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert s["total_txn_abort_cnt"] > 0       # hot keys must conflict
    assert np.asarray(st.data).sum() == s["write_cnt"]
