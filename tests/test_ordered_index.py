"""Ordered-index capability (the index_btree.cpp:88-168 answer): binary
search + bounded range windows over sorted key columns, and a range-scan
workload expressed in the engine's access-program format."""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.storage.ordered import NULL_ROW, OrderedIndex
from deneva_tpu.workloads.base import QueryPool


def sparse_keys(n=500, seed=3):
    rng = np.random.default_rng(seed)
    return np.unique(rng.integers(0, 10_000, n))


def test_lookup_and_range_match_numpy():
    keys = sparse_keys()
    idx = OrderedIndex(keys)
    rng = np.random.default_rng(5)
    q = rng.integers(0, 10_000, 256).astype(np.int32)

    got = np.asarray(idx.lookup(q))
    for qi, gi in zip(q.tolist(), got.tolist()):
        where = np.searchsorted(keys, qi)
        if where < len(keys) and keys[where] == qi:
            assert gi == where
        else:
            assert gi == -1

    lo, hi = 2000, 4000
    assert int(idx.range_count(lo, hi)) == int(
        ((keys >= lo) & (keys < hi)).sum())
    win = np.asarray(idx.range_window(lo, 32, hi=hi))
    expect = np.nonzero((keys >= lo) & (keys < hi))[0][:32]
    live = win[win != int(NULL_ROW)]
    assert (live == expect).all()


def test_batched_range_windows():
    keys = sparse_keys()
    idx = OrderedIndex(keys)
    los = np.array([0, 5000, 9999, 12000], np.int32)
    win = np.asarray(idx.range_window(los, 8))
    assert win.shape == (4, 8)
    for i, lo in enumerate(los.tolist()):
        expect = np.nonzero(keys >= lo)[0][:8]
        live = win[i][win[i] != int(NULL_ROW)]
        assert (live == expect).all()


def test_range_scan_workload_runs_through_engine():
    """A range-scan workload IS expressible: each txn's access program is
    the index's range window over the (sorted, sparse) key population —
    exactly how a btree-backed scan would drive row accesses."""
    table = 1 << 12
    pop = np.unique(np.random.default_rng(9).integers(0, table, 600))
    idx = OrderedIndex(pop)
    Q, W = 256, 6
    rng = np.random.default_rng(11)
    los = rng.integers(0, table, Q).astype(np.int32)
    rows = np.asarray(idx.range_window(los, W))          # (Q, W) positions
    keys = np.where(rows != int(NULL_ROW), pop[np.clip(rows, 0, len(pop)-1)],
                    np.int32(2**31 - 1)).astype(np.int32)
    n_req = (rows != int(NULL_ROW)).sum(axis=1).astype(np.int32)
    # last access of each scan is an update (scan-and-touch)
    iw = np.zeros_like(keys, dtype=bool)
    iw[np.arange(Q), np.maximum(n_req - 1, 0)] = n_req > 0
    pool = QueryPool(keys=keys, is_write=iw, n_req=np.maximum(n_req, 1),
                     home_part=np.zeros(Q, np.int32),
                     txn_type=np.zeros(Q, np.int32),
                     args=np.zeros((Q, 1), np.int32),
                     aux=np.zeros((Q, W), np.int32))
    cfg = Config(cc_alg="NO_WAIT", batch_size=64, synth_table_size=table,
                 req_per_query=W, query_pool_size=Q, warmup_ticks=0)
    eng = Engine(cfg, pool=pool)
    st = eng.run(40)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert int(np.asarray(st.data).sum()) == s["write_cnt"]
