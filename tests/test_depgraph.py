"""Conflict dependency observatory tests (deneva_tpu/obs/depgraph.py).

The observatory is an accounting identity, not an estimate — with the
edge ring unwrapped, the sampled wait-for edges must reconcile EXACTLY
against the ``twopl_wait_cnt`` integral and partition EXACTLY into the
``abort_*_cnt`` taxonomy, for every CC plugin and both engines.  A
wrapped ring must refuse loudly.  The off path (``Config.depgraph``
False, the default) carries zero extra device arrays and is proved
byte-identical by the lint certifier (tests/test_certify.py); here we
assert the array surface directly.  The Perfetto flow arrows use a
string id namespace that must never collide with the flight recorder's
integer abort-flow ids when obs/export.py merges both span sources.
"""

import numpy as np
import pytest

from deneva_tpu.cc import base as cc_base
from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.obs import depgraph as obs_depgraph
from deneva_tpu.obs import export as obs_export
from deneva_tpu.obs import flight as obs_flight
from deneva_tpu.obs import report as obs_report

BASE = dict(batch_size=64, synth_table_size=256, req_per_query=4,
            zipf_theta=0.9, query_pool_size=256, warmup_ticks=0)

#: the exact device-array surface the observatory adds (keep in sync
#: with obs/depgraph.py init_depgraph — the off-path purity test
#: asserts the set)
DEP_STATS_KEYS = {
    "arr_dep_ring", "arr_dep_blocker", "arr_dep_depth_hist",
    "arr_dep_part", "arr_dep_peak", "arr_dep_cnt",
    "dep_wait_edge_cnt", "dep_abort_edge_cnt", "dep_nullkey_edge_cnt",
    "dep_cross_edge_cnt", "dep_depth_sum", "dep_convoy_width_sum",
}


def dep_cfg(**kw):
    base = dict(cc_alg="WAIT_DIE", depgraph=True, abort_attribution=True,
                **BASE)
    base.update(kw)
    return Config(**base)


def run(cfg, n_ticks=64):
    eng = Engine(cfg)
    st = eng.run(n_ticks)
    return eng, st, eng.summary(st)


# MAAT's chain-validate compile alone costs ~10 s — `-m slow` per the
# tier-1 870 s budget split (its reconciliation shape is OCC's)
@pytest.mark.parametrize("alg", ["NO_WAIT", "WAIT_DIE", "TIMESTAMP",
                                 "MVCC", "OCC",
                                 pytest.param("MAAT",
                                              marks=pytest.mark.slow),
                                 "CALVIN"])
def test_reconciles_exactly(alg):
    """wait edges == twopl_wait_cnt, abort edges partition into the
    abort taxonomy per reason, partition plane sums — every plugin."""
    _, st, summary = run(dep_cfg(cc_alg=alg, warmup_ticks=8))
    snap = obs_depgraph.snapshot(st.stats)
    assert not snap["wrapped"]
    bad = obs_depgraph.reconcile(snap, summary, warmup_ticks=8)
    assert not bad, bad
    # every edge row is well-formed: waiter in range, reason registered
    B = snap["batch"]
    reasons = len(cc_base.ABORT_REASONS)
    for e in snap["edges"]:
        assert 0 <= e["waiter"] < B
        assert 0 <= e["reason"] <= reasons
        assert -1 <= e["blocker"] < B


def test_wait_chains_and_convoys_measured():
    """A WAIT-capable plugin under zipf 0.9 must measure real chains:
    nonzero depth, nonzero convoy width, histogram mass above bin 1.
    (warmup_ticks=8 shares the jit cache with the reconcile cell — the
    tier-1 870 s budget again)"""
    _, st, summary = run(dep_cfg(cc_alg="WAIT_DIE", warmup_ticks=8))
    snap = obs_depgraph.snapshot(st.stats)
    assert summary["dep_peak_depth"] >= 2
    assert summary["dep_peak_convoy"] >= 2
    assert sum(snap["depth_hist"][2:]) > 0
    assert snap["dep_depth_sum"] >= snap["dep_wait_edge_cnt"]


def test_ring_wrap_refuses_loudly():
    """An overfull ring must refuse reconciliation as the SOLE finding
    — approximate identities are never reported."""
    _, st, summary = run(dep_cfg(cc_alg="TIMESTAMP", dep_samples=32))
    snap = obs_depgraph.snapshot(st.stats)
    assert snap["wrapped"]
    bad = obs_depgraph.reconcile(snap, summary)
    assert len(bad) == 1 and bad[0][0] == "dep_ring_wrapped"
    assert summary["dep_ring_wrapped"] == 1


@pytest.mark.parametrize("alg", ["WAIT_DIE",
                                 pytest.param("OCC",
                                              marks=pytest.mark.slow)])
def test_depgraph_off_carries_nothing(alg):
    """The default path must not carry a single observatory array and
    its [summary] must not leak a dep_* key (the certifier proves the
    byte-level claim for every plugin x both engines; this pins the
    array surface — one lock + one validation plugin, the second
    slow-marked for the tier-1 budget)."""
    _, st, summary = run(Config(cc_alg=alg, abort_attribution=True,
                                **BASE), n_ticks=16)
    assert not (DEP_STATS_KEYS & set(st.stats))
    assert not [k for k in summary if k.startswith("dep_")]
    _, st2, _ = run(dep_cfg(cc_alg=alg), n_ticks=16)
    assert DEP_STATS_KEYS <= set(st2.stats)


def test_chain_depths_pointer_doubling():
    """The log-depth kernel against a hand-walked graph: a 4-chain, an
    isolated lane, a 2-cycle (saturates), a self-loop (masked)."""
    #        0 -> 1 -> 2 -> 3    4    5 <-> 6    7 -> 7
    ptr = np.array([1, 2, 3, -1, -1, 6, 5, 7], np.int32)
    d = np.asarray(obs_depgraph.chain_depths(ptr))
    assert d[3] == 0 and d[2] == 1 and d[1] == 2 and d[0] == 3
    assert d[4] == 0
    assert d[5] >= len(ptr) and d[6] >= len(ptr)   # cycle saturates
    assert d[7] == 0                               # self-loop masked


def _synth_snap(edges, nodes=1, batch=64):
    reasons = ("wait",) + tuple(cc_base.ABORT_REASONS)
    rows = []
    for w, b, key, reason, tick, node in edges:
        rows.append({"waiter": w, "blocker": b, "key": key,
                     "reason": reason, "tick": tick, "node": node,
                     "why": reasons[reason]})
        if nodes > 1 and b >= 0:
            rows[-1]["blocker_node"] = b // batch
            rows[-1]["blocker_slot"] = b % batch
    return {"columns": list(obs_depgraph.EDGE_COLUMNS), "nodes": nodes,
            "samples": 1 << 10, "batch": batch, "edge_cnt": len(rows),
            "wrapped": False, "edges": rows,
            "depth_hist": [0] * obs_depgraph.DEPTH_BINS,
            "part_edges": [len(rows)], "peak_depth": 0,
            "peak_convoy": 0, "dep_wait_edge_cnt": len(rows),
            "dep_abort_edge_cnt": 0, "dep_nullkey_edge_cnt": 0,
            "dep_cross_edge_cnt": 0, "dep_depth_sum": 0,
            "dep_convoy_width_sum": 0}


def test_cycles_found_per_tick():
    """A 3-cycle at tick 5 is found once; a chain at tick 6 is not a
    cycle; cross-tick pointers never merge into one graph."""
    snap = _synth_snap([(0, 1, 9, 0, 5, 0), (1, 2, 9, 0, 5, 0),
                        (2, 0, 9, 0, 5, 0),          # cycle @5
                        (3, 4, 7, 0, 6, 0),          # chain @6
                        (4, 3, 7, 0, 7, 0)])         # back-edge @7 only
    cyc = obs_depgraph.cycles(snap)
    assert len(cyc) == 1 and cyc[0]["tick"] == 5
    assert sorted(s for _, s in cyc[0]["cycle"]) == [0, 1, 2]


def test_critical_paths_join_flight_spans():
    """The longest blocking chain behind a committed span, walked from
    the span's own slot through the sampled tick graphs."""
    snap = _synth_snap([(0, 1, 9, 0, 5, 0), (1, 2, 9, 0, 5, 0),
                        (0, 1, 9, 0, 6, 0)])
    fsnap = {"spans": [{"kind": 0, "node": 0, "slot": 0, "admit": 4,
                        "end": 8, "block": 3}]}
    rows = obs_depgraph.critical_paths(snap, fsnap)
    assert rows and rows[0]["max_depth"] == 2 and rows[0]["at_tick"] == 5
    assert [e["waiter"] for e in rows[0]["path"]] == [0, 1]


def test_flow_events_schema_and_blockerless_skip():
    """String ``dep<n>`` flow ids, s/f pairs, blocker -1 edges draw no
    arrow (a vertex that does not exist)."""
    snap = _synth_snap([(0, 1, 9, 0, 5, 0), (2, -1, 7, 0, 5, 0),
                        (3, 0, 7, 2, 6, 0)])
    evs = obs_depgraph.flow_events(snap)
    assert len(evs) == 4                       # 2 arrows x (s, f)
    assert {e["ph"] for e in evs} == {"s", "f"}
    assert all(isinstance(e["id"], str) and e["id"].startswith("dep")
               for e in evs)
    assert evs[2]["name"].startswith("kills:")


def test_export_flow_id_namespaces_never_collide(tmp_path):
    """The obs/export.py regression: merging a record whose flight span
    track emits integer abort-flow ids with its own depgraph string
    flows — and a SECOND record of both — must keep all four flow-id
    families disjoint (Perfetto unites flow phases by id alone)."""
    cfg = dep_cfg(cc_alg="WAIT_DIE", flight=True,
                  flight_samples=1 << 14)
    eng, st, summary = run(cfg)
    rec = {"timeline": {},
           "flight": obs_flight.snapshot(st.stats),
           "depgraph": obs_depgraph.snapshot(st.stats)}
    ev0 = obs_export.record_events(rec, pid_base=0)
    ev1 = obs_export.record_events(rec,
                                   pid_base=obs_export.PID_STRIDE)

    def flow_ids(evs):
        return {(e["ph"], e["id"]) for e in evs
                if e["ph"] in ("s", "t", "f")}

    f0, f1 = flow_ids(ev0), flow_ids(ev1)
    assert f0 and f1, "both records must emit flow arrows"
    assert not ({i for _, i in f0} & {i for _, i in f1}), \
        "per-record flow-id namespaces must be disjoint"
    # within one record every id is the STRING "<pid_base>:<fid>"
    # (additive integer striding aliased records — the original bug);
    # the flight family keeps an all-digit suffix, depgraph a "dep<n>"
    # suffix, so the two families stay disjoint inside the record too
    ids0 = {i for _, i in f0}
    assert all(isinstance(i, str) and i.startswith("0:") for i in ids0)
    flight0 = {i for i in ids0 if i.split(":", 1)[1].isdigit()}
    dep0 = {i for i in ids0 if i.split(":", 1)[1].startswith("dep")}
    assert flight0 and dep0, "both flow families must be present"
    assert flight0 | dep0 == ids0 and not (flight0 & dep0)


def test_report_section_and_convoy_watchdog():
    """[depgraph] renders with the headline identities; the CONVOY bit
    (256) arms on a run-mean convoy width >= CONVOY_WIDTH_MIN."""
    _, st, summary = run(dep_cfg(cc_alg="TIMESTAMP"))
    snap = obs_depgraph.snapshot(st.stats)
    rep = obs_report.build_report(summary, depgraph=snap)
    txt = obs_report.render_text(rep)
    assert "[depgraph]" in txt and "chain depth" in txt
    mean_w = summary["dep_convoy_width_sum"] / max(
        summary["measured_ticks"], 1)
    flagged = any(n == "CONVOY" for n, _ in
                  rep["watchdog"]["findings"])
    assert flagged == (mean_w >= obs_report.CONVOY_WIDTH_MIN)
    if flagged:
        assert rep["watchdog"]["exit_code"] & obs_report.CONVOY


def test_regress_chain_depth_ceiling_self_arms_then_gates():
    """The bench.py --depgraph history record: the per-alg peak chain
    depth feeds an INVERTED obs/regress.py ceiling (depth GROWING past
    the prior median = the same cell serializing commits behind longer
    chains), self-arming on the first recorded sweep."""
    from deneva_tpu.obs import regress
    doc1 = {"metric": "depgraph_chain", "value": 8.0,
            "depgraph_chain": {"WAIT_DIE": {"max_chain_depth": 8}}}
    doc2 = {"metric": "depgraph_chain", "value": 30.0,
            "depgraph_chain": {"WAIT_DIE": {"max_chain_depth": 30}}}
    e1 = regress._entry("h", (1, 1.0), doc1)
    e2 = regress._entry("h", (1, 2.0), doc2)
    # first sweep: no prior -> the ceiling self-arms, nothing fails
    r1 = regress.gate([e1])
    assert not r1["failures"]
    assert any("depgraph_max_chain_depth[WAIT_DIE]" in s
               for s in r1["skipped"])
    # second sweep: depth ~4x the median -> regression
    r2 = regress.gate([e1, e2])
    assert any("depgraph_max_chain_depth[WAIT_DIE]" in f
               for f in r2["failures"])


def test_depgraph_excludes_exchange_split():
    with pytest.raises(AssertionError):
        Config(cc_alg="CALVIN", depgraph=True, abort_attribution=True,
               exchange_split=True, **BASE)


def test_sharded_reconciles_psum_parity_and_cross_node_chain():
    """4-node zipf-0.9: exact cluster reconciliation, device-psum'd
    depth/partition planes bit-equal to the numpy shard sum, and at
    least one measured CROSS-NODE blocking chain (global blocker ids)."""
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = dep_cfg(batch_size=32, synth_table_size=512, node_cnt=4,
                  part_cnt=4, query_pool_size=256)
    eng = ShardedEngine(cfg)
    st = eng.run(48, eng.init_state())
    summary = eng.summary(st)
    snap = eng.depgraph_snapshot(st)
    bad = obs_depgraph.reconcile(snap, summary)
    assert not bad, bad
    for key in ("arr_dep_depth_hist", "arr_dep_part"):
        dev = eng.depgraph_cluster_plane(st, key)
        host = np.asarray(st.stats[key]).sum(axis=0)
        assert (dev == host).all(), key
    cross = [e for e in snap["edges"] if e["blocker"] >= 0
             and e["blocker_node"] != e["node"]]
    assert summary["dep_cross_edge_cnt"] > 0 and cross, \
        "a 4-node zipf-0.9 cell must measure cross-node blocking"
    # the cross-node population in the ring matches the counter
    assert len(cross) == summary["dep_cross_edge_cnt"]
