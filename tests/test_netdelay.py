"""Network cost model (Config.net_delay_ticks): the rebuild of the
reference's artificial message delay (system/msg_queue.cpp:81-124
NETWORK_DELAY_TEST) and message-carried network latency
(transport/message.h:51-57).

Semantics under test: a remote access costs 2D ticks (request + response
transit) with the owner's decision binding at arbitration time; a
multi-partition commit pays 2D more for the 2PC prepare round; locks and
prewrites stay held across the transit windows; local accesses bypass
entirely.
"""

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.parallel.sharded import ShardedEngine

# This whole module was a collection error at the seed (pre shard_map
# compat fix); its ~3.5 min of sharded runs exceed the tier-1 time
# budget -- run with `-m slow`.
pytestmark = pytest.mark.slow

BASE = dict(node_cnt=2, part_cnt=2, batch_size=64,
            synth_table_size=1 << 12, req_per_query=4, zipf_theta=0.6,
            query_pool_size=1 << 10, mpr=1.0, part_per_txn=2,
            warmup_ticks=0)


def _run(cfg, n_ticks=40):
    eng = ShardedEngine(cfg)
    st = eng.run(n_ticks)
    s = eng.summary(st)
    assert eng.global_data_sum(st) == s["write_cnt"], (cfg.cc_alg, "conservation")
    return s


@pytest.mark.parametrize("alg", ["NO_WAIT", "TIMESTAMP", "OCC", "MAAT",
                                 "CALVIN"])
def test_delay_conserves_and_commits(alg):
    s = _run(Config(cc_alg=alg, net_delay_ticks=2, **BASE))
    assert s["txn_cnt"] > 0
    if alg == "CALVIN":
        assert s["total_txn_abort_cnt"] == 0


def test_latency_scales_with_delay():
    """Commit latency must grow with D (each remote access pays the round
    trip) and throughput at a fixed in-flight window must fall — the
    paper's distributed tax."""
    lat, tput = [], []
    for D in (0, 1, 3):
        s = _run(Config(cc_alg="NO_WAIT", net_delay_ticks=D, **BASE))
        lat.append(s["avg_latency_ticks_short"])
        tput.append(s["tput_per_tick"])
    assert lat[0] < lat[1] < lat[2], lat
    assert tput[0] > tput[1] > tput[2], tput
    # R=4 accesses, ~half remote at part_per_txn=2: D=3 adds >= 8 ticks
    assert lat[2] - lat[0] >= 8, lat


def test_local_txns_bypass_delay_exactly():
    """mpr=0 keeps every access home-local: the delay machinery must be
    a bit-exact no-op (same commits/aborts as D=0)."""
    kw = {**BASE, "mpr": 0.0, "part_per_txn": 1}
    a = _run(Config(cc_alg="NO_WAIT", net_delay_ticks=0, **kw))
    b = _run(Config(cc_alg="NO_WAIT", net_delay_ticks=4, **kw))
    for k in ("txn_cnt", "total_txn_abort_cnt", "write_cnt"):
        assert a[k] == b[k], (k, a[k], b[k])


def test_network_time_integral():
    """lat_network_time must integrate real transit waits when D > 0 and
    scale with D."""
    s1 = _run(Config(cc_alg="NO_WAIT", net_delay_ticks=1, **BASE))
    s3 = _run(Config(cc_alg="NO_WAIT", net_delay_ticks=3, **BASE))
    assert s1["lat_network_time"] > 0
    # per-commit network share grows with D
    n1 = s1["lat_network_time"] / max(s1["txn_cnt"], 1)
    n3 = s3["lat_network_time"] / max(s3["txn_cnt"], 1)
    assert n3 > 1.5 * n1, (n1, n3)


DELAY_PARITY_THRESH = {
    # measured at D in {1,3}: NO_WAIT/WAIT_DIE/MVCC/CALVIN exact,
    # TIMESTAMP 0.25%, OCC 0.12% (x~2 noise headroom).  MAAT measured
    # -1.1..-2.6% over seeds (round 5, was 3-4.5%): prepared neighbors
    # now push via cases 2/4/5 and commit-time forward validation runs
    # at the commit exchange; the residual is cross-owner same-tick push
    # invisibility during the transit window (PARITY.md).
    "NO_WAIT": 0.005, "WAIT_DIE": 0.005, "TIMESTAMP": 0.01, "MVCC": 0.005,
    "OCC": 0.01, "MAAT": 0.035, "CALVIN": 0.005,
}


@pytest.mark.parametrize("alg", list(DELAY_PARITY_THRESH))
def test_delay_parity_vs_oracle(alg):
    """The sequential oracle replays the delayed tick protocol; abort-rate
    divergence at D=1 must stay at (near-)exact levels — the delay model
    is part of the CC semantics, not a perf knob."""
    from deneva_tpu.oracle.parity import PARITY_EXTRA, run_pair_sharded
    extra = PARITY_EXTRA.get(alg, {})
    cfg = Config(cc_alg=alg, node_cnt=2, part_cnt=2, batch_size=64,
                 synth_table_size=1 << 14, req_per_query=6, zipf_theta=0.6,
                 query_pool_size=1 << 12, mpr=1.0, part_per_txn=2,
                 warmup_ticks=0, net_delay_ticks=1, **extra)
    r = run_pair_sharded(cfg, 40)
    assert r["batched_conserved"] and r["sequential_conserved"], r
    assert r["abort_rate_divergence"] <= DELAY_PARITY_THRESH[alg], r
    assert 0.95 <= r["tput_ratio"] <= 1.08, r


def test_delay_parity_deep_transit():
    """D=3 stays exact for the lock family (the latch arithmetic has no
    off-by-one drift at deeper pipelines)."""
    from deneva_tpu.oracle.parity import run_pair_sharded
    cfg = Config(cc_alg="NO_WAIT", node_cnt=2, part_cnt=2, batch_size=64,
                 synth_table_size=1 << 14, req_per_query=6, zipf_theta=0.6,
                 query_pool_size=1 << 12, mpr=1.0, part_per_txn=2,
                 warmup_ticks=0, net_delay_ticks=3)
    r = run_pair_sharded(cfg, 40)
    assert r["abort_rate_divergence"] == 0.0, r
    assert r["tput_ratio"] == 1.0, r


def test_occ_prepare_marks_leak_free():
    """Every UNEXPIRED prepare mark must belong to a txn whose vote round
    is still in flight (vote latched, commit/abort pending) on some node —
    anything else is a leaked reservation.  Expired marks are allowed
    (that is the designed recovery for releases lost to exchange
    overflow) because pconf ignores them."""
    cfg = Config(cc_alg="OCC", net_delay_ticks=2, **BASE)
    eng = ShardedEngine(cfg)
    st = eng.run(40)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    tick = np.asarray(st.tick).max()
    prep = np.asarray(st.db["occ_prep"]).reshape(-1)
    until = np.asarray(st.db["occ_prep_until"]).reshape(-1)
    live = (prep > 0) & (until > tick)
    # txns with a vote in flight, across all nodes
    vt = np.asarray(st.net["vote_tick"]).reshape(-1)
    ts = np.asarray(st.txn.ts).reshape(-1)
    inflight = set(ts[vt < np.int32(2**31 - 1)].tolist())
    leaked = [int(p) for p in prep[live] if int(p) not in inflight]
    assert not leaked, leaked
