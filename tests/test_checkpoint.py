"""Bit-exact checkpoint/restore (engine/checkpoint.py): save -> restore
round-trips the carry pytree leaf-for-leaf for every CC plugin, the
resumed run's [summary] matches continuing in memory, and a damaged or
mismatched checkpoint fails loudly with ValueError — never a silent
wrong resume."""

import json
import shutil

import numpy as np
import pytest
import jax

from deneva_tpu.config import Config
from deneva_tpu.engine import checkpoint
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.parallel.sharded import ShardedEngine

# every plugin round-trips; the tier-1 gate keeps the two extreme
# plugins (NO_WAIT's lock path, CALVIN's epoch path — the recovery
# substrate) and the arrival-fixture WAIT_DIE resume below, while the
# other engine compiles ride the slow tier (the tier-1 wall budget is
# nearly spent — ROADMAP.md)
ALGS = ("NO_WAIT",
        pytest.param("WAIT_DIE", marks=pytest.mark.slow),
        pytest.param("TIMESTAMP", marks=pytest.mark.slow),
        pytest.param("MVCC", marks=pytest.mark.slow),
        pytest.param("OCC", marks=pytest.mark.slow),
        pytest.param("MAAT", marks=pytest.mark.slow),
        "CALVIN")


def small_cfg(**kw):
    base = dict(cc_alg="WAIT_DIE", batch_size=32,
                synth_table_size=1 << 10, req_per_query=4,
                query_pool_size=1 << 9, zipf_theta=0.6,
                tup_read_perc=0.5, warmup_ticks=0)
    base.update(kw)
    return Config(**base)


def _leaves(state):
    return jax.tree_util.tree_leaves(state)


def assert_states_equal(a, b):
    fa, fb = _leaves(a), _leaves(b)
    assert len(fa) == len(fb)
    for i, (x, y) in enumerate(zip(fa, fb)):
        assert np.array_equal(np.asarray(x), np.asarray(y)), f"leaf {i}"


@pytest.mark.parametrize("alg", ALGS)
def test_round_trip_every_plugin(alg, tmp_path):
    """save -> restore is leaf-for-leaf bit-exact mid-run, and the
    restored carry resumes to the SAME [summary] as continuing the
    in-memory state — for all seven CC plugins."""
    eng = Engine(small_cfg(cc_alg=alg))
    st = eng.run(5)
    path = checkpoint.save(str(tmp_path / "ck.npz"), st, cfg=eng.cfg)
    rst = checkpoint.restore(path, eng.init_state(), cfg=eng.cfg)
    assert_states_equal(st, rst)
    cont = eng.run(5, st)
    resumed = eng.run(5, rst)
    assert_states_equal(cont, resumed)
    # the counter summaries match too (the *_util keys sample the host
    # clock at call time and are excluded from the bit-parity claim)
    s1, s2 = eng.summary(cont, 1.0), eng.summary(resumed, 1.0)
    for k, v in s1.items():
        if not k.endswith("_util"):
            assert s2[k] == v, k


# one saved OPEN-SYSTEM checkpoint (arrival plane in the carry) shared
# by the resume test and every damaged-file test below — the error
# paths never need their own engine compile
@pytest.fixture(scope="module")
def saved(tmp_path_factory):
    eng = Engine(small_cfg(arrival="poisson", arrival_rate=8.0))
    st = eng.run(6)
    path = checkpoint.save(
        str(tmp_path_factory.mktemp("ckpt") / "ck.npz"), st, cfg=eng.cfg)
    return eng, st, path


def test_arrival_stream_survives_restore(saved):
    """The open-system arrival plane rides the carry (PRNG key, queue,
    backlog), so a restored run draws the SAME arrival stream as the
    uninterrupted one."""
    eng, st, path = saved
    rst = checkpoint.restore(path, eng.init_state(), cfg=eng.cfg)
    assert_states_equal(st, rst)
    cont = eng.run(6, st)
    resumed = eng.run(6, rst)
    assert_states_equal(cont, resumed)
    s1, s2 = eng.summary(cont), eng.summary(resumed)
    assert s1["arrival_cnt"] == s2["arrival_cnt"]


@pytest.mark.slow
def test_sharded_round_trip_four_nodes(tmp_path):
    """The node-stacked ShardState round-trips and resumes bit-exactly
    on a 4-node sharded cell."""
    cfg = Config(cc_alg="NO_WAIT", node_cnt=4, part_cnt=4, batch_size=32,
                 synth_table_size=1 << 12, req_per_query=4,
                 query_pool_size=1 << 10, zipf_theta=0.6,
                 tup_read_perc=0.5, warmup_ticks=0, mpr=1.0,
                 part_per_txn=4)
    eng = ShardedEngine(cfg)
    st = eng.run(10)
    path = checkpoint.save(str(tmp_path / "ck.npz"), st, cfg=cfg)
    rst = checkpoint.restore(path, eng.init_state(), cfg=cfg)
    assert_states_equal(st, rst)
    cont = eng.run(10, st)
    resumed = eng.run(10, rst)
    assert_states_equal(cont, resumed)


def test_truncated_checkpoint_fails_loudly(saved, tmp_path):
    eng, _, path = saved
    bad = str(tmp_path / "trunc.npz")
    with open(path, "rb") as f:
        blob = f.read()
    with open(bad, "wb") as f:
        f.write(blob[:len(blob) // 2])
    with pytest.raises(ValueError, match="truncated or corrupt"):
        checkpoint.restore(bad, eng.init_state(), cfg=eng.cfg)


def test_corrupted_leaf_fails_crc(saved, tmp_path):
    eng, _, path = saved
    bad = str(tmp_path / "corrupt.npz")
    shutil.copy(path, bad)
    # flip one element of one leaf, keep the ORIGINAL metadata: the
    # stored crc32 must catch the damage
    with np.load(bad) as z:
        arrs = {k: np.array(z[k]) for k in z.files}
    meta = json.loads(bytes(arrs["_meta"]))
    assert meta["format"] == checkpoint.FORMAT
    victim = next(k for k in sorted(arrs)
                  if k.startswith("leaf_") and arrs[k].size > 0)
    flat = arrs[victim].reshape(-1)
    flat[0] = ~flat[0] if flat.dtype == np.bool_ else flat[0] + 1
    np.savez(bad, **arrs)
    with pytest.raises(ValueError, match="crc32 mismatch"):
        checkpoint.restore(bad, eng.init_state(), cfg=eng.cfg)


def test_wrong_geometry_rejected(saved):
    _, _, path = saved
    # a bigger batch changes leaf shapes/counts; init_state alone never
    # compiles the tick, so the mismatch check costs nothing
    other = Engine(small_cfg(arrival="poisson", arrival_rate=8.0,
                             batch_size=64))
    with pytest.raises(ValueError):
        checkpoint.restore(path, other.init_state(), cfg=other.cfg)


def test_wrong_config_fingerprint_rejected(saved):
    """Same shapes, different knobs: the config fingerprint catches a
    checkpoint from a different experiment before a silent wrong
    resume."""
    _, _, path = saved
    other = Engine(small_cfg(arrival="poisson", arrival_rate=8.0,
                             zipf_theta=0.9))
    with pytest.raises(ValueError, match="fingerprint"):
        checkpoint.restore(path, other.init_state(), cfg=other.cfg)
