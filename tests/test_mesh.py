"""Cluster mesh observatory tests (deneva_tpu/obs/mesh.py).

The traffic matrix is an accounting identity, not an estimate — with
``Config.mesh`` on, every cell of the N x N x type tensor reconciles
EXACTLY against the engine's own counters (attempted == delivered +
dropped against ``remote_entry_cnt``; tx == rx transposed; one response
word per delivered request; in-flight planes against
``lat_msg_queue_time``), for every CC plugin and replication topology.
The off path (``Config.mesh=False``, the default) must carry zero extra
device arrays and leave the ``[summary]`` line byte-identical; the on
path must hold the zero post-warmup recompile sentinel.

Sharded compiles dominate the cost, so deterministic cells are cached
module-wide and shared across tests (same config -> same schedule).
"""

import json

import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.obs import mesh as obs_mesh
from deneva_tpu.obs import trace as obs_trace
from deneva_tpu.obs.mesh import MESH_SUMMARY_KEYS, MSG_TYPES
from deneva_tpu.parallel.sharded import ShardedEngine

BASE = dict(node_cnt=2, part_cnt=2, batch_size=32,
            synth_table_size=1 << 12, req_per_query=4,
            query_pool_size=1 << 10, zipf_theta=0.6, tup_read_perc=0.5,
            warmup_ticks=0, mpr=1.0, part_per_txn=2)

ALGS = ["NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT",
        "CALVIN"]

#: the exact device-array surface the observatory adds (keep in sync
#: with obs/mesh.py init_mesh — the off-path purity test asserts the
#: set).  ``arr_mesh_inflight`` joins only for net_delay runs and
#: ``arr_mesh_trace`` only for traced runs.
MESH_STATS_KEYS = {
    "arr_mesh_tx", "arr_mesh_rx", "mesh_drop_cnt", "mesh_occ_sum",
    "mesh_occ_peak", "straggler_tick_cnt",
}

_cells = {}


def cell(alg, mesh=True, **kw):
    """Run (and cache) one deterministic sharded cell; returns
    (engine, state, summary)."""
    key = (alg, mesh, tuple(sorted(kw.items())))
    if key not in _cells:
        cfg = Config(cc_alg=alg, mesh=mesh, **{**BASE, **kw})
        eng = ShardedEngine(cfg)
        st = eng.run(40)
        _cells[key] = (eng, st, eng.summary(st))
    return _cells[key]


# Single runtime sentinel.  Per-plugin off-path byte-identity is now
# proven statically for every cell by the tick certifier's OFFPATH-IMPURE
# rule (deneva_tpu/lint/certify.py, LINT.md engine 3); this one cell
# remains to pin the runtime surface (stats keys, summary line) that the
# jaxpr-level proof does not cover.
@pytest.mark.parametrize("alg", ["WAIT_DIE"])
def test_mesh_off_is_byte_identical_and_carries_nothing(alg):
    """mesh=False (default): zero extra device arrays, zero summary
    keys; mesh=True adds EXACTLY the documented surface and leaves the
    schedule untouched."""
    eng_off, st_off, s_off = cell(alg, mesh=False)
    assert not any("mesh" in k for k in st_off.stats)
    line = eng_off.summary_line(st_off)
    assert "mesh" not in line and "imb_jain" not in line

    _, st_on, s_on = cell(alg, mesh=True)
    assert set(st_on.stats) - set(st_off.stats) == MESH_STATS_KEYS
    # the schedule itself is untouched — same commits, same aborts
    for k in ("txn_cnt", "total_txn_abort_cnt", "local_txn_start_cnt",
              "remote_entry_cnt"):
        assert s_on[k] == s_off[k], (k, s_on[k], s_off[k])
    # summary gains only the documented keys (arr_ keys are skipped)
    assert set(s_on) - set(s_off) == set(MESH_SUMMARY_KEYS)


@pytest.mark.slow  # second identical compile; tier-1 budget split
def test_mesh_off_line_is_reproducible():
    """Rerunning the identical mesh-off config reproduces the summary
    line byte for byte (modulo host-process utilization keys)."""
    eng, st, _ = cell("WAIT_DIE", mesh=False)

    def engine_bytes(ln):
        return ",".join(p for p in ln.split(",")
                        if not p.startswith(("mem_util=", "cpu_util=")))

    cfg = Config(cc_alg="WAIT_DIE", mesh=False, **BASE)
    eng2 = ShardedEngine(cfg)
    st2 = eng2.run(40)
    assert (engine_bytes(eng2.summary_line(st2))
            == engine_bytes(eng.summary_line(st)))


# tier-1 keeps one lock-pair cell (WAIT_DIE) and the epoch-exchange
# outlier (CALVIN); the remaining plugins recheck the same cell under
# `-m slow` per the tier-1 budget split
_SLOW_ALGS = [pytest.param(a, marks=pytest.mark.slow)
              for a in ("NO_WAIT", "TIMESTAMP", "MVCC", "OCC", "MAAT")]


@pytest.mark.parametrize("alg", ["WAIT_DIE", "CALVIN"] + _SLOW_ALGS)
def test_matrix_reconciles_exactly(alg):
    """Row/col sums against remote_entry_cnt, tx == rx transposed, one
    response per delivered entry, and the summary total — per plugin."""
    eng, st, s = cell(alg, mesh=True)
    snap = eng.mesh_snapshot(st)
    assert obs_mesh.reconcile(snap, s) == []
    assert s["mesh_tx_total"] > 0
    # Calvin rides the epoch lane, lock-based plugins the request lane
    tx = snap["tx"]
    if alg == "CALVIN":
        assert tx[:, :, obs_mesh.EPOCH].sum() > 0
        assert tx[:, :, obs_mesh.REQ].sum() == 0
    else:
        assert tx[:, :, obs_mesh.REQ].sum() > 0
        assert tx[:, :, obs_mesh.EPOCH].sum() == 0
    assert tx[:, :, obs_mesh.RESP].sum() > 0


# tier-2: engine 4 (lint/shard_certify.py) now proves the split
# exchange's collective plan statically for every plugin/flag cell, and
# test_scale_out.py::test_split_exchange_bit_parity_on_oracle_cell is
# the single tier-1 runtime sentinel for the split path
@pytest.mark.slow
def test_split_exchange_reconciles_and_matches_baseline():
    """Config.exchange_split (the capacity-bounded epoch-split
    exchange): the CALVIN cell still reconciles its traffic matrix
    EXACTLY — exchange B's receive side arrives as per-sub-round counts
    (note_commit_exchange_counts) and must meet the same tx == rx
    identities — and the [summary] is bit-identical to the single-round
    exchange's, the split path adding only its sub-round counter."""
    eng_b, _, s_base = cell("CALVIN", mesh=True)
    eng, st, s = cell("CALVIN", mesh=True, exchange_split=True,
                      route_capacity_factor=0.25)
    assert eng.cap < eng_b.cap                # genuinely capacity-bounded
    assert obs_mesh.reconcile(eng.mesh_snapshot(st), s) == []
    # the mesh-enabled split cell adds its sub-round counter AND the
    # mesh-side window mirror the round_windows reconcile identity pins
    assert set(s) - set(s_base) == {"exchange_round_cnt",
                                    "mesh_round_sum"}
    assert s["exchange_round_cnt"] > 0
    assert s["mesh_round_sum"] == s["exchange_round_cnt"]
    for k in s_base:
        assert s[k] == s_base[k], (k, s[k], s_base[k])


@pytest.mark.slow  # two full MAAT mesh cells; tier-1 keeps the
# test_scale_out.py rcache plane/gating cell and the runtime reconcile
def test_remote_cache_counters_and_attempts_identity():
    """Config.remote_cache (remote-grant stickiness): the MAAT cell's
    cache counters join the summary, every suppressed re-ship is an
    attempt the mesh never saw (attempts == shipped + suppressed, the
    reconcile() identity), and the matrix still reconciles exactly."""
    _, _, s_base = cell("MAAT", mesh=True)
    eng, st, s = cell("MAAT", mesh=True, remote_cache=True)
    assert obs_mesh.reconcile(eng.mesh_snapshot(st), s) == []
    for k in ("remote_attempt_cnt", "remote_cache_hit_cnt",
              "reship_suppressed_cnt"):
        assert k in s and k not in s_base, k
    assert (s["remote_attempt_cnt"]
            == s["remote_entry_cnt"] + s["reship_suppressed_cnt"])
    assert s["reship_suppressed_cnt"] > 0, \
        "contended 2-node MAAT cell must suppress some re-ships"
    assert s["remote_entry_cnt"] < s_base["remote_entry_cnt"], \
        "stickiness must cut shipped remote entries"


@pytest.mark.slow  # extra warmup-variant compile; tier-1 budget split
def test_matrix_reconciles_with_warmup():
    """The accumulation gate mirrors the bump() warmup gate on every
    leg, so the identities hold for ANY warmup."""
    eng, st, s = cell("WAIT_DIE", mesh=True, warmup_ticks=10)
    assert s["measured_ticks"] < 40
    snap = eng.mesh_snapshot(st)
    assert obs_mesh.reconcile(snap, s) == []


@pytest.mark.parametrize("alg", ["WAIT_DIE",
                                 pytest.param("MAAT",
                                              marks=pytest.mark.slow)])
def test_inflight_reconciles_with_net_delay(alg):
    """dly mode: the per-type in-flight planes decompose
    lat_msg_queue_time exactly (REQ + RESP + PREP partition the transit
    population) and the inflight arrays join the device surface."""
    eng, st, s = cell(alg, mesh=True, net_delay_ticks=2)
    assert "arr_mesh_inflight" in st.stats
    assert s["lat_msg_queue_time"] > 0
    snap = eng.mesh_snapshot(st)
    assert obs_mesh.reconcile(snap, s) == []
    # stacked (node, type) planes: total transit ticks == the integral
    assert snap["inflight"].shape == (2, len(obs_mesh.MSG_TYPES))
    assert int(snap["inflight"].sum()) == s["lat_msg_queue_time"]


def test_cluster_matrix_is_sum_of_shards():
    """The psum'd cluster matrix equals the numpy sum of the per-node
    tx planes BIT-EXACTLY (int32 addition is associative)."""
    eng, st, _ = cell("WAIT_DIE", mesh=True)
    cm = np.asarray(eng.mesh_cluster_matrix(st))
    tx = np.asarray(st.stats["arr_mesh_tx"])
    assert cm.dtype == np.int32
    assert np.array_equal(cm, tx.sum(axis=0, dtype=np.int32))


def test_ap_replica_rows_all_zero():
    """Active-passive: replicas never originate traffic — their tx rows
    (and the matching rx columns outside the replication lane) are
    all-zero, and the replication lane reconciles worker -> replica."""
    eng, st, s = cell("WAIT_DIE", mesh=True, node_cnt=2, part_cnt=1,
                      part_per_txn=1, repl_mode="ap", repl_cnt=2,
                      logging=True)
    snap = eng.mesh_snapshot(st)
    assert obs_mesh.reconcile(snap, s) == []
    tx = snap["tx"]
    n_parts = 1
    assert not tx[n_parts:].any()          # replica rows: silent
    # workers DID replicate: the repl lane points worker -> replica
    assert tx[:n_parts, :, obs_mesh.REPL].sum() > 0
    # replicas commit nothing by design -> Jain sits at ~k/n == 0.5,
    # still ABOVE the watchdog threshold (by-design asymmetry is clean)
    assert s["imb_jain"] == pytest.approx(0.5, abs=0.02)
    assert s["imb_jain"] >= obs_mesh.IMB_JAIN_MIN


def test_jain_index_and_imbalance_bit():
    """jain() algebra + the IMBALANCE (32) watchdog bit: balanced loads
    sit at 1.0 and stay clean; a one-hot load fires."""
    from deneva_tpu.obs import report as obs_report
    assert obs_mesh.jain(np.array([5, 5, 5, 5])) == 1.0
    assert obs_mesh.jain(np.zeros(4)) == 1.0       # vacuous balance
    assert obs_mesh.jain(np.array([8, 0, 0, 0])) == pytest.approx(0.25)
    clean = {"txn_cnt": 40, "imb_jain": 1.0}
    _, code = obs_report.watchdog(clean)
    assert not code & obs_report.IMBALANCE
    skewed = {"txn_cnt": 40, "imb_jain": 0.25}
    findings, code = obs_report.watchdog(skewed)
    assert code & obs_report.IMBALANCE
    assert any(f[0] == "IMBALANCE" for f in findings)


def test_report_carries_mesh_section():
    """build_report(mesh=...) renders the [mesh] section: totals,
    by-type breakdown, top pairs and the imbalance line."""
    from deneva_tpu.obs import report as obs_report
    eng, st, s = cell("WAIT_DIE", mesh=True)
    m = obs_mesh.mesh_report(eng.mesh_snapshot(st), cap=eng.cap)
    rep = obs_report.build_report(s, mesh=m)
    assert rep["mesh"] is m
    text = obs_report.render_text(rep)
    assert "[mesh]" in text
    assert "imbalance jain=" in text
    assert m["top_pairs"], "contended 2-node cell must cross the mesh"
    # round-trips through a run record
    rep2 = obs_report.report_from_record({"summary": s, "mesh": m})
    assert rep2["mesh"] == m


def test_zero_steady_recompiles_with_mesh_on():
    """The observatory is jit-safe carried state: no shape depends on
    data, so the xmeter sentinel must count ZERO post-warmup compiles."""
    cfg = Config(cc_alg="WAIT_DIE", mesh=True, xmeter=True, **BASE)
    eng = ShardedEngine(cfg)
    st = eng.run(12)
    eng.xmeter.mark_warm()
    st = eng.run(12, st)
    assert eng.xmeter.steady_violations() == []
    assert obs_mesh.reconcile(eng.mesh_snapshot(st), eng.summary(st)) == []


def test_trace_ring_and_perfetto_track(tmp_path):
    """Traced mesh runs: the per-dest companion ring surfaces as
    mesh_tx_to<j> timeline series (summing to the tx matrix row sums),
    a "mesh traffic" Perfetto counter track, and the obs.export merge
    path rebuilds the same track from a run record."""
    eng, st, _ = cell("WAIT_DIE", mesh=True, trace_ticks=40)
    assert "arr_mesh_trace" in st.stats
    tl = obs_trace.timeline(st)
    names = sorted(k for k in tl if k.startswith("mesh_tx_to"))
    assert names == ["mesh_tx_to0", "mesh_tx_to1"]
    # ring column sums == matrix row sums over every lane the ring sees
    # (the per-dest ring counts A-exchange shipments; ticks 0..39, no
    # wrap, warmup 0 -> equals the tx REQ+PREP+EPOCH attempt lanes
    # minus drops, which this small cell never takes)
    tx = np.asarray(st.stats["arr_mesh_tx"])
    shipped = (tx[:, :, obs_mesh.REQ] + tx[:, :, obs_mesh.PREP]
               + tx[:, :, obs_mesh.EPOCH]).sum(axis=0)
    ring_sums = np.array([tl[n].sum() for n in names])
    assert np.array_equal(ring_sums, shipped)

    path = str(tmp_path / "tr.json")
    obs_trace.to_chrome_trace(st, path, n_ticks=40)
    doc = json.load(open(path))
    assert doc["metadata"]["mesh_track_nodes"] == 2
    mesh_evs = [e for e in doc["traceEvents"]
                if e.get("name") == "mesh traffic"]
    assert mesh_evs and set(mesh_evs[0]["args"]) == {"to0", "to1"}

    from deneva_tpu.obs import export as obs_export
    rec = {"timeline": {k: v.tolist() for k, v in
                        obs_trace.timeline(st, per_shard=True).items()}}
    evs = obs_export.record_events(rec)
    merged = [e for e in evs if e.get("name") == "mesh traffic"]
    assert merged and {e["pid"] for e in merged} == {0, 1}


def test_snapshot_and_report_shapes():
    """snapshot()/mesh_report() schema: (N, N, T) tensors, the type
    axis order, and per-node planes sized N."""
    eng, st, _ = cell("WAIT_DIE", mesh=True)
    snap = eng.mesh_snapshot(st)
    assert snap["tx"].shape == (2, 2, len(MSG_TYPES))
    assert snap["rx"].shape == snap["tx"].shape
    assert tuple(snap["types"]) == MSG_TYPES
    m = obs_mesh.mesh_report(snap, cap=eng.cap)
    assert len(m["matrix"]) == 2 and len(m["matrix"][0]) == 2
    assert len(m["per_node"]["commits"]) == 2
    assert m["cap"] == eng.cap
    assert set(m["by_type"]) == set(MSG_TYPES)


@pytest.mark.slow  # 8-node compiles x 2 shapes exceed the tier-1 budget
def test_scaling_grid_cell(tmp_path):
    """bench.py --scaling-grid: the 8-node MAAT cell lands in
    scaling_grid.json with the speedup/efficiency/imbalance/remote-ratio
    columns, reconciles, and the history record feeds the regress gate."""
    import argparse

    import bench
    from deneva_tpu.obs import regress as obs_regress
    args = argparse.Namespace(ticks=40, algs="MAAT", grid_nodes="4,8",
                              grid_budget_mb=256.0, grid_max_batch=64)
    out = str(tmp_path)
    assert bench.run_scaling_grid(args, out_dir=out, history=True) == 0
    doc = json.load(open(f"{out}/scaling_grid.json"))
    cells = doc["grid"]["MAAT"]
    assert {c["nodes"] for c in cells} == {4, 8}
    for c in cells:
        assert set(c) >= {"nodes", "batch_per_node", "commits_per_tick",
                          "speedup", "efficiency", "imb_jain",
                          "remote_ratio", "straggler_ticks"}
        assert 0.0 < c["imb_jain"] <= 1.0
        assert c["efficiency"] > 0
    # the history line carries the efficiency cells; the regress gate
    # self-arms on first sight and gates once the trajectory repeats
    entries = obs_regress.load_history(f"{out}/bench_history.jsonl")
    assert entries and entries[-1]["scaling_grid"]
    # gate() excludes `current` from the priors BY IDENTITY, so arm it
    # with a copied point rather than a duplicated list reference
    res = obs_regress.gate(entries, current=dict(entries[-1]))
    assert any(c["name"].startswith("scaling_grid_efficiency[MAAT@")
               for c in res["checks"])
    # the amplification ratio rides the same cells, gated INVERTED
    # (remote entries shipped per requested access; growth = regression)
    assert entries[-1]["scaling_amp"]
    assert any(c["name"].startswith("scaling_grid_amplification[MAAT@")
               for c in res["checks"])
    assert res["failures"] == []
