"""Live-entry compaction tests (ops/segment.py + cc/compact.py wiring).

Three layers:

1. unit tests for the primitive triplet ``compact_entries`` /
   ``expand_entries`` / ``overflow_mask`` (order preservation, round
   trip, identity short-circuit, overflow accounting);
2. the PR's headline guarantee: with a bucket K that never overflows,
   every CC plugin's [summary] counters are BIT-IDENTICAL between the
   compacted run and the padded (``entry_compaction=False``) run, on
   YCSB and TPC-C, at a fixed pool seed;
3. the spill discipline: a deliberately tiny K overflows, the spill is
   COUNTED (``compact_overflow_cnt``) and the engine keeps committing —
   overflowed work is deferred to retries, never silently dropped.

Parity geometry notes: high contention (zipf 0.8 on 128 rows) keeps
cursors low for the progressive-acquisition algorithms, and admit_cap=4
staggers admission so OCC/MAAT finishing bursts stay under the bucket.
K=96 suffices for the access-view algorithms; MAAT validates over ALL
granted lanes of live txns (a wider view) and needs K=112.  CALVIN is
request_all: its auto bucket is the full width (identity view) by
design, so its pair pins that the flag itself changes nothing.

A sub-padded bucket is OPT-IN (``compact_lanes`` / ``compact_auto``):
the default config keeps the identity view, because a bucket that
overflows changes the (legal) schedule and would break the exact
sequential-oracle parity guarantee of PARITY.md.  The YCSB pairs here
pin explicit lanes; the TPC-C pairs exercise the ``compact_auto``
formula (K=1280 < n=2112 at this geometry, verified spill-free).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.ops import segment as seg

# ---------------------------------------------------------------------------
# 1. primitive unit tests


def _rand_live(n, p, seed):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.random(n) < p)


def test_compact_entries_preserves_live_order():
    n, K = 64, 24
    live = _rand_live(n, 0.3, 1)
    pay = jnp.arange(n, dtype=jnp.int32) * 10
    view, (cpay,) = seg.compact_entries(live, K, pay)
    assert not view.identity and view.width == K and view.n == n
    live_np = np.asarray(live)
    want = np.asarray(pay)[live_np]          # original relative order
    got = np.asarray(cpay)[np.asarray(view.live)]
    assert list(got) == list(want[:K])
    assert int(view.n_live) == int(live_np.sum())
    assert int(view.overflow) == max(int(live_np.sum()) - K, 0)


def test_expand_entries_round_trip():
    n, K = 48, 32
    live = _rand_live(n, 0.4, 2)
    assert int(jnp.sum(live.astype(jnp.int32))) <= K
    vals = jnp.arange(n, dtype=jnp.int32) + 100
    flags = _rand_live(n, 0.5, 3)
    view, (cv, cf) = seg.compact_entries(live, K, vals, flags)
    assert cf.dtype == jnp.bool_             # bools convert back
    ev, ef = seg.expand_entries(view, cv, cf, fill=0)
    live_np = np.asarray(live)
    np.testing.assert_array_equal(np.asarray(ev)[live_np],
                                  np.asarray(vals)[live_np])
    np.testing.assert_array_equal(np.asarray(ef)[live_np],
                                  np.asarray(flags)[live_np])


def test_identity_short_circuit():
    live = _rand_live(16, 0.5, 4)
    pay = jnp.arange(16, dtype=jnp.int32)
    view, (out,) = seg.compact_entries(live, 16, pay)
    assert view.identity and view.width == 16
    assert out is pay                        # no sort emitted
    (back,) = seg.expand_entries(view, out)
    assert back is out
    assert not bool(jnp.any(seg.overflow_mask(live, 16)))


def test_bucket_is_opt_in():
    # no opt-in -> padded width (identity view): the default schedule is
    # bit-identical to the uncompacted engine, PARITY.md stays exact
    assert Config(cc_alg="NO_WAIT").compact_width(2560, 256) == 2560
    # compact_auto engages the cursor-model formula: ceil(10/2) + 1 = 6
    cfg = Config(cc_alg="NO_WAIT", compact_auto=True)
    assert cfg.compact_width(2560, 256) == 1536
    # explicit lanes take precedence and are capped at n
    cfg = Config(cc_alg="NO_WAIT", compact_lanes=400)
    assert cfg.compact_width(2560, 256) == 400
    assert cfg.compact_width(320, 32) == 320


def test_overflow_mask_marks_live_tail():
    live = jnp.asarray([1, 0, 1, 1, 0, 1, 1, 0], bool)
    ovf = np.asarray(seg.overflow_mask(live, 3))
    # live ranks: idx 0->0, 2->1, 3->2, 5->3, 6->4; K=3 spills ranks 3,4
    assert list(np.nonzero(ovf)[0]) == [5, 6]
    view, _ = seg.compact_entries(live, 3, jnp.arange(8, dtype=jnp.int32))
    assert int(view.overflow) == 2


# ---------------------------------------------------------------------------
# 2. compacted vs padded bit-identical [summary]

YCSB_KW = dict(batch_size=16, req_per_query=8, synth_table_size=128,
               zipf_theta=0.8, query_pool_size=256, admit_cap=4,
               max_ticks=10**6, warmup_ticks=0)

#: per-algorithm bucket; None = auto (CALVIN: request_all -> identity)
YCSB_K = {"NO_WAIT": 96, "WAIT_DIE": 96, "TIMESTAMP": 96, "MVCC": 96,
          "OCC": 96, "MAAT": 112, "CALVIN": None}

TPCC_KW = dict(workload="TPCC", batch_size=64, num_wh=4, part_cnt=1,
               node_cnt=1, query_pool_size=1024, cust_per_dist=1000,
               max_items=128, perc_payment=0.5, admit_cap=16,
               warmup_ticks=0)


def _summary_pair(cfg_compact: Config, cfg_padded: Config, n_ticks: int):
    out = []
    for cfg in (cfg_compact, cfg_padded):
        eng = Engine(cfg)
        out.append(eng.summary(eng.run(n_ticks)))
    return out


def _assert_bit_identical(sc, sp, alg):
    sc, sp = dict(sc), dict(sp)
    ovf = sc.pop("compact_overflow_cnt", 0)
    assert ovf == 0, \
        f"{alg}: bucket overflowed ({ovf}) — " \
        "parity only holds when nothing spilled"
    # the compaction counters exist only on the opted-in side (the padded
    # run builds no view); everything else must match bit-for-bit
    sc.pop("live_entry_cnt", None)
    assert "live_entry_cnt" not in sp
    diff = {k: (sc[k], sp.get(k)) for k in sc if sc[k] != sp.get(k)}
    assert not diff, f"{alg}: compacted vs padded summary diverged: {diff}"


# the MAAT cell compiles the chain-validate twice (compact + padded)
# and alone costs ~15 s — `-m slow` per the tier-1 870 s budget split
# (MAAT compacted-width parity stays tier-1 via the fused chain-gate
# cells in test_fused.py)
@pytest.mark.parametrize("alg", [
    pytest.param(a, marks=pytest.mark.slow) if a == "MAAT" else a
    for a in YCSB_K])
def test_ycsb_parity_compact_vs_padded(alg):
    k = YCSB_K[alg]
    lanes = {} if k is None else {"compact_lanes": k}
    sc, sp = _summary_pair(
        Config(cc_alg=alg, **lanes, **YCSB_KW),
        Config(cc_alg=alg, entry_compaction=False, **YCSB_KW),
        n_ticks=200)
    _assert_bit_identical(sc, sp, alg)
    assert sc["txn_cnt"] > 0


# the MAAT cell compiles the chain-validate twice (compact + padded)
# and alone costs ~27 s; WAIT_DIE/OCC (~8 s each) are redundant with
# the YCSB parity sweep — `-m slow` per the tier-1 870 s budget split
@pytest.mark.parametrize("alg", ["NO_WAIT",
                                 pytest.param("WAIT_DIE",
                                              marks=pytest.mark.slow),
                                 "TIMESTAMP", "MVCC",
                                 pytest.param("OCC",
                                              marks=pytest.mark.slow),
                                 pytest.param("MAAT",
                                              marks=pytest.mark.slow),
                                 "CALVIN"])
def test_tpcc_parity_compact_vs_padded(alg):
    sc, sp = _summary_pair(
        Config(cc_alg=alg, compact_auto=True, **TPCC_KW),
        Config(cc_alg=alg, entry_compaction=False, **TPCC_KW),
        n_ticks=60)
    _assert_bit_identical(sc, sp, alg)
    assert sc["txn_cnt"] > 0


# ---------------------------------------------------------------------------
# 3. overflow spill: counted, retried, never dropped


@pytest.mark.parametrize("alg", ["NO_WAIT", "MAAT"])
def test_tiny_bucket_spills_and_recovers(alg):
    cfg = Config(cc_alg=alg, compact_lanes=8, **YCSB_KW)
    eng = Engine(cfg)
    s = eng.summary(eng.run(200))
    assert s["compact_overflow_cnt"] > 0     # the bucket really spilled
    assert s["txn_cnt"] > 0                  # and the engine still commits
    # spilled txns were deferred (forced retry / stalled vote), so the
    # books still balance: every admission either committed, aborted at
    # least once, or is still in flight
    in_flight = cfg.batch_size
    assert s["local_txn_start_cnt"] <= (s["txn_cnt"]
                                        + s["total_txn_abort_cnt"]
                                        + in_flight)
