"""Event-trace (DEBUG_TIMELINE analog) tests: the per-tick series must
integrate to the run's totals, and lifetimes in the ring must match the
latency stats."""

import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine


def run_traced(**kw):
    base = dict(cc_alg="NO_WAIT", batch_size=128, synth_table_size=1 << 10,
                req_per_query=4, zipf_theta=0.8, query_pool_size=1 << 10,
                trace_ticks=64)
    base.update(kw)
    eng = Engine(Config(**base))
    st = eng.run(40)
    return eng, st


def test_series_integrate_to_totals():
    eng, st = run_traced()
    s = eng.summary(st)
    commits = np.asarray(st.stats["arr_trace_commit"])
    aborts = np.asarray(st.stats["arr_trace_abort"])
    admits = np.asarray(st.stats["arr_trace_admit"])
    assert int(commits.sum()) == s["txn_cnt"]
    assert int(aborts.sum()) == s["total_txn_abort_cnt"]
    assert int(admits.sum()) == s["local_txn_start_cnt"]
    # waiting series integrates to the cc-block latency integral
    waiting = np.asarray(st.stats["arr_trace_waiting"])
    assert float(waiting.sum()) == s["lat_cc_block_time"]


def test_lifetimes_match_ring():
    eng, st = run_traced()
    n = min(int(np.asarray(st.stats["lat_ring_cursor"])),
            st.stats["arr_lat_short"].shape[0])
    assert n > 0
    dur = np.asarray(st.stats["arr_lat_short"])[:n]
    start = np.asarray(st.stats["arr_lat_start"])[:n]
    assert (dur >= eng.cfg.req_per_query).all()     # faithful window
    assert (start >= 0).all()
    assert (start + dur <= int(np.asarray(st.tick))).all()


def test_trace_off_carries_no_arrays():
    eng, st = run_traced(trace_ticks=0)
    assert "arr_trace_commit" not in st.stats
    assert "arr_lat_start" not in st.stats


def test_render_timeline(tmp_path):
    from experiments.timeline_plot import render
    eng, st = run_traced()
    out = render(eng, st, str(tmp_path / "timeline.png"))
    import os
    assert os.path.getsize(out) > 10_000


def test_sharded_trace():
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(cc_alg="WAIT_DIE", node_cnt=4, part_cnt=4, batch_size=32,
                 synth_table_size=1 << 10, req_per_query=4, zipf_theta=0.6,
                 query_pool_size=512, trace_ticks=32)
    eng = ShardedEngine(cfg)
    st = eng.run(25)
    s = eng.summary(st)
    commits = np.asarray(st.stats["arr_trace_commit"])  # (N, T)
    assert int(commits.sum()) == s["txn_cnt"]
