"""Event-trace (DEBUG_TIMELINE analog) tests: the per-tick timeline ring
must integrate to the run's totals, and lifetimes in the ring must match
the latency stats."""

import pytest
import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.obs import trace as obs_trace


def run_traced(**kw):
    base = dict(cc_alg="NO_WAIT", batch_size=128, synth_table_size=1 << 10,
                req_per_query=4, zipf_theta=0.8, query_pool_size=1 << 10,
                trace_ticks=64)
    base.update(kw)
    eng = Engine(Config(**base))
    st = eng.run(40)
    return eng, st


def test_series_integrate_to_totals():
    eng, st = run_traced()
    s = eng.summary(st)
    tot = obs_trace.totals(st)
    assert tot["commit"] == s["txn_cnt"]
    assert tot["abort"] == s["total_txn_abort_cnt"]
    assert tot["admit"] == s["local_txn_start_cnt"]
    assert tot["lock_wait"] == s["twopl_wait_cnt"]
    # the waiting-occupancy series integrates to the cc-block latency
    # integral (both count WAITING slot-ticks at end of tick)
    assert float(tot["occ_waiting"]) == s["lat_cc_block_time"]


def test_ring_wraps_and_accumulates():
    # buffer shorter than the run: the ring wraps (t % T) and ADDS, so
    # column sums still equal whole-run totals
    eng, st = run_traced(trace_ticks=16)
    s = eng.summary(st)
    assert st.stats["arr_trace"].shape[0] == 16
    tot = obs_trace.totals(st)
    assert tot["commit"] == s["txn_cnt"]
    assert tot["abort"] == s["total_txn_abort_cnt"]


def test_occupancy_partitions_batch():
    eng, st = run_traced()
    tl = obs_trace.timeline(st)
    occ = sum(tl[c] for c in ("occ_free", "occ_running", "occ_waiting",
                              "occ_backoff"))
    ticks = int(np.asarray(st.tick))
    assert (occ[:ticks] == eng.cfg.batch_size).all()


def test_lifetimes_match_ring():
    eng, st = run_traced()
    n = min(int(np.asarray(st.stats["lat_ring_cursor"])),
            st.stats["arr_lat_short"].shape[0])
    assert n > 0
    dur = np.asarray(st.stats["arr_lat_short"])[:n]
    start = np.asarray(st.stats["arr_lat_start"])[:n]
    assert (dur >= eng.cfg.req_per_query).all()     # faithful window
    assert (start >= 0).all()
    assert (start + dur <= int(np.asarray(st.tick))).all()


def test_trace_off_carries_no_arrays():
    eng, st = run_traced(trace_ticks=0)
    assert "arr_trace" not in st.stats
    assert "arr_lat_start" not in st.stats


def test_render_timeline(tmp_path):
    from experiments.timeline_plot import render
    eng, st = run_traced()
    out = render(eng, st, str(tmp_path / "timeline.png"))
    import os
    assert os.path.getsize(out) > 10_000


@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_sharded_trace():
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(cc_alg="WAIT_DIE", node_cnt=4, part_cnt=4, batch_size=32,
                 synth_table_size=1 << 10, req_per_query=4, zipf_theta=0.6,
                 query_pool_size=512, trace_ticks=32)
    eng = ShardedEngine(cfg)
    st = eng.run(25)
    s = eng.summary(st)
    buf = np.asarray(st.stats["arr_trace"])
    assert buf.shape == (4, 32, len(obs_trace.TRACE_COLUMNS))
    tot = obs_trace.totals(st)
    assert tot["commit"] == s["txn_cnt"]
    # per-shard commit series come from the leading axis
    per_shard = obs_trace.timeline(st, per_shard=True)["commit"]
    assert per_shard.shape == (4, 32)
    assert int(per_shard.sum()) == s["txn_cnt"]
