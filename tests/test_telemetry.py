"""Live SLO & streaming telemetry plane tests (obs/histo.py, obs/slo.py,
obs/telemetry.py, ``Config.slo``).

The plane's two exact reconciliation identities under test:

- ``hist_total_cnt == txn_cnt`` — every committed measured txn lands in
  exactly one log bucket (same take mask as the commit counter), for
  every CC plugin;
- cluster histogram == elementwise sum of per-shard planes, BIT-equal —
  int32 counts merge exactly (associative, commutative), which is the
  property the famlat survivor rings fundamentally lack (they keep the
  last S commits per family and BIAS the tail once arrivals outrun
  them; the divergence test below demonstrates it).

Plus the off-path contract (``Config.slo`` off adds zero carry arrays
and zero summary keys and perturbs no shared counter), the multi-window
burn-rate alert lifecycle on a synthetic rate step, the OpenMetrics /
JSONL round-trip, the Perfetto "slo burn rate" track, the self-arming
regress ceiling, and the zero-post-warm-recompile serve smoke under the
xmeter sentinel.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from deneva_tpu import stats as stats_mod
from deneva_tpu import traffic
from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.obs import histo as obs_histo
from deneva_tpu.obs import report as obs_report
from deneva_tpu.obs import slo as obs_slo
from deneva_tpu.obs import telemetry as obs_telemetry
from deneva_tpu.obs import trace as obs_trace

BASE = dict(cc_alg="NO_WAIT", batch_size=64, synth_table_size=1 << 10,
            req_per_query=4, zipf_theta=0.6, query_pool_size=1 << 10,
            warmup_ticks=0)

# MAAT's interval-validation compile dominates the suite's wall clock
# (PR 11 precedent: tier-1 MAAT coverage lives in test_maat.py)
ALGS = ("NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC",
        pytest.param("MAAT", marks=pytest.mark.slow), "CALVIN")

#: the EXACT extra [summary] keys one live family adds (the off-path
#: identity test asserts this set, nothing more, nothing less)
EXTRA_SUMMARY_KEYS = {"hist_total_cnt", "hist_phase_cnt", "slo_fam0_n",
                      "slo_fam0_p50", "slo_fam0_p95", "slo_fam0_p99"}
EXTRA_STATS_KEYS = {"arr_hist_fam", "arr_hist_phase"}


# ---------------------------------------------------------------------------
# bucket math
# ---------------------------------------------------------------------------

def test_bucket_math_exact_small_monotone_and_bounded_error():
    bins = 96
    lows = obs_histo.bucket_lows(bins)
    widths = obs_histo.bucket_widths(bins)
    vals = np.arange(0, int(lows[-1]) + 5)
    b = np.asarray(obs_histo.bucket_of(jnp.asarray(vals), bins))
    # monotone, in range
    assert (np.diff(b) >= 0).all()
    assert b.min() == 0 and b.max() == bins - 1
    # every value lies inside its bucket (until the clamp bucket)
    inside = b < bins - 1
    assert (vals[inside] >= lows[b[inside]]).all()
    assert (vals[inside] < lows[b[inside]] + widths[b[inside]]).all()
    # values < 16 bucket exactly (one bucket per integer)
    assert (b[:16] == np.arange(16)).all()
    # relative bucket width bounded: <= 1/8 of the bucket's low after
    # the exact range (the HDR guarantee the quantiles inherit)
    big = lows >= 16
    assert (widths[big] / lows[big] <= 0.125 + 1e-9).all()
    # negative / zero clamp to bucket 0
    nb = np.asarray(obs_histo.bucket_of(jnp.asarray([-5, 0]), bins))
    assert (nb == 0).all()


def test_quantile_host_and_device_agree():
    bins = 64
    rng = np.random.default_rng(7)
    # keep the population inside the 64-bin reach (960 ticks) so no
    # sample hits the clamp bucket and quantiles stay meaningful
    vals = rng.integers(0, 900, size=5000)
    b = np.asarray(obs_histo.bucket_of(jnp.asarray(vals), bins))
    hist = np.bincount(b, minlength=bins).astype(np.int64)
    lows = obs_histo.bucket_lows(bins)
    for q in (0.5, 0.95, 0.99):
        hq = obs_histo.quantile(hist, q)
        dq = float(obs_histo.device_quantile(
            jnp.asarray(hist, jnp.int32), jnp.asarray(lows, jnp.int32), q))
        # device returns the bucket LOW, host the bucket midpoint value
        assert abs(hq - dq) <= obs_histo.bucket_widths(bins)[
            int(np.searchsorted(lows, dq, side="right")) - 1]
        # within one bucket of numpy's exact quantile
        exact = float(np.quantile(vals, q, method="inverted_cdf"))
        assert hq >= exact * 0.85 and hq <= exact * 1.15
    # empty histogram -> 0
    assert obs_histo.quantile(np.zeros(bins, np.int64), 0.99) == 0.0


# ---------------------------------------------------------------------------
# exact reconciliation: histogram total == commits, per plugin
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("alg", ALGS)
def test_hist_total_equals_commits_every_plugin(alg):
    cfg = Config(**{**BASE, "cc_alg": alg}, slo=True,
                 arrival="poisson", arrival_rate=8.0)
    eng = Engine(cfg)
    st = eng.run(40)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0, "cell committed nothing"
    assert s["hist_total_cnt"] == s["txn_cnt"], (alg, s["hist_total_cnt"],
                                                 s["txn_cnt"])
    # the per-family sample counts partition the total
    assert s["slo_fam0_n"] == s["hist_total_cnt"]
    # phase plane: each phase row is a per-tick occupancy histogram, so
    # every row sums to the measured tick count
    ph = np.asarray(st.stats["arr_hist_phase"])
    rows = ph.sum(axis=1)
    assert (rows == rows[0]).all()
    assert s["hist_phase_cnt"] == int(rows.sum())


def test_hist_works_closed_loop():
    # no arrival plane at all: the histogram hook sits BEFORE the
    # arrival-plane early return, so closed-loop runs still bin commits
    cfg = Config(**BASE, slo=True)
    eng = Engine(cfg)
    st = eng.run(30)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert s["hist_total_cnt"] == s["txn_cnt"]
    assert "arrival_cnt" not in s


# ---------------------------------------------------------------------------
# off-path contract
# ---------------------------------------------------------------------------

def test_off_path_adds_nothing_and_on_path_adds_exactly():
    on = Config(**BASE, slo=True, arrival="poisson", arrival_rate=8.0)
    off = Config(**BASE, arrival="poisson", arrival_rate=8.0)
    e_on, e_off = Engine(on), Engine(off)
    st_on, st_off = e_on.run(30), e_off.run(30)
    s_on, s_off = e_on.summary(st_on), e_off.summary(st_off)
    # off: zero carry arrays, zero summary keys
    assert not any(k.startswith(("arr_hist", "arr_slo")) for k in
                   st_off.stats)
    assert not any(k.startswith(("hist_", "slo_", "burn_")) for k in s_off)
    # on: EXACTLY the documented key sets
    assert set(st_on.stats) - set(st_off.stats) == EXTRA_STATS_KEYS
    assert set(s_on) - set(s_off) == EXTRA_SUMMARY_KEYS
    # the plane is observational: every shared counter is bit-identical
    for k in s_off:
        if isinstance(s_off[k], float):
            assert s_on[k] == pytest.approx(s_off[k]), k
        else:
            assert s_on[k] == s_off[k], k


def test_slo_config_validation():
    with pytest.raises(AssertionError):
        Config(**BASE, slo=True, slo_hist_bins=20)     # not a multiple of 8
    with pytest.raises(AssertionError):
        Config(**BASE, slo=True, slo_target=1.5)
    with pytest.raises(AssertionError):
        Config(**BASE, slo=True, slo_burn_fast=50, slo_burn_slow=5)
    with pytest.raises(AssertionError):
        Config(**BASE, slo=True, slo_export_interval=0)


# ---------------------------------------------------------------------------
# merge exactness
# ---------------------------------------------------------------------------

def test_merge_exact_and_associative():
    bins = 32
    rng = np.random.default_rng(3)
    pops = [rng.integers(0, 500, size=400) for _ in range(3)]
    hists = []
    for pop in pops:
        b = np.asarray(obs_histo.bucket_of(jnp.asarray(pop), bins))
        hists.append(np.bincount(b, minlength=bins).astype(np.int64))
    a, b_, c = hists
    # merge IS elementwise add: exact, associative, commutative
    assert ((a + b_) + c == a + (b_ + c)).all()
    assert (a + b_ == b_ + a).all()
    # merged quantile == quantile of the pooled population's histogram
    pooled = np.asarray(obs_histo.bucket_of(
        jnp.asarray(np.concatenate(pops)), bins))
    pooled_hist = np.bincount(pooled, minlength=bins)
    assert (a + b_ + c == pooled_hist).all()


@pytest.mark.slow  # sharded compile cost exceeds the tier-1 budget
def test_cluster_plane_bit_equal_to_shard_sum():
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(cc_alg="NO_WAIT", node_cnt=4, part_cnt=4, batch_size=32,
                 synth_table_size=1 << 10, req_per_query=2,
                 zipf_theta=0.5, query_pool_size=1 << 10, warmup_ticks=0,
                 slo=True, arrival="poisson", arrival_rate=4.0)
    eng = ShardedEngine(cfg)
    st = eng.run(30)
    s = eng.summary(st)
    stacked = np.asarray(st.stats["arr_hist_fam"])
    assert stacked.ndim == 3 and stacked.shape[0] == 4
    cluster = np.asarray(eng.hist_cluster_plane(st))
    # identity 2: device psum merge bit-equal to the host shard sum
    assert np.array_equal(cluster, stacked.sum(axis=0))
    # identity 1 holds on the psum'd cluster counters too
    assert s["hist_total_cnt"] == s["txn_cnt"]
    assert int(cluster.sum()) == s["txn_cnt"]


# ---------------------------------------------------------------------------
# the famlat survivor-ring tail bias (why the histograms exist)
# ---------------------------------------------------------------------------

def test_famlat_ring_tail_bias_vs_exact_histogram():
    """Feed one family more commits than its survivor ring holds, with
    the tail concentrated EARLY: the keep-last-S ring forgets the tail
    and its p99 collapses, while the histogram (which binned every
    commit) stays within its bucket-width error of the truth."""
    S, bins, B = 64, 96, 50
    cfg = Config(**BASE, slo=True, arrival="poisson", arrival_rate=8.0,
                 fam_lat_samples=S)
    stats = {
        "arr_fam_lat": jnp.zeros((1, S), jnp.int32),
        "arr_fam_cursor": jnp.zeros((1,), jnp.int32),
        **obs_histo.init_histo(cfg, 1),
    }
    rng = np.random.default_rng(11)
    # 400 commits: the first batches carry the 300-600-tick tail, the
    # last ring-capacity's worth are all fast (4-10 ticks)
    lats = np.concatenate([rng.integers(300, 600, size=100),
                           rng.integers(4, 10, size=300)])
    commit = jnp.ones((B,), bool)
    fam = jnp.zeros((B,), jnp.int32)
    for i in range(0, lats.size, B):
        stats = traffic.record_family_latency(
            stats, commit, fam, jnp.asarray(lats[i:i + B], jnp.int32),
            jnp.asarray(True))
    ring = traffic.family_percentiles(stats["arr_fam_lat"],
                                      stats["arr_fam_cursor"])
    hist = np.asarray(stats["arr_hist_fam"])[0]
    assert int(hist.sum()) == lats.size          # every commit binned
    true_p99 = float(np.percentile(lats, 99))
    hist_p99 = obs_histo.quantile(hist, 0.99)
    ring_p99 = ring["famlat0_p99"]
    # the ring kept only the last S=64 fast commits: its p99 diverges
    # by an order of magnitude; the histogram stays within bucket error
    assert ring_p99 < 0.25 * true_p99, (ring_p99, true_p99)
    assert abs(hist_p99 - true_p99) <= 0.15 * true_p99, (hist_p99,
                                                         true_p99)


# ---------------------------------------------------------------------------
# the SLO engine: multi-window burn-rate alerting
# ---------------------------------------------------------------------------

def _hist_from(vals, bins):
    b = np.asarray(obs_histo.bucket_of(jnp.asarray(vals), bins))
    return np.bincount(b, minlength=bins).astype(np.int64)


def test_burn_alert_fires_and_clears_on_synthetic_rate_step():
    bins = 96
    cfg = Config(**BASE, slo=True, arrival="poisson", arrival_rate=8.0,
                 slo_p99_ceiling=64, slo_target=0.99, slo_burn_fast=5,
                 slo_burn_slow=50, slo_burn_threshold=2.0)
    tr = obs_slo.SloTracker(cfg)
    rng = np.random.default_rng(5)
    cum = np.zeros((1, bins), np.int64)
    commits = 0

    def step(tick, vals):
        nonlocal cum, commits
        cum = cum + _hist_from(vals, bins)[None]
        commits += vals.size
        return tr.observe(tick, cum,
                          {"txn_cnt": commits, "arrival_cnt": commits,
                           "queue_admit_cnt": commits,
                           "total_txn_abort_cnt": 0})

    # 10 healthy polls: every commit far under the ceiling -> burn 0
    for i in range(10):
        ev = step((i + 1) * 5, rng.integers(4, 20, size=40))
        assert ev["burn_fast"] == 0.0 and not ev["fired"]
    assert tr.alert_active is False
    # the crowd: 30% of window commits breach -> burn 30x budget; the
    # FAST window trips immediately, the alert waits for the SLOW one
    fired_at = None
    for i in range(10, 16):
        vals = np.concatenate([rng.integers(4, 20, size=28),
                               rng.integers(200, 400, size=12)])
        ev = step((i + 1) * 5, vals)
        assert ev["burn_fast"] > cfg.slo_burn_threshold
        if ev["fired"]:
            fired_at = (i + 1) * 5
    assert fired_at is not None and tr.alert_active
    assert (fired_at, "fire") in tr.events
    # drain: healthy commits again -> fast window resets -> clear
    cleared = False
    for i in range(16, 20):
        ev = step((i + 1) * 5, rng.integers(4, 20, size=40))
        cleared = cleared or ev["cleared"]
    assert cleared and not tr.alert_active
    assert [e[1] for e in tr.events] == ["fire", "clear"]
    f = tr.summary_fields()
    assert f["slo_alert_cnt"] == 1 and f["slo_alert_active"] == 0
    assert f["slo_breach_ticks"] > 0
    assert f["burn_fast"] == 0.0


def test_served_floor_and_abort_cap_breach_counters():
    cfg = Config(**BASE, slo=True, arrival="poisson", arrival_rate=8.0,
                 slo_served_floor=0.95, slo_abort_cap=0.5)
    tr = obs_slo.SloTracker(cfg)
    cum = np.zeros((1, 96), np.int64)
    tr.observe(0, cum, {"txn_cnt": 0, "arrival_cnt": 0,
                        "queue_admit_cnt": 0, "total_txn_abort_cnt": 0})
    # window: 100 arrived, 50 admitted (served 0.5), 60 aborts vs 20
    # commits (abort rate 0.75) -> both dashboards breach, no page
    cum2 = cum + _hist_from(np.full(20, 5), 96)[None]
    ev = tr.observe(5, cum2, {"txn_cnt": 20, "arrival_cnt": 100,
                              "queue_admit_cnt": 50,
                              "total_txn_abort_cnt": 60})
    assert ev["served_frac"] == pytest.approx(0.5)
    assert ev["abort_rate"] == pytest.approx(0.75)
    assert tr.served_breach_cnt == 1 and tr.abort_breach_cnt == 1
    assert not tr.alert_active


# ---------------------------------------------------------------------------
# exporter: OpenMetrics + JSONL round-trip
# ---------------------------------------------------------------------------

def test_exporter_openmetrics_and_jsonl_roundtrip(tmp_path):
    cfg = Config(**BASE, slo=True, arrival="poisson", arrival_rate=8.0)
    eng = Engine(cfg)
    exporter = obs_telemetry.TelemetryExporter(cfg, str(tmp_path))
    st = eng.run(20)
    exporter.poll(st, 20)
    st = eng.run(20, st)
    rec = exporter.poll(st, 40)
    s = eng.summary(st)

    # JSONL: append-only, schema-tagged, quantiles == histogram quantiles
    lines = [json.loads(ln) for ln in
             (tmp_path / "telemetry.jsonl").read_text().splitlines()]
    assert [r["poll"] for r in lines] == [0, 1]
    assert all(r["schema"] == obs_telemetry.JSONL_SCHEMA for r in lines)
    assert lines[-1] == rec
    fam = np.asarray(st.stats["arr_hist_fam"])
    assert rec["hist_total"] == int(fam.sum()) == s["txn_cnt"]
    assert rec["fam"]["0"]["p99"] == obs_histo.quantile(fam[0], 0.99)
    assert rec["fam"]["0"]["p99"] == s["slo_fam0_p99"]

    # OpenMetrics: parses, EOF-terminated, cumulative and reconciled
    parsed = obs_telemetry.parse_openmetrics(
        (tmp_path / "metrics.om").read_text())
    assert parsed["eof"]
    assert parsed["types"][obs_telemetry.HIST_METRIC] == "histogram"
    buckets = [(lab, v) for n, lab, v in parsed["samples"]
               if n == f"{obs_telemetry.HIST_METRIC}_bucket"
               and lab.get("family") == "0"]
    cum = [v for _, v in buckets]
    assert cum == sorted(cum), "bucket samples must be cumulative"
    assert buckets[-1][0]["le"] == "+Inf"
    count = obs_telemetry.sample_value(
        parsed, f"{obs_telemetry.HIST_METRIC}_count", family=0)
    assert count == buckets[-1][1] == rec["hist_total"]
    assert obs_telemetry.sample_value(
        parsed, f"{obs_telemetry.COMMITS_METRIC}_total") == s["txn_cnt"]
    for w in ("fast", "slow"):
        assert obs_telemetry.sample_value(
            parsed, obs_telemetry.BURN_METRIC, window=w) is not None


# ---------------------------------------------------------------------------
# summary-line passthrough + report + trace track
# ---------------------------------------------------------------------------

def test_reference_line_and_watchdog_bit():
    cfg = Config(**BASE, slo=True, arrival="poisson", arrival_rate=8.0)
    eng = Engine(cfg)
    st = eng.run(30)
    s = eng.summary(st)
    s.update({"slo_alert_active": 1, "slo_alert_cnt": 2,
              "slo_breach_ticks": 40, "slo_served_breach_cnt": 0,
              "slo_abort_breach_cnt": 0, "burn_fast": 5.0,
              "burn_slow": 3.0, "burn_served_frac": 0.8,
              "burn_abort_rate": 0.3})
    findings, code = obs_report.watchdog(s)
    assert code & obs_report.SLO
    assert any(f[0] == "SLO" for f in findings)
    rep = obs_report.build_report(s)
    assert rep["slo"]["families"][0]["p99"] == s["slo_fam0_p99"]
    assert rep["slo"]["alert_active"] == 1
    txt = obs_report.render_text(rep)
    assert "[slo]" in txt and "FIRING" in txt
    # cleared alert: no bit
    s["slo_alert_active"] = 0
    _, code2 = obs_report.watchdog(s)
    assert not code2 & obs_report.SLO
    # broken identity: bit fires through RECONCILE
    s2 = dict(s)
    s2["hist_total_cnt"] = s2["txn_cnt"] + 1
    _, code3 = obs_report.watchdog(s2)
    assert code3 & obs_report.RECONCILE and code3 & obs_report.SLO


def test_slo_trace_series_and_chrome_track(tmp_path):
    from deneva_tpu.obs import export as obs_export
    cfg = Config(**BASE, slo=True, trace_ticks=64,
                 arrival="poisson", arrival_rate=8.0)
    eng = Engine(cfg)
    st = eng.run(40)
    tl = obs_trace.timeline(st)
    assert "slo_f0_p99" in tl and "slo_f0_burn" in tl
    # the p99 gauge series is cumulative-monotone (ring accumulates a
    # nondecreasing cumulative-histogram quantile)
    p99 = tl["slo_f0_p99"]
    assert max(p99) > 0
    p = tmp_path / "tr.json"
    obs_trace.to_chrome_trace(st, str(p), n_ticks=40)
    doc = json.loads(p.read_text())
    assert doc["metadata"].get("slo_track") == ["slo_f0_p99",
                                                "slo_f0_burn"]
    assert any(ev.get("name") == "slo burn rate"
               for ev in doc["traceEvents"])
    # the export merger rebuilds the same track from a run record
    # (records are JSON, so the timeline arrives as plain lists)
    events = obs_export.record_events(
        {"timeline": {k: np.asarray(v).tolist() for k, v in tl.items()}})
    assert any(ev.get("name") == "slo burn rate" for ev in events)
    # off path: no series, no track, no metadata flag
    cfg0 = Config(**BASE, trace_ticks=64)
    eng0 = Engine(cfg0)
    st0 = eng0.run(10)
    assert not any(k.startswith("slo_f") for k in obs_trace.timeline(st0))
    p0 = tmp_path / "tr0.json"
    obs_trace.to_chrome_trace(st0, str(p0))
    assert "slo_track" not in json.loads(p0.read_text())["metadata"]


def test_regress_slo_ceiling_self_arms_then_gates():
    from deneva_tpu.obs import regress
    doc1 = {"metric": "serve_slo", "value": 40.0,
            "slo_p99": {"fam0": 40.0}}
    doc2 = {"metric": "serve_slo", "value": 90.0,
            "slo_p99": {"fam0": 90.0}}
    e1 = regress._entry("h", (1, 1.0), doc1)
    e2 = regress._entry("h", (1, 2.0), doc2)
    # first point: no prior -> the ceiling self-arms, nothing fails
    r1 = regress.gate([e1])
    assert not r1["failures"]
    assert any("slo_p99[fam0]" in s for s in r1["skipped"])
    # second point: p99 more than (1 + tol) x median -> regression
    r2 = regress.gate([e1, e2])
    assert any("slo_p99[fam0]" in f for f in r2["failures"])


# ---------------------------------------------------------------------------
# serve mode: the zero-retrace contract
# ---------------------------------------------------------------------------

def test_serve_polls_never_retrace_single_engine():
    cfg = Config(**BASE, slo=True, xmeter=True, arrival="step",
                 arrival_schedule=((0, 2.0), (20, 30.0), (40, 2.0)))
    eng = Engine(cfg)
    exporter = obs_telemetry.TelemetryExporter(
        cfg, str("/tmp/_telemetry_retrace_test"))
    st = eng.run(10)
    eng.xmeter.mark_warm()
    tick = 10
    for _ in range(5):                  # polls interleaved with running,
        st = eng.run(10, st)            # across BOTH rate steps
        tick += 10
        exporter.poll(st, tick)
    assert eng.xmeter.steady_violations() == []
    assert exporter.polls == 5


@pytest.mark.slow  # sharded compile cost exceeds the tier-1 budget
def test_serve_sharded_zero_recompiles_and_parity(tmp_path):
    from deneva_tpu.parallel.sharded import ShardedEngine
    cfg = Config(cc_alg="NO_WAIT", node_cnt=2, part_cnt=2, batch_size=32,
                 synth_table_size=1 << 10, req_per_query=2,
                 zipf_theta=0.5, query_pool_size=1 << 10, warmup_ticks=0,
                 slo=True, xmeter=True, arrival="step",
                 arrival_schedule=((0, 2.0), (20, 16.0), (40, 2.0)))
    eng = ShardedEngine(cfg)
    exporter = obs_telemetry.TelemetryExporter(cfg, str(tmp_path))
    st = eng.run(10)
    eng.xmeter.mark_warm()
    tick = 10
    for _ in range(5):
        st = eng.run(10, st)
        tick += 10
        rec = exporter.poll(st, tick)
    assert eng.xmeter.steady_violations() == []
    s = eng.summary(st)
    # the exporter collapsed the node-stacked plane exactly
    assert rec["hist_total"] == s["hist_total_cnt"] == s["txn_cnt"]
    assert np.array_equal(np.asarray(eng.hist_cluster_plane(st)),
                          np.asarray(st.stats["arr_hist_fam"]).sum(axis=0))
