"""Multi-shard engine tests on the virtual 8-device CPU mesh
(the rebuild's TPORT_TYPE=IPC local mode, SURVEY.md §4)."""

import numpy as np
import pytest
import jax

from deneva_tpu.config import Config
from deneva_tpu.parallel.sharded import ShardedEngine
from deneva_tpu.engine.scheduler import Engine

# These were collection errors at the seed (pre shard_map compat fix);
# the slower five exceed the tier-1 time budget -- run with `-m slow`
# (MAAT's commit-exchange forward validation is the costliest compile).
ALGS = ["NO_WAIT",
        pytest.param("WAIT_DIE", marks=pytest.mark.slow),
        pytest.param("TIMESTAMP", marks=pytest.mark.slow),
        pytest.param("MVCC", marks=pytest.mark.slow),
        pytest.param("OCC", marks=pytest.mark.slow),
        pytest.param("MAAT", marks=pytest.mark.slow)]


def shard_cfg(n, **kw):
    base = dict(node_cnt=n, part_cnt=n, batch_size=32,
                synth_table_size=1 << 12, req_per_query=4,
                query_pool_size=1 << 10, zipf_theta=0.6, tup_read_perc=0.5,
                warmup_ticks=0, mpr=1.0, part_per_txn=n)
    base.update(kw)
    return Config(**base)


def test_two_nodes_conservation():
    eng = ShardedEngine(shard_cfg(2))
    st = eng.run(30)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert eng.global_data_sum(st) == s["write_cnt"]
    assert s["remote_entry_cnt"] > 0     # cross-partition traffic happened


@pytest.mark.parametrize("alg", ALGS)
def test_all_algorithms_four_nodes(alg):
    eng = ShardedEngine(shard_cfg(4, cc_alg=alg))
    st = eng.run(40)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0, s
    assert eng.global_data_sum(st) == s["write_cnt"], s


@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_read_only_multipartition_never_aborts():
    eng = ShardedEngine(shard_cfg(4, txn_read_perc=1.0, zipf_theta=0.9))
    st = eng.run(30)
    s = eng.summary(st)
    assert s["total_txn_abort_cnt"] == 0
    assert s["txn_cnt"] > 0
    assert eng.global_data_sum(st) == 0


def test_eight_nodes_smoke():
    eng = ShardedEngine(shard_cfg(8, batch_size=16))
    st = eng.run(25)
    s = eng.summary(st)
    assert s["txn_cnt"] > 0
    assert eng.global_data_sum(st) == s["write_cnt"]


def test_capacity_overflow_aborts_not_corrupts():
    # starve the exchange: capacity barely above R forces overflow aborts
    cfg = shard_cfg(2, route_capacity_factor=0.05, zipf_theta=0.0)
    eng = ShardedEngine(cfg)
    st = eng.run(30)
    s = eng.summary(st)
    assert s["route_overflow_abort_cnt"] > 0
    assert eng.global_data_sum(st) == s["write_cnt"]   # still exactly-once


@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_single_node_sharded_close_to_single_shard():
    cfg = shard_cfg(1, part_per_txn=1, mpr=0.0, batch_size=64,
                    query_pool_size=1 << 10)
    sh = ShardedEngine(cfg)
    st = sh.run(40)
    s_sh = sh.summary(st)
    assert sh.global_data_sum(st) == s_sh["write_cnt"]

    single = Engine(cfg)
    s_si = single.summary(single.run(40))
    # release timing differs by one tick across the exchange, so allow slack
    assert s_sh["txn_cnt"] > 0.5 * s_si["txn_cnt"]


@pytest.mark.slow  # unlocked by the shard_map compat fix; over the tier-1 time budget
def test_greedy_window_sharded():
    eng = ShardedEngine(shard_cfg(4, acquire_window=4, zipf_theta=0.0,
                                  synth_table_size=1 << 14))
    st = eng.run(25)
    s = eng.summary(st)
    assert s["txn_cnt"] > 150
    assert eng.global_data_sum(st) == s["write_cnt"]
