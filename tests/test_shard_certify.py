"""Sharded-collective-certifier tests (lint engine 4,
deneva_tpu/lint/shard_certify.py).

Four layers: deliberately-broken shard_map fixtures, each lowered
through the real SPMD partitioner and rejected with its named rule
(COLLECTIVE-UNDECLARED / COUNTER-NONCOMMUTATIVE / AXIS-UNDECLARED /
EXCHANGE-DYNAMIC-ROUND / REPLICATION-DRIFT) — including the resurrected
PR 12 pitfall, a ``lax.scan``-lowered exchange sub-round loop built
from the REAL routing helpers and caught by the REAL contract; the
COMM_CONTRACT autodiscovery guard (every collective call site in
``parallel/`` must be declared as a CommSpec or excused here, both
directions); the meta-lint guard that every rule ID of all four engines
has a catalog row in LINT.md; and the matrix itself — clean cells in
tier-1, the full matrix under ``-m slow`` (the run scripts/check.sh
gates on), plus the CLI subprocess exit-code/json seam.
"""

import ast
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deneva_tpu.cc.base import COMM_ROLES, CommSpec
from deneva_tpu.compat import shard_map
from deneva_tpu.lint import shard_certify
from deneva_tpu.parallel import routing

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
THIS = "tests/test_shard_certify.py"
N = 4


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("node",))


def _contract(specs=(), replicated=()):
    """A fixture contract: real role policy, synthetic site list."""
    return {"axis": "node", "roles": COMM_ROLES,
            "replicated": replicated, "specs": specs}


def _lower(fn, arg, mesh=None, spec=P("node")):
    wrapped = shard_map(fn, mesh=mesh or _mesh(),
                        in_specs=(spec,), out_specs=spec)
    return shard_certify.lower_collectives(wrapped, arg, donate=False)


def _check(colls, contract, node_cnt=N):
    return shard_certify.check_collectives(colls, contract,
                                           node_cnt=node_cnt,
                                           cell="FIXTURE")


# ---------------------------------------------------------------------------
# broken fixtures: each rejected with the named rule


def test_fixture_declared_counter_psum_clean():
    """The positive anchor: a declared role=counter psum over the full
    node axis passes every check."""
    def counter_sum(x):
        return jax.lax.psum(x, "node")

    colls = _lower(counter_sum, jnp.zeros((N, 8), jnp.int32))
    assert [c.op for c in colls] == ["all_reduce"]
    spec = CommSpec(name="fix.counter", op="all_reduce",
                    site=(THIS, ("counter_sum",)),
                    role="counter", when="always")
    assert _check(colls, _contract(specs=(spec,))) == []


def test_fixture_collective_undeclared():
    """A psum nobody declared: the partitioner-inserted-reduction bug
    class, anchored at the collective's own call line."""
    def rogue_sum(x):
        return jax.lax.psum(x, "node")

    colls = _lower(rogue_sum, jnp.zeros((N, 8), jnp.int32))
    found = _check(colls, _contract(specs=()))
    assert [f.rule for f in found] == ["COLLECTIVE-UNDECLARED"]
    assert found[0].path.endswith(THIS) and found[0].line > 0
    assert "all_reduce(add)" in found[0].message


def test_fixture_counter_noncommutative():
    """A max-reduction over a declared counter plane: counters may only
    cross the mesh via add."""
    def counter_peak(x):
        return jax.lax.pmax(x, "node")

    colls = _lower(counter_peak, jnp.zeros((N, 8), jnp.int32))
    spec = CommSpec(name="fix.counter", op="all_reduce",
                    site=(THIS, ("counter_peak",)),
                    role="counter", when="always")
    found = _check(colls, _contract(specs=(spec,)))
    assert [f.rule for f in found] == ["COUNTER-NONCOMMUTATIVE"]
    assert "role=counter" in found[0].message
    assert "add" in found[0].message


def test_fixture_axis_undeclared():
    """A reduction over a sub-axis of a 2-D mesh: its replica groups
    cover half the declared node extent each — declared site or not,
    the grouping is illegal."""
    mesh2d = Mesh(np.array(jax.devices()[:N]).reshape(2, 2),
                  ("node", "sub"))

    def sub_sum(x):
        return jax.lax.psum(x, "sub")

    colls = _lower(sub_sum, jnp.zeros((2, 2, 8), jnp.int32),
                   mesh=mesh2d, spec=P("node", "sub"))
    assert [c.op for c in colls] == ["all_reduce"]
    assert len(colls[0].replica_groups) == 2          # split grouping
    spec = CommSpec(name="fix.sub", op="all_reduce",
                    site=(THIS, ("sub_sum",)),
                    role="counter", when="always")
    found = _check(colls, _contract(specs=(spec,)))
    assert [f.rule for f in found] == ["AXIS-UNDECLARED"]
    assert "'node' axis of 4 nodes" in found[0].message


def test_fixture_replication_drift():
    """A collective originating inside a computation the contract
    asserts replicated — checked BEFORE site matching, so even a
    declared spec cannot launder it."""
    def plan_like(x):
        # stands in for round_plan: a value every node is supposed to
        # compute identically, which the partitioner re-reduces instead
        return jax.lax.psum(x * 2, "node")

    def entry(x):
        return plan_like(x)

    colls = _lower(entry, jnp.zeros((N, 8), jnp.int32))
    spec = CommSpec(name="fix.decl", op="all_reduce",
                    site=(THIS, ("plan_like", "entry")),
                    role="counter", when="always")
    found = _check(colls, _contract(
        specs=(spec,), replicated=((THIS, "plan_like"),)))
    assert [f.rule for f in found] == ["REPLICATION-DRIFT"]
    assert "plan_like" in found[0].message


def test_fixture_pr12_scan_lowered_exchange():
    """The resurrected PR 12 pitfall: exchange sub-rounds carried
    through ``lax.scan`` instead of a trace-time-unrolled Python loop,
    built from the REAL routing helpers (round_plan / pack_round /
    exchange) and judged by the REAL composed contract.  Every
    loop-carried collective must come back EXCHANGE-DYNAMIC-ROUND —
    the exchange.ship declaration must NOT excuse it — anchored at the
    loop site in this file."""
    CAP = 2

    def scan_exchange(keys):
        k = keys[0]
        dest = (k % N).astype(jnp.int32)
        held = jnp.zeros_like(k)
        sd, sidx, pos, rnd = routing.round_plan(dest, held, k, CAP)

        def sub_round(acc, r):
            kept = (sd < N) & (rnd == r)
            send, _ = routing.pack_round(sd, pos % CAP, kept, sidx,
                                         N, CAP, {"key": k[sidx]})
            got = routing.exchange(send, "node")
            return acc + got["key"].sum(), jnp.int32(0)

        acc, _ = jax.lax.scan(sub_round, jnp.int32(0),
                              jnp.arange(2, dtype=jnp.int32))
        return keys + acc

    colls = _lower(scan_exchange, jnp.zeros((N, 8), jnp.int32))
    in_loop = [c for c in colls if c.op == "all_to_all"]
    assert in_loop, "fixture lost its exchange"
    assert all(c.in_loop for c in in_loop)
    found = _check(colls, shard_certify.load_comm_contract())
    rules = {f.rule for f in found}
    assert rules == {"EXCHANGE-DYNAMIC-ROUND"}, found
    f = next(iter(found))
    assert f.path.endswith(THIS), f.path    # the loop site, this file
    assert f.line > 0
    assert "while" in f.message


def test_fixture_static_unroll_is_clean():
    """The remediation the rule's fix text prescribes: the same
    sub-round structure unrolled at trace time passes the real
    contract."""
    CAP = 2

    def unrolled_exchange(keys):
        k = keys[0]
        dest = (k % N).astype(jnp.int32)
        held = jnp.zeros_like(k)
        sd, sidx, pos, rnd = routing.round_plan(dest, held, k, CAP)
        acc = jnp.int32(0)
        for r in range(2):                  # static trip count
            kept = (sd < N) & (rnd == r)
            send, _ = routing.pack_round(sd, pos % CAP, kept, sidx,
                                         N, CAP, {"key": k[sidx]})
            got = routing.exchange(send, "node")
            acc = acc + got["key"].sum()
        return keys + acc

    colls = _lower(unrolled_exchange, jnp.zeros((N, 8), jnp.int32))
    assert sum(c.op == "all_to_all" for c in colls) == 2
    assert not any(c.in_loop for c in colls)
    found = _check(colls, shard_certify.load_comm_contract())
    assert found == []


# ---------------------------------------------------------------------------
# COMM_CONTRACT autodiscovery guard (parallel/ call sites <-> CommSpecs)

#: jax.lax collective callables -> StableHLO kind they lower to
_LAX_COLLECTIVES = {
    "psum": "all_reduce", "pmax": "all_reduce", "pmin": "all_reduce",
    "ppermute": "collective_permute", "pshuffle": "collective_permute",
    "all_to_all": "all_to_all", "all_gather": "all_gather",
    "psum_scatter": "reduce_scatter",
}

#: (relpath, enclosing def, kind) call sites excused from declaration,
#: with the reason — empty today; a new entry needs the same scrutiny
#: as a lint suppression
_EXCUSED: dict = {}


def _collective_call_sites():
    """AST-discovered collective call sites under parallel/: (relpath,
    innermost enclosing def, lowered kind, line)."""
    sites = []
    pkg = os.path.join(REPO, "deneva_tpu", "parallel")
    for fname in sorted(os.listdir(pkg)):
        if not fname.endswith(".py"):
            continue
        path = os.path.join(pkg, fname)
        rel = os.path.relpath(path, REPO)
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())

        def walk(node, func):
            for child in ast.iter_child_nodes(node):
                name = func
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    name = child.name
                if isinstance(child, ast.Call):
                    callee = child.func
                    attr = (callee.attr
                            if isinstance(callee, ast.Attribute)
                            else callee.id
                            if isinstance(callee, ast.Name) else None)
                    if attr in _LAX_COLLECTIVES:
                        sites.append((rel, func, _LAX_COLLECTIVES[attr],
                                      child.lineno))
                walk(child, name)

        walk(tree, "<module>")
    return sites


def test_autodiscovery_guard_every_call_site_declared():
    """Both directions: every collective call site in parallel/ must
    match a CommSpec (or carry an excuse above), and every CommSpec
    whose site lies under parallel/ must still have a live call site —
    new cross-node traffic cannot ship undeclared, and the contract
    cannot go stale."""
    from deneva_tpu.parallel.sharded import SHARDED_COMM
    sites = _collective_call_sites()
    assert sites, "AST scan found no collective call sites at all"

    def covered(rel, func, kind):
        return any(s.op == kind and rel.endswith(s.site[0])
                   and func in s.site[1] for s in SHARDED_COMM)

    undeclared = [(rel, func, kind, line)
                  for rel, func, kind, line in sites
                  if not covered(rel, func, kind)
                  and (rel, func, kind) not in _EXCUSED]
    assert undeclared == [], (
        f"collective call sites {undeclared} are neither declared as a "
        "CommSpec (parallel/routing.py ROUTING_COMM / parallel/"
        "sharded.py SHARDED_COMM) nor excused in _EXCUSED with a "
        "reason — the sharded certifier cannot prove undeclared "
        "traffic")
    assert all(_EXCUSED.values()), "bare _EXCUSED entry without reason"

    live = {(rel, func, kind) for rel, func, kind, _ in sites}
    stale = [s.name for s in SHARDED_COMM
             if "parallel/" in s.site[0]
             and not any(rel.endswith(s.site[0]) and func in s.site[1]
                         and s.op == kind
                         for rel, func, kind in live)]
    assert stale == [], f"CommSpecs {stale} match no call site anymore"


# ---------------------------------------------------------------------------
# meta-lint guard: rule docs cannot drift (all four engines)


def test_every_rule_id_has_a_lint_md_catalog_row():
    from deneva_tpu.lint.rules import RULES
    with open(os.path.join(REPO, "LINT.md"), encoding="utf-8") as fh:
        doc = fh.read()
    rows = [ln for ln in doc.splitlines()
            if ln.lstrip().startswith("|")]
    missing = [rid for rid in RULES
               if not any(f"`{rid}`" in ln for ln in rows)]
    assert missing == [], (
        f"rules {missing} are registered in lint/rules.py but have no "
        "catalog row in LINT.md — document the rule (or delete it)")


# ---------------------------------------------------------------------------
# the matrix


def test_shard_certify_small_cells_clean():
    """Real cells covering every declared collective: CALVIN's split
    exchange, MAAT's remote-cache gather, the repl permutes, the mesh
    extremum, and the counter-agg psums — the tier-1 anchor."""
    found = shard_certify.run_shard_certify(
        algs=("CALVIN", "MAAT"), workloads=("YCSB",),
        flags=("exchange_split", "remote_cache", "repl_cnt", "mesh"))
    assert [f for f in found if not f.suppressed] == [], \
        [f"{f.rule} {f.location()}: {f.message}" for f in found]


@pytest.mark.slow
def test_shard_certify_full_matrix_clean():
    """The acceptance criterion: 0 unsuppressed findings over the full
    plugin x workload x distributed-flag matrix (same run
    scripts/check.sh gates on)."""
    found = shard_certify.run_shard_certify()
    assert [f for f in found if not f.suppressed] == [], \
        [f"{f.rule} {f.location()}: {f.message}" for f in found
         if not f.suppressed]


def test_shard_certify_cli_exit_code_and_json():
    import json
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable, "-m", "deneva_tpu.lint.shard_certify",
         "--algs", "WAIT_DIE", "--workloads", "YCSB",
         "--flags", "mesh", "--format", "json"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout)
    assert doc["unsuppressed"] == 0
    assert isinstance(doc["findings"], list)
