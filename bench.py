"""Headline benchmark: simulated committed YCSB txns/sec on one chip.

Mirrors the reference's metric of record — committed txns / measured second
(``tput=`` in statistics/stats.cpp:437-447) — on the BASELINE.json config 2
shape: YCSB, zipf 0.6 contention, 50/50 read-write, 16M rows, 10 req/txn.

The headline ``value`` is the NO_WAIT faithful cell (acquire_window=1, the
reference's sequential state machine; PROFILE.md has the cost model and
tuning).  ``greedy_tput`` is window-10 batch acquisition — the engine's
native batched operating point.  ``algs`` carries EVERY CC algorithm's
faithful cell plus a TPC-C cell (round-5 contract: the sort-bound
algorithms MAAT/MVCC and TPC-C get a driver-visible, regression-guarded
number), each with BOTH wall tput and commits/tick — the latter is immune
to the tunneled chip's +-10-30% session drift, so cross-round comparisons
should prefer it.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.
vs_baseline scales the faithful number against the north star's per-chip
share: BASELINE.md targets >=1M txns/s on a v5e-8 (8 chips), i.e. 125k/s
per chip; this bench runs a single chip.

With ``--trace`` / ``--profile`` / ``--prog-interval`` the script instead
runs ONE small observed YCSB cell through the obs subsystem (deneva_tpu/obs):
[prog] heartbeats, a Perfetto-loadable Chrome trace, a phase-profile and a
structured run record under --out-dir, plus a trace-vs-summary
reconciliation check.  EXPERIMENTS.md documents the CPU smoke invocation.

With ``--xmeter`` the script runs the compile & memory observatory smoke
(obs/xmeter.py, Config.xmeter): a warmup window, then a BLOCKED steady
window under the recompile sentinel — any post-warmup compile names its
entry point and fails the run — plus the HBM footprint ledger reconciled
against the compiled tick's own ``memory_analysis()`` and the generated
per-kernel roofline table.  scripts/check.sh gates on its exit code.

With ``--offered-load`` the script runs the open-system saturation sweep
(deneva_tpu/traffic/, Config.arrival): a Poisson arrival-rate grid per CC
algorithm on the small observed cell, finding each algorithm's saturation
KNEE (the highest rate still served with served/offered >= 0.95) and
recording p50/p99 latency, queue depth and the OVERLOAD watchdog bit per
point into ``offered_load_sweep.json``.  EXPERIMENTS.md has the recipe.

With ``--serve`` the script runs the LONG-RUNNING serve loop
(deneva_tpu/obs/telemetry.py, Config.slo): a flash-crowd rate-step
schedule plus a mid-run hot-set shift on the open-system cell, with the
host-side exporter streaming OpenMetrics + JSONL from the exact
mergeable latency histograms every ``slo_export_interval`` ticks (pure
np.asarray reads — never entering the jit path), multi-window
error-budget burn-rate alerting (obs/slo.py) printing the live SLO
table, and the xmeter sentinel proving ZERO steady-state recompiles
across the whole schedule.  Writes ``serve_slo.json``; the SLO watchdog
bit (128, obs/report.py) and the recompile/reconcile bits ride the
exit code.  EXPERIMENTS.md has the flash-crowd recipe.

With ``--faults`` the script runs the fault-plane smoke (Config.faults,
deneva_tpu/faults/): three scenarios on a small 2-node sharded CALVIN
cell — a mid-run node KILL recovered by deterministic replay from the
last checkpoint (engine/checkpoint.py) and validated bit-for-bit against
a fault-free oracle run, a STRAGGLE window and a PARTITION window (both
gated inside the jitted tick, work delayed never aborted).  Records the
recovery cost (``recovery_lag_ticks``) and the in-tick fault counters
into ``faults_smoke.json``; the RECOVERY watchdog bit (obs/report.py)
rides the exit code.  EXPERIMENTS.md has the kill-a-node recipe.

With ``--scaling-grid`` the script runs the cluster scaling surface: a
virtual-node grid (1/2/4/8, clamped to the device count) x two per-node
batch shapes sized by the obs/xmeter.py ``fit_batch`` footprint model,
every cell a ShardedEngine run with the mesh observatory
(``Config.mesh``) on, so each scaling number carries the per-node-pair
traffic matrix, Jain imbalance and remote-ratio that explain it.
Writes ``scaling_grid.json``; EXPERIMENTS.md ("Diagnosing the flat MAAT
scaling curve") reads it.

With ``--depgraph`` the script runs the conflict dependency observatory
sweep (Config.depgraph, deneva_tpu/obs/depgraph.py): each CC algorithm's
small observed cell with the device-resident wait-for graph on — every
plugin emits WHO blocked each waiter/victim, the engine samples
(waiter, blocker, key, reason, tick) edges into a keep-last ring and
keeps exact per-tick chain-depth/convoy planes — then reconciles the
edge counters exactly against the twopl_wait integral and the abort
taxonomy, detects cycles, decomposes commit critical paths against the
flight spans, and appends per-alg chain-depth cells that feed the
inverted obs/regress.py ceiling.

Every headline run additionally APPENDS one JSON line to
``<out-dir>/bench_history.jsonl`` (unix time, git commit, config
fingerprint, headline value, per-algorithm cells) — the trajectory that
``python -m deneva_tpu.obs.regress`` gates against.  ``--no-history``
skips the append (use for throwaway experiments).
"""

import argparse
import json
import os
import subprocess
import sys
import time

# the --scaling-grid virtual-node grid needs >1 device on CPU hosts, and
# --xla_force_host_platform_device_count only takes effect before the
# jax backend initialises (imports below may touch it), so the flag is
# set from argv BEFORE `import jax` — the same trick as tests/conftest.py
if ("--scaling-grid" in sys.argv or "--faults" in sys.argv) and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    # size the virtual-device pool from the requested node grid so the
    # 16/32/64-node scale-out cells are actually runnable (previously a
    # hard 8 silently clamped them away); 64 host threads is the
    # practical CPU ceiling, 8 covers the default grid and --faults
    _dev = 8
    for _i, _a in enumerate(sys.argv):
        if _a == "--grid-nodes" and _i + 1 < len(sys.argv):
            _v = sys.argv[_i + 1]
        elif _a.startswith("--grid-nodes="):
            _v = _a.split("=", 1)[1]
        else:
            continue
        _ns = [int(x) for x in _v.split(",") if x.strip().isdigit()]
        if _ns:
            _dev = max(_dev, min(max(_ns), 64))
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={_dev}")

import jax
import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine
from deneva_tpu.obs import profiler as obs_profiler
from deneva_tpu.obs import trace as obs_trace

NORTH_STAR_CLUSTER = 1_000_000   # committed txns/s on a v5e-8 (BASELINE.md)
NORTH_STAR_CHIPS = 8

YCSB_KW = dict(
    batch_size=8192,
    synth_table_size=1 << 24,   # 16M rows (paper-scale, BASELINE.md grid)
    req_per_query=10,
    zipf_theta=0.6,
    tup_read_perc=0.5,
    query_pool_size=1 << 16,
    warmup_ticks=0,
    backoff=True,
    # tuned concurrency throttle for BOTH cells: in the greedy cell it
    # holds steady-state in-flight txns low enough that the abort rate
    # stays ~0.16 (uncapped admission drives contention up and measures
    # ~280k/s vs ~430k/s capped; sweep in PROFILE.md)
    admit_cap=1024,
)

# the PROFILE.md TPC-C cell: 64 warehouses, Payment/NewOrder mix, MVCC
TPCC_KW = dict(
    workload="TPCC", cc_alg="MVCC", batch_size=8192, num_wh=64,
    cust_per_dist=2000, max_items=1024, query_pool_size=1 << 16,
    warmup_ticks=0, admit_cap=1024,
)


def run_cell(cfg: Config, n_ticks: int = 300, windows: int = 7):
    eng = Engine(cfg)
    # two warmup rounds: the first post-compile dispatch runs ~5x slow
    # (device power/prefetch state), and the second reaches steady-state
    # occupancy; SAME trip count as the timed run (fori_loop trip count is
    # static — a different count would recompile inside the timed window)
    state = eng.run_compiled(n_ticks)
    state = eng.run_compiled(n_ticks, state)
    jax.block_until_ready(state.stats["txn_cnt"])

    # median of `windows` measured windows: the tunneled chip shows
    # ~+-8-15% window-to-window variance under host load, and each
    # 300-tick window costs well under a second
    tputs, cpt = [], []
    for _ in range(windows):
        committed_before = int(np.asarray(state.stats["txn_cnt"]))
        t0 = time.perf_counter()
        state = eng.run_compiled(n_ticks, state)
        jax.block_until_ready(state.stats["txn_cnt"])
        dt = time.perf_counter() - t0
        committed = int(np.asarray(state.stats["txn_cnt"])) - committed_before
        tputs.append(committed / dt)
        cpt.append(committed / n_ticks)
    return float(np.median(tputs)), float(np.median(cpt)), eng.summary(state)


def _abort_fields(summary: dict) -> dict:
    """Per-cell abort diagnostics for the bench JSON: the whole-run abort
    rate plus the top-3 taxonomy reasons (obs/report.py; present only
    when the cell ran with Config.abort_attribution)."""
    from deneva_tpu.obs import report as obs_report
    out = {"abort_rate": round(float(summary.get("abort_rate", 0.0)), 4)}
    top = obs_report.top_reasons(summary, k=3)
    if top:
        out["top_abort_reasons"] = {name: cnt for name, cnt in top}
    return out


# small, CPU-friendly observed cell (the EXPERIMENTS.md smoke shape):
# contended enough that aborts/waits show up on the timeline
OBS_KW = dict(
    batch_size=256, synth_table_size=1 << 12, req_per_query=4,
    zipf_theta=0.8, tup_read_perc=0.5, query_pool_size=1 << 12,
    warmup_ticks=0, admit_cap=64,
)


def run_obs(args) -> int:
    """Observed run: trace + [prog] + phase profile on a small YCSB cell,
    with the abort-attribution observatory ON (taxonomy counters, hashed
    hot-key heatmap, waterfall + watchdog report from obs/report.py).
    Returns a process exit code (non-zero when reconciliation fails or
    the watchdog flags live-lock / spill storms / starved shards)."""
    from deneva_tpu.obs import report as obs_report
    windows_on = getattr(args, "windows", False)
    win_kw = {}
    if windows_on:
        # size the ring so the run can never wrap (the reconcile would
        # loudly refuse a lossy ring): one slot per latch cadence
        wt = max(args.window_ticks, 1)
        win_kw = dict(windows=True, window_ticks=wt,
                      window_slots=max(-(-args.ticks // wt), 1))
    cfg = Config(
        cc_alg=args.cc_alg,
        trace_ticks=(args.trace_ticks or args.ticks) if args.trace else 0,
        prog_interval=args.prog_interval,
        profile=args.profile,
        abort_attribution=True,
        heatmap_bins=256,
        **win_kw,
        **OBS_KW)
    eng = Engine(cfg)
    t0 = time.perf_counter()
    state = eng.run(args.ticks)
    wall = time.perf_counter() - t0
    summary = eng.summary(state, wall)
    print(eng.summary_line(state, wall))

    code = 0
    artifacts = {}
    win_snap = extra_rec = None
    if windows_on:
        # the window identity is a hard gate like the trace reconcile:
        # sum of per-window deltas must equal the final cumulative
        # counters exactly, wrap refused loudly
        from deneva_tpu.obs import windows as obs_windows
        win_snap = eng.window_snapshot(state)
        findings = obs_windows.reconcile(win_snap, summary)
        print(f"[windows] {obs_windows.n_valid(win_snap)} window(s) of "
              f"{cfg.window_ticks} tick(s), "
              f"{len(win_snap['cols_i']) - 1} int + "
              f"{len(win_snap['cols_f'])} float column(s): "
              + ("identity OK" if not findings else f"FAIL {findings}"))
        if findings:
            code = 1
        extra_rec = obs_windows.record_extra(cfg, state.stats, state.db)
    if args.trace:
        tr_path = f"{args.out_dir}/trace_{cfg.cc_alg.lower()}.json"
        os.makedirs(args.out_dir, exist_ok=True)
        obs_trace.to_chrome_trace(state, tr_path, n_ticks=args.ticks,
                                  windows=win_snap)
        artifacts["chrome_trace"] = tr_path
        # reconciliation: ring column sums == whole-run [summary] counters
        # (exact: warmup_ticks=0 and the ring accumulates on wrap)
        tot = obs_trace.totals(state)
        checks = {"commit": ("txn_cnt", tot["commit"]),
                  "abort": ("total_txn_abort_cnt", tot["abort"]),
                  "admit": ("local_txn_start_cnt", tot["admit"]),
                  "lock_wait": ("twopl_wait_cnt", tot["lock_wait"])}
        for col, (key, got) in checks.items():
            want = summary[key]
            ok = got == want
            print(f"[reconcile] trace.{col}={got} summary.{key}={want} "
                  f"{'OK' if ok else 'MISMATCH'}")
            if not ok:
                code = 1
    if args.profile or args.trace or windows_on:
        # windowed runs always leave a record: the "windows" block is
        # what `python -m deneva_tpu.obs.diff` consumes (two records,
        # or one record with --windows for the within-run phase diff)
        rec = obs_profiler.run_record(
            cfg, summary,
            phases=eng.profiler.snapshot() if eng.profiler else None,
            timeline=(obs_trace.timeline(state) if args.trace else None),
            extra={"wall_seconds": wall, "artifacts": artifacts,
                   **(extra_rec or {})})
        rec_path = obs_profiler.write_run_record(rec, out_dir=args.out_dir)
        print(f"[obs] run record: {rec_path}")
    if eng.profiler is not None:
        print(f"[obs] phases: {json.dumps(eng.profiler.snapshot())}")
    # waterfall + taxonomy + hot keys + watchdog (the obs smoke gate in
    # scripts/check.sh fails on any finding via the exit bitmask)
    rep = obs_report.build_report(
        summary, timeline=(obs_trace.timeline(state) if args.trace
                           else None),
        stats=state.stats, topk=cfg.heatmap_topk)
    print(obs_report.render_text(rep))
    code |= rep["watchdog"]["exit_code"]
    return code


def run_offered_load(args, out_dir: str = "results",
                     history: bool = True) -> int:
    """--offered-load: open-system saturation sweep (deneva_tpu/traffic/).

    Walks a Poisson arrival-rate grid per CC algorithm on the small
    observed cell and finds the saturation KNEE — the highest offered
    rate the engine still serves: ``served_frac`` = admissions/arrivals
    must stay >= 0.95 with a drained run-end queue, i.e. no OVERLOAD
    (below the knee the queue drains; past it backlog grows without
    bound and the OVERLOAD watchdog bit fires).  Each
    point records offered vs served rate, commits/tick, the short
    (ccl50/ccl99) and long (famlat p50/p99, restarts + queueing behind
    admission included) latency percentiles, final/peak queue depth and
    the watchdog bitmask.  Writes ``<out-dir>/offered_load_sweep.json``,
    prints the headline JSON line and appends an
    ``offered_load_knee`` record (knee + per-alg commits/tick at the
    knee) to the bench history for the regression gate.

    Exit code 0 when every algorithm produced a knee and every
    sub-knee point stayed OVERLOAD-free; 1 otherwise."""
    from deneva_tpu import stats as stats_mod
    from deneva_tpu.obs import report as obs_report
    rates = [float(r) for r in args.rates.split(",") if r]
    alg_list = (list(_ALGS) if args.algs == "all"
                else [a.strip().upper() for a in args.algs.split(",") if a])
    sweep, knees, algs_hist = {}, {}, {}
    code = 0
    for alg in alg_list:
        points = []
        for rate in rates:
            cfg = Config(cc_alg=alg, arrival="poisson", arrival_rate=rate,
                         slo=True, **OBS_KW)
            eng = Engine(cfg)
            state = eng.run(args.ticks)
            s = eng.summary(state)
            ticks = max(s["measured_ticks"], 1)
            arrived = s["arrival_cnt"] / ticks
            served = s["queue_admit_cnt"] / ticks
            frac = served / max(arrived, 1e-9)
            ccl = stats_mod.latency_percentiles(s["ccl_samples"],
                                                s["ccl_valid"])
            _, wd = obs_report.watchdog(s)
            points.append({
                "offered": rate,
                "arrived_per_tick": round(arrived, 2),
                "served_per_tick": round(served, 2),
                "served_frac": round(frac, 4),
                "commits_per_tick": round(s["txn_cnt"] / ticks, 2),
                "p50": ccl["ccl50"], "p99": ccl["ccl99"],
                # long-latency quantiles ROUTED THROUGH the exact SLO
                # histograms (obs/histo.py, Config.slo above): the famlat
                # survivor rings keep only the last fam_lat_samples
                # commits per family and bias the tail once arrivals
                # outrun them (tests/test_telemetry.py demonstrates the
                # divergence); the ring values stay as fallback for
                # slo-less replays of old sweeps
                "famlat_p50": s.get("slo_fam0_p50",
                                    s.get("famlat0_p50", 0.0)),
                "famlat_p99": s.get("slo_fam0_p99",
                                    s.get("famlat0_p99", 0.0)),
                "queue_len": s["queue_len"],
                "queue_peak": s["queue_peak"],
                "watchdog": wd,
            })
        sweep[alg] = points
        # a knee point must both serve >= 95% of offered AND end with a
        # drained queue (no OVERLOAD) — a borderline cell that squeaks
        # past 0.95 while carrying run-end backlog is already saturated
        ok = [p for p in points if p["served_frac"] >= 0.95
              and not p["watchdog"] & obs_report.OVERLOAD]
        knee = max((p["offered"] for p in ok), default=0.0)
        knees[alg] = knee
        at_knee = next((p for p in points if p["offered"] == knee), None)
        if at_knee is None:
            code = 1
        else:
            algs_hist[f"{alg}@knee"] = {
                "commits_per_tick": at_knee["commits_per_tick"]}
        # a sub-knee point must never trip OVERLOAD (backlog drains)
        if any(p["watchdog"] & obs_report.OVERLOAD
               for p in points if p["offered"] <= knee):
            code = 1
    doc = {
        "metric": "offered_load_knee",
        "value": knees.get("NO_WAIT", next(iter(knees.values()), 0.0)),
        "unit": "arrivals_per_tick",
        "ticks": args.ticks,
        "offered_load": rates,
        "knee": knees,
        "algs": algs_hist,
        "sweep": sweep,
        "note": "knee = max Poisson rate with served/offered >= 0.95 and "
                "a drained run-end queue (no OVERLOAD) on the small "
                "observed cell (OBS_KW); served = admissions through the "
                "traffic/ backpressure gate; past the knee the admission "
                "queue grows and OVERLOAD (16) fires",
    }
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "offered_load_sweep.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({k: v for k, v in doc.items() if k != "sweep"}))
    print(f"[offered-load] sweep written: {path}")
    if history:
        _append_history(doc, Config(cc_alg=alg_list[0], arrival="poisson",
                                    arrival_rate=rates[0], slo=True,
                                    **OBS_KW),
                        out_dir)
    return code


_ALGS = ("NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT",
         "CALVIN")


def run_serve(args, out_dir: str = "results", history: bool = True) -> int:
    """--serve: the long-running serve loop + streaming telemetry plane.

    Drives the open-system traffic engine (deneva_tpu/traffic/) through
    a flash-crowd rate-step schedule (low -> burst at 1/4 -> back to low
    at 1/2 of the run) AND a mid-run hot-set shift (the check.sh
    adaptive-smoke idiom: the query pool's back half is bijectively
    remapped to mid-table, so the hot set jumps when the pool cursor
    crosses — pure data flow, nothing retraces), while the host-side
    exporter (obs/telemetry.py) polls the carried stats every
    ``Config.slo_export_interval`` ticks WITHOUT entering the jit path:

    - OpenMetrics text exposition atomically rewritten per poll
      (``metrics.om``) + append-only JSONL stream (``telemetry.jsonl``),
      quantiles from the EXACT mergeable histograms (obs/histo.py);
    - multi-window error-budget burn-rate alerting (obs/slo.py): the
      burst burns the budget and FIRES, the drain clears it — the
      fire -> drain -> clear timeline prints as the live SLO table;
    - the whole schedule runs under the obs/xmeter.py recompile
      sentinel: ZERO steady-state recompiles after the warmup interval.

    Writes ``<out-dir>/serve_slo.json`` and appends a ``serve_slo``
    record whose per-family ``slo_p99`` cells feed the self-arming
    obs/regress.py ceiling gate.  Exit bitmask: 1 = post-warmup
    recompile, 2 = histogram reconciliation failure, plus the watchdog
    bitmask (SLO bit 128 = alert still firing at run end)."""
    import dataclasses
    from deneva_tpu.obs import report as obs_report
    from deneva_tpu.obs import telemetry as obs_telemetry
    from deneva_tpu.workloads.ycsb import gen_query_pool

    total = args.serve_ticks
    low, high = args.serve_rate, args.serve_burst
    # burst for total/8 ticks starting at 1/4: the crowd's backlog must
    # be DRAINABLE in the remaining window (capacity - low per tick), so
    # the alert can clear before run end — a burst that outruns the
    # drain leaves the SLO watchdog bit (128) firing, by design
    t_up, t_down = total // 4, (total * 3) // 8
    schedule = ((0, low), (t_up, high), (t_down, low))
    kw = dict(OBS_KW)
    # serve cell: zipf 0.5 (not the OBS 0.8) so the steady-state tail
    # sits crisply UNDER the 16-tick p99 ceiling (measured window bad
    # frac 0.0 at the baseline rate vs 30%+ inside the crowd) — the
    # zipf 0.8 cell's backoff stragglers breach any tight ceiling even
    # at steady state and the alert flaps instead of clearing; the
    # smaller pool makes the cursor actually CROSS the remapped back
    # half (the hot-set shift) several times inside the run
    kw.update(zipf_theta=0.5, query_pool_size=1 << 10)
    cfg = Config(cc_alg=args.cc_alg, slo=True, xmeter=True,
                 slo_p99_ceiling=16, abort_attribution=True,
                 arrival="step", arrival_schedule=schedule, **kw)
    # hot-set shift: front half of the pool hammers the low-id hot rows,
    # back half the same rows remapped to mid-table (bijective, zero
    # retrace when the cursor crosses)
    pool = gen_query_pool(cfg)
    n = cfg.synth_table_size - 1
    keys = pool.keys.copy()
    half = keys.shape[0] // 2
    keys[half:] = ((keys[half:] + n // 2 - 1) % n) + 1
    eng = Engine(cfg, pool=dataclasses.replace(pool, keys=keys))

    os.makedirs(out_dir, exist_ok=True)
    exporter = obs_telemetry.TelemetryExporter(cfg, out_dir)
    tracker = exporter.tracker
    interval = max(int(cfg.slo_export_interval), 1)

    t0 = time.perf_counter()
    state = eng.run(interval)          # warmup interval: compiles land here
    eng.xmeter.mark_warm()
    tick = interval
    records = [exporter.poll(state, tick)]
    while tick < total:
        state = eng.run(interval, state)
        tick += interval
        records.append(exporter.poll(state, tick))
    wall = time.perf_counter() - t0

    summary = eng.summary(state, wall)
    summary.update(tracker.summary_fields())
    print(eng.summary_line(state, wall))

    code = 0
    viol = eng.xmeter.steady_violations()
    if viol:
        for v in viol:
            print(f"[serve] RECOMPILE {v['entry']}: {v['signature']}")
        code |= 1
    else:
        cnt, ms = eng.xmeter.compile_totals()
        print(f"[serve] zero steady-state recompiles across the rate "
              f"step + hot-set shift ({cnt} warmup compiles, "
              f"{ms:.0f} ms, {len(records)} polls)")

    hist_total = int(summary["hist_total_cnt"])
    commits = int(summary["txn_cnt"])
    ok = hist_total == commits
    print(f"[reconcile] hist_total_cnt={hist_total} txn_cnt={commits} "
          f"{'OK' if ok else 'MISMATCH'}")
    if not ok:
        code |= 2

    # the live SLO table: one row per exporter poll (the JSONL stream)
    print("[serve]  tick  rate    p99  burn_fast  burn_slow  served  "
          "alert")
    for rec in records:
        rate = [p for p in schedule if p[0] <= rec["tick"]][-1][1]
        flag = rec.get("event", "").upper() \
            or ("firing" if rec["alert_active"] else "")
        print(f"  {rec['tick']:>6} {rate:>5g} {rec['fam']['0']['p99']:>6g}"
              f" {rec['burn_fast']:>10.2f} {rec['burn_slow']:>10.2f}"
              f" {rec['served_frac']:>7.3f}  {flag}")

    rep = obs_report.build_report(summary)
    print(obs_report.render_text(rep))
    code |= rep["watchdog"]["exit_code"]

    slo_p99 = {f"fam{fr['family']}": fr["p99"]
               for fr in rep.get("slo", {}).get("families", [])}
    doc = {
        "metric": "serve_slo",
        "value": float(summary.get("slo_fam0_p99", 0.0)),
        "unit": "p99_ticks",
        "ticks": total,
        "interval": interval,
        "schedule": [list(p) for p in schedule],
        "slo_p99": slo_p99,
        "alerts": [list(e) for e in tracker.events],
        "burn_fast": round(float(summary["burn_fast"]), 4),
        "burn_slow": round(float(summary["burn_slow"]), 4),
        "breach_ticks": int(summary["slo_breach_ticks"]),
        "steady_recompiles": len(viol),
        "watchdog": rep["watchdog"]["exit_code"],
        "artifacts": {"openmetrics": exporter.om_path,
                      "jsonl": exporter.jsonl_path},
        "note": "flash-crowd serve loop: rate step low->burst->low + "
                "mid-run hot-set shift under the xmeter sentinel; p99 "
                "from the exact histogram plane; alerts = the "
                "(tick, fire/clear) burn-rate timeline; exit bitmask "
                "1=recompile 2=hist reconcile | watchdog (SLO=128)",
    }
    path = os.path.join(out_dir, "serve_slo.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({k: v for k, v in doc.items()
                      if k not in ("schedule", "artifacts")}))
    print(f"[serve] record written: {path}")
    if history:
        _append_history(doc, cfg, out_dir)
    return code


# the small sharded cell every scaling-grid point runs (the OBS_KW analog
# for the cluster engine): contended enough that cross-node waits shape
# the curve, small enough that an 8-node CPU cell compiles in seconds
GRID_KW = dict(
    synth_table_size=1 << 12, req_per_query=4, zipf_theta=0.6,
    tup_read_perc=0.5, query_pool_size=1 << 10, warmup_ticks=0, mpr=1.0,
)


def _state_nbytes(state) -> int:
    return sum(getattr(leaf, "nbytes", 0)
               for leaf in jax.tree_util.tree_leaves(state))


def run_scaling_grid(args, out_dir: str = "results",
                     history: bool = True) -> int:
    """--scaling-grid: the nodes x batch cluster scaling surface.

    Runs ShardedEngine cells over a virtual-node grid (default 1/2/4/8,
    clamped to the device count) x TWO per-node batch shapes sized by the
    obs/xmeter.py ``fit_batch`` footprint model (two probe states fit the
    linear bytes(B) curve; the large shape is the biggest power of two
    the ``--grid-budget-mb`` budget admits, capped for CPU smoke runs).
    Every cell runs with ``Config.mesh`` on, so each point carries the
    traffic-matrix diagnostics that EXPLAIN its scaling number:

    - ``speedup``/``efficiency``  cluster commits/tick vs the same-shape
      1-node cell (efficiency = speedup / nodes);
    - ``imb_jain``                Jain fairness over per-node commits;
    - ``remote_ratio``            remote entry attempts per requested
      access (txn_cnt * req_per_query) — the mesh's share of the work;
    - ``straggler_ticks``/``mesh_drops``/``watchdog``.

    Each cell's mesh matrix is reconciled exactly (obs/mesh.py
    reconcile); any mismatch — or a zero-commit cell — fails the run.
    Writes ``<out-dir>/scaling_grid.json``, prints the headline JSON
    line, and appends a ``scaling_grid`` record whose per-cell
    ``efficiency`` values feed the obs/regress.py gate.  EXPERIMENTS.md
    ("Diagnosing the flat MAAT scaling curve") reads the output.

    Exit code 0 when every cell committed work and reconciled; 1
    otherwise."""
    from deneva_tpu.obs import mesh as obs_mesh
    from deneva_tpu.obs import report as obs_report
    from deneva_tpu.obs import xmeter as obs_xmeter
    from deneva_tpu.parallel.sharded import ShardedEngine

    # the grid defaults to MAAT — the flat-scaling curve under diagnosis
    # (ROADMAP item 2) — but --algs sweeps any subset
    alg_list = (["MAAT"] if args.algs == "all"
                else [a.strip().upper() for a in args.algs.split(",") if a])
    node_grid = [int(n) for n in args.grid_nodes.split(",") if n]
    avail = jax.device_count()
    usable = [n for n in node_grid if n <= avail]
    if usable != node_grid:
        print(f"[scaling-grid] {avail} devices: node grid clamped to "
              f"{usable}")
    if not usable:
        print("[scaling-grid] no runnable node counts")
        return 1

    # scale-out flags (both Config._optin, certifier-proven pure when
    # off): multi-node cells only — the 1-node anchor has no exchange.
    # test_mesh.py builds a bare Namespace, hence getattr defaults.
    rc_on = getattr(args, "grid_remote_cache", False)
    split_on = getattr(args, "grid_split", False)
    pipe_on = getattr(args, "pipeline", False)

    def grid_cfg(alg, n, b):
        extra = {}
        if n > 1 and rc_on:
            extra["remote_cache"] = True
        if n > 1 and split_on:
            extra["exchange_split"] = True
        if n > 1 and pipe_on:
            # the Config constructor does not apply _optin on-dicts, so
            # the pipelined cells set BOTH flags (pipeline_exchange's
            # on-dict implies exchange_split); inert on abort-capable
            # plugins — run with --algs CALVIN for live pipelined cells
            extra["exchange_split"] = True
            extra["pipeline_exchange"] = True
        return Config(cc_alg=alg, node_cnt=n, part_cnt=n, batch_size=b,
                      part_per_txn=min(2, n), mesh=True, **GRID_KW,
                      **extra)

    # two batch shapes from the footprint model: probe the sharded state
    # at B=32 and B=64, fit bytes(B) = fixed + per_txn * B, take the
    # largest power-of-two batch the budget admits (capped so the CPU
    # smoke stays fast), with the 32/node shape as the small anchor
    probe_n = min(2, avail)
    probes = {b: _state_nbytes(
        ShardedEngine(grid_cfg(alg_list[0], probe_n, b)).init_state())
        for b in (32, 64)}
    fit = obs_xmeter.fit_batch(args.grid_budget_mb, probes,
                               node_cnt=max(usable))
    large = 64
    while large * 2 <= min(fit["max_batch_per_node"], args.grid_max_batch):
        large *= 2
    shapes = (32, large) if large > 32 else (16, 32)
    print(f"[scaling-grid] fit_batch: per_txn={fit['per_txn_bytes']:.0f}B "
          f"fixed={fit['fixed_bytes']}B -> max "
          f"{fit['max_batch_per_node']}/node under "
          f"{args.grid_budget_mb:.0f}MB; shapes {shapes}")

    code = 0
    grid = {alg: [] for alg in alg_list}
    cells_hist = {}
    for alg in alg_list:
        for b in shapes:
            base_cpt = None
            for n in usable:
                cfg = grid_cfg(alg, n, b)
                eng = ShardedEngine(cfg)
                state = eng.run_compiled(args.ticks)       # compile+warm
                jax.block_until_ready(state.stats["txn_cnt"])
                before = int(np.asarray(state.stats["txn_cnt"]).sum())
                t0 = time.perf_counter()
                state = eng.run_compiled(args.ticks, state)
                jax.block_until_ready(state.stats["txn_cnt"])
                dt = time.perf_counter() - t0
                s = eng.summary(state)
                snap = eng.mesh_snapshot(state)
                bad = obs_mesh.reconcile(snap, s)
                for what, got, want in bad:
                    print(f"[scaling-grid] {alg} n={n} B={b} RECONCILE "
                          f"MISMATCH {what}: got={got} want={want}")
                    code = 1
                ticks = max(s["measured_ticks"], 1)
                cpt = s["txn_cnt"] / ticks
                if n == usable[0]:
                    base_cpt = cpt
                if s["txn_cnt"] == 0:
                    code = 1
                # speedup vs the smallest grid point at this shape,
                # normalised to its node count (speedup==nodes is ideal)
                speedup = (cpt / base_cpt * usable[0]
                           if base_cpt else 0.0)
                accesses = max(s["txn_cnt"] * cfg.req_per_query, 1)
                _, wd = obs_report.watchdog(s)
                cell = {
                    "nodes": n, "batch_per_node": b,
                    "commits_per_tick": round(cpt, 2),
                    "tput": round((int(np.asarray(
                        state.stats["txn_cnt"]).sum()) - before) / dt, 1),
                    "speedup": round(speedup, 3),
                    "efficiency": round(speedup / n, 4),
                    "imb_jain": round(float(s["imb_jain"]), 4),
                    "remote_ratio": round(
                        s["remote_entry_cnt"] / accesses, 4),
                    "straggler_ticks": s["straggler_tick_cnt"],
                    "mesh_drops": s["mesh_drop_cnt"],
                    "watchdog": wd,
                }
                # remote-grant stickiness diagnostics (Config.remote_cache):
                # attempts = entries the exchange WOULD have shipped,
                # suppressed = attempts answered from the device-resident
                # grant cache instead of re-shipping
                if "remote_attempt_cnt" in s:
                    cell["remote_attempts"] = s["remote_attempt_cnt"]
                    cell["reship_suppressed"] = s["reship_suppressed_cnt"]
                    cell["remote_cache_hits"] = s["remote_cache_hit_cnt"]
                # software-pipeline occupancy (Config.pipeline_exchange
                # live on this cell): the fraction of issued exchange
                # legs that overlapped another leg of their pass
                if "pipe_leg_cnt" in s:
                    cell["pipeline_overlap_frac"] = round(
                        s["pipe_overlap_cnt"] / max(s["pipe_leg_cnt"], 1),
                        4)
                grid[alg].append(cell)
                # flagged cells key their own trajectory:
                # '+rc'/'+split'/'+pipe' numbers must not shift the
                # baseline medians the obs/regress.py gate compares
                # against
                tag = (("+rc" if (n > 1 and rc_on) else "")
                       + ("+split" if (n > 1 and split_on) else "")
                       + ("+pipe" if (n > 1 and pipe_on) else ""))
                cells_hist[f"{alg}@{n}x{b}{tag}"] = {
                    "commits_per_tick": cell["commits_per_tick"],
                    "efficiency": cell["efficiency"],
                    # remote amplification, gated INVERTED by
                    # obs/regress.py (growing ratio = regression)
                    "amplification": cell["remote_ratio"]}
                if "pipeline_overlap_frac" in cell:
                    # self-arms an obs/regress.py floor for the
                    # pipelined cells' overlap fraction
                    cells_hist[f"{alg}@{n}x{b}{tag}"][
                        "pipeline_overlap_frac"] = \
                        cell["pipeline_overlap_frac"]
                print(f"[scaling-grid] {alg} n={n} B={b}{tag}: "
                      f"{cell['commits_per_tick']} commits/tick, "
                      f"speedup {cell['speedup']} "
                      f"(eff {cell['efficiency']}), "
                      f"jain {cell['imb_jain']}, "
                      f"remote {cell['remote_ratio']}")
    head = grid[alg_list[0]][-1] if grid[alg_list[0]] else {}
    doc = {
        "metric": "scaling_grid",
        "value": head.get("efficiency", 0.0),
        "unit": "parallel_efficiency",
        "ticks": args.ticks,
        "nodes": usable,
        "batch_shapes": list(shapes),
        "fit_batch": fit,
        "scaling_grid": cells_hist,
        "grid": grid,
        "note": "nodes x per-node-batch surface on the small sharded "
                "cell (GRID_KW, Config.mesh on); speedup = cluster "
                "commits/tick vs the smallest same-shape point scaled "
                "to its node count, efficiency = speedup/nodes; "
                "remote_ratio = remote entry attempts per requested "
                "access; value = the last alg's largest cell efficiency",
    }
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "scaling_grid.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({k: v for k, v in doc.items() if k != "grid"}))
    print(f"[scaling-grid] grid written: {path}")
    if history:
        _append_history(doc, grid_cfg(alg_list[0], usable[-1], shapes[-1]),
                        out_dir)
    return code


def run_faults(args, out_dir: str = "results", history: bool = True) -> int:
    """--faults: deterministic fault plane + recovery smoke
    (Config.faults, deneva_tpu/faults/).

    Three scenarios on a small 2-node sharded CALVIN cell (CALVIN so the
    per-node epoch log, ``arr_fault_elog_*``, is live):

    - KILL: node 1 dies at the mid-run tick boundary; the host driver
      (faults/recovery.py) recovers its shard slice by deterministic
      replay from the last checkpoint (``Config.checkpoint_every``,
      engine/checkpoint.py) and validates it — epoch log included —
      bit-for-bit against the pre-crash slice.  The recovered run's
      [summary] must then match a fault-free oracle run of the same
      config on every integer counter; ``recovery_lag_ticks`` is the
      recovery COST (ticks replayed).
    - STRAGGLE: one node freezes for a window; the tick gates its new
      admissions/requests/finishing (work delayed, never aborted) and
      the run still commits.
    - PARTITION: a node pair loses its link for a window; cross-pair
      new requests are withheld symmetrically and the run still commits.

    Writes ``<out-dir>/faults_smoke.json`` and appends a
    ``fault_recovery`` history record (no commits_per_tick cells, so
    the obs/regress.py gate treats it as metadata).  Exit code ORs the
    RECOVERY watchdog bit (obs/report.py) on any parity failure."""
    from deneva_tpu import faults as faults_mod
    from deneva_tpu.obs import report as obs_report
    from deneva_tpu.parallel.sharded import ShardedEngine

    if jax.device_count() < 2:
        print("[faults] needs >= 2 devices")
        return 1

    def fault_cfg(faults=(), checkpoint_every=0):
        return Config(cc_alg="CALVIN", node_cnt=2, part_cnt=2,
                      batch_size=64, part_per_txn=2, faults=faults,
                      checkpoint_every=checkpoint_every, **GRID_KW)

    ticks = args.ticks
    mid = ticks // 2
    code = 0
    doc_scen = {}

    # --- KILL: recover-by-replay, then bit-parity vs the oracle -------
    cfg = fault_cfg(faults=(("kill", 1, mid),),
                    checkpoint_every=max(2, ticks // 8))
    eng = ShardedEngine(cfg)
    ckpt_dir = os.path.join(out_dir, "faults_ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)
    t0 = time.perf_counter()
    state, counters = faults_mod.run_with_faults(eng, ticks,
                                                 ckpt_dir=ckpt_dir)
    wall = time.perf_counter() - t0
    merged = {**eng.summary(state), **counters}
    # the oracle: the SAME config run without the host-side kill (a
    # kill spec has no in-tick effect, so the jitted tick is identical)
    oracle_eng = ShardedEngine(cfg)
    o_state = oracle_eng.init_state()
    oracle_eng._build()
    for _ in range(ticks):
        o_state = oracle_eng._jit_tick(o_state)
    oracle = oracle_eng.summary(o_state)
    diff = sorted(k for k in oracle
                  if isinstance(oracle[k], (int, np.integer))
                  and k in merged and int(merged[k]) != int(oracle[k]))
    parity = not diff and counters["recovery_replay_ok"] == 1 \
        and counters["recovery_elog_ok"] == 1
    _, wd = obs_report.watchdog(merged)
    if not parity or wd & obs_report.RECOVERY:
        code |= obs_report.RECOVERY
        for k in diff:
            print(f"[faults] kill PARITY MISMATCH {k}: "
                  f"recovered={merged[k]} oracle={oracle[k]}")
    print(f"[faults] kill parity={'OK' if parity else 'MISMATCH'} "
          f"recovery_lag_ticks={counters['recovery_lag_ticks']} "
          f"ckpt_saves={counters['ckpt_save_cnt']} "
          f"ckpt_restores={counters['ckpt_restore_cnt']} "
          f"commits={int(merged['txn_cnt'])} "
          f"(oracle {int(oracle['txn_cnt'])})")
    doc_scen["kill"] = {
        "kill_tick": mid, "parity": parity,
        "recovery_lag_ticks": counters["recovery_lag_ticks"],
        "fault_replay_ticks": counters["fault_replay_ticks"],
        "ckpt_save_cnt": counters["ckpt_save_cnt"],
        "ckpt_restore_cnt": counters["ckpt_restore_cnt"],
        "commits": int(merged["txn_cnt"]),
        "watchdog": wd,
        "wall_seconds": round(wall, 3),
    }

    # --- STRAGGLE / PARTITION: in-tick gating, delay-never-abort ------
    win = (mid, mid + max(4, ticks // 8))
    for name, spec in (("straggle", ("straggle", 1, *win)),
                       ("partition", ("partition", 0, 1, *win))):
        cfg = fault_cfg(faults=(spec,))
        eng = ShardedEngine(cfg)
        state = eng.run(ticks)
        s = eng.summary(state)
        _, wd = obs_report.watchdog(s)
        ok = int(s["txn_cnt"]) > 0 and int(s["fault_req_blocked_cnt"]) > 0
        if not ok:
            code |= obs_report.RECOVERY
        print(f"[faults] {name} window={list(win)} "
              f"{'OK' if ok else 'DEAD'}: "
              f"commits={int(s['txn_cnt'])} "
              f"req_blocked={int(s['fault_req_blocked_cnt'])} "
              f"fin_deferred={int(s['fault_fin_deferred_cnt'])} "
              f"stall_ticks={int(s['fault_stall_ticks'])}")
        doc_scen[name] = {
            "window": list(win), "commits": int(s["txn_cnt"]),
            "fault_req_blocked_cnt": int(s["fault_req_blocked_cnt"]),
            "fault_fin_deferred_cnt": int(s["fault_fin_deferred_cnt"]),
            "fault_stall_ticks": int(s["fault_stall_ticks"]),
            "watchdog": wd,
        }

    doc = {
        "metric": "fault_recovery",
        "value": doc_scen["kill"]["recovery_lag_ticks"],
        "unit": "recovery_lag_ticks",
        "ticks": ticks,
        "scenarios": doc_scen,
        "note": "kill/straggle/partition smoke on the 2-node sharded "
                "CALVIN cell; kill recovers by deterministic replay "
                "from the last checkpoint and must match the "
                "fault-free oracle bit-for-bit on every integer "
                "counter; value = ticks replayed to recover (the "
                "recovery cost)",
    }
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "faults_smoke.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({k: v for k, v in doc.items() if k != "scenarios"}))
    print(f"[faults] smoke written: {path}")
    if history:
        _append_history(doc, fault_cfg(faults=(("kill", 1, mid),)),
                        out_dir)
    return code


def run_flight(args, out_dir: str = "results", history: bool = True) -> int:
    """--flight: transaction flight recorder sweep (obs/flight.py).

    Runs each CC algorithm's small observed cell with the per-txn
    lifecycle recorder in FULL-SAMPLING mode (every completed txn keeps
    its span), then:

    - checks the exactness contract: summed span phases must reconcile
      against the ``lat_*`` integrals and the event histogram against
      the ``abort_*_cnt`` taxonomy counters (any mismatch fails the run
      — the recorder is an accounting identity, not an estimate);
    - prints the ``[tail]`` attribution (obs/report.py): which phase,
      abort reasons and keys dominate the p99-and-above cohort;
    - writes one run record per algorithm with the flight snapshot under
      the top-level ``"flight"`` key (``python -m deneva_tpu.obs.export
      results/run_*.json`` merges them into one Perfetto timeline);
    - appends a ``flight_tail_attribution`` record to the bench history:
      per-alg p99 latency + phase attribution.  The cells carry no
      ``commits_per_tick``, so obs/regress.py treats them as non-gating
      metadata (its per-alg gate skips cells without that field).

    Exit code: 0 clean, 1 on any reconciliation mismatch."""
    from deneva_tpu.obs import flight as obs_flight
    from deneva_tpu.obs import report as obs_report
    alg_list = (list(_ALGS) if args.algs == "all"
                else [a.strip().upper() for a in args.algs.split(",") if a])
    code = 0
    algs_hist = {}
    rec_paths = []
    for alg in alg_list:
        cfg = Config(cc_alg=alg, flight=True, abort_attribution=True,
                     flight_samples=1 << 15, trace_ticks=args.ticks,
                     **OBS_KW)
        eng = Engine(cfg)
        t0 = time.perf_counter()
        state = eng.run(args.ticks)
        wall = time.perf_counter() - t0
        summary = eng.summary(state, wall)
        snap = obs_flight.snapshot(state)
        bad = obs_flight.reconcile(snap, summary)
        for what, got, want in bad:
            print(f"[flight] {alg} RECONCILE MISMATCH {what}: "
                  f"got={got} want={want}")
            code = 1
        tail = obs_flight.tail_attribution(snap, topk=5)
        print(f"[flight] {alg}: {snap['span_cnt']} spans, "
              f"{snap['ev_cnt']} abort events, "
              f"reconcile {'MISMATCH' if bad else 'exact'}")
        rep = obs_report.build_report(
            summary, timeline=obs_trace.timeline(state), flight=snap)
        print(obs_report.render_text(rep))
        code |= rep["watchdog"]["exit_code"]
        rec = obs_profiler.run_record(
            cfg, summary, timeline=obs_trace.timeline(state),
            extra={"wall_seconds": wall, "flight": snap, "tail": tail})
        rec_paths.append(obs_profiler.write_run_record(
            rec, out_dir=out_dir,
            name=f"run_flight_{alg.lower()}.json"))
        cell = {"p99_ticks": tail.get("p_ticks", 0.0),
                "max_ticks": tail.get("max_ticks", 0),
                "avg_restarts_at_tail": round(
                    tail.get("avg_restarts", 0.0), 2)}
        if tail.get("cohort"):
            cell["dominant_phase"] = tail["dominant_phase"]
            cell["phase_share"] = {k: round(v, 4)
                                   for k, v in tail["phase_share"].items()}
        algs_hist[alg] = cell
    doc = {
        "metric": "flight_tail_attribution",
        "value": algs_hist.get(alg_list[0], {}).get("p99_ticks", 0.0),
        "unit": "p99_latency_ticks",
        "ticks": args.ticks,
        "algs": algs_hist,
        "note": "per-alg p99 tail attribution from full-sampling flight "
                "spans on the small observed cell (OBS_KW); cells carry "
                "no commits_per_tick, so the regress gate skips them",
    }
    print(json.dumps(doc))
    print(f"[flight] run records: {' '.join(rec_paths)}")
    print(f"[flight] merge: python -m deneva_tpu.obs.export "
          f"{' '.join(rec_paths)} -o {out_dir}/flight_trace.json")
    if history:
        _append_history(doc, Config(cc_alg=alg_list[0], flight=True,
                                    abort_attribution=True, **OBS_KW),
                        out_dir)
    return code


def run_depgraph(args, out_dir: str = "results",
                 history: bool = True) -> int:
    """--depgraph: conflict dependency observatory sweep
    (Config.depgraph, obs/depgraph.py).

    Runs each CC algorithm's small observed cell with the device-resident
    wait-for graph on (blocker attribution from every plugin, sampled
    edge ring, exact per-tick chain-depth/convoy planes) plus the flight
    recorder, then:

    - checks the exactness contract (obs_depgraph.reconcile): total wait
      edges == the twopl_wait integral, abort edges partition exactly
      into the abort taxonomy, ring rows per reason == the taxonomy
      counters, and the per-partition plane sums to the edge total — a
      wrapped ring refuses loudly instead of reconciling approximately;
    - runs host-side cycle detection and the commit critical-path
      decomposition (longest blocking chain behind each sampled commit,
      joined against the flight spans) and prints the ``[depgraph]``
      report section;
    - writes one run record per algorithm with the snapshot under the
      top-level ``"depgraph"`` key (``python -m deneva_tpu.obs.export``
      merges the blocker->waiter flow arrows into the span timeline);
    - appends a ``depgraph_chain`` record to the bench history: per-alg
      peak chain depth / mean convoy width / cycle rate.  The per-alg
      ``max_chain_depth`` feeds the self-arming INVERTED obs/regress.py
      ceiling (chains lengthening = regression).

    Exit code: 0 clean, 1 on any reconciliation mismatch OR a
    post-warm recompile (the run is split warmup/steady under the
    obs/xmeter.py sentinel — the plane's unconditional OOB-drop
    scatters must never retrace); watchdog bits ride along, CONVOY=256
    masked out — a convoy on the contended smoke cell is the expected
    finding, not a failure."""
    from deneva_tpu.obs import depgraph as obs_depgraph
    from deneva_tpu.obs import flight as obs_flight
    from deneva_tpu.obs import report as obs_report
    alg_list = (list(_ALGS) if args.algs == "all"
                else [a.strip().upper() for a in args.algs.split(",") if a])
    # the observed cell at zipf 0.9 (not OBS_KW's 0.8): wait chains and
    # convoys are the whole point of this sweep, and the hotter skew is
    # what EXPERIMENTS.md profiles
    dep_kw = {**OBS_KW, "zipf_theta": 0.9}
    code = 0
    algs_hist = {}
    rec_paths = []
    for alg in alg_list:
        cfg = Config(cc_alg=alg, depgraph=True, flight=True,
                     abort_attribution=True, dep_samples=1 << 15,
                     flight_samples=1 << 14, trace_ticks=args.ticks,
                     xmeter=True, **dep_kw)
        eng = Engine(cfg)
        t0 = time.perf_counter()
        # warmup half / steady half under the recompile sentinel: the
        # observatory's scatters are unconditional OOB-drop, so a
        # steady-state recompile means the dead-lane discipline broke
        state = eng.run(args.ticks // 2)
        eng.xmeter.mark_warm()
        state = eng.run(args.ticks - args.ticks // 2, state)
        wall = time.perf_counter() - t0
        for v in eng.xmeter.steady_violations():
            print(f"[depgraph] {alg} RECOMPILE {v['entry']}: "
                  f"{v['signature']}")
            code |= 1
        summary = eng.summary(state, wall)
        snap = obs_depgraph.snapshot(state)
        fsnap = obs_flight.snapshot(state)
        bad = obs_depgraph.reconcile(snap, summary)
        for what, got, want in bad:
            print(f"[depgraph] {alg} RECONCILE MISMATCH {what}: "
                  f"got={got} want={want}")
            code = 1
        cyc = obs_depgraph.cycles(snap)
        ticks = max(int(summary["measured_ticks"]), 1)
        print(f"[depgraph] {alg}: {snap['edge_cnt']} edges sampled "
              f"({summary['dep_wait_edge_cnt']} wait / "
              f"{summary['dep_abort_edge_cnt']} abort exact), "
              f"{len(cyc)} cycle(s), "
              f"reconcile {'MISMATCH' if bad else 'exact'}")
        rep = obs_report.build_report(
            summary, timeline=obs_trace.timeline(state),
            flight=fsnap, depgraph=snap)
        print(obs_report.render_text(rep))
        # CONVOY (256) is the expected finding on this contended cell —
        # the sweep measures it, the regress ceiling gates it
        code |= rep["watchdog"]["exit_code"] & ~obs_report.CONVOY
        rec = obs_profiler.run_record(
            cfg, summary, timeline=obs_trace.timeline(state),
            extra={"wall_seconds": wall, "flight": fsnap,
                   "depgraph": snap})
        rec_paths.append(obs_profiler.write_run_record(
            rec, out_dir=out_dir,
            name=f"run_depgraph_{alg.lower()}.json"))
        algs_hist[alg] = {
            "max_chain_depth": int(summary["dep_peak_depth"]),
            "peak_convoy": int(summary["dep_peak_convoy"]),
            "mean_convoy": round(
                summary["dep_convoy_width_sum"] / ticks, 2),
            "cycle_rate": round(
                len(cyc) / max(snap["edge_cnt"], 1), 5),
            "wait_edges": int(summary["dep_wait_edge_cnt"]),
        }
    doc = {
        "metric": "depgraph_chain",
        "value": float(algs_hist.get(alg_list[0],
                                     {}).get("max_chain_depth", 0)),
        "unit": "peak_wait_chain_depth",
        "ticks": args.ticks,
        "depgraph_chain": algs_hist,
        "note": "per-alg wait-for-graph profile on the small observed "
                "cell at zipf 0.9 (OBS_KW shape, Config.depgraph): "
                "peak chain depth "
                "(pointer-doubled, exact), peak/mean convoy width, "
                "cycle rate over sampled edges; max_chain_depth feeds "
                "the inverted regress ceiling",
    }
    print(json.dumps(doc))
    print(f"[depgraph] run records: {' '.join(rec_paths)}")
    print(f"[depgraph] merge: python -m deneva_tpu.obs.export "
          f"{' '.join(rec_paths)} -o {out_dir}/depgraph_trace.json")
    if history:
        _append_history(doc, Config(cc_alg=alg_list[0], depgraph=True,
                                    flight=True, abort_attribution=True,
                                    **dep_kw),
                        out_dir)
    return code


# the contended adaptive-controller cell (--adaptive): zipf 0.9 on a
# small table at a batch big enough that the acceptance shape (B >= 2048)
# holds on CPU; admit_cap keeps steady-state in-flight pressure high but
# not degenerate.  The HOT variant replays the same shape through the
# reference's SKEW_METHOD==HOT generator (Config.skew_method).
ADAPT_KW = dict(
    batch_size=2048, synth_table_size=1 << 12, req_per_query=4,
    zipf_theta=0.9, tup_read_perc=0.5, query_pool_size=1 << 12,
    warmup_ticks=0, admit_cap=256,
)

#: the static-backoff ladder the adaptive controller must beat: the
#: reference's fixed ABORT_PENALTY at 1/4/16 ticks plus backoff OFF
_ADAPT_STATICS = (("p1", dict(abort_penalty_ticks=1)),
                  ("p4", dict(abort_penalty_ticks=4)),
                  ("p16", dict(abort_penalty_ticks=16)),
                  ("nobackoff", dict(backoff=False)))

#: the two contention shapes per algorithm: broad zipf skew (backoff /
#: width territory) and the reference's HOT generator pointed at a
#: 4-row hot set — the tiny-dominant-key regime the escalation gate
#: exists for (a bucket must carry > 1/ctrl_esc_share of all conflict
#: heat to escalate; ~200 warm keys would never clear that bar)
_ADAPT_CELLS = (("zipf0.9", {}),
                ("hot", dict(skew_method="hot", access_perc=0.95,
                             data_perc=0.001)))

_ADAPT_ALGS = ("NO_WAIT", "WAIT_DIE", "OCC", "MAAT")


def run_adaptive(args, out_dir: str = "results",
                 history: bool = True) -> int:
    """--adaptive: the contended controller sweep (Config.adaptive,
    deneva_tpu/ctrl/).

    Two contention shapes — zipf 0.9 and the reference's HOT skew
    (ACCESS_PERC=0.9 of traffic to DATA_PERC=0.05 of data) — each run
    under NO_WAIT / WAIT_DIE / OCC / MAAT with the static-backoff
    ladder (ABORT_PENALTY 1/4/16 ticks + backoff off) and once with the
    adaptive controller on.  Every variant reports the chip-noise-immune
    commits/tick; ``adaptive_vs_static`` is the per-cell ratio of the
    adaptive number to the BEST static — the controller must not just
    beat the default, it must beat the best hand-tuned point in the
    ladder.  The adaptive cells also report what the controller did
    (escalations, gate stalls, width steps, converged bases).

    Writes ``<out-dir>/adaptive_sweep.json`` and appends an
    ``adaptive_contention`` record whose ``adaptive_vs_static`` ratios
    feed the self-arming obs/regress.py floor.

    Exit code 0 when, on the zipf 0.9 cell, adaptive beats every static
    for NO_WAIT AND for at least one of OCC/MAAT (the ISSUE acceptance
    bar); 1 otherwise."""
    alg_list = (list(_ADAPT_ALGS) if args.algs == "all"
                else [a.strip().upper() for a in args.algs.split(",") if a])
    sweep, ratios = {}, {}
    for cell_name, cell_kw in _ADAPT_CELLS:
        for alg in alg_list:
            variants = {}
            for var_name, var_kw in _ADAPT_STATICS:
                cfg = Config(cc_alg=alg, abort_attribution=True,
                             **ADAPT_KW, **cell_kw, **var_kw)
                _, cpt, summ = run_cell(cfg, n_ticks=args.ticks, windows=3)
                variants[var_name] = {
                    "commits_per_tick": round(cpt, 2),
                    **_abort_fields(summ)}
            cfg = Config(cc_alg=alg, adaptive=True, abort_attribution=True,
                         heatmap_bins=64, **ADAPT_KW, **cell_kw)
            _, cpt, summ = run_cell(cfg, n_ticks=args.ticks, windows=3)
            variants["adaptive"] = {
                "commits_per_tick": round(cpt, 2),
                **_abort_fields(summ),
                "ctrl": {
                    "escalations": int(summ.get("ctrl_escalate_cnt", 0)),
                    "deescalations": int(summ.get("ctrl_deescalate_cnt", 0)),
                    "gate_blocks": int(summ.get("ctrl_esc_block_cnt", 0)),
                    "width_steps": int(summ.get("ctrl_width_step_cnt", 0)),
                    "width_idx": int(summ.get("ctrl_width_idx", 0)),
                }}
            best_static = max(v["commits_per_tick"]
                              for k, v in variants.items()
                              if k != "adaptive")
            ratio = variants["adaptive"]["commits_per_tick"] \
                / max(best_static, 1e-9)
            ratios[f"{alg}@{cell_name}"] = round(ratio, 4)
            sweep[f"{alg}@{cell_name}"] = variants
            cells = " ".join(f"{k}={v['commits_per_tick']}"
                             for k, v in variants.items())
            print(f"[adaptive] {alg}@{cell_name}: ratio {ratio:.3f} "
                  f"vs best static {best_static} ({cells})")
    # acceptance bar: on the zipf 0.9 cell adaptive must beat every
    # static for NO_WAIT and for at least one of OCC / MAAT
    nw = ratios.get("NO_WAIT@zipf0.9", 0.0)
    vmax = max(ratios.get("OCC@zipf0.9", 0.0),
               ratios.get("MAAT@zipf0.9", 0.0))
    code = 0 if (nw > 1.0 and vmax > 1.0) else 1
    doc = {
        "metric": "adaptive_contention",
        "value": nw,
        "unit": "adaptive_over_best_static_cpt",
        "ticks": args.ticks,
        "adaptive_vs_static": ratios,
        "sweep": sweep,
        "note": "per-cell ratio of adaptive commits/tick to the BEST "
                "static-backoff variant (ABORT_PENALTY 1/4/16 + "
                "backoff off) on the contended ADAPT_KW shape; "
                "value = NO_WAIT@zipf0.9; exit 0 iff NO_WAIT and one "
                "of OCC/MAAT beat every static on the zipf 0.9 cell",
    }
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "adaptive_sweep.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({k: v for k, v in doc.items() if k != "sweep"}))
    print(f"[adaptive] sweep written: {path}")
    if history:
        _append_history(doc, Config(cc_alg=alg_list[0], adaptive=True,
                                    abort_attribution=True,
                                    heatmap_bins=64, **ADAPT_KW),
                        out_dir)
    return code


def _git_commit() -> str | None:
    try:
        out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             capture_output=True, text=True, timeout=10)
        return out.stdout.strip() or None if out.returncode == 0 else None
    except OSError:  # pragma: no cover - no git binary
        return None


def _append_history(doc: dict, cfg: Config, out_dir: str = "results") -> str:
    """Append this run's headline cells to ``<out-dir>/bench_history.jsonl``
    — the append-only trajectory the regression gate
    (``python -m deneva_tpu.obs.regress``) compares new snapshots against.
    One line per run: unix time + git commit + config fingerprint for
    provenance, the headline metric/value, and the per-algorithm cells
    (regress gates on their chip-noise-immune ``commits_per_tick``)."""
    rec = {
        "unix_time": int(time.time()),
        "commit": _git_commit(),
        "config_fingerprint": obs_profiler.config_fingerprint(cfg),
        # measurement platform: obs/regress.py gates same-platform
        # trajectories only (a CPU smoke point must never lower — or
        # fail — the TPU trajectory's median, the PR 7 pollution bug)
        "platform": jax.default_backend(),
        "metric": doc["metric"],
        "value": doc["value"],
    }
    if "commits_per_tick" in doc:
        rec["commits_per_tick"] = doc["commits_per_tick"]
    if "algs" in doc:
        rec["algs"] = doc["algs"]
    # open-system sweep provenance (--offered-load): the rate grid and
    # per-algorithm knee ride along; regress keys the trajectory on the
    # distinct "offered_load_knee" metric + "<ALG>@knee" cells, so the
    # headline tput trajectories are untouched
    # --scaling-grid cells ride the same way: the per-cell efficiency
    # dict keys a distinct "scaling_grid" trajectory in obs/regress.py
    # --adaptive records ride the same way: the per-cell ratio dict keys
    # a distinct "adaptive_contention" trajectory with a self-arming
    # floor in obs/regress.py
    # --serve records ride the same way: the per-family p99 dict keys a
    # distinct "serve_slo" trajectory with a self-arming CEILING (lower
    # is better) in obs/regress.py
    # --depgraph records ride the same way: the per-alg chain cells key
    # a distinct "depgraph_chain" trajectory with a self-arming inverted
    # max-chain-depth CEILING in obs/regress.py
    for k in ("offered_load", "knee", "nodes", "batch_shapes",
              "scaling_grid", "adaptive_vs_static", "slo_p99",
              "depgraph_chain"):
        if k in doc:
            rec[k] = doc[k]
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "bench_history.jsonl")
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return path


def run_xmeter(args) -> int:
    """--xmeter: compile & memory observatory smoke on the small observed
    cell.  Warmup window, then mark_warm + blocked steady window — the
    sentinel must count ZERO steady-state compiles; the ledger's carry
    total must reconcile against the compiled tick's
    ``memory_analysis()`` argument bytes within 1%.  Exit bitmask:
    1 = post-warmup recompile, 2 = ledger reconcile failure."""
    from deneva_tpu.obs import xmeter as obs_xmeter
    cfg = Config(cc_alg=args.cc_alg, xmeter=True, **OBS_KW)
    eng = Engine(cfg)
    t0 = time.perf_counter()
    state = eng.run(args.ticks)                # warmup: compiles land here
    eng.xmeter.mark_warm()
    eng.xmeter.block = True                    # wall-true per-call ms
    state = eng.run(args.ticks, state)         # metered steady window
    wall = time.perf_counter() - t0
    summary = eng.summary(state, wall)
    print(eng.summary_line(state, wall))

    code = 0
    viol = eng.xmeter.steady_violations()
    if viol:
        for v in viol:
            print(f"[xmeter] RECOMPILE {v['entry']}: {v['signature']}")
        code = 1
    else:
        cnt, ms = eng.xmeter.compile_totals()
        print(f"[xmeter] steady state held: {cnt} warmup compiles "
              f"({ms:.0f} ms), zero after mark_warm")

    rows = eng.ledger(state)
    analysis = eng.xmeter.analyze("tick")
    rec = obs_xmeter.reconcile_ledger(rows, analysis)
    print(f"[xmeter] ledger reconcile: carry={rec['carry_bytes']} "
          f"executable argument={rec['argument_bytes']} "
          f"ratio={rec['ratio']:.4f} {'OK' if rec['ok'] else 'MISMATCH'}")
    if not rec["ok"]:
        code |= 2
    print(obs_xmeter.ledger_text(rows))
    roof = eng.xmeter.roofline()
    if roof:
        print(obs_xmeter.roofline_markdown(roof))

    record = obs_profiler.run_record(
        cfg, summary, extra={"wall_seconds": wall,
                             "xmeter": eng.xmeter.snapshot()})
    rec_path = obs_profiler.write_run_record(record, out_dir=args.out_dir)
    print(f"[obs] run record: {rec_path}")
    return code


def run_single_alg(alg: str, out_dir: str = "results",
                   history: bool = True, fused: bool = False):
    """--alg: the headline YCSB cell (faithful, acquire_window=1) under one
    CC plugin, same one-line JSON shape as the full sweep.  Runs with
    abort attribution on so the cell reports WHY it aborted."""
    per_chip_star = NORTH_STAR_CLUSTER / NORTH_STAR_CHIPS
    cfg = Config(cc_alg=alg, acquire_window=1, fused_arbitrate=fused,
                 abort_attribution=True, **YCSB_KW)
    tput, cpt, summ = run_cell(cfg)
    doc = {
        "metric": f"ycsb_{alg.lower()}_zipf0.6_tput_faithful",
        "value": round(float(tput), 1),
        "unit": "committed_txns_per_sec",
        "vs_baseline": round(float(tput) / per_chip_star, 4),
        "commits_per_tick": round(float(cpt), 1),
        **_abort_fields(summ),
        "note": "single-algorithm headline cell (--alg); acquire_window 1; "
                "vs_baseline = value / (1M-cluster north star / 8 chips)",
    }
    print(json.dumps(doc))
    if history:
        _append_history(doc, cfg, out_dir)


def main(out_dir: str = "results", history: bool = True,
         fused: bool = False):
    # --fused flips Config.fused_arbitrate on EVERY cell; the config
    # fingerprint (obs/profiler.py, dataclasses.asdict) keys the history
    # line, so fused and lax trajectories never collate into one series
    per_chip_star = NORTH_STAR_CLUSTER / NORTH_STAR_CHIPS
    faithful, _, _ = run_cell(Config(cc_alg="NO_WAIT", acquire_window=1,
                                     fused_arbitrate=fused, **YCSB_KW))
    greedy, _, _ = run_cell(Config(cc_alg="NO_WAIT", acquire_window=10,
                                   fused_arbitrate=fused, **YCSB_KW))

    # every algorithm's faithful cell + TPC-C, smaller measurement (the
    # compile dominates; commits/tick is the stable number).  These cells
    # run attributed so the sweep reports each algorithm's abort rate and
    # top-3 reasons; the two headline cells above stay unattributed (the
    # metric of record is measured on the untouched default tick).
    algs = {}
    for alg in ("NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT",
                "CALVIN"):
        t, c, summ = run_cell(Config(cc_alg=alg, acquire_window=1,
                                     fused_arbitrate=fused,
                                     abort_attribution=True, **YCSB_KW),
                              n_ticks=200, windows=3)
        algs[alg] = {"tput": round(t, 1), "commits_per_tick": round(c, 1),
                     **_abort_fields(summ)}
    t, c, summ = run_cell(Config(abort_attribution=True,
                                 fused_arbitrate=fused, **TPCC_KW),
                          n_ticks=100, windows=3)
    algs["TPCC_MVCC_64wh"] = {"tput": round(t, 1),
                              "commits_per_tick": round(c, 1),
                              **_abort_fields(summ)}

    doc = {
        "metric": "ycsb_nowait_zipf0.6_tput_faithful",
        "value": round(float(faithful), 1),
        "unit": "committed_txns_per_sec",
        "vs_baseline": round(float(faithful) / per_chip_star, 4),
        "greedy_tput": round(float(greedy), 1),
        "algs": algs,
        "note": "value=acquire_window 1 (reference-faithful); greedy_tput="
                "window 10; vs_baseline = faithful / (1M-cluster north star"
                " / 8 chips); algs[*].commits_per_tick is chip-noise-immune",
    }
    print(json.dumps(doc))
    if history:
        _append_history(doc, Config(cc_alg="NO_WAIT", acquire_window=1,
                                    fused_arbitrate=fused, **YCSB_KW),
                        out_dir)


def _cli():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--trace", action="store_true",
                   help="record the per-tick timeline and export a "
                        "Perfetto-loadable Chrome trace JSON")
    p.add_argument("--trace-ticks", type=int, default=0,
                   help="trace ring depth (default: --ticks, so every "
                        "tick gets its own row)")
    p.add_argument("--profile", action="store_true",
                   help="host-side phase profiling (compile vs dispatch "
                        "vs execute + jit recompile count)")
    p.add_argument("--prog-interval", type=int, default=0,
                   help="emit a [prog] heartbeat line every N ticks")
    p.add_argument("--ticks", type=int, default=200,
                   help="ticks for the observed run (default 200)")
    p.add_argument("--cc-alg", default="NO_WAIT",
                   help="CC algorithm for the observed run")
    p.add_argument("--alg", default=None,
                   choices=("NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC",
                            "OCC", "MAAT", "CALVIN"),
                   help="run ONLY this algorithm's headline YCSB cell "
                        "(faithful, acquire_window=1) and print the same "
                        "one-line JSON")
    p.add_argument("--offered-load", action="store_true",
                   help="open-system saturation sweep: walk a Poisson "
                        "arrival-rate grid per CC algorithm to the "
                        "saturation knee (served/offered >= 0.95) and "
                        "write offered_load_sweep.json")
    p.add_argument("--rates", default="2,4,8,16,32,64",
                   help="comma-separated arrival-rate grid for "
                        "--offered-load (arrivals/tick)")
    p.add_argument("--algs", default="all",
                   help="comma-separated CC algorithms for "
                        "--offered-load (default: all seven)")
    p.add_argument("--serve", action="store_true",
                   help="long-running serve loop: flash-crowd rate step "
                        "+ mid-run hot-set shift on the open-system "
                        "cell with Config.slo on, the obs/telemetry.py "
                        "exporter streaming OpenMetrics + JSONL every "
                        "slo_export_interval ticks and the xmeter "
                        "sentinel proving zero steady-state recompiles; "
                        "writes serve_slo.json (exit bitmask 1=recompile "
                        "2=hist reconcile | watchdog)")
    p.add_argument("--serve-ticks", type=int, default=360,
                   help="total serve-loop ticks (burst at 1/4, drain "
                        "at 1/2)")
    p.add_argument("--serve-rate", type=float, default=4.0,
                   help="baseline arrival rate for --serve "
                        "(arrivals/tick)")
    p.add_argument("--serve-burst", type=float, default=48.0,
                   help="flash-crowd burst arrival rate for --serve")
    p.add_argument("--scaling-grid", action="store_true",
                   help="cluster scaling surface: virtual-node grid x "
                        "two fit_batch-sized per-node batch shapes on "
                        "the sharded engine with Config.mesh on; writes "
                        "scaling_grid.json with speedup/efficiency/"
                        "imbalance/remote-ratio per cell (exit 1 on a "
                        "mesh reconcile mismatch or zero-commit cell)")
    p.add_argument("--grid-nodes", default="1,2,4,8",
                   help="comma-separated node counts for --scaling-grid "
                        "(clamped to the device count)")
    p.add_argument("--grid-remote-cache", action="store_true",
                   help="run the --scaling-grid cells with "
                        "Config.remote_cache (remote-grant stickiness) "
                        "on every multi-node cell; cells key their own "
                        "'+rc' regression trajectory")
    p.add_argument("--grid-split", action="store_true",
                   help="run the --scaling-grid cells with "
                        "Config.exchange_split (capacity-bounded "
                        "epoch-split exchange) on every multi-node "
                        "cell; cells key their own '+split' trajectory")
    p.add_argument("--pipeline", action="store_true",
                   help="run the --scaling-grid cells with "
                        "Config.pipeline_exchange (software-pipelined "
                        "split exchange, implies exchange_split) on "
                        "every multi-node cell; cells key their own "
                        "'+pipe' trajectory and carry "
                        "pipeline_overlap_frac (use --algs CALVIN — "
                        "the flag is inert on abort-capable plugins)")
    p.add_argument("--grid-budget-mb", type=float, default=256.0,
                   help="per-node HBM budget feeding the fit_batch "
                        "model that sizes the large --scaling-grid "
                        "batch shape")
    p.add_argument("--grid-max-batch", type=int, default=256,
                   help="cap on the fit_batch-derived per-node batch "
                        "shape (keeps the CPU smoke fast; raise on "
                        "real chips)")
    p.add_argument("--adaptive", action="store_true",
                   help="adaptive contention controller sweep: zipf 0.9 "
                        "and HOT-skew cells x {static backoff ladder, "
                        "Config.adaptive} for NO_WAIT/WAIT_DIE/OCC/MAAT; "
                        "writes adaptive_sweep.json and the "
                        "adaptive_vs_static ratios the regress gate "
                        "floors (exit 1 unless adaptive beats every "
                        "static for NO_WAIT + one of OCC/MAAT on the "
                        "zipf 0.9 cell)")
    p.add_argument("--faults", action="store_true",
                   help="fault-plane smoke: kill/straggle/partition "
                        "scenarios on the 2-node sharded CALVIN cell; "
                        "the kill recovers by deterministic replay from "
                        "the last checkpoint and must match the "
                        "fault-free oracle bit-for-bit (exit carries "
                        "the RECOVERY watchdog bit on any parity "
                        "failure); writes faults_smoke.json")
    p.add_argument("--flight", action="store_true",
                   help="transaction flight recorder sweep: per-alg "
                        "full-sampling lifecycle spans, exact phase/"
                        "abort reconciliation, [tail] p99 attribution, "
                        "per-alg run records for obs.export (exit 1 on "
                        "any reconcile mismatch)")
    p.add_argument("--depgraph", action="store_true",
                   help="conflict dependency observatory sweep: per-alg "
                        "device-resident wait-for graph with exact edge "
                        "reconciliation, cycle detection, commit "
                        "critical paths and the [depgraph] report; "
                        "appends per-alg chain-depth cells to the "
                        "history for the inverted regress ceiling "
                        "(exit 1 on any reconcile mismatch)")
    p.add_argument("--xmeter", action="store_true",
                   help="compile & memory observatory smoke: recompile "
                        "sentinel + ledger reconcile + roofline "
                        "(exit 1/2 on sentinel/reconcile failure)")
    p.add_argument("--fused", action="store_true",
                   help="run the headline cells with the fused VMEM "
                        "sort+scan arbitration kernel "
                        "(Config.fused_arbitrate); the config "
                        "fingerprint keys the history line, so fused "
                        "runs form their own regression trajectory")
    p.add_argument("--windows", action="store_true",
                   help="causal-diagnosis window plane (Config.windows) "
                        "on the observed run: latch the full counter "
                        "vocabulary every --window-ticks ticks, prove "
                        "the sum-of-deltas identity, and land the ring "
                        "in the run record for obs/diff.py; with --diff "
                        "and ONE record, diff two phases WITHIN it")
    p.add_argument("--window-ticks", type=int, default=8,
                   help="latch cadence in ticks (default %(default)s; "
                        "the ring is sized so the run never wraps)")
    p.add_argument("--diff", nargs="+", metavar="RECORD",
                   help="differential run comparator (obs/diff.py): two "
                        "run-record paths A B — or one with --windows — "
                        "rank the causes of the change and map each to "
                        "its config lever; no engine run happens")
    p.add_argument("--no-history", action="store_true",
                   help="skip the bench_history.jsonl trajectory append "
                        "(headline runs only; obs runs never append)")
    p.add_argument("--out-dir", default="results",
                   help="directory for trace JSON + run record + "
                        "bench_history.jsonl")
    return p.parse_args()


if __name__ == "__main__":
    _args = _cli()
    if _args.diff:
        from deneva_tpu.obs import diff as obs_diff
        _argv = list(_args.diff)
        if _args.windows:
            _argv.append("--windows")
        raise SystemExit(obs_diff.main(_argv))
    if _args.scaling_grid:
        raise SystemExit(run_scaling_grid(_args, out_dir=_args.out_dir,
                                          history=not _args.no_history))
    if _args.offered_load:
        raise SystemExit(run_offered_load(_args, out_dir=_args.out_dir,
                                          history=not _args.no_history))
    if _args.serve:
        raise SystemExit(run_serve(_args, out_dir=_args.out_dir,
                                   history=not _args.no_history))
    if _args.adaptive:
        raise SystemExit(run_adaptive(_args, out_dir=_args.out_dir,
                                      history=not _args.no_history))
    if _args.faults:
        raise SystemExit(run_faults(_args, out_dir=_args.out_dir,
                                    history=not _args.no_history))
    if _args.flight:
        raise SystemExit(run_flight(_args, out_dir=_args.out_dir,
                                    history=not _args.no_history))
    if _args.depgraph:
        raise SystemExit(run_depgraph(_args, out_dir=_args.out_dir,
                                      history=not _args.no_history))
    if _args.xmeter:
        raise SystemExit(run_xmeter(_args))
    if _args.trace or _args.profile or _args.prog_interval \
            or _args.windows:
        raise SystemExit(run_obs(_args))
    if _args.alg:
        run_single_alg(_args.alg, out_dir=_args.out_dir,
                       history=not _args.no_history, fused=_args.fused)
    else:
        main(out_dir=_args.out_dir, history=not _args.no_history,
             fused=_args.fused)
