"""Headline benchmark: simulated committed YCSB txns/sec on one chip.

Mirrors the reference's metric of record — committed txns / measured second
(``tput=`` in statistics/stats.cpp:437-447) — on the BASELINE.json config 2
shape: YCSB, zipf 0.6 contention, 50/50 read-write, 16M rows, 10 req/txn.

The headline ``value`` is the NO_WAIT faithful cell (acquire_window=1, the
reference's sequential state machine; PROFILE.md has the cost model and
tuning).  ``greedy_tput`` is window-10 batch acquisition — the engine's
native batched operating point.  ``algs`` carries EVERY CC algorithm's
faithful cell plus a TPC-C cell (round-5 contract: the sort-bound
algorithms MAAT/MVCC and TPC-C get a driver-visible, regression-guarded
number), each with BOTH wall tput and commits/tick — the latter is immune
to the tunneled chip's +-10-30% session drift, so cross-round comparisons
should prefer it.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.
vs_baseline scales the faithful number against the north star's per-chip
share: BASELINE.md targets >=1M txns/s on a v5e-8 (8 chips), i.e. 125k/s
per chip; this bench runs a single chip.
"""

import json
import time

import jax
import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine

NORTH_STAR_CLUSTER = 1_000_000   # committed txns/s on a v5e-8 (BASELINE.md)
NORTH_STAR_CHIPS = 8

YCSB_KW = dict(
    batch_size=8192,
    synth_table_size=1 << 24,   # 16M rows (paper-scale, BASELINE.md grid)
    req_per_query=10,
    zipf_theta=0.6,
    tup_read_perc=0.5,
    query_pool_size=1 << 16,
    warmup_ticks=0,
    backoff=True,
    # tuned concurrency throttle for BOTH cells: in the greedy cell it
    # holds steady-state in-flight txns low enough that the abort rate
    # stays ~0.16 (uncapped admission drives contention up and measures
    # ~280k/s vs ~430k/s capped; sweep in PROFILE.md)
    admit_cap=1024,
)

# the PROFILE.md TPC-C cell: 64 warehouses, Payment/NewOrder mix, MVCC
TPCC_KW = dict(
    workload="TPCC", cc_alg="MVCC", batch_size=8192, num_wh=64,
    cust_per_dist=2000, max_items=1024, query_pool_size=1 << 16,
    warmup_ticks=0, admit_cap=1024,
)


def run_cell(cfg: Config, n_ticks: int = 300, windows: int = 7):
    eng = Engine(cfg)
    # two warmup rounds: the first post-compile dispatch runs ~5x slow
    # (device power/prefetch state), and the second reaches steady-state
    # occupancy; SAME trip count as the timed run (fori_loop trip count is
    # static — a different count would recompile inside the timed window)
    state = eng.run_compiled(n_ticks)
    state = eng.run_compiled(n_ticks, state)
    jax.block_until_ready(state.stats["txn_cnt"])

    # median of `windows` measured windows: the tunneled chip shows
    # ~+-8-15% window-to-window variance under host load, and each
    # 300-tick window costs well under a second
    tputs, cpt = [], []
    for _ in range(windows):
        committed_before = int(np.asarray(state.stats["txn_cnt"]))
        t0 = time.perf_counter()
        state = eng.run_compiled(n_ticks, state)
        jax.block_until_ready(state.stats["txn_cnt"])
        dt = time.perf_counter() - t0
        committed = int(np.asarray(state.stats["txn_cnt"])) - committed_before
        tputs.append(committed / dt)
        cpt.append(committed / n_ticks)
    return float(np.median(tputs)), float(np.median(cpt))


def main():
    per_chip_star = NORTH_STAR_CLUSTER / NORTH_STAR_CHIPS
    faithful, _ = run_cell(Config(cc_alg="NO_WAIT", acquire_window=1,
                                  **YCSB_KW))
    greedy, _ = run_cell(Config(cc_alg="NO_WAIT", acquire_window=10,
                                **YCSB_KW))

    # every algorithm's faithful cell + TPC-C, smaller measurement (the
    # compile dominates; commits/tick is the stable number)
    algs = {}
    for alg in ("NO_WAIT", "WAIT_DIE", "TIMESTAMP", "MVCC", "OCC", "MAAT",
                "CALVIN"):
        t, c = run_cell(Config(cc_alg=alg, acquire_window=1, **YCSB_KW),
                        n_ticks=200, windows=3)
        algs[alg] = {"tput": round(t, 1), "commits_per_tick": round(c, 1)}
    t, c = run_cell(Config(**TPCC_KW), n_ticks=100, windows=3)
    algs["TPCC_MVCC_64wh"] = {"tput": round(t, 1),
                              "commits_per_tick": round(c, 1)}

    print(json.dumps({
        "metric": "ycsb_nowait_zipf0.6_tput_faithful",
        "value": round(float(faithful), 1),
        "unit": "committed_txns_per_sec",
        "vs_baseline": round(float(faithful) / per_chip_star, 4),
        "greedy_tput": round(float(greedy), 1),
        "algs": algs,
        "note": "value=acquire_window 1 (reference-faithful); greedy_tput="
                "window 10; vs_baseline = faithful / (1M-cluster north star"
                " / 8 chips); algs[*].commits_per_tick is chip-noise-immune",
    }))


if __name__ == "__main__":
    main()
