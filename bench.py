"""Headline benchmark: simulated committed YCSB txns/sec on one chip.

Mirrors the reference's metric of record — committed txns / measured second
(``tput=`` in statistics/stats.cpp:437-447) — on the BASELINE.json config 2
shape: YCSB, zipf 0.6 contention, 50/50 read-write, 16M rows, 10 req/txn.

Two cells are measured (PROFILE.md has the cost model and tuning):
- **faithful**: acquire_window=1, the reference's sequential state machine
  (one access arbitrated per txn per tick) — the reference-comparable
  number and the headline ``value``;
- **greedy**: acquire_window=10 batch acquisition — the engine's native
  batched operating point (abort-rate-shifting vs the reference;
  Config.acquire_window docstring).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.
vs_baseline scales the faithful number against the north star's per-chip
share: BASELINE.md targets >=1M txns/s on a v5e-8 (8 chips), i.e. 125k/s
per chip; this bench runs a single chip.
"""

import json
import time

import jax
import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine

NORTH_STAR_CLUSTER = 1_000_000   # committed txns/s on a v5e-8 (BASELINE.md)
NORTH_STAR_CHIPS = 8


def run_cell(acquire_window: int, batch_size: int, admit_cap: int,
             n_ticks: int = 300, with_summary: bool = False):
    cfg = Config(
        cc_alg="NO_WAIT",
        batch_size=batch_size,
        synth_table_size=1 << 24,   # 16M rows (paper-scale, BASELINE.md grid)
        req_per_query=10,
        zipf_theta=0.6,
        tup_read_perc=0.5,
        query_pool_size=1 << 16,
        warmup_ticks=0,
        backoff=True,
        acquire_window=acquire_window,
        admit_cap=admit_cap,
    )
    eng = Engine(cfg)
    # two warmup rounds: the first post-compile dispatch runs ~5x slow
    # (device power/prefetch state), and the second reaches steady-state
    # occupancy; SAME trip count as the timed run (fori_loop trip count is
    # static — a different count would recompile inside the timed window)
    state = eng.run_compiled(n_ticks)
    state = eng.run_compiled(n_ticks, state)
    jax.block_until_ready(state.stats["txn_cnt"])

    # median of 7 measured windows: the tunneled chip shows ~+-8-15%
    # window-to-window variance under host load, and each 300-tick window
    # costs well under a second — more windows is the cheap stabilizer
    tputs = []
    for _ in range(7):
        committed_before = int(np.asarray(state.stats["txn_cnt"]))
        t0 = time.perf_counter()
        state = eng.run_compiled(n_ticks, state)
        jax.block_until_ready(state.stats["txn_cnt"])
        dt = time.perf_counter() - t0
        committed = int(np.asarray(state.stats["txn_cnt"])) - committed_before
        tputs.append(committed / dt)
    tput = float(np.median(tputs))
    if with_summary:
        return tput, eng.summary(state)
    return tput


def main():
    # admit_cap=1024 is a tuned concurrency throttle for BOTH cells: in the
    # greedy cell it holds steady-state in-flight txns low enough that the
    # abort rate stays ~0.16 (uncapped admission drives contention up and
    # measures ~280k/s vs ~430k/s capped; sweep in PROFILE.md)
    faithful = run_cell(acquire_window=1, batch_size=8192, admit_cap=1024)
    greedy = run_cell(acquire_window=10, batch_size=8192, admit_cap=1024)
    per_chip_star = NORTH_STAR_CLUSTER / NORTH_STAR_CHIPS
    print(json.dumps({
        "metric": "ycsb_nowait_zipf0.6_tput_faithful",
        "value": round(float(faithful), 1),
        "unit": "committed_txns_per_sec",
        "vs_baseline": round(float(faithful) / per_chip_star, 4),
        "greedy_tput": round(float(greedy), 1),
        "note": "value=acquire_window 1 (reference-faithful); greedy_tput="
                "window 10; vs_baseline = faithful / (1M-cluster north star"
                " / 8 chips)",
    }))


if __name__ == "__main__":
    main()
