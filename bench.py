"""Headline benchmark: simulated committed YCSB txns/sec on one chip.

Mirrors the reference's metric of record — committed txns / measured second
(``tput=`` in statistics/stats.cpp:437-447) — for the BASELINE.json config 2
shape: YCSB, zipf contention, 50/50 read-write.  Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is value / 1e6 — the fraction of the 1M txns/s north star
(BASELINE.md: ">=1M simulated concurrent YCSB txns/s on a v5e-8"; we bench a
single chip here).
"""

import json
import time

import jax
import numpy as np

from deneva_tpu.config import Config
from deneva_tpu.engine.scheduler import Engine


def main():
    cfg = Config(
        cc_alg="NO_WAIT",
        batch_size=16384,
        synth_table_size=1 << 24,   # 16M rows (paper-scale, BASELINE.md grid)
        req_per_query=10,
        zipf_theta=0.6,
        tup_read_perc=0.5,
        query_pool_size=1 << 16,
        warmup_ticks=0,
        backoff=True,
        acquire_window=10,  # greedy batch acquisition (see Config docstring)
    )
    eng = Engine(cfg)
    state = eng.init_state()

    # compile + warm up to steady state; SAME trip count as the timed run —
    # run_compiled's fori_loop treats n_ticks as static, so a different count
    # would put a recompile inside the timed window
    n_ticks = 300
    state = eng.run_compiled(n_ticks, state)
    committed_before = int(np.asarray(state.stats["txn_cnt"]))

    t0 = time.perf_counter()
    state = eng.run_compiled(n_ticks, state)
    jax.block_until_ready(state.stats["txn_cnt"])
    dt = time.perf_counter() - t0

    s = eng.summary(state)
    tput = (s["txn_cnt"] - committed_before) / dt
    print(json.dumps({
        "metric": "ycsb_nowait_zipf0.6_tput",
        "value": round(float(tput), 1),
        "unit": "committed_txns_per_sec",
        "vs_baseline": round(float(tput) / 1e6, 4),
    }))


if __name__ == "__main__":
    main()
