"""Adaptive contention controller — on-device, jit-safe (Config.adaptive).

Three coupled policies, each fed by an observability plane the engine
already carries, each a pre-traced select/`lax.switch` path (the steady
state NEVER recompiles as the controller adapts — the xmeter sentinel
proves it in scripts/check.sh):

(a) **abort-reason-driven backoff** (`penalty`): the single exponential
    schedule (scheduler `_penalty`) becomes a per-reason base tuned by an
    EWMA of that reason's abort rate (`arr_ctrl_reason_ewma`, fed by the
    note_aborts taxonomy sites).  Lock-family kills (NO_WAIT conflict,
    WAIT_DIE wound, T/O too-old) start cheap but COMPOUND exponentially
    in restarts — a lock kill costs almost nothing, so the right response
    to sustained pressure is draining the over-saturated batch (the
    static sweep's p16 regime).  Backoff thrash (died the tick it woke)
    rides the compounding schedule under lock/T-O plugins — it is the
    direct evidence the previous penalty was too short — and stays flat
    under validation plugins (see _class_tables).  Validation-family
    aborts (OCC/MAAT) are the opposite: the txn burned a full execution
    already,
    compounding its penalty starves throughput (and collapses MAAT
    timestamp ranges), so their penalty stays FLAT and small, with a
    per-lane deterministic jitter that desynchronizes retry storms (a
    batch of vaborted txns with equal penalties re-collides wholesale
    every period; jitter spreads them).

(b) **hot-key escalation** (`esc_stall` + the ring maintained in
    `update`): heatmap buckets whose conflict-rate EWMA crosses
    ``ctrl_esc_up`` AND carries a dominant share (> 1/``ctrl_esc_share``)
    of the whole heatmap's heat promote their representative key into a
    small serialization ring; while a key is escalated, at most ONE
    writer per tick OPENS on it (oldest timestamp wins; losing lanes at
    cursor 0 — holding no locks yet, so the stall has no side effects —
    simply make no request this tick; mid-txn lanes are never stalled:
    freezing their held locks would wedge the rest of the table).
    Aborting and restarting a doomed writer costs a full backoff +
    re-execution; stalling it costs one tick.  Gate stalls
    feed back into the bucket's conflict plane (`note_stall_heat`), so a
    productively-gated key stays escalated instead of thrashing the
    hysteresis; a key too hot for one writer/tick to drain crosses the
    ``ctrl_esc_up * ctrl_esc_overload`` bound and is released (or never
    taken on) — broad zipf-style contention is backoff's job, not the
    gate's.  De-escalation below ``ctrl_esc_down`` (hysteresis) makes
    cold keys free again.  Only plugins that declare ``esc_gate_ok``
    (2PL family + TIMESTAMP) take the gate: their held-lock/prewrite
    state makes "stall without deciding" safe and meaningful.

    Progress: a lane stalls only while a strictly-older live txn targets
    the same escalated key this tick.  Following that "older" edge
    strictly decreases ts, so every stall chain ends at a txn that takes
    the normal arbitration path — the gate can delay, never deadlock.

(c) **occupancy-driven width selection** (`width_ladder` + the gear
    chosen in `update`): a slot-occupancy EWMA (in-flight lanes,
    backoff sleepers included — a batch full of them IS the contended
    regime) picks one gear from a
    small static ladder of pre-traced ``plugin.access`` variants —
    wider ``compact_lanes`` (spill retries hurt exactly when occupancy
    is high) and ``sub_ticks`` engagement (within-tick lock handoff
    pays off under contention) — via ``lax.switch`` over branches XLA
    compiled once.  Single-shard engine only: the sharded owner tick
    pins its virtual-entry geometry per node.

State lives in the donated stats carry: ``arr_ctrl_*`` planes (excluded
from [summary] by prefix) plus ``ctrl_*`` 0-d scalars that surface in
[summary] and round-trip through stats.parse_summary.  Everything is
int32 fixed-point (values scaled by 2**CTRL_SCALE) — no floats, no
widening, donation-friendly.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from deneva_tpu.cc import base as cc_base
from deneva_tpu.config import Config
from deneva_tpu.engine.state import BIG_TS, NULL_KEY, STATUS_FREE, TxnState

#: fixed-point shift for every controller EWMA (value << CTRL_SCALE)
CTRL_SCALE = 4

#: lock/T-O kills die before doing work — the kill is cheap, so the base
#: starts at 1; but the cure for SUSTAINED lock pressure is draining the
#: batch, so this class keeps the exponential-in-restarts growth up to
#: the hard ceiling (the static sweep's winning p16 regime lives inside
#: it)
_FAST_REASONS = ("nowait_conflict", "waitdie_wound", "ts_too_old_read",
                 "ts_too_old_write", "mvcc_version_miss")
#: validation-family aborts burned a whole execution before dying —
#: compounding their penalty starves throughput (and for MAAT collapses
#: the surviving timestamp ranges), so this class is FLAT: no restart
#: growth, a tiny EWMA-tuned base, and the per-lane jitter that spreads
#: the re-colliding vabort cohort.  backoff_reabort is classified per
#: algorithm in _class_tables: it follows the plugin's dominant abort
#: family.
_SLOW_REASONS = ("occ_validation", "maat_range_collapse")


def _class_tables(cfg: Config):
    """Static per-reason (min base, cap, flat+jittered) tables, indexed
    by reason code - 1 (aligned with cc_base.ABORT_REASONS).  Reasons in
    neither class (user/capacity artifacts) retry near-immediately.

    backoff_reabort (died the very tick it woke) follows the plugin's
    dominant abort family, a static trace-time property: under lock/T-O
    algorithms it is lock pressure and the direct evidence the previous
    penalty was too short, so it compounds with the fast class; under
    validation plugins (``vabort_reason`` set) wake-tick thrash is
    validation thrash, and compounding it starves the pipeline the same
    way compounding vaborts does, so it stays flat."""
    from deneva_tpu import cc as cc_registry
    n = len(cc_base.ABORT_REASONS)
    mins = np.ones(n, np.int32)
    caps = np.full(n, min(4, cfg.ctrl_backoff_max), np.int32)
    flat = np.zeros(n, bool)
    for nm in _FAST_REASONS:
        caps[cc_base.REASON[nm] - 1] = cfg.ctrl_backoff_max
    for nm in _SLOW_REASONS:
        # cap 2, not 4: the flat class's lever is jittered desync, and
        # the reference's constant-1 (NO_BACKOFF) regime wins for the
        # validation family — a base above ~2 only delays commits
        i = cc_base.REASON[nm] - 1
        caps[i] = min(2, cfg.ctrl_backoff_max)
        flat[i] = True
    i = cc_base.REASON["backoff_reabort"] - 1
    if cc_registry.get(cfg.cc_alg).vabort_reason is None:
        caps[i] = cfg.ctrl_backoff_max
    else:
        caps[i] = min(2, cfg.ctrl_backoff_max)
        flat[i] = True
    return mins, caps, flat


def _bases(cfg: Config, ewma):
    """Per-reason backoff base from the abort-rate EWMA: grows by one
    tick per 2**ctrl_gain_shift aborts/tick of that reason, clipped into
    the reason's static [min, cap] class band.  The flat (validation)
    class takes a 4x weaker gain — its lever is jittered desync, not
    delay, so its base should leave 1 only under real thrash.
    Self-regulating: a long base drains the in-flight set, the abort
    rate falls, the EWMA decays and the base follows it back down."""
    mins, caps, flat = _class_tables(cfg)
    grow = ewma >> (CTRL_SCALE + cfg.ctrl_gain_shift)
    grow = jnp.where(jnp.asarray(flat), grow >> 2, grow)
    return jnp.clip(jnp.asarray(mins) + grow, jnp.asarray(mins),
                    jnp.asarray(caps))


def init_ctrl(cfg: Config) -> dict:
    """Controller carry block, merged into the engine stats dict by
    _zeros_stats (both engines).  ``arr_ctrl_*`` planes stay out of
    [summary]; the 0-d ``ctrl_*`` scalars surface automatically."""
    n = len(cc_base.ABORT_REASONS)
    s = {
        # per-tick inputs, zeroed at tick start (zero_tick_planes) and
        # filled at the existing taxonomy/heatmap emission sites
        "arr_ctrl_reason_tick": jnp.zeros(n, jnp.int32),
        "arr_ctrl_conf_tick": jnp.zeros(cfg.heatmap_bins, jnp.int32),
        "arr_ctrl_bit_tick": jnp.zeros((cfg.heatmap_bins, 31), jnp.int32),
        # EWMAs (int32 fixed-point, << CTRL_SCALE)
        "arr_ctrl_reason_ewma": jnp.zeros(n, jnp.int32),
        "arr_ctrl_heat": jnp.zeros(cfg.heatmap_bins, jnp.int32),
        # per-bucket bitwise key majority (the heavy-hitter estimator
        # behind escalation): EWMA of each key bit over the bucket's
        # conflict events.  When one key dominates its bucket — the
        # regime escalation targets — the majority bit pattern IS that
        # key; `update` re-hashes it as a validity check, so collision
        # noise can only suppress an escalation, never aim it wrong.
        "arr_ctrl_bit_ewma": jnp.zeros((cfg.heatmap_bins, 31), jnp.int32),
        # escalation ring: key + the heatmap bucket it came from
        "arr_ctrl_esc_key": jnp.full(cfg.ctrl_esc_keys, NULL_KEY,
                                     jnp.int32),
        "arr_ctrl_esc_bucket": jnp.full(cfg.ctrl_esc_keys, -1, jnp.int32),
        # summary scalars (gauges refreshed per tick + decision counters;
        # a controller surface like the heatmap: not warmup-gated)
        "ctrl_occ_ewma": jnp.zeros((), jnp.int32),
        "ctrl_width_idx": jnp.zeros((), jnp.int32),
        "ctrl_esc_active": jnp.zeros((), jnp.int32),
        "ctrl_escalate_cnt": jnp.zeros((), jnp.int32),
        "ctrl_deescalate_cnt": jnp.zeros((), jnp.int32),
        "ctrl_width_step_cnt": jnp.zeros((), jnp.int32),
        "ctrl_esc_block_cnt": jnp.zeros((), jnp.int32),
    }
    for name in cc_base.ABORT_REASONS:
        s[f"ctrl_base_{name}"] = jnp.zeros((), jnp.int32)
    return s


def zero_tick_planes(stats: dict) -> dict:
    """Reset the controller's per-tick input planes (tick start)."""
    return {**stats,
            "arr_ctrl_reason_tick":
                jnp.zeros_like(stats["arr_ctrl_reason_tick"]),
            "arr_ctrl_conf_tick":
                jnp.zeros_like(stats["arr_ctrl_conf_tick"]),
            "arr_ctrl_bit_tick":
                jnp.zeros_like(stats["arr_ctrl_bit_tick"])}


def penalty(cfg: Config, stats: dict, restarts, code_b, t):
    """(B,) adaptive backoff penalty — policy (a).

    Replaces scheduler ``_penalty`` when Config.adaptive: per-reason
    EWMA-tuned base; the lock-kill class keeps the exponential-in-
    restarts growth clipped to its cap, while the flat validation class
    never compounds.  Every class then takes a deterministic
    per-(lane, tick) jitter proportional to its penalty: lanes killed
    the same tick wake the same tick and re-collide wholesale, and
    spreading each cohort over [pen, 1.5*pen] breaks that resonance —
    the one lever the static ladder structurally lacks.  ``code_b`` is
    the lane's abort reason this tick (0 / unregistered falls back to
    "other"); lanes that are not aborting get an arbitrary value the
    caller masks away."""
    n = len(cc_base.ABORT_REASONS)
    _, caps_np, flat_np = _class_tables(cfg)
    base = _bases(cfg, stats["arr_ctrl_reason_ewma"])
    code = jnp.where(code_b <= 0, jnp.int32(cc_base.REASON["other"]),
                     jnp.minimum(code_b, jnp.int32(n)))
    ci = code - 1
    is_flat = jnp.asarray(flat_np)[ci]
    shift = jnp.where(is_flat, 0, jnp.minimum(restarts, 6))
    pen = jnp.minimum(base[ci] << shift, jnp.asarray(caps_np)[ci])
    # retry-storm desync: hash(lane, tick) in [0, pen/2 + 1] — the +2
    # window keeps even a base-1 cohort split across two ticks
    lane = jnp.arange(restarts.shape[0], dtype=jnp.uint32)
    h = (lane * jnp.uint32(0x9E3779B1)
         ^ (t.astype(jnp.uint32) + jnp.uint32(1)) * jnp.uint32(0x85EBCA6B))
    jit = (h % (pen.astype(jnp.uint32) // 2 + 2)).astype(jnp.int32)
    pen = pen + jit
    return jnp.maximum(pen, 1).astype(jnp.int32)


def esc_stall(cfg: Config, stats: dict, txn: TxnState, active):
    """(B,) mask — policy (b)'s one-writer-per-tick gate.

    A lane stalls iff its FIRST access (cursor 0 — it holds nothing yet)
    is a write to an escalated key and a strictly older live txn is
    writing the same key this tick.  The caller empties the stalled
    lanes' request window (clamps n_req to the cursor) so every plugin
    path sees no request: no grant, no wait, no abort — a clean one-tick
    stall.  The cursor-0 restriction is load-bearing: a mid-txn lane
    holds locks, and stalling it would extend every held lock's hold
    time for the whole multi-tick stall — under broad skew the stalled
    hot-key writers' footprints poison the rest of the table and the
    batch wedges.  A lock-free lane's stall is genuinely free."""
    ring = stats["arr_ctrl_esc_key"]                        # (E,)
    B, R = txn.keys.shape
    ridx = jnp.arange(R, dtype=jnp.int32)[None, :]
    m = ridx == jnp.clip(txn.cursor, 0, R - 1)[:, None]
    cur_key = jnp.sum(jnp.where(m, txn.keys, 0), axis=1)
    cur_w = jnp.any(m & txn.is_write, axis=1)
    cand = active & (txn.cursor == 0) & (txn.n_req > 0) & cur_w
    match = (cand[:, None] & (cur_key[:, None] == ring[None, :])
             & (ring != NULL_KEY)[None, :])                 # (B, E)
    # oldest writer per escalated key wins (ts unique across live txns)
    win_ts = jnp.min(jnp.where(match, txn.ts[:, None], BIG_TS), axis=0)
    return jnp.any(match & (txn.ts[:, None] > win_ts[None, :]), axis=1)


def note_stall_heat(cfg: Config, stats: dict, txn: TxnState, stall):
    """Feed this tick's gate stalls back into the controller's conflict
    plane — policy (b)'s stabilizer.

    A stalled writer is a conflict the gate absorbed: without this
    feedback the gated bucket cools (stalls raise no aborts), the
    hysteresis releases it, the retry storm returns and the controller
    thrashes escalate/de-escalate.  Counting stalls as bucket heat keeps
    a productively-gated key escalated — and lets a gate that is
    QUEUEING rather than draining (arrivals far above one writer/tick)
    heat its bucket past the overload bound in `update`, releasing
    itself.  Controller plane only: the user-facing heatmap keeps
    counting real CC friction."""
    B, R = txn.keys.shape
    ridx = jnp.arange(R, dtype=jnp.int32)[None, :]
    m = ridx == jnp.clip(txn.cursor, 0, R - 1)[:, None]
    key_b = jnp.sum(jnp.where(m, txn.keys, 0), axis=1)
    bins = cfg.heatmap_bins
    log2 = bins.bit_length() - 1
    if log2 == 0:
        hidx = jnp.zeros_like(key_b)
    else:
        hidx = ((key_b.astype(jnp.uint32) * jnp.uint32(2654435761))
                >> jnp.uint32(32 - log2)).astype(jnp.int32)
    idx = jnp.where(stall, hidx, bins)
    bits = ((key_b[:, None] >> jnp.arange(31, dtype=jnp.int32))
            & 1).astype(jnp.int32)
    return {**stats,
            "arr_ctrl_conf_tick":
                stats["arr_ctrl_conf_tick"].at[idx].add(1, mode="drop"),
            "arr_ctrl_bit_tick":
                stats["arr_ctrl_bit_tick"].at[idx].add(bits, mode="drop")}


def update(cfg: Config, stats: dict, status, ladder_len: int) -> dict:
    """End-of-tick controller step: fold this tick's observations into
    the EWMAs and re-decide all three policies.  Pure jnp — selects,
    clips, one tiny argmax/argmin pair over the heatmap/ring widths."""
    sh = cfg.ctrl_ewma_shift
    bins = cfg.heatmap_bins
    out = dict(stats)

    # ---- (a) per-reason abort-rate EWMA -> published backoff bases ----
    ewma = stats["arr_ctrl_reason_ewma"]
    ewma = ewma + (((stats["arr_ctrl_reason_tick"] << CTRL_SCALE) - ewma)
                   >> sh)
    out["arr_ctrl_reason_ewma"] = ewma
    base = _bases(cfg, ewma)
    for i, name in enumerate(cc_base.ABORT_REASONS):
        out[f"ctrl_base_{name}"] = base[i]

    # ---- (b) bucket heat EWMA -> escalation ring (with hysteresis) ----
    heat = stats["arr_ctrl_heat"]
    heat = heat + (((stats["arr_ctrl_conf_tick"] << CTRL_SCALE) - heat)
                   >> sh)
    out["arr_ctrl_heat"] = heat
    bit = stats["arr_ctrl_bit_ewma"]
    bit = bit + (((stats["arr_ctrl_bit_tick"] << CTRL_SCALE) - bit) >> sh)
    out["arr_ctrl_bit_ewma"] = bit
    # heavy-hitter per bucket: a bit is set in the majority key iff it is
    # set in more than half the bucket's (EWMA-weighted) conflicts; the
    # re-hash check below rejects patterns that aren't a key of this
    # bucket (no single dominant key => usually fails => no escalation)
    maj_key = jnp.sum(jnp.where(2 * bit > heat[:, None],
                                jnp.int32(1) << jnp.arange(31,
                                                           dtype=jnp.int32),
                                0), axis=1)                      # (bins,)

    key = stats["arr_ctrl_esc_key"]
    bucket = stats["arr_ctrl_esc_bucket"]
    up = jnp.int32(cfg.ctrl_esc_up << CTRL_SCALE)
    down = jnp.int32(cfg.ctrl_esc_down << CTRL_SCALE)
    over = jnp.int32((cfg.ctrl_esc_up * cfg.ctrl_esc_overload)
                     << CTRL_SCALE)
    slot_heat = jnp.where(bucket >= 0, heat[jnp.clip(bucket, 0, bins - 1)],
                          0)
    # release a slot that went cold (hysteresis) OR blew past the
    # overload bound: gate stalls feed back into the conflict plane
    # (note_stall_heat), so a gate that is queueing rather than draining
    # — per-key arrivals far above its one-writer-per-tick service rate —
    # heats its own bucket until this releases it
    cold = (key != NULL_KEY) & ((slot_heat < down) | (slot_heat >= over))
    n_de = jnp.sum(cold.astype(jnp.int32))
    key = jnp.where(cold, NULL_KEY, key)
    bucket = jnp.where(cold, -1, bucket)
    slot_heat = jnp.where(cold, 0, slot_heat)

    # escalate the hottest not-yet-escalated bucket into the weakest slot
    # (at most one promotion per tick — adaptation is deliberately slow
    # next to the tick rate, and the trace ring shows every step)
    bidx = jnp.arange(bins, dtype=jnp.int32)
    already = jnp.any(bidx[:, None] == bucket[None, :], axis=1)  # (bins,)
    cand = jnp.argmax(jnp.where(already, jnp.int32(-1), heat)
                      ).astype(jnp.int32)
    cand_heat = heat[cand]
    cand_key = maj_key[cand]
    log2 = bins.bit_length() - 1
    if log2 == 0:
        key_ok = cand_key > 0
    else:
        rehash = ((cand_key.astype(jnp.uint32) * jnp.uint32(2654435761))
                  >> jnp.uint32(32 - log2)).astype(jnp.int32)
        key_ok = (cand_key > 0) & (rehash == cand)
    empty = key == NULL_KEY
    score = jnp.where(empty, jnp.int32(-1), slot_heat)
    victim = jnp.argmin(score).astype(jnp.int32)
    # dominance: only a bucket carrying more than 1/ctrl_esc_share of the
    # WHOLE heatmap's conflict heat is worth serializing.  Broad zipf
    # contention spreads heat across buckets (no single key dominates —
    # backoff, not the gate, is the right tool); a tiny pathological hot
    # set concentrates it.  The overload bound mirrors the release rule:
    # a key too hot for one writer/tick is never taken on.
    dominant = cand_heat > jnp.sum(heat) // jnp.int32(cfg.ctrl_esc_share)
    do = ((cand_heat >= up) & (cand_heat < over) & dominant & key_ok
          & ~already[cand] & (cand_heat > score[victim]))
    # scalar victim index: a single slot is duplicate-free by construction
    key = key.at[victim].set(jnp.where(do, cand_key, key[victim]),
                             unique_indices=True)
    bucket = bucket.at[victim].set(jnp.where(do, cand, bucket[victim]),
                                   unique_indices=True)
    out["arr_ctrl_esc_key"] = key
    out["arr_ctrl_esc_bucket"] = bucket
    out["ctrl_escalate_cnt"] = (stats["ctrl_escalate_cnt"]
                                + do.astype(jnp.int32))
    out["ctrl_deescalate_cnt"] = stats["ctrl_deescalate_cnt"] + n_de
    out["ctrl_esc_active"] = jnp.sum((key != NULL_KEY).astype(jnp.int32))

    # ---- (c) slot-occupancy EWMA -> ladder gear ----
    # occupancy = in-flight slots (everything not FREE, backoff sleepers
    # included): a batch full of backing-off lanes IS congestion — the
    # contended regime where the wider gear pays — even though few lanes
    # are RUNNING at any instant
    occ = jnp.sum((status != STATUS_FREE).astype(jnp.int32))
    oe = stats["ctrl_occ_ewma"]
    oe = oe + (((occ << CTRL_SCALE) - oe) >> sh)
    out["ctrl_occ_ewma"] = oe
    B = status.shape[0]
    idx = jnp.zeros((), jnp.int32)
    for k in range(ladder_len - 1):
        # gear k+1 engages above occupancy B*(k+1)/ladder_len
        thr = jnp.int32((B * (k + 1) // ladder_len) << CTRL_SCALE)
        idx = idx + (oe > thr).astype(jnp.int32)
    out["ctrl_width_idx"] = idx
    out["ctrl_width_step_cnt"] = (stats["ctrl_width_step_cnt"]
                                  + (idx != stats["ctrl_width_idx"]
                                     ).astype(jnp.int32))
    return out


def width_ladder(cfg: Config, plugin) -> list:
    """Static gear ladder for policy (c): index 0 is the exact configured
    behavior; higher gears trade work for contention tolerance.  Gears
    exist only where legal for (cfg, plugin) — an ineligible cell gets a
    one-entry ladder and the scheduler skips the switch entirely."""
    if not cfg.adaptive:
        return [cfg]
    from deneva_tpu.config import READ_COMMITTED, SERIALIZABLE
    ladder = [cfg]
    if cfg.entry_compaction and cfg.compact_lanes is not None:
        # high occupancy = more live entries = compaction spill retries;
        # widen the bucket under load (compact_width clamps to B*R)
        ladder.append(cfg.replace(compact_lanes=cfg.compact_lanes * 2))
    sub_ok = (cfg.sub_ticks == 1 and cfg.acquire_window == 1
              and plugin.name in ("NO_WAIT", "WAIT_DIE", "TIMESTAMP")
              and (plugin.name == "TIMESTAMP"
                   or cfg.isolation_level in (SERIALIZABLE,
                                              READ_COMMITTED)))
    if sub_ok:
        # within-tick lock handoff: worth its extra sub-rounds exactly
        # when the batch is full of conflicting lanes
        ladder.append(cfg.replace(sub_ticks=cfg.ctrl_sub_ticks))
    return ladder
