"""Adaptive contention controller (Config.adaptive).

Closes the loop from the observability planes (abort taxonomy, conflict
heatmap, live occupancy) back into the engine at runtime — the Deneva
study's core finding (contention dominates protocol choice) turned from
measurement into mechanism.  See ctrl/controller.py for the three
policies and their invariants.
"""

from deneva_tpu.ctrl.controller import (  # noqa: F401
    CTRL_SCALE, esc_stall, init_ctrl, note_stall_heat, penalty, update,
    width_ladder, zero_tick_planes)
