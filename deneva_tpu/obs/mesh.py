"""Cluster mesh observatory: the per-node-pair traffic matrix (obs
pillar 8 — the last unobserved subsystem).

The reference attributes distributed performance to MESSAGES — per-type
counters and queue delays in statistics/stats.cpp (msg_queue_delay,
msg_send/receive per RemReqType) behind the VLDB'17 finding that
coordination cost dominates at scale — yet the sharded engine collapsed
all cross-node behavior into one ``remote_entry_cnt`` scalar.  Opt-in
through ``Config.mesh``, every node carries two ``(N, T)`` int32 planes
inside its stats dict (node-stacked under shard_map, so the fetched
cluster tensors are ``(N, N, T)``):

- ``arr_mesh_tx``  row ``i`` of the cluster matrix: messages THIS node
  delivered to dest ``j``, tagged by message type ``t``;
- ``arr_mesh_rx``  the mirror: messages received FROM src ``i``.

The type axis (:data:`MSG_TYPES`) rebuilds the reference's RemReqType
taxonomy at the exchange sites of ``parallel/sharded.py``:

====== =========== ====================================================
 col    type        accumulation site
====== =========== ====================================================
 0      request     exchange A (RQRY): delivered non-finishing entries
 1      response    exchange A' (RQRY_RSP/RACK): one decision word per
                    delivered entry, counted at BOTH ends
 2      prepare     exchange A entries flagged for validation (the 2PC
                    prepare/vote leg riding exchange A, flags bit 3)
 3      commit      exchange B (RFIN): delivered commit-effect entries
 4      repl        log-replication ppermute records (LOG_MSG)
 5      epoch       Calvin: ALL exchange-A traffic (the sequencer's
                    epoch fan-out incl. recon-shadow reads) classifies
                    here instead of request/prepare
====== =========== ====================================================

NOT counted (documented non-messages): the MaaT commit-forward-push
third leg (dense lanes riding the A-pack permutation — bounds piggyback
on the response, not a new message) and the replication-ack ppermutes
(scalar high-water marks).  AP replica nodes therefore have all-zero tx
rows except their (empty) repl lane.

Exact identities (all warmup-gated with the same ``measuring`` mask as
the counters they reconcile against; tests/test_mesh.py):

- per node: ``tx`` row-sum over {request, prepare, epoch}
  + ``mesh_drop_cnt`` (exchange-A overflow)  ==  ``remote_entry_cnt``
  (attempted == delivered + dropped);
- ``tx[i, j, t] == rx[j, i, t]`` bit-exact for every type (both ends of
  the same all_to_all / ppermute count the same delivered lanes);
- per pair: ``tx[j, i, response] == tx[i, j, request+prepare+epoch]``
  (one decision word back per delivered entry);
- net_delay runs: ``arr_mesh_inflight`` (the per-type in-transit
  message population) sums to the ``lat_msg_queue_time`` integral;
- the device-psum'd cluster matrix (:func:`cluster_matrix`) is
  bit-exact equal to the host sum of per-node tx planes.

Load planes ride along: per-tick exchange-A occupancy (delivered
entries vs ``cap``) integrates into ``mesh_occ_sum`` / ``mesh_occ_peak``
and a pmax straggler bit (``straggler_tick_cnt``: ticks this node's
occupancy topped the cluster); host side, per-node commit loads fold
into Jain's fairness index ``imb_jain`` (1.0 = perfectly balanced,
1/N = one node doing everything), the ``[mesh]`` report section and the
IMBALANCE watchdog bit (obs/report.py).  With ``Config.trace_ticks``
a per-dest sent-count companion ring (``arr_mesh_trace``) feeds the
per-node-pair Perfetto counter tracks (obs/trace.py / obs/export.py).

When ``Config.mesh`` is False (default) no arrays are carried and the
[summary] line is byte-identical to a build without this module.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from deneva_tpu.engine.state import NULL_KEY

#: message-type axis of the traffic tensor (the RemReqType rebuild)
MSG_TYPES = ("request", "response", "prepare", "commit", "repl", "epoch")
REQ, RESP, PREP, COMMIT, REPL, EPOCH = range(len(MSG_TYPES))

#: the exact [summary] surface the observatory adds (tests assert it):
#: the four int counters ride the sharded psum; imb_jain / mesh_tx_total
#: are host-computed in ShardedEngine.summary
MESH_SUMMARY_KEYS = ("mesh_drop_cnt", "mesh_occ_sum", "mesh_occ_peak",
                     "straggler_tick_cnt", "imb_jain", "mesh_tx_total")

#: Jain's index below this (with commits flowing) fires the IMBALANCE
#: watchdog bit.  J = k/n when k of n nodes carry all the load, so a
#: balanced AP cluster (replicas commit nothing by design) sits at
#: ~0.5 - epsilon; the threshold lives strictly below that by-design
#: asymmetry so AP runs stay clean while genuine straggler collapse
#: (well under half the cluster doing the work) fires.
IMB_JAIN_MIN = 0.45


# ---------------------------------------------------------------------------
# device side (jit-safe; every helper no-ops when the plane is absent)
# ---------------------------------------------------------------------------

def init_mesh(cfg, n_nodes: int) -> dict:
    """Stats-dict entries for the observatory; empty when off (the
    disabled path carries nothing)."""
    if not cfg.mesh:
        return {}
    T = len(MSG_TYPES)
    out = {
        "arr_mesh_tx": jnp.zeros((n_nodes, T), jnp.int32),
        "arr_mesh_rx": jnp.zeros((n_nodes, T), jnp.int32),
        # exchange-A overflow: attempted-but-dropped entries, so
        # delivered + dropped reconciles against remote_entry_cnt
        "mesh_drop_cnt": jnp.zeros((), jnp.int32),
        # exchange-A occupancy integral / peak (delivered entries per
        # tick; the psum'd cluster peak is the SUM of per-node peaks,
        # a pressure bound like queue_peak, not a max)
        "mesh_occ_sum": jnp.zeros((), jnp.int32),
        "mesh_occ_peak": jnp.zeros((), jnp.int32),
        # ticks this node's occupancy equalled the cluster pmax (> 0);
        # ties count on every tied node
        "straggler_tick_cnt": jnp.zeros((), jnp.int32),
    }
    if cfg.net_delay_ticks > 0:
        # per-type in-transit message population; sums to the
        # lat_msg_queue_time integral (only a delay model has transit)
        out["arr_mesh_inflight"] = jnp.zeros(T, jnp.int32)
    if cfg.trace_ticks > 0:
        # per-dest sent-count companion ring for the per-node-pair
        # Perfetto counter tracks — SEPARATE array so TRACE_COLUMNS and
        # every consumer of it stay unchanged (obs/trace.py discipline)
        out["arr_mesh_trace"] = jnp.zeros((cfg.trace_ticks, n_nodes),
                                          jnp.int32)
    return out


def note_exchange_a(stats: dict, dest, shipped, dropped, fin_e, is_epoch,
                    n_nodes: int, measuring):
    """Home side of exchange A: type-tagged tx scatter of delivered
    entries (+ the response-leg rx mirror — one decision word will come
    back per delivered entry) and the drop counter.  Returns
    ``(stats, per_dest)`` where ``per_dest`` is the UNGATED (N,)
    delivered-count vector (occupancy + trace ring input; None off)."""
    if "arr_mesh_tx" not in stats:
        return stats, None
    inc = jnp.where(measuring & shipped, 1, 0).astype(jnp.int32)
    # classification is static per plugin (Calvin's A traffic IS the
    # epoch fan-out); otherwise flags bit 3 splits prepare from request
    col = (jnp.full_like(dest, EPOCH) if is_epoch
           else jnp.where(fin_e, PREP, REQ).astype(jnp.int32))
    # commutative scatter-add; dead/overflow lanes carry inc == 0 and
    # dest == n_nodes drops (LINT.md race-free idiom)
    tx = stats["arr_mesh_tx"].at[dest, col].add(inc, mode="drop")
    rx = stats["arr_mesh_rx"].at[dest, RESP].add(inc, mode="drop")
    drop = stats["mesh_drop_cnt"] + jnp.sum(
        jnp.where(measuring & dropped, 1, 0).astype(jnp.int32))
    per_dest = jnp.zeros(n_nodes, jnp.int32).at[dest].add(
        shipped.astype(jnp.int32), mode="drop")
    return {**stats, "arr_mesh_tx": tx, "arr_mesh_rx": rx,
            "mesh_drop_cnt": drop}, per_dest


def note_owner_rx(stats: dict, recv_key, recv_flags, is_epoch, measuring
                  ) -> dict:
    """Owner side of exchange A: received live lanes per src row (+ the
    response-leg tx mirror — this node returns one decision word per
    live lane it received)."""
    if "arr_mesh_rx" not in stats:
        return stats
    g = jnp.where(measuring & (recv_key != NULL_KEY), 1, 0).astype(
        jnp.int32)                                    # (N, cap)
    n_live = jnp.sum(g, axis=1)                       # (N,) per src
    rx = stats["arr_mesh_rx"]
    if is_epoch:
        rx = rx.at[:, EPOCH].add(n_live)
    else:
        fin = ((recv_flags >> 3) & 1) == 1
        n_fin = jnp.sum(jnp.where(fin, g, 0), axis=1)
        rx = rx.at[:, PREP].add(n_fin)
        rx = rx.at[:, REQ].add(n_live - n_fin)
    tx = stats["arr_mesh_tx"].at[:, RESP].add(n_live)
    return {**stats, "arr_mesh_rx": rx, "arr_mesh_tx": tx}


def note_owner_rx_counts(stats: dict, n_live, n_fin, is_epoch, measuring
                         ) -> dict:
    """Owner side of the epoch-split exchange A (Config.exchange_split):
    per-src delivered-lane counts accumulated across sub-rounds inside
    the lax.scan (the per-round (N, cap) recv planes are ephemeral),
    folded into the planes once per tick.  Callers pass counts with the
    self row already zeroed — the self-lane is process-local, not a
    message.  The decision pass rides the same sub-round windows and is
    NOT counted as a second request leg (documented non-message, like
    the MaaT forward-push lanes); its decbits return is the usual
    one-response-per-delivered-entry mirror."""
    if "arr_mesh_rx" not in stats:
        return stats
    n_live = jnp.where(measuring, n_live, 0)
    n_fin = jnp.where(measuring, n_fin, 0)
    rx = stats["arr_mesh_rx"]
    if is_epoch:
        rx = rx.at[:, EPOCH].add(n_live)
    else:
        rx = rx.at[:, PREP].add(n_fin)
        rx = rx.at[:, REQ].add(n_live - n_fin)
    tx = stats["arr_mesh_tx"].at[:, RESP].add(n_live)
    return {**stats, "arr_mesh_rx": rx, "arr_mesh_tx": tx}


def note_commit_exchange_counts(stats: dict, dest, shipped, n_recv,
                                measuring) -> dict:
    """Exchange B under the epoch-split exchange (Config.exchange_split):
    same two ends as note_commit_exchange, but the receive side arrives
    as per-source counts accumulated across the commit sub-rounds inside
    the lax.scan.  Callers pass ``n_recv`` with the self row already
    zeroed — the all_to_all self-lane delivery of local commit entries
    is process-local, not a message."""
    if "arr_mesh_tx" not in stats:
        return stats
    inc = jnp.where(measuring & shipped, 1, 0).astype(jnp.int32)
    tx = stats["arr_mesh_tx"].at[dest, COMMIT].add(inc, mode="drop")
    rx = stats["arr_mesh_rx"].at[:, COMMIT].add(
        jnp.where(measuring, n_recv, 0).astype(jnp.int32))
    return {**stats, "arr_mesh_tx": tx, "arr_mesh_rx": rx}


def note_commit_exchange(stats: dict, dest, shipped, recv_key, measuring
                         ) -> dict:
    """Exchange B (RFIN): delivered commit-effect entries, both ends.
    ``shipped`` must already exclude local and overflowed lanes (a
    deferred txn's successfully-packed entries DID travel — they count;
    the owner ignores them via the commit flag, not the wire)."""
    if "arr_mesh_tx" not in stats:
        return stats
    inc = jnp.where(measuring & shipped, 1, 0).astype(jnp.int32)
    tx = stats["arr_mesh_tx"].at[dest, COMMIT].add(inc, mode="drop")
    live = jnp.where(measuring & (recv_key != NULL_KEY), 1, 0).astype(
        jnp.int32)
    rx = stats["arr_mesh_rx"].at[:, COMMIT].add(jnp.sum(live, axis=1))
    return {**stats, "arr_mesh_tx": tx, "arr_mesh_rx": rx}


def note_repl(stats: dict, dest_idx, n_sent, src_idx, n_recv, measuring
              ) -> dict:
    """Log-replication ppermute (LOG_MSG records): per-record counts at
    both ends.  Callers pass clamped indices (``n_nodes`` == no peer,
    dropped); the scalar ack ppermutes are NOT messages (documented)."""
    if "arr_mesh_tx" not in stats:
        return stats
    z = jnp.int32(0)
    tx = stats["arr_mesh_tx"].at[dest_idx, REPL].add(
        jnp.where(measuring, n_sent, z), mode="drop")
    rx = stats["arr_mesh_rx"].at[src_idx, REPL].add(
        jnp.where(measuring, n_recv, z), mode="drop")
    return {**stats, "arr_mesh_tx": tx, "arr_mesh_rx": rx}


def note_inflight(stats: dict, n_req, n_resp, n_prep, measuring) -> dict:
    """net_delay mode: the tick's in-transit message population split by
    type — requests still travelling to owners; responses = grant words
    plus abort decisions in transit home; prepare = 2PC prepare requests
    and vote words in flight.  The three sum to exactly the
    ``lat_msg_queue_time`` bump of the same tick."""
    if "arr_mesh_inflight" not in stats:
        return stats
    z = jnp.int32(0)
    # lane order is the MSG_TYPES order: req, resp, prep, commit/repl/
    # epoch never travel through the delay buffers
    vec = jnp.stack([jnp.asarray(n_req, jnp.int32),
                     jnp.asarray(n_resp, jnp.int32),
                     jnp.asarray(n_prep, jnp.int32), z, z, z])
    return {**stats, "arr_mesh_inflight":
            stats["arr_mesh_inflight"] + jnp.where(measuring, vec, 0)}


def note_occupancy(stats: dict, per_dest, axis_name: str, measuring
                   ) -> dict:
    """Exchange-A occupancy load plane + the pmax straggler bit (the
    node whose delivered-entry count peaks this tick; ties all count)."""
    if "mesh_occ_sum" not in stats or per_dest is None:
        return stats
    occ = jnp.sum(per_dest)
    mx = jax.lax.pmax(occ, axis_name)
    g = jnp.where(measuring, occ, 0)
    strag = measuring & (occ == mx) & (mx > 0)
    return {**stats,
            "mesh_occ_sum": stats["mesh_occ_sum"] + g,
            "mesh_occ_peak": jnp.maximum(stats["mesh_occ_peak"], g),
            "straggler_tick_cnt": stats["straggler_tick_cnt"]
            + strag.astype(jnp.int32)}


def note_round_windows(stats: dict, per_dest, n_self, cap: int, measuring
                       ) -> dict:
    """Mesh-side sub-round bookkeeping for the epoch-split exchange:
    the number of capacity windows implied by the DELIVERED per-dest
    counts — ``ceil(max(per_dest, n_self) / cap)``, with the self lane
    (excluded from ``per_dest`` on the split path) supplied separately.
    ceil is monotone, so the max-of-ceils the engine's round_plan counts
    (``exchange_round_cnt``) equals this ceil-of-max exactly, and the
    split path drops nothing structurally — :func:`reconcile` pins the
    per-node identity ``mesh_round_sum == exchange_round_cnt``."""
    if "mesh_round_sum" not in stats or per_dest is None:
        return stats
    busiest = jnp.maximum(jnp.max(per_dest), jnp.asarray(n_self, jnp.int32))
    rounds = (busiest + (cap - 1)) // cap
    return {**stats, "mesh_round_sum":
            stats["mesh_round_sum"] + jnp.where(measuring, rounds, 0)}


def note_trace(stats: dict, t, per_dest) -> dict:
    """Per-dest sent counts into the companion ring (wrap-and-accumulate,
    NOT warmup-gated — the trace-ring discipline of obs/trace.py)."""
    if "arr_mesh_trace" not in stats or per_dest is None:
        return stats
    buf = stats["arr_mesh_trace"]
    return {**stats, "arr_mesh_trace":
            buf.at[t % buf.shape[0]].add(per_dest, unique_indices=True)}


# ---------------------------------------------------------------------------
# host side
# ---------------------------------------------------------------------------

def jain(xs) -> float:
    """Jain's fairness index (sum x)^2 / (n * sum x^2): 1.0 = perfectly
    balanced, 1/n = one node doing everything; 1.0 for an all-zero
    vector (nothing flowed, nothing is unfair)."""
    xs = np.asarray(xs, dtype=np.float64).reshape(-1)
    denom = xs.size * float((xs * xs).sum())
    if denom == 0.0:
        return 1.0
    return float(xs.sum()) ** 2 / denom


def snapshot(state_or_stats) -> dict:
    """Fetch the node-stacked planes to numpy: the (N, N, T) cluster
    tensors (axis 0 = sender for ``tx``, receiver for ``rx``), the
    per-node load planes, and the per-type inflight populations."""
    stats = getattr(state_or_stats, "stats", state_or_stats)
    assert "arr_mesh_tx" in stats, "run with Config.mesh=True"
    tx = np.asarray(stats["arr_mesh_tx"])
    rx = np.asarray(stats["arr_mesh_rx"])
    assert tx.ndim == 3, "mesh planes are node-stacked (sharded engine)"

    def per(k):
        return (np.asarray(stats[k]).reshape(-1).copy()
                if k in stats else None)

    snap = {
        "nodes": tx.shape[0],
        "types": list(MSG_TYPES),
        "tx": tx, "rx": rx,
        "drops": per("mesh_drop_cnt"),
        "occ_sum": per("mesh_occ_sum"),
        "occ_peak": per("mesh_occ_peak"),
        "straggler": per("straggler_tick_cnt"),
        "commits": per("txn_cnt"),
        "aborts": per("total_txn_abort_cnt"),
        "remote": per("remote_entry_cnt"),
        # epoch-split exchange only: the engine's occupied sub-round
        # count and the mesh-side window count it must equal
        "rounds": per("exchange_round_cnt"),
        "round_sum": per("mesh_round_sum"),
        "measured_ticks": int(np.asarray(stats["measured_ticks"]).max()),
    }
    if "arr_mesh_inflight" in stats:
        snap["inflight"] = np.asarray(stats["arr_mesh_inflight"])
    return snap


def reconcile(snap: dict, summary: dict) -> list:
    """The exact identities, as ``(what, got, want)`` mismatch tuples
    (empty == all good; tests + the check.sh mesh smoke gate)."""
    bad = []
    tx, rx = snap["tx"], snap["rx"]
    # both ends of every exchange counted the same delivered lanes
    if not np.array_equal(tx, np.transpose(rx, (1, 0, 2))):
        diff = int(np.abs(tx.astype(np.int64)
                          - np.transpose(rx, (1, 0, 2))).sum())
        bad.append(("tx_rx_transpose_absdiff", diff, 0))
    # one decision word home per delivered exchange-A entry, per pair
    a_pair = tx[:, :, REQ] + tx[:, :, PREP] + tx[:, :, EPOCH]
    if not np.array_equal(tx[:, :, RESP], a_pair.T):
        bad.append(("response_mirror", int(tx[:, :, RESP].sum()),
                    int(a_pair.sum())))
    # attempted == delivered + dropped, per node
    if snap["remote"] is not None and snap["drops"] is not None:
        attempts = (tx[:, :, (REQ, PREP, EPOCH)].sum(axis=(1, 2))
                    + snap["drops"])
        for i in range(snap["nodes"]):
            if int(attempts[i]) != int(snap["remote"][i]):
                bad.append((f"remote_entry[{i}]", int(attempts[i]),
                            int(snap["remote"][i])))
    # epoch-split exchange: the mesh-side window count derived from the
    # delivered per-dest traffic lands exactly on the engine's
    # round_plan bookkeeping, per node (zero drops structurally on the
    # split path, and ceil-of-max == max-of-ceil) — so drops, occupancy
    # and rounds balance in one identity set
    if snap.get("rounds") is not None and snap.get("round_sum") is not None:
        for i in range(snap["nodes"]):
            if int(snap["round_sum"][i]) != int(snap["rounds"][i]):
                bad.append((f"round_windows[{i}]",
                            int(snap["round_sum"][i]),
                            int(snap["rounds"][i])))
    # remote-grant stickiness (Config.remote_cache): every attempted
    # remote entry either shipped or was answered from the cache —
    # attempts == shipped (remote_entry_cnt) + suppressed, cluster-wide
    if "remote_attempt_cnt" in summary:
        got = (int(summary["remote_entry_cnt"])
               + int(summary.get("reship_suppressed_cnt", 0)))
        want = int(summary["remote_attempt_cnt"])
        if got != want:
            bad.append(("remote_cache_attempts", got, want))
    # in-transit population sums to the per-message queue-time integral
    if "inflight" in snap and "lat_msg_queue_time" in summary:
        got = int(snap["inflight"].sum())
        want = int(summary["lat_msg_queue_time"])
        if got != want:
            bad.append(("msg_queue_population", got, want))
    # the summary's cluster totals match the fetched planes
    if "mesh_tx_total" in summary:
        got = int(tx.sum())
        if got != int(summary["mesh_tx_total"]):
            bad.append(("summary_tx_total", got,
                        int(summary["mesh_tx_total"])))
    return bad


def cluster_matrix(jax_mesh, tx_stacked) -> np.ndarray:
    """Device-side psum of the per-node tx planes over the node axis in
    one jitted shard_map — bit-exact equal to the host
    ``tx_stacked.sum(axis=0)`` ((N, T) per-dest per-type totals)."""
    from jax.sharding import PartitionSpec as P
    from deneva_tpu.compat import shard_map
    axis = jax_mesh.axis_names[0]
    spec = P(axis)

    def agg(tx):
        return jax.lax.psum(tx[0], axis)[None]

    f = jax.jit(shard_map(agg, mesh=jax_mesh, in_specs=(spec,),
                          out_specs=spec))
    return np.asarray(f(tx_stacked))[0]


def imbalance(snap: dict) -> dict:
    """Jain's indices over the per-node load planes plus the straggler
    attribution (which node topped exchange occupancy most often)."""
    out = {"imb_jain": jain(snap["commits"])
           if snap["commits"] is not None else 1.0}
    if snap["occ_sum"] is not None:
        out["imb_jain_occ"] = jain(snap["occ_sum"])
    if snap["straggler"] is not None:
        out["straggler_node"] = int(np.argmax(snap["straggler"]))
        out["straggler_ticks"] = int(snap["straggler"].max())
    return out


def mesh_report(snap: dict, cap: int | None = None, topk: int = 8) -> dict:
    """The machine-readable ``[mesh]`` section (obs/report.py renders
    it): per-type cluster totals, the (N, N) volume matrix, the top
    traffic pairs, the per-node load planes and the imbalance block."""
    tx = snap["tx"]
    N = snap["nodes"]
    vol = tx.sum(axis=2)                      # (N, N) messages i -> j
    order = np.argsort(-vol, axis=None)
    pairs = []
    for k in order[:topk]:
        i, j = int(k) // N, int(k) % N
        if vol[i, j] <= 0:
            break
        pairs.append({"src": i, "dst": j, "msgs": int(vol[i, j])})
    ticks = max(snap["measured_ticks"], 1)
    per_node = {}
    for key in ("commits", "aborts", "remote", "occ_peak", "straggler"):
        if snap.get(key) is not None:
            per_node[key] = [int(v) for v in snap[key]]
    if snap.get("occ_sum") is not None:
        per_node["occ_avg"] = [round(float(v) / ticks, 2)
                               for v in snap["occ_sum"]]
    out = {
        "nodes": N,
        "ticks": snap["measured_ticks"],
        "by_type": {name: int(tx[:, :, i].sum())
                    for i, name in enumerate(MSG_TYPES)},
        "matrix": vol.astype(int).tolist(),
        "top_pairs": pairs,
        "per_node": per_node,
        "drops": int(snap["drops"].sum())
        if snap.get("drops") is not None else 0,
        "imbalance": imbalance(snap),
    }
    if "inflight" in snap:
        out["inflight"] = {name: int(snap["inflight"].sum(axis=0)[i])
                           for i, name in enumerate(MSG_TYPES)}
    if cap is not None:
        out["cap"] = int(cap)
    return out
