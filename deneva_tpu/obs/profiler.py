"""Host-side phase profiler + structured run records.

The reference accumulates host timers around every queue and processing
phase (statistics/stats.h time families).  In this rebuild the whole tick
is ONE jit'd XLA program, so the meaningful host-visible phases are:

- ``trace_lower_compile``  first dispatch of a (function, shape) pair:
                           jax tracing + StableHLO lowering + XLA
                           compilation (detected by the jit cache growing
                           across the call);
- ``dispatch``             steady-state enqueue cost of a cached dispatch;
- ``execute``              device time to drain the enqueued tick(s)
                           (``jax.block_until_ready``).

Profiling blocks after every dispatch so phases are real wall times —
that forfeits host/device pipelining, which is the documented observation
cost of ``Config.profile`` (never extra device work; the tick graph is
untouched).  ``jit_recompiles`` counts cache misses — a recompile storm
mid-run (e.g. a shape-changing host loop) is the single most common
silent performance bug this catches.

:func:`run_record` assembles a structured JSON document (config
fingerprint + summary + phase times + optional timeline) and
:func:`write_run_record` lands it under ``results/`` so every measured
run leaves a machine-readable artifact next to its ``[summary]`` line.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from contextlib import contextmanager
from typing import Any, Optional

import jax

RECORD_SCHEMA = "deneva-tpu/run-record/v1"


class PhaseProfiler:
    """Accumulating phase timers + counters (re-entrant per phase name)."""

    def __init__(self):
        self.phases: dict[str, dict] = {}
        self.counters: dict[str, int] = {}

    # -- primitives ----------------------------------------------------
    def add(self, name: str, seconds: float) -> None:
        p = self.phases.setdefault(name, {"seconds": 0.0, "count": 0})
        p["seconds"] += float(seconds)
        p["count"] += 1

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self.add(name, time.perf_counter() - t0)

    # -- jit-aware dispatch --------------------------------------------
    @staticmethod
    def jit_cache_size(fn) -> Optional[int]:
        """Compiled-variant count of a jitted callable (None when the
        running jax version doesn't expose it)."""
        try:
            return fn._cache_size()
        except Exception:
            return None

    def dispatch(self, fn, *args):
        """Call a jitted ``fn``, attributing the call to
        ``trace_lower_compile`` (cache grew => this call traced, lowered
        and compiled) or ``dispatch``, then block in ``execute``."""
        before = self.jit_cache_size(fn)
        t0 = time.perf_counter()
        out = fn(*args)
        dt = time.perf_counter() - t0
        after = self.jit_cache_size(fn)
        if before is not None and after is not None and after > before:
            self.add("trace_lower_compile", dt)
            self.count("jit_recompiles")
        else:
            self.add("dispatch", dt)
        with self.phase("execute"):
            jax.block_until_ready(out)
        return out

    # -- reporting ------------------------------------------------------
    def snapshot(self) -> dict:
        return {"phases": {k: dict(v) for k, v in self.phases.items()},
                "counters": dict(self.counters)}


def config_fingerprint(cfg) -> str:
    """Stable short hash of the full Config cell, so run records from the
    same experiment cell collate regardless of when they ran."""
    blob = json.dumps(dataclasses.asdict(cfg), sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def _jsonable(v: Any):
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if hasattr(v, "tolist"):        # numpy/jax arrays AND scalars
        return v.tolist()
    return v


def run_record(cfg, summary: dict, phases: Optional[dict] = None,
               timeline: Optional[dict] = None,
               extra: Optional[dict] = None) -> dict:
    """Structured record of one measured run: config fingerprint +
    [summary] contents + profiler snapshot + optional per-tick timeline
    (obs.trace.timeline output)."""
    rec = {
        "schema": RECORD_SCHEMA,
        "config_fingerprint": config_fingerprint(cfg),
        "config": _jsonable(dataclasses.asdict(cfg)),
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "unix_time": time.time(),
        "summary": _jsonable(summary),
        "profile": _jsonable(phases) if phases else None,
        "timeline": _jsonable(timeline) if timeline else None,
    }
    if getattr(cfg, "fused_arbitrate", False):
        # the fused kernel's loud static-fallback accounting
        # (ops/fused.py): any sort that fell back to lax.sort at trace
        # time is on the record, never silent.  Kept out of [summary] —
        # the fused path's summary lines must stay bit-identical to the
        # lax path's (tests/test_fused.py).
        from deneva_tpu.ops import fused
        rec["fused_fallbacks"] = fused.fallback_snapshot()
    if extra:
        rec.update(_jsonable(extra))
    return rec


def write_run_record(record: dict, out_dir: str = "results",
                     name: Optional[str] = None) -> str:
    os.makedirs(out_dir, exist_ok=True)
    if name is None:
        name = (f"run_{record.get('config_fingerprint', 'unknown')}_"
                f"{int(record.get('unix_time', time.time()))}.json")
    path = os.path.join(out_dir, name)
    with open(path, "w") as f:
        json.dump(record, f, indent=1)
    return path
