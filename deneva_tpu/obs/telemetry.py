"""Streaming telemetry exporter: the zero-retrace serve-mode bridge.

``TelemetryExporter.poll(state, tick)`` snapshots the carried stats
WITHOUT entering the jit path — pure ``np.asarray`` device reads, no
traced function is called, so a poll can never recompile the tick (the
serve loop proves this under the obs/xmeter.py sentinel).  Each poll:

- feeds the SLO tracker (obs/slo.py) one histogram snapshot;
- appends one JSON object to the append-only ``telemetry.jsonl``
  stream (tick, per-family n/p50/p95/p99 from the EXACT histograms,
  burn rates, served fraction, abort rate, alert state);
- atomically rewrites the OpenMetrics text exposition
  (``metrics.om``): one ``histogram`` family over the log buckets
  (cumulative ``_bucket{le=...}`` samples; ``_sum`` is approximated
  from bucket midpoints and documented as such — the quantiles come
  from the buckets, never from ``_sum``), burn-rate / alert gauges and
  the commit counter, ``# EOF``-terminated per the spec.

Quantiles here are derived from the histogram plane, NOT the famlat
survivor rings — the rings keep only the last ``fam_lat_samples``
commits per family and bias the tail once arrivals outrun them
(README "Live SLO & telemetry" documents the bias window).

``parse_openmetrics`` is the matching reader used by the round-trip
test and the scripts/check.sh telemetry smoke.
"""

from __future__ import annotations

import json
import os

import numpy as np

from deneva_tpu.obs import histo as obs_histo
from deneva_tpu.obs import slo as obs_slo

#: exposition metric names (the ``deneva_`` namespace)
HIST_METRIC = "deneva_latency_ticks"
BURN_METRIC = "deneva_slo_burn_rate"
ALERT_METRIC = "deneva_slo_alert_active"
COMMITS_METRIC = "deneva_commits"

JSONL_SCHEMA = 1


def _scalar(stats: dict, key: str) -> int:
    """Cumulative counter as a host int; node-stacked sharded scalars
    ((N,) arrays) sum exactly."""
    if key not in stats:
        return 0
    return int(np.asarray(stats[key]).sum())


class TelemetryExporter:
    """Host-side streaming exporter around one engine's state."""

    def __init__(self, cfg, out_dir: str, tracker=None):
        self.cfg = cfg
        self.out_dir = out_dir
        self.tracker = tracker if tracker is not None \
            else obs_slo.SloTracker(cfg)
        os.makedirs(out_dir, exist_ok=True)
        self.jsonl_path = os.path.join(out_dir, "telemetry.jsonl")
        self.om_path = os.path.join(out_dir, "metrics.om")
        self.polls = 0

    # -- the poll ------------------------------------------------------

    def poll(self, state, tick: int) -> dict:
        """Snapshot -> track -> stream.  Returns the JSONL record."""
        stats = state.stats
        fam = obs_histo._collapse(stats["arr_hist_fam"])
        counters = {k: _scalar(stats, k) for k in obs_slo.COUNTERS}
        ev = self.tracker.observe(tick, fam, counters)
        rec = {"schema": JSONL_SCHEMA, "tick": int(tick),
               "poll": self.polls,
               "commits": counters["txn_cnt"],
               "hist_total": int(fam.sum()),
               "fam": {}}
        for f in range(fam.shape[0]):
            rec["fam"][str(f)] = {
                "n": int(fam[f].sum()),
                **{f"p{p}": obs_histo.quantile(fam[f], p / 100.0)
                   for p in obs_histo.SLO_PCTS}}
        rec.update({k: ev[k] for k in ("burn_fast", "burn_slow",
                                       "served_frac", "abort_rate")})
        rec["alert_active"] = int(self.tracker.alert_active)
        if ev["fired"]:
            rec["event"] = "fire"
        elif ev["cleared"]:
            rec["event"] = "clear"
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        self._write_openmetrics(fam, rec)
        self.polls += 1
        return rec

    # -- OpenMetrics exposition ----------------------------------------

    def _write_openmetrics(self, fam: np.ndarray, rec: dict) -> None:
        lines = []
        F, bins = fam.shape
        lows = obs_histo.bucket_lows(bins)
        widths = obs_histo.bucket_widths(bins)
        highs = lows + widths - 1            # inclusive upper bounds
        lines.append(f"# TYPE {HIST_METRIC} histogram")
        lines.append(f"# UNIT {HIST_METRIC} ticks")
        lines.append(f"# HELP {HIST_METRIC} commit latency (first "
                     "start -> commit) per txn family; log buckets, "
                     "_sum approximated from bucket midpoints")
        for f in range(F):
            cum = np.cumsum(fam[f])
            last = int(np.max(np.nonzero(fam[f])[0])) \
                if fam[f].any() else 0
            for b in range(last + 1):
                lines.append(
                    f'{HIST_METRIC}_bucket{{family="{f}",'
                    f'le="{int(highs[b])}"}} {int(cum[b])}')
            n = int(fam[f].sum())
            lines.append(f'{HIST_METRIC}_bucket{{family="{f}",'
                         f'le="+Inf"}} {n}')
            lines.append(f'{HIST_METRIC}_count{{family="{f}"}} {n}')
            approx = float((fam[f] * (lows + (widths - 1) / 2)).sum())
            lines.append(f'{HIST_METRIC}_sum{{family="{f}"}} {approx:g}')
        lines.append(f"# TYPE {BURN_METRIC} gauge")
        lines.append(f'{BURN_METRIC}{{window="fast"}} '
                     f'{rec["burn_fast"]:g}')
        lines.append(f'{BURN_METRIC}{{window="slow"}} '
                     f'{rec["burn_slow"]:g}')
        lines.append(f"# TYPE {ALERT_METRIC} gauge")
        lines.append(f"{ALERT_METRIC} {rec['alert_active']}")
        lines.append(f"# TYPE {COMMITS_METRIC} counter")
        lines.append(f"{COMMITS_METRIC}_total {rec['commits']}")
        lines.append("# EOF")
        tmp = self.om_path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        os.replace(tmp, self.om_path)


# ---------------------------------------------------------------------------
# the matching reader (round-trip test + check.sh smoke)
# ---------------------------------------------------------------------------

def parse_openmetrics(text: str) -> dict:
    """Minimal OpenMetrics text parser for the exporter's own output:
    returns {"types": {name: type}, "samples": [(name, labels, value)],
    "eof": bool}."""
    types, samples, eof = {}, [], False
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line == "# EOF":
            eof = True
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(None, 3)
            types[name] = mtype
            continue
        if line.startswith("#"):
            continue
        head, _, val = line.rpartition(" ")
        labels = {}
        if "{" in head:
            name, _, lab = head.partition("{")
            for part in lab.rstrip("}").split(","):
                if not part:
                    continue
                k, _, v = part.partition("=")
                labels[k] = v.strip('"')
        else:
            name = head
        samples.append((name, labels, float(val)))
    return {"types": types, "samples": samples, "eof": eof}


def sample_value(parsed: dict, name: str, **labels):
    """First sample matching ``name`` and every given label (None when
    absent)."""
    for n, lab, v in parsed["samples"]:
        if n == name and all(lab.get(k) == str(w)
                             for k, w in labels.items()):
            return v
    return None
