"""Device-resident tick timeline (the DEBUG_TIMELINE analog, reference
config.h:269 + scripts/timeline.py).

One preallocated ``(Config.trace_ticks, K)`` int32 ring buffer rides the
scheduler's carry (inside the stats dict, so the ``lax.while_loop`` /
``fori_loop`` body threads it like every other counter).  Each tick the
engine accumulates ONE row — admissions, commits, aborts by reason,
lock-wait decisions, and the slot-status occupancy histogram — with a
single row scatter (cheap on TPU: unique index, contiguous second dim).
Ticks past the depth wrap (``t % T``) and ACCUMULATE, so column sums
always equal the whole run's totals even when the buffer is shorter than
the run; for per-tick plots pick ``trace_ticks`` >= the run length.

In ``ShardedEngine`` the stats dict is stacked over the node axis, so the
buffer is ``(N, T, K)`` and per-shard commit counts (shard imbalance) come
for free from the leading axis.

The buffer is fetched from device ONCE at run end; host-side exports:

- :func:`timeline`         named numpy series for
                           ``experiments/timeline_plot.py``;
- :func:`totals`           column sums (reconcile against ``[summary]``);
- :func:`to_chrome_trace`  Chrome trace-event JSON, loadable in Perfetto
                           (https://ui.perfetto.dev) as counter tracks.

When ``Config.trace_ticks == 0`` (default) no arrays exist and the tick
graph is bit-identical to a build without this module.
"""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np

from deneva_tpu.engine.state import (STATUS_BACKOFF, STATUS_FREE,
                                     STATUS_RUNNING, STATUS_WAITING)

#: trace row schema.  Flow columns are per-tick event counts; ``abort``
#: is the tick's total_txn_abort_cnt increment (cc aborts + validation
#: aborts), ``vabort``/``user_abort`` the reason breakdown, ``lock_wait``
#: the tick's WAIT decisions (parked continuations).  The ``occ_*``
#: columns are the end-of-tick slot-status histogram (they sum to B).
#: ``live_entries``/``compact_ovf`` are the tick's deltas of the CC
#: compaction counters (cc/base.py note_compaction): live entries seen by
#: compacted kernels and live entries spilled past the static bucket.
TRACE_COLUMNS = ("admit", "commit", "abort", "vabort", "user_abort",
                 "lock_wait", "occ_free", "occ_running", "occ_waiting",
                 "occ_backoff", "live_entries", "compact_ovf")
COL = {name: i for i, name in enumerate(TRACE_COLUMNS)}

#: columns grouped into Perfetto counter tracks
_FLOW = ("admit", "commit", "abort", "vabort", "user_abort", "lock_wait")
_OCC = ("occ_free", "occ_running", "occ_waiting", "occ_backoff")
_COMPACT = ("live_entries", "compact_ovf")

#: adaptive-controller companion ring schema (deneva_tpu/ctrl/): the
#: per-tick decision snapshot — escalated-key count, chosen width gear,
#: occupancy/hottest-bucket EWMAs (integer part), the largest per-reason
#: backoff base, and the CUMULATIVE escalation / gate-block counters
#: (monotone step counters render each decision as an edge in Perfetto).
#: Gauges, not flows: rows are meaningful per tick, so pick trace_ticks
#: >= the run length (the wrap-accumulate caveat bites harder here).
CTRL_COLUMNS = ("esc_active", "width_idx", "occ_ewma", "heat_max",
                "backoff_base_max", "escalations", "esc_blocked")

#: software-pipeline companion ring schema (Config.pipeline_exchange,
#: parallel/sharded.py): per tick, the exchange legs issued by the
#: split-exchange passes and how many of them were issued while another
#: leg of the same pass was still in flight (the double buffer keeps
#: exactly one collective outstanding, so legs - occupied_passes is the
#: overlapped count).  The Perfetto "pipeline occupancy" track and the
#: host-side ``pipeline_overlap_frac`` (bench.py / obs/regress.py) both
#: derive from these two columns.
PIPE_COLUMNS = ("pipe_legs", "pipe_overlap")

#: dependency-observatory companion ring schema (Config.depgraph,
#: obs/depgraph.py): per tick, the wait/abort EDGES appended to the
#: sampling ring (a flow column — the tick's delta of arr_dep_cnt), the
#: max wait-chain depth (pointer doubling) and the convoy width (max
#: blocker in-degree).  The depth/convoy columns are gauges under the
#: wrap-accumulate caveat of :func:`record_ctrl`.
DEP_COLUMNS = ("dep_edges", "dep_depth", "dep_convoy")


def init_trace(cfg, lat_samples: int) -> dict:
    """Stats-dict entries for the timeline; empty when tracing is off
    (the disabled path carries nothing)."""
    if cfg.trace_ticks <= 0:
        return {}
    out = {
        "arr_trace": jnp.zeros((cfg.trace_ticks, len(TRACE_COLUMNS)),
                               jnp.int32),
        # lifetime companion ring: commit-latency samples also record
        # their start tick so recent txn lifetimes can be drawn
        # (record_commit_latency fills it; timeline_plot.py reads it)
        "arr_lat_start": jnp.zeros(lat_samples, jnp.int32),
    }
    if cfg.abort_attribution:
        # companion per-reason ring (one column per cc/base.py
        # ABORT_REASONS code) kept SEPARATE from arr_trace so the
        # TRACE_COLUMNS schema — and every consumer of it — is unchanged
        # when attribution is off; arr_reason_tick is the tick-local
        # accumulator the scheduler's note_aborts fills
        from deneva_tpu.cc.base import ABORT_REASONS
        n = len(ABORT_REASONS)
        out["arr_reason_trace"] = jnp.zeros((cfg.trace_ticks, n),
                                            jnp.int32)
        out["arr_reason_tick"] = jnp.zeros(n, jnp.int32)
    if cfg.arrival is not None:
        # admission-queue depth companion ring (one column), same
        # SEPARATE-array discipline as the reason ring: TRACE_COLUMNS —
        # and every consumer of it — is unchanged for closed-loop runs
        out["arr_queue_trace"] = jnp.zeros(cfg.trace_ticks, jnp.int32)
    if cfg.adaptive:
        # controller-decision companion ring, same SEPARATE-array
        # discipline: non-adaptive traces carry nothing extra
        out["arr_ctrl_trace"] = jnp.zeros(
            (cfg.trace_ticks, len(CTRL_COLUMNS)), jnp.int32)
    if cfg.depgraph:
        # dependency-observatory companion ring, same SEPARATE-array
        # discipline: non-depgraph traces carry nothing extra
        out["arr_dep_trace"] = jnp.zeros(
            (cfg.trace_ticks, len(DEP_COLUMNS)), jnp.int32)
    return out


def record_tick(stats: dict, t, status, *, admit, commit, abort, vabort,
                user_abort, lock_wait, live_entries=0,
                compact_ovf=0) -> dict:
    """Accumulate this tick's row (device side; no-op unless the buffer
    exists).  NOT warmup-gated — the timeline shows warmup dynamics too,
    so column sums match the warmup-gated [summary] counters exactly only
    when ``warmup_ticks == 0``."""
    if "arr_trace" not in stats:
        return stats
    buf = stats["arr_trace"]
    occ = [jnp.sum((status == s).astype(jnp.int32))
           for s in (STATUS_FREE, STATUS_RUNNING, STATUS_WAITING,
                     STATUS_BACKOFF)]
    row = jnp.stack([jnp.asarray(v, jnp.int32) for v in
                     (admit, commit, abort, vabort, user_abort, lock_wait)]
                    + occ
                    + [jnp.asarray(v, jnp.int32)
                       for v in (live_entries, compact_ovf)])
    return {**stats,
            "arr_trace": buf.at[t % buf.shape[0]].add(
                row, unique_indices=True)}


def record_reasons(stats: dict, t) -> dict:
    """Accumulate the tick's per-reason abort histogram (filled into
    ``arr_reason_tick`` by engine/scheduler.py note_aborts) into the
    reason ring.  Same wrap-and-accumulate discipline — and the same
    warmup caveat — as :func:`record_tick`; no-op unless the run traces
    with ``Config.abort_attribution``."""
    if "arr_reason_trace" not in stats:
        return stats
    buf = stats["arr_reason_trace"]
    return {**stats,
            "arr_reason_trace": buf.at[t % buf.shape[0]].add(
                stats["arr_reason_tick"], unique_indices=True)}


def record_queue(stats: dict, t) -> dict:
    """Accumulate the end-of-admission backlog (``queue_len``,
    deneva_tpu/traffic/) into the queue-depth ring.  Same
    wrap-and-accumulate discipline as :func:`record_tick`, so the ring
    sum equals the whole run's backlog integral (the UNGATED
    ``lat_work_queue_time`` when ``warmup_ticks == 0``); no-op unless
    the run traces with an arrival model."""
    if "arr_queue_trace" not in stats:
        return stats
    buf = stats["arr_queue_trace"]
    return {**stats,
            "arr_queue_trace": buf.at[t % buf.shape[0]].add(
                stats["queue_len"], unique_indices=True)}


def record_ctrl(stats: dict, t) -> dict:
    """Record the adaptive controller's end-of-tick decision snapshot
    (engine/scheduler.py calls this AFTER ctrl.update, so the row is the
    state the NEXT tick will act under).  Same wrap-and-accumulate
    discipline as :func:`record_tick`; no-op unless the run traces with
    ``Config.adaptive``."""
    if "arr_ctrl_trace" not in stats:
        return stats
    from deneva_tpu.cc.base import ABORT_REASONS
    from deneva_tpu.ctrl import CTRL_SCALE
    buf = stats["arr_ctrl_trace"]
    row = jnp.stack([
        stats["ctrl_esc_active"],
        stats["ctrl_width_idx"],
        stats["ctrl_occ_ewma"] >> CTRL_SCALE,
        jnp.max(stats["arr_ctrl_heat"]) >> CTRL_SCALE,
        jnp.max(jnp.stack([stats[f"ctrl_base_{n}"]
                           for n in ABORT_REASONS])),
        stats["ctrl_escalate_cnt"],
        stats["ctrl_esc_block_cnt"],
    ]).astype(jnp.int32)
    return {**stats,
            "arr_ctrl_trace": buf.at[t % buf.shape[0]].add(
                row, unique_indices=True)}


def record_pipe(stats: dict, t, legs, lapped) -> dict:
    """Accumulate the tick's pipeline-occupancy row — issued exchange
    legs and legs issued with another leg of the same pass in flight
    (parallel/sharded.py computes both from the occupied sub-round
    counts).  Same wrap-and-accumulate discipline — and the same warmup
    caveat — as :func:`record_tick`; no-op unless the run traces with
    ``Config.pipeline_exchange`` on the split path."""
    if "arr_pipe_trace" not in stats:
        return stats
    buf = stats["arr_pipe_trace"]
    row = jnp.stack([jnp.asarray(legs, jnp.int32),
                     jnp.asarray(lapped, jnp.int32)])
    return {**stats,
            "arr_pipe_trace": buf.at[t % buf.shape[0]].add(
                row, unique_indices=True)}


def record_dep(stats: dict, t, edges, depth, convoy) -> dict:
    """Accumulate the tick's dependency-observatory row — edges latched
    into the sampling ring this tick, the max wait-chain depth and the
    convoy width (engine/scheduler.py computes all three from
    obs/depgraph.py tick_planes).  Same wrap-and-accumulate discipline —
    and the same warmup caveat — as :func:`record_tick`; no-op unless
    the run traces with ``Config.depgraph``."""
    if "arr_dep_trace" not in stats:
        return stats
    buf = stats["arr_dep_trace"]
    row = jnp.stack([jnp.asarray(edges, jnp.int32),
                     jnp.asarray(depth, jnp.int32),
                     jnp.asarray(convoy, jnp.int32)])
    return {**stats,
            "arr_dep_trace": buf.at[t % buf.shape[0]].add(
                row, unique_indices=True)}


def record_slo(cfg, stats: dict, t) -> dict:
    """Record the SLO plane's per-family device-side gauges — the
    bucket-low p99 estimate (ticks) and the CUMULATIVE error-budget
    burn rate x1000 (obs/histo.py fixed point) — into the SLO ring
    (columns ``[p99_f0..p99_fF-1, burn_f0..burn_fF-1]``).  Gauges under
    the same wrap-and-accumulate discipline (and caveat) as
    :func:`record_ctrl`; the bucket lows and the over-ceiling mask are
    baked trace constants, so the series costs zero recompiles.  No-op
    unless the run traces with ``Config.slo``."""
    if "arr_slo_trace" not in stats:
        return stats
    from deneva_tpu.obs import histo as obs_histo
    buf = stats["arr_slo_trace"]
    fam = stats["arr_hist_fam"]
    F, bins = fam.shape
    lows_np = obs_histo.bucket_lows(bins)
    lows = jnp.asarray(lows_np, jnp.int32)
    over = jnp.asarray((lows_np > cfg.slo_p99_ceiling).astype(np.int32))
    budget = 1.0 - cfg.slo_target
    row = jnp.stack(
        [obs_histo.device_quantile(fam[f], lows, 0.99) for f in range(F)]
        + [obs_histo.device_burn_milli(fam[f], over, budget)
           for f in range(F)]).astype(jnp.int32)
    return {**stats,
            "arr_slo_trace": buf.at[t % buf.shape[0]].add(
                row, unique_indices=True)}


def _buffer(state_or_stats) -> np.ndarray:
    stats = getattr(state_or_stats, "stats", state_or_stats)
    assert "arr_trace" in stats, "run with Config.trace_ticks > 0"
    return np.asarray(stats["arr_trace"])


def _reason_buffer(state_or_stats) -> np.ndarray | None:
    stats = getattr(state_or_stats, "stats", state_or_stats)
    if "arr_reason_trace" not in stats:
        return None
    return np.asarray(stats["arr_reason_trace"])


def _queue_buffer(state_or_stats) -> np.ndarray | None:
    stats = getattr(state_or_stats, "stats", state_or_stats)
    if "arr_queue_trace" not in stats:
        return None
    return np.asarray(stats["arr_queue_trace"])


def _mesh_buffer(state_or_stats) -> np.ndarray | None:
    stats = getattr(state_or_stats, "stats", state_or_stats)
    if "arr_mesh_trace" not in stats:
        return None
    return np.asarray(stats["arr_mesh_trace"])


def _ctrl_buffer(state_or_stats) -> np.ndarray | None:
    stats = getattr(state_or_stats, "stats", state_or_stats)
    if "arr_ctrl_trace" not in stats:
        return None
    return np.asarray(stats["arr_ctrl_trace"])


def _pipe_buffer(state_or_stats) -> np.ndarray | None:
    stats = getattr(state_or_stats, "stats", state_or_stats)
    if "arr_pipe_trace" not in stats:
        return None
    return np.asarray(stats["arr_pipe_trace"])


def _dep_buffer(state_or_stats) -> np.ndarray | None:
    stats = getattr(state_or_stats, "stats", state_or_stats)
    if "arr_dep_trace" not in stats:
        return None
    return np.asarray(stats["arr_dep_trace"])


def _slo_buffer(state_or_stats) -> np.ndarray | None:
    stats = getattr(state_or_stats, "stats", state_or_stats)
    if "arr_slo_trace" not in stats:
        return None
    return np.asarray(stats["arr_slo_trace"])


def _slo_names(n_cols: int) -> tuple:
    """Series names for the (T, 2F) SLO ring: p99 gauges then burn
    gauges, one per family (``slo_f{f}_p99`` / ``slo_f{f}_burn``)."""
    F = n_cols // 2
    return tuple([f"slo_f{f}_p99" for f in range(F)]
                 + [f"slo_f{f}_burn" for f in range(F)])


def _reason_names() -> tuple:
    from deneva_tpu.cc.base import ABORT_REASONS
    return tuple(f"abort_{name}" for name in ABORT_REASONS)


def timeline(state_or_stats, per_shard: bool = False) -> dict:
    """Named numpy series, one ``(T,)`` array per column (sharded buffers
    sum the node axis for the cluster-wide view unless ``per_shard``,
    which keeps them ``(N, T)``).  Runs traced with
    ``Config.abort_attribution`` additionally carry one ``abort_<reason>``
    series per registered reason code; mesh-observatory runs
    (``Config.mesh`` with tracing) one ``mesh_tx_to<j>`` series per
    destination node (messages shipped toward node j that tick)."""
    a = _buffer(state_or_stats)
    r = _reason_buffer(state_or_stats)
    q = _queue_buffer(state_or_stats)
    m = _mesh_buffer(state_or_stats)      # stacked: (N, trace_ticks, N)
    c = _ctrl_buffer(state_or_stats)
    sl = _slo_buffer(state_or_stats)
    p = _pipe_buffer(state_or_stats)
    d = _dep_buffer(state_or_stats)
    if a.ndim == 3 and not per_shard:
        a = a.sum(axis=0)
        r = r.sum(axis=0) if r is not None else None
        q = q.sum(axis=0) if q is not None else None
        m = m.sum(axis=0) if m is not None else None
        c = c.sum(axis=0) if c is not None else None
        sl = sl.sum(axis=0) if sl is not None else None
        p = p.sum(axis=0) if p is not None else None
        # depth/convoy are gauges, not flows — the cluster-wide view
        # takes the max over shards (edges column sums would be the
        # flow-correct merge, but a mixed reduce per column buys little;
        # max keeps "worst chain anywhere" which is the question asked)
        d = d.max(axis=0) if d is not None else None
    if a.ndim == 3:
        out = {name: a[:, :, i] for i, name in enumerate(TRACE_COLUMNS)}
        if r is not None:
            out.update({name: r[:, :, i]
                        for i, name in enumerate(_reason_names())})
        if q is not None:
            out["queue_depth"] = q
        if m is not None:
            out.update({f"mesh_tx_to{j}": m[:, :, j]
                        for j in range(m.shape[-1])})
        if c is not None:
            out.update({f"ctrl_{name}": c[:, :, i]
                        for i, name in enumerate(CTRL_COLUMNS)})
        if sl is not None:
            out.update({name: sl[:, :, i] for i, name
                        in enumerate(_slo_names(sl.shape[-1]))})
        if p is not None:
            out.update({name: p[:, :, i]
                        for i, name in enumerate(PIPE_COLUMNS)})
        if d is not None:
            out.update({name: d[:, :, i]
                        for i, name in enumerate(DEP_COLUMNS)})
        return out
    out = {name: a[:, i] for i, name in enumerate(TRACE_COLUMNS)}
    if r is not None:
        out.update({name: r[:, i]
                    for i, name in enumerate(_reason_names())})
    if q is not None:
        out["queue_depth"] = q
    if m is not None:
        out.update({f"mesh_tx_to{j}": m[:, j] for j in range(m.shape[-1])})
    if c is not None:
        out.update({f"ctrl_{name}": c[:, i]
                    for i, name in enumerate(CTRL_COLUMNS)})
    if sl is not None:
        out.update({name: sl[:, i] for i, name
                    in enumerate(_slo_names(sl.shape[-1]))})
    if p is not None:
        out.update({name: p[:, i] for i, name in enumerate(PIPE_COLUMNS)})
    if d is not None:
        out.update({name: d[:, i] for i, name in enumerate(DEP_COLUMNS)})
    return out


def totals(state_or_stats) -> dict:
    """Whole-run column sums (occupancy columns integrate to
    slot-ticks).  These reconcile exactly with the [summary] counters
    commits/aborts/admissions when ``warmup_ticks == 0``."""
    a = _buffer(state_or_stats)
    flat = a.reshape(-1, a.shape[-1]).sum(axis=0)
    out = {name: int(flat[i]) for i, name in enumerate(TRACE_COLUMNS)}
    r = _reason_buffer(state_or_stats)
    if r is not None:
        rflat = r.reshape(-1, r.shape[-1]).sum(axis=0)
        out.update({name: int(rflat[i])
                    for i, name in enumerate(_reason_names())})
    q = _queue_buffer(state_or_stats)
    if q is not None:
        # backlog integral (txn-ticks queued behind admission); equals
        # the ungated lat_work_queue_time when warmup_ticks == 0
        out["queue_depth"] = int(q.sum())
    return out


def to_chrome_trace(state_or_stats, path: str, n_ticks: int | None = None,
                    tick_us: float = 1.0,
                    xmeter: dict | None = None,
                    flight: dict | None = None,
                    windows: dict | None = None,
                    depgraph: dict | None = None) -> str:
    """Export the timeline as Chrome trace-event JSON (the JSON Array
    Format with counter events, loadable at ui.perfetto.dev).

    One process per shard; two counter tracks per shard (txn flow and
    slot occupancy).  ``tick_us`` maps one scheduler tick onto the trace
    timebase (pass the measured mean tick microseconds for wall-true
    plots; the default keeps tick units).  ``xmeter`` (an obs/xmeter.py
    ``XMeter.snapshot()``) adds a 5th counter track, "kernel ms": the
    metered per-call blocked durations of every jitted entry point,
    indexed by call number on the same timebase.  ``flight`` (an
    obs/flight.py ``snapshot()``) adds the per-txn SPAN track beside the
    counter tracks: one duration slice per sampled txn lifecycle with
    nested per-attempt child slices and abort-reason flow arrows.
    ``windows`` (an obs/windows.py ``snapshot()`` or a run record's
    ``"windows"`` block) adds the 11th counter track, "window deltas":
    one cluster-wide counter per snapshot column, stepping at each
    window boundary by that window's delta — the coarse causal view
    (which phase of the run moved which counter) beside the per-tick
    rows, derived host-side so the device plane stays two rings.
    ``depgraph`` (an obs/depgraph.py ``snapshot()`` or a run record's
    ``"depgraph"`` block) adds blocker→waiter flow arrows from the
    sampled wait-for edges; runs traced with ``Config.depgraph`` also
    carry the 12th counter track, "chain depth" (per-tick sampled edges,
    max wait-chain depth, convoy width).  Depgraph flow ids are strings
    (``"dep<n>"``), disjoint by type from the flight track's integer
    flow ids, so the two arrow families merge into one export without
    Perfetto uniting unrelated arrows."""
    a = _buffer(state_or_stats)
    shards = a[None] if a.ndim == 2 else a          # (N, T, K)
    rbuf = _reason_buffer(state_or_stats)
    rshards = None
    if rbuf is not None:
        rshards = rbuf[None] if rbuf.ndim == 2 else rbuf
    qbuf = _queue_buffer(state_or_stats)
    qshards = None
    if qbuf is not None:
        qshards = qbuf[None] if qbuf.ndim == 1 else qbuf
    mbuf = _mesh_buffer(state_or_stats)
    mshards = None
    if mbuf is not None:
        mshards = mbuf[None] if mbuf.ndim == 2 else mbuf
    cbuf = _ctrl_buffer(state_or_stats)
    cshards = None
    if cbuf is not None:
        cshards = cbuf[None] if cbuf.ndim == 2 else cbuf
    sbuf = _slo_buffer(state_or_stats)
    sshards = None
    if sbuf is not None:
        sshards = sbuf[None] if sbuf.ndim == 2 else sbuf
    pbuf = _pipe_buffer(state_or_stats)
    pshards = None
    if pbuf is not None:
        pshards = pbuf[None] if pbuf.ndim == 2 else pbuf
    dbuf = _dep_buffer(state_or_stats)
    dshards = None
    if dbuf is not None:
        dshards = dbuf[None] if dbuf.ndim == 2 else dbuf
    rnames = _reason_names()
    N, T, _ = shards.shape
    if n_ticks is not None:
        T = min(T, int(n_ticks))
    events = []
    for node in range(N):
        events.append({"name": "process_name", "ph": "M", "pid": node,
                       "tid": 0,
                       "args": {"name": f"shard{node}" if N > 1
                                else "engine"}})
        buf = shards[node]
        for t in range(T):
            ts = float(t) * tick_us
            events.append({"name": "txn flow", "ph": "C", "ts": ts,
                           "pid": node,
                           "args": {c: int(buf[t, COL[c]])
                                    for c in _FLOW}})
            events.append({"name": "slot occupancy", "ph": "C", "ts": ts,
                           "pid": node,
                           "args": {c: int(buf[t, COL[c]])
                                    for c in _OCC}})
            events.append({"name": "compaction", "ph": "C", "ts": ts,
                           "pid": node,
                           "args": {c: int(buf[t, COL[c]])
                                    for c in _COMPACT}})
            if rshards is not None:
                # 4th counter track, present only for attribution runs
                # (the 3-track schema above is a compatibility contract)
                events.append({"name": "abort reasons", "ph": "C",
                               "ts": ts, "pid": node,
                               "args": {c: int(rshards[node][t, i])
                                        for i, c in enumerate(rnames)}})
            if qshards is not None:
                # 6th counter track (same conditional discipline): the
                # admission-queue depth of open-system (arrival) runs
                events.append({"name": "admission queue", "ph": "C",
                               "ts": ts, "pid": node,
                               "args": {"queue_depth":
                                        int(qshards[node][t])}})
            if mshards is not None:
                # 7th counter track (same conditional discipline): per
                # node-pair traffic of mesh-observatory runs — one
                # counter per destination node, this shard's outbound
                # messages toward it that tick
                events.append({"name": "mesh traffic", "ph": "C",
                               "ts": ts, "pid": node,
                               "args": {f"to{j}":
                                        int(mshards[node][t, j])
                                        for j in
                                        range(mshards.shape[-1])}})
            if cshards is not None:
                # 8th counter track (same conditional discipline): the
                # adaptive controller's per-tick decisions — escalated
                # keys, width gear, backoff level, cumulative
                # escalation/gate-block edges (CTRL_COLUMNS)
                events.append({"name": "controller decisions", "ph": "C",
                               "ts": ts, "pid": node,
                               "args": {c: int(cshards[node][t, i])
                                        for i, c in
                                        enumerate(CTRL_COLUMNS)}})
            if sshards is not None:
                # 9th counter track (same conditional discipline): the
                # SLO plane's per-family p99 estimate (ticks) and
                # cumulative burn-rate x1000 gauges (Config.slo with
                # tracing; obs/histo.py)
                events.append({"name": "slo burn rate", "ph": "C",
                               "ts": ts, "pid": node,
                               "args": {c: int(sshards[node][t, i])
                                        for i, c in enumerate(
                                            _slo_names(
                                                sshards.shape[-1]))}})
            if pshards is not None:
                # 10th counter track (same conditional discipline):
                # the split exchange's software-pipeline occupancy —
                # issued collective legs vs legs issued with another
                # leg in flight (Config.pipeline_exchange with tracing;
                # parallel/sharded.py)
                events.append({"name": "pipeline occupancy", "ph": "C",
                               "ts": ts, "pid": node,
                               "args": {c: int(pshards[node][t, i])
                                        for i, c in
                                        enumerate(PIPE_COLUMNS)}})
            if dshards is not None:
                # 12th counter track (same conditional discipline): the
                # dependency observatory's per-tick planes — sampled
                # wait/abort edges, max wait-chain depth (pointer
                # doubling) and convoy width (Config.depgraph with
                # tracing; obs/depgraph.py)
                events.append({"name": "chain depth", "ph": "C",
                               "ts": ts, "pid": node,
                               "args": {c: int(dshards[node][t, i])
                                        for i, c in
                                        enumerate(DEP_COLUMNS)}})
    xentries = []
    if xmeter:
        # 5th counter track, present only when an xmeter snapshot is
        # passed (same compatibility discipline as the 4th): one "kernel
        # ms" counter per entry point, its per-call blocked dispatch
        # durations indexed by call number on the shared timebase.
        for name, ent in sorted(xmeter.get("entries", {}).items()):
            durs = ent.get("durations_ms") or []
            if not durs:
                continue
            xentries.append(name)
            for i, ms in enumerate(durs):
                events.append({"name": "kernel ms", "ph": "C",
                               "ts": float(i) * tick_us, "pid": 0,
                               "args": {name: float(ms)}})
    wcols = []
    if windows:
        # 11th counter track (same conditional discipline): per-window
        # counter DELTAS at the window-boundary ticks, host-derived from
        # the obs/windows.py keep-last ring (snapshot dict or the JSON
        # "windows" record block — both carry cols_i/ring_i/cnt/slots).
        # A wrapped ring is skipped, not guessed at: lossy deltas would
        # draw a lie.
        wring = np.asarray(windows["ring_i"], np.int64)
        wv = min(int(windows["cnt"]), int(windows["slots"]),
                 wring.shape[0])
        if int(windows["cnt"]) <= int(windows["slots"]) and wv > 0:
            cols = list(windows["cols_i"])
            ti = cols.index("tick")
            wd = np.diff(wring[:wv], axis=0,
                         prepend=np.zeros((1, wring.shape[1]), np.int64))
            wcols = [c for c in cols if c != "tick"]
            for w in range(wv):
                events.append(
                    {"name": "window deltas", "ph": "C",
                     "ts": float(wring[w, ti]) * tick_us, "pid": 0,
                     "args": {c: int(wd[w, j])
                              for j, c in enumerate(cols) if j != ti}})
    n_spans = 0
    if flight:
        # per-txn span track (same conditional discipline as the other
        # optional tracks): obs/flight.py renders its own Perfetto
        # duration/flow events on the shared tick_us timebase — the
        # sampled lifecycles line up under the counter rows above
        from deneva_tpu.obs import flight as obs_flight
        events.extend(obs_flight.span_events(flight, tick_us=tick_us))
        n_spans = len(flight.get("spans", ()))
    n_dep_flows = 0
    if depgraph:
        # blocker→waiter flow arrows from the sampled wait-for graph
        # (string flow ids — see docstring; obs/export.py relies on the
        # int/str split when it re-keys flows across merged runs)
        from deneva_tpu.obs import depgraph as obs_depgraph
        dep_flows = obs_depgraph.flow_events(depgraph, tick_us=tick_us)
        events.extend(dep_flows)
        n_dep_flows = len(dep_flows) // 2
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "metadata": {"tool": "deneva_tpu.obs.trace",
                        "columns": list(TRACE_COLUMNS),
                        "tick_us": tick_us, "shards": N, "ticks": T}}
    if rshards is not None:
        doc["metadata"]["reason_columns"] = list(rnames)
    if qshards is not None:
        doc["metadata"]["queue_track"] = True
    if mshards is not None:
        doc["metadata"]["mesh_track_nodes"] = int(mshards.shape[-1])
    if cshards is not None:
        doc["metadata"]["ctrl_track"] = list(CTRL_COLUMNS)
    if sshards is not None:
        doc["metadata"]["slo_track"] = list(_slo_names(sshards.shape[-1]))
    if pshards is not None:
        doc["metadata"]["pipe_track"] = list(PIPE_COLUMNS)
    if dshards is not None:
        doc["metadata"]["dep_track"] = list(DEP_COLUMNS)
    if depgraph:
        doc["metadata"]["dep_flows"] = n_dep_flows
    if wcols:
        doc["metadata"]["window_track"] = wcols
    if xentries:
        doc["metadata"]["xmeter_entries"] = xentries
    if flight:
        doc["metadata"]["flight_spans"] = n_spans
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
