"""Differential run comparator: the host half of causal diagnosis.

``python -m deneva_tpu.obs.diff runA.json runB.json`` takes two run
records (obs/profiler.py write_run_record) and answers the question
every hand-derived finding so far had to answer by staring at raw
counters: *what changed, and which knob moves it*.  It computes an
exact delta decomposition of the throughput/latency change over the
identity vocabulary the observatories already reconcile — per-commit
``lat_*`` phase costs, the abort taxonomy mix, remote amplification
(the bench scaling grid's ``remote_entry_cnt / (txn_cnt *
req_per_query)``), queue backlog, error-budget burn, shard imbalance,
controller escalation churn, exchange occupancy, compile/footprint
shifts from the xmeter extras — ranks the causes by normalized
contribution, and maps each ranked cause to the config lever that
moves it (``remote_cache``, ``compact_auto``, ``fused_arbitrate``,
``adaptive``, ``exchange_split``, ``pipeline_exchange``).

With ``--windows`` (one record carrying the obs/windows.py snapshot
plane) the same comparator runs WITHIN a run: the window deltas split
at ``--split-tick`` (default: midpoint) into two phase summaries —
pre/post a hot-set shift, a rate step, a fault injection, or an
adaptive gear change — and the early phase diffs against the late one.

Output: a ``[diagnosis]`` section (also rendered by obs/report.py when
a report carries one) plus a JSON artifact (``-o``).  The regress gate
(obs/regress.py) calls :func:`diagnose_entries` on every failure, so
CI regressions arrive pre-triaged with the same ranked-cause format.

Scoring: each cause is a run-length-normalized rate (per commit, per
tick, or a share), so A and B compare across different run lengths;
the score is ``|b - a| / (|a| + |b| + tau)`` with a per-cause noise
floor ``tau`` — a relative-change measure in [0, 1) that ranks a
0 -> 8.4 amplification blow-up above a 0.98 -> 0.99 imbalance wiggle.
"""

from __future__ import annotations

import json

import numpy as np

#: per-cause noise floors (tau): the magnitude below which a relative
#: change is treated as noise rather than signal
TAU_SHARE = 0.05      # shares / rates in [0, 1]
TAU_TICKS = 2.0       # per-commit tick costs
TAU_RATIO = 0.25      # open-ended ratios (amplification, burn)


def _g(s: dict, k: str, default: float = 0.0) -> float:
    try:
        return float(s.get(k, default))
    except (TypeError, ValueError):
        return default


def _per_commit(s: dict, k: str) -> float:
    return _g(s, k) / max(_g(s, "txn_cnt"), 1.0)


def _per_tick(s: dict, k: str) -> float:
    return _g(s, k) / max(_g(s, "measured_ticks"), 1.0)


def _outcomes(s: dict) -> float:
    return max(_g(s, "txn_cnt") + _g(s, "total_txn_abort_cnt"), 1.0)


def amplification(s: dict, cfg: dict) -> float:
    """Remote entries shipped per requested access — bench.py's scaling
    grid ``remote_ratio`` (gated inverted by obs/regress.py)."""
    req = float(cfg.get("req_per_query", 1) or 1)
    return _g(s, "remote_entry_cnt") / max(_g(s, "txn_cnt") * req, 1.0)


def _reason_lever(name: str) -> tuple:
    """Config lever for one abort-taxonomy reason, by reason family."""
    if "compact" in name or "spill" in name:
        return ("compact_auto", "compaction pressure: widen the lane "
                "budget or pin compact_lanes")
    if "route" in name or "overflow" in name:
        return ("exchange_split", "exchange lane overflow: split the "
                "exchange into capacity-bounded sub-rounds")
    return ("adaptive", "conflict churn: let the controller tune "
            "backoff / escalate the hot keys")


#: static cause registry: (name, lever, description, extractor, tau,
#: higher_is_better).  Extractors see (summary, config_dict); a cause
#: joins a diff only when one of its keys is present in either summary
#: (key, tested via the probe field).
_CAUSES = (
    ("lat_process_per_commit", "fused_arbitrate",
     "execute-phase compute per commit (sort/arbitration bound: fuse "
     "the VMEM kernel, or compact the entry lanes)",
     lambda s, c: _per_commit(s, "lat_process_time"),
     "lat_process_time", TAU_TICKS, False),
    ("lat_cc_block_per_commit", "adaptive",
     "lock-wait ticks per commit (contention stall: controller backoff "
     "/ escalation tuning)",
     lambda s, c: _per_commit(s, "lat_cc_block_time"),
     "lat_cc_block_time", TAU_TICKS, False),
    ("lat_abort_backoff_per_commit", "adaptive",
     "abort-backoff ticks per commit (restart churn: controller "
     "backoff tuning)",
     lambda s, c: _per_commit(s, "lat_abort_time"),
     "lat_abort_time", TAU_TICKS, False),
    ("lat_network_per_commit", "remote_cache",
     "remote-shipment ticks per commit (coordination cost: cache "
     "remote grants to suppress re-ships)",
     lambda s, c: _per_commit(s, "lat_network_time"),
     "lat_network_time", TAU_TICKS, False),
    ("abort_rate", "adaptive",
     "aborts per outcome (wasted work share)",
     lambda s, c: _g(s, "total_txn_abort_cnt") / _outcomes(s),
     "total_txn_abort_cnt", TAU_SHARE, False),
    ("remote_amplification", "remote_cache",
     "remote entries shipped per requested access (the PR 9 flat-MAAT "
     "cause: restart-driven re-shipment — cache remote grants)",
     amplification, "remote_entry_cnt", TAU_RATIO, False),
    ("reship_suppression", "remote_cache",
     "re-ships suppressed per remote attempt (cache effectiveness)",
     lambda s, c: _g(s, "reship_suppressed_cnt")
     / max(_g(s, "remote_attempt_cnt"), 1.0),
     "&remote_attempt_cnt", TAU_SHARE, True),
    ("queue_backlog_per_tick", "adaptive",
     "admission backlog left per measured tick (offered load above the "
     "service knee)",
     lambda s, c: _per_tick(s, "queue_len"),
     "queue_len", TAU_RATIO, False),
    ("burn_fast", "adaptive",
     "fast-window error-budget burn rate (SLO pressure)",
     lambda s, c: _g(s, "burn_fast"), "burn_fast", TAU_RATIO, False),
    ("imbalance", "exchange_split",
     "1 - Jain fairness over per-node commit loads (shard skew); the "
     "wide noise floor keeps a 0.98 -> 0.99 Jain wiggle out of the "
     "ranking",
     lambda s, c: 1.0 - _g(s, "imb_jain", 1.0),
     "imb_jain", TAU_RATIO, False),
    ("straggler_per_tick", "pipeline_exchange",
     "straggler ticks per measured tick (nodes idling on the slowest "
     "exchange leg: overlap the sub-rounds)",
     lambda s, c: _per_tick(s, "straggler_tick_cnt"),
     "straggler_tick_cnt", TAU_SHARE, False),
    ("ctrl_escalations_per_commit", "adaptive",
     "hot-key escalations per commit (the PR 13 hot-cell cause: "
     "saturated-hot-set escalation serializing the batch)",
     lambda s, c: _per_commit(s, "ctrl_escalate_cnt"),
     "ctrl_escalate_cnt", TAU_SHARE, False),
    ("ctrl_gate_stalls_per_commit", "adaptive",
     "serialization-gate stalls per commit (escalated keys queueing "
     "behind the gate)",
     lambda s, c: _per_commit(s, "ctrl_esc_block_cnt"),
     "ctrl_esc_block_cnt", TAU_SHARE, False),
    ("exchange_rounds_per_tick", "exchange_split",
     "occupied exchange sub-rounds per measured tick (split-exchange "
     "serialization depth)",
     lambda s, c: _per_tick(s, "exchange_round_cnt"),
     "exchange_round_cnt", TAU_RATIO, False),
    ("pipeline_overlap_frac", "pipeline_exchange",
     "overlapped exchange legs per issued leg (software-pipeline "
     "occupancy — higher is better)",
     lambda s, c: _g(s, "pipe_overlap_cnt")
     / max(_g(s, "pipe_leg_cnt"), 1.0),
     "&pipe_leg_cnt", TAU_SHARE, True),
    ("compile_cnt", "fused_arbitrate",
     "XLA compiles over the run (xmeter: recompile churn eats "
     "wall-clock, not schedule ticks)",
     lambda s, c: _g(s, "compile_cnt"), "compile_cnt", TAU_RATIO, False),
    ("hbm_gib", "compact_auto",
     "resident HBM footprint, GiB (xmeter ledger: compact the entry "
     "lanes to shrink the carry)",
     lambda s, c: _g(s, "hbm_bytes") / 2**30,
     "hbm_bytes", TAU_SHARE, False),
)


def _score(a: float, b: float, tau: float) -> float:
    return abs(b - a) / (abs(a) + abs(b) + tau)


def diff_summaries(sa: dict, sb: dict, cfg_a: dict | None = None,
                   cfg_b: dict | None = None,
                   label_a: str = "A", label_b: str = "B") -> dict:
    """The diagnosis dict: outcome deltas + causes ranked by score.
    A cause rides only when its probe key is present in either summary
    (an absent plane reads as 0 on the side missing it)."""
    cfg_a, cfg_b = cfg_a or {}, cfg_b or {}
    tput_a = _g(sa, "txn_cnt") / max(_g(sa, "measured_ticks"), 1.0)
    tput_b = _g(sb, "txn_cnt") / max(_g(sb, "measured_ticks"), 1.0)
    lat_a = _g(sa, "txn_total_time_ticks") / max(_g(sa, "txn_cnt"), 1.0)
    lat_b = _g(sb, "txn_total_time_ticks") / max(_g(sb, "txn_cnt"), 1.0)
    causes = []

    def add(name, lever, desc, va, vb, tau, good):
        sc = _score(va, vb, tau)
        worse = (vb < va) if good else (vb > va)
        causes.append({"cause": name, "lever": lever, "desc": desc,
                       "a": va, "b": vb, "delta": vb - va,
                       "score": sc, "regressing": bool(worse and sc > 0)})

    for name, lever, desc, fn, probe, tau, good in _CAUSES:
        if probe.startswith("&"):
            # effectiveness ratios of an opt-in mechanism (suppression,
            # overlap) join only when BOTH runs carry the plane — when
            # one side lacks the mechanism, "effectiveness fell to 0"
            # merely restates the config delta and would mask the
            # behavioral cause (e.g. amplification) behind it
            if probe[1:] not in sa or probe[1:] not in sb:
                continue
        elif probe not in sa and probe not in sb:
            continue
        add(name, lever, desc, fn(sa, cfg_a), fn(sb, cfg_b), tau, good)
    # dynamic per-reason abort-taxonomy causes (cc/base.py registry keys
    # present on attributed runs), as shares of all outcomes
    reasons = sorted({k for k in (*sa, *sb)
                      if k.startswith("abort_") and k.endswith("_cnt")})
    for k in reasons:
        name = k[len("abort_"):-len("_cnt")]
        lever, why = _reason_lever(name)
        add(f"abort_mix[{name}]", lever,
            f"'{name}' aborts per outcome ({why})",
            _g(sa, k) / _outcomes(sa), _g(sb, k) / _outcomes(sb),
            TAU_SHARE, False)
    causes.sort(key=lambda c: -c["score"])
    ranked = [c for c in causes if c["score"] > 0.0]
    return {"kind": "run_diff", "a": label_a, "b": label_b,
            "tput_a": tput_a, "tput_b": tput_b,
            "tput_ratio": tput_b / max(tput_a, 1e-9),
            "latency_a": lat_a, "latency_b": lat_b,
            "causes": ranked,
            "top_cause": ranked[0]["cause"] if ranked else None,
            "top_lever": ranked[0]["lever"] if ranked else None}


def diff_records(rec_a: dict, rec_b: dict,
                 label_a: str = "A", label_b: str = "B") -> dict:
    """Diff two run-record JSON documents (obs/profiler.py)."""
    return diff_summaries(rec_a["summary"], rec_b["summary"],
                          rec_a.get("config"), rec_b.get("config"),
                          label_a, label_b)


# ---------------------------------------------------------------------------
# window-vs-window: one record, two phases
# ---------------------------------------------------------------------------

def segment_summaries(rec: dict, split_tick: int | None = None) -> tuple:
    """Split a record's obs/windows.py snapshot plane into two phase
    summaries: counter deltas summed over the windows at or before
    ``split_tick`` (default: the midpoint window) vs the rest, plus a
    per-phase ``measured_ticks`` so every per-tick/per-commit cause
    normalizes within its own phase.  The split is EXACT: the two
    pseudo-summaries add back to the run's cumulative counters (the
    window identity)."""
    win = rec.get("windows")
    if not win:
        raise ValueError("record carries no windows block "
                         "(run with Config.windows)")
    if win.get("wrapped"):
        raise ValueError(
            f"window ring wrapped ({win['cnt']} windows latched, "
            f"{win['slots']} kept) — refusing to segment a lossy ring")
    ring_i = np.asarray(win["ring_i"], np.int64)
    ring_f = np.asarray(win["ring_f"], np.float64)
    ticks = ring_i[:, win["cols_i"].index("tick")]
    if split_tick is None:
        split_tick = int(ticks[max(len(ticks) // 2 - 1, 0)])
    early = ticks <= split_tick
    if not early.any() or early.all():
        raise ValueError(f"split tick {split_tick} leaves an empty "
                         f"phase (windows end at {ticks.tolist()})")

    def phase(mask):
        d_i = np.diff(ring_i, axis=0,
                      prepend=np.zeros((1, ring_i.shape[1]), np.int64))
        d_f = np.diff(ring_f, axis=0,
                      prepend=np.zeros((1, ring_f.shape[1]), np.float64))
        s = {k: int(v) for k, v in
             zip(win["cols_i"], d_i[mask].sum(axis=0)) if k != "tick"}
        s.update({k: float(v) for k, v in
                  zip(win["cols_f"], d_f[mask].sum(axis=0))})
        return s

    return phase(early), phase(~early), int(split_tick)


def diff_windows(rec: dict, split_tick: int | None = None) -> dict:
    """Window-vs-window diagnosis within one record: early phase is the
    baseline, late phase the comparison."""
    sa, sb, split = segment_summaries(rec, split_tick)
    cfg = rec.get("config")
    out = diff_summaries(sa, sb, cfg, cfg,
                         label_a=f"ticks<={split}",
                         label_b=f"ticks>{split}")
    out["kind"] = "window_diff"
    out["split_tick"] = split
    return out


# ---------------------------------------------------------------------------
# regress-gate triage: failing trajectory point vs its median prior
# ---------------------------------------------------------------------------

#: ride-along families an obs/regress.py trajectory entry carries, with
#: the lever the family's regression maps to and whether higher is
#: better (mirrors the gate's floor/ceiling orientation)
_ENTRY_FAMILIES = (
    ("algs", "commits_per_tick", "fused_arbitrate", True),
    ("knees", "offered_load_knee", "adaptive", True),
    ("scaling_grid", "efficiency", "exchange_split", True),
    ("scaling_amp", "amplification", "remote_cache", False),
    ("pipeline_overlap", "pipeline_overlap_frac",
     "pipeline_exchange", True),
    ("adaptive_vs_static", "adaptive_vs_static", "adaptive", True),
    ("slo_p99", "slo_p99", "adaptive", False),
)


def diagnose_entries(current: dict, prior: list[dict]) -> dict:
    """Triage one failing trajectory point against the median of its
    priors: every ride-along cell the point carries is scored against
    the per-key median over the priors that also carry it, ranked by
    the same relative-change score as the run diff.  This is what the
    regress gate attaches to its failures — the regression arrives
    naming the cell, the direction and the lever."""
    causes = []
    fams = [("value", f"headline[{current.get('metric')}]",
             "fused_arbitrate", True)]
    for fam, metric, lever, good in _ENTRY_FAMILIES:
        for key in sorted(current.get(fam, {}) or {}):
            fams.append((f"{fam}.{key}", f"{metric}[{key}]", lever, good))
    for path, name, lever, good in fams:
        fam, _, key = path.partition(".")
        cur = (current.get("value") if fam == "value"
               else current.get(fam, {}).get(key))
        if cur is None:
            continue
        base = [e.get("value") if fam == "value"
                else e.get(fam, {}).get(key) for e in prior]
        base = [v for v in base if v is not None]
        if not base:
            continue
        med = float(np.median(base))
        sc = _score(med, float(cur), TAU_RATIO)
        worse = (cur < med) if good else (cur > med)
        causes.append({"cause": name, "lever": lever,
                       "desc": f"trajectory cell vs median of "
                               f"{len(base)} prior point(s)",
                       "a": med, "b": float(cur), "delta": float(cur) - med,
                       "score": sc, "regressing": bool(worse and sc > 0)})
    causes.sort(key=lambda c: (-c["regressing"], -c["score"]))
    ranked = [c for c in causes if c["score"] > 0.0]
    top = next((c for c in ranked if c["regressing"]),
               ranked[0] if ranked else None)
    return {"kind": "regress_diff",
            "a": "median(prior)", "b": current.get("source", "current"),
            "causes": ranked,
            "top_cause": top["cause"] if top else None,
            "top_lever": top["lever"] if top else None}


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def render_diagnosis(diag: dict, topk: int = 8) -> str:
    """The ``[diagnosis]`` section (obs/report.py render_text emits the
    same lines when a report carries a diagnosis)."""
    lines = []
    if "tput_a" in diag:
        lines.append(
            f"[diagnosis] {diag['a']} -> {diag['b']}: throughput "
            f"{diag['tput_a']:.2f} -> {diag['tput_b']:.2f} commits/tick "
            f"({diag['tput_ratio']:.2f}x), latency "
            f"{diag['latency_a']:.1f} -> {diag['latency_b']:.1f} ticks")
    else:
        lines.append(f"[diagnosis] {diag['b']} vs {diag['a']}")
    if not diag["causes"]:
        lines.append("  (no cause moved above its noise floor)")
    for i, c in enumerate(diag["causes"][:topk]):
        tag = "REGRESSING" if c["regressing"] else "shifted  "
        lines.append(
            f"  {i + 1}. {tag} {c['cause']:<34} "
            f"{c['a']:>10.4g} -> {c['b']:<10.4g} "
            f"score {c['score']:.2f}  lever: {c['lever']}")
        lines.append(f"     {c['desc']}")
    if diag.get("top_cause"):
        lines.append(f"  verdict: {diag['top_cause']} "
                     f"(try Config.{diag['top_lever']})")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m deneva_tpu.obs.diff",
        description="differential run comparator: rank the causes of a "
                    "throughput/latency change and map each to its "
                    "config lever")
    p.add_argument("records", nargs="+",
                   help="two run-record JSON paths (A B), or one record "
                        "with --windows")
    p.add_argument("--windows", action="store_true",
                   help="diff two phases WITHIN one record's window "
                        "plane (Config.windows)")
    p.add_argument("--split-tick", type=int, default=None,
                   help="window-mode phase boundary (default: midpoint)")
    p.add_argument("-o", "--out", default=None,
                   help="also write the diagnosis JSON artifact here")
    p.add_argument("--json", action="store_true",
                   help="print the JSON diagnosis instead of text")
    args = p.parse_args(argv)

    recs = []
    for path in args.records:
        with open(path) as f:
            recs.append(json.load(f))
    if args.windows:
        if len(recs) != 1:
            p.error("--windows takes exactly one record")
        diag = diff_windows(recs[0], args.split_tick)
    else:
        if len(recs) != 2:
            p.error("run diff takes exactly two records (A B)")
        diag = diff_records(recs[0], recs[1],
                            label_a=args.records[0],
                            label_b=args.records[1])
    if args.out:
        with open(args.out, "w") as f:
            json.dump(diag, f)
    print(json.dumps(diag) if args.json else render_diagnosis(diag))
    return 0


if __name__ == "__main__":          # pragma: no cover - CLI shim
    raise SystemExit(main())
