"""Compile & memory observatory (``Config.xmeter``): recompile sentinel,
HBM footprint ledger, and per-kernel roofline.

PRs 1 and 4 instrumented the transaction plane (tick trace ring, abort
taxonomy); this module instruments the layer BELOW it — the XLA
compile/dispatch/memory plane, where the silent performance bugs live:

- **recompile sentinel** — every jitted entry point the engine dispatches
  is wrapped (:meth:`XMeter.wrap`) or windowed (:meth:`XMeter.watch`) so
  compilations are counted PER ENTRY POINT with their trigger signature
  (arg shapes/dtypes + treedef).  Two detectors corroborate: the jit
  dispatch cache growing across a call (``fn._cache_size()``, the same
  probe obs/profiler.py uses) and jax's own compile-event stream
  (``jax.monitoring`` ``backend_compile`` duration events), with an
  explicit ``expect_compile`` hint as the fallback where neither exists.
  After :meth:`XMeter.mark_warm`, a steady-state run must report zero
  further compiles; :meth:`XMeter.steady_violations` names the offending
  entry point and the signature that triggered it.

- **HBM footprint ledger** — :func:`state_ledger` walks the engine's
  donated/carried state pytree (engine/state.py TxnState + db/tables/
  stats rings) plus the constant plane (the device query pool) into a
  per-array ledger (name, shape, dtype, nbytes, carry/constant/temp);
  :meth:`XMeter.analyze` AOT-compiles an entry point from its captured
  abstract signature and reconciles the ledger against the executable's
  ``memory_analysis()`` live-buffer accounting (donated carry ==
  ``argument_size_in_bytes`` exactly on every backend tested; the gate
  allows 1%).  :func:`budget_check` turns the same ledger into the
  ROADMAP's sizing tool: flag when the (txn x access) tensor plane would
  spill a ``--budget-mb`` HBM budget at a target B/R/NODE_CNT
  (CLI: ``python -m deneva_tpu.obs.xmeter --budget-mb ...``).

- **per-kernel roofline** — ``cost_analysis()`` FLOPs / bytes-accessed
  paired with measured blocked dispatch time into achieved-vs-peak
  fractions (:meth:`XMeter.roofline`), rendered by obs/report.py and as
  a 5th Perfetto counter track (obs/trace.py); PROFILE.md's primitive
  cost table is generated from this instead of maintained by hand.

Everything here is host-side: no extra device arrays, no change to any
tick graph.  The observation cost is the AOT lower+compile that
:meth:`analyze` performs once per analyzed entry point (it does NOT
populate the dispatch cache, so it never shadows a real compile) and,
when ``block=True``, a ``block_until_ready`` per metered call so
roofline times are real device times.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager
from typing import Any, Optional

import jax
import numpy as np

SNAPSHOT_SCHEMA = "deneva-tpu/xmeter/v1"

#: array-plane classification in the ledger
KIND_CARRY = "carry"        # donated engine state, threaded tick to tick
KIND_CONSTANT = "constant"  # device-resident read-only plane (query pool)
KIND_TEMP = "temp"          # executable scratch (memory_analysis temp)

#: nominal peak envelopes for the roofline denominator, per backend.
#: "tpu" is the BASELINE.md north-star part (v5e: 197 TFLOP/s bf16,
#: 819 GB/s HBM); "cpu" is a nominal laptop-class envelope so smoke runs
#: produce finite fractions — CPU fractions are indicative only.
PEAKS = {
    "tpu": {"flops_per_s": 197e12, "bytes_per_s": 819e9},
    "cpu": {"flops_per_s": 5e10, "bytes_per_s": 2e10},
}

#: per-entry call-duration ring depth (host list; oldest dropped)
_DURATION_RING = 4096


# ---------------------------------------------------------------------------
# backend-compile event stream (jax.monitoring)
# ---------------------------------------------------------------------------

#: module-level singleton: jax.monitoring only exposes
#: ``clear_event_listeners`` (no per-listener unregister), so the
#: listener installs once per process and every XMeter reads deltas.
_BACKEND = {"installed": False, "available": False,
            "count": 0, "seconds": 0.0}


def _on_event_duration(event: str, duration: float, **_kw) -> None:
    if "backend_compile" in event:
        _BACKEND["count"] += 1
        _BACKEND["seconds"] += float(duration)


def install_backend_listener() -> bool:
    """Idempotently hook jax's compile-duration event stream; returns
    whether the stream is available on this jax version."""
    if _BACKEND["installed"]:
        return _BACKEND["available"]
    _BACKEND["installed"] = True
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_event_duration)
        _BACKEND["available"] = True
    except Exception:       # pragma: no cover - jax without monitoring
        _BACKEND["available"] = False
    return _BACKEND["available"]


def backend_compile_totals() -> tuple[int, float]:
    """(count, seconds) of backend compiles observed process-wide."""
    return _BACKEND["count"], _BACKEND["seconds"]


# ---------------------------------------------------------------------------
# call signatures
# ---------------------------------------------------------------------------

def call_signature(args: tuple, kwargs: dict | None = None) -> tuple:
    """Hashable trigger signature of a call: the pytree structure plus
    each array leaf's (shape, dtype, weak_type) — exactly the cache key
    components whose change forces a retrace — with non-array leaves
    recorded by repr (static values baked into the trace)."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    sig = []
    for x in leaves:
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            sig.append((tuple(x.shape), str(x.dtype),
                        bool(getattr(x, "weak_type", False))))
        else:
            sig.append(("static", repr(x)))
    return (str(treedef), tuple(sig))


def abstract_args(args: tuple) -> tuple:
    """ShapeDtypeStruct skeleton of a call's arguments, captured BEFORE
    dispatch (donation invalidates the concrete buffers after)."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        if hasattr(x, "shape") and hasattr(x, "dtype") else x, args)


# ---------------------------------------------------------------------------
# per-entry-point meter
# ---------------------------------------------------------------------------

class EntryMeter:
    """Compile/dispatch accounting for ONE jitted entry point."""

    def __init__(self, name: str):
        self.name = name
        self.compile_cnt = 0          # calls/windows that compiled
        self.compile_ms = 0.0
        self.calls = 0
        self.call_ms = 0.0
        self.sigs: dict[tuple, int] = {}
        self.warm_at: Optional[int] = None   # compile_cnt at mark_warm
        self.post_warm: list[dict] = []      # violations after mark_warm
        self.durations_ms: list[float] = []  # per-call (blocked) wall ms
        self.abstract: Optional[tuple] = None
        self.fn: Any = None                  # jitted callable for analyze()
        self.analysis: Optional[dict] = None

    def note(self, compiled: bool, dt_ms: float, compile_ms: float,
             sig: Optional[tuple], blocked: bool) -> None:
        self.calls += 1
        self.call_ms += dt_ms
        if sig is not None:
            self.sigs[sig] = self.sigs.get(sig, 0) + 1
        if blocked:
            self.durations_ms.append(dt_ms)
            if len(self.durations_ms) > _DURATION_RING:
                del self.durations_ms[0]
        if compiled:
            self.compile_cnt += 1
            self.compile_ms += compile_ms
            if self.warm_at is not None and self.compile_cnt > self.warm_at:
                self.post_warm.append({
                    "entry": self.name,
                    "compile_ms": round(compile_ms, 3),
                    "signature": repr(sig) if sig is not None else None,
                })

    def mean_ms(self) -> Optional[float]:
        if self.durations_ms:
            return float(np.mean(self.durations_ms))
        return None

    def snapshot(self) -> dict:
        return {
            "compile_cnt": self.compile_cnt,
            "compile_ms": round(self.compile_ms, 3),
            "calls": self.calls,
            "call_ms": round(self.call_ms, 3),
            "mean_ms": self.mean_ms(),
            "distinct_signatures": len(self.sigs),
            "post_warm": list(self.post_warm),
            "analysis": self.analysis,
            "durations_ms": [round(d, 4) for d in self.durations_ms],
        }


class MeteredFn:
    """Transparent wrapper over a jitted callable: every ``__call__``
    flows through :meth:`XMeter.record_call`.  Exposes ``_cache_size``
    and ``lower`` so obs/profiler.py's dispatch attribution and the AOT
    analysis path keep working on the wrapped function."""

    def __init__(self, xm: "XMeter", entry: EntryMeter, fn):
        self._xm = xm
        self._entry = entry
        self._fn = fn

    def _cache_size(self) -> Optional[int]:
        try:
            return self._fn._cache_size()
        except Exception:
            return None

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        return self._xm.record_call(self._entry, self._fn, args, kwargs)


class XMeter:
    """The observatory: entry-point meters + ledger/roofline assembly.

    ``block``: when True every metered call blocks until ready before
    the clock stops, so per-call durations are real device times (the
    roofline numerator).  Off by default — blocking forfeits host/device
    pipelining, same trade as ``Config.profile``.
    """

    def __init__(self, cfg=None, block: bool = False):
        self.cfg = cfg
        self.block = block
        self.entries: dict[str, EntryMeter] = {}
        self.warm = False
        install_backend_listener()

    # -- metering ------------------------------------------------------
    def entry(self, name: str) -> EntryMeter:
        e = self.entries.get(name)
        if e is None:
            e = self.entries[name] = EntryMeter(name)
        return e

    def wrap(self, name: str, fn) -> MeteredFn:
        """Wrap a jitted callable for per-call metering."""
        e = self.entry(name)
        e.fn = fn
        return MeteredFn(self, e, fn)

    def record_call(self, entry: EntryMeter, fn, args: tuple,
                    kwargs: dict):
        sig = call_signature(args, kwargs)
        if entry.abstract is None and not kwargs:
            entry.abstract = abstract_args(args)
        try:
            before = fn._cache_size()
        except Exception:
            before = None
        bc0, bs0 = backend_compile_totals()
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        if self.block:
            jax.block_until_ready(out)
        dt_ms = (time.perf_counter() - t0) * 1e3
        try:
            after = fn._cache_size()
        except Exception:
            after = None
        bc1, bs1 = backend_compile_totals()
        compiled = (before is not None and after is not None
                    and after > before) or bc1 > bc0
        compile_ms = (bs1 - bs0) * 1e3 if bc1 > bc0 else (
            dt_ms if compiled else 0.0)
        entry.note(compiled, dt_ms, compile_ms, sig, self.block)
        return out

    @contextmanager
    def watch(self, name: str, sig: Any = None,
              expect_compile: Optional[bool] = None):
        """Meter a compile/dispatch window that is not a single wrapped
        call (bound-method jits, the sharded fresh-jit scan).  Compile
        detection rides the backend event stream; ``expect_compile`` is
        the caller's static knowledge, used when the stream is
        unavailable."""
        e = self.entry(name)
        bc0, bs0 = backend_compile_totals()
        t0 = time.perf_counter()
        yield e
        dt_ms = (time.perf_counter() - t0) * 1e3
        bc1, bs1 = backend_compile_totals()
        if _BACKEND["available"]:
            compiled = bc1 > bc0
        else:                     # pragma: no cover - jax w/o monitoring
            compiled = bool(expect_compile)
        compile_ms = (bs1 - bs0) * 1e3 if bc1 > bc0 else (
            dt_ms if compiled else 0.0)
        wsig = None if sig is None else ("watch", repr(sig))
        e.note(compiled, dt_ms, compile_ms, wsig, blocked=False)

    # -- steady-state sentinel ----------------------------------------
    def mark_warm(self) -> None:
        """Declare warmup over: any compile after this is a violation."""
        self.warm = True
        for e in self.entries.values():
            e.warm_at = e.compile_cnt

    def steady_violations(self) -> list[dict]:
        """Post-warmup recompiles, naming the offending entry point and
        the signature that triggered each (empty == steady state held)."""
        out = []
        for e in self.entries.values():
            out.extend(e.post_warm)
        return out

    # -- totals / summary ---------------------------------------------
    def compile_totals(self) -> tuple[int, float]:
        cnt = sum(e.compile_cnt for e in self.entries.values())
        ms = sum(e.compile_ms for e in self.entries.values())
        return cnt, ms

    def summary_fields(self, hbm_bytes: Optional[int] = None) -> dict:
        """The [summary] keys (merged by Engine.summary only when the
        observatory is on, so the off path stays byte-identical)."""
        cnt, ms = self.compile_totals()
        out = {"compile_cnt": cnt, "compile_ms": round(ms, 3)}
        if hbm_bytes is not None:
            out["hbm_bytes"] = int(hbm_bytes)
        return out

    # -- AOT cost/memory analysis -------------------------------------
    def analyze(self, name: str) -> dict:
        """AOT lower+compile the entry point from its captured abstract
        signature; attach cost_analysis/memory_analysis numbers.  One
        extra compile per call (it does not touch the dispatch cache —
        steady-state detection is unaffected)."""
        e = self.entries[name]
        assert e.fn is not None and e.abstract is not None, \
            f"entry '{name}' was never called through a wrap()ed fn"
        compiled = e.fn.lower(*e.abstract).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        mem = compiled.memory_analysis()
        e.analysis = {
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
            "argument_bytes": int(getattr(mem, "argument_size_in_bytes",
                                          0)),
            "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
            "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
            "alias_bytes": int(getattr(mem, "alias_size_in_bytes", 0)),
            "code_bytes": int(getattr(mem, "generated_code_size_in_bytes",
                                      0)),
        }
        return e.analysis

    # -- roofline -------------------------------------------------------
    def roofline(self, peaks: Optional[dict] = None,
                 backend: Optional[str] = None) -> list[dict]:
        """Achieved-vs-peak rows for every analyzed entry with measured
        (blocked) durations.  ``bound`` names the roofline side whose
        peak-time requirement is larger — the resource the kernel would
        saturate first."""
        if peaks is None:
            backend = backend or jax.default_backend()
            peaks = PEAKS.get(backend, PEAKS["cpu"])
        pf, pb = peaks["flops_per_s"], peaks["bytes_per_s"]
        rows = []
        for name in sorted(self.entries):
            e = self.entries[name]
            mean_ms = e.mean_ms()
            if e.analysis is None or mean_ms is None or mean_ms <= 0:
                continue
            t = mean_ms / 1e3
            fl, by = e.analysis["flops"], e.analysis["bytes_accessed"]
            rows.append({
                "entry": name,
                "calls": e.calls,
                "mean_ms": round(mean_ms, 4),
                "flops": fl,
                "bytes_accessed": by,
                "achieved_gflops": round(fl / t / 1e9, 3),
                "achieved_gbps": round(by / t / 1e9, 3),
                "peak_flop_frac": round(fl / t / pf, 6),
                "peak_bw_frac": round(by / t / pb, 6),
                "bound": "memory" if by / pb >= fl / pf else "compute",
            })
        return rows

    # -- export ---------------------------------------------------------
    def snapshot(self) -> dict:
        cnt, ms = self.compile_totals()
        bc, bs = backend_compile_totals()
        return {
            "schema": SNAPSHOT_SCHEMA,
            "backend": jax.default_backend(),
            "compile_cnt": cnt,
            "compile_ms": round(ms, 3),
            "warm": self.warm,
            "steady_violations": self.steady_violations(),
            "entries": {k: e.snapshot()
                        for k, e in sorted(self.entries.items())},
            "backend_compile_events": {"count": bc,
                                       "seconds": round(bs, 3)},
            "roofline": self.roofline(),
        }


# ---------------------------------------------------------------------------
# HBM footprint ledger
# ---------------------------------------------------------------------------

def _named_leaves(prefix: str, obj):
    """Depth-first (name, array) walk of the engine state pytree:
    NamedTuples by field, dicts by sorted key, sequences by index."""
    if hasattr(obj, "_asdict"):                      # NamedTuple
        for k, v in obj._asdict().items():
            yield from _named_leaves(f"{prefix}.{k}" if prefix else k, v)
    elif isinstance(obj, dict):
        for k in sorted(obj):
            yield from _named_leaves(f"{prefix}.{k}" if prefix else str(k),
                                     obj[k])
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            yield from _named_leaves(f"{prefix}[{i}]", v)
    elif hasattr(obj, "shape") and hasattr(obj, "dtype"):
        yield prefix, obj


def _nbytes(x) -> int:
    return int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize \
        if x.shape else np.dtype(x.dtype).itemsize


def state_ledger(state, constants: Optional[dict] = None,
                 temp_bytes: int = 0) -> list[dict]:
    """Per-array HBM ledger of an engine's resident footprint.

    ``state``: the donated carry (EngineState / ShardState) — every leaf
    is a ``carry`` row.  ``constants``: named read-only device planes
    (e.g. ``{"pool": engine.pool_dev}``) — ``constant`` rows.
    ``temp_bytes``: executable scratch from ``memory_analysis()``
    (:meth:`XMeter.analyze`) — one synthetic ``temp`` row.
    """
    rows = []
    for name, arr in _named_leaves("", state):
        rows.append({"name": name, "shape": tuple(arr.shape),
                     "dtype": str(arr.dtype), "nbytes": _nbytes(arr),
                     "kind": KIND_CARRY})
    for group, obj in sorted((constants or {}).items()):
        for name, arr in _named_leaves(group, obj):
            rows.append({"name": name, "shape": tuple(arr.shape),
                         "dtype": str(arr.dtype), "nbytes": _nbytes(arr),
                         "kind": KIND_CONSTANT})
    if temp_bytes > 0:
        rows.append({"name": "<xla temp>", "shape": (), "dtype": "opaque",
                     "nbytes": int(temp_bytes), "kind": KIND_TEMP})
    return rows


def ledger_totals(rows: list[dict]) -> dict:
    """Per-kind byte totals plus the grand total."""
    out = {KIND_CARRY: 0, KIND_CONSTANT: 0, KIND_TEMP: 0}
    for r in rows:
        out[r["kind"]] = out.get(r["kind"], 0) + r["nbytes"]
    out["total"] = sum(out[k] for k in (KIND_CARRY, KIND_CONSTANT,
                                        KIND_TEMP))
    return out


def reconcile_ledger(rows: list[dict], analysis: dict,
                     tol: float = 0.01) -> dict:
    """Gate: the ledger's carry total must match the executable's
    live-argument accounting (``memory_analysis().argument_size_in_bytes``
    — the tick donates its whole carry, so the two count the same
    buffers) within ``tol``."""
    carry = ledger_totals(rows)[KIND_CARRY]
    arg = int(analysis["argument_bytes"])
    ratio = carry / arg if arg else float("inf")
    return {"carry_bytes": carry, "argument_bytes": arg,
            "ratio": round(ratio, 6),
            "ok": arg > 0 and abs(ratio - 1.0) <= tol}


def budget_check(rows: list[dict], budget_mb: float,
                 node_cnt: int = 1) -> dict:
    """Does the per-node footprint (x node_cnt replicas cluster-wide)
    fit an HBM budget?  Reports the (txn x access) tensor-plane share —
    the B- and B*R-shaped arrays that scale with the in-flight window —
    separately, because that is the axis the ROADMAP's million-user
    scaling grows."""
    tot = ledger_totals(rows)
    budget = int(budget_mb * (1 << 20))
    plane = sum(r["nbytes"] for r in rows
                if r["kind"] == KIND_CARRY and len(r["shape"]) >= 1
                and r["name"].split(".")[0] in ("txn", "net"))
    per_node = tot["total"]
    return {
        "budget_bytes": budget,
        "per_node_bytes": per_node,
        "cluster_bytes": per_node * node_cnt,
        "txn_plane_bytes": plane,
        "headroom_bytes": budget - per_node,
        "spill": per_node > budget,
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def roofline_markdown(rows: list[dict]) -> str:
    """The generated PROFILE.md table (replaces the hand-maintained
    primitive cost table for metered entry points)."""
    head = ("| entry | calls | mean ms | MFLOP | MB touched | GFLOP/s | "
            "GB/s | peak FLOP | peak BW | bound |")
    sep = "|" + "---|" * 10
    lines = [head, sep]
    for r in rows:
        lines.append(
            f"| {r['entry']} | {r['calls']} | {r['mean_ms']:.3f} | "
            f"{r['flops'] / 1e6:.2f} | {r['bytes_accessed'] / 1e6:.2f} | "
            f"{r['achieved_gflops']:.2f} | {r['achieved_gbps']:.2f} | "
            f"{r['peak_flop_frac']:.2%} | {r['peak_bw_frac']:.2%} | "
            f"{r['bound']} |")
    return "\n".join(lines)


def ledger_text(rows: list[dict], top: int = 12) -> str:
    tot = ledger_totals(rows)
    lines = [f"[ledger] {tot['total'] / 1e6:.2f} MB resident "
             f"(carry {tot[KIND_CARRY] / 1e6:.2f} / constant "
             f"{tot[KIND_CONSTANT] / 1e6:.2f} / temp "
             f"{tot[KIND_TEMP] / 1e6:.2f})"]
    for r in sorted(rows, key=lambda r: -r["nbytes"])[:top]:
        lines.append(f"  {r['name']:<32} {str(r['shape']):<16} "
                     f"{r['dtype']:<8} {r['nbytes']:>12} {r['kind']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI: HBM sizing tool
# ---------------------------------------------------------------------------

def fit_batch(budget_mb: float, probe_totals: dict[int, int],
              node_cnt: int = 1) -> dict:
    """Linear footprint model from two probe batch sizes: bytes(B) =
    fixed + per_txn * B, solved for the largest B under the budget."""
    (b0, t0), (b1, t1) = sorted(probe_totals.items())
    per_txn = (t1 - t0) / max(b1 - b0, 1)
    fixed = t0 - per_txn * b0
    budget = budget_mb * (1 << 20)
    max_b = int((budget - fixed) / per_txn) if per_txn > 0 else 0
    return {"fixed_bytes": int(fixed), "per_txn_bytes": float(per_txn),
            "max_batch_per_node": max(max_b, 0),
            "max_batch_cluster": max(max_b, 0) * node_cnt}


def main(argv=None) -> int:
    import argparse
    from deneva_tpu.config import Config
    from deneva_tpu.engine.scheduler import Engine

    p = argparse.ArgumentParser(
        prog="python -m deneva_tpu.obs.xmeter",
        description="HBM footprint ledger + sizing: flag when the "
                    "(txn x access) plane would spill a budget at a "
                    "target B/R/NODE_CNT, and report the max batch the "
                    "budget admits")
    p.add_argument("--budget-mb", type=float, required=True,
                   help="HBM budget per node in MB (v5e chip: 16384)")
    p.add_argument("--batch", type=int, default=8192,
                   help="target in-flight txns per node (B)")
    p.add_argument("--req", type=int, default=10,
                   help="accesses per txn (R)")
    p.add_argument("--rows", type=int, default=1 << 24,
                   help="table rows (SYNTH_TABLE_SIZE)")
    p.add_argument("--node-cnt", type=int, default=1,
                   help="cluster nodes (footprint replicates per node)")
    p.add_argument("--cc-alg", default="NO_WAIT")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    def ledger_at(batch: int) -> list[dict]:
        cfg = Config(cc_alg=args.cc_alg, batch_size=batch,
                     synth_table_size=args.rows, req_per_query=args.req,
                     query_pool_size=min(1 << 12, args.rows), xmeter=True)
        eng = Engine(cfg)
        return state_ledger(eng.init_state(),
                            constants={"pool": eng.pool_dev})

    # probe two small batches for the linear model, then evaluate the
    # target batch exactly
    probes = {b: ledger_totals(ledger_at(b))["total"] for b in (256, 512)}
    target_rows = ledger_at(args.batch)
    check = budget_check(target_rows, args.budget_mb,
                         node_cnt=args.node_cnt)
    fit = fit_batch(args.budget_mb, probes, node_cnt=args.node_cnt)
    doc = {"target": {"batch": args.batch, "req": args.req,
                      "rows": args.rows, "node_cnt": args.node_cnt,
                      "cc_alg": args.cc_alg}, **check, **fit}
    if args.json:
        print(json.dumps(doc))
    else:
        print(ledger_text(target_rows))
        print(f"[budget] per-node {check['per_node_bytes'] / 1e6:.2f} MB "
              f"vs {args.budget_mb:.0f} MB budget -> "
              f"{'SPILL' if check['spill'] else 'fits'} "
              f"(txn-plane {check['txn_plane_bytes'] / 1e6:.2f} MB; "
              f"cluster x{args.node_cnt} = "
              f"{check['cluster_bytes'] / 1e6:.2f} MB)")
        print(f"[budget] max B under budget: "
              f"{fit['max_batch_per_node']} per node "
              f"({fit['per_txn_bytes']:.0f} B/txn + "
              f"{fit['fixed_bytes'] / 1e6:.2f} MB fixed)")
    return 1 if check["spill"] else 0


if __name__ == "__main__":          # pragma: no cover - CLI shim
    raise SystemExit(main())
