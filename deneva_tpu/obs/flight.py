"""Transaction flight recorder: per-txn lifecycle spans (obs pillar 7).

Everything the repo measured before this module is AGGREGATE — lat_*
integrals, per-reason abort counters, per-tick counter tracks — which
answers "where did the fleet's time go" but never "why was THIS p99
transaction slow", the question the reference's per-txn state machine
(txn.cpp lifecycle + stats.cpp lat_* families) was instrumented for.
Opt-in through ``Config.flight`` (requires ``abort_attribution``), the
engine carries three device planes inside the stats dict:

- **open-span columns** (``(B,)`` per slot): admission tick
  (``arr_flight_admit``; -1 = slot idle), first-acquire tick
  (``arr_flight_facq``; -1 until the cursor first advances), and one
  warmup-gated tick accumulator per lifecycle phase mirroring the lat_*
  vocabulary — ``queue`` (client arrival -> admission, open-system runs),
  ``proc`` (RUNNING), ``block`` (WAITING), ``backoff`` (BACKOFF),
  ``net`` (sharded: blocked on message transit / remote entries);
- **completed-span ring** ``arr_flight_span`` (``(S, C)``,
  ``Config.flight_samples`` rows x FLIGHT_COLUMNS): harvested at the
  commit/user-abort bookkeeping site with the repo's keep-last ring +
  distinct-OOB-dead-lane scatter discipline (LINT.md);
- **restart-event ring** ``arr_flight_ev`` (``(4S, E)`` x
  EVENT_COLUMNS): one row per abort EVENT, appended inside
  ``note_aborts`` — i.e. at EXACTLY the sites that bump the aggregate
  abort counters, with the same masks and the same code normalization
  as ``_reason_hist`` — so the measured-window event histogram equals
  the ``abort_<reason>_cnt`` taxonomy exactly (including the reference's
  vabort double-count).

Exactness contract (the PR 4 taxonomy / PR 6 conservation discipline):
in full-sampling mode — rings never wrap, ``flight_qdrop_cnt == 0`` —

    sum(span.phase) + sum(open-slot accumulators)  ==  lat_<phase> integral
    hist(events at tick >= warmup)                 ==  abort_*_cnt

for every plugin and both engines (tests/test_flight.py).  Sampled mode
(small S) degrades to a keep-last window of recent completions, the
StatsArr analog.

In ``ShardedEngine`` the stats dict is stacked over the node axis, so
the rings arrive ``(N, S, C)``; :func:`snapshot` tags each span/event
with its node and merges per-node rings onto the one lockstep tick
clock.  Host-side exports:

- :func:`snapshot`          numpy -> dicts (spans / open spans / events);
- :func:`span_events`       Perfetto DURATION slices ("X") per sampled
                            txn with nested per-attempt slices and
                            abort-reason FLOW arrows ("s"/"f") across
                            restarts — a span track beside the six
                            counter tracks of obs/trace.py;
- :func:`tail_attribution`  the [tail] report section (obs/report.py):
                            dominant phase + abort reasons + hot keys
                            of the p99-and-above latency cohort;
- :func:`reconcile`         the exact identities above, as a mismatch
                            list (tests + the bench --flight gate).

When ``Config.flight`` is False (default) no arrays are carried and the
[summary] line is byte-identical to a build without this module.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from deneva_tpu.cc import base as cc_base
from deneva_tpu.engine.state import (NULL_KEY, STATUS_BACKOFF, STATUS_FREE,
                                     STATUS_RUNNING, STATUS_WAITING)

#: completed-span row schema.  ``admit``/``facq``/``end`` are ticks
#: (``facq`` = first cursor advance; a txn that commits the tick it was
#: admitted stamps ``facq = end``); ``kind`` 0 = commit, 1 = user abort;
#: ``restarts`` the attempt count at completion; the five phase columns
#: are warmup-gated tick counts mirroring the lat_* vocabulary (queue ->
#: lat_work_queue_time, proc -> lat_process_time, block ->
#: lat_cc_block_time, backoff -> lat_abort_time, net -> lat_network_time).
FLIGHT_COLUMNS = ("slot", "admit", "facq", "end", "kind", "restarts",
                  "queue", "proc", "block", "backoff", "net")
FCOL = {name: i for i, name in enumerate(FLIGHT_COLUMNS)}

#: abort-event row schema: the tick the event was counted, the slot it
#: hit, the NORMALIZED reason code (same clamp as _reason_hist, so the
#: host histogram partitions exactly like abort_*_cnt) and the failing
#: access key (NULL_KEY for whole-txn events: validation/user aborts).
EVENT_COLUMNS = ("tick", "slot", "code", "key")
ECOL = {name: i for i, name in enumerate(EVENT_COLUMNS)}

#: event ring depth = EV_FACTOR * Config.flight_samples (a txn restarts
#: several times per completion under contention)
EV_FACTOR = 4

#: span phase column -> the lat_* integral it reconciles against
PHASE_KEYS = (("queue", "lat_work_queue_time"),
              ("proc", "lat_process_time"),
              ("block", "lat_cc_block_time"),
              ("backoff", "lat_abort_time"),
              ("net", "lat_network_time"))

_ACCS = ("queue", "proc", "block", "backoff", "net")


# ---------------------------------------------------------------------------
# device side (jit-safe; every helper no-ops when the plane is absent)
# ---------------------------------------------------------------------------

def init_flight(cfg) -> dict:
    """Stats-dict entries for the recorder; empty when off (the disabled
    path carries nothing)."""
    if not cfg.flight:
        return {}
    B, S = cfg.batch_size, cfg.flight_samples
    out = {
        "arr_flight_admit": jnp.full((B,), -1, jnp.int32),
        "arr_flight_facq": jnp.full((B,), -1, jnp.int32),
        "arr_flight_span": jnp.zeros((S, len(FLIGHT_COLUMNS)), jnp.int32),
        "arr_flight_ev": jnp.zeros((EV_FACTOR * S, len(EVENT_COLUMNS)),
                                   jnp.int32),
        # cumulative harvest counts double as ring cursors (pos = cnt +
        # rank mod cap) and as the host's wrap detector; flight_-prefixed
        # scalars surface in [summary] (stats.py passthrough)
        "flight_span_cnt": jnp.zeros((), jnp.int32),
        "flight_ev_cnt": jnp.zeros((), jnp.int32),
    }
    for a in _ACCS:
        out[f"arr_flight_{a}"] = jnp.zeros((B,), jnp.int32)
    return out


def note_admit(stats: dict, free, t, qwait=None) -> dict:
    """Open a span on this tick's admitted lanes: stamp the admission
    tick, reset first-acquire and the phase accumulators, and bank the
    pre-admission work-queue wait (``qwait``, from the arrival-tick ring
    of traffic/arrival.py; None/0 for closed-loop runs)."""
    if "arr_flight_admit" not in stats:
        return stats
    out = dict(stats)
    out["arr_flight_admit"] = jnp.where(free, t, stats["arr_flight_admit"])
    out["arr_flight_facq"] = jnp.where(free, -1, stats["arr_flight_facq"])
    for a in _ACCS:
        k = f"arr_flight_{a}"
        v = qwait if (a == "queue" and qwait is not None) else 0
        out[k] = jnp.where(free, v, stats[k])
    return out


def harvest_spans(stats: dict, done, ua, txn, t) -> dict:
    """Close the spans of this tick's completing txns (``done`` = commit
    | user-abort) into the keep-last ring and mark their slots idle so
    the end-of-tick accumulators never double-count a freed lane.  Same
    scatter discipline as record_commit_latency: survivors of a
    sequential append occupy distinct in-ring positions mod S, dead
    lanes map to DISTINCT out-of-bounds rows."""
    if "arr_flight_span" not in stats:
        return stats
    ring = stats["arr_flight_span"]
    S = ring.shape[0]
    B = done.shape[0]
    admit = stats["arr_flight_admit"]
    rec = done & (admit >= 0)
    facq = stats["arr_flight_facq"]
    row = jnp.stack([
        jnp.arange(B, dtype=jnp.int32),                 # slot
        admit,
        jnp.where(facq < 0, t, facq),                   # same-tick commit
        jnp.full((B,), t, jnp.int32),                   # end
        jnp.where(ua, 1, 0).astype(jnp.int32),          # kind
        txn.restarts,
    ] + [stats[f"arr_flight_{a}"] for a in _ACCS], axis=1)  # (B, C)
    rank = jnp.cumsum(rec.astype(jnp.int32)) - rec.astype(jnp.int32)
    n = jnp.sum(rec.astype(jnp.int32))
    live = rec & (rank >= n - S)
    pos = jnp.where(live, (stats["flight_span_cnt"] + rank) % S,
                    S + jnp.arange(B, dtype=jnp.int32))
    out = {**stats,
           "arr_flight_span": ring.at[pos].set(row, mode="drop",
                                               unique_indices=True),
           "flight_span_cnt": stats["flight_span_cnt"] + n,
           "arr_flight_admit": jnp.where(rec, -1, admit)}
    for a in _ACCS:
        k = f"arr_flight_{a}"
        out[k] = jnp.where(rec, 0, stats[k])
    return out


def track_phases(stats: dict, txn, t, measuring) -> dict:
    """End-of-tick per-slot phase accumulation — the per-txn mirror of
    track_state_latencies, applied with the SAME status masks and the
    same warmup gate, so summed span phases reconcile exactly against
    the lat_* integrals.  Also stamps the first-acquire tick the first
    time a live txn's cursor leaves 0."""
    if "arr_flight_admit" not in stats:
        return stats
    open_ = stats["arr_flight_admit"] >= 0
    m = measuring & open_
    out = dict(stats)
    for a, st_v in (("proc", STATUS_RUNNING), ("block", STATUS_WAITING),
                    ("backoff", STATUS_BACKOFF)):
        k = f"arr_flight_{a}"
        out[k] = stats[k] + jnp.where(m & (txn.status == st_v), 1, 0)
    facq = stats["arr_flight_facq"]
    out["arr_flight_facq"] = jnp.where(
        open_ & (facq < 0) & (txn.cursor > 0) & (txn.status != STATUS_FREE),
        t, facq)
    return out


def track_net(stats: dict, inc_b, measuring) -> dict:
    """Per-slot network-phase accumulation (sharded engine): ``inc_b``
    is the SAME per-txn population whose sum bumps lat_network_time this
    tick — blocked-on-transit bools in net-delay mode, remote-entry
    counts in the D=0 proxy — so the identity holds in both modes."""
    if "arr_flight_net" not in stats:
        return stats
    inc = jnp.where(measuring & (stats["arr_flight_admit"] >= 0),
                    inc_b.astype(jnp.int32), 0)
    return {**stats, "arr_flight_net": stats["arr_flight_net"] + inc}


def record_events(stats: dict, code_b, mask_b, t, key_b=None) -> dict:
    """Append one abort-event row per masked lane (called from
    note_aborts, so event sites == counter sites).  Codes are normalized
    exactly like _reason_hist (<=0 -> "other", high codes clamp), hence
    hist(measured events) == abort_*_cnt.  NOT warmup-gated — the host
    filters by tick for the reconciliation, keeps all for the trace."""
    if "arr_flight_ev" not in stats:
        return stats
    ring = stats["arr_flight_ev"]
    cap = ring.shape[0]
    B = mask_b.shape[0]
    n_reg = len(cc_base.ABORT_REASONS)
    code = jnp.where(code_b <= 0, jnp.int32(cc_base.REASON["other"]), code_b)
    code = jnp.minimum(code, n_reg)
    # lint: disable-next=TRACED-BRANCH is-None STRUCTURE check: key_b is None iff the caller carries no key column (static per call site), never a traced-value branch
    if key_b is None:
        key_b = jnp.full((B,), NULL_KEY, jnp.int32)
    row = jnp.stack([jnp.full((B,), t, jnp.int32),
                     jnp.arange(B, dtype=jnp.int32),
                     code, key_b], axis=1)
    rank = jnp.cumsum(mask_b.astype(jnp.int32)) - mask_b.astype(jnp.int32)
    n = jnp.sum(mask_b.astype(jnp.int32))
    live = mask_b & (rank >= n - cap)
    pos = jnp.where(live, (stats["flight_ev_cnt"] + rank) % cap,
                    cap + jnp.arange(B, dtype=jnp.int32))
    return {**stats,
            "arr_flight_ev": ring.at[pos].set(row, mode="drop",
                                              unique_indices=True),
            "flight_ev_cnt": stats["flight_ev_cnt"] + n}


# ---------------------------------------------------------------------------
# host side
# ---------------------------------------------------------------------------

def _ring_rows(ring: np.ndarray, cnt: int) -> np.ndarray:
    """Valid rows of a keep-last ring in chronological order."""
    cap = ring.shape[0]
    if cnt <= cap:
        return ring[:cnt]
    return np.roll(ring, -(cnt % cap), axis=0)


def snapshot(state_or_stats) -> dict:
    """Fetch the recorder planes as plain dicts (JSON-ready; lands in
    profiler run records under the top-level ``"flight"`` key).  Sharded
    states arrive node-stacked; every span/event gains a ``node`` field
    and the per-node rings merge on the shared tick clock."""
    stats = getattr(state_or_stats, "stats", state_or_stats)
    assert "arr_flight_span" in stats, "run with Config.flight"
    span = np.asarray(stats["arr_flight_span"])
    ev = np.asarray(stats["arr_flight_ev"])
    if span.ndim == 2:                       # single shard -> 1-node stack
        span, ev = span[None], ev[None]
        scnt = np.asarray(stats["flight_span_cnt"]).reshape(1)
        ecnt = np.asarray(stats["flight_ev_cnt"]).reshape(1)
        admit = np.asarray(stats["arr_flight_admit"])[None]
        facq = np.asarray(stats["arr_flight_facq"])[None]
        accs = {a: np.asarray(stats[f"arr_flight_{a}"])[None]
                for a in _ACCS}
    else:
        scnt = np.asarray(stats["flight_span_cnt"])
        ecnt = np.asarray(stats["flight_ev_cnt"])
        admit = np.asarray(stats["arr_flight_admit"])
        facq = np.asarray(stats["arr_flight_facq"])
        accs = {a: np.asarray(stats[f"arr_flight_{a}"]) for a in _ACCS}
    N, S, _ = span.shape
    reasons = ("?",) + tuple(cc_base.ABORT_REASONS)
    spans, events, opens = [], [], []
    for node in range(N):
        for r in _ring_rows(span[node], int(scnt[node])):
            d = {c: int(r[i]) for i, c in enumerate(FLIGHT_COLUMNS)}
            d["node"] = node
            spans.append(d)
        for r in _ring_rows(ev[node], int(ecnt[node])):
            d = {c: int(r[i]) for i, c in enumerate(EVENT_COLUMNS)}
            d["node"] = node
            d["reason"] = reasons[min(max(d["code"], 0), len(reasons) - 1)]
            events.append(d)
        for slot in np.nonzero(admit[node] >= 0)[0]:
            d = {"node": node, "slot": int(slot),
                 "admit": int(admit[node][slot]),
                 "facq": int(facq[node][slot])}
            d.update({a: int(accs[a][node][slot]) for a in _ACCS})
            opens.append(d)
    # merged view stays tick-sorted across nodes (one lockstep clock)
    spans.sort(key=lambda d: (d["end"], d["node"], d["slot"]))
    events.sort(key=lambda d: (d["tick"], d["node"], d["slot"]))
    out = {"columns": list(FLIGHT_COLUMNS),
           "event_columns": list(EVENT_COLUMNS),
           "nodes": N, "samples": S,
           "span_cnt": int(scnt.sum()), "ev_cnt": int(ecnt.sum()),
           "span_wrapped": bool((scnt > S).any()),
           "ev_wrapped": bool((ecnt > ev.shape[1]).any()),
           "spans": spans, "events": events, "open_spans": opens}
    qd = stats.get("flight_qdrop_cnt")
    if qd is not None:
        out["qdrop_cnt"] = int(np.asarray(qd).sum())
    return out


def reconcile(snap: dict, summary: dict, warmup_ticks: int = 0) -> list:
    """The full-sampling exactness checks, as ``(what, got, want)``
    mismatch tuples (empty = exact).  Valid only while no ring wrapped
    (the caller's full-sampling contract); a wrapped ring or dropped
    queue stamps are reported as findings rather than silently passed."""
    bad = []
    if snap["span_wrapped"]:
        bad.append(("span_ring_wrapped", snap["span_cnt"], snap["samples"]))
    if snap["ev_wrapped"]:
        bad.append(("ev_ring_wrapped", snap["ev_cnt"],
                    EV_FACTOR * snap["samples"]))
    if bad:
        return bad
    both = snap["spans"] + snap["open_spans"]
    for col, key in PHASE_KEYS:
        want = summary.get(key)
        if want is None or (col == "queue" and snap.get("qdrop_cnt")):
            continue   # plane absent (closed loop / single shard) or
        got = sum(d[col] for d in both)     # queue stamps invalidated
        if col == "queue":
            # still-queued clients at run end hold wait the integral
            # already counted; the caller folds that residual in via
            # summary["flight_queue_residual"] (tests compute it)
            got += summary.get("flight_queue_residual", 0)
        if got != int(want):
            bad.append((col, got, int(want)))
    hist: dict = {}
    for e in snap["events"]:
        if e["tick"] >= warmup_ticks:
            hist[e["reason"]] = hist.get(e["reason"], 0) + 1
    for name in cc_base.ABORT_REASONS:
        want = int(summary.get(f"abort_{name}_cnt", 0))
        got = hist.get(name, 0)
        if got != want:
            bad.append((f"abort_{name}", got, want))
    return bad


def tail_attribution(snap: dict, pct: float = 99.0, topk: int = 5) -> dict:
    """Attribute the latency tail: over completed spans, take the
    ``pct``-and-above cohort by total latency (end - admit) and report
    which lifecycle phase dominates it (vs the all-spans baseline),
    which abort reasons its restarts hit, and which keys those restarts
    failed on — the "why is THIS p99 slow" answer."""
    spans = [d for d in snap["spans"] if d["kind"] == 0]
    if not spans:
        return {"n": 0, "cohort": 0}
    lat = np.asarray([d["end"] - d["admit"] for d in spans], np.int64)
    thresh = float(np.percentile(lat, pct))
    cohort = [d for d, l in zip(spans, lat) if l >= thresh]

    def shares(pop):
        tot = {a: sum(d[a] for d in pop) for a in _ACCS}
        s = max(sum(tot.values()), 1)
        return tot, {a: tot[a] / s for a in _ACCS}

    c_ticks, c_share = shares(cohort)
    _, all_share = shares(spans)
    # join restart events into the cohort's lifetimes (node, slot, window)
    win = {}
    for d in cohort:
        win.setdefault((d["node"], d["slot"]), []).append(
            (d["admit"], d["end"]))
    reasons: dict = {}
    keys: dict = {}
    for e in snap["events"]:
        for lo, hi in win.get((e["node"], e["slot"]), ()):
            if lo <= e["tick"] <= hi:
                reasons[e["reason"]] = reasons.get(e["reason"], 0) + 1
                if e["key"] != NULL_KEY:
                    keys[e["key"]] = keys.get(e["key"], 0) + 1
                break
    top = lambda d: sorted(d.items(), key=lambda kv: -kv[1])[:topk]
    return {"n": len(spans), "cohort": len(cohort),
            "p_ticks": thresh, "pct": pct,
            "max_ticks": int(lat.max()),
            "phase_ticks": c_ticks, "phase_share": c_share,
            "all_share": all_share,
            "dominant_phase": max(c_share, key=lambda a: c_share[a]),
            "avg_restarts": (sum(d["restarts"] for d in cohort)
                             / max(len(cohort), 1)),
            "top_reasons": top(reasons), "top_keys": top(keys)}


def span_events(snap: dict, tick_us: float = 1.0) -> list:
    """Perfetto DURATION events for the sampled spans — the span track
    beside the counter tracks of obs/trace.py to_chrome_trace.  One
    process per node, one thread per slot (a slot hosts one txn at a
    time, so its spans never overlap); each txn is an "X" slice spanning
    admit..end with nested per-attempt child slices split at its abort
    events, linked by abort-reason FLOW arrows ("s" -> "f") so a restart
    chain reads left-to-right across the track."""
    events = []
    seen_threads = set()
    flow_id = 0
    by_owner: dict = {}
    for e in snap["events"]:
        by_owner.setdefault((e["node"], e["slot"]), []).append(e)
    for d in snap["spans"]:
        pid, tid = d["node"], d["slot"]
        if (pid, tid) not in seen_threads:
            seen_threads.add((pid, tid))
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": f"slot{tid}"}})
        t0, t1 = d["admit"], d["end"]
        dur = max(t1 - t0, 0) + 1           # inclusive tick span
        kind = "user_abort" if d["kind"] else "txn"
        events.append({
            "name": kind, "cat": "flight", "ph": "X",
            "ts": t0 * tick_us, "dur": dur * tick_us,
            "pid": pid, "tid": tid,
            "args": {k: d[k] for k in ("facq", "restarts", *_ACCS)}})
        mine = [e for e in by_owner.get((pid, tid), ())
                if t0 <= e["tick"] <= t1]
        # attempt boundaries at the (deduped) abort ticks; a vabort's
        # double-counted event collapses into one boundary
        cuts = sorted({e["tick"] for e in mine})
        lo = t0
        for i, cut in enumerate(cuts):
            events.append({
                "name": f"attempt{i}", "cat": "flight", "ph": "X",
                "ts": lo * tick_us, "dur": max(cut - lo, 0) * tick_us
                + tick_us, "pid": pid, "tid": tid, "args": {}})
            reason = next(e["reason"] for e in mine if e["tick"] == cut)
            flow_id += 1
            events.append({"name": reason, "cat": "abort-flow", "ph": "s",
                           "id": flow_id, "ts": cut * tick_us,
                           "pid": pid, "tid": tid})
            events.append({"name": reason, "cat": "abort-flow", "ph": "f",
                           "bp": "e", "id": flow_id,
                           "ts": min(cut + 1, t1) * tick_us,
                           "pid": pid, "tid": tid})
            lo = min(cut + 1, t1)
        events.append({
            "name": f"attempt{len(cuts)}", "cat": "flight", "ph": "X",
            "ts": lo * tick_us, "dur": max(t1 - lo, 0) * tick_us + tick_us,
            "pid": pid, "tid": tid, "args": {}})
    return events
