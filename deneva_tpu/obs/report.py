"""Cluster waterfall + abort-attribution report ("where did the time go /
why did we abort"), and the obs watchdog.

Consumes the observatory's three data products:

- the ``[summary]`` counter dict (``Engine.summary`` /
  ``ShardedEngine.summary`` — the sharded one is already the bit-exact
  psum over the node axis), including the ``abort_<reason>_cnt`` taxonomy
  counters of ``Config.abort_attribution`` (cc/base.py ABORT_REASONS);
- the optional per-tick timeline (``obs.trace.timeline`` or the
  ``timeline`` field of a run record, obs/profiler.py);
- the optional contention heatmap arrays of ``Config.heatmap_bins``
  (``arr_conflict_hist`` / ``arr_conflict_key`` / ``arr_part_conflict`` /
  ``arr_wait_depth_hist`` in the stats dict).

Everything renders twice: :func:`render_text` for terminals and
:func:`build_report` for machines (plain-JSON-serializable dict).

The watchdog (:func:`watchdog`) turns the same inputs into CI-grade
findings with a process exit bitmask.  This block is THE definition of
the full mask (the README watchdog table mirrors it)::

    RECONCILE  (1)  counters fail their exact identities
    LIVELOCK   (2)  a zero-commit window with live abort/admission churn
    SPILL      (4)  compaction spill storm (forced-retry pressure)
    STARVED    (8)  a shard committing nothing while the cluster commits
    OVERLOAD  (16)  open-system run ended with more than ~1 service
                    tick of admission backlog still queued (offered
                    load exceeded the saturation knee and never drained)
    IMBALANCE (32)  mesh runs: Jain's fairness over per-node commit
                    loads fell below IMB_JAIN_MIN (obs/mesh.py) while
                    the cluster was committing — more than half the
                    nodes effectively idle
    RECOVERY  (64)  fault runs: a killed node's replay-recovered shard
                    slice (or its CALVIN epoch log) failed bit-parity
                    against the pre-crash oracle
                    (recovery_replay_ok / recovery_elog_ok = 0,
                    faults/recovery.py)
    SLO      (128)  Config.slo runs: the run ended with the multi-window
                    error-budget alert still FIRING (slo_alert_active,
                    obs/slo.py — both the fast and slow burn windows
                    above slo_burn_threshold; a drained flash crowd
                    clears before run end and does not fire), or the
                    exact histogram-total == committed-txn
                    reconciliation identity failed
    CONVOY   (256)  Config.depgraph runs: the sustained mean convoy
                    width (dep_convoy_width_sum / measured_ticks,
                    obs/depgraph.py — the per-tick max blocker
                    in-degree) stayed at or above CONVOY_WIDTH_MIN —
                    the run spent its measured window serialized behind
                    single hot blockers, not merely contended

CLI: ``python -m deneva_tpu.obs.report <run_record.json> [--json]``
exits with the watchdog bitmask, so a CI stage can gate on it
(scripts/check.sh does).
"""

from __future__ import annotations

import json

import numpy as np

# watchdog finding flags (process exit bitmask; the module docstring
# above is the single documentation point for the full mask)
RECONCILE = 1
LIVELOCK = 2
SPILL = 4
STARVED = 8
OVERLOAD = 16
IMBALANCE = 32
RECOVERY = 64
SLO = 128
CONVOY = 256

#: a zero-commit run of at least this many ticks, with abort/admission
#: churn inside it, is flagged as live-lock
LIVELOCK_WINDOW = 16
#: compaction spills above this fraction of (commits + aborts) are a storm
SPILL_FRAC = 0.05
#: a run-mean convoy width (txns queued behind one blocker per measured
#: tick) at or above this is a convoy, not ordinary contention
CONVOY_WIDTH_MIN = 4

#: the waterfall's phase rows: [summary] latency-decomposition integrals
#: (engine/scheduler.py track_state_latencies; all in txn-slot-ticks) and
#: the trace occupancy column each must integrate to (warmup_ticks == 0)
_PHASES = (("process", "lat_process_time", "occ_running"),
           ("cc_block", "lat_cc_block_time", "occ_waiting"),
           ("abort_backoff", "lat_abort_time", "occ_backoff"),
           ("network", "lat_network_time", None))


def _reason_counts(summary: dict) -> dict:
    from deneva_tpu.cc.base import ABORT_REASONS
    return {name: int(summary[f"abort_{name}_cnt"])
            for name in ABORT_REASONS
            if f"abort_{name}_cnt" in summary}


def top_reasons(summary: dict, k: int = 3) -> list:
    """Top-k ``(reason, count)`` pairs, nonzero only, count-descending
    (ties broken by registry order).  Empty when the run was not
    attributed."""
    rc = _reason_counts(summary)
    ranked = sorted(rc.items(), key=lambda kv: -kv[1])
    return [(n, c) for n, c in ranked[:k] if c > 0]


def reconcile(summary: dict, timeline: dict | None = None) -> list:
    """Exact-identity checks; returns a list of human-readable failure
    strings (empty == all good).

    - taxonomy: sum(abort_<reason>_cnt) == total_txn_abort_cnt
      + vabort_cnt + user_abort_cnt (vaborts are counted at both their
      own bump site and the total site, by construction — see
      engine/scheduler.py note_aborts call sites);
    - timeline: flow column sums == [summary] counters, and each
      waterfall phase integral == its occupancy column sum (exact when
      ``warmup_ticks == 0``; callers with warmup pass ``timeline=None``).
    """
    bad = []
    # SLO histogram plane (Config.slo, obs/histo.py): every committed
    # measured txn lands in EXACTLY one bucket, so the histogram total
    # equals the committed-txn count — exactly, no sampling slack
    if "hist_total_cnt" in summary:
        got = int(summary["hist_total_cnt"])
        want = int(summary.get("txn_cnt", 0))
        if got != want:
            bad.append(f"histogram: hist_total_cnt={got} != "
                       f"txn_cnt={want}")
    rc = _reason_counts(summary)
    if rc:
        want = int(summary.get("total_txn_abort_cnt", 0)) \
            + int(summary.get("vabort_cnt", 0)) \
            + int(summary.get("user_abort_cnt", 0))
        got = sum(rc.values())
        if got != want:
            bad.append(f"taxonomy: sum(abort_*_cnt)={got} != "
                       f"total+vabort+user={want}")
    # dependency-observatory edge counters (Config.depgraph,
    # obs/depgraph.py): every CC wait decision records exactly one wait
    # edge, every taxonomy abort exactly one abort edge — the counters
    # are warmup-gated at the same site as their counterparts, so both
    # identities are exact, not sampled
    if "dep_wait_edge_cnt" in summary and "twopl_wait_cnt" in summary:
        got = int(summary["dep_wait_edge_cnt"])
        want = int(summary["twopl_wait_cnt"])
        if got != want:
            bad.append(f"depgraph: dep_wait_edge_cnt={got} != "
                       f"twopl_wait_cnt={want}")
    if "dep_abort_edge_cnt" in summary and rc:
        got = int(summary["dep_abort_edge_cnt"])
        want = sum(rc.values())
        if got != want:
            bad.append(f"depgraph: dep_abort_edge_cnt={got} != "
                       f"sum(abort_*_cnt)={want}")
    if timeline is not None:
        def colsum(col):
            return int(np.asarray(timeline[col]).sum())
        for col, key in (("commit", "txn_cnt"),
                         ("abort", "total_txn_abort_cnt"),
                         ("admit", "local_txn_start_cnt"),
                         ("vabort", "vabort_cnt"),
                         ("user_abort", "user_abort_cnt"),
                         ("lock_wait", "twopl_wait_cnt")):
            if col in timeline and key in summary:
                got, want = colsum(col), int(summary[key])
                if got != want:
                    bad.append(f"timeline: sum({col})={got} != "
                               f"{key}={want}")
        for phase, key, col in _PHASES:
            if col and col in timeline and key in summary:
                got, want = colsum(col), int(summary[key])
                if got != want:
                    bad.append(f"waterfall: {phase} occupancy sum({col})="
                               f"{got} != {key}={want}")
        # per-reason series integrate to the taxonomy counters
        for name, cnt in rc.items():
            col = f"abort_{name}"
            if col in timeline:
                got = colsum(col)
                if got != cnt:
                    bad.append(f"timeline: sum({col})={got} != "
                               f"abort_{name}_cnt={cnt}")
    return bad


def hot_keys(stats: dict, topk: int = 8) -> list:
    """Top-k contended keys from the hashed conflict histogram
    (``Config.heatmap_bins``); list of ``{"key", "hits"}`` dicts,
    hits-descending.  The per-bin key is the LARGEST key that hashed into
    the bin (a representative, exact unless keys collide in the bin);
    sharded stacked ``(N, bins)`` arrays contribute per-node entries,
    merged by key."""
    if "arr_conflict_hist" not in stats:
        return []
    hist = np.asarray(stats["arr_conflict_hist"]).reshape(-1)
    keys = np.asarray(stats["arr_conflict_key"]).reshape(-1)
    agg = {}
    for k, h in zip(keys.tolist(), hist.tolist()):
        if h > 0:
            agg[k] = agg.get(k, 0) + h
    ranked = sorted(agg.items(), key=lambda kv: (-kv[1], kv[0]))
    return [{"key": int(k), "hits": int(h)} for k, h in ranked[:topk]]


def _ctrl_section(summary: dict) -> dict | None:
    """The ``[ctrl]`` section: what the adaptive contention controller
    (Config.adaptive, deneva_tpu/ctrl/) DID over the run — escalation /
    de-escalation churn, serialization-gate stalls, width-ladder steps,
    the end-of-run gear/occupancy, and the per-reason backoff bases it
    converged to.  ``None`` (section omitted) when the run did not carry
    the controller.  Sharded summaries sum the scalars over nodes, so
    bases/gauges read as node-totals there."""
    if "ctrl_escalate_cnt" not in summary:
        return None
    from deneva_tpu.cc.base import ABORT_REASONS
    from deneva_tpu.ctrl import CTRL_SCALE
    bases = {name: int(summary[f"ctrl_base_{name}"])
             for name in ABORT_REASONS
             if f"ctrl_base_{name}" in summary}
    return {
        "escalations": int(summary.get("ctrl_escalate_cnt", 0)),
        "deescalations": int(summary.get("ctrl_deescalate_cnt", 0)),
        "gate_blocks": int(summary.get("ctrl_esc_block_cnt", 0)),
        "width_steps": int(summary.get("ctrl_width_step_cnt", 0)),
        "esc_active": int(summary.get("ctrl_esc_active", 0)),
        "width_idx": int(summary.get("ctrl_width_idx", 0)),
        "occ_ewma": int(summary.get("ctrl_occ_ewma", 0)) >> CTRL_SCALE,
        "backoff_bases": bases,
    }


def _slo_section(summary: dict) -> dict | None:
    """The ``[slo]`` section: the live objective view of a ``Config.slo``
    run — per-family quantiles routed through the EXACT mergeable
    histograms (obs/histo.py; not the tail-biased famlat survivor rings),
    the fast/slow error-budget burn rates, alert state and breach
    tallies (obs/slo.py SloTracker, merged into the summary by serve-mode
    callers).  ``None`` (section omitted) when the plane was off."""
    if "hist_total_cnt" not in summary:
        return None
    fams = sorted({int(k[len("slo_fam"):].split("_")[0])
                   for k in summary if k.startswith("slo_fam")})
    out = {
        "families": [{
            "family": f,
            "n": int(summary.get(f"slo_fam{f}_n", 0)),
            "p50": float(summary.get(f"slo_fam{f}_p50", 0.0)),
            "p95": float(summary.get(f"slo_fam{f}_p95", 0.0)),
            "p99": float(summary.get(f"slo_fam{f}_p99", 0.0)),
        } for f in fams],
        "hist_total": int(summary["hist_total_cnt"]),
    }
    if "burn_fast" in summary:
        out.update({
            "burn_fast": float(summary["burn_fast"]),
            "burn_slow": float(summary["burn_slow"]),
            "served_frac": float(summary.get("burn_served_frac", 1.0)),
            "abort_rate": float(summary.get("burn_abort_rate", 0.0)),
            "alert_active": int(summary.get("slo_alert_active", 0)),
            "alerts": int(summary.get("slo_alert_cnt", 0)),
            "breach_ticks": int(summary.get("slo_breach_ticks", 0)),
        })
    return out


def _dep_section(summary: dict, depgraph: dict | None,
                 flight: dict | None = None, topk: int = 8) -> dict | None:
    """The ``[depgraph]`` section: what the conflict dependency
    observatory (Config.depgraph, obs/depgraph.py) measured — exact
    edge-counter totals from the summary, plus (when a ``snapshot()``
    dict rides along) the sampled-graph views: wait-chain depth
    histogram, cycles detected over the sampled edges, and the commit
    critical paths joined against the flight recorder's sampled spans.
    ``None`` (section omitted) when the plane was off."""
    if "dep_wait_edge_cnt" not in summary:
        return None
    ticks = max(int(summary.get("measured_ticks", 0)), 1)
    out = {
        "wait_edges": int(summary["dep_wait_edge_cnt"]),
        "abort_edges": int(summary.get("dep_abort_edge_cnt", 0)),
        "cross_edges": int(summary.get("dep_cross_edge_cnt", 0)),
        "nullkey_edges": int(summary.get("dep_nullkey_edge_cnt", 0)),
        "peak_depth": int(summary.get("dep_peak_depth", 0)),
        "peak_convoy": int(summary.get("dep_peak_convoy", 0)),
        "mean_depth_sum": float(summary.get("dep_depth_sum", 0)) / ticks,
        "mean_convoy": float(summary.get("dep_convoy_width_sum", 0))
        / ticks,
        "ring_cnt": int(summary.get("dep_ring_cnt", 0)),
        "ring_wrapped": bool(summary.get("dep_ring_wrapped", 0)),
    }
    if depgraph is not None:
        from deneva_tpu.obs import depgraph as obs_depgraph
        out["depth_hist"] = [int(v) for v in depgraph["depth_hist"]]
        out["part_edges"] = [int(v) for v in depgraph["part_edges"]]
        cyc = obs_depgraph.cycles(depgraph)
        out["cycles"] = len(cyc)
        if cyc:
            out["cycle_samples"] = cyc[:topk]
        if flight is not None:
            out["critical_paths"] = obs_depgraph.critical_paths(
                depgraph, flight, topk=topk)
    return out


def build_report(summary: dict, timeline: dict | None = None,
                 stats: dict | None = None, topk: int = 8,
                 xmeter: dict | None = None,
                 flight: dict | None = None,
                 mesh: dict | None = None,
                 diagnosis: dict | None = None,
                 depgraph: dict | None = None) -> dict:
    """The machine-readable waterfall: phases (slot-ticks + share),
    throughput, the abort taxonomy, hot keys / per-partition conflicts /
    wait-depth histogram (when the run kept a heatmap), reconciliation
    failures and watchdog findings.  ``xmeter`` (an
    obs/xmeter.py XMeter.snapshot()) adds the compile/roofline section:
    per-entry compile counts, post-warmup violations, and the
    achieved-vs-peak roofline rows.  ``flight`` (an obs/flight.py
    ``snapshot()``) adds the ``[tail]`` section: which lifecycle phase,
    abort reasons and keys dominate the p99-and-above latency cohort."""
    phases = {}
    total = 0
    for phase, key, _ in _PHASES:
        v = int(summary.get(key, 0))
        phases[phase] = v
        total += v
    commits = int(summary.get("txn_cnt", 0))
    aborts = int(summary.get("total_txn_abort_cnt", 0))
    rep = {
        "ticks": int(summary.get("measured_ticks", 0)),
        "commits": commits,
        "aborts": aborts,
        "abort_rate": float(summary.get(
            "abort_rate", aborts / max(aborts + commits, 1))),
        "phases": phases,
        "phase_ticks_total": total,
        "abort_reasons": _reason_counts(summary),
        "top_reasons": top_reasons(summary, k=topk),
    }
    if stats is not None:
        rep["hot_keys"] = hot_keys(stats, topk=topk)
        if "arr_part_conflict" in stats:
            pc = np.asarray(stats["arr_part_conflict"])
            rep["part_conflicts"] = pc.reshape(-1, pc.shape[-1]) \
                                      .sum(axis=0).tolist() \
                if pc.ndim > 1 else pc.tolist()
        if "arr_wait_depth_hist" in stats:
            wd = np.asarray(stats["arr_wait_depth_hist"])
            rep["wait_depth_hist"] = wd.reshape(-1, wd.shape[-1]) \
                                       .sum(axis=0).tolist() \
                if wd.ndim > 1 else wd.tolist()
    if xmeter is not None:
        rep["xmeter"] = {
            "compile_cnt": int(xmeter.get("compile_cnt", 0)),
            "compile_ms": float(xmeter.get("compile_ms", 0.0)),
            "steady_violations": list(xmeter.get("steady_violations",
                                                 [])),
            "roofline": list(xmeter.get("roofline", [])),
        }
    if flight is not None:
        from deneva_tpu.obs.flight import tail_attribution
        rep["tail"] = tail_attribution(flight, topk=topk)
    if mesh is not None:
        # the [mesh] section: pass an obs/mesh.py mesh_report dict (or a
        # run record's "mesh" field) — per-node-pair traffic volumes,
        # type breakdown, load planes and the imbalance block
        rep["mesh"] = mesh
    if diagnosis is not None:
        # the [diagnosis] section: pass an obs/diff.py diagnosis dict
        # (run diff, window-vs-window diff, or a regress-gate triage) —
        # ranked causes with their config levers ride the report
        rep["diagnosis"] = diagnosis
    dep = _dep_section(summary, depgraph, flight=flight, topk=topk)
    if dep is not None:
        rep["depgraph"] = dep
    ctrl = _ctrl_section(summary)
    if ctrl is not None:
        rep["ctrl"] = ctrl
    slo = _slo_section(summary)
    if slo is not None:
        rep["slo"] = slo
    rep["reconcile_failures"] = reconcile(summary, timeline)
    findings, code = watchdog(summary, timeline,
                              precomputed_reconcile=rep["reconcile_failures"],
                              mesh=mesh)
    rep["watchdog"] = {"exit_code": code, "findings": findings}
    return rep


def watchdog(summary: dict, timeline: dict | None = None,
             precomputed_reconcile: list | None = None,
             mesh: dict | None = None) -> tuple:
    """(findings, exit_bitmask).  Each finding is ``(FLAG_NAME, message)``;
    the bitmask ORs the flags the module docstring defines."""
    findings = []
    code = 0

    rec = (reconcile(summary, timeline)
           if precomputed_reconcile is None else precomputed_reconcile)
    for msg in rec:
        findings.append(("RECONCILE", msg))
        code |= RECONCILE

    commits = int(summary.get("txn_cnt", 0))
    aborts = int(summary.get("total_txn_abort_cnt", 0))
    if timeline is not None and "commit" in timeline:
        cm = np.asarray(timeline["commit"])
        ab = np.asarray(timeline.get("abort", np.zeros_like(cm)))
        ad = np.asarray(timeline.get("admit", np.zeros_like(cm)))
        if cm.ndim > 1:                   # (N, T) per-shard view
            per_shard = cm.sum(axis=1)
            if commits > 0 and (per_shard == 0).any():
                idle = np.nonzero(per_shard == 0)[0].tolist()
                findings.append(
                    ("STARVED", f"shards {idle} committed 0 txns while "
                                f"the cluster committed {commits}"))
                code |= STARVED
            cm, ab, ad = cm.sum(axis=0), ab.sum(axis=0), ad.sum(axis=0)
        # longest zero-commit streak with churn (aborts or admissions
        # firing inside it): the live-lock signature
        streak = best = churn = best_churn = 0
        for c, a, d in zip(cm.tolist(), ab.tolist(), ad.tolist()):
            if c == 0:
                streak += 1
                churn += a + d
                if streak > best:
                    best, best_churn = streak, churn
            else:
                streak = churn = 0
        if best >= LIVELOCK_WINDOW and best_churn > 0:
            findings.append(
                ("LIVELOCK", f"zero-commit window of {best} ticks with "
                             f"{best_churn} aborts/admissions inside it"))
            code |= LIVELOCK
    elif commits == 0 and aborts > 0:
        findings.append(("LIVELOCK",
                         f"0 commits against {aborts} aborts"))
        code |= LIVELOCK

    spills = int(summary.get("abort_compact_spill_cnt", 0))
    ovf = int(summary.get("compact_overflow_cnt", 0))
    if max(spills, ovf) > SPILL_FRAC * max(commits + aborts, 1):
        findings.append(
            ("SPILL", f"compaction spill storm: spill_aborts={spills} "
                      f"overflow={ovf} vs {commits + aborts} outcomes"))
        code |= SPILL

    # open-system overload: the run ended with more admission backlog
    # than one measured tick of service can drain.  A recovered flash
    # crowd (deneva_tpu/traffic/ rate-step schedule back below the knee)
    # ends with queue_len == 0 and does NOT fire; a sustained
    # over-offered rate leaves the queue growing and does.  Keys are
    # present only for Config.arrival runs — closed-loop summaries skip
    # this check entirely.
    if "queue_len" in summary:
        qlen = int(summary["queue_len"])
        ticks = max(int(summary.get("measured_ticks", 0)), 1)
        service = max(1, commits // ticks)
        if qlen > service:
            findings.append(
                ("OVERLOAD", f"admission backlog at run end: "
                             f"queue_len={qlen} > {service} "
                             f"commits/tick (peak={int(summary.get('queue_peak', 0))}, "
                             f"arrivals={int(summary.get('arrival_cnt', 0))})"))
            code |= OVERLOAD

    # mesh-run shard imbalance: Jain over per-node commit loads (from the
    # [mesh] section when given, else the summary's imb_jain key — both
    # exist only for Config.mesh runs, so other summaries skip this)
    from deneva_tpu.obs.mesh import IMB_JAIN_MIN
    jain_v = None
    if mesh is not None:
        jain_v = float(mesh.get("imbalance", {}).get("imb_jain", 1.0))
    elif "imb_jain" in summary:
        jain_v = float(summary["imb_jain"])
    if jain_v is not None and commits > 0 and jain_v < IMB_JAIN_MIN:
        strag = ""
        if mesh is not None:
            imb = mesh.get("imbalance", {})
            if "straggler_node" in imb:
                strag = (f" (straggler node {imb['straggler_node']}, "
                         f"{imb.get('straggler_ticks', 0)} peak ticks)")
        findings.append(
            ("IMBALANCE", f"Jain fairness {jain_v:.3f} < {IMB_JAIN_MIN} "
                          f"over per-node commit loads{strag}"))
        code |= IMBALANCE

    # fault-plane recovery parity: a kill-a-node run must recover by
    # deterministic replay to a bit-identical shard slice (and CALVIN
    # epoch log).  Keys are host-side counters merged by the fault
    # driver (faults/recovery.py run_with_faults) — present only for
    # Config.faults runs with kills, so other summaries skip this.
    kills = int(summary.get("fault_kill_cnt", 0))
    if kills > 0:
        replay_ok = int(summary.get("recovery_replay_ok", 0))
        elog_ok = int(summary.get("recovery_elog_ok", 1))
        if replay_ok < 1 or elog_ok < 1:
            what = ("replayed shard slice" if replay_ok < 1
                    else "CALVIN epoch log")
            findings.append(
                ("RECOVERY", f"{what} diverged from the pre-crash "
                             f"oracle after {kills} kill(s) "
                             f"({int(summary.get('fault_replay_ticks', 0))} "
                             f"ticks replayed) — recovery is not "
                             f"deterministic"))
            code |= RECOVERY

    # SLO error-budget alert still firing at run end (Config.slo serve
    # runs merge obs/slo.py SloTracker fields into the summary): a
    # drained flash crowd clears the alert before the run ends; a
    # sustained breach leaves it active.  The exact histogram identity
    # failing is the same flag — the plane's numbers can't be trusted.
    if int(summary.get("slo_alert_active", 0)) > 0:
        findings.append(
            ("SLO", f"error-budget alert ACTIVE at run end: "
                    f"burn fast={float(summary.get('burn_fast', 0.0)):.2f}x"
                    f" slow={float(summary.get('burn_slow', 0.0)):.2f}x "
                    f"budget ({int(summary.get('slo_breach_ticks', 0))} "
                    f"ticks in breach over "
                    f"{int(summary.get('slo_alert_cnt', 0))} alert(s))"))
        code |= SLO
    if "hist_total_cnt" in summary and any(
            f[0] == "RECONCILE" and f[1].startswith("histogram:")
            for f in findings):
        code |= SLO

    # convoy serialization (Config.depgraph runs, obs/depgraph.py): the
    # RUN-MEAN convoy width — txns parked behind a single blocker, per
    # measured tick — held at CONVOY_WIDTH_MIN or above.  A transient
    # pile-up averages out; a gate/hot-row convoy that serialized the
    # whole measured window does not.
    if "dep_convoy_width_sum" in summary:
        ticks = max(int(summary.get("measured_ticks", 0)), 1)
        mean_w = int(summary["dep_convoy_width_sum"]) / ticks
        if mean_w >= CONVOY_WIDTH_MIN:
            findings.append(
                ("CONVOY", f"sustained convoy: mean width "
                           f"{mean_w:.1f} >= {CONVOY_WIDTH_MIN} txns "
                           f"behind one blocker (peak "
                           f"{int(summary.get('dep_peak_convoy', 0))}, "
                           f"peak chain depth "
                           f"{int(summary.get('dep_peak_depth', 0))})"))
            code |= CONVOY
    return findings, code


def render_text(rep: dict) -> str:
    """Terminal waterfall (fixed-width bars, no color)."""
    lines = []
    total = max(rep["phase_ticks_total"], 1)
    lines.append(f"[waterfall] where did the time go "
                 f"({rep['phase_ticks_total']} txn-slot-ticks over "
                 f"{rep['ticks']} ticks)")
    for phase, v in rep["phases"].items():
        frac = v / total
        bar = "#" * int(round(frac * 40))
        lines.append(f"  {phase:<14} {bar:<40} {v:>10} ({frac:6.1%})")
    n_ab = sum(rep["abort_reasons"].values())
    lines.append(f"[aborts] why did we abort "
                 f"(rate {rep['abort_rate']:.3f}; {rep['commits']} commits"
                 f" / {rep['aborts']} aborts)")
    if rep["abort_reasons"]:
        for name, c in sorted(rep["abort_reasons"].items(),
                              key=lambda kv: -kv[1]):
            if c == 0:
                continue
            frac = c / max(n_ab, 1)
            bar = "#" * int(round(frac * 40))
            lines.append(f"  {name:<20} {bar:<40} {c:>8} ({frac:6.1%})")
    else:
        lines.append("  (run without Config.abort_attribution "
                     "-- no taxonomy)")
    if rep.get("hot_keys"):
        lines.append("[hotkeys] most contended rows "
                     "(hashed heatmap representatives)")
        for hk in rep["hot_keys"]:
            lines.append(f"  key={hk['key']:<10} hits={hk['hits']}")
    if rep.get("wait_depth_hist"):
        wd = rep["wait_depth_hist"]
        lines.append("[waitdepth] wait-streak length histogram "
                     f"(ticks waited; last bin = >={len(wd) - 1}): "
                     + " ".join(str(v) for v in wd))
    if rep.get("xmeter") is not None:
        xr = rep["xmeter"]
        lines.append(f"[compile] {xr['compile_cnt']} compiles, "
                     f"{xr['compile_ms']:.1f} ms"
                     + ("" if not xr["steady_violations"] else
                        f"; {len(xr['steady_violations'])} POST-WARMUP "
                        "recompile(s):"))
        for v in xr["steady_violations"]:
            lines.append(f"  RECOMPILE {v.get('entry')}: "
                         f"{v.get('signature')}")
        if xr["roofline"]:
            lines.append("[roofline] achieved vs peak per entry point")
            for r in xr["roofline"]:
                lines.append(
                    f"  {r['entry']:<14} {r['mean_ms']:>8.3f} ms  "
                    f"{r['achieved_gflops']:>8.2f} GFLOP/s "
                    f"({r['peak_flop_frac']:6.2%})  "
                    f"{r['achieved_gbps']:>8.2f} GB/s "
                    f"({r['peak_bw_frac']:6.2%})  {r['bound']}-bound")
    if rep.get("tail") is not None:
        tl = rep["tail"]
        if tl.get("cohort"):
            lines.append(
                f"[tail] p{tl['pct']:g} cohort: {tl['cohort']}/{tl['n']} "
                f"spans at >= {tl['p_ticks']:.0f} ticks "
                f"(max {tl['max_ticks']}), avg {tl['avg_restarts']:.1f} "
                f"restarts, dominant phase {tl['dominant_phase']}")
            total_t = max(sum(tl["phase_ticks"].values()), 1)
            for phase, v in tl["phase_ticks"].items():
                frac = v / total_t
                delta = frac - tl["all_share"].get(phase, 0.0)
                bar = "#" * int(round(frac * 40))
                lines.append(f"  {phase:<14} {bar:<40} {v:>10} "
                             f"({frac:6.1%}, {delta:+6.1%} vs all)")
            for name, c in tl.get("top_reasons", []):
                lines.append(f"  tail-abort {name:<20} {c}")
            for key, c in tl.get("top_keys", []):
                lines.append(f"  tail-key   {key:<20} {c}")
        else:
            lines.append("[tail] no completed spans sampled")
    if rep.get("mesh") is not None:
        m = rep["mesh"]
        total = sum(m["by_type"].values())
        lines.append(
            f"[mesh] {m['nodes']}-node traffic matrix: {total} messages "
            f"over {m['ticks']} ticks (drops={m['drops']})")
        by_type = "  ".join(f"{name}={cnt}"
                            for name, cnt in m["by_type"].items() if cnt > 0)
        lines.append("  types  " + (by_type or "(no cross-node traffic)"))
        for p in m.get("top_pairs", []):
            lines.append(f"  pair {p['src']}->{p['dst']:<3} "
                         f"{p['msgs']:>10} msgs")
        imb = m.get("imbalance", {})
        imb_line = f"  imbalance jain={imb.get('imb_jain', 1.0):.3f}"
        if "imb_jain_occ" in imb:
            imb_line += f" (occupancy {imb['imb_jain_occ']:.3f})"
        if "straggler_node" in imb:
            imb_line += (f"; straggler node {imb['straggler_node']} "
                         f"({imb.get('straggler_ticks', 0)} peak ticks)")
        lines.append(imb_line)
        pn = m.get("per_node", {})
        if "commits" in pn:
            lines.append("  node commits " + " ".join(
                str(v) for v in pn["commits"]))
        if "occ_avg" in pn:
            cap = f" (cap {m['cap']})" if "cap" in m else ""
            lines.append("  exchange occupancy avg " + " ".join(
                str(v) for v in pn["occ_avg"])
                + f", peak {max(pn.get('occ_peak', [0]))}{cap}")
    if rep.get("depgraph") is not None:
        d = rep["depgraph"]
        wrapped = " RING-WRAPPED" if d["ring_wrapped"] else ""
        lines.append(
            f"[depgraph] wait-for graph: {d['wait_edges']} wait / "
            f"{d['abort_edges']} abort edges "
            f"({d['cross_edges']} cross-node, "
            f"{d['nullkey_edges']} keyless); chain depth "
            f"mean {d['mean_depth_sum']:.1f} peak {d['peak_depth']}; "
            f"convoy width mean {d['mean_convoy']:.1f} "
            f"peak {d['peak_convoy']}; "
            f"{d['ring_cnt']} edges sampled{wrapped}")
        if d.get("depth_hist"):
            dh = d["depth_hist"]
            lines.append("  depth hist (waiters at chain depth d; last "
                         f"bin = >={len(dh) - 1}): "
                         + " ".join(str(v) for v in dh))
        if d.get("cycles"):
            lines.append(f"  CYCLES: {d['cycles']} deadlock cycle(s) in "
                         "the sampled graph")
            for c in d.get("cycle_samples", []):
                path = " -> ".join(f"{n}:{s}" for n, s in c["cycle"])
                lines.append(f"    tick {c['tick']}: {path}")
        for cp in d.get("critical_paths", []):
            path = " -> ".join(f"{e['node']}:{e['waiter']}"
                               for e in cp["path"])
            lines.append(
                f"  critical-path slot {cp['node']}:{cp['slot']} "
                f"latency {cp['latency']} (blocked {cp['block_ticks']}) "
                f"depth {cp['max_depth']}@t{cp['at_tick']}: {path}")
    if rep.get("ctrl") is not None:
        c = rep["ctrl"]
        lines.append(
            f"[ctrl] adaptive controller decisions: "
            f"{c['escalations']} escalation(s) / "
            f"{c['deescalations']} de-escalation(s), "
            f"{c['gate_blocks']} gate stall(s), "
            f"{c['width_steps']} width step(s); "
            f"end state: {c['esc_active']} key(s) escalated, "
            f"gear {c['width_idx']}, occupancy ewma {c['occ_ewma']}")
        bases = {n: b for n, b in c["backoff_bases"].items() if b > 0}
        if bases:
            lines.append("  backoff bases (ticks): " + " ".join(
                f"{n}={b}" for n, b in sorted(bases.items(),
                                              key=lambda kv: -kv[1])))
    if rep.get("slo") is not None:
        sl = rep["slo"]
        lines.append(f"[slo] exact-histogram latency objectives "
                     f"({sl['hist_total']} commits binned)")
        for fr in sl["families"]:
            lines.append(
                f"  fam{fr['family']:<3} n={fr['n']:<8} "
                f"p50={fr['p50']:<8g} p95={fr['p95']:<8g} "
                f"p99={fr['p99']:<8g} ticks")
        if "burn_fast" in sl:
            state = "FIRING" if sl["alert_active"] else "ok"
            lines.append(
                f"  budget burn fast={sl['burn_fast']:.2f}x "
                f"slow={sl['burn_slow']:.2f}x  served={sl['served_frac']:.3f}"
                f"  abort_rate={sl['abort_rate']:.3f}  alert={state} "
                f"({sl['alerts']} fired, {sl['breach_ticks']} breach ticks)")
    if rep.get("diagnosis") is not None:
        from deneva_tpu.obs.diff import render_diagnosis
        lines.append(render_diagnosis(rep["diagnosis"]))
    for flag, msg in rep["watchdog"]["findings"]:
        lines.append(f"[watchdog] {flag}: {msg}")
    if not rep["watchdog"]["findings"]:
        lines.append("[watchdog] clean")
    return "\n".join(lines)


def report_from_record(rec: dict) -> dict:
    """Build the report from a run-record JSON document
    (obs/profiler.py write_run_record)."""
    return build_report(rec["summary"], rec.get("timeline"),
                        xmeter=rec.get("xmeter"),
                        flight=rec.get("flight"),
                        mesh=rec.get("mesh"),
                        diagnosis=rec.get("diagnosis"),
                        depgraph=rec.get("depgraph"))


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        description="waterfall + abort-attribution report from a "
                    "run record; exits with the watchdog bitmask")
    p.add_argument("record", help="run_record JSON path "
                                  "(obs/profiler.py write_run_record)")
    p.add_argument("--json", action="store_true",
                   help="print the machine-readable report instead")
    args = p.parse_args(argv)
    with open(args.record) as f:
        rec = json.load(f)
    rep = report_from_record(rec)
    if args.json:
        print(json.dumps(rep))
    else:
        print(render_text(rep))
    return rep["watchdog"]["exit_code"]


if __name__ == "__main__":           # pragma: no cover - CLI shim
    raise SystemExit(main())
