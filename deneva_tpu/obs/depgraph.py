"""Conflict dependency observatory: the device-resident wait-for graph
(obs pillar 8 — "WHO is in the way", the question the reference answers
by walking lock-owner lists in a debugger).

Every prior pillar measures the fleet (counters, timelines, windows) or
the victim (flight spans, abort taxonomy).  None of them name the OTHER
txn: the lock holder a WAIT parked behind, the conflicting writer a
TIMESTAMP/MVCC abort lost to, the validation victim an OCC rollback was
charged against.  Opt-in through ``Config.depgraph`` (requires
``abort_attribution``), every cc plugin emits a blocker identity
alongside its grant/wait/abort decision (``AccessDecision.blocker``,
slot+1 wire encoding, 0 = none) and the engine carries four device
planes inside the stats dict:

- **edge ring** ``arr_dep_ring`` (``Config.dep_samples`` rows x
  EDGE_COLUMNS): one sampled ``(waiter, blocker, key, reason, tick,
  node)`` row per WAIT decision (reason 0) and per abort EVENT (the
  normalized cc/base.py reason code), appended with the repo's
  keep-last ring + distinct-OOB-dead-lane scatter discipline (LINT.md)
  at EXACTLY the sites that bump ``twopl_wait_cnt`` and the
  ``abort_<reason>_cnt`` taxonomy — same masks, same warmup gate on the
  counters — so the ring partitions exactly against both families;
- **blocker-pointer plane** ``arr_dep_blocker`` (``(B,)``; -1 = not
  waiting): this tick's wait-for graph as a functional graph (each
  waiter names at most one blocker), refreshed from the access
  decisions every tick;
- **aggregate planes**: chain-depth histogram ``arr_dep_depth_hist``
  (last bin saturates: cycles land there), per-partition edge counts
  ``arr_dep_part`` (key % part_cnt; keyless whole-txn events count in
  ``dep_nullkey_edge_cnt`` so the partition plane still sums exactly),
  and the run-peak gauges ``arr_dep_peak`` ([max chain depth, max
  convoy width] — max-merged across nodes, never summed);
- **summable scalars** ``dep_*`` (0-d int32, auto-[summary] /
  auto-psum / window-vocabulary like every other counter):
  ``dep_wait_edge_cnt``, ``dep_abort_edge_cnt``,
  ``dep_nullkey_edge_cnt``, ``dep_cross_edge_cnt`` (sharded: blocker
  resident on another node), ``dep_depth_sum`` (per-tick sum of
  waiting lanes' chain depths) and ``dep_convoy_width_sum`` (per-tick
  max blocker in-degree).

Chain depth is computed per tick by ITERATED POINTER DOUBLING over the
blocker plane (``chain_depths``): ceil(log2(B)) gather rounds instead
of a B-step walk, cycles saturate instead of hanging.  The convoy plane
is the blocker in-degree histogram — a depth-1 convoy of width w is w
txns parked behind one holder, the gate-serialization signature.

Exactness contract (the PR 4 taxonomy / PR 6 conservation discipline),
for every plugin and both engines while the ring has not wrapped::

    dep_wait_edge_cnt            == twopl_wait_cnt
    dep_abort_edge_cnt           == sum(abort_<reason>_cnt)
    ring rows (reason == 0)      == dep_wait_edge_cnt        (measured)
    ring rows (reason == r)      == abort_<r>_cnt            (per r)
    sum(arr_dep_part) + dep_nullkey_edge_cnt
                                 == dep_wait + dep_abort edges

A wrapped ring REFUSES to reconcile (loudly, first finding) rather than
degrade to approximate counts.

In ``ShardedEngine`` blocker identities are GLOBAL txn ids
(``node * B + slot``), the per-tick blocker planes all_gather into one
cluster-wide functional graph (so a chain crossing nodes measures its
true depth on every member's home node), and the summable planes psum
into a cluster plane bit-equal to the numpy shard sum.

Host-side exports:

- :func:`snapshot`        numpy -> dicts (edges with node tags + the
                          aggregate planes);
- :func:`reconcile`       the exact identities above, as mismatch
                          tuples (tests + the bench --depgraph gate);
- :func:`cycles`          would-be-deadlock cycles over each tick's
                          sampled functional graph (O(edges));
- :func:`critical_paths`  commit critical-path decomposition: the
                          longest blocking chain behind each sampled
                          commit, joined against the obs/flight.py
                          span ring;
- :func:`flow_events`     Perfetto FLOW arrows blocker -> waiter that
                          merge into the flight span track (string
                          ``dep<n>`` flow ids — a namespace that can
                          never collide with the recorder's integer
                          abort-flow ids);
- :func:`summary_keys` / :func:`record_extra`  [summary] bookkeeping
                          and the run-record ``"depgraph"`` block
                          (obs/report.py renders it as [depgraph]).

When ``Config.depgraph`` is False (default) no arrays are carried and
the [summary] line is byte-identical to a build without this module
(config._optin registers the claim; tests/test_certify.py proves it).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from deneva_tpu.cc import base as cc_base
from deneva_tpu.engine.state import NULL_KEY

#: edge row schema.  ``waiter``/``blocker`` are txn slots (GLOBAL ids
#: ``node * B + slot`` in the sharded engine; blocker -1 = the decision
#: carried no identity — e.g. a window-mode fast path or a
#: history-conflict abort with no live opponent); ``reason`` 0 = WAIT
#: edge, else the normalized cc/base.py abort code; ``key`` the
#: contended row (NULL_KEY for whole-txn events); ``tick`` the decision
#: tick; ``node`` the WAITER's home node.
EDGE_COLUMNS = ("waiter", "blocker", "key", "reason", "tick", "node")
DCOL = {name: i for i, name in enumerate(EDGE_COLUMNS)}

#: chain-depth histogram bins; the last bin saturates (depth >=
#: DEPTH_BINS - 1, including cycle members, whose doubled depth clamps)
DEPTH_BINS = 16

#: run-peak gauge layout of ``arr_dep_peak``
PEAK_COLUMNS = ("depth", "convoy")


# ---------------------------------------------------------------------------
# device side (jit-safe; every helper no-ops when the plane is absent)
# ---------------------------------------------------------------------------

def init_depgraph(cfg) -> dict:
    """Stats-dict entries for the observatory; empty when off (the
    disabled path carries nothing).  The ``dep_*`` 0-d scalars ride the
    generic counter machinery (summary scrape, sharded psum, window
    vocabulary); the ``arr_*`` planes are excluded from all three and
    fetched whole by :func:`snapshot`."""
    if not cfg.depgraph:
        return {}
    B, S = cfg.batch_size, cfg.dep_samples
    out = {
        "arr_dep_ring": jnp.zeros((S, len(EDGE_COLUMNS)), jnp.int32),
        "arr_dep_blocker": jnp.full((B,), -1, jnp.int32),
        "arr_dep_depth_hist": jnp.zeros((DEPTH_BINS,), jnp.int32),
        "arr_dep_part": jnp.zeros((cfg.part_cnt,), jnp.int32),
        "arr_dep_peak": jnp.zeros((len(PEAK_COLUMNS),), jnp.int32),
        # cumulative ring appends: the cursor (pos = cnt + rank mod S)
        # and the host's wrap detector; arr_-prefixed on purpose — the
        # per-node value must NOT be psum-merged (wrap detection is
        # per-ring), snapshot/summary_keys read it raw
        "arr_dep_cnt": jnp.zeros((), jnp.int32),
    }
    for k in ("dep_wait_edge_cnt", "dep_abort_edge_cnt",
              "dep_nullkey_edge_cnt", "dep_cross_edge_cnt",
              "dep_depth_sum", "dep_convoy_width_sum"):
        out[k] = jnp.zeros((), jnp.int32)
    return out


def note_waits(stats: dict, wait_b, blocker_b) -> dict:
    """Refresh the blocker-pointer plane from this tick's access
    decisions: waiting lanes point at their blocker's slot (-1 = waiting
    with no identified blocker), every other lane clears to -1.  Called
    once per tick at the SAME site that bumps ``twopl_wait_cnt``."""
    if "arr_dep_blocker" not in stats:
        return stats
    return {**stats,
            "arr_dep_blocker": jnp.where(wait_b, blocker_b, -1)
            .astype(jnp.int32)}


def record_edges(stats: dict, counter: str, mask_b, blocker_b, key_b,
                 reason_b, t, measuring, node=0, cross_b=None) -> dict:
    """Scatter one edge row per masked lane into the keep-last ring and
    bump ``counter`` (``dep_wait_edge_cnt`` / ``dep_abort_edge_cnt``)
    by the MEASURED edge count — the same warmup gate as the counter
    family the identity targets.  The ring itself records warmup edges
    too (the host filters by tick, obs/flight.py discipline), so the
    trace shows warmup dynamics.  ``blocker_b`` is the resolved slot
    (-1 = none), NOT the wire slot+1 encoding; ``cross_b`` marks edges
    whose blocker lives on another node (sharded engine)."""
    if "arr_dep_ring" not in stats:
        return stats
    ring = stats["arr_dep_ring"]
    cap = ring.shape[0]
    B = mask_b.shape[0]
    m32 = mask_b.astype(jnp.int32)
    rank = jnp.cumsum(m32) - m32
    n = jnp.sum(m32)
    live = mask_b & (rank >= n - cap)
    pos = jnp.where(live, (stats["arr_dep_cnt"] + rank) % cap,
                    cap + jnp.arange(B, dtype=jnp.int32))
    row = jnp.stack([
        jnp.arange(B, dtype=jnp.int32)
        + jnp.asarray(node, jnp.int32) * B,               # global waiter
        blocker_b.astype(jnp.int32),
        key_b.astype(jnp.int32),
        jnp.broadcast_to(jnp.asarray(reason_b, jnp.int32), (B,)),
        jnp.broadcast_to(jnp.asarray(t, jnp.int32), (B,)),
        jnp.broadcast_to(jnp.asarray(node, jnp.int32), (B,)),
    ], axis=1)
    meas = mask_b & measuring
    nm = jnp.sum(meas.astype(jnp.int32))
    haskey = key_b != NULL_KEY
    part = stats["arr_dep_part"]
    P = part.shape[0]
    ppos = jnp.where(meas & haskey, key_b % P, P)
    out = {**stats,
           "arr_dep_ring": ring.at[pos].set(row, mode="drop",
                                            unique_indices=True),
           "arr_dep_cnt": stats["arr_dep_cnt"] + n,
           "arr_dep_part": part.at[ppos].add(1, mode="drop"),
           counter: stats[counter] + nm,
           "dep_nullkey_edge_cnt": stats["dep_nullkey_edge_cnt"]
           + jnp.sum((meas & ~haskey).astype(jnp.int32))}
    # lint: disable-next=TRACED-BRANCH is-None STRUCTURE check: cross_b is None iff the caller is the single-shard engine (static per call site), never a traced-value branch
    if cross_b is not None:
        out["dep_cross_edge_cnt"] = stats["dep_cross_edge_cnt"] \
            + jnp.sum((meas & cross_b).astype(jnp.int32))
    return out


def chain_depths(ptr):
    """Chain depth of every lane of a ``(M,)`` blocker-pointer plane
    (-1 = no blocker) by iterated pointer doubling: ceil(log2(M))
    rounds of ``depth[i] += depth[ptr[i]]; ptr[i] = ptr[ptr[i]]``.
    Self-loops are masked; members of longer cycles never reach -1 and
    their depth saturates toward 2^rounds >= M (callers clamp)."""
    M = ptr.shape[0]
    idx = jnp.arange(M, dtype=jnp.int32)
    ptr = jnp.where(ptr == idx, -1, ptr)
    depth = (ptr >= 0).astype(jnp.int32)
    for _ in range(max((M - 1).bit_length(), 1)):
        j = jnp.clip(ptr, 0)
        nd = depth + jnp.where(ptr >= 0, depth[j], 0)
        ptr = jnp.where(ptr >= 0, ptr[j], ptr)
        depth = nd
    return depth


def tick_planes(stats: dict, measuring, ptr=None, lo=None):
    """End-of-tick aggregates from the blocker-pointer plane: chain
    depths (pointer doubling), the depth histogram, the convoy
    (blocker in-degree) width, and the run peaks.  Returns
    ``(stats, depth_max, convoy_width)`` — the per-tick gauges feed the
    trace companion ring (obs/trace.py record_dep).

    Single shard: reads ``arr_dep_blocker`` directly.  Sharded: pass
    the all_gathered GLOBAL plane as ``ptr`` and this node's first
    global slot as ``lo`` — depths/in-degrees compute over the whole
    cluster graph, then each node banks only its OWN ``B`` lanes, so
    the psum of the summable planes counts every lane exactly once
    while cross-node chains still measure their true depth."""
    if "arr_dep_blocker" not in stats:
        return stats, jnp.int32(0), jnp.int32(0)
    local = stats["arr_dep_blocker"]
    B = local.shape[0]
    full = local if ptr is None else ptr
    M = full.shape[0]
    idx = jnp.arange(M, dtype=jnp.int32)
    full = jnp.where(full == idx, -1, full)
    waiting = full >= 0
    depth = jnp.minimum(chain_depths(full), M)   # cycles read as M
    heads = jnp.zeros(M + 1, jnp.int32).at[
        jnp.where(waiting, full, M)].add(1)
    if ptr is None:
        d_l, w_l, h_l = depth, waiting, heads[:M]
    else:
        start = (jnp.asarray(lo, jnp.int32),)
        d_l = jax.lax.dynamic_slice(depth, start, (B,))
        w_l = jax.lax.dynamic_slice(waiting, start, (B,))
        h_l = jax.lax.dynamic_slice(heads, start, (B,))
    d_l = jnp.where(w_l, d_l, 0)
    dmax = jnp.max(d_l)
    width = jnp.max(h_l)
    g = measuring.astype(jnp.int32)
    bins = stats["arr_dep_depth_hist"].shape[0]
    hpos = jnp.where(w_l & measuring, jnp.minimum(d_l, bins - 1), bins)
    out = {**stats,
           "arr_dep_depth_hist":
           stats["arr_dep_depth_hist"].at[hpos].add(1, mode="drop"),
           "arr_dep_peak": jnp.maximum(stats["arr_dep_peak"],
                                       jnp.stack([dmax, width]) * g),
           "dep_depth_sum": stats["dep_depth_sum"] + g * jnp.sum(d_l),
           "dep_convoy_width_sum":
           stats["dep_convoy_width_sum"] + g * width}
    return out, dmax, width


# ---------------------------------------------------------------------------
# host side
# ---------------------------------------------------------------------------

def _ring_rows(ring: np.ndarray, cnt: int) -> np.ndarray:
    """Valid rows of a keep-last ring in chronological order."""
    cap = ring.shape[0]
    if cnt <= cap:
        return ring[:cnt]
    return np.roll(ring, -(cnt % cap), axis=0)


def _edge_dict(r, reasons) -> dict:
    d = {c: int(r[i]) for i, c in enumerate(EDGE_COLUMNS)}
    d["why"] = ("wait" if d["reason"] == 0
                else reasons[min(max(d["reason"], 0), len(reasons) - 1)])
    return d


def snapshot(state_or_stats) -> dict:
    """Fetch the observatory planes as plain dicts (JSON-ready; lands
    in profiler run records under the top-level ``"depgraph"`` key).
    Sharded states arrive node-stacked; per-node rings merge on the
    shared tick clock, summable planes sum, peak gauges max."""
    stats = getattr(state_or_stats, "stats", state_or_stats)
    assert "arr_dep_ring" in stats, "run with Config.depgraph"
    ring = np.asarray(stats["arr_dep_ring"])
    hist = np.asarray(stats["arr_dep_depth_hist"])
    part = np.asarray(stats["arr_dep_part"])
    peak = np.asarray(stats["arr_dep_peak"])
    blk = np.asarray(stats["arr_dep_blocker"])
    cnt = np.asarray(stats["arr_dep_cnt"])
    if ring.ndim == 2:                       # single shard -> 1-node stack
        ring, hist, part, peak, blk = (a[None] for a in
                                       (ring, hist, part, peak, blk))
        cnt = cnt.reshape(1)
    N, S, _ = ring.shape
    B = blk.shape[1]
    reasons = ("wait",) + tuple(cc_base.ABORT_REASONS)
    edges = []
    for node in range(N):
        for r in _ring_rows(ring[node], int(cnt[node])):
            d = _edge_dict(r, reasons)
            if N > 1 and d["blocker"] >= 0:
                d["blocker_node"] = d["blocker"] // B
                d["blocker_slot"] = d["blocker"] % B
            edges.append(d)
    edges.sort(key=lambda d: (d["tick"], d["node"], d["waiter"]))
    out = {"columns": list(EDGE_COLUMNS),
           "nodes": N, "samples": S, "batch": B,
           "edge_cnt": int(cnt.sum()),
           "wrapped": bool((cnt > S).any()),
           "edges": edges,
           "depth_hist": hist.sum(axis=0).tolist(),
           "part_edges": part.sum(axis=0).tolist(),
           "peak_depth": int(peak[:, 0].max()),
           "peak_convoy": int(peak[:, 1].max())}
    for k in ("dep_wait_edge_cnt", "dep_abort_edge_cnt",
              "dep_nullkey_edge_cnt", "dep_cross_edge_cnt",
              "dep_depth_sum", "dep_convoy_width_sum"):
        out[k] = int(np.asarray(stats[k]).sum())
    return out


def reconcile(snap: dict, summary: dict, warmup_ticks: int = 0) -> list:
    """The full-sampling exactness checks, as ``(what, got, want)``
    mismatch tuples (empty = exact).  A wrapped ring is REFUSED — it is
    reported as the sole finding and nothing else is checked, because a
    keep-last window cannot prove any of the count identities."""
    if snap["wrapped"]:
        return [("dep_ring_wrapped", snap["edge_cnt"], snap["samples"])]
    bad = []
    if "twopl_wait_cnt" in summary:
        want = int(summary["twopl_wait_cnt"])
        if snap["dep_wait_edge_cnt"] != want:
            bad.append(("wait_edges_vs_twopl_wait",
                        snap["dep_wait_edge_cnt"], want))
    meas = [e for e in snap["edges"] if e["tick"] >= warmup_ticks]
    got = sum(1 for e in meas if e["reason"] == 0)
    if got != snap["dep_wait_edge_cnt"]:
        bad.append(("ring_wait_rows", got, snap["dep_wait_edge_cnt"]))
    hist: dict = {}
    for e in meas:
        if e["reason"] != 0:
            hist[e["why"]] = hist.get(e["why"], 0) + 1
    for name in cc_base.ABORT_REASONS:
        want = int(summary.get(f"abort_{name}_cnt", 0))
        if hist.get(name, 0) != want:
            bad.append((f"ring_abort_{name}", hist.get(name, 0), want))
    taxo = sum(int(summary.get(f"abort_{name}_cnt", 0))
               for name in cc_base.ABORT_REASONS)
    if f"abort_{cc_base.ABORT_REASONS[0]}_cnt" in summary \
            and snap["dep_abort_edge_cnt"] != taxo:
        bad.append(("abort_edges_vs_taxonomy",
                    snap["dep_abort_edge_cnt"], taxo))
    got = sum(snap["part_edges"]) + snap["dep_nullkey_edge_cnt"]
    want = snap["dep_wait_edge_cnt"] + snap["dep_abort_edge_cnt"]
    if got != want:
        bad.append(("partition_plane_total", got, want))
    return bad


def _blocker_vertex(snap: dict, e: dict) -> tuple:
    if snap["nodes"] > 1:
        return (e["blocker"] // snap["batch"],
                e["blocker"] % snap["batch"])
    return (e["node"], e["blocker"])


def cycles(snap: dict, warmup_ticks: int = 0) -> list:
    """Would-be-deadlock cycles over each tick's sampled wait-for
    graph.  Per tick the graph is FUNCTIONAL (each waiter names at most
    one blocker), so one pointer walk with visit coloring finds every
    cycle in O(edges); cross-node cycles come out for free from the
    global blocker ids.  Returns ``[{"tick", "cycle": [[node, slot],
    ...]}, ...]`` — under NO_WAIT-style policies these are the
    deadlocks the eager abort PREVENTED, measured instead of assumed."""
    by_tick: dict = {}
    for e in snap["edges"]:
        if e["tick"] < warmup_ticks or e["blocker"] < 0:
            continue
        by_tick.setdefault(e["tick"], {})[(e["node"], e["waiter"]
                                           % snap["batch"]
                                           if snap["nodes"] > 1
                                           else e["waiter"])] = e
    out = []
    for t, emap in sorted(by_tick.items()):
        done: set = set()
        for v0 in emap:
            if v0 in done:
                continue
            path: list = []
            seen: dict = {}
            u = v0
            while u in emap and u not in done:
                if u in seen:
                    out.append({"tick": t,
                                "cycle": [list(x) for x in
                                          path[seen[u]:]]})
                    break
                seen[u] = len(path)
                path.append(u)
                u = _blocker_vertex(snap, emap[u])
            done.update(path)
    return out


def critical_paths(snap: dict, flight_snap: dict, topk: int = 10,
                   warmup_ticks: int = 0) -> list:
    """Commit critical-path decomposition: for each committed span the
    flight recorder sampled, the LONGEST blocking chain behind it —
    walk the sampled wait edges of its lifetime, tick by tick,
    following blocker pointers within the tick.  Rows sort by the
    span's blocked ticks (the lat_cc_block_time contribution), so the
    head of the list is the commit whose latency the graph explains
    most."""
    emap: dict = {}
    for e in snap["edges"]:
        if e["tick"] < warmup_ticks or e["reason"] != 0:
            continue
        w = (e["waiter"] % snap["batch"] if snap["nodes"] > 1
             else e["waiter"])
        emap[(e["node"], w, e["tick"])] = e

    def chain(node, slot, tick):
        path, seen = [], set()
        cur = (node, slot)
        while (*cur, tick) in emap and cur not in seen:
            seen.add(cur)
            e = emap[(*cur, tick)]
            path.append(e)
            if e["blocker"] < 0:
                break
            cur = _blocker_vertex(snap, e)
        return path

    rows = []
    for d in flight_snap.get("spans", ()):
        if d.get("kind", 0) != 0:
            continue
        best: list = []
        for t in range(d["admit"], d["end"] + 1):
            p = chain(d["node"], d["slot"], t)
            if len(p) > len(best):
                best = p
        if not best:
            continue
        rows.append({
            "node": d["node"], "slot": d["slot"],
            "admit": d["admit"], "end": d["end"],
            "latency": d["end"] - d["admit"],
            "block_ticks": d.get("block", 0),
            "max_depth": len(best),
            "at_tick": best[0]["tick"],
            "path": [{k: e[k] for k in
                      ("waiter", "blocker", "key", "node")}
                     for e in best]})
    rows.sort(key=lambda r: (-r["block_ticks"], -r["max_depth"]))
    return rows[:topk]


def flow_events(snap: dict, tick_us: float = 1.0,
                limit: int = 4096) -> list:
    """Perfetto FLOW arrows blocker -> waiter, merging into the flight
    span track (same pid=node / tid=slot addressing as
    obs/flight.py span_events).  Flow ids are STRINGS (``"dep<n>"``) —
    a namespace disjoint by type from the recorder's integer abort-flow
    ids, so the merged document never aliases arrows
    (tests/test_depgraph.py regression).  Wait edges draw as "blocks",
    abort edges as "kills:<reason>"."""
    events = []
    n = 0
    for e in snap["edges"]:
        if e["blocker"] < 0:
            continue
        if n >= limit:
            break
        bnode, bslot = _blocker_vertex(snap, e)
        wslot = (e["waiter"] % snap["batch"] if snap["nodes"] > 1
                 else e["waiter"])
        name = "blocks" if e["reason"] == 0 else f"kills:{e['why']}"
        fid = f"dep{n}"
        n += 1
        events.append({"name": name, "cat": "dep-flow", "ph": "s",
                       "id": fid, "ts": e["tick"] * tick_us,
                       "pid": bnode, "tid": bslot})
        events.append({"name": name, "cat": "dep-flow", "ph": "f",
                       "bp": "e", "id": fid,
                       "ts": (e["tick"] + 0.5) * tick_us,
                       "pid": e["node"], "tid": wslot})
    return events


def summary_keys(stats: dict) -> dict:
    """[summary] bookkeeping merged by Engine.summary when the plane is
    on: ring fill / wrap flag (max across nodes — wrap is per-ring) and
    the cluster peak gauges (max-merged, never summed).  All integers,
    stats.py dep_* passthrough (never time-scaled)."""
    cnt = np.asarray(stats["arr_dep_cnt"]).reshape(-1)
    S = int(np.asarray(stats["arr_dep_ring"]).shape[-2])
    peak = np.asarray(stats["arr_dep_peak"]).reshape(-1,
                                                     len(PEAK_COLUMNS))
    return {"dep_ring_cnt": int(cnt.max()),
            "dep_ring_wrapped": int(bool((cnt > S).any())),
            "dep_peak_depth": int(peak[:, 0].max()),
            "dep_peak_convoy": int(peak[:, 1].max())}


def record_extra(cfg, stats: dict) -> dict:
    """Run-record block (obs/profiler.py): the full snapshot under the
    top-level ``"depgraph"`` key; empty when the plane is off."""
    if "arr_dep_ring" not in stats:
        return {}
    return {"depgraph": snapshot(stats)}
