"""Observability subsystem — the rebuild of the reference's measurement
stack (statistics/stats.cpp + PROG_TIMER + DEBUG_TIMELINE printfs).

Three pillars, all opt-in through ``Config`` so the disabled path adds
zero device work:

- :mod:`deneva_tpu.obs.trace`     device-resident per-tick timeline ring
                                  (``Config.trace_ticks``), exportable as
                                  Chrome trace-event JSON (Perfetto);
- :mod:`deneva_tpu.obs.prog`      periodic ``[prog]`` heartbeat lines
                                  (``Config.prog_interval``), same
                                  key=value contract as ``[summary]``;
- :mod:`deneva_tpu.obs.profiler`  host-side phase timers around
                                  trace/lower/compile vs execute
                                  (``Config.profile``) plus structured
                                  JSON run records under ``results/``;
- :mod:`deneva_tpu.obs.xmeter`    compile & memory observatory
                                  (``Config.xmeter``): recompile
                                  sentinel, HBM footprint ledger and
                                  per-kernel roofline from the compiled
                                  executables' cost/memory analyses;
- :mod:`deneva_tpu.obs.regress`   bench regression gate — compares the
                                  current BENCH snapshot against the
                                  trajectory median
                                  (``python -m deneva_tpu.obs.regress``).

xmeter and regress are deliberately NOT imported here: both double as
``python -m`` CLIs (like obs.report), and importing a ``-m`` target from
its package ``__init__`` trips runpy's found-in-sys.modules warning.
"""

from deneva_tpu.obs import prog, profiler, trace  # noqa: F401
