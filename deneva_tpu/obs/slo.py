"""Sliding-window SLO engine: error-budget burn-rate alerting over the
histogram plane (obs/histo.py).

Host-side and allocation-free on device: the tracker consumes periodic
SNAPSHOTS of the carried ``arr_hist_fam`` plane plus a few cumulative
counters, and evaluates the objectives on window DELTAS (cumulative
histograms subtract exactly — int counts — so a window delta is the
exact histogram of the txns that committed inside the window):

- **latency ceiling**  fraction of window commits whose bucket lies
  entirely above ``Config.slo_p99_ceiling`` ticks; the error budget is
  ``1 - slo_target`` (target 0.99 => 1% of commits may breach).  The
  bucket test is conservative by design: a sample counts as bad only
  when its whole bucket is past the ceiling.
- **burn rate**  Google-SRE multi-window form: ``burn = bad_frac /
  budget`` evaluated over a FAST (``slo_burn_fast`` ticks) and a SLOW
  (``slo_burn_slow``) window.  The alert FIRES when BOTH exceed
  ``slo_burn_threshold`` (fast = it is happening now, slow = it is not
  a blip) and CLEARS when the fast window drops back under — the
  standard fast-trigger / fast-reset pairing.
- **served-fraction floor / abort-rate cap**  open-system admission
  (``queue_admit_cnt / arrival_cnt`` per window) must stay >=
  ``slo_served_floor``; window aborts per (aborts + commits) must stay
  <= ``slo_abort_cap``.  Breaches count, they do not gate the alert —
  the burn rate is the page, these are the dashboard.

``summary_fields()`` surfaces the ``slo_*`` / ``burn_*`` [summary]
scalars the watchdog bit 128 (obs/report.py) and the stats.py
passthrough consume; ``events`` keeps the (tick, "fire"/"clear")
timeline the EXPERIMENTS.md flash-crowd recipe prints.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from deneva_tpu.obs import histo as obs_histo

#: cumulative counters the tracker differences per window (all optional
#: — a closed-loop run has no arrival plane, a NO_WAIT run still has
#: aborts; missing keys delta to 0)
COUNTERS = ("txn_cnt", "total_txn_abort_cnt", "arrival_cnt",
            "queue_admit_cnt")


class SloTracker:
    """Multi-window error-budget tracker over histogram snapshots."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.budget = 1.0 - cfg.slo_target
        # snapshots: (tick, fam_plane copy, counters dict); the deque
        # only needs to span the slow window plus one poll interval
        self._snaps: deque = deque()
        self.events: list = []          # (tick, "fire" | "clear")
        self.alert_active = False
        self.alert_cnt = 0
        self.breach_ticks = 0           # ticks observed with fast burn hot
        self.served_breach_cnt = 0
        self.abort_breach_cnt = 0
        self._last = None               # latest evaluation dict

    # -- feeding -------------------------------------------------------

    def observe(self, tick: int, fam_plane, counters: dict) -> dict:
        """Ingest one snapshot (host arrays; node-stacked planes are
        collapsed) and evaluate both windows.  Returns the evaluation
        dict ({"burn_fast", "burn_slow", "served_frac", "abort_rate",
        "fired", "cleared"})."""
        fam = np.asarray(obs_histo._collapse(fam_plane), np.int64)
        cnt = {k: int(counters.get(k, 0)) for k in COUNTERS}
        prev_tick = self._snaps[-1][0] if self._snaps else None
        self._snaps.append((int(tick), fam.copy(), cnt))
        horizon = int(tick) - self.cfg.slo_burn_slow
        while len(self._snaps) > 2 and self._snaps[1][0] <= horizon:
            self._snaps.popleft()

        fast = self._window(tick, self.cfg.slo_burn_fast)
        slow = self._window(tick, self.cfg.slo_burn_slow)
        served = self._served(fast)
        abort_rate = self._abort_rate(fast)
        burn_fast, burn_slow = fast["burn"], slow["burn"]

        fired = cleared = False
        hot = (burn_fast > self.cfg.slo_burn_threshold)
        if hot and prev_tick is not None:
            self.breach_ticks += int(tick) - int(prev_tick)
        if not self.alert_active and hot \
                and burn_slow > self.cfg.slo_burn_threshold:
            self.alert_active, fired = True, True
            self.alert_cnt += 1
            self.events.append((int(tick), "fire"))
        elif self.alert_active and not hot:
            self.alert_active, cleared = False, True
            self.events.append((int(tick), "clear"))
        if served < self.cfg.slo_served_floor:
            self.served_breach_cnt += 1
        if abort_rate > self.cfg.slo_abort_cap:
            self.abort_breach_cnt += 1

        self._last = {"tick": int(tick), "burn_fast": burn_fast,
                      "burn_slow": burn_slow, "served_frac": served,
                      "abort_rate": abort_rate, "fired": fired,
                      "cleared": cleared,
                      "window_commits": int(fast["total"])}
        return self._last

    # -- window math ---------------------------------------------------

    def _base(self, tick: int, window: int):
        """Most recent snapshot at or before ``tick - window`` (falls
        back to the oldest — a young tracker evaluates what it has)."""
        base = self._snaps[0]
        for s in self._snaps:
            if s[0] <= int(tick) - window:
                base = s
            else:
                break
        return base

    def _window(self, tick: int, window: int) -> dict:
        now = self._snaps[-1]
        base = self._base(tick, window)
        delta = now[1] - base[1]
        total = int(delta.sum())
        lows = obs_histo.bucket_lows(delta.shape[-1])
        bad = int(delta[:, lows > self.cfg.slo_p99_ceiling].sum())
        frac = bad / total if total > 0 else 0.0
        return {"total": total, "bad": bad, "frac": frac,
                "burn": frac / self.budget, "delta": delta,
                "base_tick": base[0],
                "counters": {k: now[2][k] - base[2][k] for k in COUNTERS}}

    @staticmethod
    def _served(win: dict) -> float:
        c = win["counters"]
        arrived = c["arrival_cnt"]
        return (c["queue_admit_cnt"] / arrived) if arrived > 0 else 1.0

    @staticmethod
    def _abort_rate(win: dict) -> float:
        c = win["counters"]
        done = c["total_txn_abort_cnt"] + c["txn_cnt"]
        return (c["total_txn_abort_cnt"] / done) if done > 0 else 0.0

    # -- surfacing -----------------------------------------------------

    def summary_fields(self) -> dict:
        """[summary] scalars: ``slo_*`` counters verbatim ints,
        ``burn_*`` dimensionless floats (stats.py passthrough rules)."""
        last = self._last or {}
        return {
            "slo_alert_active": int(self.alert_active),
            "slo_alert_cnt": int(self.alert_cnt),
            "slo_breach_ticks": int(self.breach_ticks),
            "slo_served_breach_cnt": int(self.served_breach_cnt),
            "slo_abort_breach_cnt": int(self.abort_breach_cnt),
            "burn_fast": float(last.get("burn_fast", 0.0)),
            "burn_slow": float(last.get("burn_slow", 0.0)),
            "burn_served_frac": float(last.get("served_frac", 1.0)),
            "burn_abort_rate": float(last.get("abort_rate", 0.0)),
        }
