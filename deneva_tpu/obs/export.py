"""Unified Perfetto export: run records -> one Chrome trace file.

``python -m deneva_tpu.obs.export run_*.json [-o trace.json]`` merges
every given run record (obs/profiler.py write_run_record documents) into
ONE Chrome trace-event JSON loadable at ui.perfetto.dev:

- the per-tick counter tracks rebuilt from each record's ``timeline``
  series (the same track grouping as obs/trace.py to_chrome_trace:
  txn flow, slot occupancy, compaction, plus the conditional abort-
  reasons, admission-queue, and per-node-pair mesh-traffic tracks);
- the per-txn SPAN track from each record's ``flight`` snapshot
  (obs/flight.py span_events: nested lifecycle/attempt slices with
  abort-reason flow arrows) — counters above, the sampled lifecycles
  that explain them below, on one shared tick clock;
- blocker->waiter flow arrows from each record's ``depgraph`` snapshot
  (obs/depgraph.py flow_events), merged into the same span track.  Flow
  ids are re-keyed per record (prefixed into ``"<pid_base>:<fid>"``
  strings) because Perfetto unites flow phases by id alone — see
  ``_rekey_flows``.

Records merge side by side as separate Perfetto process groups (one pid
block per record, per node), so a 7-algorithm bench sweep reads as seven
labelled lanes in one timeline.  Like obs/xmeter.py and obs/regress.py,
this module is deliberately NOT imported by obs/__init__ — ``python -m``
execution would otherwise warn about the double import.
"""

from __future__ import annotations

import json

# per-record pid stride: node pids of record i live in [i*stride, ...);
# 4096 nodes per record is far beyond any mesh this build drives
PID_STRIDE = 4096

#: counter-track grouping, mirroring obs/trace.py to_chrome_trace
_TRACKS = (("txn flow", ("admit", "commit", "abort", "vabort",
                         "user_abort", "lock_wait")),
           ("slot occupancy", ("occ_free", "occ_running", "occ_waiting",
                               "occ_backoff")),
           ("compaction", ("live_entries", "compact_ovf")))


def _series(timeline: dict, name: str, node: int, n_nodes: int):
    """One record timeline column as a flat per-tick list for ``node``
    (cluster records may store (N, T) nested lists; flat (T,) series are
    node 0's — and the cluster sum's — view)."""
    col = timeline.get(name)
    if col is None:
        return None
    if col and isinstance(col[0], list):      # (N, T) per-shard series
        return col[node] if node < len(col) else None
    return col if node == 0 else None


def _rekey_flows(evs, pid_base: int) -> list:
    """Shift span/flow events into a record's pid block AND re-key their
    flow ids into a per-record namespace.  Perfetto unites flow phases
    ("s"/"t"/"f") by id alone, not (pid, id) — two merged records each
    emitting flight flow 1 would otherwise draw one arrow spanning
    unrelated process groups.  Every id becomes the STRING
    ``"<pid_base>:<fid>"``: the prefix separates records, and within one
    record the flight recorder's integer abort-flow ids ("0:51") can
    never render equal to a depgraph blocker id ("0:dep51") — additive
    integer striding would alias records (``(i + f) * stride`` collides
    across (record, fid) pairs; tests/test_depgraph.py regression)."""
    out = []
    for ev in evs:
        ev = dict(ev)
        ev["pid"] = pid_base + ev["pid"]
        fid = ev.get("id")
        if fid is not None:
            ev["id"] = f"{pid_base}:{fid}"
        out.append(ev)
    return out


def record_events(rec: dict, pid_base: int = 0, tick_us: float = 1.0,
                  label: str = "") -> list:
    """Trace events for ONE run record: counter tracks from its
    ``timeline`` plus the span track from its ``flight`` snapshot."""
    events = []
    timeline = rec.get("timeline") or {}
    flight = rec.get("flight")
    n_nodes = 1
    for col in timeline.values():
        if col and isinstance(col[0], list):
            n_nodes = max(n_nodes, len(col))
    if flight:
        n_nodes = max(n_nodes, int(flight.get("nodes", 1)))
    reason_names = sorted(k for k in timeline if k.startswith("abort_"))
    # per-node-pair outbound traffic of mesh-observatory runs; numeric
    # sort so to10 doesn't land between to1 and to2
    mesh_names = sorted((k for k in timeline
                         if k.startswith("mesh_tx_to")),
                        key=lambda k: int(k[len("mesh_tx_to"):]))
    # adaptive-controller decision series (obs/trace.py CTRL_COLUMNS,
    # present only for Config.adaptive runs with a trace ring)
    ctrl_names = sorted(k for k in timeline if k.startswith("ctrl_"))
    # SLO plane gauges (obs/trace.py record_slo: slo_f{f}_p99 /
    # slo_f{f}_burn, Config.slo runs with a trace ring); numeric family
    # sort so f10 doesn't land between f1 and f2
    slo_names = sorted((k for k in timeline if k.startswith("slo_f")),
                       key=lambda k: (int(k[len("slo_f"):].split("_")[0]),
                                      k))
    for node in range(n_nodes):
        pid = pid_base + node
        pname = label or "engine"
        if n_nodes > 1:
            pname = f"{pname}/shard{node}"
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": pname}})
        for track, cols in _TRACKS:
            series = {c: _series(timeline, c, node, n_nodes)
                      for c in cols}
            series = {c: s for c, s in series.items() if s is not None}
            if not series:
                continue
            T = min(len(s) for s in series.values())
            for t in range(T):
                events.append({"name": track, "ph": "C",
                               "ts": float(t) * tick_us, "pid": pid,
                               "args": {c: int(series[c][t])
                                        for c in series}})
        for t_name, cols in (("abort reasons", reason_names),
                             ("admission queue", ("queue_depth",)),
                             ("mesh traffic", mesh_names),
                             ("controller decisions", ctrl_names),
                             ("slo burn rate", slo_names),
                             # conflict dependency observatory planes
                             # (obs/trace.py DEP_COLUMNS)
                             ("chain depth", ("dep_edges", "dep_depth",
                                              "dep_convoy"))):
            series = {c: _series(timeline, c, node, n_nodes)
                      for c in cols}
            series = {c: s for c, s in series.items() if s is not None}
            if not series:
                continue
            T = min(len(s) for s in series.values())
            for t in range(T):
                events.append({"name": t_name, "ph": "C",
                               "ts": float(t) * tick_us, "pid": pid,
                               "args": {c: int(series[c][t])
                                        for c in series}})
    if flight:
        from deneva_tpu.obs import flight as obs_flight
        events.extend(_rekey_flows(
            obs_flight.span_events(flight, tick_us=tick_us), pid_base))
    dep = rec.get("depgraph")
    if dep and dep.get("edges"):
        # blocker->waiter flow arrows of the conflict dependency
        # observatory (obs/depgraph.py flow_events), same per-record
        # pid/flow-id namespacing as the flight span track above
        from deneva_tpu.obs import depgraph as obs_depgraph
        events.extend(_rekey_flows(
            obs_depgraph.flow_events(dep, tick_us=tick_us), pid_base))
    win = rec.get("windows")
    if win and not win.get("wrapped"):
        # window-delta counter track (obs/trace.py's conditional 11th
        # track), rebuilt from the record's obs/windows.py block: one
        # cluster-wide counter per snapshot column stepping by that
        # window's delta at its boundary tick.  A wrapped ring is
        # skipped — lossy deltas would draw a lie.
        cols = list(win["cols_i"])
        ti = cols.index("tick")
        prev = [0] * len(cols)
        for row in win["ring_i"]:
            events.append({"name": "window deltas", "ph": "C",
                           "ts": float(row[ti]) * tick_us,
                           "pid": pid_base,
                           "args": {c: int(row[j]) - int(prev[j])
                                    for j, c in enumerate(cols)
                                    if j != ti}})
            prev = row
    return events


def export(paths, out_path: str, tick_us: float = 1.0) -> dict:
    """Merge the run records at ``paths`` into one Chrome trace at
    ``out_path``; returns the metadata block (record labels + counts)."""
    events = []
    labels = []
    for i, path in enumerate(paths):
        with open(path) as f:
            rec = json.load(f)
        cfg = rec.get("config") or {}
        label = str(cfg.get("cc_alg") or rec.get("config_fingerprint")
                    or path)
        labels.append(label)
        events.extend(record_events(rec, pid_base=i * PID_STRIDE,
                                    tick_us=tick_us, label=label))
    meta = {"tool": "deneva_tpu.obs.export", "records": labels,
            "tick_us": tick_us, "events": len(events)}
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms",
                   "metadata": meta}, f)
    return meta


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        description="merge run records into one Perfetto/Chrome trace "
                    "(counter tracks + per-txn flight span track)")
    p.add_argument("records", nargs="+",
                   help="run_record JSON paths (obs/profiler.py)")
    p.add_argument("-o", "--out", default="trace_merged.json",
                   help="output Chrome trace path")
    p.add_argument("--tick-us", type=float, default=1.0,
                   help="microseconds per scheduler tick on the trace "
                        "timebase")
    args = p.parse_args(argv)
    meta = export(args.records, args.out, tick_us=args.tick_us)
    print(f"wrote {args.out}: {meta['events']} events from "
          f"{len(meta['records'])} record(s) "
          f"({', '.join(meta['records'])})")
    return 0


if __name__ == "__main__":           # pragma: no cover - CLI shim
    raise SystemExit(main())
