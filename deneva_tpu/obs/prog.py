"""Periodic ``[prog]`` progress emission — the PROG_TIMER heartbeat
(reference system/thread.cpp:86-105 + statistics/stats.cpp progress dump).

The reference dumps a cumulative stats snapshot every PROG_TIMER seconds
so a stalled or convecting run is visible long before the final
``[summary]``.  Here the heartbeat is tick-driven (``Config.prog_interval``
or the engines' ``prog_every`` argument) and renders the SAME key=value
vocabulary through ``stats.format_summary(..., prog=True)`` — every
``[prog]`` line round-trips through ``stats.parse_summary`` exactly like
a ``[summary]`` line, so downstream parsers can plot the run's trajectory
from a log alone.

Each emission syncs the device (the stats fetch blocks on the in-flight
tick) — an observation cost paid only when enabled.
"""

from __future__ import annotations

from typing import Callable, Optional


class ProgressEmitter:
    """Collects and prints ``[prog]`` lines for an engine's run loop.

    ``interval``: emit every that-many ticks (0/None = never).
    ``out``: sink callable (defaults to ``print(..., flush=True)``);
    emitted lines are also kept on ``self.lines`` so harnesses and tests
    can parse them without capturing stdout.
    """

    def __init__(self, engine, interval: Optional[int],
                 out: Optional[Callable[[str], None]] = None):
        self.engine = engine
        self.interval = int(interval or 0)
        self.out = out
        self.lines: list[str] = []

    def maybe_emit(self, state, ticks_done: int) -> Optional[str]:
        """Call once per tick with the 1-based tick count of this run."""
        if self.interval > 0 and ticks_done % self.interval == 0:
            return self.emit(state)
        return None

    def emit(self, state) -> str:
        line = self.engine.summary_line(state, prog=True)
        self.lines.append(line)
        if self.out is not None:
            self.out(line)
        else:
            print(line, flush=True)
        return line
