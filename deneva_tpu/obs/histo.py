"""Mergeable on-device latency histograms: the exact-tail device plane.

The PR 6 ``famlat`` survivor rings keep the LAST ``fam_lat_samples``
commit latencies per family, so once arrivals outrun the ring the p99
is computed over a biased suffix — exactly when the tail matters most
(the flash crowd the SLO plane exists to watch).  This module replaces
sampling with counting: HDR-style log-bucket histograms carried in the
donated stats carry, accumulated jit-pure at the existing commit /
harvest sites and merged EXACTLY (elementwise int32 add — associative,
commutative, lossless), so the cluster histogram is bit-equal to the
numpy sum of the per-shard planes and quantiles are exact to the bucket
resolution no matter the arrival rate.

Bucketing (:func:`bucket_of`): value ``v`` keeps :data:`HIST_MANTISSA`
mantissa bits — ``shift = max(msb(v) - 3, 0)``, ``bucket = shift * 8 +
(v >> shift)`` — so buckets 0..15 are EXACT single-tick cells and every
later bucket has <= 12.5% relative width (``HIST_SUB = 8`` sub-buckets
per octave).  The default 96 bins cover latencies to ~15k ticks with
the last bucket open-ended (clip).

Two planes ride the carry when ``Config.slo`` is on (``arr_``-prefixed
like every non-summary array, so both engines' scalar summaries skip
them):

- ``arr_hist_fam``    ``(F, BINS)``  commit latency (first start ->
  commit, the famlat LONG latency) per txn family; total count ==
  ``txn_cnt`` EXACTLY (same ``commit & measuring`` take mask).
- ``arr_hist_phase``  ``(3, BINS)``  per-tick slot-occupancy histograms
  for the ``lat_*`` phase vocabulary (:data:`PHASES`: process /
  cc_block / abort): each measured tick buckets the number of slots in
  that state, so every row sums to ``measured_ticks`` EXACTLY.

Off path (``Config.slo`` false, the default) this module contributes
zero carried arrays and zero summary keys — the certifier holds the
flag byte-identical like every other ``_optin`` observatory.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: sub-buckets per octave (2**HIST_MANTISSA): <= 1/HIST_SUB relative
#: bucket width past the exact range
HIST_SUB = 8
HIST_MANTISSA = 3

#: arr_hist_phase rows, mirroring the lat_* harvest vocabulary of
#: engine/scheduler.py track_state_latencies
PHASES = ("process", "cc_block", "abort")

#: slo_fam{f}_p{P} summary quantiles (matches traffic/arrival.py
#: FAM_PCTS so the histogram view is drop-in comparable to famlat)
SLO_PCTS = (50, 95, 99)


# ---------------------------------------------------------------------------
# bucket geometry (host + device views of the SAME mapping)
# ---------------------------------------------------------------------------

def bucket_of(v, bins: int):
    """Jit-pure log-bucket index for int value(s) ``v`` (clipped to
    ``[0, bins)``; negatives bucket as 0, the last bucket is
    open-ended)."""
    v = jnp.maximum(jnp.asarray(v, jnp.int32), 0)
    msb = 31 - jax.lax.clz(jnp.maximum(v, 1))
    shift = jnp.maximum(msb - HIST_MANTISSA, 0)
    b = shift * HIST_SUB + jax.lax.shift_right_logical(v, shift)
    return jnp.minimum(b, bins - 1)


def bucket_lows(bins: int) -> np.ndarray:
    """Inclusive lower bound of every bucket (int64 host array); the
    exact inverse of :func:`bucket_of` on bucket boundaries."""
    b = np.arange(bins, dtype=np.int64)
    s = np.maximum(b // HIST_SUB - 1, 0)
    return (b - s * HIST_SUB) << s


def bucket_widths(bins: int) -> np.ndarray:
    """Value count covered by each bucket (the last one nominally)."""
    b = np.arange(bins, dtype=np.int64)
    s = np.maximum(b // HIST_SUB - 1, 0)
    return np.int64(1) << s


def bucket_value(b: int, bins: int) -> float:
    """Representative (midpoint) value of bucket ``b`` — exact for the
    single-width buckets 0..15."""
    return float(bucket_lows(bins)[b] + (bucket_widths(bins)[b] - 1) / 2)


def quantile(counts, q: float) -> float:
    """Exact-to-bucket-resolution quantile of one histogram row: the
    representative value of the bucket holding the ``ceil(q * n)``-th
    sample (0.0 on an empty row)."""
    counts = np.asarray(counts, np.int64)
    n = int(counts.sum())
    if n == 0:
        return 0.0
    rank = max(int(np.ceil(q * n)), 1)
    b = int(np.searchsorted(np.cumsum(counts), rank))
    return bucket_value(b, counts.shape[0])


# ---------------------------------------------------------------------------
# carried planes: init + jit-pure accumulation
# ---------------------------------------------------------------------------

def init_histo(cfg, n_families: int = 1) -> dict:
    """Stats-dict entries for the SLO histogram plane; empty when
    ``Config.slo`` is off (the disabled path carries nothing)."""
    if not cfg.slo:
        return {}
    bins = cfg.slo_hist_bins
    out = {
        "arr_hist_fam": jnp.zeros((n_families, bins), jnp.int32),
        "arr_hist_phase": jnp.zeros((len(PHASES), bins), jnp.int32),
    }
    if cfg.trace_ticks > 0:
        # per-tick SLO gauge ring -> the "slo burn rate" Perfetto track
        # (obs/trace.py record_slo): [p99_f0..p99_fF, burn_f0..burn_fF]
        out["arr_slo_trace"] = jnp.zeros((cfg.trace_ticks, 2 * n_families),
                                         jnp.int32)
    return out


def record_commit(stats: dict, commit, txn_type, lat, measuring) -> dict:
    """Bucket committing txns' LONG latencies into the per-family
    histogram.  Dead lanes scatter to the out-of-bounds family row F
    and drop; the add is commutative, so duplicate (fam, bucket) cells
    race-free accumulate (LINT.md scatter discipline).  No-op when the
    plane is off."""
    if "arr_hist_fam" not in stats:
        return stats
    hist = stats["arr_hist_fam"]
    F, bins = hist.shape
    take = commit & measuring
    fam = jnp.where(take, jnp.clip(txn_type, 0, F - 1), F)
    b = bucket_of(lat, bins)
    return {**stats,
            "arr_hist_fam": hist.at[fam, b].add(1, mode="drop")}


def record_phase_counts(stats: dict, counts, measuring) -> dict:
    """Bucket this tick's per-phase slot occupancies (``counts`` in
    :data:`PHASES` order, int32 scalars) — one increment per row per
    measured tick, so every row sums to ``measured_ticks`` exactly.
    Unmeasured ticks scatter to the out-of-bounds row and drop."""
    if "arr_hist_phase" not in stats:
        return stats
    hist = stats["arr_hist_phase"]
    P, bins = hist.shape
    rows = jnp.where(measuring, jnp.arange(P, dtype=jnp.int32), P)
    b = bucket_of(jnp.stack(counts), bins)
    return {**stats,
            "arr_hist_phase": hist.at[rows, b].add(1, mode="drop")}


# ---------------------------------------------------------------------------
# device-side quantile / burn estimates (the trace-ring gauges)
# ---------------------------------------------------------------------------

def device_quantile(hist_row, lows, q: float):
    """Jit-pure bucket-low quantile of one histogram row (int32 ticks;
    0 on an empty row).  ``lows`` is the baked :func:`bucket_lows`
    constant."""
    total = jnp.sum(hist_row)
    rank = jnp.maximum(jnp.ceil(q * total).astype(jnp.int32), 1)
    idx = jnp.argmax(jnp.cumsum(hist_row) >= rank)
    return jnp.where(total > 0, lows[idx], 0).astype(jnp.int32)


def device_burn_milli(hist_row, over_mask, budget: float):
    """Jit-pure cumulative burn rate x1000 (int32 fixed point): the
    fraction of samples whose bucket lies entirely ABOVE the latency
    ceiling, over the error budget ``1 - slo_target``.  ``over_mask``
    is the baked ``bucket_lows > ceiling`` int32 constant."""
    total = jnp.sum(hist_row)
    over = jnp.sum(hist_row * over_mask)
    burn = over.astype(jnp.float32) / jnp.maximum(total, 1) / budget
    return jnp.where(total > 0, (burn * 1000.0), 0.0).astype(jnp.int32)


# ---------------------------------------------------------------------------
# host-side summary + cluster merge
# ---------------------------------------------------------------------------

def _collapse(plane) -> np.ndarray:
    """Host view of a carried plane: node-stacked ``(N, R, BINS)``
    arrays np-sum over the node axis (exact merge — int add)."""
    plane = np.asarray(plane)
    return plane.sum(axis=0, dtype=np.int64) if plane.ndim == 3 else plane


def summary_keys(fam_plane, phase_plane) -> dict:
    """``hist_*`` / ``slo_fam{f}_*`` [summary] keys from the carried
    planes (single-shard ``(R, BINS)`` or node-stacked
    ``(N, R, BINS)``)."""
    fam = _collapse(fam_plane)
    phase = _collapse(phase_plane)
    out = {"hist_total_cnt": int(fam.sum()),
           "hist_phase_cnt": int(phase.sum())}
    for f in range(fam.shape[0]):
        out[f"slo_fam{f}_n"] = int(fam[f].sum())
        for p in SLO_PCTS:
            out[f"slo_fam{f}_p{p}"] = quantile(fam[f], p / 100.0)
    return out


def cluster_plane(jax_mesh, plane_stacked) -> np.ndarray:
    """Device-side psum of the node-stacked histogram planes over the
    node axis in one jitted shard_map — bit-exact equal to the host
    ``plane_stacked.sum(axis=0)`` (int add is exact; the identity the
    tests assert).  Same pattern as obs/mesh.py cluster_matrix."""
    from jax.sharding import PartitionSpec as P
    from deneva_tpu.compat import shard_map
    axis = jax_mesh.axis_names[0]
    spec = P(axis)

    def agg(h):
        return jax.lax.psum(h[0], axis)[None]

    f = jax.jit(shard_map(agg, mesh=jax_mesh, in_specs=(spec,),
                          out_specs=spec))
    return np.asarray(f(plane_stacked))[0]
