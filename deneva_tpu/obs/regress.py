"""Bench regression gate: compare the current headline cell against the
repo's own measured trajectory.

``python -m deneva_tpu.obs.regress BENCH_r*.json results/`` loads every
trajectory point it is given — the committed ``BENCH_r*.json`` snapshots
(one per PR round; failed rounds with ``rc != 0`` or a null ``parsed``
payload are skipped) plus any ``bench_history.jsonl`` appended by
bench.py runs — and gates two families:

- **headline** — the wall-clock ``value`` of the current point vs the
  median of prior points carrying the SAME metric name.  Wall tput on
  the tunneled chip drifts +-10-30% session to session (PROFILE.md), so
  the default tolerance is generous (``--tolerance 0.5``); the gate
  catches collapses, not noise.
- **per-alg commits_per_tick** — the chip-noise-immune metric (committed
  txns per scheduler tick is a pure function of the schedule, not the
  clock).  Default ``--cpt-tolerance 0.15``: a 20% drop in any
  algorithm's cell fails the gate.

- **required cells** — a headline point must still CARRY the sort-bound
  cells the optimization rounds guard (``REQUIRED_CELLS``: MAAT, MVCC,
  OCC, TPCC_MVCC_64wh) once any prior headline point has; a cell that
  silently vanishes from the sweep would otherwise evade its
  commits_per_tick gate.

Open-system sweep records (bench.py ``--offered-load``) join the same
trajectory under their own ``offered_load_knee`` metric and
``<ALG>@knee`` cells; their per-algorithm saturation knee is gated like
commits_per_tick (a knee collapse = the engine saturates earlier than it
used to).  Cluster scaling-grid records (bench.py ``--scaling-grid``)
likewise gate each ``<ALG>@<nodes>x<batch>`` cell's parallel efficiency
at the same tolerance (an efficiency collapse = the cluster scales worse
at that point than it used to), plus the cell's remote ``amplification``
ratio (remote entries shipped per requested access) with the comparison
INVERTED — amplification growing past (1 + tol) x median means each
access ships more mesh traffic than it used to, the exact regression the
remote-grant stickiness work (Config.remote_cache) exists to prevent.

Serve-mode SLO records (bench.py ``--serve``) carry one exact-histogram
p99 per txn family (``slo_p99[fam*]``); like amplification these gate
INVERTED — the latency tail GROWING past (1 + tol) x median under the
same flash-crowd schedule is the regression the SLO plane exists to
catch.

Dependency-observatory records (bench.py ``--depgraph``) carry one
``depgraph_chain`` cell per algorithm with its peak wait-chain depth;
gated INVERTED like the SLO tails — the deepest blocking chain GROWING
past (1 + tol) x median on the same contended cell means commits now
serialize behind longer dependency chains than they used to.  Self-arms
on the first recorded sweep, like every other cell family.

Every point records the ``platform`` it was measured on (bench.py tags
``jax.default_backend()``), and the gate compares same-platform
trajectories ONLY: a CPU smoke point never gates against TPU history or
vice versa — the structural fix for the PR 7 one-off repair of the
CPU-polluted TPU trajectory.  Legacy untagged points (recorded before
the tag existed) stay in every comparison, so old history keeps
protecting until the trajectory is re-measured.

A gate with no prior data (e.g. per-alg cells first appeared in round 5)
is SKIPPED with a note, not failed — the gate self-arms as history
accumulates.  Exit code = number of regressions (0 == clean), wired
into scripts/check.sh after the bench smoke.  When the gate FAILS it
auto-attaches a causal diagnosis (obs/diff.py diagnose_entries): the
failing point vs the median of its priors, every ride-along cell ranked
by relative change and mapped to its config lever, printed as a
``[diagnosis]`` section and written next to the history file as
``diagnosis_regress.json`` — the regression arrives pre-triaged.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

DEFAULT_HEADLINE_TOL = 0.5
DEFAULT_CPT_TOL = 0.15

HISTORY_BASENAME = "bench_history.jsonl"

# the sort-bound cells the round-5/round-7 work optimizes (compaction,
# then the fused arbitration kernel): driver-visible numbers that must
# not silently VANISH from the headline sweep — a dropped cell would
# evade the commits_per_tick gate entirely.  Enforced only on headline
# points, and self-arming: a cell is required once any prior headline
# point carried it.
HEADLINE_METRIC = "ycsb_nowait_zipf0.6_tput_faithful"
REQUIRED_CELLS = ("MAAT", "MVCC", "OCC", "TPCC_MVCC_64wh")


# ---------------------------------------------------------------------------
# trajectory loading
# ---------------------------------------------------------------------------

def _cpt(cell) -> Optional[float]:
    """commits_per_tick from a per-alg cell (dict cell or bare number)."""
    if isinstance(cell, dict):
        v = cell.get("commits_per_tick")
    else:
        v = cell
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _entry(source: str, order: tuple, doc: dict) -> Optional[dict]:
    """Normalize one trajectory point; None when it carries no metric."""
    metric = doc.get("metric")
    try:
        value = float(doc.get("value"))
    except (TypeError, ValueError):
        return None
    algs = {}
    for alg, cell in (doc.get("algs") or {}).items():
        c = _cpt(cell)
        if c is not None:
            algs[alg] = c
    out = {"source": source, "order": order, "metric": metric,
           "value": value, "algs": algs,
           # measurement platform (bench.py tags jax.default_backend());
           # None on legacy points recorded before the tag existed —
           # those gate everywhere, tagged points gate same-platform only
           "platform": doc.get("platform")}
    # open-system sweep records (bench.py --offered-load) carry the rate
    # grid and the per-algorithm saturation knee; older records without
    # them normalize to an empty dict, so mixed trajectories keep
    # loading and the knee gate self-arms like the per-alg cells did
    knees = {}
    for alg, v in (doc.get("knee") or {}).items():
        try:
            knees[alg] = float(v)
        except (TypeError, ValueError):
            continue
    out["knees"] = knees
    if "offered_load" in doc:
        out["offered_load"] = doc["offered_load"]
    # cluster scaling-grid records (bench.py --scaling-grid) carry one
    # parallel-efficiency cell per (alg, nodes, batch) grid point; same
    # normalize-to-empty discipline, so the efficiency gate self-arms
    grid = {}
    for cell_key, cell in (doc.get("scaling_grid") or {}).items():
        try:
            grid[cell_key] = float(cell.get("efficiency")
                                   if isinstance(cell, dict) else cell)
        except (TypeError, ValueError):
            continue
    out["scaling_grid"] = grid
    # the same grid cells carry the remote amplification ratio (remote
    # entries shipped per requested access) once the scale-out rounds
    # record it; gated INVERTED (lower is better), self-arming like the
    # efficiency cells
    amp = {}
    for cell_key, cell in (doc.get("scaling_grid") or {}).items():
        if isinstance(cell, dict) and "amplification" in cell:
            try:
                amp[cell_key] = float(cell["amplification"])
            except (TypeError, ValueError):
                continue
    out["scaling_amp"] = amp
    # pipelined grid cells (bench.py --scaling-grid --pipeline) carry
    # the software-pipeline overlap fraction (overlapped exchange legs
    # per issued leg, Config.pipeline_exchange); gated as a FLOOR —
    # overlap collapsing means the exchange re-serialized — self-arming
    # like the efficiency cells
    pov = {}
    for cell_key, cell in (doc.get("scaling_grid") or {}).items():
        if isinstance(cell, dict) and "pipeline_overlap_frac" in cell:
            try:
                pov[cell_key] = float(cell["pipeline_overlap_frac"])
            except (TypeError, ValueError):
                continue
    out["pipeline_overlap"] = pov
    # adaptive-controller sweep records (bench.py --adaptive) carry one
    # adaptive-over-best-static commits/tick ratio per (alg, contention)
    # cell; same normalize-to-empty discipline, so the floor self-arms
    # on the first recorded sweep
    avs = {}
    for cell_key, v in (doc.get("adaptive_vs_static") or {}).items():
        try:
            avs[cell_key] = float(v)
        except (TypeError, ValueError):
            continue
    out["adaptive_vs_static"] = avs
    # serve-mode SLO records (bench.py --serve) carry one exact-histogram
    # p99 per txn family; gated INVERTED like the amplification cells
    # (lower is better — the tail GROWING is the regression), self-arming
    # on the first recorded serve run
    slo = {}
    for cell_key, v in (doc.get("slo_p99") or {}).items():
        try:
            slo[cell_key] = float(v)
        except (TypeError, ValueError):
            continue
    out["slo_p99"] = slo
    # dependency-observatory records (bench.py --depgraph) carry one
    # peak wait-chain depth per algorithm; gated INVERTED like the SLO
    # tails (depth growing = commits serialize behind longer chains),
    # self-arming on the first recorded sweep
    chains = {}
    for cell_key, cell in (doc.get("depgraph_chain") or {}).items():
        try:
            chains[cell_key] = float(cell.get("max_chain_depth")
                                     if isinstance(cell, dict) else cell)
        except (TypeError, ValueError):
            continue
    out["depgraph_chain"] = chains
    return out


def load_snapshot(path: str) -> Optional[dict]:
    """A committed BENCH_r*.json: {"n", "rc", "parsed"} — failed rounds
    (rc != 0 / parsed null, e.g. the round-2 mid-history crash) are
    part of the record but not of the trajectory."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("rc", 0) != 0 or not doc.get("parsed"):
        return None
    return _entry(path, (0, float(doc.get("n", 0))), doc["parsed"])


def load_history(path: str) -> list[dict]:
    """bench.py's append-only results/bench_history.jsonl (one JSON
    object per line: unix_time, commit, config_hash, metric, value,
    algs).  Malformed lines are skipped — the file is append-only across
    crashes."""
    out = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            e = _entry(path, (1, float(doc.get("unix_time", 0))), doc)
            if e is not None:
                out.append(e)
    return out


def load_trajectory(paths: list[str]) -> list[dict]:
    """Snapshots + history, chronological (snapshots by round number
    first — they predate the history file — then history by time)."""
    entries = []
    for p in paths:
        if os.path.isdir(p):
            e = load_history(os.path.join(p, HISTORY_BASENAME))
            entries.extend(e)
        elif p.endswith(".jsonl"):
            entries.extend(load_history(p))
        else:
            e = load_snapshot(p)
            if e is not None:
                entries.append(e)
    entries.sort(key=lambda e: e["order"])
    return entries


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def gate(entries: list[dict], current: Optional[dict] = None,
         tolerance: float = DEFAULT_HEADLINE_TOL,
         cpt_tolerance: float = DEFAULT_CPT_TOL) -> dict:
    """Compare ``current`` (default: the latest entry) against the
    median of the prior trajectory.  Returns {"current", "checks",
    "failures", "skipped"}; a check fails when the current value drops
    below (1 - tolerance) x median(prior)."""
    if current is None:
        if not entries:
            return {"current": None, "checks": [], "failures": [],
                    "skipped": ["empty trajectory: nothing to gate"]}
        current = entries[-1]
    prior = [e for e in entries if e is not current]
    # same-platform trajectories only: a point tagged with a platform
    # gates against priors on that platform (plus legacy untagged
    # points); an untagged current keeps the whole trajectory.  This is
    # the structural form of the PR 7 repair — a CPU smoke run can no
    # longer fail (or silently lower) the TPU trajectory's median
    plat = current.get("platform")
    if plat is not None:
        prior = [e for e in prior
                 if e.get("platform") in (None, plat)]
    checks, failures, skipped = [], [], []

    def check(name: str, cur: float, baseline: list[float], tol: float):
        if not baseline:
            skipped.append(f"{name}: no prior data "
                           f"(current={cur:g}; gate arms next round)")
            return
        med = float(np.median(baseline))
        floor = (1.0 - tol) * med
        ok = cur >= floor
        checks.append({"name": name, "current": cur, "median": med,
                       "floor": floor, "n_prior": len(baseline),
                       "ok": ok})
        if not ok:
            failures.append(f"{name}: {cur:g} < floor {floor:g} "
                            f"(median {med:g} over {len(baseline)} "
                            f"prior, tol {tol:g})")

    def check_ceiling(name: str, cur: float, baseline: list[float],
                      tol: float):
        """Inverted check for lower-is-better metrics (remote
        amplification): fail when the current value GROWS past
        (1 + tol) x median(prior)."""
        if not baseline:
            skipped.append(f"{name}: no prior data "
                           f"(current={cur:g}; gate arms next round)")
            return
        med = float(np.median(baseline))
        ceiling = (1.0 + tol) * med
        ok = cur <= ceiling
        checks.append({"name": name, "current": cur, "median": med,
                       "ceiling": ceiling, "n_prior": len(baseline),
                       "ok": ok})
        if not ok:
            failures.append(f"{name}: {cur:g} > ceiling {ceiling:g} "
                            f"(median {med:g} over {len(baseline)} "
                            f"prior, tol {tol:g})")

    check(f"headline[{current['metric']}]", current["value"],
          [e["value"] for e in prior if e["metric"] == current["metric"]],
          tolerance)
    for alg, cur in sorted(current["algs"].items()):
        check(f"commits_per_tick[{alg}]", cur,
              [e["algs"][alg] for e in prior if alg in e["algs"]],
              cpt_tolerance)
    if current.get("metric") == HEADLINE_METRIC:
        for alg in REQUIRED_CELLS:
            if alg in current["algs"]:
                continue
            seen = sum(1 for e in prior
                       if e["metric"] == HEADLINE_METRIC
                       and alg in e["algs"])
            if seen:
                failures.append(
                    f"required cell commits_per_tick[{alg}] missing "
                    f"from the current headline point ({seen} prior "
                    "point(s) carried it)")
            else:
                skipped.append(f"required cell {alg}: no prior data "
                               "(requirement arms once it appears)")
    # saturation-knee trajectory (--offered-load records): an
    # algorithm's knee collapsing means it saturates at a lower offered
    # rate than it used to — the same schedule-pure gate as
    # commits_per_tick, so it shares that tolerance
    for alg, cur in sorted(current.get("knees", {}).items()):
        check(f"offered_load_knee[{alg}]", cur,
              [e["knees"][alg] for e in prior
               if alg in e.get("knees", {})],
              cpt_tolerance)
    # scaling-grid trajectory (--scaling-grid records): a grid cell's
    # parallel efficiency collapsing means the cluster scales worse at
    # that (alg, nodes, batch) point than it used to — schedule-pure
    # like commits_per_tick, so it shares that tolerance and self-arms
    # once the trajectory carries the cell
    for cell_key, cur in sorted(current.get("scaling_grid", {}).items()):
        check(f"scaling_grid_efficiency[{cell_key}]", cur,
              [e["scaling_grid"][cell_key] for e in prior
               if cell_key in e.get("scaling_grid", {})],
              cpt_tolerance)
    # remote-amplification trajectory (the same --scaling-grid cells):
    # INVERTED — the ratio GROWING means every requested access ships
    # more remote entries over the mesh than it used to (the PR 9
    # flat-MAAT diagnosis), so the gate is a ceiling, not a floor
    for cell_key, cur in sorted(current.get("scaling_amp", {}).items()):
        check_ceiling(f"scaling_grid_amplification[{cell_key}]", cur,
                      [e["scaling_amp"][cell_key] for e in prior
                       if cell_key in e.get("scaling_amp", {})],
                      cpt_tolerance)
    # pipeline-overlap trajectory (--scaling-grid --pipeline records):
    # a pipelined cell's overlap fraction collapsing means the split
    # exchange's issue order re-serialized (the compiler stopped
    # overlapping the collectives with shard-local compute) — gated as
    # a floor at the shared schedule-pure tolerance, self-arming once a
    # pipelined run lands in the history
    for cell_key, cur in sorted(current.get("pipeline_overlap",
                                            {}).items()):
        check(f"pipeline_overlap_frac[{cell_key}]", cur,
              [e["pipeline_overlap"][cell_key] for e in prior
               if cell_key in e.get("pipeline_overlap", {})],
              cpt_tolerance)
    # adaptive-vs-static trajectory (--adaptive records): a cell's ratio
    # dropping means the controller's closed loop wins less over the best
    # hand-tuned static backoff than it used to — schedule-pure like
    # commits_per_tick, so it shares that tolerance and self-arms once
    # the first sweep lands in the history
    for cell_key, cur in sorted(current.get("adaptive_vs_static",
                                            {}).items()):
        check(f"adaptive_vs_static[{cell_key}]", cur,
              [e["adaptive_vs_static"][cell_key] for e in prior
               if cell_key in e.get("adaptive_vs_static", {})],
              cpt_tolerance)
    # serve-mode p99 trajectory (--serve records): INVERTED — the
    # per-family exact-histogram p99 GROWING past the ceiling means the
    # same flash-crowd schedule now leaves a fatter latency tail than it
    # used to, the regression the SLO plane exists to catch; self-arms
    # once the first serve run lands in the history
    for cell_key, cur in sorted(current.get("slo_p99", {}).items()):
        check_ceiling(f"slo_p99[{cell_key}]", cur,
                      [e["slo_p99"][cell_key] for e in prior
                       if cell_key in e.get("slo_p99", {})],
                      cpt_tolerance)
    # wait-chain-depth trajectory (--depgraph records): INVERTED — the
    # per-alg peak chain depth GROWING past the ceiling means the same
    # contended cell now serializes commits behind longer dependency
    # chains than it used to; self-arms once the first sweep lands
    for cell_key, cur in sorted(current.get("depgraph_chain",
                                            {}).items()):
        check_ceiling(f"depgraph_max_chain_depth[{cell_key}]", cur,
                      [e["depgraph_chain"][cell_key] for e in prior
                       if cell_key in e.get("depgraph_chain", {})],
                      cpt_tolerance)
    result = {"current": current, "checks": checks, "failures": failures,
              "skipped": skipped}
    if failures:
        # a failing gate ships pre-triaged: rank every ride-along cell
        # of the failing point against the median of the same priors the
        # checks used, mapped to config levers (obs/diff.py)
        from deneva_tpu.obs import diff as obs_diff
        result["diagnosis"] = obs_diff.diagnose_entries(current, prior)
    return result


def render_text(result: dict) -> str:
    lines = []
    cur = result["current"]
    if cur is not None:
        lines.append(f"[regress] current: {cur['source']} "
                     f"({cur['metric']}={cur['value']:g}, "
                     f"{len(cur['algs'])} per-alg cells)")
    for c in result["checks"]:
        bound = (f"floor {c['floor']:g}" if "floor" in c
                 else f"ceiling {c['ceiling']:g}")
        lines.append(f"  {'OK  ' if c['ok'] else 'FAIL'} {c['name']}: "
                     f"{c['current']:g} vs median {c['median']:g} "
                     f"({bound}, n={c['n_prior']})")
    # failures without a numeric check row (the required-cell rule)
    for f in result["failures"]:
        if f.startswith("required cell"):
            lines.append(f"  FAIL {f}")
    for s in result["skipped"]:
        lines.append(f"  skip {s}")
    n = len(result["failures"])
    lines.append(f"[regress] {n} regression(s)")
    if result.get("diagnosis"):
        from deneva_tpu.obs import diff as obs_diff
        lines.append(obs_diff.render_diagnosis(result["diagnosis"]))
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m deneva_tpu.obs.regress",
        description="gate the current bench point against the "
                    "trajectory median; exit code = regressions")
    p.add_argument("paths", nargs="+",
                   help="BENCH_r*.json snapshots, bench_history.jsonl "
                        "files, or directories containing one")
    p.add_argument("--tolerance", type=float,
                   default=DEFAULT_HEADLINE_TOL,
                   help="allowed fractional drop of the wall-clock "
                        "headline vs the median (default %(default)s: "
                        "wall tput drifts with the session)")
    p.add_argument("--cpt-tolerance", type=float,
                   default=DEFAULT_CPT_TOL,
                   help="allowed fractional drop of per-alg "
                        "commits_per_tick (default %(default)s)")
    p.add_argument("--current", default=None,
                   help="gate THIS snapshot path instead of the latest "
                        "trajectory point")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    entries = load_trajectory(args.paths)
    current = None
    if args.current:
        current = load_snapshot(args.current)
        if current is None:
            print(f"[regress] --current {args.current} has no parsed "
                  "metric (failed run?)")
            return 1
        entries = [e for e in entries
                   if e["source"] != current["source"]] + [current]
    result = gate(entries, current=current, tolerance=args.tolerance,
                  cpt_tolerance=args.cpt_tolerance)
    if result.get("diagnosis"):
        # the failure artifact lands next to the history file (first
        # directory argument), so CI archives the triage with the gate
        out_dir = next((p for p in args.paths if os.path.isdir(p)), ".")
        art = os.path.join(out_dir, "diagnosis_regress.json")
        with open(art, "w") as f:
            json.dump(result["diagnosis"], f)
        print(f"[regress] diagnosis artifact: {art}")
    if args.json:
        print(json.dumps(result))
    else:
        print(render_text(result))
    return min(len(result["failures"]), 125)


if __name__ == "__main__":          # pragma: no cover - CLI shim
    raise SystemExit(main())
