"""Windowed counter snapshots: the device half of causal diagnosis.

Every published finding so far (the PR 9 "flat MAAT scaling is remote
amplification, not imbalance"; the PR 13 "adaptive collapses on HOT
cells") was hand-derived from END-of-run counters — one cumulative
number per run, no way to see WHEN inside a run the behavior changed.
This module makes runs phase-segmentable: every ``Config.window_ticks``
ticks the tick function latches the FULL cumulative counter vocabulary
(the engine aggregates, the per-reason abort taxonomy, the ``lat_*``
integrals, queue depth/backlog, ``ctrl_*`` decisions, remote/reship
counts, the mesh row sum when that plane rides) into a keep-last
snapshot ring in the donated stats carry.  Host-side consumers
(:mod:`deneva_tpu.obs.diff`, the Perfetto export) difference adjacent
snapshots into per-window deltas — pre/post a hot-set shift, a rate
step, a fault injection, or an adaptive gear change.

The plane is self-verifying under the exact identity

    sum of window deltas == final cumulative counters

which holds bit-exactly for the int32 columns (telescoping int sums)
and requires the LAST snapshot to equal the final carry for the float32
columns (:func:`reconcile` checks both, plus the tick stamps that pin
each row to its window).  A run that latches more windows than the ring
holds is REFUSED loudly (a ``window_ring_wrapped`` finding, like the
flight recorder's span ring) — a wrapped ring can no longer prove the
identity, and silently passing would be a lie.

Column vocabulary: derived, not declared.  :func:`columns` scrapes the
same stats/db dicts the [summary] scrape reads — every 0-d non-``arr_``
int32/float32 stats scalar (minus ``wr_ring_cursor``, which the write-
buffer flush resets), every 0-d db ``_cnt`` plugin counter, a leading
``tick`` stamp, and a derived ``mesh_tx_total`` row sum when the mesh
plane is carried — so new counters join the window vocabulary the tick
they are added, with no second registry to drift.

Sharded runs carry one ring per node (the tick body under shard_map
sees single-node shapes, so the SAME latch serves both engines); the
node-stacked ``(N, S, K)`` int rings merge EXACTLY by elementwise add
— :meth:`ShardedEngine.window_cluster_plane` proves the device psum
bit-equal to the host sum, the obs/histo.py pattern.

Off path (``Config.windows`` false, the default) this module
contributes zero carried arrays and zero summary keys — the certifier
holds the flag byte-identical like every other ``_optin`` observatory.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

#: stats scalars excluded from the window vocabulary: non-cumulative
#: bookkeeping the run protocol resets mid-run (engine/scheduler.py
#: _flush_body zeroes the write-ring cursor at every run() boundary, so
#: its "final" value is not the value the last latch saw)
EXCLUDE = ("wr_ring_cursor",)

#: derived int column: the mesh observatory's whole-plane row sum
#: (obs/mesh.py; [summary] mesh_tx_total), latched when the plane rides
MESH_COL = "mesh_tx_total"

#: stamp column (always column 0 of the int ring): the 1-based tick the
#: snapshot was latched at — row w must stamp (w+1) * window_ticks, the
#: contiguity check that catches a lost window
TICK_COL = "tick"


def columns(stats: dict, db: dict, stacked: bool = False):
    """The window vocabulary, derived from the carried dicts: sorted
    ``(int_cols, float_cols)`` name tuples.  ``stacked`` reads the
    host-side node-stacked view (scalars carry a leading node axis).
    Deterministic in the key sets only, so the device latch and every
    host consumer agree by construction."""
    nd = 1 if stacked else 0
    ints, floats = [TICK_COL], []
    for k in sorted(stats):
        if k.startswith(("arr_", "window_")) or k in EXCLUDE:
            continue
        v = stats[k]
        if v.ndim != nd:
            continue
        if v.dtype == jnp.int32:
            ints.append(k)
        elif v.dtype == jnp.float32:
            floats.append(k)
    ints += [k for k in sorted(db)
             if k.endswith("_cnt") and db[k].ndim == nd
             and db[k].dtype == jnp.int32]
    if "arr_mesh_tx" in stats:
        ints.append(MESH_COL)
    return tuple(ints), tuple(floats)


def init_windows(cfg, stats: dict, db: dict) -> dict:
    """Stats-dict entries for the snapshot plane; empty when
    ``Config.windows`` is off (the disabled path carries nothing).
    Called AFTER the rest of the carry exists — the ring widths are the
    derived vocabulary's, so they see every other observatory's
    scalars."""
    if not cfg.windows:
        return {}
    ints, floats = columns(stats, db)
    S = cfg.window_slots
    return {
        "arr_window_i32": jnp.zeros((S, len(ints)), jnp.int32),
        "arr_window_f32": jnp.zeros((S, len(floats)), jnp.float32),
        # cumulative latch count: ring cursor (mod S) AND wrap detector
        # in one scalar, the flight-recorder idiom.  arr_-prefixed so
        # neither engine's scalar scrape nor the sharded counter psum
        # picks it up (it is per-node bookkeeping, not a counter).
        "arr_window_cnt": jnp.zeros((), jnp.int32),
    }


def latch(cfg, stats: dict, db: dict, t) -> dict:
    """Jit-pure end-of-tick latch: every ``window_ticks``-th tick, copy
    the cumulative vocabulary into the next ring row (keep-last: write
    position ``cnt % S``).  Off ticks scatter to the out-of-bounds row
    and drop — unconditional compute, no lax.cond, so the traced graph
    is tick-invariant (zero post-warm recompiles).  No-op when the
    plane is off."""
    if "arr_window_cnt" not in stats:
        return stats
    ints, floats = columns(stats, db)
    W = jnp.int32(cfg.window_ticks)
    cnt = stats["arr_window_cnt"]
    do = (t + 1) % W == 0

    def value(k):
        if k == TICK_COL:
            return t + 1
        if k == MESH_COL:
            return jnp.sum(stats["arr_mesh_tx"]).astype(jnp.int32)
        return stats[k] if k in stats else db[k]

    ring_i = stats["arr_window_i32"]
    ring_f = stats["arr_window_f32"]
    S = ring_i.shape[0]
    pos = jnp.where(do, cnt % S, S)
    row_i = jnp.stack([value(k).astype(jnp.int32) for k in ints])
    row_f = jnp.stack([value(k).astype(jnp.float32) for k in floats])
    return {**stats,
            "arr_window_i32": ring_i.at[pos].set(
                row_i, mode="drop", unique_indices=True),
            "arr_window_f32": ring_f.at[pos].set(
                row_f, mode="drop", unique_indices=True),
            "arr_window_cnt": cnt + do.astype(jnp.int32)}


# ---------------------------------------------------------------------------
# host side: snapshot / deltas / reconcile
# ---------------------------------------------------------------------------

def _stacked(stats: dict) -> bool:
    return np.asarray(stats["arr_window_cnt"]).ndim == 1


def snapshot(cfg, stats: dict, db: dict) -> dict:
    """Host view of the window plane: cluster rings (node axis summed
    for the int columns — the exact merge; float columns summed the
    same way the cluster summary host-sums its float scalars), the
    latch count, and the final cumulative counters read from the SAME
    dicts, for :func:`reconcile`.  ``None`` when the plane is off."""
    if "arr_window_cnt" not in stats:
        return None
    stacked = _stacked(stats)
    ints, floats = columns(stats, db, stacked=stacked)
    ring_i = np.asarray(stats["arr_window_i32"])
    ring_f = np.asarray(stats["arr_window_f32"])
    cnts = np.asarray(stats["arr_window_cnt"])
    if stacked:
        nodes = ring_i.shape[0]
        # lockstep tick clock: every node latches the same windows, so
        # the stacked rings align row-for-row and merge by adding —
        # except the tick stamp, identical across nodes (keep one copy)
        stamp = ring_i[0, :, 0]
        ring_i = ring_i.sum(axis=0, dtype=np.int64)
        ring_i[:, 0] = stamp
        ring_f = ring_f.sum(axis=0, dtype=np.float64)
        cnt = int(cnts.max())
    else:
        nodes, cnt = 1, int(cnts)
        ring_i = ring_i.astype(np.int64)
        ring_f = ring_f.astype(np.float64)

    def final(k, cast):
        if k == TICK_COL:
            return None
        if k == MESH_COL:
            return int(np.asarray(stats["arr_mesh_tx"]).sum())
        v = np.asarray(stats[k] if k in stats else db[k])
        return cast(v.sum()) if stacked else cast(v)

    return {"cols_i": ints, "cols_f": floats,
            "ring_i": ring_i, "ring_f": ring_f,
            "cnt": cnt, "cnts": cnts if stacked else np.asarray([cnt]),
            "slots": ring_i.shape[0], "nodes": nodes,
            "window_ticks": cfg.window_ticks,
            "final_i": {k: final(k, int) for k in ints if k != TICK_COL},
            "final_f": {k: final(k, float) for k in floats}}


def n_valid(snap: dict) -> int:
    """Rows of the ring holding live snapshots (all of them once the
    run latched ``slots`` windows)."""
    return min(snap["cnt"], snap["slots"])


def wrapped(snap: dict) -> bool:
    return snap["cnt"] > snap["slots"]


def deltas(snap: dict) -> dict:
    """Per-window delta rows: adjacent-snapshot differences with the
    zero init as the baseline — ``{"ticks": (V,), "int": (V, Ki) by
    cols_i, "float": (V, Kf) by cols_f}`` over the V valid windows (in
    latch order; meaningful only while the ring has not wrapped)."""
    v = n_valid(snap)
    ring_i, ring_f = snap["ring_i"][:v], snap["ring_f"][:v]
    base_i = np.zeros((1, ring_i.shape[1]), ring_i.dtype)
    base_f = np.zeros((1, ring_f.shape[1]), ring_f.dtype)
    return {"ticks": ring_i[:, 0].copy(),
            "int": np.diff(ring_i, axis=0, prepend=base_i),
            "float": np.diff(ring_f, axis=0, prepend=base_f)}


def reconcile(snap: dict, summary: dict | None = None) -> list:
    """Findings list proving the window identity (empty == clean):

    - ``window_ring_wrapped``: more windows latched than kept — the
      loud refusal; a wrapped ring cannot prove anything below.
    - ``window_cnt_skew``: sharded nodes disagree on the latch count
      (the tick clock is lockstep; disagreement is a latch bug).
    - ``window_tick_stamp``: row w not stamped ``(w+1) * window_ticks``
      — a lost or misplaced window.
    - ``window_int_identity``: sum of per-window deltas != the final
      cumulative counter, per int column (exact, int arithmetic).
    - ``window_float_final``: last snapshot != the final carry value,
      per float column (the float form of the identity: the telescoped
      delta sum IS the last snapshot).
    - ``window_summary_drift``: a column's final disagrees with the
      engine summary dict, when one is passed (same vocabulary, same
      values — catches a scrape/latch divergence).
    """
    bad = []
    if wrapped(snap):
        bad.append(("window_ring_wrapped", snap["cnt"], snap["slots"]))
        return bad
    if int(snap["cnts"].min()) != int(snap["cnts"].max()):
        bad.append(("window_cnt_skew", snap["cnts"].tolist()))
        return bad
    W, v = snap["window_ticks"], n_valid(snap)
    d = deltas(snap)
    want = np.arange(1, v + 1, dtype=np.int64) * W
    if not np.array_equal(d["ticks"], want):
        bad.append(("window_tick_stamp", d["ticks"].tolist(),
                    want.tolist()))
    sums = d["int"].sum(axis=0)
    for j, k in enumerate(snap["cols_i"]):
        if k == TICK_COL:
            continue
        if int(sums[j]) != snap["final_i"][k]:
            bad.append(("window_int_identity", k, int(sums[j]),
                        snap["final_i"][k]))
    last_f = (snap["ring_f"][v - 1] if v
              else np.zeros(len(snap["cols_f"])))
    for j, k in enumerate(snap["cols_f"]):
        if float(last_f[j]) != snap["final_f"][k]:
            bad.append(("window_float_final", k, float(last_f[j]),
                        snap["final_f"][k]))
    if summary is not None:
        for k, fin in snap["final_i"].items():
            if k in summary and k != "measured_ticks" \
                    and int(summary[k]) != fin:
                bad.append(("window_summary_drift", k, fin,
                            int(summary[k])))
    return bad


def summary_keys(cfg, stats: dict) -> dict:
    """``window_*`` [summary] keys (merged only when the plane is on):
    the latch count (max across nodes — lockstep, reconcile pins the
    skew), wrap verdict, and the ring geometry the host needs to
    re-derive windows from the record."""
    cnts = np.asarray(stats["arr_window_cnt"])
    cnt = int(cnts.max())
    return {"window_cnt": cnt,
            "window_wrapped": int(cnt > cfg.window_slots),
            "window_slots": cfg.window_slots,
            "window_ticks_per": cfg.window_ticks}


def record_extra(cfg, stats: dict, db: dict) -> dict:
    """Run-record extra block (obs/profiler.py write_run_record): the
    full window plane as JSON-serializable lists, so obs/diff.py can
    segment a recorded run without the device arrays.  ``{}`` when the
    plane is off."""
    snap = snapshot(cfg, stats, db)
    if snap is None:
        return {}
    v = n_valid(snap)
    return {"windows": {
        "cols_i": list(snap["cols_i"]), "cols_f": list(snap["cols_f"]),
        "ring_i": snap["ring_i"][:v].tolist(),
        "ring_f": snap["ring_f"][:v].tolist(),
        "cnt": snap["cnt"], "slots": snap["slots"],
        "window_ticks": snap["window_ticks"], "nodes": snap["nodes"],
        "wrapped": wrapped(snap)}}
