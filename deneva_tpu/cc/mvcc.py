"""Multi-version timestamp ordering (CC_ALG=MVCC) — rebuild of Row_mvcc
(concurrency_control/row_mvcc.cpp:198-364).

Per-row state is a bounded version ring of HIS_RECYCLE_LEN slots
(config.h:130), the tensorized write-history + read-history:

  w_ring  (rows, H) — committed version timestamps (0 = empty slot)
  r_ring  (rows, H) — max read-ts observed per version (per-version rts)
  rts0    (rows,)   — read-ts on the implicit initial version (wts = 0)
  w_floor (rows,)   — max version-ts ever evicted from the ring; any access
                      whose target version falls at or below the floor
                      cannot be resolved safely and aborts (the reference
                      instead blocks recycling of in-use versions,
                      row_mvcc.cpp:311-318)

Eviction replaces the MINIMUM-ts slot (not insertion order): commits need
not arrive in ts order (a long-running old txn can commit an old version
late), and evicting by ts keeps the ring = "the H newest versions", which
makes the floor rule sound: a read/prewrite at ts is safe iff no evicted
version lies in (target_version_ts, ts].

Decision rules (requests processed in ts order within a tick; a "pending
prewrite" is a granted write access of a live txn):

  READ at ts   : v = newest committed version with wts <= ts.
                 w_floor in (v.wts, ts] -> Abort (target version evicted)
                 pts = max pending-prewrite ts < ts on this row.
                 pts > v.wts            -> WAIT  (conflict(): a prewrite-read
                                          couple with no committed write in
                                          between, row_mvcc.cpp:198-215)
                 else grant; r_ring[v] = max(r_ring[v], ts)
  WRITE at ts  : v = newest committed version with wts <= ts.
                 w_floor in (v.wts, ts] -> Abort (cannot see evicted rts)
                 r_ring[v] > ts         -> Abort (a read that observed v at a
                                          later ts; row_mvcc.cpp:217-239)
                 else grant (prewrite pending until commit)
  commit       : insert one version per written row into the min-ts slot;
                 when several txns commit the same row in one tick only the
                 newest becomes a version, the others fold into w_floor
                 (a reader between them would abort — safe, and such ties
                 are rare)
  abort        : pending prewrites vanish (XP_REQ debuffer); read history is
                 retained, as in the reference (only P_REQ is debuffered)

Within-tick one-directionality: sorted-by-ts processing means earlier
entries (smaller ts) can affect later ones only via the pending-prewrite
prefix; a same-tick granted read can never conflict a same-tick later
prewrite (its ts is smaller), and later reads see earlier granted prewrites
through the prefix — matching sequential arrival in ts order.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


from deneva_tpu.cc.base import AccessDecision, CCPlugin, static_reason
from deneva_tpu.cc import compact as ccompact
from deneva_tpu.config import Config
from deneva_tpu.engine.state import (BIG_TS, NULL_KEY, TxnState,
                                     contract_window, expand_window,
                                     make_entries, request_window)
from deneva_tpu.ops import segment as seg


class Mvcc(CCPlugin):
    name = "MVCC"
    new_ts_on_restart = True
    #: all MVCC access aborts are one family — the target version is
    #: unreachable (evicted past the floor, or a later read already
    #: observed it; module doc decision rules)
    access_abort_reasons = ("mvcc_version_miss",)

    def init_db(self, cfg: Config, n_rows: int, B: int, R: int) -> dict:
        # rings are stored FLAT (n_rows * H,), addressed as key*H + slot:
        # a 2-D (n_rows, H) layout turns every .at[k, slot] update into an
        # XLA transpose + dynamic-update-slice loop over the whole 512 MB
        # array (~160 ms/tick at 16M rows); the flat layout keeps them
        # plain 1-D scatters (PROFILE.md)
        H = cfg.his_recycle_len
        return {
            **super().init_db(cfg, n_rows, B, R),
            "w_ring": jnp.zeros(n_rows * H, jnp.int32),
            "r_ring": jnp.zeros(n_rows * H, jnp.int32),
            "rts0": jnp.zeros(n_rows, jnp.int32),
            "w_floor": jnp.zeros(n_rows, jnp.int32),
            # committed writes folded into the floor because a commit
            # burst straddled the K-lane merge slice (safe-abort
            # direction, but a source of abort bias — parity runs check
            # this stayed 0; a full-width lax.cond fallback was rejected
            # because the cond would carry both 512 MB rings)
            "mvcc_tail_fold_cnt": jnp.zeros((), jnp.int32),
        }

    def on_ts_rebase(self, cfg: Config, db: dict, shift) -> dict:
        shift_keep = lambda a: jnp.where(a > 0, jnp.maximum(a - shift, 1), 0)
        return {**db,
                "w_ring": shift_keep(db["w_ring"]),
                "r_ring": shift_keep(db["r_ring"]),
                "rts0": jnp.maximum(db["rts0"] - shift, 0),
                "w_floor": jnp.maximum(db["w_floor"] - shift, 0)}

    def _version_lookup(self, db, key, ts):
        """Newest committed version with wts <= ts for each entry.

        Returns (v_ts, v_slot, evicted): v_ts == 0 means the initial version;
        evicted flags entries whose true target version may have left the
        ring (an evicted version-ts lies in (v_ts, ts]).
        """
        n_rows = db["rts0"].shape[0]
        H = db["w_ring"].shape[0] // n_rows
        k = jnp.clip(key, 0, n_rows - 1)
        ring = db["w_ring"][(k * H)[:, None]
                            + jnp.arange(H, dtype=jnp.int32)[None, :]]
        eligible = (ring > 0) & (ring <= ts[:, None])
        v_ts = jnp.max(jnp.where(eligible, ring, 0), axis=1)
        v_slot = jnp.argmax(jnp.where(eligible, ring, -1), axis=1)
        floor = db["w_floor"][k]
        evicted = (floor > v_ts) & (floor <= ts)
        return v_ts, v_slot.astype(jnp.int32), evicted

    def access(self, cfg: Config, db: dict, txn: TxnState, active):
        ent = make_entries(txn, active, window=cfg.acquire_window)
        B, R = txn.keys.shape
        n_rows = db["rts0"].shape[0]
        H = db["w_ring"].shape[0] // n_rows

        # version lookup at the REQUEST lanes only (B*W, not B*R: only
        # requests consult per-row state; gathers are per-lane latency)
        rkey, riw, valid = request_window(txn, active, cfg.acquire_window)
        W = rkey.shape[1]
        kw = rkey.reshape(-1)
        tsw = jnp.broadcast_to(txn.ts[:, None], (B, W)).reshape(-1)
        v_ts_w, v_slot_w, evicted_w = self._version_lookup(db, kw, tsw)
        kwc = jnp.clip(kw, 0, n_rows - 1)
        rts_v_w = jnp.where(v_ts_w > 0,
                            db["r_ring"][kwc * H + v_slot_w],
                            db["rts0"][kwc])

        # prewrite rule: a later read already observed my target version
        w_abort_w = (rts_v_w > tsw) | evicted_w
        w_abort = expand_window(
            txn, w_abort_w.reshape(B, W)).reshape(-1)
        evicted = expand_window(
            txn, evicted_w.reshape(B, W)).reshape(-1)
        v_ts = expand_window(txn, v_ts_w.reshape(B, W)).reshape(-1)

        # pending-prewrite prefix per row segment (ts order), at the
        # compacted live width (cc/compact.py: finishing txns' held
        # prewrites rank first, so they can never become invisible)
        db, ac = ccompact.compact_access(cfg, db, ent, B, R,
                                         extras=(w_abort, evicted, v_ts))
        c = ac.ent
        w_ab_c, evict_c, v_ts_c = ac.extras
        nK = c.key.shape[0]
        orig = jnp.arange(nK, dtype=jnp.int32)
        payload = (c.is_write, c.held, c.req, w_ab_c, orig)
        if cfg.depgraph:
            payload = payload + (c.txn,)
        (skey, sts), spay = seg.sort_by((c.key, c.ts), payload)
        s_iw, s_held, s_req, s_wab, s_orig = spay[:5]
        starts = seg.segment_starts(skey)
        live = skey != NULL_KEY
        pending_w = live & s_iw & (s_held | (s_req & ~s_wab))
        # max pending-prewrite ts strictly before me in ts order
        pref = seg.seg_prefix_max(jnp.where(pending_w, sts, 0), starts)
        pts = seg.unpermute(s_orig, pref)

        r_wait = (pts > v_ts_c) & (pts > 0)
        r_abort = evict_c

        grant_e = c.req & jnp.where(c.is_write, ~w_ab_c,
                                    ~r_abort & ~r_wait)
        wait_e = c.req & ~c.is_write & ~r_abort & r_wait
        abort_e = c.req & ~grant_e & ~wait_e
        blk = None
        if cfg.depgraph:
            # blocker of a conflict()-WAITING read: the nearest preceding
            # pending prewrite in ts order — the largest-ts prewriter
            # below me, exactly the `pts` the wait rule tested.  Aborts
            # (version evicted / observed by a later committed read) are
            # against history, not a live txn: 0.
            s_slot = spay[5]
            lane = jnp.arange(nK, dtype=jnp.int32)
            blane = seg.seg_prefix_max(jnp.where(pending_w, lane, -1),
                                       starts, identity=-1)
            blk_s = jnp.where(blane >= 0,
                              s_slot[jnp.clip(blane, 0)] + 1, 0)
            blk = jnp.where(wait_e, seg.unpermute(s_orig, blk_s), 0)
            blk = ccompact.finish_blocker(ac, blk).reshape(B, R)
        reason = static_reason(cfg, self.access_abort_reasons[0],
                               abort_e.shape)
        grant_e, wait_e, abort_e = ccompact.finish_access(
            ac, ent.req, grant_e, wait_e, abort_e)
        reason = ccompact.finish_reason(ac, ent.req, reason)

        # granted reads record their rts on the version they read;
        # scatter from the request lanes (grant only exists there)
        grant_w2 = grant_e.reshape(B, R)
        gr_w = contract_window(txn, grant_w2, W).reshape(-1) \
            & ~riw.reshape(-1)
        r_ring = db["r_ring"].at[
            jnp.where(gr_w & (v_ts_w > 0), kwc * H + v_slot_w,
                      jnp.int32(2**31 - 1))].max(tsw, mode="drop")
        rts0 = db["rts0"].at[
            jnp.where(gr_w & (v_ts_w == 0), kw, NULL_KEY)].max(
            tsw, mode="drop")

        return (AccessDecision(grant=grant_w2,
                               wait=wait_e.reshape(B, R),
                               abort=abort_e.reshape(B, R),
                               reason=None if reason is None
                               else reason.reshape(B, R),
                               blocker=blk),
                {**db, "r_ring": r_ring, "rts0": rts0})

    def on_commit(self, cfg: Config, db: dict, txn: TxnState, committed,
                  commit_ts, tick):
        # insert EVERY committed write as a version (several same-tick
        # commits to one row each install a version in the reference too —
        # folding all but the newest into the floor was measured as a
        # systematic +4% abort bias at zipf 0.9, PARITY.md); a version
        # older than everything retained still folds into w_floor
        B, R = txn.keys.shape
        n_rows = db["rts0"].shape[0]
        H = db["w_ring"].shape[0] // n_rows
        ridx = jnp.arange(R, dtype=jnp.int32)[None, :]
        wmask = (committed[:, None] & txn.is_write
                 & (ridx < txn.n_req[:, None])).reshape(-1)
        key = jnp.where(wmask, txn.keys.reshape(-1), NULL_KEY)
        ts = jnp.broadcast_to(txn.ts[:, None], (B, R)).reshape(-1)

        # newest-first within each row: sort by (key, BIG - ts); dead lanes
        # sort last, so live committed writes are a PREFIX of the sorted
        # array — slice it to K lanes and gather only those rings
        (skey, _), (sts, slive) = seg.sort_by(
            (key, BIG_TS - ts), (ts, wmask))
        # slice width: the steady-state write-lane bound (admission cap
        # x writes per txn; commits/tick cannot exceed admissions/tick in
        # steady state) so only a commit burst can straddle it — and a
        # straddle folds into the floor (safe-abort direction), it cannot
        # lose a committed write's visibility.  The ring gather below is
        # K*H lanes (~2.7 ms at the old 2x width, PROFILE.md) — the
        # dominant MVCC commit cost, so size it tight.
        acap = cfg.admit_cap if cfg.admit_cap is not None else B
        # written rows per txn: TPC-C commits at most district + order +
        # max_items_per_txn stock/orderline writes, far below its padded
        # R=33 access width — the ring gather below is K*H lanes, so the
        # bound directly sets the dominant commit cost
        from deneva_tpu.config import TPCC
        wpt = (cfg.max_items_per_txn + 2) if cfg.workload == TPCC else R
        K = min(skey.shape[0], max(4096, acap * wpt))
        skeyK, stsK, sliveK = skey[:K], sts[:K], slive[:K]
        kk = jnp.clip(skeyK, 0, n_rows - 1)
        starts = seg.segment_starts(skeyK)
        pos = seg.pos_in_segment(starts)     # rank among row's new versions

        # closed form of iterative newest-first min-slot insertion: the
        # merged ring is the top-H of (old ring ∪ new versions).  A new
        # version at in-row rank p survives iff p + |{old > v_p}| < H (once
        # one folds, all younger fold too); a survivor replaces the p-th
        # smallest old slot, whose value goes to the floor; folded versions
        # fold their own ts into the floor.  No loop, ONE ring gather of K
        # lanes (the old per-rank while_loop re-gathered B*R lanes per
        # iteration — ~90 ms/tick at 16M rows).
        ring = db["w_ring"][(kk * H)[:, None]
                            + jnp.arange(H, dtype=jnp.int32)[None, :]]
        cnt_gt = jnp.sum((ring > stsK[:, None]).astype(jnp.int32), axis=1)
        survive = sliveK & (pos + cnt_gt < H)
        ring_asc = jnp.sort(ring, axis=1)
        slot_asc = jnp.argsort(ring, axis=1).astype(jnp.int32)
        onehot = jnp.arange(H, dtype=jnp.int32)[None, :] \
            == jnp.minimum(pos, H - 1)[:, None]
        slot = jnp.sum(jnp.where(onehot, slot_asc, 0), axis=1)
        old_at_p = jnp.sum(jnp.where(onehot, ring_asc, 0), axis=1)

        # survivors land on distinct ring cells (per row, distinct ranks p
        # pick distinct old slots via the slot_asc permutation); folded
        # lanes map to DISTINCT out-of-bounds cells so unique_indices=True
        # holds globally and the .set scatters stay order-independent
        iflat = jnp.where(survive, kk * H + slot,
                          n_rows * H + jnp.arange(K, dtype=jnp.int32))
        w_ring = db["w_ring"].at[iflat].set(stsK, mode="drop",
                                            unique_indices=True)
        r_ring = db["r_ring"].at[iflat].set(0, mode="drop",
                                            unique_indices=True)
        w_floor = db["w_floor"].at[jnp.where(sliveK, kk, n_rows)].max(
            jnp.where(survive, old_at_p, stsK), mode="drop")

        # >K committed write lanes in one tick (needs > 8192; admission is
        # capped far below): fold the overflow into the floor (safe-abort
        # direction), only when it actually happens — and COUNT it, so a
        # run can prove its results never took the fold bias
        fold_cnt = db["mvcc_tail_fold_cnt"]
        if skey.shape[0] > K:
            tail_live = slive[K:]

            def _fold(op):
                fl, c = op
                fl = fl.at[jnp.where(tail_live,
                                     jnp.clip(skey[K:], 0, n_rows - 1),
                                     n_rows)].max(sts[K:], mode="drop")
                return fl, c + jnp.sum(tail_live.astype(jnp.int32))

            w_floor, fold_cnt = jax.lax.cond(
                jnp.any(tail_live), _fold, lambda op: op,
                (w_floor, fold_cnt))
        return {**db, "w_ring": w_ring, "r_ring": r_ring,
                "w_floor": w_floor, "mvcc_tail_fold_cnt": fold_cnt}


