"""Optimistic concurrency control (CC_ALG=OCC) — rebuild of OptCC
(concurrency_control/occ.cpp:116-294, Kung-Robinson backward validation).

The reference serializes every validation through a global semaphore and
walks an unbounded history list of committed write sets
(occ.cpp:136-141,277-286).  Here validation is a per-tick batch job with no
critical section:

- the history list becomes one dense array ``wcommit`` (rows,) holding the
  scheduler tick of the last committed write per row; "some txn with commit
  tn in (my start, my finish] wrote row k" is then the O(1) test
  ``wcommit[k] > my start_tick`` (reads-only, occ.cpp:167-180);
- the active-writer check (occ.cpp:185-199) becomes a same-tick sorted join:
  txns finishing in the same tick are serialized by ts, and a txn conflicts
  if an earlier-in-order finisher that itself passed the history check
  writes a key in my read or write set (test_valid vs rset AND wset);
- reads never block and never update shared state at access time (the work
  phase is entirely optimistic), so ``access`` grants everything.

start_ts is re-drawn per attempt (worker_thread.cpp:500-502); the engine's
per-restart ``start_tick`` provides exactly that.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deneva_tpu.cc.base import AccessDecision, CCPlugin
from deneva_tpu.cc import base as cc_base
from deneva_tpu.config import Config
from deneva_tpu.engine.state import TxnState, NULL_KEY, make_entries
from deneva_tpu.ops import segment as seg


class Occ(CCPlugin):
    name = "OCC"
    new_ts_on_restart = True
    release_on_vabort = True   # prepare marks need the RFIN(abort) release
    #: OCC never aborts at access time; every CC abort is a failed
    #: backward validation (history or active-set check)
    vabort_reason = "occ_validation"
    #: adaptive escalation gate stays OFF: access always grants here, so
    #: stalling a writer at its cursor removes no validation conflict —
    #: the adaptive win for OCC comes from policy (a)'s long jittered
    #: vabort backoff draining the conflicting cohort instead
    esc_gate_ok = False

    def init_db(self, cfg: Config, n_rows: int, B: int, R: int) -> dict:
        db = {**super().init_db(cfg, n_rows, B, R),
              "occ_wcommit": jnp.full(n_rows, -1, jnp.int32),
              # validation outcome counters (the occ_check/abort families
              # of statistics/stats.h): history-check failures vs
              # active-set conflicts; warmup-gated, surfaced in [summary]
              "occ_hist_abort_cnt": jnp.zeros((), jnp.int32),
              "occ_active_abort_cnt": jnp.zeros((), jnp.int32)}
        if cfg.depgraph:
            # validation victim of the last active-set failure per slot
            # (txn slot, -1 = none): the earlier same-tick valid writer
            # the failed validator lost to.  The engine reads this at its
            # vabort note_aborts site (dependency observatory edges).
            db["dep_vblocker"] = jnp.full(B, -1, jnp.int32)
        if cfg.net_delay_ticks > 0:
            # prepare-phase reservation (net_delay mode): a yes-voted
            # validator's writes block later validators until its delayed
            # commit/abort applies — the 2PC prepared state of the
            # reference's distributed OCC (a validated txn stays in the
            # active set until finish, occ.cpp:219-233).  occ_prep holds
            # the pending validator's ts; occ_prep_until its expiry tick
            # (vote transit + slack), so a release lost to routing overflow
            # cannot block a row forever.
            db["occ_prep"] = jnp.zeros(n_rows, jnp.int32)
            db["occ_prep_until"] = jnp.zeros(n_rows, jnp.int32)
        return db

    def on_ts_rebase(self, cfg: Config, db: dict, shift) -> dict:
        if "occ_prep" not in db:
            return db
        p = db["occ_prep"]
        return {**db,
                "occ_prep": jnp.where(p > 0, jnp.maximum(p - shift, 1), 0)}

    def on_prepared_entries(self, cfg: Config, db: dict, keys, ts,
                            prepared, tick):
        # keep my marks alive while my commit is in transit/deferred
        if "occ_prep" not in db:
            return db
        n_rows = db["occ_prep"].shape[0]
        kc = jnp.clip(keys, 0, n_rows - 1)
        mine = prepared & (db["occ_prep"][kc] == ts)
        until = db["occ_prep_until"].at[jnp.where(mine, keys, NULL_KEY)].max(
            tick + cfg.net_delay_ticks + 2, mode="drop")
        return {**db, "occ_prep_until": until}

    def on_finalize_entries(self, cfg: Config, db: dict, keys, cts, live):
        # clear my prepare marks at commit/abort finish (RFIN receipt)
        if "occ_prep" not in db:
            return db
        n_rows = db["occ_prep"].shape[0]
        kc = jnp.clip(keys, 0, n_rows - 1)
        clear = live & (db["occ_prep"][kc] == cts)
        prep = db["occ_prep"].at[jnp.where(clear, keys, NULL_KEY)].min(
            0, mode="drop")
        return {**db, "occ_prep": prep}

    def access(self, cfg: Config, db: dict, txn: TxnState, active):
        # optimistic work phase: every access proceeds immediately — no
        # wait edges exist for OCC by construction (the depgraph blocker
        # plane is structurally present but always "none"; validation
        # victims surface through dep_vblocker at vabort time instead)
        B, R = txn.keys.shape
        req = make_entries(txn, active,
                           window=cfg.acquire_window).req.reshape(B, R)
        z = jnp.zeros((B, R), dtype=bool)
        zb = jnp.zeros((B, R), jnp.int32) if cfg.depgraph else None
        return AccessDecision(grant=req, wait=z, abort=z, blocker=zb), db

    def validate(self, cfg: Config, db: dict, txn: TxnState, finishing, tick):
        B, R = txn.keys.shape
        n_rows = db["occ_wcommit"].shape[0]
        ridx = jnp.arange(R, dtype=jnp.int32)[None, :]
        valid_acc = finishing[:, None] & (ridx < txn.n_req[:, None])
        rmask = valid_acc & ~txn.is_write
        wmask = valid_acc & txn.is_write

        # --- history check: a committed write landed on my read set after
        # my (re)start (occ.cpp:167-180).  Only FINISHING txns consult the
        # table, so compact their rows into a K-row buffer first (row
        # scatters are cheap; the K*R-lane gather replaces a B*R-lane one,
        # PROFILE.md); a >K finishing burst falls back to the full-width
        # gather under lax.cond ---
        K = min(B, 2048)
        if K >= B:
            # compaction saves nothing at small batches — gather full-width
            k = jnp.clip(txn.keys, 0, n_rows - 1)
            conf = rmask & (db["occ_wcommit"][k] > txn.start_tick[:, None])
            pass1 = finishing & ~conf.any(axis=1)
            return self._active_writer_fixed_point(cfg, db, txn, finishing,
                                                   pass1, tick)
        n_fin = jnp.sum(finishing.astype(jnp.int32))
        frank = jnp.cumsum(finishing.astype(jnp.int32)) \
            - finishing.astype(jnp.int32)
        # dead lanes map past K + B so indices stay GLOBALLY unique even
        # when a >K finishing burst pushes finisher ranks into [K, B)
        # (both ranges drop; unique_indices=True must hold regardless,
        # the cond below only selects which result is used)
        rowpos = jnp.where(finishing, frank,
                           K + B + jnp.arange(B, dtype=jnp.int32))
        buf_keys = jnp.full((K, R), NULL_KEY, jnp.int32).at[rowpos].set(
            jnp.where(rmask, txn.keys, NULL_KEY), mode="drop",
            unique_indices=True)
        buf_start = jnp.zeros(K, jnp.int32).at[rowpos].set(
            txn.start_tick, mode="drop", unique_indices=True)
        # inverse map: rank -> slot, for scattering the verdict back
        slot_of_rank = jnp.full(K, B, jnp.int32).at[rowpos].set(
            jnp.arange(B, dtype=jnp.int32), mode="drop",
            unique_indices=True)

        def _hist_compact(_):
            kb = jnp.clip(buf_keys, 0, n_rows - 1)
            conf = (buf_keys != NULL_KEY) \
                & (db["occ_wcommit"][kb] > buf_start[:, None])
            bad_buf = conf.any(axis=1)
            return jnp.zeros(B, dtype=bool).at[slot_of_rank].set(
                bad_buf, mode="drop", unique_indices=True)

        def _hist_full(_):
            k = jnp.clip(txn.keys, 0, n_rows - 1)
            conf = rmask & (db["occ_wcommit"][k] > txn.start_tick[:, None])
            return conf.any(axis=1)

        hist_bad = jax.lax.cond(n_fin <= K, _hist_compact, _hist_full,
                                operand=None)
        pass1 = finishing & ~hist_bad
        return self._active_writer_fixed_point(cfg, db, txn, finishing,
                                               pass1, tick)

    def _active_writer_fixed_point(self, cfg: Config, db: dict,
                                   txn: TxnState, finishing, pass1, tick):
        # --- same-tick active-writer check (occ.cpp:185-233): serialize
        # this tick's finishers by ts.  Under the global semaphore a FAILED
        # validator removes itself from the active set before the next
        # validator snapshots it (occ.cpp:219-233), so only finishers that
        # themselves fully validate may block later ones.  That is a
        # prefix-dependent greedy filter; compute its unique fixed point by
        # iterating "valid = pass1 & no earlier VALID writer conflicts"
        # (iteration n settles every conflict chain of depth <= n). ---
        B, R = txn.keys.shape
        ridx = jnp.arange(R, dtype=jnp.int32)[None, :]
        valid_acc = finishing[:, None] & (ridx < txn.n_req[:, None])
        if "occ_prep" in db:
            # prepare-mark conflict: a FOREIGN validator yes-voted a write
            # on one of my rows and its delayed commit/abort is still in
            # flight — conservative no-vote, like conflicting with a
            # prepared active-set member (occ.cpp:185-199 across ticks)
            n_rows = db["occ_prep"].shape[0]
            kc = jnp.clip(txn.keys, 0, n_rows - 1)
            prep = db["occ_prep"][kc]
            pconf = valid_acc & (prep > 0) & (prep != txn.ts[:, None]) \
                & (db["occ_prep_until"][kc] > tick)
            pass1 = pass1 & ~pconf.any(axis=1)
        ent_live = (valid_acc & pass1[:, None]).reshape(-1)
        key = jnp.where(ent_live, txn.keys.reshape(-1), NULL_KEY)
        ts = jnp.broadcast_to(txn.ts[:, None], (B, R)).reshape(-1)
        iw = txn.is_write.reshape(-1)
        tx = jnp.broadcast_to(
            jnp.arange(B, dtype=jnp.int32)[:, None], (B, R)).reshape(-1)
        n = B * R

        # live lanes (finishing, history-passed) compact to the static
        # bucket K: the whole fixed point then sorts K lanes per
        # iteration instead of the padded B*R.  All lanes here are
        # retryable — a spilled lane's txn simply votes no (forced
        # retry), exactly a failed validator leaving the active set —
        # so no class ranking is needed (contrast cc/compact.py).
        Kc = cfg.compact_width(n, B)
        view, (key, ts, iw, tx) = seg.compact_entries(
            ent_live, Kc, key, ts, iw, tx)
        db = cc_base.note_compaction(db, view)
        if not view.identity:
            ovf_b = jnp.any(
                seg.overflow_mask(ent_live, Kc).reshape(B, R), axis=1)
            pass1 = pass1 & ~ovf_b
        (skey, sts), (s_iw, s_tx) = seg.sort_by((key, ts), (iw, tx))
        starts = seg.segment_starts(skey)
        live = skey != NULL_KEY
        # a txn never conflicts with itself (test_valid intersects OTHER
        # txns' sets): exclude my own run by reading the blocking count at
        # my (key, txn)-run start
        run_start = starts | seg.segment_starts(s_tx)

        if R == 1 and cfg.node_cnt > 1:
            # Sharded virtual-txn context (every row of `txn` is one routed
            # access entry; single-shard R==1 workloads have node_cnt==1
            # and skip this — their ts-groups would all be singletons):
            # the reference's active set is per NODE per txn
            # (occ.cpp:219-233) — a validator failing ANY local check
            # leaves this node's active set entirely.  Entries of one home
            # txn share a globally unique ts, so aggregate per-entry
            # verdicts over ts-runs: validity (and blocking power) becomes
            # per-(owner, home txn), not per row.
            gord = jnp.arange(B, dtype=jnp.int32)
            gkey = jnp.where(finishing, txn.ts, NULL_KEY)
            # lint: disable-next=PAD-WIDTH-SORT (B,)-wide per-txn ts-group sort (sharded R==1 owner view): width is the txn axis, not padded B*R entries
            (g_sorted,), (g_orig,) = seg.sort_by((gkey,), (gord,))
            gstarts = seg.segment_starts(g_sorted)

            def group_and(ok_e):
                bad = (finishing & ~ok_e).astype(jnp.int32)
                # lint: disable-next=PAD-WIDTH-SORT same (B,)-wide per-txn ts-group reduction as above: re-sorts on the fixed group keys
                _, _, s_bad = seg.sort_pack((gkey, gord, bad), num_keys=2,
                                            is_stable=False)
                g_bad = seg.seg_reduce(s_bad, gstarts, "max")
                return finishing & seg.unpermute(g_orig, g_bad == 0)
        else:
            group_and = None

        def step(carry):
            valid, _ = carry
            # ship per-txn validity into sorted entry order by re-sorting
            # on the SAME fixed keys (a 3-operand sort is ~4x cheaper than
            # the per-lane gathers valid[s_tx] / cnt[run_start_idx] it
            # replaces, PROFILE.md); compaction preserves txn-major order
            # so valid[tx] stays a monotone gather
            valid_e = valid[jnp.clip(tx, 0, B - 1)]
            _, _, s_valid = seg.sort_pack(
                (key, ts, valid_e.astype(jnp.int32)), num_keys=2,
                is_stable=False)
            blocking = live & s_iw & (s_valid == 1)
            cnt_before = seg.seg_cumsum_exclusive(
                blocking.astype(jnp.int32), starts)
            at_start = seg.at_run_start(cnt_before, run_start, starts,
                                        -1, "max")
            # per-txn ANY via scatter-max straight from sorted order
            # (commutative, duplicate txn lanes race-free; dead lanes drop
            # at index B) — replaces the old unpermute + (B, R) reshape
            conflict_b = jnp.zeros(B, jnp.int32).at[
                jnp.where(live & (at_start > 0), s_tx, B)].max(
                1, mode="drop")
            new_valid = pass1 & (conflict_b == 0)
            if group_and is not None:
                new_valid = group_and(new_valid)
            return new_valid, jnp.any(new_valid != valid)

        # initial changed=True derived from pass1 so its sharding (varying
        # axes under shard_map) matches the body output.  (A speculative
        # 2-step unroll was measured SLOWER here — OCC's carry is one (B,)
        # bool, so the while boundary is cheap and unrolled steps just add
        # sorts; MAAT, whose carries are wide, keeps the unroll.)
        valid0 = group_and(pass1) if group_and is not None else pass1
        valid, _ = jax.lax.while_loop(
            lambda c: c[1], step, (valid0, jnp.any(pass1) | True))
        if "dep_vblocker" in db:
            # validation victim (Config.depgraph): with the fixed point
            # settled, a failed validator's blocker is the nearest earlier
            # VALID writer lane in its row segment — the same "blocking"
            # predicate the loop converged on, read once more to recover
            # identity instead of just existence
            valid_e = valid[jnp.clip(tx, 0, B - 1)]
            _, _, s_valid = seg.sort_pack(
                (key, ts, valid_e.astype(jnp.int32)), num_keys=2,
                is_stable=False)
            blocking = live & s_iw & (s_valid == 1)
            lane = jnp.arange(skey.shape[0], dtype=jnp.int32)
            blane = seg.seg_prefix_max(jnp.where(blocking, lane, -1),
                                       starts, identity=-1)
            bat = seg.at_run_start(blane, run_start, starts, -1, "max")
            has_b = live & (bat >= 0)
            vb = jnp.full(B, -1, jnp.int32).at[
                jnp.where(has_b, s_tx, B)].max(
                s_tx[jnp.clip(bat, 0)], mode="drop")
            db = {**db,
                  "dep_vblocker": jnp.where(pass1 & ~valid, vb, -1)}
        measuring = tick >= cfg.warmup_ticks
        cnt = lambda m: jnp.where(measuring,
                                  jnp.sum(m.astype(jnp.int32)), 0)
        # outcome counters bump once per VALIDATION EVENT (like the
        # reference's per-validate() increments — a deferred commit's
        # re-validation counts again there too).  Sharded (grouped) path:
        # one representative entry per (owner, home txn) group, so
        # per-entry masks don't inflate by the accesses-per-node factor.
        if group_and is not None:
            rep = seg.unpermute(g_orig, gstarts) & finishing
            hist_fail = rep & ~group_and(pass1)
            active_fail = rep & group_and(pass1) & ~valid
        else:
            hist_fail = finishing & ~pass1
            active_fail = pass1 & ~valid
        db = {**db,
              # hist-abort: the validation failed the committed-history /
              # prepare-mark checks; active-abort: passed them but lost
              # to an earlier valid same-round validator
              "occ_hist_abort_cnt": db["occ_hist_abort_cnt"]
              + cnt(hist_fail),
              "occ_active_abort_cnt": db["occ_active_abort_cnt"]
              + cnt(active_fail)}
        if "occ_prep" in db:
            # stamp prepare marks on the yes-voted write set (exclusive by
            # construction: foreign-marked rows failed pconf above and two
            # same-tick valid writers of one row are impossible — the fixed
            # point serializes them)
            wm = valid[:, None] & txn.is_write & (ridx < txn.n_req[:, None])
            keysf = jnp.where(wm, txn.keys, NULL_KEY).reshape(-1)
            # lint: disable-next=SCATTER-RACE live keys are exclusive
            # (keys unique within a txn; two same-tick valid writers of a
            # row impossible, the fixed point serializes them) and dead
            # lanes drop out of bounds at NULL_KEY
            prep = db["occ_prep"].at[keysf].set(ts, mode="drop")
            # lint: disable-next=SCATTER-RACE same exclusivity invariant
            until = db["occ_prep_until"].at[keysf].set(
                tick + cfg.net_delay_ticks + 2, mode="drop")
            db = {**db, "occ_prep": prep, "occ_prep_until": until}
        return valid, db

    def on_commit(self, cfg: Config, db: dict, txn: TxnState, committed,
                  commit_ts, tick):
        # append my write set to "history": bump each written row's last
        # committed-write tick (occ.cpp:277-286, tn = tnc++)
        B, R = txn.keys.shape
        ridx = jnp.arange(R, dtype=jnp.int32)[None, :]
        wmask = committed[:, None] & txn.is_write & (ridx < txn.n_req[:, None])
        wcommit = db["occ_wcommit"].at[txn.keys.reshape(-1)].max(
            jnp.where(wmask, tick, -1).reshape(-1), mode="drop")
        return {**db, "occ_wcommit": wcommit}
