"""Batched two-phase-locking arbitration (NO_WAIT / WAIT_DIE / CALVIN locks).

Replaces the reference's per-row mutex + owner/waiter pointer lists
(concurrency_control/row_lock.cpp:52-217) with one sorted join per tick:

  sort all live lock entries by (row_key, held-before-request, priority)
  and resolve grants with prefix reductions inside each row segment.

Tick semantics (the batched reformulation of sequential arrival order):
requests on a row are processed as if they arrived in priority (timestamp)
order, after all currently-held locks.  A request is granted iff it is
compatible with every lock that is held or granted earlier in that order:

  grant(read)  = no write lock held-or-granted earlier in my row segment
  grant(write) = I am the very first entry in my row segment

On failure the per-algorithm rules of row_lock.cpp apply:

- NO_WAIT  — abort immediately (row_lock.cpp:86-90).
- WAIT_DIE — wait iff older than every current owner (requester ts < all
  owner ts, row_lock.cpp:91-151); because requests are processed in ts
  order, any granted request earlier in my segment is older than me, so
  canwait reduces to: no granted request before me AND ts < min held ts.
- CALVIN   — FIFO, never aborts: priority is the sequence number, a failed
  entry blocks everything behind it (conflict if any waiter exists,
  row_lock.cpp:78-81,152-170), so grant requires *no write entry at all*
  earlier in the segment (failed or not).

Waiters hold no explicit queue: a WAITING txn re-submits the same request
with the same priority next tick, which reproduces the priority-ordered
waiter list of the reference (waiters kept in ts order, row_lock.cpp:134-141).

The sort is packed to three int32 operands (two keys + one payload) to keep
the TPU bitonic sort cheap: key/kind share one word (config asserts row ids
fit 30 bits) and flags/index share another (entry index fits 23 bits).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from deneva_tpu.engine.state import Entries, BIG_TS
from deneva_tpu.ops import segment as seg

_IDX_BITS = 23
_IDX_MASK = (1 << _IDX_BITS) - 1
_DEAD_ROW = (1 << 30) - 1


def arbitrate(ent: Entries, policy: str):
    """Resolve this tick's lock requests.

    Returns (grant, wait, abort): (B*R,) masks in original entry order,
    true only at request positions.
    """
    n = ent.key.shape[0]
    assert n <= 1 << _IDX_BITS, n
    live = ent.held | ent.req
    row = jnp.where(live, ent.key, _DEAD_ROW)
    kind = jnp.where(ent.held, 0, 1)
    keykind = row * 2 + kind
    payload = (jnp.arange(n, dtype=jnp.int32)
               | (ent.is_write.astype(jnp.int32) << _IDX_BITS)
               | (ent.held.astype(jnp.int32) << (_IDX_BITS + 1))
               | (ent.req.astype(jnp.int32) << (_IDX_BITS + 2)))

    skk, sts, spay = lax.sort((keykind, ent.ts, payload), num_keys=2,
                              is_stable=False)
    s_iw = (spay >> _IDX_BITS) & 1 == 1
    s_held = (spay >> (_IDX_BITS + 1)) & 1 == 1
    s_req = (spay >> (_IDX_BITS + 2)) & 1 == 1
    s_idx = spay & _IDX_MASK
    srow = skk >> 1
    s_live = srow != _DEAD_ROW

    starts = seg.segment_starts(srow)
    pos = seg.pos_in_segment(starts)

    if policy == "CALVIN":
        # FIFO: any write earlier in the segment (granted or not) blocks.
        any_w_before = seg.seg_any_before(s_iw & s_live, starts)
        s_grant = s_req & jnp.where(s_iw, pos == 0, ~any_w_before)
        s_wait = s_req & ~s_grant
        s_abort = jnp.zeros_like(s_grant)
    else:
        # A write only ever takes effect at segment position 0; a held X lock
        # is also necessarily at position 0 (exclusive => sole live entry
        # apart from this tick's requests).  So "conflicting lock earlier in
        # order" == "a write at pos 0 or a held write before me".
        eff_w_before = seg.seg_any_before(
            s_iw & s_live & (s_held | (pos == 0)), starts)
        s_grant = s_req & jnp.where(s_iw, pos == 0, ~eff_w_before)
        s_fail = s_req & ~s_grant
        if policy == "NO_WAIT":
            s_wait = jnp.zeros_like(s_fail)
            s_abort = s_fail
        elif policy == "WAIT_DIE":
            granted_before = seg.seg_any_before(s_grant, starts)
            min_held_ts = seg.seg_min_where(sts, s_held, starts, BIG_TS)
            canwait = ~granted_before & (sts < min_held_ts)
            s_wait = s_fail & canwait
            s_abort = s_fail & ~canwait
        else:  # pragma: no cover
            raise ValueError(policy)

    packed = (s_grant.astype(jnp.int32) | (s_wait.astype(jnp.int32) << 1)
              | (s_abort.astype(jnp.int32) << 2))
    out = jnp.zeros(n, jnp.int32).at[s_idx].set(packed)
    return out & 1 == 1, (out >> 1) & 1 == 1, (out >> 2) & 1 == 1
