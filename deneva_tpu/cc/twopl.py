"""Batched two-phase-locking arbitration (NO_WAIT / WAIT_DIE / CALVIN locks).

Replaces the reference's per-row mutex + owner/waiter pointer lists
(concurrency_control/row_lock.cpp:52-217) with one sorted join per tick:

  sort all live lock entries by (row_key, held-before-request, priority)
  and resolve grants with prefix reductions inside each row segment.

Tick semantics (the batched reformulation of sequential arrival order):
requests on a row are processed as if they arrived in priority (timestamp)
order, after all currently-held locks.  A request is granted iff it is
compatible with every lock that is held or granted earlier in that order:

  grant(read)  = no write lock held-or-granted earlier in my row segment
  grant(write) = I am the very first entry in my row segment

On failure the per-algorithm rules of row_lock.cpp apply:

- NO_WAIT  — abort immediately (row_lock.cpp:86-90).
- WAIT_DIE — wait iff older than every current owner (requester ts < all
  owner ts, row_lock.cpp:91-151); because requests are processed in ts
  order, any granted request earlier in my segment is older than me, so
  canwait reduces to: no granted request before me AND ts < min held ts.
- CALVIN   — FIFO, never aborts: priority is the sequence number, a failed
  entry blocks everything behind it (conflict if any waiter exists,
  row_lock.cpp:78-81,152-170), so grant requires *no write entry at all*
  earlier in the segment (failed or not).

Waiters hold no explicit queue: a WAITING txn re-submits the same request
with the same priority next tick, which reproduces the priority-ordered
waiter list of the reference (waiters kept in ts order, row_lock.cpp:134-141).

The sort is packed to three int32 operands (two keys + one payload) to keep
the TPU bitonic sort cheap: key/kind share one word (config asserts row ids
fit 30 bits) and flags/index share another (entry index fits 23 bits).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from deneva_tpu.engine.state import Entries, BIG_TS, NULL_KEY
from deneva_tpu.ops import segment as seg

_IDX_BITS = 23
_IDX_MASK = (1 << _IDX_BITS) - 1
_DEAD_ROW = (1 << 30) - 1


def arbitrate(ent: Entries, policy: str, want_blocker: bool = False):
    """Resolve this tick's lock requests.

    Returns (grant, wait, abort): (B*R,) masks in original entry order,
    true only at request positions.

    ``want_blocker`` (Config.depgraph) appends a fourth (B*R,) int32
    array: the blocker identity of every failed request, encoded as
    blocker txn slot + 1 (0 = none).  A failed WRITE points at its
    immediate predecessor in the row's (held-first, ts) segment order —
    so a writer convoy reads back as a depth ladder 1..k, not k
    independent depth-1 waits — and a failed READ points at the nearest
    preceding write entry that actually blocks it under the policy.
    """
    n = ent.key.shape[0]
    assert n <= 1 << _IDX_BITS, n
    live = ent.held | ent.req
    row = jnp.where(live, ent.key, _DEAD_ROW)
    kind = jnp.where(ent.held, 0, 1)
    keykind = row * 2 + kind
    payload = (jnp.arange(n, dtype=jnp.int32)
               | (ent.is_write.astype(jnp.int32) << _IDX_BITS)
               | (ent.held.astype(jnp.int32) << (_IDX_BITS + 1))
               | (ent.req.astype(jnp.int32) << (_IDX_BITS + 2)))

    skk, sts, spay = seg.sort_pack((keykind, ent.ts, payload), num_keys=2,
                                   is_stable=False)
    s_iw = (spay >> _IDX_BITS) & 1 == 1
    s_held = (spay >> (_IDX_BITS + 1)) & 1 == 1
    s_req = (spay >> (_IDX_BITS + 2)) & 1 == 1
    s_idx = spay & _IDX_MASK
    srow = skk >> 1
    s_live = srow != _DEAD_ROW

    starts = seg.segment_starts(srow)
    pos = seg.pos_in_segment(starts)

    if policy == "CALVIN":
        # FIFO: any write earlier in the segment (granted or not) blocks.
        w_blocks = s_iw & s_live
        any_w_before = seg.seg_any_before(w_blocks, starts)
        s_grant = s_req & jnp.where(s_iw, pos == 0, ~any_w_before)
        s_wait = s_req & ~s_grant
        s_abort = jnp.zeros_like(s_grant)
    else:
        # A write only ever takes effect at segment position 0; a held X lock
        # is also necessarily at position 0 (exclusive => sole live entry
        # apart from this tick's requests).  So "conflicting lock earlier in
        # order" == "a write at pos 0 or a held write before me".
        w_blocks = s_iw & s_live & (s_held | (pos == 0))
        eff_w_before = seg.seg_any_before(w_blocks, starts)
        s_grant = s_req & jnp.where(s_iw, pos == 0, ~eff_w_before)
        s_fail = s_req & ~s_grant
        if policy == "NO_WAIT":
            s_wait = jnp.zeros_like(s_fail)
            s_abort = s_fail
        elif policy == "WAIT_DIE":
            granted_before = seg.seg_any_before(s_grant, starts)
            min_held_ts = seg.seg_min_where(sts, s_held, starts, BIG_TS)
            canwait = ~granted_before & (sts < min_held_ts)
            s_wait = s_fail & canwait
            s_abort = s_fail & ~canwait
        else:  # pragma: no cover
            raise ValueError(policy)

    packed = (s_grant.astype(jnp.int32) | (s_wait.astype(jnp.int32) << 1)
              | (s_abort.astype(jnp.int32) << 2))
    if not want_blocker:
        out = seg.unpermute(s_idx, packed)
        return out & 1 == 1, (out >> 1) & 1 == 1, (out >> 2) & 1 == 1

    # blocker attribution (Config.depgraph): the nearest earlier segment
    # lane responsible for this failure.  A failed WRITE needs pos == 0,
    # so ANY earlier live lane blocks it — its immediate predecessor
    # makes writer convoys read back as depth ladders; a failed READ is
    # blocked specifically by the nearest earlier blocking-write lane
    # (w_blocks above matches each policy's grant rule).  The exclusive
    # segmented prefix-max of the lane index finds both; the txn-slot
    # gather only runs on this opted-in path.
    lane = jnp.arange(n, dtype=jnp.int32)
    s_fail = s_req & ~s_grant
    prev_any = seg.seg_prefix_max(jnp.where(s_live, lane, -1), starts,
                                  identity=-1)
    prev_w = seg.seg_prefix_max(jnp.where(w_blocks, lane, -1), starts,
                                identity=-1)
    blane = jnp.where(s_iw, prev_any, prev_w)
    s_txn = ent.txn[s_idx]
    blk1 = jnp.where(s_fail & (blane >= 0),
                     s_txn[jnp.clip(blane, 0)] + 1, 0)
    out, blk = seg.unpermute_many(s_idx, packed, blk1)
    return (out & 1 == 1, (out >> 1) & 1 == 1, (out >> 2) & 1 == 1,
            blk)


# ---------------------------------------------------------------------------
# Dense per-row arbitration — the scatter/gather formulation
# ---------------------------------------------------------------------------
#
# The sorted-segment `arbitrate` above costs a bitonic sort of B*R entries
# every tick (O(n log^2 n) passes on the TPU).  When the row space is dense
# (it always is here — keys are catalog rows), the same decisions follow
# from six per-row aggregates computed with O(n) scatters into persistent
# row-indexed arrays, then read back with O(n) gathers:
#
#   held_cnt, held_w, min_held_ts   — over entries holding locks
#   min_req_ts, min_wreq_ts, min_rreq_ts — over this tick's requests
#
# Decision algebra (equivalent to the sorted version; proofs in terms of
# the segment order (row, held-first, ts)):
#   write grants  <=>  it is the segment head: nothing held on the row and
#                      its ts is the minimum request ts.
#   read grants (NO_WAIT/WAIT_DIE)  <=>  no held write, and not blocked by
#     the one write request that can take effect: that write sits at the
#     segment head, which requires an empty held set and the row's minimum
#     request being a write older than the read.
#   read grants (CALVIN FIFO)  <=>  no held write and EVERY write request
#     on the row is younger (waiting writes block readers behind them,
#     row_lock.cpp:78-81).
#   WAIT_DIE canwait (row_lock.cpp:91-151) = no granted request older than
#     me and ts < min held ts; "granted request older than me" reduces per
#     the same head analysis to a comparison of the row minima.
#
# The scratch arrays live in the CC db dict and are restored to their
# identity values at every touched row before the tick returns, so between
# ticks they are constant — no per-tick O(rows) clear, no rebase handling
# (BIG_TS identities are not timestamps).
#
# Tie safety: timestamps are unique across live transactions by
# construction (monotone counter draws; the sorted path's index tie-break
# only matters after the ~2^31-draw rebase clamp, see scheduler.py).

LOCK_TMP = ("lk_held",)

_SIGN = jnp.int32(-(2**31))   # ts - 2^31 marks a WRITE in the packed min


def init_lock_tmp(n_rows: int) -> dict:
    """Identity-valued per-row held-lock scratch for `arbitrate_window`.

    One packed int32 per row; the sign encodes "a write lock is held":
    min over held entries of {iw ? ts - 2^31 : ts} yields (a) whether the
    row is held at all (== BIG_TS if not), (b) whether a write holds it
    (value < 0), and (c) the min holder ts (a held write is exclusive, so
    if one exists it is the sole holder and its ts IS the min).
    """
    return {"lk_held": jnp.full(n_rows, BIG_TS, jnp.int32)}


def arbitrate_window(txn, active, policy: str, tmp: dict,
                     window: int, read_locks_held: bool = True):
    """Dense-row arbitration for the cursor-window request model.

    Held-lock state is aggregated by SCATTER over the (B, R) entry lanes
    into a per-row scratch, requests are extracted by masked reductions,
    and only the requests (B*W lanes, not B*R) are sorted; the single
    dynamic lookup is the held-scratch gather at the sorted request rows.

    Measured on TPU (PROFILE.md) this is ~15% SLOWER than the plain
    sorted-segment `arbitrate`: any gather indexed by row id into the
    (rows,)-sized scratch is latency-bound, monotone or not, and one such
    gather outweighs the saved sort width.  Kept (equivalence-tested, off
    by default) as the better kernel for hardware with cheap gathers.

    Decision algebra identical to `arbitrate`.
    Returns ((B,R) grant, wait, abort, tmp') with tmp' identity-restored.
    """
    B, R = txn.keys.shape
    W = min(window, R)
    ridx = jnp.arange(R, dtype=jnp.int32)[None, :]
    cur = txn.cursor[:, None]
    act = active[:, None]
    ts = txn.ts
    held = act & (ridx < cur)
    if not read_locks_held:
        held = held & txn.is_write

    # -- held aggregate: one scatter-min of the sign-packed priority --
    p_held = jnp.where(txn.is_write, ts[:, None] + _SIGN, ts[:, None])
    hrow = jnp.where(held, txn.keys, NULL_KEY)
    lk_held = tmp["lk_held"].at[hrow.reshape(-1)].min(
        p_held.reshape(-1), mode="drop")

    # -- request extraction: masked reductions, no gathers --
    rkey, riw, validq = [], [], []
    for j in range(W):
        m = (ridx == cur + j)
        v = active & (txn.cursor + j < txn.n_req)
        rkey.append(jnp.where(v, jnp.sum(jnp.where(m, txn.keys, 0), axis=1),
                              NULL_KEY))
        riw.append(jnp.any(m & txn.is_write, axis=1) & v)
        validq.append(v)
    rkey = jnp.stack(rkey, axis=1)       # (B, W)
    riw = jnp.stack(riw, axis=1)
    validq = jnp.stack(validq, axis=1)

    # -- sort ONLY the requests by (row, ts): B*W lanes, not B*R --
    n = B * W
    assert n <= 1 << _IDX_BITS, n
    rrow = jnp.where(validq, rkey, NULL_KEY).reshape(-1)
    tsw = jnp.broadcast_to(ts[:, None], (B, W)).reshape(-1)
    payload = (jnp.arange(n, dtype=jnp.int32)
               | (riw.reshape(-1).astype(jnp.int32) << _IDX_BITS))
    srow, sts, spay = seg.sort_pack((rrow, tsw, payload), num_keys=2,
                                    is_stable=False)
    s_iw = (spay >> _IDX_BITS) & 1 == 1
    s_idx = spay & _IDX_MASK
    s_live = srow != NULL_KEY

    starts = seg.segment_starts(srow)
    pos = seg.pos_in_segment(starts)
    si = seg.start_index(starts)
    head_iw = s_iw[si]                  # monotone gather: cheap
    head_ts = sts[si]

    # held lookup at SORTED row order — a monotone gather, the cheap kind
    h = lk_held[jnp.where(s_live, srow, 0)]
    no_held = h == BIG_TS
    hw = h < 0
    mh = jnp.where(hw, h - _SIGN, h)    # min held ts (write is exclusive)

    grant_w = no_held & (pos == 0)
    if policy == "CALVIN":
        # FIFO: any older write request (granted or not) blocks a read
        any_w_before = seg.seg_any_before(s_iw & s_live, starts)
        grant_r = ~hw & ~any_w_before
        s_grant = s_live & jnp.where(s_iw, grant_w, grant_r)
        s_wait = s_live & ~s_grant
        s_abort = jnp.zeros_like(s_grant)
    else:
        head_is_older_write = no_held & head_iw & (pos > 0)
        grant_r = ~hw & ~head_is_older_write
        s_grant = s_live & jnp.where(s_iw, grant_w, grant_r)
        s_fail = s_live & ~s_grant
        if policy == "NO_WAIT":
            s_wait = jnp.zeros_like(s_fail)
            s_abort = s_fail
        elif policy == "WAIT_DIE":
            # granted set on my row: nothing under a held write; all older
            # read requests unless the row is free with a write at its
            # head; exactly that head write otherwise (row_lock.cpp:91-151)
            mrr = seg.seg_min_where(sts, ~s_iw & s_live, starts, BIG_TS)
            head_write = no_held & head_iw
            granted_before = ~hw & jnp.where(head_write, head_ts < sts,
                                             mrr < sts)
            canwait = ~granted_before & (sts < mh)
            s_wait = s_fail & canwait
            s_abort = s_fail & ~canwait
        else:  # pragma: no cover
            raise ValueError(policy)

    packed = (s_grant.astype(jnp.int32) | (s_wait.astype(jnp.int32) << 1)
              | (s_abort.astype(jnp.int32) << 2))
    out = seg.unpermute(s_idx, packed)
    grantW = (out & 1 == 1).reshape(B, W)
    waitW = ((out >> 1) & 1 == 1).reshape(B, W)
    abortW = ((out >> 2) & 1 == 1).reshape(B, W)

    # -- map (B, W) window decisions back onto (B, R) masks: elementwise --
    def to_BR(mskW):
        out = jnp.zeros((B, R), dtype=bool)
        for j in range(W):
            out = out | (mskW[:, j:j + 1] & (ridx == cur + j))
        return out

    # -- identity-restore the held scratch at every touched row --
    # hrow has duplicate row ids whenever several S-lock holders share a
    # row, so the restore must be a commutative combine: .max(BIG_TS) is
    # order-independent and saturates to the identity (BIG_TS = int32
    # max), where a duplicate-index .set applies in unspecified order
    tmp = {**tmp,
           "lk_held": lk_held.at[hrow.reshape(-1)].max(BIG_TS, mode="drop")}
    return to_BR(grantW), to_BR(waitW), to_BR(abortW), tmp


# ---------------------------------------------------------------------------
# Sub-ticked arbitration — finer time quantization for parity
# ---------------------------------------------------------------------------

def ts_groups(ts, active, K: int):
    """Contiguous timestamp groups for sub-round arbitration: rank live
    txns by ts and split into K quantile groups (shared by the 2PL and
    TIMESTAMP sub-tick kernels)."""
    B = ts.shape[0]
    tsk = jnp.where(active, ts, BIG_TS)
    order = jnp.argsort(tsk)
    # order is an argsort permutation of arange(B): indices are distinct
    # by construction, so the inverse-permutation scatter is race-free
    rank = jnp.zeros(B, jnp.int32).at[order].set(
        jnp.arange(B, dtype=jnp.int32), unique_indices=True)
    n_act = jnp.maximum(jnp.sum(active.astype(jnp.int32)), 1)
    return jnp.minimum(rank * K // n_act, K - 1)


def arbitrate_subticked(txn, active, policy: str, K: int,
                        read_locks_held: bool = True,
                        pipelined: bool = False,
                        want_blocker: bool = False):
    """Arbitrate one tick's requests in K timestamp-ordered sub-rounds.

    The one-round tick decides all requests against the tick-START lock
    state: a txn aborted this tick still blocks its rows until next tick,
    and a granted lock only takes effect for later requests through the
    priority order.  A sequential interleaving instead sees every release
    and grant IMMEDIATELY (the within-batch ordering effect flagged in
    SURVEY.md §7).  Sub-ticking splits the batch into K contiguous ts
    groups: group k arbitrates against the lock state left by groups < k
    (grants added, aborted txns' locks removed).  K -> B converges to the
    sequential reference's schedule; PARITY.md quantifies divergence vs K.

    ``pipelined`` (Config.pipeline_exchange) software-pipelines the
    sub-rounds: every round's request plane is materialized BEFORE the
    serial grant chain, so round k+1's entry packing is free to run
    while round k's arbitration sort lands.  Sound because a group-k txn
    cannot be dead before round k — :func:`arbitrate` only sets abort
    bits at request positions (holders are never wounded), and a txn's
    sole request lane enters at exactly its own group's round — so the
    ``~dead`` term in the request mask is redundant and the plane is
    round-invariant.  The held mask (which DOES depend on earlier
    rounds' grants and deaths) stays in the serial chain; every value
    is bit-identical to the in-order loop.

    Requires acquire_window == 1 (one request per txn per tick, the
    faithful state machine).  Returns (grant, wait, abort) (B, R) masks.
    """
    B, R = txn.keys.shape
    ridx = jnp.arange(R, dtype=jnp.int32)[None, :]
    cur = txn.cursor[:, None]
    held_base = active[:, None] & (ridx < cur)
    if not read_locks_held:
        held_base = held_base & txn.is_write
    req_base = active[:, None] & (ridx == cur) & (cur < txn.n_req[:, None])

    # contiguous ts groups (ts unique among live txns)
    group = ts_groups(txn.ts, active, K)

    G = jnp.zeros((B, R), dtype=bool)
    W = jnp.zeros((B, R), dtype=bool)
    A = jnp.zeros((B, R), dtype=bool)
    BLK = jnp.zeros((B, R), dtype=jnp.int32)
    dead = jnp.zeros(B, dtype=bool)

    flat = lambda x: x.reshape(-1)
    tse = jnp.broadcast_to(txn.ts[:, None], (B, R))
    txe = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32)[:, None], (B, R))

    req_rounds = None
    if pipelined:
        # hoisted request planes: issued up front, outside the serial
        # G/dead carry, so the compiler may overlap them with any round
        req_rounds = [req_base & (active & (group == k))[:, None]
                      for k in range(K)]

    for k in range(K):
        held_m = (held_base | G) & ~dead[:, None]
        if pipelined:
            req_m = req_rounds[k]
        else:
            grp = active & (group == k) & ~dead
            req_m = req_base & grp[:, None]
        live = held_m | req_m
        ent = Entries(
            key=flat(jnp.where(live, txn.keys, NULL_KEY)),
            txn=flat(txe), ridx=flat(jnp.broadcast_to(ridx, (B, R))),
            ts=flat(tse), is_write=flat(txn.is_write),
            held=flat(held_m), req=flat(req_m))
        if want_blocker:
            g, w, a, blk = arbitrate(ent, policy, want_blocker=True)
            BLK = jnp.maximum(BLK, blk.reshape(B, R))
        else:
            g, w, a = arbitrate(ent, policy)
        g, w, a = g.reshape(B, R), w.reshape(B, R), a.reshape(B, R)
        G, W, A = G | g, W | w, A | a
        dead = dead | a.any(axis=1)
    if want_blocker:
        return G, W, A, BLK
    return G, W, A
