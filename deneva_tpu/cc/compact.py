"""Access-path live-prefix compaction glue shared by the CC plugins.

``ops/segment.py`` provides the width mechanics (compact_entries /
expand_entries); this module adds the CC-specific safety discipline for
the ACCESS kernels, where the entry view mixes lanes with very different
failure semantics:

- a REQUEST lane's owner can always be told to retry (abort, or wait for
  a never-aborting plugin), so request lanes may spill past the bucket;
- a HELD lane of a txn that still has requests this tick may also spill:
  forcing that txn to retry releases its locks, which makes their
  invisibility to this tick's arbitration consistent with the retry;
- a HELD lane of a txn with NO requests this tick (a finishing txn,
  holding its locks to commit) must NEVER be invisible — nothing can
  force that txn to retry, so a conflicting grant against its unseen
  lock would break mutual exclusion.

Compaction therefore ranks lanes (non-retryable held, retryable held,
requests), each class keeping its original relative order.  The
non-retryable class fits the bucket on every sane tick; if it ever does
not (``unsafe``), the whole tick's arbitration degrades to all-WAIT — a
one-tick stall is always conservative, the finishing txns commit and
release on the next commit phase, and the spill is counted in
``compact_overflow_cnt``, never silent.

The class reordering cannot perturb decisions relative to the padded
path: every downstream sort keys on (row, ts, ...) at minimum, per-txn
timestamps are unique among live txns, and workloads de-duplicate keys
within a txn — so no two lanes from different classes can tie, and
stable tie-breaking only ever compares lanes whose relative order
compaction preserved.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from deneva_tpu.cc import base as cc_base
from deneva_tpu.config import Config
from deneva_tpu.engine.state import Entries
from deneva_tpu.ops import segment as seg


class AccessCompaction(NamedTuple):
    """One access-path compaction: the geometry, the compacted entries the
    kernel should arbitrate, and the spill bookkeeping ``finish_access``
    folds back into the expanded decision masks."""

    view: seg.CompactView
    ent: Entries            # width-K entry view (identity when K == n)
    unsafe: jnp.ndarray     # () bool: non-retryable lanes spilled -> stall
    ovf_b: jnp.ndarray      # (B,) txns with retryable spilled lanes
    extras: tuple = ()      # caller payloads compacted with the same sort


def compact_access(cfg: Config, db: dict, ent: Entries, B: int, R: int,
                   request_all: bool = False, extras: tuple = ()):
    """Compact an access-phase entry view to K lanes (see module doc).

    Returns ``(db, AccessCompaction)``; db carries the occupancy counter
    bumps.  ``K >= n`` (compaction off / small geometry) yields an
    identity view with the original entries.  ``extras`` are additional
    (n,) per-lane arrays the caller needs at the compacted width (e.g.
    precomputed abort predicates); they ride the same sort and come back
    as ``.extras``.
    """
    n = ent.key.shape[0]
    K = cfg.compact_width(n, B, request_all=request_all)
    live = ent.held | ent.req
    if K >= n:
        view, _ = seg.compact_entries(live, n)
        db = cc_base.note_compaction(db, view)
        return db, AccessCompaction(
            view=view, ent=ent,
            unsafe=jnp.zeros((), dtype=bool),
            ovf_b=jnp.zeros(B, dtype=bool),
            extras=tuple(extras))

    # lane classes: held lanes of txns with no request this tick cannot be
    # forced to retry and must rank first (see module doc)
    has_req_b = jnp.any(ent.req.reshape(B, R), axis=1)
    has_req_e = jnp.broadcast_to(has_req_b[:, None], (B, R)).reshape(-1)
    c1 = ent.held & ~has_req_e
    c2 = ent.held & has_req_e
    idx = jnp.arange(n, dtype=jnp.int32)
    keyrank = jnp.where(c1, idx,
                        jnp.where(c2, n + idx,
                                  jnp.where(ent.req, 2 * n + idx,
                                            3 * n + idx)))
    i32 = jnp.int32
    conv = tuple(x.astype(i32) if x.dtype == bool else x for x in extras)
    # lint: disable-next=PAD-WIDTH-SORT this IS the compaction-building sort: it must see all n lanes to rank live ones into the prefix
    sorted_ = seg.sort_pack(
        (keyrank, ent.key, ent.txn, ent.ridx, ent.ts,
         ent.is_write.astype(i32), ent.held.astype(i32),
         ent.req.astype(i32)) + conv,
        num_keys=1, is_stable=False)
    skey = sorted_[0]
    cent = Entries(
        key=sorted_[1][:K], txn=sorted_[2][:K], ridx=sorted_[3][:K],
        ts=sorted_[4][:K], is_write=sorted_[5][:K] == 1,
        held=sorted_[6][:K] == 1, req=sorted_[7][:K] == 1)
    cex = tuple(s[:K] == 1 if x.dtype == bool else s[:K]
                for x, s in zip(extras, sorted_[8:]))

    n_live = jnp.sum(live.astype(i32))
    n_c1 = jnp.sum(c1.astype(i32))
    view = seg.CompactView(
        width=K, n=n, orig_sorted=skey % n, live=skey[:K] < 3 * n,
        n_live=n_live,
        overflow=jnp.maximum(n_live - K, jnp.zeros((), i32)))
    db = cc_base.note_compaction(db, view)

    # spilled lanes: live entries whose class-ordered rank is >= K
    excl = lambda m: jnp.cumsum(m.astype(i32)) - m.astype(i32)
    n_c2 = jnp.sum(c2.astype(i32))
    rank = jnp.where(c1, excl(c1),
                     jnp.where(c2, n_c1 + excl(c2),
                               n_c1 + n_c2 + excl(ent.req)))
    ovf_e = live & (rank >= K)
    return db, AccessCompaction(
        view=view, ent=cent,
        unsafe=n_c1 > K,
        ovf_b=jnp.any((ovf_e & (c2 | ent.req)).reshape(B, R), axis=1),
        extras=cex)


def finish_access(ac: AccessCompaction, req_e: jnp.ndarray,
                  grant: jnp.ndarray, wait: jnp.ndarray,
                  abort: jnp.ndarray, never_aborts: bool = False):
    """Expand width-K decision masks to full width and fold in the spill
    semantics: txns with retryable spilled lanes are forced to retry
    (wait when the plugin never aborts), and an ``unsafe`` tick degrades
    to all-WAIT.  Returns full-width (grant, wait, abort)."""
    n = req_e.shape[0]
    B = ac.ovf_b.shape[0]
    grant, wait, abort = seg.expand_entries(ac.view, grant, wait, abort)
    ovf_e = jnp.broadcast_to(ac.ovf_b[:, None], (B, n // B)).reshape(-1)
    retry = req_e & ovf_e
    grant = grant & ~ovf_e
    if never_aborts:
        wait = (wait & ~ovf_e) | retry
        abort = abort & ~ovf_e
    else:
        wait = wait & ~ovf_e
        abort = (abort & ~ovf_e) | retry
    # pathological spill of non-retryable held lanes: stall the tick
    grant = grant & ~ac.unsafe
    wait = jnp.where(ac.unsafe, req_e, wait)
    abort = abort & ~ac.unsafe
    return grant, wait, abort


def finish_reason(ac: AccessCompaction, req_e: jnp.ndarray,
                  reason, never_aborts: bool = False):
    """Expand a width-K reason plane (AccessDecision.reason) the same way
    ``finish_access`` expands its masks, restamping the spill semantics:
    forced-retry lanes carry ``compact_spill`` (the abort the fold just
    synthesized has nothing to do with the plugin's own rule).  A
    never-aborting plugin spills to WAIT, and an ``unsafe`` tick aborts
    nothing, so neither needs a restamp — the engine only reads reasons
    where ``abort`` holds.  None (observatory off) passes through."""
    # lint: disable-next=TRACED-BRANCH is-None STRUCTURE check: reason is None iff abort_attribution is off (static per config), never a traced-value branch
    if reason is None:
        return None
    n = req_e.shape[0]
    B = ac.ovf_b.shape[0]
    (reason,) = seg.expand_entries(ac.view, reason)
    if not never_aborts:
        ovf_e = jnp.broadcast_to(ac.ovf_b[:, None], (B, n // B)).reshape(-1)
        reason = jnp.where(req_e & ovf_e,
                           jnp.int32(cc_base.REASON["compact_spill"]),
                           reason)
    return reason


def finish_blocker(ac: AccessCompaction, blocker):
    """Expand a width-K blocker plane (AccessDecision.blocker, slot+1
    encoding) the same way ``finish_access`` expands its masks.  A
    spill-forced retry and an ``unsafe`` all-WAIT stall have no single
    blocker, so their lanes carry 0 (= none) — which is also what the
    zero-fill of ``expand_entries`` gives every spilled/dead lane, so
    only the unsafe stall needs an explicit mask.  None (Config.depgraph
    off) passes through."""
    # lint: disable-next=TRACED-BRANCH is-None STRUCTURE check: blocker is None iff depgraph is off (static per config), never a traced-value branch
    if blocker is None:
        return None
    (blocker,) = seg.expand_entries(ac.view, blocker)
    return jnp.where(ac.unsafe, 0, blocker)
