"""CC-algorithm kernel registry — the rebuild of the CC_ALG compile switch."""

from deneva_tpu.cc.base import AccessDecision, CCPlugin
from deneva_tpu.cc.no_wait import NoWait, WaitDie
from deneva_tpu.cc.timestamp import Timestamp
from deneva_tpu.cc.mvcc import Mvcc
from deneva_tpu.cc.occ import Occ
from deneva_tpu.cc.maat import Maat
from deneva_tpu.cc.calvin import Calvin

REGISTRY: dict[str, CCPlugin] = {}


def register(plugin: CCPlugin) -> CCPlugin:
    REGISTRY[plugin.name] = plugin
    return plugin


register(NoWait())
register(WaitDie())
register(Timestamp())
register(Mvcc())
register(Occ())
register(Maat())
register(Calvin())


def get(name: str) -> CCPlugin:
    if name not in REGISTRY:
        raise KeyError(f"CC algorithm {name!r} not registered "
                       f"(have: {sorted(REGISTRY)})")
    return REGISTRY[name]


__all__ = ["AccessDecision", "CCPlugin", "REGISTRY", "register", "get"]
