"""CALVIN: deterministic, lock-based, abort-free execution.

The reference's Calvin path (SURVEY.md §3.3) is three cooperating threads:

- the sequencer batches client txns into 5 ms epochs and assigns each a
  deterministic global id ``txn_id = node_id + node_cnt * seq``
  (system/sequencer.cpp:207; SEQ_BATCH_TIMER config.h:348);
- the lock thread walks the epoch's txns in id order and acquires ALL of a
  txn's locks up front via the FIFO, never-aborting Row_lock CALVIN mode
  (system/calvin_thread.cpp:40-100, row_lock.cpp:78-81,152-170;
  TxnManager::acquire_locks benchmarks/ycsb_txn.cpp:49-88);
- workers run the 6-phase machine (RW_ANALYSIS .. DONE) once all locks are
  granted, forwarding local reads to the active nodes via RFWD messages
  (benchmarks/ycsb_txn.cpp:255-353, system/txn.cpp:958-990), then release
  locks and CALVIN_ACK the sequencer (worker_thread.cpp:127-137).

TPU reformulation:

- the epoch timer becomes per-tick admission of up to ``cfg.epoch_size``
  fresh txns (``epoch_admission``): one scheduler tick = one sequencer batch
  release, and the admission timestamp is the deterministic sequence number
  (node-interleaved ``seq * node_cnt + node_id`` in the sharded engine —
  exactly the reference's id formula);
- lock acquisition requests a txn's ENTIRE access set every tick
  (``request_all`` — the acquire_locks loop), arbitrated by the stateless
  FIFO grant of cc/twopl.py: a write grants only at the head of its row's
  live-entry order, a read only if no write precedes it, and nothing ever
  aborts (``never_aborts``);
- a txn executes (commits + applies writes) the tick after its last lock
  grants, so the commit schedule is the deterministic frontier-by-frontier
  traversal of the batch's conflict DAG — the property the sequencer +
  sched_queue machinery exists to enforce;
- in the sharded engine the per-tick entry exchange to row owners is the
  forwarding fabric (RFWD): owners arbitrate their rows' FIFO order locally
  and grant decisions flow home through the inverse all_to_all
  (deneva_tpu/parallel/sharded.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from deneva_tpu.cc.base import AccessDecision, CCPlugin
from deneva_tpu.cc import compact as ccompact
from deneva_tpu.cc import twopl
from deneva_tpu.config import Config
from deneva_tpu.engine.state import TxnState, make_entries


class Calvin(CCPlugin):
    name = "CALVIN"
    epoch_admission = True   # sequencer batch release per tick
    request_all = True       # acquire_locks() requests every access up front
    never_aborts = True      # row_lock.cpp:78-81: Calvin mode never aborts

    def access(self, cfg: Config, db: dict, txn: TxnState, active):
        B, R = txn.keys.shape
        # Calvin ignores isolation-level release-early hooks: locks are held
        # from grant to wrapup regardless (system/txn.cpp:778-788).
        # request_all makes every access a request, so the sorted-segment
        # join (not the cursor-window fast path) is the natural kernel.
        # request_all also means the auto compaction bucket never applies
        # (every active lane is live); an explicit compact_lanes still
        # compacts, with spilled txns WAITING out the tick (never_aborts).
        ent = make_entries(txn, active, read_locks_held=True, window=R)
        db, ac = ccompact.compact_access(cfg, db, ent, B, R,
                                         request_all=True)
        if cfg.depgraph:
            # blocker = the epoch predecessor in the row's FIFO order
            # (the txn whose unfinished frontier position delays mine)
            g, w, a, blk = twopl.arbitrate(ac.ent, "CALVIN",
                                           want_blocker=True)
            blk = ccompact.finish_blocker(ac, blk).reshape(B, R)
        else:
            g, w, a = twopl.arbitrate(ac.ent, "CALVIN")
            blk = None
        g, w, a = ccompact.finish_access(ac, ent.req, g, w, a,
                                         never_aborts=True)
        return AccessDecision(grant=g.reshape(B, R), wait=w.reshape(B, R),
                              abort=a.reshape(B, R), blocker=blk), db
