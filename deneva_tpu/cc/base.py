"""CC-algorithm plugin boundary.

The reference selects its algorithm at compile time (``#define CC_ALG``,
config.h:101) and splices per-algorithm code into row_t::get_row and the
worker loop with ``#if`` blocks.  Here the boundary is explicit: each
algorithm is a plugin of jit-traceable batch kernels, registered in
``deneva_tpu.cc.REGISTRY``.

A plugin sees the whole scheduler tick at once:

- ``access``   — decide grant/wait/abort for every active txn's *current*
  access (the batched analog of row_t::get_row, storage/row.cpp:197-310).
- ``validate`` — commit-time validation for every finishing txn (the analog
  of TxnManager::validate: OCC central_validate, MaaT validate; trivial for
  2PL, concurrency_control/occ.cpp:116-239, maat.cpp:29-174).
- ``on_commit`` / ``on_abort`` — CC metadata updates at txn end (the analog
  of row_t::return_row write-back/rollback, storage/row.cpp:351-420).
- ``on_start`` — per-txn CC state init at (re)admission (the analog of
  process_rtxn's per-CC_ALG blocks, worker_thread.cpp:492-508).

All hooks are pure: (cfg, db, txn, mask) -> updated arrays.  ``db`` is a flat
dict of device arrays holding both per-row CC state (wts/rts, version rings)
and per-txn-slot CC state (OCC read snapshots, MaaT bounds).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from deneva_tpu.config import Config
from deneva_tpu.engine.state import TxnState


class HookSpec(NamedTuple):
    """Machine-readable kernel-hook signature, consumed by the static
    analyzer (deneva_tpu/lint/jaxpr_engine.py).

    ``args``: symbolic names of the hook's arguments after the fixed
    (cfg, db) prefix; the verifier materializes each as an abstract value
    (see lint/contract.py ARG_BUILDERS).  ``returns``: the output
    protocol — ``"db"`` the updated db dict (same pytree structure,
    shapes and dtypes as the input db), ``"decision"`` an AccessDecision
    of (B, R) bool masks, ``"votes"`` a (B,) bool mask.  A single-element
    ``returns`` means the hook returns that value directly; otherwise a
    tuple in this order.
    """

    args: tuple
    returns: tuple


#: The plugin-boundary contract: every registered plugin's hooks must
#: abstract-eval under these signatures with a structure-stable db.
#: Enforced by `python -m deneva_tpu.lint` (engine 2) and scripts/check.sh.
#: Entry-lane arguments (keys_e/ts_e/mask_e) are width-polymorphic: the
#: verifier traces them at the COMPACTED width Config.compact_width(B*R)
#: — callers may hand these hooks a live-prefix view (ops/segment.py) or
#: the padded B*R lanes, so a hook must never assume the padded geometry.
KERNEL_CONTRACT: dict = {
    "on_start": HookSpec(args=("txn", "mask_b"), returns=("db",)),
    "access": HookSpec(args=("txn", "mask_b"), returns=("decision", "db")),
    "validate": HookSpec(args=("txn", "mask_b", "tick"),
                         returns=("votes", "db")),
    "on_commit": HookSpec(args=("txn", "mask_b", "ts_b", "tick"),
                          returns=("db",)),
    "on_abort": HookSpec(args=("txn", "mask_b"), returns=("db",)),
    "on_finalize_entries": HookSpec(args=("keys_e", "ts_e", "mask_e"),
                                    returns=("db",)),
    "on_prepared_entries": HookSpec(args=("keys_e", "ts_e", "mask_e",
                                          "tick"), returns=("db",)),
    "on_ts_rebase": HookSpec(args=("tick",), returns=("db",)),
}

#: Whole-program tick obligations, the engine-3 companion to the per-hook
#: KERNEL_CONTRACT above: the lint tick certifier (deneva_tpu/lint/
#: certify.py, LINT.md engine 3) traces make_tick/make_sharded_tick over
#: every registered plugin x workload x opt-in flag (config.py
#: optin_flags) at this geometry and proves OFFPATH-IMPURE /
#: CARRY-DRIFT / DONATION-DECLINED / SCATTER-RACE-JAXPR / DTYPE-WIDEN.
#: ``wide_dtypes`` names the convert_element_type targets the int32
#: end-to-end design forbids (the 2**31 ts-rebase boundary, packed sort
#: keys); ``racy_scatters`` the order-dependent scatter primitives that
#: must declare unique_indices.
TICK_CERTIFY: dict = {
    "geometry": {"batch_size": 8, "req_per_query": 4,
                 "synth_table_size": 64, "query_pool_size": 64,
                 "node_cnt": 4},
    "wide_dtypes": ("int64", "uint64", "float64"),
    "racy_scatters": ("scatter", "scatter-apply"),
}


class CommSpec(NamedTuple):
    """One declared cross-node collective of the distributed data plane,
    consumed by the sharded collective certifier (lint/shard_certify.py,
    LINT.md engine 4).

    The certifier lowers the sharded tick through the real SPMD
    partitioner and matches every collective op of the post-partitioning
    StableHLO against these records.  A spec is keyed by
    ``(op, site)``: ``op`` is the StableHLO collective kind
    (``all_to_all`` / ``all_reduce`` / ``all_gather`` /
    ``collective_permute``) and ``site`` is ``(path suffix, function
    names)`` — a collective matches when its callsite chain contains a
    frame inside ``site[0]`` whose function name is in ``site[1]``.
    Matching by function (not line) survives line drift; op kind
    disambiguates multiple collectives inside one closure.

    ``role`` classifies the operands by provenance and fixes the legal
    reduction set (COMM_ROLES): ``data`` moves data-plane entry tensors
    (value movement only — an all-reduce over a data role is never
    declarable), ``counter`` crosses commutative int32 counter planes
    (add only), ``clock`` takes a global extremum of a monotone scalar
    (max), ``log`` ships replication log records point-to-point.
    ``when`` records the static config predicate that compiles the
    collective in.
    """

    name: str                       # stable id, e.g. "exchange.ship"
    op: str                         # StableHLO collective kind
    site: tuple                     # (path suffix, (func, ...))
    role: str                       # COMM_ROLES key
    when: str                       # static gate, for docs/findings
    note: str = ""


#: The communication-plane contract policy, the engine-4 companion to
#: TICK_CERTIFY: the axis every collective must span, the legal
#: reduction combiners per operand role, and the functions whose values
#: the design asserts REPLICATED across nodes (round plans and config
#: scalars are computed identically on every shard — the SPMD
#: partitioner deciding one needs a cross-partition reduction is exactly
#: the PR 12 corruption class, rule REPLICATION-DRIFT).  The site list
#: itself lives next to the code that issues the collectives:
#: parallel/routing.py ROUTING_COMM and parallel/sharded.py SHARDED_COMM
#: (cc must not import parallel — parallel imports cc; sharded.py
#: asserts its axis name equals COMM_CONTRACT["axis"] at import).
COMM_CONTRACT: dict = {
    "axis": "node",
    "collectives": ("all_reduce", "all_gather", "all_to_all",
                    "collective_permute"),
    "replicated": (("parallel/routing.py", "round_plan"),),
}

#: operand role -> all-reduce combiners the role may legally cross with
#: (empty: the role must never be reduced across the mesh at all)
COMM_ROLES: dict = {
    "data": (),
    "counter": ("add",),
    "clock": ("max",),
    "log": (),
}


# --- abort-reason taxonomy (the observatory's machine-readable registry) ---
#: Every abort event the engine records is tagged with exactly one of
#: these reasons; the per-reason counters partition the aggregates so
#: sum(abort_<reason>_cnt) == total_txn_abort_cnt + vabort_cnt +
#: user_abort_cnt holds exactly (a validation abort counts through both
#: the total and the vabort site, mirroring how the aggregates overlap).
#: Order is the wire format: codes are index+1 (0 = "no reason
#: recorded") and the sharded engine ships a code in decision bits 4..7,
#: so the registry must stay under 16 entries and is append-only.
ABORT_REASONS = (
    "nowait_conflict",      # NO_WAIT: requested row held incompatibly
    "waitdie_wound",        # WAIT_DIE: younger requester dies
    "ts_too_old_read",      # TIMESTAMP: read under a newer committed write
    "ts_too_old_write",     # TIMESTAMP: write under a newer read/write ts
    "mvcc_version_miss",    # MVCC: version evicted / pending prewrite lost
    "occ_validation",       # OCC: read set intersects a committed write set
    "maat_range_collapse",  # MAAT: [lower, upper) squeezed empty
    "user_abort",           # workload logic rollback (TPC-C rbk)
    "compact_spill",        # live-entry compaction bucket overflow retry
    "backoff_reabort",      # re-abort on the first tick back from backoff
    "route_overflow",       # sharded: per-(src,dst) route capacity abort
    "other",                # unattributed (stays zero unless a plugin
                            # emits an abort without tagging it)
)
#: reason name -> nonzero wire code
REASON = {name: i + 1 for i, name in enumerate(ABORT_REASONS)}
REASON_NONE = 0
assert len(ABORT_REASONS) < 16, "reason codes must fit 4 decision bits"


def static_reason(cfg, name: str, shape) -> "jnp.ndarray | None":
    """Constant reason-lane array for plugins whose access aborts all
    carry one code (None when the observatory is off — the engine then
    classifies any abort as ``other``)."""
    if not cfg.abort_attribution:
        return None
    return jnp.full(shape, REASON[name], dtype=jnp.int32)


def compaction_counters(cfg) -> dict:
    """The two db scalars a plugin carries when the config opts into a
    live-prefix compaction bucket (ops/segment.py): ``live_entry_cnt``
    accumulates the live entries offered to compacted kernels per tick
    (float32 — int32 would wrap within minutes at headline widths) and
    ``compact_overflow_cnt`` the live entries that ranked beyond the
    static bucket K and were forced to retry.  Both auto-surface in
    ``[summary]`` via the db ``_cnt`` convention.  Without the opt-in
    (``compact_lanes`` / ``compact_auto``) the view is the identity
    everywhere and the keys are ABSENT — summaries stay comparable with
    engines that never build an entry view at all (dense lock state),
    and the db structure is still stable for any given config."""
    if (not cfg.entry_compaction
            or (cfg.compact_lanes is None and not cfg.compact_auto)):
        return {}
    return {"live_entry_cnt": jnp.zeros((), jnp.float32),
            "compact_overflow_cnt": jnp.zeros((), jnp.int32)}


def note_compaction(db: dict, view) -> dict:
    """Fold one compact_entries view into the occupancy counters (no-op
    when the config never opted into a bucket — see above)."""
    if "live_entry_cnt" not in db:
        return db
    return {**db,
            "live_entry_cnt": db["live_entry_cnt"]
            + view.n_live.astype(jnp.float32),
            "compact_overflow_cnt": db["compact_overflow_cnt"]
            + view.overflow}


class AccessDecision(NamedTuple):
    """Per-access outcome for this tick's requests; masks are (B, R) and
    mutually exclusive, true only at requested access positions (the window
    [cursor, cursor+acquire_window)).  The engine advances each txn's cursor
    over its granted prefix and applies the wait/abort decision found at the
    first non-granted requested access.

    ``reason`` — optional abort attribution (same shape, int32 REASON
    codes, meaningful where ``abort``): None whenever the config leaves
    ``abort_attribution`` off, so the default decision pytree keeps its
    3-leaf contract shape (None contributes no leaf).

    ``blocker`` — optional blocker identity for the dependency
    observatory (same shape, int32 BLOCKER SLOT + 1, 0 = no identified
    blocker, meaningful where ``wait`` or ``abort``): the txn slot whose
    held lock / pending write / validated range caused this decision.
    The +1 wire encoding survives the zero-fill of compaction spill
    lanes and expand_entries (a spilled lane's synthesized retry has no
    single blocker — 0 is the honest value).  None whenever
    ``Config.depgraph`` is off, keeping the certified off-path pytree
    byte-identical.  Presence is static per (plugin, cfg), like
    ``reason``."""

    grant: jnp.ndarray
    wait: jnp.ndarray
    abort: jnp.ndarray
    reason: jnp.ndarray | None = None
    blocker: jnp.ndarray | None = None


class CCPlugin:
    name: str = "?"
    #: reference worker_thread.cpp:492-495 — TIMESTAMP/MVCC (and OCC's
    #: start_ts) re-draw a timestamp on every restart; WAIT_DIE keeps its
    #: first timestamp forever (assigned only in the CL_QRY branch).
    new_ts_on_restart: bool = False
    #: Calvin: admission is gated to cfg.epoch_size fresh txns per tick
    #: (the SEQ_BATCH_TIMER batch release, system/sequencer.cpp:283-326).
    epoch_admission: bool = False
    #: Calvin: a txn requests its whole access set every tick
    #: (TxnManager::acquire_locks, ycsb_txn.cpp:49-88) instead of the
    #: cursor window.
    request_all: bool = False
    #: Calvin: no abort path exists (row_lock.cpp:78-81); the sharded
    #: engine defers instead of aborting on routing overflow.
    never_aborts: bool = False
    #: strict-2PL family: granted write accesses are exclusive row locks,
    #: so the debug invariant kernel may assert the lock matrix
    #: (engine/debug.py, row_lock.cpp:309-314).
    lock_based: bool = False
    #: adaptive hot-key escalation gate (deneva_tpu/ctrl/ policy b): True
    #: iff "this txn makes no request this tick" is always safe and the
    #: key it was about to touch is where the conflict would happen.
    #: Holds for the arrival-order plugins (2PL family, TIMESTAMP), whose
    #: cursor access IS the conflict point; False for the validation
    #: family (OCC/MAAT) — reads never block there and serializing them
    #: at the access would add latency without removing any validation
    #: conflict — and for Calvin's epoch-batched lock acquisition.
    esc_gate_ok: bool = False

    # --- abort attribution (ABORT_REASONS registry above) ---
    #: registered reason names this plugin's ACCESS decisions can carry
    #: (() for plugins that never abort at access: OCC/MAAT/CALVIN)
    access_abort_reasons: tuple[str, ...] = ()
    #: registered reason tagged on this plugin's validation (vote-no)
    #: aborts; None for plugins whose validate never rejects
    vabort_reason: str | None = None

    def emitted_reasons(self, cfg: Config) -> frozenset:
        """Every registered reason this plugin can emit under ``cfg`` —
        the taxonomy-exhaustiveness contract tests assert against
        (engine-level codes ride along: user aborts, backoff re-aborts,
        compaction spill, sharded route overflow)."""
        out = {"user_abort"}
        if self.access_abort_reasons:
            out |= set(self.access_abort_reasons)
            out.add("backoff_reabort")
        if self.vabort_reason:
            out.add(self.vabort_reason)
        if cfg.entry_compaction and not self.never_aborts \
                and (cfg.compact_lanes is not None or cfg.compact_auto):
            out.add("compact_spill")
        if cfg.node_cnt > 1 and not self.never_aborts:
            out.add("route_overflow")
        for name in out:
            assert name in REASON, name
        return frozenset(out)

    # --- multi-shard support (deneva_tpu/parallel/sharded.py) ---
    #: db keys holding per-TXN-slot (B,) arrays that must travel with each
    #: routed access entry to the owner shard (the CC metadata the reference
    #: ships inside QueryMessage/AckMessage, message.h:341-363,165-183),
    #: and be merged back at home with the given op after the exchange.
    txn_db_fields: tuple[str, ...] = ()
    txn_db_merge: dict = {}            # field -> "max" | "min"
    #: db key whose (B,) value is the txn's commit timestamp shipped with
    #: the commit exchange (MaaT's find_bound lower); None -> txn.ts
    commit_ts_field: str | None = None
    #: MaaT: the sharded engine ships each entry's ACCESS tick
    #: (start_tick + ridx // window) in the start_tick field so the owner's
    #: directional squeeze sees true per-row access order (single-access
    #: virtual txns have ridx 0).
    ship_access_tick: bool = False
    #: remote-grant stickiness (Config.remote_cache,
    #: parallel/sharded.py): True for plugins whose access decision for a
    #: given (row, txn ts) cannot change while the owner's row state is
    #: unchanged — MAAT's forced grant qualifies; lock-based waits do not
    #: (a wait can resolve without any row-state write the epoch counter
    #: would see).  The engine then caches remote grants per txn slot and
    #: suppresses re-ships while the owner's epoch counter is unmoved.
    remote_cache_ok: bool = False
    #: db keys whose per-entry row contribution ``remote_cache_probe``
    #: returns and the engine caches / replays on a hit (max-merged into
    #: the home txn's planes with neutral 0, like txn_db_merge "max").
    remote_cache_fields: tuple[str, ...] = ()
    #: net_delay mode: validation-aborted txns ship their entries through
    #: the commit exchange with commit=0 so owners can clear prepare-phase
    #: reservations (the RFIN(abort) release of a prepared participant,
    #: worker_thread.cpp:302-343).  OCC sets this (its prepare marks).
    release_on_vabort: bool = False
    #: MaaT: the commit exchange (RFIN) applies the commit-time forward
    #: validation at each owner — pushes onto row members the committer
    #: never saw (row_maat.cpp:208-307) happen only for txns that COMMIT
    #: globally, exactly like the reference; a validator that voted yes
    #: locally but lost 2PC must not land them.  The sharded engine then
    #: runs `commit_forward_entries` at exchange B over the A-phase live
    #: view and ships the pushed bounds home on a third exchange leg.
    commit_forward_push: bool = False
    #: (lower_field, upper_field) db keys the commit-time pushes merge into
    forward_push_fields: tuple[str, str] = ()

    def commit_forward_entries(self, cfg: Config, c, l):
        """Owner-side commit-time pushes: c/l are dicts of committed-entry
        and live-entry lanes (see parallel/sharded.py call site).  Returns
        (lower_push, upper_push) per live lane."""
        raise NotImplementedError

    def home_commit_check(self, cfg: Config, db: dict, txn: TxnState,
                          commit_try: jnp.ndarray) -> jnp.ndarray:
        """Final home-side check after per-owner votes merge (the
        coordinator's re-validation when all RACK_PREPs are in,
        worker_thread.cpp:302-343).  Owners vote on local views; constraints
        merged from different owners can still be jointly unsatisfiable."""
        return commit_try

    def init_db(self, cfg: Config, n_rows: int, B: int, R: int) -> dict:
        return compaction_counters(cfg)

    def on_start(self, cfg: Config, db: dict, txn: TxnState,
                 started: jnp.ndarray) -> dict:
        return db

    def access(self, cfg: Config, db: dict, txn: TxnState,
               active: jnp.ndarray) -> tuple[AccessDecision, dict]:
        raise NotImplementedError

    def validate(self, cfg: Config, db: dict, txn: TxnState,
                 finishing: jnp.ndarray, tick: jnp.ndarray
                 ) -> tuple[jnp.ndarray, dict]:
        return finishing, db

    def on_commit(self, cfg: Config, db: dict, txn: TxnState,
                  committed: jnp.ndarray, commit_ts: jnp.ndarray,
                  tick: jnp.ndarray) -> dict:
        return db

    def on_abort(self, cfg: Config, db: dict, txn: TxnState,
                 aborted: jnp.ndarray) -> dict:
        return db

    def remote_cache_probe(self, cfg: Config, db: dict, keys: jnp.ndarray,
                           iw: jnp.ndarray, live: jnp.ndarray) -> dict:
        """Owner-side hook (Config.remote_cache): the PURE per-entry row
        contribution for each ``remote_cache_fields`` key — what this
        row's CURRENT state adds to the accessing txn's planes, NOT the
        owner's merged txn view (which would leak a previous attempt's
        accumulated state into a replay).  Non-live lanes return the
        merge-neutral 0."""
        raise NotImplementedError

    def on_finalize_entries(self, cfg: Config, db: dict, keys: jnp.ndarray,
                            cts: jnp.ndarray, live: jnp.ndarray) -> dict:
        """Owner-side hook on every entry arriving through the commit
        exchange (commit AND vabort-release), after on_commit: clear any
        prepare-phase per-row reservations stamped with this txn's cts
        (net_delay mode; no-op by default)."""
        return db

    def on_prepared_entries(self, cfg: Config, db: dict, keys: jnp.ndarray,
                            ts: jnp.ndarray, prepared: jnp.ndarray,
                            tick) -> dict:
        """Owner-side hook on entries flagged prepared (yes-voted, commit
        in transit or RFIN-deferred): extend the prepare reservations'
        expiry so a deferral of any length cannot outlive its marks
        (net_delay mode; no-op by default)."""
        return db

    def on_ts_rebase(self, cfg: Config, db: dict, shift: jnp.ndarray) -> dict:
        """Shift any timestamp-valued db arrays down by `shift` (the engine
        periodically rebases int32 timestamps to dodge wraparound)."""
        return db
