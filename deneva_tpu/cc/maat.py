"""MaaT dynamic timestamp-range validation (CC_ALG=MAAT) — rebuild of
Maat + TimeTable + Row_maat (concurrency_control/maat.cpp:29-190,
row_maat.cpp:99-314).

State mapping
-------------
reference                                   this build
TimeTable [lower,upper) hashed buckets  ->  maat_lower/maat_upper (B,) slots
row timestamp_last_read/_last_write     ->  maat_lr/maat_lw (rows,) dense
row uncommitted_reads/writes sets       ->  the granted live access entries
txn greatest_read/write_timestamp       ->  maat_gr/maat_gw (B,) snapshots
                                            accumulated at access-grant time

Accesses never block or abort (soft locks only, row_maat.cpp:99-164): the
work phase grants everything, snapshotting greatest lr/lw seen.  All range
arithmetic happens at validation/commit, one batched pass per tick:

- case 1/3 (maat.cpp:46-48,68-70): lower > snapshot gw; for writers also
  lower > snapshot gr.  Using access-time snapshots (not commit-time values)
  matters: a writer that committed AFTER my access must push my upper DOWN
  (I read the old value), not my lower up.
- cases 2/4/5 against VALIDATED/COMMITTED neighbors (maat.cpp:49-110):
  committed neighbors already pushed my bounds at their commit (forward
  validation below); same-tick finishers are serialized by ts and act
  VALIDATED toward later finishers via per-row prefix reductions over their
  pre-tick bounds.
- neighbor squeeze at successful validation + commit-time forward
  validation (maat.cpp:121-157, row_maat.cpp:208-307) are consolidated into
  one pass — in a synchronous tick the live set at validation and at commit
  is identical: for each committing txn T, live readers of rows T wrote get
  upper <= T.lower-1, and live writers of rows T read or wrote get
  lower >= T.upper+1.
- commit_ts = final lower (find_bound, maat.cpp:176-190); rows written get
  lw = max(lw, commit_ts), rows read get lr = max(lr, commit_ts).

Known divergences (documented, parity measured by abort rates): snapshot
*sets* are not tracked per txn — the live join at validation approximates
"was in the row's uncommitted set at my access time"; the reference's
commit-time push of unknown-writer uppers (row_maat.cpp:222-233), which
orders writers it never observed BEFORE itself, is dropped in favor of the
validation-side after-squeeze (both directions would conflict).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deneva_tpu.cc.base import AccessDecision, CCPlugin
from deneva_tpu.config import Config
from deneva_tpu.engine.state import (BIG_TS, NULL_KEY, STATUS_RUNNING,
                                     STATUS_WAITING, TxnState, make_entries,
                                     request_window)
from deneva_tpu.ops import segment as seg


class Maat(CCPlugin):
    name = "MAAT"
    new_ts_on_restart = True
    # bounds/snapshots ride along with routed entries (the lower/upper the
    # reference carries in Ack/Query messages, message.h:165-183) and merge
    # back at home: ranges only ever tighten
    txn_db_fields = ("maat_lower", "maat_upper", "maat_gw", "maat_gr")
    txn_db_merge = {"maat_lower": "max", "maat_upper": "min",
                    "maat_gw": "max", "maat_gr": "max"}
    commit_ts_field = "maat_lower"
    ship_access_tick = True

    def init_db(self, cfg: Config, n_rows: int, B: int, R: int) -> dict:
        db = {
            "maat_lr": jnp.zeros(n_rows, jnp.int32),
            "maat_lw": jnp.zeros(n_rows, jnp.int32),
            "maat_lower": jnp.zeros(B, jnp.int32),
            "maat_upper": jnp.full(B, BIG_TS, jnp.int32),
            "maat_gw": jnp.zeros(B, jnp.int32),
            "maat_gr": jnp.zeros(B, jnp.int32),
        }
        # NOTE a pending-ring deferral of the commit-time lr/lw scatters
        # (the wr_ring pattern) was built and measured SLOWER here: the
        # read-side join over a >=2*B*R-capacity ring costs ~1.4 ms and
        # the flush cond copies both 64 MB carries (~1.9 ms) vs the
        # ~2.4 ms the direct scatters cost (PROFILE.md round 4).

        # validation case counters (the maat_case1-6 families of
        # maat.cpp:46-111 / statistics/stats.h), warmup-gated like
        # INC_STATS; db scalars ending in _cnt surface into [summary]
        for k in ("maat_case1_cnt", "maat_case2_cnt", "maat_case3_cnt",
                  "maat_case4_cnt", "maat_case6_cnt"):
            db[k] = jnp.zeros((), jnp.int32)
        return db

    def on_start(self, cfg: Config, db: dict, txn: TxnState, started):
        # time_table.init (worker_thread.cpp:504-508): [0, MAX), fresh snaps
        return {**db,
                "maat_lower": jnp.where(started, 0, db["maat_lower"]),
                "maat_upper": jnp.where(started, BIG_TS, db["maat_upper"]),
                "maat_gw": jnp.where(started, 0, db["maat_gw"]),
                "maat_gr": jnp.where(started, 0, db["maat_gr"])}

    def on_ts_rebase(self, cfg: Config, db: dict, shift) -> dict:
        # every MaaT db array is timestamp-valued; shift them with the
        # engine's periodic rebase (0 stays "never", BIG_TS stays "open")
        pos = lambda a: jnp.where(a > 0, jnp.maximum(a - shift, 1), 0)
        out = {**db,
               "maat_lr": pos(db["maat_lr"]),
               "maat_lw": pos(db["maat_lw"]),
               "maat_gw": pos(db["maat_gw"]),
               "maat_gr": pos(db["maat_gr"]),
               "maat_lower": jnp.maximum(db["maat_lower"] - shift, 0),
               "maat_upper": jnp.where(db["maat_upper"] >= BIG_TS, BIG_TS,
                                       jnp.maximum(db["maat_upper"] - shift,
                                                   1))}
        return out

    def access(self, cfg: Config, db: dict, txn: TxnState, active):
        B, R = txn.keys.shape
        ent = make_entries(txn, active, window=cfg.acquire_window)
        req = ent.req.reshape(B, R)
        n_rows = db["maat_lr"].shape[0]

        # snapshot greatest last-write/last-read over this tick's granted
        # accesses (row_maat.cpp:131-136,183-189); everything is granted.
        # Row state is gathered at the REQUEST lanes only (B*W, not B*R).
        rkey, riw, valid = request_window(txn, active, cfg.acquire_window)
        kw = jnp.clip(rkey, 0, n_rows - 1).reshape(-1)
        shape = rkey.shape
        lw_k = jnp.where(valid, db["maat_lw"][kw].reshape(shape), 0)
        lr_k = jnp.where(valid & riw, db["maat_lr"][kw].reshape(shape), 0)
        gw = jnp.maximum(db["maat_gw"], lw_k.max(axis=1))
        gr = jnp.maximum(db["maat_gr"], lr_k.max(axis=1))

        z = jnp.zeros((B, R), dtype=bool)
        return (AccessDecision(grant=req, wait=z, abort=z),
                {**db, "maat_gw": gw, "maat_gr": gr})

    def validate(self, cfg: Config, db: dict, txn: TxnState, finishing, tick):
        B, R = txn.keys.shape
        n = B * R

        # entry view: all granted accesses of live txns (the soft-lock sets)
        live_txn = ((txn.status == STATUS_RUNNING)
                    | (txn.status == STATUS_WAITING))
        ridx = jnp.arange(R, dtype=jnp.int32)[None, :]
        granted = (ridx < txn.cursor[:, None]) & (ridx < txn.n_req[:, None])
        ent_live = (live_txn[:, None] & granted).reshape(-1)
        fin_e = (finishing[:, None] & granted).reshape(-1)

        key = jnp.where(ent_live, txn.keys.reshape(-1), NULL_KEY)
        ts = jnp.broadcast_to(txn.ts[:, None], (B, R)).reshape(-1)
        iw = txn.is_write.reshape(-1)
        tx = jnp.broadcast_to(
            jnp.arange(B, dtype=jnp.int32)[:, None], (B, R)).reshape(-1)

        orig = jnp.arange(n, dtype=jnp.int32)
        (skey, sts), (s_iw, s_fin, s_tx, s_orig) = seg.sort_by(
            (key, ts), (iw, fin_e, tx, orig))
        starts = seg.segment_starts(skey)

        # saturating +-1 (the reference pins at 0 / UINT64_MAX,
        # maat.cpp:57-62,81-86; int32 wraparound would erase the push)
        up1 = lambda v: jnp.minimum(v, BIG_TS - 1) + 1
        dn1 = lambda v: jnp.maximum(v, 1) - 1

        def to_sorted(*vals_B):
            """Broadcast per-txn (B,) values to entries and permute into
            this sort's order by re-sorting on the same fixed keys — on
            TPU one extra sort is ~4x cheaper than the per-lane
            valid[s_tx]-style gathers it replaces (PROFILE.md).

            PRECONDITION: (key, ts) ties are intra-txn only — timestamps
            are unique per live txn — so this is_stable=False re-sort can
            only permute lanes WITHIN one txn's run, and only per-txn-
            constant payloads may ship through it (a per-lane-varying
            payload, or a future duplicate-ts scheme, would silently
            misalign tie groups; checked when debug_invariants is on)."""
            pay = tuple(jnp.broadcast_to(v[:, None].astype(jnp.int32),
                                         (B, R)).reshape(-1)
                        for v in vals_B)
            out = jax.lax.sort((key, ts) + pay, num_keys=2, is_stable=False)
            return out[2:]

        def txn_reduce(perm, sorted_val, op):
            """Per-txn reduction over sorted entries: un-permute to entry
            order, reduce over the R lanes."""
            v = seg.unpermute(perm, sorted_val).reshape(B, R)
            return v.min(axis=1) if op == "min" else v.max(axis=1)

        # cases 1/3: lower above the greatest committed write/read ts seen
        # at access time (snapshots).  Independent of same-tick neighbors.
        lower = jnp.maximum(db["maat_lower"], db["maat_gw"] + 1)
        case1 = finishing & (db["maat_lower"] <= db["maat_gw"])
        has_write = (txn.is_write & granted).any(axis=1)
        case3 = finishing & has_write & (lower <= db["maat_gr"])
        lower = jnp.where(finishing & has_write,
                          jnp.maximum(lower, db["maat_gr"] + 1), lower)

        # Same-tick earlier validators are already COMMITTED AND RELEASED
        # by the time I validate (validation is serialized and
        # TimeTable::release runs at commit, txn.cpp:431), so cases 2/4/5
        # IGNORE them.  What binds me instead is the push they applied as
        # they committed (validation squeeze + commit-time forward
        # validation, row_maat.cpp:189-314), with commit_ts = their final
        # lower:
        #   committed WRITER of a row I touch  -> my upper <= cts - 1
        #   committed READER of a row I write  -> my lower >= cts + 1
        # (same-tick finishers were admitted together, so in ts order the
        # later finisher accessed each shared row after the earlier one —
        # the "unseen neighbor" direction of the forward push).  Each
        # push uses the NEIGHBOR's final lower, which itself depends on
        # pushes from even-earlier validators -> compute the unique fixed
        # point of the ts-ordered chain.
        static_lower = lower

        # exclude my own entries from the prefix pushes (a txn never pushes
        # itself; also keeps the fixed point free of self-oscillation on
        # duplicate-key txns): read the prefix value at my (key, txn)-run
        # start
        run_start = starts | seg.segment_starts(s_tx)

        def caps(okv, lov):
            s_ok, s_lo = to_sorted(okv, lov)
            okx = (s_ok == 1) & s_fin
            pmw_full = seg.seg_prefix_min(
                jnp.where(okx & s_iw, dn1(s_lo), BIG_TS), starts, BIG_TS)
            pmw = seg.at_run_start(pmw_full, run_start, starts, BIG_TS,
                                   "min")
            plr_full = seg.seg_prefix_max(
                jnp.where(okx & ~s_iw, up1(s_lo), 0), starts, 0)
            plr = seg.at_run_start(plr_full, run_start, starts, 0, "max")
            cap_e = jnp.where(s_fin, pmw, BIG_TS)
            push_e = jnp.where(s_fin & s_iw, plr, 0)
            # ONE unpermute sort ships both reductions home
            up_e, lo_e = seg.unpermute_many(s_orig, cap_e, push_e)
            upper_new = jnp.minimum(db["maat_upper"],
                                    up_e.reshape(B, R).min(axis=1))
            lower_new = jnp.maximum(static_lower,
                                    lo_e.reshape(B, R).max(axis=1))
            return lower_new, upper_new

        def step(carry):
            okv, lov, _up, _ = carry
            lower_new, upper_new = caps(okv, lov)
            new_ok = finishing & (lower_new < upper_new)
            changed = jnp.any(new_ok != okv) | jnp.any(lower_new != lov)
            return new_ok, lower_new, upper_new, changed

        # SPECULATIVE UNROLL (PROFILE.md): the ts-ordered chain usually
        # settles in <= 2 iterations; unrolled steps fuse into the tick
        # graph (no while-carry scoped-memory round trips) and the loop
        # runs only for genuinely deeper chains.  `upper` rides the carry,
        # so no extra caps() pass is needed after convergence: the loop
        # exits exactly when a step reproduces its inputs.
        ok, lower, upper, ch = step((finishing, static_lower,
                                     db["maat_upper"],
                                     jnp.any(finishing) | True))
        ok, lower, upper, ch = step((ok, lower, upper, ch))
        ok, lower, upper, _ = jax.lax.cond(
            ch,
            lambda op: jax.lax.while_loop(lambda c: c[3], step, op),
            lambda op: op,
            (ok, lower, upper, ch))

        # case counters (maat.cpp:46-111 families): 1/3 snapshot pushes,
        # 2 = upper capped by earlier validated writers, 4 = lower pushed
        # by earlier validated readers, 6 = range emptied (abort).  Bumped
        # once per VALIDATION EVENT: in the sharded virtual-entry context
        # (R==1, entries of one home txn share a unique ts) a
        # representative-entry mask keeps counts per (owner, txn), not
        # per routed access; its per-entry bound values sample one owner
        # view, like the reference's per-node validate.
        measuring = tick >= cfg.warmup_ticks
        if R == 1 and cfg.node_cnt > 1:
            gord = jnp.arange(B, dtype=jnp.int32)
            gkey = jnp.where(finishing, txn.ts, NULL_KEY)
            (g_sorted,), (g_orig,) = seg.sort_by((gkey,), (gord,))
            rep = seg.unpermute(
                g_orig, seg.segment_starts(g_sorted)) & finishing
        else:
            rep = finishing
        cnt = lambda m: jnp.where(measuring,
                                  jnp.sum((m & rep).astype(jnp.int32)), 0)
        case_inc = {
            "maat_case1_cnt": db["maat_case1_cnt"] + cnt(case1),
            "maat_case3_cnt": db["maat_case3_cnt"] + cnt(case3),
            "maat_case2_cnt": db["maat_case2_cnt"]
            + cnt(upper < db["maat_upper"]),
            "maat_case4_cnt": db["maat_case4_cnt"]
            + cnt(lower > static_lower),
            "maat_case6_cnt": db["maat_case6_cnt"] + cnt(~ok),
        }

        # --- directional neighbor squeeze: consolidation of the validation
        # squeeze (maat.cpp:121-170) + commit-time forward validation
        # (row_maat.cpp:189-314).  The direction a live txn W is pushed
        # relative to a committer C depends on per-row ACCESS ORDER:
        #   running writer W vs committing writer C:
        #     W accessed before C -> C saw W:  W after C (lower >= C.up+1)
        #     W accessed after C  -> C never saw W: the reference orders W
        #       BEFORE C (upper <= commit_ts-1, row_maat.cpp:222-233)
        #   running writer W vs committing reader C: W after C either way
        #     (upper+1 if C saw W at validation, commit_ts+1 = lower+1 if
        #      not, row_maat.cpp:249-274)
        #   running reader R vs committing writer C: R before C either way
        #     (upper <= C.lower - 1)
        # Access order is computable without extra state because MaaT
        # accesses never block: access r granted at start_tick + r//window.
        atick = (jnp.broadcast_to(txn.start_tick[:, None], (B, R))
                 + ridx // max(cfg.acquire_window, 1)).reshape(-1)
        # running entries carry their CURRENT db bounds; committing entries
        # their final validated bounds — shipped through the sort as
        # payloads instead of gathered per lane afterwards
        lo_cur = jnp.where(finishing, lower, db["maat_lower"])
        up_cur = jnp.where(finishing, upper, db["maat_upper"])
        bcast = lambda v: jnp.broadcast_to(
            v[:, None].astype(jnp.int32), (B, R)).reshape(-1)
        (k2, a2, t2), (w2, f2, ok2, lo2, up2, orig2) = seg.sort_by(
            (key, atick, ts),
            (iw, fin_e, bcast(ok), bcast(lo_cur), bcast(up_cur), orig))
        st2 = seg.segment_starts(k2)
        live2 = k2 != NULL_KEY
        okx = ok2 == 1
        cw = live2 & f2 & w2 & okx          # committing writers
        cr = live2 & f2 & ~w2 & okx         # committing readers
        run2 = live2 & ~f2                  # live, not finishing

        # validator self-adjustment before the after-push (maat.cpp:145-156):
        # a committer's upper ducks under the range of a running writer it
        # SAW (prefix in access order) when possible, weakening that push
        cand = jnp.where(run2 & w2,
                         jnp.where(up2 < BIG_TS, up2 - 2,
                                   jnp.where(lo2 > 1, lo2 - 1, BIG_TS)),
                         BIG_TS)
        pre_cand = seg.seg_prefix_min(cand, st2, BIG_TS)
        adj = txn_reduce(orig2, jnp.where(live2 & f2, pre_cand, BIG_TS),
                 "min")
        upper_v = jnp.where(ok, jnp.maximum(jnp.minimum(upper, adj),
                                            lower + 1), upper)
        # re-sort shipping (same precondition as to_sorted: ts unique per
        # txn, payload per-txn-constant)
        _, _, _, up2c = jax.lax.sort((key, atick, ts, bcast(upper_v)),
                                     num_keys=3, is_stable=False)

        # committers AFTER me in access order saw my entry (I was in their
        # uncommitted sets): their validation orders me AFTER them.
        # Committers BEFORE me never saw me: their commit-push orders me
        # BEFORE them (writers) / AFTER commit_ts (readers).
        suf_up_cw = seg.seg_suffix_max(jnp.where(cw, up1(up2c), 0), st2, 0)
        suf_up_cr = seg.seg_suffix_max(jnp.where(cr, up1(up2c), 0), st2, 0)
        pre_lo_cr = seg.seg_prefix_max(jnp.where(cr, up1(lo2), 0), st2, 0)
        pre_lo_cw = seg.seg_prefix_min(jnp.where(cw, dn1(lo2), BIG_TS),
                                       st2, BIG_TS)
        all_lo_cw = seg.seg_min_where(dn1(lo2), cw, st2, BIG_TS)

        # running writers: ordered after committers that saw them, before
        # committing writers that did not
        w_lo = jnp.maximum(jnp.maximum(suf_up_cw, suf_up_cr), pre_lo_cr)
        w_up = pre_lo_cw
        # running readers: before every committing writer of the row
        r_up = all_lo_cw

        new_lo2 = jnp.where(run2 & w2, w_lo, 0)
        new_up2 = jnp.where(run2, jnp.where(w2, w_up, r_up), BIG_TS)

        up_e2, lo_e2 = seg.unpermute_many(orig2, new_up2, new_lo2)
        upper_arr = jnp.minimum(db["maat_upper"],
                                up_e2.reshape(B, R).min(axis=1))
        lower_arr = jnp.maximum(db["maat_lower"],
                                lo_e2.reshape(B, R).max(axis=1))
        # also persist the validators' own tightened bounds
        upper_arr = jnp.where(finishing, upper_v, upper_arr)
        lower_arr = jnp.where(finishing, lower, lower_arr)

        return ok, {**db, **case_inc,
                    "maat_lower": lower_arr, "maat_upper": upper_arr}

    def home_commit_check(self, cfg: Config, db: dict, txn: TxnState,
                          commit_try):
        # find_bound at the coordinator (maat.cpp:176-190): per-owner votes
        # check only locally-tightened ranges; the MERGED range can be empty
        # (one owner raised lower past another owner's lowered upper)
        return commit_try & (db["maat_lower"] < db["maat_upper"])

    def on_commit(self, cfg: Config, db: dict, txn: TxnState, committed,
                  commit_ts, tick):
        # commit_timestamp = lower (find_bound); bump row lr/lw
        B, R = txn.keys.shape
        cts = db["maat_lower"]
        ridx = jnp.arange(R, dtype=jnp.int32)[None, :]
        acc = committed[:, None] & (ridx < txn.n_req[:, None])
        wmask = (acc & txn.is_write).reshape(-1)
        rmask = (acc & ~txn.is_write).reshape(-1)
        keys = txn.keys.reshape(-1)
        cts_e = jnp.broadcast_to(cts[:, None], (B, R)).reshape(-1)
        lw = db["maat_lw"].at[keys].max(jnp.where(wmask, cts_e, 0), mode="drop")
        lr = db["maat_lr"].at[keys].max(jnp.where(rmask, cts_e, 0), mode="drop")
        return {**db, "maat_lw": lw, "maat_lr": lr}
